"""PagedGenerativeRunner: continuous batching over the paged KV cache.

The successor to ``runners.GenerativeRunner`` (which is retained as the
fixed-slot memory baseline): sequences own **block tables** over a shared
page pool instead of max-length slots, so the same KV memory sustains
several times the concurrency — admission is gated on **free pages**, not
free slots. Three capabilities ride the page structure:

- **prefix caching** — full prompt pages are hash-consed by content-chain
  digest (``paged_kv.PrefixCache``); a request whose prompt prefix was
  served before adopts the cached pages (refcounted) and prefills only
  the tail. The ``serving.prefill_tokens`` counter counts *computed*
  tokens, so a prefix hit is directly visible as a lower count.
- **chunked prefill** — a long prompt is processed one bucket-sized chunk
  per scheduler iteration, interleaved with the decode batch, instead of
  stalling every co-resident sequence for one monolithic prefill. Prompts
  are no longer capped by the largest bucket — only by ``max_seq`` and
  the page pool.
- **speculative decoding** — a small draft spec proposes ``draft_k``
  tokens per round (ONE ``lax.scan`` dispatch), and the target model
  verifies all of them in ONE batched ``verify_tokens`` step (the same
  shape discipline as bucketed prefill). Greedy acceptance keeps the
  output token-exact: a draft token is committed only when it equals the
  target's own greedy choice, and the bonus token is always the
  target's. Rejected speculation is rolled back exactly — the K/V rows
  are dead (position-masked until overwritten) and the pages allocated
  past the new frontier are freed.

Every compiled program is fixed-shape (per-bucket chunk prefills, one
decode, one propose scan, one verify), so steady-state traffic compiles
nothing after ``warmup()`` — the PR-6 guarantee, now with paging.

Page exhaustion is a first-class state, distinct from overload: admission
blocks (``page_starved()``), decode rows stall, and when nothing can
progress the youngest sequence is **preempted** (pages freed, the request
re-admitted later via chunked prefill over prompt+generated — greedy
decode makes the recompute token-identical). All of it is counted
(``serving.kv.*``, ``serving.preemptions``) so the doctor's
``kv_page_exhaustion`` detector can name memory pressure instead of
letting it masquerade as traffic overload.
"""
import collections

import numpy as np
import jax
import jax.numpy as jnp

from .. import compilecache as _cc
from .. import observability as _obs
from .bucketing import pad_to_bucket, select_bucket
from .paged_kv import PageAllocator, PrefixCache, chain_hashes
from .runners import _Stats, _count, finish_request
from .scheduler import STATUS_DEADLINE, STATUS_ERROR, STATUS_OK

__all__ = ['PagedGenerativeRunner']


class _PagedStats(_Stats):
    """Slot-runner tallies plus the paging/speculation surface."""

    def __init__(self):
        super().__init__()
        self.prefix_hit_pages = 0
        self.prefix_lookup_pages = 0
        self.spec_proposed = 0
        self.spec_accepted = 0
        self.preemptions = 0
        self.decode_stalls = 0
        self.prefill_stalls = 0

    def as_dict(self):
        d = super().as_dict()
        d.update({
            'prefix_hit_pages': self.prefix_hit_pages,
            'prefix_lookup_pages': self.prefix_lookup_pages,
            'spec_proposed': self.spec_proposed,
            'spec_accepted': self.spec_accepted,
            'draft_acceptance': (
                round(self.spec_accepted / self.spec_proposed, 4)
                if self.spec_proposed else 0.0),
            'preemptions': self.preemptions,
            'decode_stalls': self.decode_stalls,
            'prefill_stalls': self.prefill_stalls,
        })
        return d


class _Side:
    """One model's paged world: cache pytree, allocator, block tables and
    (optionally) a prefix cache. The runner drives one of these for the
    target and — in speculative mode — a mirrored one for the draft."""

    def __init__(self, spec, rows, num_pages, page_size, max_seq,
                 prefix_cache):
        self.spec = spec
        self.page_size = int(page_size)
        self.rows = int(rows)
        self.max_pages = -(-int(max_seq) // self.page_size)      # ceil
        self.alloc = PageAllocator(num_pages)
        self.prefix = PrefixCache(self.alloc) if prefix_cache else None
        self.cache = spec.init_paged_cache(num_pages, page_size)
        self.blocks = np.zeros((self.rows, self.max_pages), np.int32)
        self.n_pages = [0] * self.rows

    def _alloc_one(self):
        """One page, evicting unreferenced prefix-cache entries (LRU) under
        pressure. None when the pool is truly exhausted."""
        while True:
            if self.alloc.free_count():
                return self.alloc.alloc()
            if self.prefix is None or not self.prefix.evict_one():
                return None

    def ensure(self, row, upto_pos):
        """Allocate block-table slots so position ``upto_pos`` is writable.
        False (with no partial damage beyond already-owned pages) when the
        pool is exhausted — the caller stalls, sheds, or preempts."""
        need = upto_pos // self.page_size + 1
        while self.n_pages[row] < need:
            page = self._alloc_one()
            if page is None:
                return False
            self.blocks[row, self.n_pages[row]] = page
            self.n_pages[row] += 1
        return True

    def evictable(self):
        if self.prefix is None:
            return 0
        return sum(1 for p in self.prefix._entries.values()
                   if self.alloc.refcount(p) == 1)

    def adopt_shared(self, row, pages):
        """Install prefix-hit pages (already increfed by ``lookup``) as the
        row's leading block-table entries."""
        for i, p in enumerate(pages):
            self.blocks[row, i] = p
        self.n_pages[row] = len(pages)

    def trim(self, row, keep_upto_pos):
        """Exact speculative rollback: free block-table slots beyond the
        one holding ``keep_upto_pos``. Shared prefix pages are never
        trimmed (they are a prefix of the row, and the frontier never
        retreats into the prompt)."""
        keep = keep_upto_pos // self.page_size + 1
        while self.n_pages[row] > keep:
            n = self.n_pages[row] - 1
            self.alloc.decref(int(self.blocks[row, n]))
            self.blocks[row, n] = 0
            self.n_pages[row] = n

    def release(self, row):
        for i in range(self.n_pages[row]):
            self.alloc.decref(int(self.blocks[row, i]))
        self.blocks[row, :] = 0
        self.n_pages[row] = 0

    def register_prefix(self, row, digests, upto_pages):
        """Hash-cons the row's first ``upto_pages`` prompt pages so later
        admits with the same prefix adopt them instead of recomputing.
        Called per completed chunk (a page is registerable the moment all
        its positions are written), so even same-iteration admits share."""
        if self.prefix is None:
            return
        for i in range(min(upto_pages, len(digests))):
            self.prefix.insert(digests[i], int(self.blocks[row, i]))


class PagedGenerativeRunner:
    """Iteration-level continuous batching over ``paged_kv`` (see module
    docstring). The compiled set: one chunk-prefill program per prompt
    bucket (x2 with a draft), one decode, and in speculative mode one
    propose scan + one verify — all warmed by ``warmup()``."""

    kind = 'generative'

    def __init__(self, name, queue, spec, page_size=16, num_pages=None,
                 max_concurrency=None, draft=None, draft_k=4,
                 prefix_cache=True, default_max_new_tokens=32):
        self.name = name
        self.queue = queue
        self.spec = spec
        self.page_size = int(page_size)
        if self.page_size < 1:
            raise ValueError(f"serving[{name}]: page_size must be >= 1, "
                             f"got {page_size}")
        self.rows = int(max_concurrency or spec.max_batch)
        self.buckets = tuple(sorted(spec.prompt_buckets))
        self.chunk = self.buckets[-1]
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.draft_k = int(draft_k)
        if draft is not None and self.draft_k < 1:
            raise ValueError(f"serving[{name}]: draft_k must be >= 1, "
                             f"got {draft_k}")
        if draft is not None and draft.max_seq < spec.max_seq:
            raise ValueError(
                f"serving[{name}]: draft max_seq {draft.max_seq} < target "
                f"max_seq {spec.max_seq} — the draft must cover every "
                "position it speculates at")
        max_pages = -(-int(spec.max_seq) // self.page_size)
        if num_pages is None:
            # worst case: every row at max_seq (+1 for the null page) —
            # no memory win by default; size it down to realize one
            num_pages = self.rows * max_pages + 1
        self.num_pages = int(num_pages)
        self.target = _Side(spec, self.rows, self.num_pages, self.page_size,
                            spec.max_seq, prefix_cache)
        self.draft = None
        if draft is not None:
            self.draft = _Side(draft, self.rows, self.num_pages,
                               self.page_size, spec.max_seq, prefix_cache)
        self.seqs = [None] * self.rows
        self.stats = _PagedStats()
        self.step_no = 0
        self.journal = collections.deque(maxlen=1024)
        self._preempted = collections.deque()
        self._page_starved = False
        self._stalled_this_pump = False
        self._digest_memo = {}

        def _prefill(cache, block_row, toks, start, length):
            cache, logits = spec.prefill_chunk(cache, block_row, toks,
                                               start, length)
            return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        def _decode(cache, blocks, toks, pos):
            cache, logits = spec.decode_paged(cache, blocks, toks, pos)
            return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

        self._prefill = _cc.CachedJit(_prefill)
        self._decode = _cc.CachedJit(_decode)
        self._verify = self._propose = None
        self._draft_prefill = self._draft_decode = None
        if draft is not None:
            def _draft_prefill(cache, block_row, toks, start, length):
                cache, logits = draft.prefill_chunk(cache, block_row, toks,
                                                    start, length)
                return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def _draft_decode(cache, blocks, toks, pos):
                cache, logits = draft.decode_paged(cache, blocks, toks, pos)
                return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

            def _propose(cache, blocks, last, pos):
                # draft_k sequential greedy steps in ONE dispatch
                def body(carry, _):
                    c, cur, p = carry
                    c, logits = draft.decode_paged(c, blocks, cur, p)
                    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    return (c, nxt, p + 1), nxt
                (cache, _, _), props = jax.lax.scan(
                    body, (cache, last, pos), None, length=self.draft_k)
                return cache, jnp.moveaxis(props, 0, 1)        # [B, k]

            def _verify(cache, blocks, toks, pos):
                cache, logits = spec.verify_tokens(cache, blocks, toks, pos)
                return cache, jnp.argmax(logits, axis=-1).astype(jnp.int32)

            self._draft_prefill = _cc.CachedJit(_draft_prefill)
            self._draft_decode = _cc.CachedJit(_draft_decode)
            self._propose = _cc.CachedJit(_propose)
            self._verify = _cc.CachedJit(_verify)

    # -- helpers ---------------------------------------------------------
    @staticmethod
    def _generated(s):
        """ALL tokens generated for this request: pre-preemption ('done',
        folded into the re-admitted prompt) + since (re-)admission."""
        return s['done'] + s['tokens']

    def _sides(self):
        return (self.target,) if self.draft is None else (self.target,
                                                          self.draft)

    @property
    def slots(self):
        """Slot-view compatibility: one entry per block-table row."""
        return list(self.seqs)

    def page_starved(self):
        """True when the last scheduler pass was blocked on free pages —
        the engine uses this to attribute sheds to memory pressure
        (``serving.shed.page_exhaustion``) instead of traffic overload."""
        return self._page_starved or self._stalled_this_pump

    def kv_info(self):
        """Introspection for tests/bench/stats: page + prefix + draft
        accounting of the target side."""
        t = self.target
        info = {
            'page_size': self.page_size,
            'num_pages': self.num_pages,
            'pages_used': t.alloc.used_count(),
            'pages_free': t.alloc.free_count(),
            'page_utilization': round(t.alloc.utilization(), 4),
            'max_concurrency': self.rows,
        }
        if t.prefix is not None:
            info.update({
                'prefix_pages_cached': len(t.prefix),
                'prefix_hits': t.prefix.hits,
                'prefix_misses': t.prefix.misses,
                'prefix_hit_rate': round(t.prefix.hit_rate(), 4),
            })
        if self.draft is not None:
            info['draft_k'] = self.draft_k
            info['draft_acceptance'] = (
                round(self.stats.spec_accepted / self.stats.spec_proposed, 4)
                if self.stats.spec_proposed else 0.0)
        return info

    def validate(self, req):
        toks = np.asarray(req.inputs.get('tokens', ()))
        if toks.size == 0:
            raise ValueError(
                f"serving[{self.name}]: generative request needs a "
                "non-empty 'tokens' input")
        n = toks.ravel().shape[0]
        if n + 1 > self.spec.max_seq:
            raise ValueError(
                f"serving[{self.name}]: prompt of {n} tokens leaves no "
                f"room to decode within max_seq {self.spec.max_seq} "
                "(chunked prefill lifts the per-bucket cap, not the "
                "sequence budget)")
        need = (n - 1) // self.page_size + 1
        if need > self.target.alloc.usable:
            raise ValueError(
                f"serving[{self.name}]: prompt needs {need} KV page(s) but "
                f"the pool holds {self.target.alloc.usable} — grow "
                "num_pages or page_size")

    def has_work(self):
        return (len(self.queue) > 0 or bool(self._preempted) or
                any(s is not None for s in self.seqs))

    def evict_in_flight(self):
        """Vacate every resident sequence AND the preempted backlog
        (engine shutdown): ``[(request, partial_outputs)]``."""
        out = []
        for row in range(self.rows):
            s = self.seqs[row]
            if s is None:
                continue
            self._release_row(row)
            self.stats.leaves += 1
            _count('serving.leaves')
            self.journal.append(('leave', s['req'].id, self.step_no))
            out.append((s['req'],
                        {'tokens': np.asarray(self._generated(s),
                                              np.int32)}))
        while self._preempted:
            item = self._preempted.popleft()
            out.append((item['req'],
                        {'tokens': np.asarray(item['tokens'], np.int32)}))
        return out

    def warmup(self):
        """Ready the whole closed program set against the null row/page,
        with int32-array scalars exactly like the real calls: each program
        deserializes from a bound compilecache artifact dir (zero
        compiles) or compiles once. With telemetry on, every program lands
        in the cost ledger either way."""
        def zi(*shape):
            # host-built zeros: no tiny fill-program compile on a cold boot
            return jnp.asarray(np.zeros(shape, np.int32))

        def warm(fn, label, kind, *args, **meta):
            return fn.warm(f'serving.{self.name}.{label}', *args, kind=kind,
                           meta=dict(meta, model=self.name))
        n = 0
        z = jnp.asarray(0, jnp.int32)
        one = jnp.asarray(1, jnp.int32)
        trow = zi(self.target.max_pages)
        for cb in self.buckets:
            toks = zi(cb)
            args = (self.target.cache, trow, toks, z, one)
            self.target.cache, _ = warm(
                self._prefill, f'prefill{cb}', 'serving.prefill', *args,
                bucket=cb)
            n += 1
        tblocks = zi(self.rows, self.target.max_pages)
        zb = zi(self.rows)
        dargs = (self.target.cache, tblocks, zb, zb)
        self.target.cache, _ = warm(self._decode, 'decode',
                                    'serving.decode', *dargs,
                                    batch=self.rows)
        n += 1
        if self.draft is not None:
            drow = zi(self.draft.max_pages)
            for cb in self.buckets:
                toks = zi(cb)
                args = (self.draft.cache, drow, toks, z, one)
                self.draft.cache, _ = warm(
                    self._draft_prefill, f'draft_prefill{cb}',
                    'serving.prefill', *args, bucket=cb)
                n += 1
            dblocks = zi(self.rows, self.draft.max_pages)
            ddargs = (self.draft.cache, dblocks, zb, zb)
            self.draft.cache, _ = warm(self._draft_decode, 'draft_decode',
                                       'serving.decode', *ddargs,
                                       batch=self.rows)
            pargs = (self.draft.cache, dblocks, zb, zb)
            self.draft.cache, _ = warm(self._propose, 'propose',
                                       'serving.speculate', *pargs,
                                       k=self.draft_k)
            zk = zi(self.rows, self.draft_k + 1)
            vargs = (self.target.cache, tblocks, zk, zk)
            self.target.cache, _ = warm(self._verify, 'verify',
                                        'serving.speculate', *vargs,
                                        k=self.draft_k)
            n += 3
        return n

    # -- one scheduler iteration -----------------------------------------
    def step(self):
        self.step_no += 1
        self._stalled_this_pump = False
        did = self._admit()
        did = self._prefill_pump() or did
        did = self._decode_pump() or did
        if not did and self._stalled_this_pump:
            did = self._relieve_pressure() or did
        if _obs.enabled():
            self._export_gauges()
        return did

    def _export_gauges(self):
        t = self.target
        _obs.gauge('serving.kv.page_utilization').set(
            round(t.alloc.utilization(), 4))
        _obs.gauge('serving.kv.pages_free').set(t.alloc.free_count())
        if t.prefix is not None:
            _obs.gauge('serving.kv.prefix_hit_rate').set(
                round(t.prefix.hit_rate(), 4))
            _obs.gauge('serving.kv.prefix_pages_cached').set(len(t.prefix))
        if self.draft is not None and self.stats.spec_proposed:
            _obs.gauge('serving.spec.acceptance_rate').set(round(
                self.stats.spec_accepted / self.stats.spec_proposed, 4))

    # -- admission (gated on free pages, not free slots) ------------------
    def _shared_probe(self, digests, n):
        """Side-effect-free count of prefix pages BOTH sides would hit.
        Capped at (n-1)//page_size: the last prompt token is always
        recomputed so its logits (-> first generated token) exist."""
        usable = min(len(digests), (n - 1) // self.page_size)
        common = usable
        for side in self._sides():
            if side.prefix is None:
                return 0
            common = min(common, side.prefix.probe(digests[:usable]))
        return common

    def _digests_for(self, prompt):
        return chain_hashes(prompt, self.page_size) \
            if any(s.prefix is not None for s in self._sides()) else []

    def _admittable(self, req):
        # rows are bounded by pop_ready_while's max_n; only a PAGE
        # shortfall may raise the starvation flag (it attributes sheds).
        # Digests are memoized for _start_seq — one SHA pass per prompt
        # per admission attempt, not two.
        prompt = np.asarray(req.inputs['tokens'], np.int32).ravel()
        digests = self._digests_for(prompt)
        self._digest_memo[req.id] = digests
        if self._feasible(prompt, digests):
            return True
        self._page_starved = True
        return False

    def _feasible(self, prompt, digests):
        """Do both sides have (free + LRU-evictable) pages for the whole
        prompt after prefix sharing? The whole-prompt gate keeps a long
        admit from starving mid-prefill in the common case; residual
        races stall and retry."""
        n = len(prompt)
        shared = self._shared_probe(digests, n)
        need = (n - 1) // self.page_size + 1 - shared
        return all(side.alloc.free_count() + side.evictable() >= need
                   for side in self._sides())

    def _admit(self):
        did = False
        free_rows = [i for i, s in enumerate(self.seqs) if s is None]
        self._page_starved = False
        self._digest_memo = {}         # predicate -> _start_seq, one pass
        if not free_rows:
            expired = self.queue.reap_expired()
            for r in expired:
                self._expire(r)
            return bool(expired)
        # re-admit preempted sequences first (they were admitted once;
        # jumping the queue preserves completion order under pressure)
        while free_rows and self._preempted:
            item = self._preempted[0]
            if 'digests' not in item:
                item['digests'] = self._digests_for(item['prompt'])
            if not self._feasible(item['prompt'], item['digests']):
                if all(side.alloc.free_count() + side.evictable() >=
                       side.alloc.usable for side in self._sides()):
                    # the pool is as empty as it can get and the sequence
                    # STILL does not fit: fail it, don't spin forever
                    self._preempted.popleft()
                    self.stats.errors += 1
                    finish_request(
                        item['req'], STATUS_ERROR,
                        {'tokens': np.asarray(item['tokens'], np.int32)},
                        error=RuntimeError(
                            f"serving[{self.name}]: preempted sequence "
                            "needs more KV pages than the pool holds "
                            f"({self.target.alloc.usable} usable) — grow "
                            "num_pages or lower max_new_tokens"))
                    did = True
                    continue
                self._page_starved = True
                break
            st = self._start_seq(free_rows[0], item['req'], item['prompt'],
                                 item['max_new'], item['tokens'],
                                 digests=item['digests'])
            if st == 'stall':
                self._page_starved = True
                break
            self._preempted.popleft()
            did = True
            if st == 'started':
                free_rows.pop(0)
        if not free_rows or self._page_starved:
            expired = self.queue.reap_expired()
            for r in expired:
                self._expire(r)
            return did or bool(expired)
        ready, expired = self.queue.pop_ready_while(self._admittable,
                                                    len(free_rows))
        for r in expired:
            self._expire(r)
        did = did or bool(expired)
        for r in ready:
            did = True
            row = free_rows.pop(0)
            prompt = np.asarray(r.inputs['tokens'], np.int32).ravel()
            max_new = int(self.default_max_new_tokens
                          if r.max_new_tokens is None else r.max_new_tokens)
            st = self._start_seq(row, r, prompt, max_new, [],
                                 digests=self._digest_memo.get(r.id))
            if st == 'stall':
                # feasibility raced an eviction estimate: put it back at
                # the head (no shed — it was already admitted once)
                self.queue.push_front(r)
                self._page_starved = True
                self.stats.prefill_stalls += 1
                _count('serving.kv.prefill_stalls')
                break
            if st != 'started':
                free_rows.insert(0, row)
        return did

    def _start_seq(self, row, req, prompt, max_new, tokens_done,
                   digests=None):
        """Admit one sequence into ``row``: adopt shared prefix pages,
        run the first prefill chunk. -> 'started' | 'stall' (nothing
        consumed) | 'failed' (request completed as error)."""
        n = len(prompt)
        if digests is None:
            digests = self._digests_for(prompt)
        usable = min(len(digests), (n - 1) // self.page_size) \
            if digests else 0
        adopted = []
        common = usable
        for side in self._sides():
            pages = []
            if side.prefix is not None:
                for d in digests[:common]:
                    page = side.prefix.lookup(d)
                    if page is None:
                        break
                    pages.append(page)
            common = min(common, len(pages))
            adopted.append((side, pages))
        for side, pages in adopted:
            while len(pages) > common:       # over-adopted vs the other side
                side.alloc.decref(pages.pop())
            side.adopt_shared(row, pages)
        c = common * self.page_size
        if common:
            self.stats.prefix_hit_pages += common
            _count('serving.kv.prefix_hit_pages', common)
        self.stats.prefix_lookup_pages += usable
        # 'done' holds tokens generated BEFORE a preemption; they are part
        # of the re-admitted prompt, so they must NOT also count into the
        # position invariant pos == len(prompt) + len(tokens) - 1 that the
        # decode/speculation paths maintain. 'tokens' is generation since
        # (re-)admission only; outputs/limits use done + tokens.
        s = {'req': req, 'prompt': np.asarray(prompt, np.int32),
             'done': list(tokens_done), 'tokens': [], 'last': None,
             'pos': 0, 'max_new': int(max_new), 'fill_next': c,
             'shared': c, 'ready': False, 'joined': self.step_no,
             'digests': digests, 'draft_pos': None}
        self.seqs[row] = s
        st = self._fill_chunk(row)
        if st == 'stall':
            self._release_row(row)
            return 'stall'
        if st == 'failed':
            return 'failed'
        self.stats.joins += 1
        _count('serving.joins')
        self.journal.append(('join', req.id, self.step_no))
        if _obs.enabled():
            _obs.event('serving.join', model=self.name, request=req.id,
                       slot=row, prompt_len=n,
                       prefix_hit_pages=common,
                       chunked=bool(s['fill_next'] < n))
        if st == 'done':
            self._maybe_finish(row)
        return 'started'

    # -- chunked prefill --------------------------------------------------
    def _fill_chunk(self, row):
        """One prompt chunk for ``row`` on both sides. -> 'done' | 'more'
        | 'stall' | 'failed'."""
        s = self.seqs[row]
        n = len(s['prompt'])
        start = s['fill_next']
        remaining = n - start
        nvalid = min(remaining, self.chunk)
        cb = self.chunk if remaining > self.chunk \
            else select_bucket(remaining, self.buckets)
        for side in self._sides():
            if not side.ensure(row, start + nvalid - 1):
                self._page_stall('prefill')
                return 'stall'
        padded = jnp.asarray(pad_to_bucket(s['prompt'][start:start + nvalid],
                                           cb))
        st32 = jnp.asarray(start, jnp.int32)
        nv32 = jnp.asarray(nvalid, jnp.int32)
        try:
            with _obs.timer('serving.prefill', model=self.name,
                            bucket=cb) as t:
                self.target.cache, toks = self._prefill(
                    self.target.cache, jnp.asarray(self.target.blocks[row]),
                    padded, st32, nv32)
                if self.draft is not None:
                    self.draft.cache, _ = self._draft_prefill(
                        self.draft.cache,
                        jnp.asarray(self.draft.blocks[row]),
                        padded, st32, nv32)
            s['req'].add_phase_ms('prefill', t.elapsed_ms)
            if _obs.enabled():
                _obs.async_instant('prefill_chunk', s['req'].id,
                                   cat='serving.request', start=start,
                                   bucket=cb, n=nvalid)
        except Exception as e:               # model bug: fail the request,
            self._fail_row(row, e)           # not the engine worker
            return 'failed'
        s['fill_next'] = start + nvalid
        self.stats.prefill_tokens += nvalid
        _count('serving.prefill_tokens', nvalid)
        # hash-cons every page this chunk completed, immediately: admits
        # later in the SAME iteration already share them
        for side in self._sides():
            side.register_prefix(row, s['digests'],
                                 s['fill_next'] // self.page_size)
        if s['fill_next'] < n:
            return 'more'
        first = int(np.asarray(toks)[nvalid - 1])
        s['tokens'].append(first)
        s['last'] = first
        s['pos'] = n
        s['ready'] = True
        if self.draft is not None:
            s['draft_pos'] = n
        return 'done'

    def _prefill_pump(self):
        """One chunk per still-filling row per iteration: long prompts
        admit in slices interleaved with the decode batch."""
        did = False
        for row in range(self.rows):
            s = self.seqs[row]
            if s is None or s['ready']:
                continue
            st = self._fill_chunk(row)
            if st == 'done':
                self._maybe_finish(row)
            if st in ('done', 'more', 'failed'):
                did = True
        return did

    # -- decode -----------------------------------------------------------
    def _decode_pump(self):
        ready = [i for i in range(self.rows)
                 if self.seqs[i] is not None and self.seqs[i]['ready']]
        if not ready:
            return False
        if self.draft is None:
            return self._plain_decode(ready)
        spec_rows, plain_rows = [], []
        for i in ready:
            s = self.seqs[i]
            # rows too close to max_seq (or whose draft fell >1 behind via
            # the fallback) finish on the plain path
            if (s['pos'] + self.draft_k <= self.spec.max_seq - 1 and
                    s['pos'] - s['draft_pos'] <= 1):
                spec_rows.append(i)
            else:
                plain_rows.append(i)
        did = False
        if plain_rows:
            did = self._plain_decode(plain_rows) or did
        if spec_rows:
            did = self._spec_round(spec_rows) or did
        return did

    def _masked_blocks(self, side, rows):
        """Block tables with non-participant rows nulled: their (ignored)
        writes land in the null page instead of live pages."""
        blocks = np.zeros_like(side.blocks)
        for i in rows:
            blocks[i] = side.blocks[i]
        return blocks

    def _plain_decode(self, rows):
        run = []
        for i in rows:
            if self.target.ensure(i, self.seqs[i]['pos']):
                run.append(i)
            else:
                self._page_stall('decode')
        if not run:
            return False
        b = self.rows
        toks = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        for i in run:
            toks[i] = self.seqs[i]['last']
            pos[i] = self.seqs[i]['pos']
        self.stats.batches += 1
        _count('serving.decode_steps')
        self.stats.occupancy(len(run) / b)
        try:
            with _obs.timer('serving.decode', model=self.name,
                            active=len(run)) as t:
                self.target.cache, nxt = self._decode(
                    self.target.cache, self._masked_blocks(self.target, run),
                    toks, pos)
        except Exception as e:
            for i in run:
                self._fail_row(i, e)
            return True
        nxt = np.asarray(nxt)
        telemetry = _obs.enabled()
        for i in run:
            s = self.seqs[i]
            s['pos'] += 1
            tok = int(nxt[i])
            s['tokens'].append(tok)
            s['last'] = tok
            s['req'].add_phase_ms('decode', t.elapsed_ms)
            self.stats.decode_tokens += 1
            _count('serving.decode_tokens')
            if telemetry:
                _obs.async_instant('decode', s['req'].id,
                                   cat='serving.request',
                                   tokens=len(self._generated(s)))
            self._maybe_finish(i)
        return True

    def _spec_round(self, rows):
        """Draft proposes ``k`` tokens (one scan dispatch), target verifies
        all of them plus the pending token in ONE batched step; greedy
        accept keeps the stream token-exact and rejected pages are freed
        (exact rollback)."""
        k = self.draft_k
        run = []
        for i in rows:
            s = self.seqs[i]
            if (self.target.ensure(i, s['pos'] + k) and
                    self.draft.ensure(i, s['pos'] + k - 1)):
                run.append(i)
            else:
                self._page_stall('decode')
        if not run:
            return False
        b = self.rows
        # 1) catch-up: after a fully-accepted round the draft is one
        #    committed token behind — ingest it (one batched decode)
        behind = [i for i in run
                  if self.seqs[i]['pos'] - self.seqs[i]['draft_pos'] == 1]
        self.stats.batches += 1
        _count('serving.decode_steps')
        self.stats.occupancy(len(run) / b)
        try:
            if behind:
                ctoks = np.zeros((b,), np.int32)
                cpos = np.zeros((b,), np.int32)
                for i in behind:
                    s = self.seqs[i]
                    d = s['draft_pos']
                    ctoks[i] = s['tokens'][d - len(s['prompt'])]
                    cpos[i] = d
                self.draft.cache, _ = self._draft_decode(
                    self.draft.cache, self._masked_blocks(self.draft,
                                                          behind),
                    ctoks, cpos)
                for i in behind:
                    self.seqs[i]['draft_pos'] += 1
            # 2) propose
            last = np.zeros((b,), np.int32)
            pos = np.zeros((b,), np.int32)
            for i in run:
                last[i] = self.seqs[i]['last']
                pos[i] = self.seqs[i]['pos']
            dblocks = self._masked_blocks(self.draft, run)
            with _obs.timer('serving.propose', model=self.name, k=k) as tp:
                self.draft.cache, props = self._propose(
                    self.draft.cache, dblocks, last, pos)
            props = np.asarray(props)                      # [B, k]
            for i in run:
                self.seqs[i]['draft_pos'] = self.seqs[i]['pos'] + k
            # 3) verify: [last, t1..tk] at positions pos..pos+k — one step
            vtoks = np.zeros((b, k + 1), np.int32)
            vpos = np.zeros((b, k + 1), np.int32)
            for i in run:
                vtoks[i, 0] = self.seqs[i]['last']
                vtoks[i, 1:] = props[i]
                vpos[i] = self.seqs[i]['pos'] + np.arange(k + 1)
            with _obs.timer('serving.verify', model=self.name, k=k) as tv:
                self.target.cache, greedy = self._verify(
                    self.target.cache, self._masked_blocks(self.target, run),
                    vtoks, vpos)
        except Exception as e:
            for i in run:
                self._fail_row(i, e)
            return True
        greedy = np.asarray(greedy)                        # [B, k+1]
        telemetry = _obs.enabled()
        # 4) accept/commit + exact page rollback
        for i in run:
            s = self.seqs[i]
            m = 0
            while m < k and props[i, m] == greedy[i, m]:
                m += 1
            s['req'].add_phase_ms('draft', tp.elapsed_ms)
            s['req'].add_phase_ms('verify', tv.elapsed_ms)
            if telemetry:
                _obs.async_instant('verify', s['req'].id,
                                   cat='serving.request', proposed=k,
                                   accepted=m)
            self.stats.spec_proposed += k
            self.stats.spec_accepted += m
            _count('serving.spec.proposed', k)
            _count('serving.spec.accepted', m)
            eos = self.spec.eos_id
            commit = [int(t) for t in props[i, :m]] + [int(greedy[i, m])]
            for tok in commit:
                s['tokens'].append(tok)
                s['last'] = tok
                self.stats.decode_tokens += 1
                _count('serving.decode_tokens')
                if (len(self._generated(s)) >= s['max_new'] or
                        (eos is not None and tok == eos)):
                    break
            s['pos'] = len(s['prompt']) + len(s['tokens']) - 1
            s['draft_pos'] = min(s['draft_pos'], s['pos'])
            self.target.trim(i, s['pos'])
            self.draft.trim(i, s['draft_pos'])
            self._maybe_finish(i)
        return True

    # -- pressure ---------------------------------------------------------
    def _page_stall(self, where):
        self._stalled_this_pump = True
        if where == 'decode':
            self.stats.decode_stalls += 1
            _count('serving.kv.decode_stalls')
        else:
            self.stats.prefill_stalls += 1
            _count('serving.kv.prefill_stalls')
        if _obs.enabled():
            _obs.event('serving.page_exhausted', model=self.name,
                       where=where,
                       pages_free=self.target.alloc.free_count())

    def _relieve_pressure(self):
        """Nothing progressed and something stalled on pages: preempt the
        youngest sequence (pages freed; it re-admits later via chunked
        prefill over prompt+generated — token-identical under greedy).
        A sequence stalling *alone* can never fit: fail it instead."""
        active = [i for i in range(self.rows) if self.seqs[i] is not None]
        if not active:
            return False
        victim = max(active, key=lambda i: (self.seqs[i]['joined'], i))
        if len(active) == 1 and not self._preempted:
            self._fail_row(victim, RuntimeError(
                f"serving[{self.name}]: sequence needs more KV pages than "
                f"the pool holds ({self.target.alloc.usable} usable) — "
                "grow num_pages or lower max_new_tokens"))
            return True
        s = self.seqs[victim]
        self._release_row(victim)
        self._preempted.append({
            'req': s['req'],
            # tokens generated THIS residency fold into the prompt (they
            # will be re-prefilled); the full generated list rides along
            # so the eventual response still returns everything
            'prompt': np.concatenate(
                [s['prompt'], np.asarray(s['tokens'], np.int32)]),
            'max_new': s['max_new'],
            'tokens': self._generated(s),
        })
        self.stats.preemptions += 1
        _count('serving.preemptions')
        self.journal.append(('preempt', s['req'].id, self.step_no))
        if _obs.enabled():
            _obs.event('serving.preempt', model=self.name,
                       request=s['req'].id,
                       tokens_so_far=len(self._generated(s)))
            _obs.async_instant('preempt', s['req'].id,
                               cat='serving.request',
                               tokens=len(self._generated(s)))
        return True

    # -- row lifecycle -----------------------------------------------------
    def _release_row(self, row):
        for side in self._sides():
            side.release(row)
        self.seqs[row] = None

    def _fail_row(self, row, exc):
        s = self.seqs[row]
        self._release_row(row)
        self.stats.errors += 1
        self.stats.leaves += 1
        _count('serving.leaves')
        self.journal.append(('leave', s['req'].id, self.step_no))
        finish_request(s['req'], STATUS_ERROR,
                       {'tokens': np.asarray(self._generated(s), np.int32)},
                       error=exc)

    def _maybe_finish(self, row):
        s = self.seqs[row]
        r = s['req']
        eos = self.spec.eos_id
        done = (len(self._generated(s)) >= s['max_new'] or
                s['pos'] + 1 >= self.spec.max_seq or
                (eos is not None and s['last'] == eos))
        status = STATUS_OK
        if r.expired():
            done, status = True, STATUS_DEADLINE
            self.stats.expired += 1
            _count('serving.deadline_expired')
        if not done:
            return
        self._release_row(row)
        self.stats.leaves += 1
        self.stats.completed += 1
        _count('serving.leaves')
        self.journal.append(('leave', r.id, self.step_no))
        if _obs.enabled():
            _obs.event('serving.leave', model=self.name, request=r.id,
                       slot=row, tokens=len(self._generated(s)),
                       status=status)
            info = self.kv_info()
            _obs.event('serving.kv_stats', model=self.name,
                       page_utilization=info['page_utilization'],
                       prefix_hit_rate=info.get('prefix_hit_rate'),
                       draft_acceptance=info.get('draft_acceptance'),
                       preemptions=self.stats.preemptions,
                       decode_stalls=self.stats.decode_stalls)
        finish_request(r, status,
                       {'tokens': np.asarray(self._generated(s),
                                             np.int32)})

    def _expire(self, req):
        self.stats.expired += 1
        _count('serving.deadline_expired')
        finish_request(req, STATUS_DEADLINE)
