"""FleetRouter: fault-tolerant dispatch across N ServingEngine replicas.

The single-process ``ServingEngine`` dies with every request it holds.
This module is the fleet tier above it — a stdlib router that keeps the
service answering through replica crashes, hangs, overload, and rolling
restarts (docs/SERVING.md, "Fleet fabric"):

- **health-gated dispatch** — a replica receives traffic only while its
  engine is dispatchable (worker alive / not killed), its admission queue
  is below the depth gate, its paged KV cache is not starved, and its
  **circuit breaker** allows it. The breaker is passive: ``trip_after``
  consecutive failures open it, a cooldown from the shared
  ``resilience.retry`` backoff curve must elapse before a **half-open**
  probe window re-admits it, and only ``half_open_probes`` consecutive
  probe successes close it again. A relaunched (cold) replica rejoins
  through the same half-open gate so its compile warmup cannot eat live
  traffic.
- **deadline-bounded budgets** — every fleet request carries one
  end-to-end deadline. Retries and hedges inherit the *remaining* budget,
  never a fresh one; when the budget is gone the router answers
  ``'deadline'`` without dispatching.
- **failover retries** — a replica fault (death, hang timeout, engine
  stop) triggers a bounded re-dispatch on a *different* replica, but only
  for idempotent work: requests marked ``idempotent=False`` and
  generative failures that already carry partial output are never
  replayed (the silent-double-generation anti-pattern).
- **tail-latency hedging** — ``hedge_after_ms`` fires one duplicate on a
  different replica when the primary straggles; first response wins, the
  loser is cancelled (free while still queued) and counted.
- **graceful drain** — ``drain(name)`` stops new admits and waits (under
  a watchdog deadline) for the replica's queued + resident requests to
  finish: the zero-downtime rolling-restart primitive. ``readmit()``
  returns it to rotation through half-open warmup.
- **shed ladder** — fleet-wide SLO burn (PR 13 tracker) degrades service
  honestly: level 1 rejects sub-floor-priority tenants, level 2 also
  shrinks generative budgets, level 3 rejects everything (the 429
  analogue), each shed shaped as ``FleetOverloadError``.
- **prefix affinity** — generative prompts route by rendezvous hash of
  their content-chain digest (``paged_kv.chain_hashes``), so identical
  prefixes land on the replica whose prefix cache already holds them.

Everything lands on the telemetry spine — ``serving.router.*`` counters
(global + ``{replica=}``-labeled), ``serving.router_stats`` cumulative
events (``tools/telemetry_dump.py --serving`` renders the per-replica
table), circuit/failover/drain events for the doctor's
``replica_flapping`` / ``retry_storm`` detectors, flight-recorder entries
for post-mortems, and a ``serving.fleet`` async trace lane per fleet
request linking every attempt to the replica that served (or failed) it.
"""
import hashlib
import itertools
import threading
import time

from .. import observability as _obs
from ..observability.timing import Stopwatch
from ..resilience.retry import backoff_delay
from ..resilience.watchdog import WatchdogTimeout
from .admission import DEFAULT_TENANT, QuotaExceededError, record_shed
from .engine import EngineDeadError
from .paged_kv import chain_hashes
from .scheduler import (QueueFullError, Response, STATUS_CANCELLED,
                        STATUS_DEADLINE, STATUS_ERROR)

__all__ = ['FleetRouter', 'RouterPolicy', 'ReplicaHandle', 'CircuitBreaker',
           'FleetPending', 'ReplicaError', 'NoHealthyReplicaError',
           'FleetOverloadError', 'CIRCUIT_CLOSED', 'CIRCUIT_OPEN',
           'CIRCUIT_HALF_OPEN']

CIRCUIT_CLOSED = 'closed'
CIRCUIT_OPEN = 'open'
CIRCUIT_HALF_OPEN = 'half_open'

_POLL_TICK = 0.01              # router-side attempt poll (hedge resolution)
_fleet_ids = itertools.count(1)

# shed-ladder levels (docs/SERVING.md "Shed ladder")
SHED_NONE = 0                  # steady state
SHED_PRIORITY = 1              # reject tenants below the priority floor
SHED_DEGRADE = 2               # + shrink generative token budgets
SHED_REJECT = 3                # 429 everything
_SHED_NAMES = {SHED_NONE: 'none', SHED_PRIORITY: 'priority',
               SHED_DEGRADE: 'degrade', SHED_REJECT: 'reject'}


class ReplicaError(RuntimeError):
    """A fleet request failed because of replica faults — shaped with the
    replica id(s) that failed it so a post-mortem needs no log spelunking.
    ``replicas`` lists every replica tried, ``replica`` the last one."""

    def __init__(self, message, replica=None, replicas=(), request=None):
        super().__init__(message)
        self.replica = replica
        self.replicas = tuple(replicas) if replicas else (
            (replica,) if replica is not None else ())
        self.request = request


class NoHealthyReplicaError(ReplicaError):
    """Dispatch found no admittable replica (all dead, draining, tripped,
    or over the queue-depth gate)."""


class FleetOverloadError(RuntimeError):
    """The shed ladder rejected this request (429 analogue). ``level`` is
    the ladder rung (1 = priority shed, 3 = reject-all) and ``reason``
    the human-readable rung name."""

    def __init__(self, message, level, reason):
        super().__init__(message)
        self.level = level
        self.reason = reason


class RouterPolicy:
    """Knobs for the fleet fabric; defaults favor fast CPU tests.

    ``max_retries`` bounds failover re-dispatches per request (on top of
    the first attempt). ``hedge_after_ms=None`` disables hedging.
    ``attempt_timeout_ms`` is the hang detector — an attempt older than
    this with no response is abandoned and failed over (``None``: rely on
    the request deadline / replica-death detection only).
    ``on_replica_death`` is ``'redispatch'`` (stranded idempotent work
    retries elsewhere) or ``'fail_fast'`` (shaped ``ReplicaError``
    immediately). The ``shed_burn_*`` thresholds map fleet SLO burn to
    ladder rungs; ``shed_priority_floor`` is the minimum priority admitted
    at level 1+. ``circuit_jitter=0`` keeps chaos tests deterministic;
    production fleets want the default retry jitter (0.5) so probes don't
    stampede."""

    def __init__(self, max_retries=2, hedge_after_ms=None,
                 attempt_timeout_ms=None, on_replica_death='redispatch',
                 trip_after=3, circuit_cooldown_s=0.25,
                 circuit_cooldown_factor=2.0, circuit_max_cooldown_s=30.0,
                 circuit_jitter=0.0, half_open_probes=2,
                 max_queue_depth=None, affinity_page_size=16,
                 shed_burn_soft=1.0, shed_burn_hard=2.0, shed_burn_stop=4.0,
                 shed_priority_floor=1, shed_max_new_tokens=8):
        if on_replica_death not in ('redispatch', 'fail_fast'):
            raise ValueError(
                "RouterPolicy: on_replica_death must be 'redispatch' or "
                f"'fail_fast', got {on_replica_death!r}")
        if max_retries < 0:
            raise ValueError("RouterPolicy: max_retries must be >= 0")
        self.max_retries = int(max_retries)
        self.hedge_after_ms = hedge_after_ms
        self.attempt_timeout_ms = attempt_timeout_ms
        self.on_replica_death = on_replica_death
        self.trip_after = int(trip_after)
        self.circuit_cooldown_s = float(circuit_cooldown_s)
        self.circuit_cooldown_factor = float(circuit_cooldown_factor)
        self.circuit_max_cooldown_s = float(circuit_max_cooldown_s)
        self.circuit_jitter = float(circuit_jitter)
        self.half_open_probes = int(half_open_probes)
        self.max_queue_depth = max_queue_depth
        self.affinity_page_size = int(affinity_page_size)
        self.shed_burn_soft = float(shed_burn_soft)
        self.shed_burn_hard = float(shed_burn_hard)
        self.shed_burn_stop = float(shed_burn_stop)
        self.shed_priority_floor = int(shed_priority_floor)
        self.shed_max_new_tokens = int(shed_max_new_tokens)


class CircuitBreaker:
    """Passive per-replica breaker: closed → (``trip_after`` consecutive
    failures) → open → (cooldown from the shared ``resilience.retry``
    backoff curve, doubling per trip) → half-open probe window →
    (``half_open_probes`` consecutive successes) → closed; any half-open
    failure re-opens with a longer cooldown. Every transition is an
    ``serving.router.circuit`` event — the doctor's ``replica_flapping``
    detector counts them."""

    def __init__(self, replica, trip_after=3, cooldown_s=0.25, factor=2.0,
                 max_cooldown_s=30.0, jitter=0.0, half_open_probes=2):
        self.replica = replica
        self.trip_after = int(trip_after)
        self.cooldown_s = float(cooldown_s)
        self.factor = float(factor)
        self.max_cooldown_s = float(max_cooldown_s)
        self.jitter = float(jitter)
        self.half_open_probes = int(half_open_probes)
        self.state = CIRCUIT_CLOSED
        self.trips = 0                 # lifetime opens
        self.closes = 0                # lifetime recoveries
        self._consecutive = 0
        self._opened = None            # Stopwatch started at last open
        self._probes_left = 0
        self._probe_successes = 0
        self._lock = threading.Lock()

    def _transition(self, state, **why):
        self.state = state
        if _obs.enabled():
            _obs.event('serving.router.circuit', replica=self.replica,
                       state=state, trips=self.trips, **why)
            _obs.counter('serving.router.circuit_transitions').inc()
        _obs.flight.record('router.circuit', replica=self.replica,
                           state=state)

    def cooldown(self):
        """Seconds the circuit stays open before the next half-open probe
        window — the shared retry backoff curve keyed by trip count, so a
        replica that keeps failing is probed exponentially less often."""
        return backoff_delay(self.trips, backoff=self.cooldown_s,
                             factor=self.factor,
                             max_backoff=self.max_cooldown_s,
                             jitter=self.jitter)

    def allow(self):
        """May the router dispatch to this replica right now? Transitions
        open → half-open as a side effect once the cooldown elapses."""
        with self._lock:
            if self.state == CIRCUIT_CLOSED:
                return True
            if self.state == CIRCUIT_OPEN:
                if self._opened is not None and \
                        self._opened.elapsed() >= self.cooldown():
                    self._probes_left = self.half_open_probes
                    self._probe_successes = 0
                    self._transition(CIRCUIT_HALF_OPEN, reason='cooldown')
                    return True
                return False
            return self._probes_left > 0   # half-open: bounded probes

    def on_dispatch(self):
        with self._lock:
            if self.state == CIRCUIT_HALF_OPEN and self._probes_left > 0:
                self._probes_left -= 1

    def record_success(self):
        with self._lock:
            self._consecutive = 0
            if self.state == CIRCUIT_HALF_OPEN:
                self._probe_successes += 1
                if self._probe_successes >= self.half_open_probes:
                    self.closes += 1
                    self._transition(CIRCUIT_CLOSED, reason='probes_ok')

    def record_failure(self, reason=''):
        with self._lock:
            self._consecutive += 1
            if self.state == CIRCUIT_HALF_OPEN or (
                    self.state == CIRCUIT_CLOSED and
                    self._consecutive >= self.trip_after):
                self._open(reason)

    def trip(self, reason):
        """Open immediately (replica death — no need to wait for
        ``trip_after`` echoes of the same corpse). Idempotent."""
        with self._lock:
            if self.state != CIRCUIT_OPEN:
                self._open(reason)

    def force_half_open(self, reason='rejoin'):
        """Cold-rejoin gate: a relaunched/readmitted replica re-enters
        rotation probe-by-probe so its compile warmup meets bounded
        traffic, not the full request stream."""
        with self._lock:
            self._probes_left = self.half_open_probes
            self._probe_successes = 0
            self._opened = Stopwatch()
            self._transition(CIRCUIT_HALF_OPEN, reason=reason)

    def _open(self, reason):
        # callers hold self._lock
        self.trips += 1
        self._opened = Stopwatch()
        self._consecutive = 0
        self._transition(CIRCUIT_OPEN, reason=reason)


class ReplicaHandle:
    """Router-side view of one replica: the engine, its breaker, its
    drain state, and its dispatch ledger (the telemetry-dump columns)."""

    def __init__(self, name, engine, policy):
        self.name = name
        self.engine = engine
        self.policy = policy
        self.breaker = CircuitBreaker(
            name, trip_after=policy.trip_after,
            cooldown_s=policy.circuit_cooldown_s,
            factor=policy.circuit_cooldown_factor,
            max_cooldown_s=policy.circuit_max_cooldown_s,
            jitter=policy.circuit_jitter,
            half_open_probes=policy.half_open_probes)
        self.draining = False
        self.drained = False
        # the ledger lock serializes counter bumps: result() drives the
        # retry/hedge machine on arbitrary client threads, so different
        # requests' drivers race on this one handle's counters
        self._ledger = threading.Lock()
        self.dispatched = 0
        self.completed = 0
        self.failed = 0
        self.retried = 0               # failover re-dispatches landing here
        self.hedged = 0                # hedge duplicates landing here
        self.hedge_wins = 0
        self.drained_requests = 0
        self.queue_full = 0
        self.deaths = 0
        self.restarts = 0              # supervisor relaunches

    def admittable(self, model):
        """Health gate: is this replica a valid dispatch target for
        ``model`` right now?"""
        if self.draining or not self.engine.dispatchable():
            return False
        if not self.engine.has_model(model):
            return False
        if self.engine.page_starved(model):
            return False
        lim = self.policy.max_queue_depth
        if lim is not None and self.engine.queued_count(model) >= int(lim):
            return False
        return self.breaker.allow()

    def bump(self, counter, n=1):
        """Atomically increment one dispatch-ledger counter."""
        with self._ledger:
            setattr(self, counter, getattr(self, counter) + n)

    def stats_row(self):
        with self._ledger:
            return {'dispatched': self.dispatched,
                    'completed': self.completed,
                    'failed': self.failed, 'retried': self.retried,
                    'hedged': self.hedged, 'hedge_wins': self.hedge_wins,
                    'drained': self.drained_requests,
                    'queue_full': self.queue_full, 'deaths': self.deaths,
                    'restarts': self.restarts,
                    'circuit': self.breaker.state,
                    'trips': self.breaker.trips, 'draining': self.draining}


class _FleetRequest:
    """Router-side record of one client request across all its attempts."""

    __slots__ = ('id', 'model', 'inputs', 'deadline_ms', 'max_new_tokens',
                 'priority', 'idempotent', 'generative', 'affinity', 'sw',
                 'attempts', 'tried', 'retries_used', 'hedged', 'fail_fast',
                 'lock', 'settled', 'tenant')

    def __init__(self, model, inputs, deadline_ms, max_new_tokens, priority,
                 idempotent, generative, affinity, tenant=None):
        self.id = next(_fleet_ids)
        self.model = model
        self.inputs = inputs
        self.deadline_ms = None if deadline_ms is None else float(deadline_ms)
        self.max_new_tokens = max_new_tokens
        self.tenant = tenant
        self.priority = int(priority)
        self.idempotent = idempotent
        self.generative = generative
        self.affinity = affinity
        self.sw = Stopwatch()
        self.attempts = []             # live _Attempts
        self.tried = []                # replica names, dispatch order
        self.retries_used = 0
        self.hedged = False
        self.fail_fast = False         # set by the fail_fast death policy
        self.lock = threading.Lock()   # one result() driver at a time
        self.settled = None            # ('response', resp) | ('raise', exc)

    def remaining_ms(self):
        if self.deadline_ms is None:
            return None
        return self.deadline_ms - self.sw.elapsed_ms()


class _Attempt:
    __slots__ = ('handle', 'pending', 'kind', 'sw')

    def __init__(self, handle, pending, kind):
        self.handle = handle
        self.pending = pending
        self.kind = kind               # 'first' | 'retry' | 'hedge'
        self.sw = Stopwatch()


class FleetPending:
    """Client handle for one routed request. ``result()`` drives the
    retry/hedge state machine on the calling thread — the router spawns
    no threads of its own; concurrency is the clients'."""

    __slots__ = ('_router', '_fr')

    def __init__(self, router, fr):
        self._router = router
        self._fr = fr

    @property
    def fleet_id(self):
        return self._fr.id

    @property
    def replicas_tried(self):
        return tuple(self._fr.tried)

    def done(self):
        return any(a.pending.done() for a in self._fr.attempts)

    def result(self, timeout=None):
        return self._router._await(self._fr, timeout=timeout)


class FleetRouter:
    """The fleet fabric front door. See the module docstring for the
    behavior contract; ``add_replica()`` engines may be background-started
    (``start()``) or manually pumped (the router never pumps for dispatch,
    but ``drain()`` will pump a manual-drive replica to completion)."""

    def __init__(self, policy=None, tenants=None):
        self.policy = policy or RouterPolicy()
        self.tenants = tenants         # admission.TenantArbiter or None
        self._handles = {}
        self._lock = threading.Lock()
        self._rr = itertools.count()   # tie-break rotation for _pick

    # -- fleet membership ----------------------------------------------
    def add_replica(self, name, engine):
        with self._lock:
            if name in self._handles:
                raise ValueError(f"router: replica {name!r} already in "
                                 "the fleet")
            self._handles[name] = ReplicaHandle(name, engine, self.policy)
        if _obs.enabled():
            _obs.event('serving.router.replica_added', replica=name)
            _obs.gauge('serving.router.replicas').set(len(self._handles))
        return self._handles[name]

    def remove_replica(self, name):
        with self._lock:
            h = self._handles.pop(name, None)
        if h is None:
            raise KeyError(f"router: no replica {name!r}")
        if _obs.enabled():
            _obs.gauge('serving.router.replicas').set(len(self._handles))
        return h.engine

    def replica(self, name):
        h = self._handles.get(name)
        if h is None:
            raise KeyError(f"router: no replica {name!r} "
                           f"(have {sorted(self._handles)})")
        return h

    def replicas(self):
        with self._lock:
            return list(self._handles.values())

    # -- shed ladder ----------------------------------------------------
    def shed_level(self):
        """Current ladder rung from peak per-model SLO burn (PR 13
        tracker): 0 none, 1 reject sub-floor priorities, 2 also shrink
        generative budgets, 3 reject everything."""
        from ..observability import slo as _slo
        burns = _slo.burn_rates()
        peak = max(burns.values()) if burns else 0.0
        p = self.policy
        if peak >= p.shed_burn_stop:
            return SHED_REJECT
        if peak >= p.shed_burn_hard:
            return SHED_DEGRADE
        if peak >= p.shed_burn_soft:
            return SHED_PRIORITY
        return SHED_NONE

    def _shed_gate(self, model, priority, tenant=None):
        level = self.shed_level()
        # ladder level 1 is tenant-aware when an arbiter is attached:
        # "reject below THIS tenant's priority_floor" — a premium tenant
        # (floor 0) keeps flowing at level 1 while a batch tenant (high
        # floor) sheds first; without tenancy the global policy floor
        # applies as before
        floor = (self.tenants.priority_floor(tenant)
                 if self.tenants is not None
                 else self.policy.shed_priority_floor)
        if level >= SHED_REJECT or (
                level >= SHED_PRIORITY and priority < floor):
            reason = _SHED_NAMES[level]
            if _obs.enabled():
                _obs.counter('serving.router.shed').inc()
                _obs.event('serving.router.shed', model=model,
                           level=level, reason=reason, priority=priority,
                           tenant=tenant)
            raise FleetOverloadError(
                f"router: fleet shedding at level {level} ({reason}) — "
                f"request for {model!r} (priority {priority}, floor "
                f"{floor}) rejected; retry with backoff",
                level=level, reason=reason)
        return level

    # -- placement ------------------------------------------------------
    def _affinity_key(self, model, inputs, generative):
        if not generative or not isinstance(inputs, dict):
            return None
        toks = inputs.get('tokens')
        if toks is None:
            return None
        toks = [int(t) for t in toks]
        chain = chain_hashes(toks, self.policy.affinity_page_size)
        if chain:
            return chain[-1]
        # prompt shorter than one page: hash it whole — still deterministic
        return hashlib.sha256(repr(toks).encode()).hexdigest()

    @staticmethod
    def _rendezvous(key, handles):
        """Highest-random-weight placement: stable while the healthy set
        is stable, minimal movement when it changes — the property that
        makes per-replica prefix caches act fleet-wide."""
        return max(handles, key=lambda h: hashlib.sha256(
            (str(key) + '|' + h.name).encode()).digest())

    def _pick(self, model, affinity, exclude=()):
        with self._lock:
            handles = [h for h in self._handles.values()
                       if h.name not in exclude]
        cands = [h for h in handles if h.admittable(model)]
        if not cands:
            return None
        if affinity is not None:
            return self._rendezvous(affinity, cands)
        # least-loaded placement for affinity-free work; rotate the
        # tie-break so an idle fleet spreads instead of piling on one name
        off = next(self._rr) % len(cands)
        cands = cands[off:] + cands[:off]
        return min(cands, key=lambda h: h.engine.queued_count(model))

    # -- dispatch -------------------------------------------------------
    def _dispatch(self, fr, kind, required=True):
        """Place one attempt of ``fr`` on a not-yet-tried admittable
        replica. Submit-time rejections (queue full, raced death) fall
        through to the next candidate. Returns the live ``_Attempt``, or
        None / raises ``NoHealthyReplicaError`` when the fleet has no
        target (``required`` controls which — a hedge that finds no spare
        replica is simply not fired)."""
        exclude = set(fr.tried)
        while True:
            h = self._pick(fr.model, fr.affinity, exclude=exclude)
            if h is None:
                if not required:
                    return None
                raise NoHealthyReplicaError(
                    f"router: no healthy replica for {fr.model!r} "
                    f"(fleet request {fr.id}, tried "
                    f"{fr.tried or 'none'})", replicas=fr.tried,
                    request=fr.id)
            try:
                pending = h.engine.submit(
                    fr.model, fr.inputs, deadline_ms=fr.remaining_ms(),
                    max_new_tokens=fr.max_new_tokens, tenant=fr.tenant)
            except QuotaExceededError:
                # tenant-global, not replica-local: every replica would
                # answer the same, so burning failover candidates on it
                # only hides the real shed reason — surface it
                raise
            except QueueFullError as e:
                # backed-up replica: a health signal, not a breaker trip —
                # the queue-depth gate handles persistent backlog
                h.bump('queue_full')
                exclude.add(h.name)
                if _obs.enabled():
                    _obs.event('serving.router.queue_full', fleet=fr.id,
                               replica=h.name, reason=e.reason)
                continue
            except EngineDeadError:
                self._replica_died(h, fleet=fr.id)
                exclude.add(h.name)
                continue
            h.breaker.on_dispatch()
            h.bump('dispatched')
            if kind == 'retry':
                h.bump('retried')
                fr.retries_used += 1
            elif kind == 'hedge':
                h.bump('hedged')
            fr.tried.append(h.name)
            attempt = _Attempt(h, pending, kind)
            fr.attempts.append(attempt)
            if _obs.enabled():
                # one label set per family (the registry enforces it):
                # per-replica counters only — fleet totals are the sum
                # over labels (doctor._labeled / telemetry_dump do this)
                lbl = {'replica': h.name}
                _obs.counter('serving.router.dispatched',
                             labels=lbl).inc()
                if kind == 'retry':
                    _obs.counter('serving.router.retries', labels=lbl).inc()
                elif kind == 'hedge':
                    _obs.counter('serving.router.hedges', labels=lbl).inc()
                _obs.async_instant(
                    f'dispatch:{kind}', fr.id, cat='serving.fleet',
                    replica=h.name, engine_request=pending.request_id)
            return attempt

    def submit(self, model, inputs, deadline_ms=None, max_new_tokens=None,
               priority=1, idempotent=None, tenant=None):
        """Route one request into the fleet -> ``FleetPending``.

        ``priority`` feeds the shed ladder (higher survives longer;
        the default 1 sits exactly at the default floor). ``idempotent``
        is the retry/hedge contract: ``None`` (default) lets the router
        infer — one-shot requests are idempotent, generative requests are
        retried only while no partial output exists; ``False`` pins the
        request to its first replica (a continuation whose replay would
        double-generate). ``tenant`` names the submitting tenant: with a
        ``tenants=`` arbiter attached, the token-bucket quota is charged
        here (over-quota raises ``QuotaExceededError``, reason
        ``'quota'``) and ladder level 1 rejects below the *tenant's*
        ``priority_floor``. Raises ``FleetOverloadError`` when the shed
        ladder rejects, ``NoHealthyReplicaError`` when no replica can
        take it, ``KeyError`` when no replica serves ``model``."""
        with self._lock:
            handles = list(self._handles.values())
        if not any(h.engine.has_model(model) for h in handles):
            raise KeyError(f"router: no replica serves model {model!r}")
        if self.tenants is not None:
            # fleet front door owns the quota charge — replica engines in
            # this fleet must NOT share the same arbiter, or each request
            # is double-charged
            try:
                self.tenants.check(tenant, model)
            except QuotaExceededError as e:
                record_shed(tenant, e.reason)
                if _obs.enabled():
                    _obs.counter('serving.shed').inc()
                    _obs.counter('serving.shed.quota').inc()
                    _obs.event('serving.shed', model=model, reason=e.reason,
                               tenant=tenant or DEFAULT_TENANT)
                raise
        level = self._shed_gate(model, priority, tenant=tenant)
        generative = any(h.engine.has_model(model) and
                         h.engine.model_kind(model) == 'generative'
                         for h in handles)
        if level >= SHED_DEGRADE and generative:
            cap = self.policy.shed_max_new_tokens
            max_new_tokens = cap if max_new_tokens is None \
                else min(int(max_new_tokens), cap)
            if _obs.enabled():
                _obs.event('serving.router.degrade', model=model,
                           max_new_tokens=max_new_tokens)
        fr = _FleetRequest(model, inputs, deadline_ms, max_new_tokens,
                           priority, idempotent, generative,
                           self._affinity_key(model, inputs, generative),
                           tenant=tenant)
        if _obs.enabled():
            _obs.async_begin('fleet', fr.id, cat='serving.fleet',
                             model=model, priority=priority, tenant=tenant)
        try:
            self._dispatch(fr, kind='first')
        except NoHealthyReplicaError:
            if _obs.enabled():
                _obs.counter('serving.router.rejected').inc()
                _obs.async_end('fleet', fr.id, cat='serving.fleet',
                               status='no_replica')
            raise
        except QuotaExceededError:
            if _obs.enabled():
                _obs.async_end('fleet', fr.id, cat='serving.fleet',
                               status='shed', reason='quota')
            raise
        return FleetPending(self, fr)

    def predict(self, model, inputs, deadline_ms=None, max_new_tokens=None,
                priority=1, idempotent=None, timeout=None, tenant=None):
        """Blocking one-call convenience: submit + result."""
        return self.submit(model, inputs, deadline_ms=deadline_ms,
                           max_new_tokens=max_new_tokens, priority=priority,
                           idempotent=idempotent,
                           tenant=tenant).result(timeout=timeout)

    # -- the retry/hedge state machine ----------------------------------
    @staticmethod
    def _replica_fault(err):
        """Did this error come from the replica, not the request? Only
        replica faults are failover-retryable; a model error would fail
        identically everywhere."""
        if isinstance(err, (EngineDeadError, WatchdogTimeout)):
            return True
        return isinstance(err, RuntimeError) and \
            'engine stopped' in str(err)

    def _retryable(self, fr):
        if fr.idempotent is False or fr.fail_fast:
            return False
        return fr.retries_used < self.policy.max_retries

    def _replica_died(self, h, fleet=None):
        """Record an observed replica death (once per corpse: the breaker
        trip is idempotent, the death counter only moves on the opening
        transition)."""
        first = h.breaker.state != CIRCUIT_OPEN
        h.breaker.trip('replica_death')
        if first:
            h.bump('deaths')
            if _obs.enabled():
                _obs.counter('serving.router.replica_death').inc()
                _obs.event('serving.router.replica_death', replica=h.name,
                           fleet=fleet)
            _obs.flight.record('router.replica_death', replica=h.name)

    def _attempt_failed(self, fr, attempt, why, err=None):
        if attempt in fr.attempts:
            fr.attempts.remove(attempt)
        h = attempt.handle
        h.bump('failed')
        if why == 'replica_death':
            self._replica_died(h, fleet=fr.id)
        else:
            h.breaker.record_failure(why)
        if why == 'timeout':
            # reap the abandoned duplicate if it never left the queue
            h.engine.cancel(attempt.pending)
        if why == 'replica_death' and \
                self.policy.on_replica_death == 'fail_fast':
            fr.fail_fast = True
        if _obs.enabled():
            _obs.counter('serving.router.failures',
                         labels={'replica': h.name}).inc()
            _obs.event('serving.router.failover', fleet=fr.id,
                       replica=h.name, why=why,
                       error=None if err is None else repr(err))
            _obs.async_instant(f'failover:{why}', fr.id,
                               cat='serving.fleet', replica=h.name)
        _obs.flight.record('router.failover', fleet=fr.id, replica=h.name,
                           why=why)

    def _settle(self, fr, winner, resp):
        """First response wins: cancel/abandon the losers, credit the
        winner, close the fleet trace lane, and shape the answer exactly
        as ``PendingRequest.result`` would."""
        h = winner.handle
        for loser in list(fr.attempts):
            if loser is winner:
                continue
            fr.attempts.remove(loser)
            cancelled = loser.handle.engine.cancel(loser.pending)
            if _obs.enabled():
                _obs.counter('serving.router.hedge_cancelled' if cancelled
                             else 'serving.router.hedge_wasted').inc()
        fr.attempts.clear()
        h.bump('completed')
        h.breaker.record_success()
        if winner.kind == 'hedge':
            h.bump('hedge_wins')
            if _obs.enabled():
                _obs.counter('serving.router.hedge_wins',
                             labels={'replica': h.name}).inc()
        if _obs.enabled():
            _obs.event('serving.router.request', fleet=fr.id,
                       model=fr.model, replica=h.name, status=resp.status,
                       attempt=winner.kind, retries=fr.retries_used,
                       hedged=fr.hedged,
                       latency_ms=round(fr.sw.elapsed_ms(), 3))
            _obs.async_end('fleet', fr.id, cat='serving.fleet',
                           status=resp.status, replica=h.name)
        self.emit_stats()
        if resp.status == STATUS_ERROR and resp.error is not None:
            fr.settled = ('raise', resp.error)
            raise resp.error
        fr.settled = ('response', resp)
        return resp

    def _fail(self, fr, why):
        last = fr.tried[-1] if fr.tried else None
        if _obs.enabled():
            _obs.counter('serving.router.failed').inc()
            _obs.event('serving.router.request', fleet=fr.id,
                       model=fr.model, replica=last, status='failed',
                       why=why, retries=fr.retries_used, hedged=fr.hedged,
                       latency_ms=round(fr.sw.elapsed_ms(), 3))
            _obs.async_end('fleet', fr.id, cat='serving.fleet',
                           status='failed', why=why)
        self.emit_stats()
        exc = ReplicaError(
            f"router: fleet request {fr.id} for {fr.model!r} failed "
            f"({why}) after {len(fr.tried)} attempt(s) on "
            f"{fr.tried}; last replica: {last}",
            replica=last, replicas=fr.tried, request=fr.id)
        fr.settled = ('raise', exc)
        raise exc

    def _deadline_response(self, fr):
        resp = Response(STATUS_DEADLINE, None, fr.model, fr.id,
                        fr.sw.elapsed_ms(), 0.0)
        if _obs.enabled():
            _obs.event('serving.router.request', fleet=fr.id,
                       model=fr.model, replica=None, status='deadline',
                       retries=fr.retries_used, hedged=fr.hedged,
                       latency_ms=round(fr.sw.elapsed_ms(), 3))
            _obs.async_end('fleet', fr.id, cat='serving.fleet',
                           status='deadline')
        fr.settled = ('response', resp)
        return resp

    def _await(self, fr, timeout=None):
        """Drive ``fr`` to an answer on the calling thread: poll live
        attempts, detect replica death/hangs, fail over within budget,
        fire the hedge, and settle on the first response."""
        p = self.policy
        with fr.lock:                  # one driver per fleet request
            if fr.settled is not None:   # replay a settled outcome
                kind, val = fr.settled
                if kind == 'raise':
                    raise val
                return val
            if not fr.attempts and not fr.tried:
                raise ReplicaError("router: request was never dispatched",
                                   request=fr.id)
            sw = Stopwatch()
            while True:
                # 1) settled attempt? (first response wins)
                for a in list(fr.attempts):
                    if not a.pending.done():
                        continue
                    resp = a.pending._req.response
                    if resp.status == STATUS_CANCELLED:
                        fr.attempts.remove(a)
                    elif resp.status == STATUS_ERROR and \
                            self._replica_fault(resp.error) and \
                            not (fr.generative and resp.outputs):
                        # a replica fault with NO partial output: eligible
                        # for failover. Partial generative output pins the
                        # answer — replaying would double-generate.
                        self._attempt_failed(fr, a, 'error', resp.error)
                    else:
                        return self._settle(fr, a, resp)
                # 2) stranded on a dead replica?
                for a in list(fr.attempts):
                    if not a.handle.engine.dispatchable():
                        self._attempt_failed(fr, a, 'replica_death')
                # 3) hang detector
                if p.attempt_timeout_ms is not None:
                    for a in list(fr.attempts):
                        if a.sw.elapsed_ms() > p.attempt_timeout_ms:
                            self._attempt_failed(fr, a, 'timeout')
                # 4) out of budget?
                rem = fr.remaining_ms()
                if rem is not None and rem <= 0:
                    for a in list(fr.attempts):
                        fr.attempts.remove(a)
                        a.handle.engine.cancel(a.pending)
                    return self._deadline_response(fr)
                # 5) nothing in flight: fail over or give up
                if not fr.attempts:
                    if not self._retryable(fr):
                        why = ('replica_death' if fr.fail_fast else
                               'non_idempotent' if fr.idempotent is False
                               else 'attempts_exhausted')
                        self._fail(fr, why)
                    try:
                        self._dispatch(fr, kind='retry')
                    except NoHealthyReplicaError:
                        self._fail(fr, 'no_healthy_replica')
                # 6) tail hedge
                if (p.hedge_after_ms is not None and not fr.hedged and
                        len(fr.attempts) == 1 and fr.idempotent is not False
                        and sw.elapsed_ms() >= p.hedge_after_ms):
                    if self._dispatch(fr, kind='hedge',
                                      required=False) is not None:
                        fr.hedged = True
                    else:
                        fr.hedged = True   # no spare replica: don't re-try
                # 7) bounded wait
                if timeout is not None and sw.elapsed() >= timeout:
                    raise WatchdogTimeout(
                        f"router: no response for fleet request {fr.id} "
                        f"within {timeout:.1f}s (attempts on {fr.tried})",
                        what='fleet response', waited=sw.elapsed())
                time.sleep(_POLL_TICK)

    # -- drain / rejoin -------------------------------------------------
    def drain(self, name, timeout=30.0):
        """Gracefully take ``name`` out of rotation: stop new admits, let
        its queued + resident requests finish under a watchdog deadline,
        then hand the (still-running) engine back for stop/upgrade. A
        manual-drive engine is pumped here; a started one drains on its
        own worker. Raises ``WatchdogTimeout`` when residents outlive
        ``timeout`` and ``ReplicaError`` if the replica dies mid-drain —
        in both cases it stays out of rotation. Zero resident requests
        are aborted on the happy path: that is the whole point."""
        h = self.replica(name)
        h.draining = True
        pending = h.engine.queued_count() + h.engine.resident_count()
        if _obs.enabled():
            _obs.event('serving.router.drain', replica=name,
                       state='draining', pending=pending)
        _obs.flight.record('router.drain', replica=name, state='draining',
                           pending=pending)
        sw = Stopwatch()
        while h.engine.queued_count() or h.engine.resident_count():
            if not h.engine.dispatchable():
                if _obs.enabled():
                    _obs.event('serving.router.drain', replica=name,
                               state='died', pending=pending)
                raise ReplicaError(
                    f"router: replica {name!r} died mid-drain",
                    replica=name)
            if sw.elapsed() >= timeout:
                raise WatchdogTimeout(
                    f"router: drain of replica {name!r} still has "
                    f"{h.engine.queued_count()} queued + "
                    f"{h.engine.resident_count()} resident after "
                    f"{timeout:.1f}s", what='replica drain',
                    waited=sw.elapsed())
            if not h.engine.alive():
                h.engine.pump()        # manual-drive replica: drive it
            else:
                time.sleep(_POLL_TICK)
        h.drained = True
        h.bump('drained_requests', pending)
        if _obs.enabled():
            _obs.counter('serving.router.drained',
                         labels={'replica': name}).inc()
            _obs.event('serving.router.drain', replica=name,
                       state='drained', drained=pending, aborted=0,
                       ms=round(sw.elapsed_ms(), 3))
        _obs.flight.record('router.drain', replica=name, state='drained',
                           drained=pending)
        self.emit_stats()
        return h.engine

    def readmit(self, name, engine=None, warm=False):
        """Return a drained/relaunched replica to rotation. ``engine=``
        swaps in a fresh engine (supervisor relaunch); unless ``warm``,
        it re-enters through the half-open probe gate so a cold compile
        storm meets bounded traffic."""
        h = self.replica(name)
        if engine is not None:
            h.engine = engine
            h.bump('restarts')
        h.draining = False
        h.drained = False
        if warm:
            h.breaker = CircuitBreaker(
                name, trip_after=self.policy.trip_after,
                cooldown_s=self.policy.circuit_cooldown_s,
                factor=self.policy.circuit_cooldown_factor,
                max_cooldown_s=self.policy.circuit_max_cooldown_s,
                jitter=self.policy.circuit_jitter,
                half_open_probes=self.policy.half_open_probes)
        else:
            h.breaker.force_half_open(reason='rejoin')
        if _obs.enabled():
            _obs.event('serving.router.rejoin', replica=name,
                       warm=bool(warm), relaunched=engine is not None)
        _obs.flight.record('router.rejoin', replica=name, warm=bool(warm))
        self.emit_stats()
        return h

    # -- introspection --------------------------------------------------
    def stats(self):
        with self._lock:
            handles = list(self._handles.values())
        return {'replicas': {h.name: h.stats_row() for h in handles},
                'shed_level': self.shed_level()}

    def health(self):
        """The fleet slice of ``/healthz``: per-replica gate inputs and
        verdicts."""
        with self._lock:
            handles = list(self._handles.values())
        out = {}
        for h in handles:
            out[h.name] = {
                'dispatchable': h.engine.dispatchable(),
                'draining': h.draining,
                'circuit': h.breaker.state,
                'queued': h.engine.queued_count(),
                'resident': h.engine.resident_count(),
            }
        return {'fleet': {'replicas': out, 'shed_level': self.shed_level()}}

    def emit_stats(self):
        """Cumulative ``serving.router_stats`` event (last one wins) —
        the feed for ``tools/telemetry_dump.py --serving``'s per-replica
        table."""
        if not _obs.enabled():
            return
        with self._lock:
            handles = list(self._handles.values())
        _obs.event('serving.router_stats',
                   replicas={h.name: h.stats_row() for h in handles},
                   shed_level=self.shed_level())
