"""Model runners: how one scheduler step turns queued requests into math.

Two execution shapes cover the inference surface:

- ``BatchRunner`` — one-shot predict models (classify/embed/score). Each
  engine step re-packs the queue into the smallest bucket that fits
  (dynamic batching): requests that arrived while the previous batch ran
  join the very next one. The batch callable is either ``jax.jit``-wrapped
  here (Layer / function models) or an ``Executor.run`` closure, in which
  case the Executor **program cache** is the warm-program store and its
  hit/miss counters are the cache telemetry.
- ``GenerativeRunner`` — iteration-level continuous batching for decode
  (Orca-style): every step admits waiting requests into free KV-cache
  slots (bucketed prefill), then runs ONE fixed-shape decode step for all
  active slots; finished sequences leave their slot immediately, so a
  short request never waits for a long one to finish. Greedy decode; the
  jitted step set is closed (one prefill per prompt bucket + one decode),
  so steady-state traffic compiles nothing. This is the FIXED-SLOT
  baseline (``register(..., kv_cache='slot')``): every sequence reserves
  ``max_seq`` rows. The default generative path is
  ``paged_runner.PagedGenerativeRunner`` — same scheduling contract over
  a paged cache (several times the concurrency at equal memory, prefix
  sharing, chunked prefill, speculative decoding).

Runners never block: ``step()`` does at most one batch / one decode
iteration and returns whether it did work; the engine's worker loop (or a
test's manual pump) drives it.
"""
import collections

import numpy as np
import jax
import jax.numpy as jnp

from .. import compilecache as _cc
from .. import observability as _obs
from .bucketing import (BucketSpec, pad_to_bucket, select_bucket,
                        stack_examples)
from .scheduler import STATUS_OK, STATUS_DEADLINE, STATUS_ERROR

__all__ = ['BatchRunner', 'GenerativeRunner', 'finish_request']


def _count(name, n=1):
    if _obs.enabled():
        _obs.counter(name).inc(n)


def _observe(name, v):
    if _obs.enabled():
        _obs.histogram(name).observe(v)


def finish_request(req, status, outputs=None, error=None):
    """Complete a request and mirror the outcome onto the telemetry spine
    (latency/queue-wait histograms, a per-request event carrying the
    queue/prefill/decode breakdown, the SLO tracker's judgment, and the
    closing edge of the request's async trace lane)."""
    req.complete(status, outputs, error=error)
    resp = req.response
    _count('serving.completed')
    _count(f'serving.status.{status}')
    from ..observability import slo as _slo
    _slo.record(req.model, status, resp.latency_ms)
    from .admission import record_completion
    record_completion(req, status, resp.latency_ms)
    if _obs.enabled():
        _obs.histogram('serving.latency_ms').observe(resp.latency_ms)
        _obs.histogram('serving.queue_wait_ms').observe(resp.queue_ms)
        _obs.event('serving.request', model=req.model, status=status,
                   tenant=getattr(req, 'tenant', None) or 'default',
                   latency_ms=round(resp.latency_ms, 3),
                   queue_ms=round(resp.queue_ms, 3),
                   **{f'{k}_ms': round(v, 3)
                      for k, v in resp.breakdown.items()})
        _obs.async_end('request', req.id, cat='serving.request',
                       status=status,
                       latency_ms=round(resp.latency_ms, 3))


def _slice_outputs(outs, i):
    """Per-request view of batched outputs: slice leading axis ``i`` through
    dict/tuple/list structure."""
    if isinstance(outs, dict):
        return {k: _slice_outputs(v, i) for k, v in outs.items()}
    if isinstance(outs, (list, tuple)):
        return type(outs)(_slice_outputs(v, i) for v in outs)
    return np.asarray(outs)[i]


class _Stats:
    """Plain always-on tallies (telemetry mirrors them when enabled)."""

    def __init__(self):
        self.completed = 0
        self.expired = 0
        self.errors = 0
        self.batches = 0
        self.joins = 0
        self.leaves = 0
        self.decode_tokens = 0
        self.prefill_tokens = 0
        self._occ_sum = 0.0
        self._occ_n = 0

    def occupancy(self, frac):
        self._occ_sum += frac
        self._occ_n += 1
        _observe('serving.batch_occupancy', frac)

    def as_dict(self):
        return {
            'completed': self.completed, 'expired': self.expired,
            'errors': self.errors, 'batches': self.batches,
            'joins': self.joins, 'leaves': self.leaves,
            'decode_tokens': self.decode_tokens,
            'prefill_tokens': self.prefill_tokens,
            'mean_batch_occupancy': (
                round(self._occ_sum / self._occ_n, 4) if self._occ_n else 0.0),
        }


class BatchRunner:
    """Dynamic batching over a one-shot batched callable.

    ``batch_fn(feeds)`` takes ``{name: array [B, ...]}`` and returns an
    array / tuple / dict with leading batch axis. ``example`` (one request's
    inputs, no batch axis) pins the shape/dtype spec: submits that disagree
    are rejected at admission, warmup knows what zeros to fabricate, and
    the compiled shape set stays closed. ``jit_compile=False`` is for
    callables that already manage compilation (Executor programs,
    Predictor exports).
    """

    kind = 'batch'

    def __init__(self, name, queue, batch_fn, example, bucket_spec=None,
                 jit_compile=True):
        self.name = name
        self.queue = queue
        self.spec = bucket_spec or BucketSpec()
        self.example = {k: np.asarray(v) for k, v in example.items()}
        self._jitted = bool(jit_compile)
        # CachedJit = jax.jit + the persistent executable tier: warmup
        # against a bound artifact dir deserializes instead of compiling
        self._fn = _cc.CachedJit(batch_fn) if jit_compile else batch_fn
        self.stats = _Stats()

    def validate(self, req):
        missing = sorted(set(self.example) - set(req.inputs))
        if missing:
            raise ValueError(
                f"serving[{self.name}]: request missing inputs {missing}")
        for k, ex in self.example.items():
            a = np.asarray(req.inputs[k])
            if a.shape != ex.shape or a.dtype != ex.dtype:
                raise ValueError(
                    f"serving[{self.name}]: input {k!r} has shape/dtype "
                    f"{a.shape}/{a.dtype}, registered example is "
                    f"{ex.shape}/{ex.dtype} — serving shapes are a closed "
                    "set (see serving.bucketing); pad client-side or "
                    "register a matching model")

    def has_work(self):
        return len(self.queue) > 0

    def evict_in_flight(self):
        """-> [(request, partial_outputs)] for requests resident in the
        runner but no longer in the queue (engine shutdown). One-shot
        batches are synchronous inside step(), so nothing is resident."""
        return []

    def warmup(self):
        """Ready every bucket once with zero feeds: against a bound
        compilecache artifact dir this deserializes the bucket's AOT
        executable (zero compiles); otherwise it compiles once — the only
        compiles a well-bucketed model ever pays. With telemetry on, each
        bucket's program is cost-ledgered either way (Executor-backed
        models are ledgered by the Executor itself at its cache miss)."""
        for b in self.spec.batch_buckets:
            feeds = {k: jnp.asarray(np.zeros((b,) + ex.shape, ex.dtype))
                     for k, ex in self.example.items()}
            if self._jitted:
                out = self._fn.warm(f'serving.{self.name}.b{b}', feeds,
                                    kind='serving.batch',
                                    meta={'model': self.name, 'bucket': b})
            else:
                out = self._fn(feeds)
            jax.tree_util.tree_map(lambda x: np.asarray(x), out)
        return len(self.spec.batch_buckets)

    def step(self):
        ready, expired = self.queue.pop_ready(self.spec.max_batch)
        for r in expired:
            self.stats.expired += 1
            _count('serving.deadline_expired')
            finish_request(r, STATUS_DEADLINE)
        if not ready:
            return bool(expired)
        bucket = self.spec.batch_bucket(len(ready))
        feeds = {k: jnp.asarray(
                     stack_examples([r.inputs[k] for r in ready], bucket))
                 for k in self.example}
        self.stats.batches += 1
        _count('serving.batches')
        self.stats.occupancy(len(ready) / bucket)
        telemetry = _obs.enabled()
        if telemetry:
            for r in ready:
                _obs.async_instant('batch', r.id, cat='serving.request',
                                   bucket=bucket, n=len(ready))
        try:
            with _obs.timer('serving.batch', model=self.name,
                            batch=len(ready), bucket=bucket) as t:
                outs = self._fn(feeds)
            outs = jax.tree_util.tree_map(np.asarray, outs)
            for r in ready:
                r.add_phase_ms('run', t.elapsed_ms)
            # slice before completing anything: a malformed output (e.g. no
            # leading batch axis) must fail the whole batch, not the engine
            per_req = [_slice_outputs(outs, i) for i in range(len(ready))]
        except Exception as e:                       # model bug: fail the
            for r in ready:                          # batch, not the engine
                self.stats.errors += 1
                finish_request(r, STATUS_ERROR, error=e)
            return True
        for r, out in zip(ready, per_req):
            self.stats.completed += 1
            status = STATUS_DEADLINE if r.expired() else STATUS_OK
            if status == STATUS_DEADLINE:
                self.stats.expired += 1
                _count('serving.deadline_expired')
            finish_request(r, status, out)
        return True


class GenerativeRunner:
    """Continuous batching: per-iteration join/leave over KV-cache slots.

    ``spec`` is a ``kv_cache.GenerativeSpec``. The runner owns the cache
    pytree and the slot table; requests are greedy-decoded. The compiled
    set is exactly ``len(spec.prompt_buckets)`` prefill programs plus one
    decode program — all fixed shapes, compiled at warmup.
    """

    kind = 'generative'

    def __init__(self, name, queue, spec, default_max_new_tokens=32):
        self.name = name
        self.queue = queue
        self.spec = spec
        self.default_max_new_tokens = int(default_max_new_tokens)
        self.cache = spec.init_cache()
        self.slots = [None] * spec.max_batch
        self.stats = _Stats()
        self.step_no = 0
        # join/leave journal for tests/debugging: (event, request_id, step)
        self.journal = collections.deque(maxlen=1024)

        def _prefill(cache, toks, length, slot):
            cache, logits = spec.prefill(cache, toks, length, slot)
            return cache, jnp.argmax(logits)

        def _decode(cache, toks, pos):
            cache, logits = spec.decode(cache, toks, pos)
            return cache, jnp.argmax(logits, axis=-1)

        self._prefill = _cc.CachedJit(_prefill)
        self._decode = _cc.CachedJit(_decode)

    def validate(self, req):
        toks = np.asarray(req.inputs.get('tokens', ()))
        if toks.size == 0:
            raise ValueError(
                f"serving[{self.name}]: generative request needs a "
                "non-empty 'tokens' input")
        if toks.ravel().shape[0] > self.spec.prompt_buckets[-1]:
            raise ValueError(
                f"serving[{self.name}]: prompt of {toks.ravel().shape[0]} "
                f"tokens exceeds the largest prompt bucket "
                f"{self.spec.prompt_buckets[-1]}")

    def has_work(self):
        return len(self.queue) > 0 or any(s is not None for s in self.slots)

    def evict_in_flight(self):
        """Vacate every occupied KV slot (engine shutdown): returns
        ``[(request, partial_outputs)]`` so the engine can complete them
        with their tokens-so-far instead of stranding the clients."""
        out = []
        for slot, s in enumerate(self.slots):
            if s is None:
                continue
            self.slots[slot] = None
            self.stats.leaves += 1
            _count('serving.leaves')
            self.journal.append(('leave', s['req'].id, self.step_no))
            out.append((s['req'],
                        {'tokens': np.asarray(s['tokens'], np.int32)}))
        return out

    def warmup(self):
        """Ready every prefill bucket + the decode step: deserialize from
        a bound compilecache artifact dir (zero compiles) or compile once.
        Uses slot 0 with dummy tokens; a real join later overwrites the
        slot's cache. With telemetry on, each program lands in the cost
        ledger either way."""
        n = 0
        for lb in self.spec.prompt_buckets:
            toks = jnp.asarray(np.zeros((lb,), np.int32))
            # length/slot must be int32 ARRAYS exactly like the real calls:
            # a python int here traces a weak-typed variant and the first
            # real request would recompile the bucket
            args = (self.cache, toks, jnp.asarray(1, jnp.int32),
                    jnp.asarray(0, jnp.int32))
            self.cache, _ = self._prefill.warm(
                f'serving.{self.name}.prefill{lb}', *args,
                kind='serving.prefill',
                meta={'model': self.name, 'bucket': lb})
            n += 1
        b = self.spec.max_batch
        dargs = (self.cache, jnp.asarray(np.zeros((b,), np.int32)),
                 jnp.asarray(np.zeros((b,), np.int32)))
        self.cache, _ = self._decode.warm(
            f'serving.{self.name}.decode', *dargs, kind='serving.decode',
            meta={'model': self.name, 'batch': b})
        return n + 1

    # -- one scheduler iteration ---------------------------------------
    def step(self):
        self.step_no += 1
        did = self._admit()
        did = self._decode_step() or did
        return did

    def _admit(self):
        free = [i for i, s in enumerate(self.slots) if s is None]
        if not free:
            # still reap already-dead requests so they don't rot in queue
            expired = self.queue.reap_expired()
            for r in expired:
                self._expire(r)
            return bool(expired)
        ready, expired = self.queue.pop_ready(len(free))
        for r in expired:
            self._expire(r)
        did = bool(expired)
        for r in ready:
            did = True
            slot = free.pop(0)
            prompt = np.asarray(r.inputs['tokens'], np.int32).ravel()
            lb = select_bucket(len(prompt), self.spec.prompt_buckets)
            padded = pad_to_bucket(prompt, lb)
            try:
                with _obs.timer('serving.prefill', model=self.name,
                                bucket=lb) as t:
                    self.cache, nxt = self._prefill(
                        self.cache, jnp.asarray(padded),
                        jnp.asarray(len(prompt), jnp.int32),
                        jnp.asarray(slot, jnp.int32))
                r.add_phase_ms('prefill', t.elapsed_ms)
            except Exception as e:                   # model bug: fail the
                self.stats.errors += 1               # request, not the
                free.insert(0, slot)                 # engine worker
                finish_request(r, STATUS_ERROR, error=e)
                continue
            first = int(np.asarray(nxt))
            self.stats.joins += 1
            self.stats.prefill_tokens += len(prompt)
            _count('serving.joins')
            _count('serving.prefill_tokens', len(prompt))
            self.journal.append(('join', r.id, self.step_no))
            if _obs.enabled():
                _obs.event('serving.join', model=self.name, request=r.id,
                           slot=slot, prompt_len=len(prompt))
                _obs.async_instant('prefill', r.id, cat='serving.request',
                                   slot=slot, bucket=lb,
                                   prompt_len=len(prompt))
            max_new = int(self.default_max_new_tokens
                          if r.max_new_tokens is None else r.max_new_tokens)
            state = {'req': r, 'tokens': [first], 'last': first,
                     'pos': len(prompt), 'max_new': max_new}
            self.slots[slot] = state
            self._maybe_finish(slot)
        return did

    def _decode_step(self):
        active = [i for i, s in enumerate(self.slots) if s is not None]
        if not active:
            return False
        b = self.spec.max_batch
        toks = np.zeros((b,), np.int32)
        pos = np.zeros((b,), np.int32)
        for i in active:
            toks[i] = self.slots[i]['last']
            pos[i] = self.slots[i]['pos']
        self.stats.batches += 1
        _count('serving.decode_steps')
        self.stats.occupancy(len(active) / b)
        try:
            with _obs.timer('serving.decode', model=self.name,
                            active=len(active)) as t:
                self.cache, nxt = self._decode(self.cache, jnp.asarray(toks),
                                               jnp.asarray(pos))
        except Exception as e:                       # model bug: fail the
            for i in active:                         # co-batched requests,
                s = self.slots[i]                    # not the engine worker
                self.slots[i] = None
                self.stats.errors += 1
                self.stats.leaves += 1
                _count('serving.leaves')
                self.journal.append(('leave', s['req'].id, self.step_no))
                finish_request(s['req'], STATUS_ERROR,
                               {'tokens': np.asarray(s['tokens'], np.int32)},
                               error=e)
            return True
        nxt = np.asarray(nxt)
        telemetry = _obs.enabled()
        for i in active:
            s = self.slots[i]
            s['pos'] += 1
            tok = int(nxt[i])
            s['tokens'].append(tok)
            s['last'] = tok
            s['req'].add_phase_ms('decode', t.elapsed_ms)
            self.stats.decode_tokens += 1
            _count('serving.decode_tokens')
            if telemetry:
                _obs.async_instant('decode', s['req'].id,
                                   cat='serving.request',
                                   tokens=len(s['tokens']))
            self._maybe_finish(i)
        return True

    # -- slot lifecycle -------------------------------------------------
    def _maybe_finish(self, slot):
        s = self.slots[slot]
        r = s['req']
        eos = self.spec.eos_id
        done = (len(s['tokens']) >= s['max_new'] or
                s['pos'] + 1 >= self.spec.max_seq or
                (eos is not None and s['last'] == eos))
        status = STATUS_OK
        if r.expired():
            done, status = True, STATUS_DEADLINE
            self.stats.expired += 1
            _count('serving.deadline_expired')
        if not done:
            return
        self.slots[slot] = None
        self.stats.leaves += 1
        self.stats.completed += 1
        _count('serving.leaves')
        self.journal.append(('leave', r.id, self.step_no))
        if _obs.enabled():
            _obs.event('serving.leave', model=self.name, request=r.id,
                       slot=slot, tokens=len(s['tokens']), status=status)
        finish_request(r, status,
                       {'tokens': np.asarray(s['tokens'], np.int32)})

    def _expire(self, req):
        self.stats.expired += 1
        _count('serving.deadline_expired')
        finish_request(req, STATUS_DEADLINE)
