"""Admission control + request lifecycle for the serving engine.

Production edges live here, not in the model runners:

- **bounded admission queue**: ``capacity`` requests per model; a full
  queue rejects at submit time (``QueueFullError`` — the HTTP-429 analogue)
  instead of growing an unbounded backlog whose tail can never meet its
  deadline anyway (load shedding).
- **per-request deadlines**: every request carries a budget measured from
  submit (``observability.Stopwatch``, the GL011-sanctioned clock). A
  request that expires while still queued is completed with status
  ``'deadline'`` *without* running — burning a batch slot on a response
  nobody is waiting for steals capacity from requests that can still win.
  Generative requests that expire mid-decode finish early with their
  partial output and the same status.
- **completion handoff**: the worker thread completes a request; the
  client blocks on ``PendingRequest.result()`` with a bounded, tick-based
  wait (``resilience.watchdog`` discipline — a dead engine raises instead
  of hanging the caller forever).
"""
import collections
import itertools
import threading

from ..observability.timing import Stopwatch
from ..resilience.watchdog import WatchdogTimeout

__all__ = ['QueueFullError', 'Request', 'Response', 'PendingRequest',
           'AdmissionQueue', 'STATUS_OK', 'STATUS_DEADLINE', 'STATUS_ERROR',
           'STATUS_CANCELLED']

STATUS_OK = 'ok'
STATUS_DEADLINE = 'deadline'
STATUS_ERROR = 'error'
STATUS_CANCELLED = 'cancelled'   # caller withdrew it (hedge loser, drain)

_WAIT_TICK = 0.05
_ids = itertools.count(1)


class QueueFullError(RuntimeError):
    """Admission queue at capacity: the request was shed (429-style).

    Raised at submit time so the client can back off / retry elsewhere;
    nothing was enqueued. ``reason`` distinguishes the two ways a backlog
    builds — ``'queue_full'`` (offered load exceeds drain rate: real
    overload) vs ``'page_exhaustion'`` (the paged KV cache is out of
    memory, so admission stalled and the queue backed up behind it). The
    engine stamps it from the runner's ``page_starved()`` signal so the
    doctor's ``serving_overload``/``kv_page_exhaustion`` detectors can
    tell traffic from memory pressure.
    """

    def __init__(self, model, capacity, reason='queue_full'):
        super().__init__(
            f"serving: model {model!r} admission queue is full "
            f"(capacity {capacity}) — request shed ({reason}); retry "
            "with backoff")
        self.model = model
        self.capacity = capacity
        self.reason = reason


class Response:
    """What a completed request resolves to.

    ``status`` is ``'ok'``, ``'deadline'`` (expired; ``outputs`` holds any
    partial generative output, else None) or ``'error'`` (``error`` holds
    the exception). ``latency_ms`` is submit→complete, ``queue_ms`` the
    part spent waiting for a batch slot, and ``breakdown`` the per-phase
    wall-time attribution the runners accumulate (``prefill``/``decode``/
    ``verify`` for generative models, ``run`` for one-shot batches; decode
    wall time is shared by every request co-resident in the batch).
    """

    __slots__ = ('status', 'outputs', 'model', 'request_id', 'latency_ms',
                 'queue_ms', 'error', 'breakdown')

    def __init__(self, status, outputs, model, request_id, latency_ms,
                 queue_ms, error=None, breakdown=None):
        self.status = status
        self.outputs = outputs
        self.model = model
        self.request_id = request_id
        self.latency_ms = latency_ms
        self.queue_ms = queue_ms
        self.error = error
        self.breakdown = breakdown or {}

    @property
    def ok(self):
        return self.status == STATUS_OK

    def __repr__(self):
        return (f"Response(status={self.status!r}, model={self.model!r}, "
                f"id={self.request_id}, latency_ms={self.latency_ms:.1f})")


class Request:
    """One inference request moving through the engine.

    ``inputs`` is a dict name -> per-example array (no batch axis) for
    one-shot models, or ``{'tokens': int array [L]}`` (+ ``max_new_tokens``)
    for generative ones. The engine owns all mutation after submit; clients
    only see the ``PendingRequest`` view.
    """

    __slots__ = ('id', 'model', 'inputs', 'deadline_ms', 'max_new_tokens',
                 'tenant', 'sw', 'queue_ms', 'phase_ms', '_event', 'response')

    def __init__(self, model, inputs, deadline_ms=None, max_new_tokens=None,
                 tenant=None):
        self.id = next(_ids)
        self.model = model
        self.inputs = inputs
        self.deadline_ms = None if deadline_ms is None else float(deadline_ms)
        self.max_new_tokens = max_new_tokens
        self.tenant = tenant or 'default'   # admission.DEFAULT_TENANT
        self.sw = Stopwatch()          # lifetime clock, started at submit
        self.queue_ms = 0.0
        self.phase_ms = {}             # runner-attributed wall ms per phase
        self._event = threading.Event()
        self.response = None

    def add_phase_ms(self, phase, ms):
        """Attribute ``ms`` of wall time to a lifecycle phase (prefill /
        decode / verify / run). Batched phases charge every participant
        the full batch wall time — the honest per-request view of time
        spent in that phase, not an exclusive-device accounting."""
        self.phase_ms[phase] = self.phase_ms.get(phase, 0.0) + float(ms)

    def expired(self):
        return (self.deadline_ms is not None and
                self.sw.elapsed_ms() > self.deadline_ms)

    def remaining_ms(self):
        if self.deadline_ms is None:
            return None
        return self.deadline_ms - self.sw.elapsed_ms()

    def complete(self, status, outputs=None, error=None):
        if self._event.is_set():
            return                     # first completion wins
        self.response = Response(status, outputs, self.model, self.id,
                                 self.sw.elapsed_ms(), self.queue_ms,
                                 error=error,
                                 breakdown={k: round(v, 3) for k, v
                                            in self.phase_ms.items()})
        self._event.set()

    def done(self):
        return self._event.is_set()


class PendingRequest:
    """Client-side handle: a future over one Request."""

    __slots__ = ('_req', '_alive')

    def __init__(self, req, alive):
        self._req = req
        self._alive = alive            # () -> bool: is the engine running?

    @property
    def request_id(self):
        return self._req.id

    def done(self):
        return self._req.done()

    def result(self, timeout=None):
        """Block (tick-based, watchdog discipline) for the Response.

        Raises ``WatchdogTimeout`` when ``timeout`` seconds pass, or when
        the engine stops while the request is still in flight — a dead
        worker must never strand its clients in an unbounded wait.
        """
        sw = Stopwatch()
        while not self._req._event.wait(_WAIT_TICK):
            if timeout is not None and sw.elapsed() >= timeout:
                raise WatchdogTimeout(
                    f"serving: no response for request {self._req.id} "
                    f"within {timeout:.1f}s", what='serving response',
                    waited=sw.elapsed())
            if not self._alive():
                # one grace tick: stop() completes queued/in-flight
                # requests as shaped errors just after the worker dies —
                # prefer that answer to a raw timeout
                if self._req._event.wait(_WAIT_TICK):
                    break
                raise WatchdogTimeout(
                    f"serving: engine stopped with request {self._req.id} "
                    "still in flight", what='serving response',
                    waited=sw.elapsed())
        resp = self._req.response
        if resp.status == STATUS_ERROR and resp.error is not None:
            raise resp.error
        return resp


class AdmissionQueue:
    """Bounded FIFO per model, with deadline-aware pops.

    ``push`` raises ``QueueFullError`` at capacity (shed). ``pop_ready``
    returns up to ``max_n`` live requests and separately the queued
    requests whose deadline already expired (the caller completes those
    with status ``'deadline'`` and never runs them).
    """

    def __init__(self, model, capacity=256):
        self.model = model
        self.capacity = int(capacity)
        self._dq = collections.deque()
        self._lock = threading.Lock()

    def __len__(self):
        return len(self._dq)

    def push(self, req):
        with self._lock:
            if len(self._dq) >= self.capacity:
                raise QueueFullError(self.model, self.capacity)
            self._dq.append(req)

    def push_front(self, req):
        """Re-admit ``req`` at the head of the queue, bypassing the
        capacity check: the request was already admitted once (a paged
        runner stalling on KV pages, or a preemption) and must not be
        shed on its way back in."""
        with self._lock:
            self._dq.appendleft(req)

    def pop_ready(self, max_n):
        """-> (ready, expired): up to ``max_n`` live requests in FIFO
        order, plus every expired request encountered on the way."""
        return self.pop_ready_while(None, max_n)

    def pop_ready_while(self, admit, max_n):
        """Admission-gated pop: like ``pop_ready`` but stops at the first
        live request ``admit(req)`` declines (strict FIFO — no head-of-
        line jumping). The paged runner's predicate gates on **free KV
        pages**, not free slots: a prompt whose pages cannot be allocated
        right now stays queued, and everything behind it waits its turn.
        ``admit=None`` admits everything."""
        ready, expired = [], []
        with self._lock:
            while self._dq and len(ready) < max_n:
                req = self._dq[0]
                if req.expired():
                    expired.append(self._dq.popleft())
                    continue
                if admit is not None and not admit(req):
                    break
                ready.append(self._dq.popleft())
        # expired requests spent their WHOLE life queued — stamp them too,
        # or the queue-wait histogram under-reports exactly the longest
        # waiters
        for r in ready + expired:
            r.queue_ms = r.sw.elapsed_ms()
        return ready, expired

    def remove(self, req):
        """Withdraw ``req`` if it is still queued. Returns True when it was
        removed (never popped by the worker), False when the worker already
        owns it — the caller must then let it run to completion. The
        router's hedge path uses this: a hedge loser still waiting for a
        batch slot is cancelled for free; one already resident finishes
        and its answer is discarded."""
        with self._lock:
            try:
                self._dq.remove(req)
            except ValueError:
                return False
        return True

    def reap_expired(self):
        """Remove and return every expired request anywhere in the queue
        (used when no batch slot is free: a dead request must not wait for
        one just to be told it's dead)."""
        expired, live = [], []
        with self._lock:
            for r in self._dq:
                (expired if r.expired() else live).append(r)
            self._dq.clear()
            self._dq.extend(live)
        for r in expired:
            r.queue_ms = r.sw.elapsed_ms()
        return expired

    def drain(self):
        """Remove and return every queued request (engine shutdown)."""
        with self._lock:
            out = list(self._dq)
            self._dq.clear()
        return out
