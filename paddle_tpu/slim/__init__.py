"""Model compression (quantization). Parity surface:
python/paddle/fluid/contrib/slim/quantization — QAT transform pass and
post-training quantization, rebuilt as layer wrapping + calibration
(see quant.py / qat.py / ptq.py docstrings for the design mapping).
"""
from .quant import (abs_max_scale, channel_abs_max_scale, kl_scale,
                    quantize_weight, dequantize_weight, fake_quant_dequant,
                    FakeQuantAbsMax, MovingAverageAbsMax)
from .qat import QuantedLinear, QuantedConv2D, quantize_qat
from .ptq import (PostTrainingQuantization, Int8Linear, Int8Conv2D,
                  save_quantized_model, load_quantized_model)

__all__ = ['abs_max_scale', 'channel_abs_max_scale', 'kl_scale',
           'quantize_weight', 'dequantize_weight', 'fake_quant_dequant',
           'FakeQuantAbsMax', 'MovingAverageAbsMax',
           'QuantedLinear', 'QuantedConv2D', 'quantize_qat',
           'PostTrainingQuantization', 'Int8Linear', 'Int8Conv2D',
           'save_quantized_model', 'load_quantized_model']
