"""Post-training quantization over a trained model.

Parity: fluid/contrib/slim/quantization/post_training_quantization.py —
the reference runs calibration batches through the inference Program,
collects per-tensor activation ranges ('abs_max' / 'KL' algos), then
rewrites weights to int8. Here calibration attaches forward pre-hooks on
quantizable layers, and ``quantize()`` swaps them for int8-weight layers
(int8 payload + scale held; dequantized on the fly for the bf16/fp32 MXU
matmul — weight-only storage quantization plus simulated activation
quantization, the TPU-honest equivalent of the reference's int8 kernels).

``save_quantized_model``/``load_quantized_model`` round-trip the int8
payloads + scales through an .npz, quartering weight bytes on disk.
"""
import numpy as np

from .. import nn
from ..core.tensor import Tensor
from .quant import (kl_scale_from_hist, quantize_weight, fake_quant_dequant)

__all__ = ['PostTrainingQuantization', 'Int8Linear', 'Int8Conv2D',
           'save_quantized_model', 'load_quantized_model']


class _Int8Layer(nn.Layer):
    """Shared int8-weight wrapper.

    The int8 payload (device array) + scale are the only persistent copy of
    the weight — the inner layer's fp32 Parameter is released (set to None;
    named_parameters/state_dict skip None slots), so resident weight bytes
    really are quartered. Each forward dequantizes transiently (XLA fuses
    the int8->fp cast+scale into the consumer matmul/conv under jit) and
    fake-quants the input activation with the calibrated scale.
    """

    def __init__(self, layer, weight_name, channel_axis, act_scale,
                 weight_bits=8, activation_bits=8, q_payload=None):
        super().__init__()
        self.inner = layer
        self._wname = weight_name
        self._axis = channel_axis
        self.act_scale = act_scale
        self.act_bits = activation_bits
        if q_payload is None:
            w = getattr(layer, weight_name)
            q_payload = quantize_weight(np.asarray(w.numpy()),
                                        bits=weight_bits,
                                        channel_axis=channel_axis)
        self._adopt(*q_payload)

    def _adopt(self, q_np, scale):
        """Install an int8 payload + scale and release the fp Parameter."""
        import jax.numpy as jnp
        self.q_weight = jnp.asarray(q_np)
        self.w_scale = scale
        shape = [1] * self.q_weight.ndim
        shape[self._axis] = -1
        self._scale_dev = jnp.asarray(
            np.asarray(scale, np.float32).reshape(shape)
            if np.ndim(scale) else np.float32(scale))
        self.inner._parameters[self._wname] = None   # free the fp32 copy
        self.inner.__dict__.pop(self._wname, None)

    def _dequantized(self):
        import jax.numpy as jnp
        from ..core.tensor import Tensor
        return Tensor(self.q_weight.astype(jnp.float32) * self._scale_dev)

    def forward(self, x):
        if self.act_scale is not None:
            x = fake_quant_dequant(x, self.act_scale, self.act_bits)
        # shadow the (released) Parameter slot with the transient weight
        setattr(self.inner, self._wname, self._dequantized())
        try:
            return self.inner(x)
        finally:
            self.inner.__dict__.pop(self._wname, None)


class Int8Linear(_Int8Layer):
    """weight layout (in, out): per-out-channel scales on axis 1."""

    def __init__(self, layer, act_scale=None, **kw):
        super().__init__(layer, 'weight', 1, act_scale, **kw)


class Int8Conv2D(_Int8Layer):
    """weight layout (out, in, kh, kw): per-out-channel scales on axis 0."""

    def __init__(self, layer, act_scale=None, **kw):
        super().__init__(layer, 'weight', 0, act_scale, **kw)


_PTQ_RULES = {nn.Linear: Int8Linear, nn.Conv2D: Int8Conv2D}


class _AbsMaxObserver:
    """O(1)-memory running abs-max over calibration batches."""

    def __init__(self, bits):
        self.bits = bits
        self.amax = 0.0

    def observe(self, arr):
        self.amax = max(self.amax, float(np.abs(arr).max()))

    def scale(self):
        qmax = 2 ** (self.bits - 1) - 1
        return (self.amax or 1.0) / qmax


class _HistObserver:
    """O(bins)-memory abs-value histogram for KL calibration.

    The range grows by doubling when a batch exceeds it, merging adjacent
    bin pairs — an exact rebin, so the histogram stays faithful without
    retaining any activation tensors.
    """

    def __init__(self, bits, bins=2048):
        self.bits = bits
        self.bins = bins
        self.range = None
        self.hist = np.zeros(bins, np.float64)

    def observe(self, arr):
        a = np.abs(np.asarray(arr, np.float32)).reshape(-1)
        amax = float(a.max()) if a.size else 0.0
        if self.range is None:
            self.range = amax or 1e-8
        while amax > self.range:
            merged = self.hist.reshape(-1, 2).sum(axis=1)
            self.hist = np.concatenate(
                [merged, np.zeros(self.bins // 2, np.float64)])
            self.range *= 2
        h, _ = np.histogram(a, bins=self.bins, range=(0, self.range))
        self.hist += h

    def scale(self):
        if self.range is None:
            return 1.0 / (2 ** (self.bits - 1) - 1)
        edges = np.linspace(0, self.range, self.bins + 1)
        return kl_scale_from_hist(self.hist, edges, self.bits)


class PostTrainingQuantization:
    """Calibrate activation scales on sample data, then quantize.

    model: trained Layer; data_loader: iterable of input batches (a Tensor,
    or a tuple whose first element is the input); algo: 'abs_max' | 'KL'.
    Calibration is O(1)/O(bins) memory per layer — activations are folded
    into running observers, never retained.
    """

    def __init__(self, model, data_loader, algo='abs_max', batch_nums=None,
                 activation_bits=8, weight_bits=8):
        if algo not in ('abs_max', 'KL'):
            raise ValueError("algo must be 'abs_max' or 'KL', got %r" % algo)
        self.model = model
        self.data_loader = data_loader
        self.algo = algo
        self.batch_nums = batch_nums
        self.activation_bits = activation_bits
        self.weight_bits = weight_bits
        self._observers = {}     # layer name -> observer

    def _calibrate(self):
        hooks = []

        def make_hook(key):
            obs_cls = _HistObserver if self.algo == 'KL' else _AbsMaxObserver
            self._observers[key] = obs = obs_cls(self.activation_bits)

            def hook(layer, inputs):
                x = inputs[0] if isinstance(inputs, tuple) else inputs
                obs.observe(np.asarray(
                    x.numpy() if isinstance(x, Tensor) else x))
            return hook

        for name, sub in self.model.named_sublayers():
            if type(sub) in _PTQ_RULES:
                hooks.append(sub.register_forward_pre_hook(make_hook(name)))
        was_training = self.model.training
        self.model.eval()
        try:
            for i, batch in enumerate(self.data_loader):
                if self.batch_nums is not None and i >= self.batch_nums:
                    break
                x = batch[0] if isinstance(batch, (tuple, list)) else batch
                if not isinstance(x, Tensor):
                    x = Tensor(np.asarray(x))
                self.model(x)
        finally:
            for h in hooks:
                h.remove()
            if was_training:
                self.model.train()

    def quantize(self):
        """Returns the model with quantizable sublayers swapped for int8
        wrappers (in place)."""
        self._calibrate()
        rules = _PTQ_RULES
        scales = {name: obs.scale()
                  for name, obs in self._observers.items()}

        def swap(layer, prefix=''):
            for name, child in list(layer._sub_layers.items()):
                full = prefix + name
                cls = rules.get(type(child))
                if cls is not None:
                    layer._sub_layers[name] = cls(
                        child, act_scale=scales.get(full),
                        weight_bits=self.weight_bits,
                        activation_bits=self.activation_bits)
                else:
                    swap(child, full + '.')
            return layer

        return swap(self.model)


def save_quantized_model(model, path):
    """Persist a PTQ-quantized model: int8 payloads + scales for wrapped
    layers, fp32 for everything else, one .npz."""
    arrays = {}
    for name, sub in model.named_sublayers(include_self=True):
        if isinstance(sub, _Int8Layer):
            arrays['q:%s:weight' % name] = sub.q_weight
            arrays['q:%s:w_scale' % name] = np.asarray(sub.w_scale)
            if sub.act_scale is not None:
                arrays['q:%s:act_scale' % name] = np.asarray(sub.act_scale)
            bias = getattr(sub.inner, 'bias', None)
            if bias is not None:
                arrays['q:%s:bias' % name] = np.asarray(bias.numpy())
    # non-quantized params by state_dict key
    quant_prefixes = tuple(
        name + '.' for name, sub in model.named_sublayers(include_self=True)
        if isinstance(sub, _Int8Layer))
    for k, v in model.state_dict().items():
        if not k.startswith(quant_prefixes):
            arrays['p:' + k] = np.asarray(v.numpy())
    np.savez(path, **arrays)


def load_quantized_model(model, path, activation_bits=8):
    """Rebuild int8 wrappers on a fresh (same-architecture) model from a
    save_quantized_model archive; returns the model."""
    import jax.numpy as jnp
    data = np.load(path)
    qnames = sorted({k.split(':')[1] for k in data.files
                     if k.startswith('q:')})
    rules = _PTQ_RULES

    def find(layer, dotted):
        obj = layer
        for part in dotted.split('.'):
            obj = obj._sub_layers[part]
        return obj

    def parent_of(layer, dotted):
        parts = dotted.split('.')
        obj = layer
        for part in parts[:-1]:
            obj = obj._sub_layers[part]
        return obj, parts[-1]

    for name in qnames:
        child = find(model, name)
        cls = rules.get(type(child))
        if cls is None:
            raise ValueError("layer %r is not quantizable (%s)"
                             % (name, type(child).__name__))
        act_key = 'q:%s:act_scale' % name
        wrapper = cls(child,
                      act_scale=(float(data[act_key])
                                 if act_key in data.files else None),
                      activation_bits=activation_bits,
                      q_payload=(data['q:%s:weight' % name],
                                 data['q:%s:w_scale' % name]))
        bias_key = 'q:%s:bias' % name
        if bias_key in data.files and child.bias is not None:
            child.bias._inplace_value(jnp.asarray(data[bias_key]))
        parent, leaf = parent_of(model, name)
        parent._sub_layers[leaf] = wrapper
    # restore untouched params
    sd = model.state_dict()
    for k in data.files:
        if k.startswith('p:') and k[2:] in sd:
            sd[k[2:]]._inplace_value(jnp.asarray(data[k]))
    return model
