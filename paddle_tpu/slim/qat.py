"""Quantization-aware training: quant wrappers over Linear/Conv2D.

Parity: fluid/contrib/slim/quantization/quantization_pass.py
(QuantizationTransformPass) — the reference walks the Program IR and
inserts fake_quant ops before every quantizable op's weight/activation
inputs; here the same effect is layer wrapping: ``quantize_qat(model)``
swaps each Linear/Conv2D for a wrapper that fake-quant-dequants its
weight (per-channel abs-max) and input activation (moving-average
abs-max) on every forward, with straight-through gradients.
"""
import numpy as np
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from .quant import FakeQuantAbsMax, MovingAverageAbsMax

__all__ = ['QuantedLinear', 'QuantedConv2D', 'quantize_qat']


class _QuantWrapper(nn.Layer):
    def __init__(self, layer, weight, channel_axis, weight_bits=8,
                 activation_bits=8):
        super().__init__()
        self.inner = layer
        self._wname = weight
        self.weight_quanter = FakeQuantAbsMax(weight_bits, channel_axis)
        self.act_quanter = MovingAverageAbsMax(activation_bits)
        # the EMA activation scale must survive save/load: mirror it in a
        # persistable buffer (negative sentinel = not yet observed)
        self.register_buffer('act_scale',
                             Tensor(np.array([-1.0], np.float32)))

    def forward(self, x):
        if self.act_quanter.scale is None:
            restored = float(self.act_scale.numpy()[0])
            if restored > 0:   # a state_dict round-trip restored the scale
                self.act_quanter.scale = restored
        x = self.act_quanter(x, training=self.training)
        if self.act_quanter.scale is not None:
            self.act_scale._inplace_value(jnp.asarray(
                np.array([self.act_quanter.scale], np.float32)))
        qw = self.weight_quanter(getattr(self.inner, self._wname))
        # shadow the Parameter with the fake-quantized weight for this call:
        # a plain Tensor assigned via __setattr__ lands in __dict__ and wins
        # attribute lookup; popping it un-shadows the untouched Parameter
        setattr(self.inner, self._wname, qw)
        try:
            out = self.inner(x)
        finally:
            self.inner.__dict__.pop(self._wname, None)
        return out


class QuantedLinear(_QuantWrapper):
    """Linear with fake-quantized weight (per-out-channel, axis 1: weight
    layout is (in, out)) + input activation."""

    def __init__(self, layer, **kw):
        super().__init__(layer, 'weight', channel_axis=1, **kw)


class QuantedConv2D(_QuantWrapper):
    """Conv2D with fake-quantized weight (per-out-channel, axis 0: weight
    layout is (out, in, kh, kw)) + input activation."""

    def __init__(self, layer, **kw):
        super().__init__(layer, 'weight', channel_axis=0, **kw)


_QAT_RULES = {nn.Linear: QuantedLinear, nn.Conv2D: QuantedConv2D}


def quantize_qat(model, weight_bits=8, activation_bits=8):
    """Swap every Linear/Conv2D in ``model`` (in place, recursively) for
    its quant-aware wrapper; returns the model. Train as usual afterwards —
    state_dict keys gain an ``inner.`` segment, matching the wrapper tree.
    """
    for name, child in list(model._sub_layers.items()):
        cls = _QAT_RULES.get(type(child))
        if cls is not None:
            model._sub_layers[name] = cls(
                child, weight_bits=weight_bits,
                activation_bits=activation_bits)
        else:
            quantize_qat(child, weight_bits, activation_bits)
    return model
