"""Quantization core: scales, fake-quant (QAT), int8 payloads.

Parity targets:
- fluid/contrib/slim/quantization/quantization_pass.py — the reference's
  QAT pass rewrites the Program graph, inserting fake_quantize/dequantize
  ops around weights and activations; here the same math is a
  straight-through-estimator ``fake_quant_dequant`` applied functionally
  inside quant-aware layer wrappers (no graph surgery — XLA retraces).
- post_training_quantization.py — activation-scale calibration by
  abs-max / histogram-KL over sample batches, then weight conversion to
  int8 with per-tensor or per-channel scales.

TPU-first notes: simulated-quant compute stays in fp32/bf16 (dequantized
weights feed the MXU — int8 storage quarters checkpoint/HBM weight bytes,
which is where the inference win is on TPU); symmetric signed-int8
quantization only, the scheme both the reference's defaults and XLA's
int8 dot support share.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op

__all__ = ['abs_max_scale', 'channel_abs_max_scale', 'kl_scale',
           'kl_scale_from_hist', 'quantize_weight', 'dequantize_weight',
           'fake_quant_dequant', 'FakeQuantAbsMax', 'MovingAverageAbsMax']


def abs_max_scale(x, bits=8):
    """Per-tensor symmetric scale: max|x| / (2^(bits-1) - 1)."""
    qmax = 2 ** (bits - 1) - 1
    return float(np.abs(np.asarray(x)).max()) / qmax or 1.0 / qmax


def channel_abs_max_scale(w, axis, bits=8):
    """Per-output-channel scales along ``axis``."""
    qmax = 2 ** (bits - 1) - 1
    w = np.asarray(w)
    red = tuple(i for i in range(w.ndim) if i != axis)
    s = np.abs(w).max(axis=red) / qmax
    return np.where(s == 0, 1.0 / qmax, s).astype(np.float32)


def kl_scale(samples, bits=8, bins=2048):
    """Histogram-KL calibration (the reference PTQ's 'KL' algo): choose the
    clip threshold whose quantized distribution has minimal KL divergence
    from the original, then scale = threshold / qmax."""
    qmax = 2 ** (bits - 1) - 1
    x = np.abs(np.concatenate([np.asarray(s).reshape(-1)
                               for s in samples]))
    amax = x.max()
    if amax == 0:
        return 1.0 / qmax
    hist, edges = np.histogram(x, bins=bins, range=(0, amax))
    return kl_scale_from_hist(hist, edges, bits)


def kl_scale_from_hist(hist, edges, bits=8):
    """KL threshold search over a prebuilt abs-value histogram (lets PTQ
    calibrate in O(bins) memory instead of retaining activations)."""
    qmax = 2 ** (bits - 1) - 1
    levels = 2 ** (bits - 1)   # abs-value histogram: positive levels only
    bins = len(hist)
    hist = np.asarray(hist, np.float64)
    if hist.sum() == 0:
        return 1.0 / qmax
    best_kl, best_t = np.inf, bins
    for t in range(levels, bins + 1, 16):
        p = hist[:t].copy()
        p[t - 1] += hist[t:].sum()        # clip tail mass into last bin
        if p.sum() == 0:
            continue
        # quantize the first t bins down to `levels` buckets
        chunks = np.array_split(hist[:t], levels)
        q = np.concatenate([
            np.full(len(c), c.sum() / max((c > 0).sum(), 1)) * (c > 0)
            for c in chunks])
        p /= p.sum()
        qs = q.sum()
        if qs == 0:
            continue
        q /= qs
        mask = p > 0
        kl = float(np.sum(p[mask] * np.log(
            p[mask] / np.maximum(q[mask], 1e-12))))
        if kl < best_kl:
            best_kl, best_t = kl, t
    threshold = edges[best_t]
    return float(threshold) / qmax


def quantize_weight(w, bits=8, channel_axis=None):
    """fp weight -> (int8 payload, scale). Per-channel when channel_axis
    is given (the reference quantizes conv/linear weights per output
    channel by default)."""
    w = np.asarray(w, np.float32)
    if channel_axis is None:
        scale = abs_max_scale(w, bits)
        q = np.clip(np.round(w / scale), -127, 127).astype(np.int8)
        return q, np.float32(scale)
    scale = channel_abs_max_scale(w, channel_axis, bits)
    shape = [1] * w.ndim
    shape[channel_axis] = -1
    q = np.clip(np.round(w / scale.reshape(shape)), -127, 127) \
        .astype(np.int8)
    return q, scale


def dequantize_weight(q, scale, channel_axis=None, dtype=np.float32):
    q = np.asarray(q)
    if channel_axis is None:
        return (q.astype(np.float32) * float(scale)).astype(dtype)
    shape = [1] * q.ndim
    shape[channel_axis] = -1
    return (q.astype(np.float32) *
            np.asarray(scale).reshape(shape)).astype(dtype)


@jax.custom_vjp
def _fake_qdq(x, scale, qmax):
    q = jnp.clip(jnp.round(x / scale), -qmax, qmax)
    return q * scale


def _fake_qdq_fwd(x, scale, qmax):
    return _fake_qdq(x, scale, qmax), (x, scale, qmax)


def _fake_qdq_bwd(res, g):
    # straight-through estimator: pass the gradient inside the clip range,
    # zero it outside (the reference's fake_quantize grad kernel)
    x, scale, qmax = res
    inside = (jnp.abs(x) <= scale * qmax).astype(g.dtype)
    return g * inside, None, None


_fake_qdq.defvjp(_fake_qdq_fwd, _fake_qdq_bwd)


def fake_quant_dequant(x, scale, bits=8):
    """Simulated quantization with straight-through gradients; ``scale``
    may be per-tensor (scalar) or broadcastable per-channel."""
    from ..tensor._helpers import _t
    x = _t(x)
    qmax = float(2 ** (bits - 1) - 1)
    scale_arr = jnp.asarray(np.asarray(scale, np.float32))

    def fn(v):
        return _fake_qdq(v.astype(jnp.float32), scale_arr, qmax) \
            .astype(v.dtype)

    return apply_op(fn, (x,))


class FakeQuantAbsMax:
    """Weight quantizer: fresh abs-max scale each call (weights change
    every step under QAT)."""

    def __init__(self, bits=8, channel_axis=None):
        self.bits = bits
        self.channel_axis = channel_axis

    def scale_of(self, w):
        wnp = np.asarray(w.numpy() if isinstance(w, Tensor) else w)
        if self.channel_axis is None:
            return abs_max_scale(wnp, self.bits)
        s = channel_abs_max_scale(wnp, self.channel_axis, self.bits)
        shape = [1] * wnp.ndim
        shape[self.channel_axis] = -1
        return s.reshape(shape)

    def __call__(self, w):
        return fake_quant_dequant(w, self.scale_of(w), self.bits)


class MovingAverageAbsMax:
    """Activation quantizer: EMA of batch abs-max (the reference's
    moving_average_abs_max); frozen scale at eval."""

    def __init__(self, bits=8, momentum=0.9):
        self.bits = bits
        self.momentum = momentum
        self.scale = None

    def observe(self, x):
        s = abs_max_scale(np.asarray(
            x.numpy() if isinstance(x, Tensor) else x), self.bits)
        self.scale = s if self.scale is None else \
            self.momentum * self.scale + (1 - self.momentum) * s

    def __call__(self, x, training=True):
        if training:
            self.observe(x)
        if self.scale is None:
            return x
        return fake_quant_dequant(x, self.scale, self.bits)
