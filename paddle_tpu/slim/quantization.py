"""The 1.8 ``fluid.contrib.slim.quantization`` surface. Parity:
python/paddle/fluid/contrib/slim/quantization/*.py.

TPU-first redesign: the reference's quantization is ProgramDesc IR passes
(insert fake-quant ops, freeze, convert); here quantization is LAYER
WRAPPING + calibration (slim/quant.py, qat.py, ptq.py) because the whole
program is one XLA computation — there is no op-graph to mutate. The
class names below keep 1.8 scripts importable: the ones with a direct
analogue delegate to it; the pass-pipeline classes raise with the
replacement recipe.
"""
from . import (  # noqa: F401
    FakeQuantAbsMax, MovingAverageAbsMax, QuantedLinear, QuantedConv2D,
    quantize_qat, PostTrainingQuantization, Int8Linear, Int8Conv2D,
    save_quantized_model, load_quantized_model, quantize_weight,
    dequantize_weight)

__all__ = [
    'FakeQuantAbsMax', 'FakeQuantMovingAverage', 'QuantizedConv2D',
    'QuantizedLinear', 'ImperativeQuantAware', 'PostTrainingQuantization',
    'WeightQuantization', 'QuantizationTransformPass',
    'QuantizationFreezePass', 'ConvertToInt8Pass', 'AddQuantDequantPass',
    'OutScaleForTrainingPass', 'OutScaleForInferencePass',
    'TransformForMobilePass', 'QuantInt8MkldnnPass', 'Quant2Int8MkldnnPass',
]

# 1.8 spellings of the layer wrappers / observers
FakeQuantMovingAverage = MovingAverageAbsMax
QuantizedConv2D = QuantedConv2D
QuantizedLinear = QuantedLinear


class ImperativeQuantAware:
    """Dygraph QAT driver (imperative/qat.py ImperativeQuantAware):
    ``quantize(model)`` wraps Linear/Conv2D sublayers with fake-quant
    (slim.quantize_qat); ``save_quantized_model`` emits the int8-resident
    artifact."""

    def __init__(self, weight_bits=8, activation_bits=8,
                 weight_quantize_type='abs_max',
                 activation_quantize_type='moving_average_abs_max',
                 moving_rate=0.9, quantizable_layer_type=None):
        self._weight_bits = weight_bits
        self._activation_bits = activation_bits

    def quantize(self, model):
        return quantize_qat(model, weight_bits=self._weight_bits,
                            activation_bits=self._activation_bits)

    def save_quantized_model(self, model, path, input_spec=None):
        return save_quantized_model(model, path)


class WeightQuantization:
    """Weight-only quantization of a saved model
    (quantization/quantize_transpiler_v2... WeightQuantization): loads the
    state, int8-quantizes every >=2-D float weight (per-channel abs-max),
    saves the quantized artifact."""

    def __init__(self, model_dir, model_filename=None, params_filename=None):
        self._model_dir = model_dir
        self._model_filename = model_filename
        self._params_filename = params_filename

    def quantize_weight_to_int8(self, save_model_dir, weight_bits=8,
                                quantizable_op_type=None, threshold_rate=0.0):
        import os
        import pickle
        import numpy as np
        src = os.path.join(self._model_dir,
                           self._params_filename or '__persistables__')
        with open(src, 'rb') as f:
            state = pickle.load(f)
        out = {}
        for name, arr in state.items():
            arr = np.asarray(arr)
            if arr.ndim >= 2 and arr.dtype in (np.float32, np.float64):
                # paddle conv weights are (oc, ic, kh, kw): per-OUTPUT-
                # channel scales (axis 0); linear weights (in, out): axis -1
                axis = 0 if arr.ndim == 4 else arr.ndim - 1
                q, scale = quantize_weight(arr, bits=weight_bits,
                                           channel_axis=axis)
                out[name] = {'int8': np.asarray(q), 'scale': np.asarray(scale)}
            else:
                out[name] = arr
        os.makedirs(save_model_dir, exist_ok=True)
        dst = os.path.join(save_model_dir,
                           self._params_filename or '__persistables__')
        from ..resilience.atomic_io import atomic_pickle_dump
        atomic_pickle_dump(out, dst)
        return dst


def _pass_shim(name, recipe):
    class _Pass:
        def __init__(self, *a, **k):
            raise RuntimeError(
                f"{name} mutates the ProgramDesc op graph, which this "
                f"TPU-first build replaces with layer wrapping + "
                f"calibration. Use {recipe} instead.")
    _Pass.__name__ = name
    _Pass.__qualname__ = name
    return _Pass


QuantizationTransformPass = _pass_shim(
    'QuantizationTransformPass',
    'slim.quantize_qat(model) (fake-quant wrapping, STE custom_vjp)')
QuantizationFreezePass = _pass_shim(
    'QuantizationFreezePass',
    'slim.save_quantized_model (scales persist with the artifact)')
ConvertToInt8Pass = _pass_shim(
    'ConvertToInt8Pass',
    'slim.PostTrainingQuantization(...).quantize() (int8-resident weights)')
AddQuantDequantPass = _pass_shim(
    'AddQuantDequantPass', 'slim.quantize_qat activation fake-quant')
OutScaleForTrainingPass = _pass_shim(
    'OutScaleForTrainingPass',
    'slim.quantize_qat (per-layer moving-average scales train in-line)')
OutScaleForInferencePass = _pass_shim(
    'OutScaleForInferencePass',
    'slim.save_quantized_model (scales are saved with the model)')
TransformForMobilePass = _pass_shim(
    'TransformForMobilePass',
    'jit.save / inference.Predictor (StableHLO export serves all targets)')
QuantInt8MkldnnPass = _pass_shim(
    'QuantInt8MkldnnPass', 'slim.PostTrainingQuantization (mkldnn is '
    'CPU-specific; XLA lowers int8 natively)')
Quant2Int8MkldnnPass = _pass_shim(
    'Quant2Int8MkldnnPass', 'slim.PostTrainingQuantization')
