"""paddle_tpu.static. Parity: python/paddle/static/ + fluid static API."""
from .graph import (Program, Block, Variable, Operator, program_guard,
                    default_main_program, default_startup_program, data,
                    current_capture_program)
from .executor import Executor
from .io import (save_persistables, load_persistables, save_params,
                 load_params, save_vars, load_vars, save_inference_model,
                 load_inference_model)
from ..jit import InputSpec
from . import nn

# CompiledProgram / ParallelExecutor parity: whole-program XLA compilation is
# the only mode; these wrappers exist so reference scripts run unmodified.


class BuildStrategy:
    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    def __init__(self):
        self.reduce_strategy = self.ReduceStrategy.AllReduce
        self.memory_optimize = True
        self.enable_inplace = True
        self.fuse_all_reduce_ops = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """Parity: fluid/compiler.py CompiledProgram. with_data_parallel turns
    on REAL mesh execution: the Executor compiles the program with feeds
    sharded over a 1-D 'data' mesh spanning the visible devices and params
    replicated — XLA inserts the gradient all-reduce (the reference's
    ParallelExecutor + NCCL allreduce path) from the shardings."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy
        self._dp = False
        self._dp_places = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        self._dp = True
        self._dp_places = places
        return self

    @property
    def _fingerprint(self):
        return self._program._fingerprint

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, '_program'), item)


ParallelExecutor = CompiledProgram


def name_scope(prefix=None):
    from ..utils import unique_name
    return unique_name.guard(prefix + '/' if prefix else None)
