"""paddle_tpu.static. Parity: python/paddle/static/ + fluid static API."""
from .graph import (Program, Block, Variable, Operator, program_guard,
                    default_main_program, default_startup_program, data,
                    current_capture_program)
from .executor import Executor
from .io import (save_persistables, load_persistables, save_params,
                 load_params, save_vars, load_vars, save_inference_model,
                 load_inference_model)
from ..jit import InputSpec
from . import nn

# CompiledProgram / ParallelExecutor parity: whole-program XLA compilation is
# the only mode; these wrappers exist so reference scripts run unmodified.


class BuildStrategy:
    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    def __init__(self):
        self.reduce_strategy = self.ReduceStrategy.AllReduce
        self.memory_optimize = True
        self.enable_inplace = True
        self.fuse_all_reduce_ops = True


class ExecutionStrategy:
    def __init__(self):
        self.num_threads = 1
        self.num_iteration_per_drop_scope = 10


class CompiledProgram:
    """Parity: fluid/compiler.py CompiledProgram. with_data_parallel turns
    on REAL mesh execution: the Executor compiles the program with feeds
    sharded over a 1-D 'data' mesh spanning the visible devices and params
    replicated — XLA inserts the gradient all-reduce (the reference's
    ParallelExecutor + NCCL allreduce path) from the shardings."""

    def __init__(self, program_or_graph, build_strategy=None):
        self._program = program_or_graph
        self._build_strategy = build_strategy
        self._dp = False
        self._dp_places = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, places=None):
        self._dp = True
        self._dp_places = places
        return self

    @property
    def _fingerprint(self):
        return self._program._fingerprint

    def __getattr__(self, item):
        return getattr(object.__getattribute__(self, '_program'), item)


ParallelExecutor = CompiledProgram


def name_scope(prefix=None):
    from ..utils import unique_name
    return unique_name.guard(prefix + '/' if prefix else None)


# -- 2.0-beta static top-level surface ---------------------------------------
from .nn import (fc, batch_norm, embedding, conv2d)  # noqa: F401,E402
from ..fluid.backward import append_backward  # noqa: F401,E402
from ..fluid.layers import (bilinear_tensor_product,  # noqa: F401,E402
                            conv2d_transpose, conv3d, conv3d_transpose,
                            create_parameter, crf_decoding, data_norm,
                            deformable_conv, group_norm, hsigmoid,
                            instance_norm, layer_norm, multi_box_head, nce,
                            prelu, row_conv, spectral_norm)
from ..fluid.control_flow import Print  # noqa: F401,E402
from ..nn.initializer import WeightNormParamAttr  # noqa: F401,E402
from ..fluid.layers import py_func  # noqa: F401,E402


def save(program, model_path, protocol=4):
    """Save a Program's parameters + persistables (static/io.py save):
    writes model_path.pdparams with the parameter payloads."""
    import numpy as _np
    from ..framework import save as _fsave
    state = {v.name: _np.asarray(v.concrete.numpy())
             for v in program.all_parameters()}
    _fsave(state, model_path + '.pdparams')


def load(program, model_path, executor=None, var_list=None):
    """Load parameters saved by static.save back into the Program."""
    import jax.numpy as _jnp
    from ..framework import load as _fload
    state = _fload(model_path if model_path.endswith('.pdparams')
                   else model_path + '.pdparams')
    for v in program.all_parameters():
        if v.name in state:
            val = state[v.name]
            val = val.numpy() if hasattr(val, 'numpy') else val
            v.concrete._inplace_value(
                _jnp.asarray(val).astype(v.concrete.dtype))


def global_scope():
    from ..fluid import global_scope as _gs
    return _gs()


def scope_guard(scope):
    from ..fluid import scope_guard as _sg
    return _sg(scope)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    """Static-graph gradients of targets wrt inputs (fluid/backward.py
    gradients), via the same whole-program jax.grad lowering
    append_backward uses. target_gradients supplies the output cotangents
    (the documented weighted-vjp semantics); no_grad_set is not supported
    in the closure IR (raise rather than silently ignore)."""
    import jax
    import jax.numpy as jnp
    from .graph import current_capture_program
    from .executor import _interpret_ops
    from ..core.tensor import apply_op
    if no_grad_set:
        raise NotImplementedError(
            "gradients(no_grad_set=...) is not supported by the closure-IR "
            "lowering; mark vars stop_gradient=True instead")
    prog = current_capture_program() or default_main_program()
    targets = targets if isinstance(targets, (list, tuple)) else [targets]
    inputs = inputs if isinstance(inputs, (list, tuple)) else [inputs]
    if target_gradients is not None and not isinstance(
            target_gradients, (list, tuple)):
        target_gradients = [target_gradients]
    ops = list(prog.global_block.ops)

    grad_vars = []
    for inp in inputs:
        # bind per-input state NOW: a late-binding closure would leave
        # every grad_fn reading the LAST iteration's feeds
        feeds = [v for v in prog.global_block.vars.values()
                 if getattr(v, 'is_data', False) and v is not inp]
        cotans = list(target_gradients) if target_gradients else None

        def grad_fn(*in_vals, _inp=inp, _ops=ops, _feeds=feeds,
                    _nw=len(feeds)):
            env = {id(_inp): in_vals[0]}
            for v, val in zip(_feeds, in_vals[1:1 + _nw]):
                env[id(v)] = val
            cot_vals = in_vals[1 + _nw:]

            def scalar_of(x0):
                e = dict(env)
                e[id(_inp)] = x0
                e = _interpret_ops(_ops, e)
                total = 0.0
                for ti, t in enumerate(targets):
                    if id(t) in e:
                        if cot_vals:
                            total = total + jnp.sum(e[id(t)] *
                                                    cot_vals[ti])
                        else:
                            total = total + jnp.sum(e[id(t)])
                return total
            return jax.grad(scalar_of)(in_vals[0])

        args = [inp] + feeds + (cotans or [])
        grad_vars.append(apply_op(grad_fn, tuple(args)))
    return grad_vars
