"""Executor: lowers a captured Program to ONE compiled XLA computation.

Parity: python/paddle/fluid/executor.py (+ paddle/fluid/framework/executor.cc
per-op dispatch; ParallelExecutor SSA-graph scheduling). TPU-first: instead of
dispatching 1 kernel per op, the whole fetch-pruned op list is interpreted
once under jax.jit — XLA fuses/schedules it. Training programs (after
optimizer.minimize) compile forward+backward+update into the same program,
with jax.grad providing what append_backward provides in the reference.
"""
import functools

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..core.dtypes import convert_dtype
from .graph import Program, Variable, default_main_program
from .. import observability as _obs


def _program_params(program):
    """Ordered parameter Variables a program's ops read."""
    seen, out = set(), []
    for op in program.global_block.ops:
        for v in op.inputs:
            if v.concrete is not None and isinstance(v.concrete, Parameter) \
                    and id(v) not in seen:
                seen.add(id(v))
                out.append(v)
    return out


def _interpret_ops(ops, env):
    """Run a Program op list over an id(var)->payload environment.

    Ops whose inputs are unavailable are skipped (fetch-pruning happens
    implicitly); constants come from each Variable's concrete payload.
    Shared by Executor compilation and the portable jax.export path so the
    two can never diverge.
    """
    for op in ops:
        args = []
        ok = True
        for v in op.inputs:
            if id(v) in env:
                args.append(env[id(v)])
            elif v.concrete is not None:
                args.append(v.concrete._value)
            else:
                ok = False
                break
        if not ok:
            continue
        res = op.fn(*args)
        if op.n_outputs == 1:
            env[id(op.outputs[0])] = res
        else:
            for ov, r in zip(op.outputs, res):
                env[id(ov)] = r
    return env


def _fetch_outs(fetch_vars, env):
    outs = []
    for fv in fetch_vars:
        if id(fv) in env:
            outs.append(env[id(fv)])
        elif fv.concrete is not None:
            outs.append(fv.concrete._value)
        else:
            raise RuntimeError(
                f"fetch var {fv.name} not computed — check feeds")
    return outs


def _unshard_committed(tree):
    """Pull leaves that are still committed to a non-trivial mesh sharding
    back to host (a sharding-config toggle leaves the previous plan's
    placements in the param concretes / optimizer slots; a replicated-
    pinned dp jit rejects them). The next step's output re-places them,
    so the host round-trip happens once per toggle."""
    def fix(v):
        if getattr(getattr(v, 'sharding', None), 'spec', None):
            return np.asarray(v)
        return v
    return jax.tree_util.tree_map(fix, tree)


class Executor:
    def __init__(self, place=None):
        self.place = place
        self._cache = {}

    def close(self):
        self._cache.clear()

    def run(self, program=None, feed=None, fetch_list=None, feed_var_name='feed',
            fetch_var_name='fetch', scope=None, return_numpy=True,
            use_program_cache=True, verify=None):
        program = program or default_main_program()
        feed = feed or {}
        fetch_list = fetch_list or []
        if not isinstance(fetch_list, (list, tuple)):
            fetch_list = [fetch_list]

        from .fluid_format import FluidProgram
        if isinstance(program, FluidProgram):
            # a translated reference-format (Paddle 1.8) inference program:
            # run its jitted forward with the canonical exe.run signature
            return program.run(feed, fetch_list=fetch_list or None)

        # startup program: params were initialized eagerly at creation — no-op
        if not program.global_block.ops and not fetch_list:
            return []

        # static verification before compilation (analysis engine 2):
        # explicit verify=True/False wins, else PADDLE_TPU_VERIFY=1 or
        # analysis.set_always_verify(True) turns it on. Malformed programs
        # raise ProgramVerificationError with op-indexed findings instead of
        # a KeyError deep inside the jitted interpreter.
        from ..analysis.verify import assert_verified, verify_enabled
        if verify_enabled(verify):
            with _obs.timer('executor.verify'):
                assert_verified(program, fetch_list=fetch_list)

        fetch_vars = [self._resolve(program, f) for f in fetch_list]
        feed_items = sorted(feed.items())
        feed_names = [k for k, _ in feed_items]
        feed_vals = []
        for k, v in feed_items:
            if isinstance(v, Tensor):
                feed_vals.append(v._value)
            else:
                arr = np.asarray(v)
                var = program.global_block.vars.get(k)
                if var is not None:
                    arr = arr.astype(np.dtype(var.dtype))
                feed_vals.append(jnp.asarray(arr))

        train_spec = program._train_spec
        params = self._program_params(program)
        param_names = [v.name for v in params]
        param_vals = [v.concrete._value for v in params]

        dp = bool(getattr(program, '_dp', False))
        # the live sharding config is part of the compiled program's
        # identity: toggling fleet sharding between runs must recompile,
        # not silently reuse the other plan's cached step
        from ..distributed.strategy import current_config
        sharding_cfg = current_config() if dp else None
        key = (program._fingerprint, tuple(feed_names),
               tuple((tuple(v.shape), str(v.dtype)) for v in feed_vals),
               tuple(v.name for v in fetch_vars), train_spec is not None,
               sharding_cfg, dp)
        telemetry = _obs.enabled()
        if key not in self._cache:
            if telemetry:
                _obs.counter('executor.program_cache.misses').inc()
            with _obs.timer('executor.build'):
                self._cache[key] = self._compile(program, feed_names,
                                                 fetch_vars, param_names,
                                                 train_spec, dp=dp)
            # persistent tier (paddle_tpu.compilecache): a bound cache dir
            # turns this in-memory miss into a deserialize instead of a
            # compile (or an AOT compile-once + commit on true miss)
            attach = getattr(self._cache[key], 'attach_disk_cache', None)
            attached = bool(attach is not None
                            and attach(feed_vals, param_vals))
            if attach is None:
                # donated train steps are not serialized: counted bypass
                from .. import compilecache as _cc
                _cc.note_bypass(
                    getattr(self._cache[key], 'cost_label',
                            f'executor.train.p{program._fingerprint}'),
                    reason='donated_train_step')
            if telemetry and not attached:
                # cost explorer: ledger this program's FLOPs/bytes/peak
                # memory once, at build time (train steps capture
                # themselves at first dispatch — see TrainStep; attached
                # entries are ledgered by the persistent tier without the
                # extra capture compile)
                cap = getattr(self._cache[key], 'capture_costs', None)
                if cap is not None:
                    cap(feed_vals, param_vals)
        elif telemetry:
            _obs.counter('executor.program_cache.hits').inc()
            lbl = getattr(self._cache[key], 'cost_label', None)
            if lbl:
                _obs.costs.mark_hit(lbl)
        compiled = self._cache[key]
        # sampled sync: the run span blocks on the fetched outputs only on
        # sampled occurrences, so timing the step never adds a host sync the
        # steady-state pipeline would not have had
        outs = None
        with _obs.timer('executor.run', sync=lambda: outs):
            if train_spec is not None:
                optimizer = train_spec[1]
                pv = {v.name: val for v, val in zip(params, param_vals)}
                if getattr(optimizer, '_static_state', None) is None:
                    optimizer._static_state = \
                        optimizer.init_state_values(pv)
                # the engine step owns the whole functional state (and
                # donates it where the backend honors donation); params
                # stay authoritative in the Variables' concrete payloads
                if getattr(compiled, 'sharding', None) is not None:
                    # fleet sharding config live: init_state compiles the
                    # sharded program (first run) and places params +
                    # opt-state on the mesh per the FSDP/TP plan
                    state = compiled.init_state(
                        pv, {}, opt_state=optimizer._static_state)
                else:
                    state = {'params': pv, 'buffers': {},
                             'opt': optimizer._static_state}
                    if dp:
                        # a previous sharded run leaves committed sharded
                        # params/slots in the concretes; the replicated-
                        # pinned dp jit rejects those — pull the
                        # stragglers once (the step output re-places them)
                        state = _unshard_committed(state)
                state, result = compiled(state, feed_vals)
                optimizer._static_state = state['opt']
                outs = result.outputs
                new_param_vals = [state['params'][v.name] for v in params]
            else:
                if dp and sharding_cfg is None:
                    param_vals = _unshard_committed(param_vals)
                outs, new_param_vals = compiled(feed_vals, param_vals)
        if new_param_vals is not None:
            for v, nv in zip(params, new_param_vals):
                v.concrete._inplace_value(nv)
        if return_numpy:
            fetched = [np.asarray(jax.device_get(o)) for o in outs]
            if telemetry:
                _obs.record_host_transfer(
                    sum(a.nbytes for a in fetched), kind='executor.fetch')
            return fetched
        return [Tensor(o) for o in outs]

    # -- dataset-driven training (the reference's train/ device-worker
    # trainers: fluid/executor.py train_from_dataset -> C++ Hogwild/
    # Section trainers over a DataFeed) --------------------------------------
    def train_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        """Run a training Program over a fleet dataset
        (DatasetFactory.create_dataset + MultiSlot files).

        TPU-first divergence: the reference spawns `thread` host workers
        each driving per-op kernels (Hogwild async updates); here every
        batch is ONE XLA computation that already saturates the chip, so
        batches run sequentially on-device while the MultiSlot text
        parsing runs through the native csrc parser. `thread` is accepted
        for API parity.
        """
        return self._run_from_dataset(program, dataset, fetch_list,
                                      fetch_info, print_period,
                                      debug=debug, train=True)

    def infer_from_dataset(self, program=None, dataset=None, scope=None,
                           thread=0, debug=False, fetch_list=None,
                           fetch_info=None, print_period=100):
        return self._run_from_dataset(program, dataset, fetch_list,
                                      fetch_info, print_period,
                                      debug=debug, train=False)

    def _run_from_dataset(self, program, dataset, fetch_list, fetch_info,
                          print_period, debug=False, train=True):
        from .._native import multislot
        program = program or default_main_program()
        if dataset is None:
            raise ValueError("train_from_dataset: dataset is required")
        use_vars = list(getattr(dataset, 'use_vars', []))
        if not use_vars:
            raise ValueError(
                "train_from_dataset: dataset.set_use_var([...]) must name "
                "the feed Variables (in MultiSlot slot order)")
        records = list(dataset)
        if not records:
            dataset.load_into_memory()
            records = list(dataset)
        bs = max(int(getattr(dataset, 'batch_size', 1)), 1)
        n_slots = len(use_vars)
        step = 0
        for start in range(0, len(records), bs):
            batch_lines = [ln.strip() for ln in records[start:start + bs]
                           if ln.strip()]
            if not batch_lines:
                continue
            values, counts = multislot.parse_batch(batch_lines, n_slots)
            feed = {}
            pos = 0
            # slice the flat value stream line-major into per-slot padded
            # dense arrays
            per_slot = [[] for _ in range(n_slots)]
            for li in range(counts.shape[0]):
                for s in range(n_slots):
                    c = int(counts[li, s])
                    per_slot[s].append(values[pos:pos + c])
                    pos += c
            for s, var in enumerate(use_vars):
                rows = per_slot[s]
                width = max((len(r) for r in rows), default=1)
                arr = np.zeros((len(rows), width), np.float64)
                for i, r in enumerate(rows):
                    arr[i, :len(r)] = r
                dt = np.dtype(var.dtype)
                name = getattr(var, 'name', str(var))
                want = tuple(getattr(var, 'shape', ()) or ())
                if len(want) == 1:
                    if width != 1:
                        raise ValueError(
                            f"train_from_dataset: slot {s} feeds 1-D "
                            f"variable '{name}' (shape {list(want)}) but a "
                            f"line carries {width} values per instance; "
                            f"declare the variable as [-1, {width}] or fix "
                            f"the slot arity in the data file")
                    arr = arr.reshape(len(rows))
                elif len(want) == 2 and want[-1] == 1 and width == 1:
                    arr = arr.reshape(len(rows), *want[1:])
                feed[name] = arr.astype(dt)
            outs = self.run(program, feed=feed,
                            fetch_list=list(fetch_list or []))
            if fetch_list and print_period and step % print_period == 0:
                labels = fetch_info or [getattr(f, 'name', str(f))
                                        for f in fetch_list]
                msg = ", ".join(f"{n}={np.asarray(o).ravel()[:4]}"
                                for n, o in zip(labels, outs))
                print(f"[dataset step {step}] {msg}")
            step += 1
        return None

    # -- internals ----------------------------------------------------------
    def _resolve(self, program, f):
        if isinstance(f, Variable):
            return f
        if isinstance(f, str):
            name = f.split('@')[0]
            return program.global_block.var(name)
        if isinstance(f, Tensor):
            # concrete tensor (e.g. a create_global_var Parameter a Switch
            # branch assigns into): fetch through its cached block Variable
            # so in-graph writes to its slot are visible
            return program.global_block.concrete_var(f)
        raise TypeError(f"bad fetch entry {f!r}")

    def _program_params(self, program):
        return _program_params(program)

    def _compile(self, program, feed_names, fetch_vars, param_names,
                 train_spec, dp=False):
        ops = program.global_block.ops

        def interpret(env):
            return _interpret_ops(ops, env)

        block = program.global_block
        feed_vars = [block.var(n) for n in feed_names]
        params = self._program_params(program)

        # data-parallel compile (CompiledProgram.with_data_parallel): feeds
        # shard over a 1-D 'data' mesh, params/opt-state replicate; XLA
        # derives the grad all-reduce from the shardings — numerics match
        # the single-device run on the concatenated batch exactly. When a
        # fleet sharding config is live (DistributedStrategy.sharding/
        # tensor_parallel resolved by fleet.init), the train path upgrades
        # to the full FSDP/TP plan through the same engine builder.
        from ..distributed.strategy import current_config
        sharding_cfg = current_config() if dp else None
        dp_shardings = None
        jit_kwargs = {}
        sharded_feed = None
        if dp:
            from jax.sharding import (Mesh, NamedSharding,
                                      PartitionSpec as P)
            if sharding_cfg is not None:
                # feeds go to the config mesh; params keep whatever
                # placement they carry (FSDP/TP training left them
                # committed sharded — pinning them to replicated
                # in_shardings would raise on the first call)
                sharded_feed = sharding_cfg.batch_sharding()
            else:
                mesh = Mesh(np.asarray(jax.devices()), ('data',))
                feed_sh = NamedSharding(mesh, P('data'))
                repl = NamedSharding(mesh, P())
                n_feed = len(feed_vars)
                n_param = len(params)
                jit_kwargs['in_shardings'] = ([feed_sh] * n_feed,
                                              [repl] * n_param)
                # engine step signature (state, batch): replicate the whole
                # state pytree (sharding-as-prefix), shard the feeds
                dp_shardings = (repl, [feed_sh] * n_feed)

        if train_spec is None:
            @functools.partial(jax.jit, **jit_kwargs)
            def run_jit(feed_vals, param_vals):
                env = {}
                for v, val in zip(feed_vars, feed_vals):
                    env[id(v)] = val
                for v, val in zip(params, param_vals):
                    env[id(v)] = val
                env = interpret(env)
                return _fetch_outs(fetch_vars, env), None

            fp = program._fingerprint
            state = {}          # persistent-tier executable, if attached
            if sharded_feed is None:
                def run(feed_vals, param_vals):
                    exe = state.get('exe')
                    if exe is not None:
                        comp, from_cache = exe
                        try:
                            return comp(feed_vals, param_vals)
                        except Exception as e:
                            # a deserialized executable the runtime rejects
                            # at dispatch: evict + count, recover live
                            state.pop('exe', None)
                            if from_cache:
                                from .. import compilecache as _cc
                                _cc.note_incompat(
                                    getattr(run, 'cost_label', f'p{fp}'),
                                    reason=repr(e)[:200])
                    return run_jit(feed_vals, param_vals)
            else:
                def run(feed_vals, param_vals):
                    feed_vals = [jax.device_put(v, sharded_feed)
                                 for v in feed_vals]
                    return run_jit(feed_vals, param_vals)

            def capture_costs(feed_vals, param_vals):
                """AOT cost/memory capture into the observability cost
                ledger (one extra compile, once per cache entry)."""
                from ..observability import costs as _costs
                fv = feed_vals
                if sharded_feed is not None:
                    fv = [jax.device_put(v, sharded_feed)
                          for v in feed_vals]
                sig = ','.join(
                    'x'.join(str(d) for d in np.shape(v)) or '()'
                    for v in fv)
                run.cost_label = f'executor.p{fp}[{sig}]'
                _costs.capture(run.cost_label, run_jit, fv, param_vals,
                               kind='executor.infer',
                               meta={'fingerprint': fp, 'dp': dp})
            run.capture_costs = capture_costs

            def attach_disk_cache(feed_vals, param_vals):
                """Install this entry's executable from the persistent
                compile tier (load-or-AOT-compile-once, see
                ``paddle_tpu.compilecache``). True means the run path now
                dispatches an AOT executable and the cost ledger is
                already populated — skip capture_costs (and its extra
                compile) for this entry."""
                from .. import compilecache as _cc
                if _cc.active() is None:
                    return False
                sig = ','.join(
                    'x'.join(str(d) for d in np.shape(v)) or '()'
                    for v in feed_vals)
                run.cost_label = f'executor.p{fp}[{sig}]'
                if dp:
                    # sharded-feed programs carry mesh placements a
                    # serialized executable cannot re-derive portably:
                    # deliberate, counted bypass
                    _cc.note_bypass(run.cost_label, reason='dp_sharded')
                    return False
                comp, src = _cc.fetch_or_compile(
                    run.cost_label, run_jit, (feed_vals, param_vals),
                    kind='executor.infer',
                    meta={'fingerprint': fp, 'dp': dp})
                if comp is None:
                    return False
                state['exe'] = (comp, src == 'hit')
                return True
            run.attach_disk_cache = attach_disk_cache
            return run

        # train path: ONE compiled step through the unified engine builder
        # (buffer donation where supported, shared update/clip/decay rule)
        # — the same step hapi Model.fit(jit=True) and engine.fit run
        from ..engine import build_train_step
        loss_var, optimizer = train_spec
        trainable = {v.name for v in params if not v.stop_gradient}
        meta = {v.name: v.concrete for v in params}

        def program_loss_fn(pvals, buffers, feed_vals, key):
            env = {}
            for v, val in zip(feed_vars, feed_vals):
                env[id(v)] = val
            for v in params:
                env[id(v)] = pvals[v.name]
            env = interpret(env)
            loss = jnp.sum(env[id(loss_var)])
            outs = []
            for fv in fetch_vars:
                if id(fv) in env:
                    outs.append(env[id(fv)])
                else:
                    outs.append(fv.concrete._value)
            return loss, tuple(outs), buffers

        step = build_train_step(loss_fn=program_loss_fn,
                                optimizer=optimizer, params_meta=meta,
                                trainable=trainable, with_key=False,
                                in_shardings=dp_shardings,
                                sharding=sharding_cfg)
        step.cost_label = f'executor.train.p{program._fingerprint}'
        return step


def program_infer_fn(program, feed_names, fetch_vars):
    """Standalone pure inference function over a Program.

    Returns ``(fn, params)`` where ``fn(feed_vals, param_vals) -> list`` of
    fetch payloads and ``params`` is the ordered list of parameter
    Variables the function takes positionally. Used by save_inference_model
    to jax.export the fetch subgraph so a Predictor can run it in a fresh
    process with no Program rebuild. Shares _interpret_ops/_fetch_outs with
    Executor._compile, so the two execution paths cannot diverge.
    """
    ops = program.global_block.ops
    block = program.global_block
    feed_vars = [block.var(n) for n in feed_names]
    params = _program_params(program)

    def fn(feed_vals, param_vals):
        env = {}
        for v, val in zip(feed_vars, feed_vals):
            env[id(v)] = val
        for v, val in zip(params, param_vals):
            env[id(v)] = val
        env = _interpret_ops(ops, env)
        return _fetch_outs(fetch_vars, env)

    return fn, params
