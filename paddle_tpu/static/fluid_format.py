"""Reference (Paddle 1.8 fluid) checkpoint/inference-model format interop.

Parity targets:
- LoDTensor binary serialization: paddle/fluid/framework/lod_tensor.cc:246
  (SerializeToStream) + tensor_util.cc:620 (TensorToStream): uint32 version,
  LoD levels, then a Tensor record (uint32 version, int32-length-prefixed
  VarType.TensorDesc protobuf, raw data bytes).
- save/load var files: python/paddle/fluid/io.py:141 (save_vars writes one
  LoDTensor file per var, or one save_combine file holding them
  back-to-back in list order — operators/save_combine_op.h).
- __model__: a framework.proto ProgramDesc protobuf
  (paddle/fluid/framework/framework.proto:212).

TPU-first: nothing here touches a ProgramDesc at runtime — the parsed
program is translated ONCE into a closed jnp forward function (one XLA
computation), and weights become device arrays. The protobuf layer is a
minimal generic wire-format reader/writer (no protoc dependency); field
numbers are cited from framework.proto.
"""
import struct

import numpy as np

__all__ = ['load_fluid_lod_tensor', 'load_fluid_persistables',
           'load_fluid_inference_model', 'parse_program_desc',
           'FluidProgram', 'save_fluid_lod_tensor']

# framework.proto VarType.Type enum (framework.proto:105)
_FLUID_DTYPES = {0: np.bool_, 1: np.int16, 2: np.int32, 3: np.int64,
                 4: np.float16, 5: np.float32, 6: np.float64,
                 20: np.uint8, 21: np.int8}
_FLUID_DTYPE_OF = {np.dtype(v).name: k for k, v in _FLUID_DTYPES.items()}


# ---------------------------------------------------------------------------
# generic protobuf wire format (proto2), reader + writer
# ---------------------------------------------------------------------------

def _read_varint(buf, pos):
    result = 0
    shift = 0
    while True:
        b = buf[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7


def _parse_fields(buf):
    """Parse a protobuf message into {field_number: [raw values]} where a
    raw value is an int (varint/fixed) or bytes (length-delimited)."""
    fields = {}
    pos = 0
    n = len(buf)
    while pos < n:
        key, pos = _read_varint(buf, pos)
        fnum, wtype = key >> 3, key & 7
        if wtype == 0:                       # varint
            val, pos = _read_varint(buf, pos)
        elif wtype == 1:                     # 64-bit
            val = struct.unpack_from('<q', buf, pos)[0]
            pos += 8
        elif wtype == 2:                     # length-delimited
            ln, pos = _read_varint(buf, pos)
            val = bytes(buf[pos:pos + ln])
            pos += ln
        elif wtype == 5:                     # 32-bit
            val = struct.unpack_from('<i', buf, pos)[0]
            pos += 4
        else:
            raise ValueError(f"unsupported protobuf wire type {wtype}")
        fields.setdefault(fnum, []).append(val)
    return fields


def _varints(raw_list):
    """Decode a repeated int64/int32 field that may be packed or unpacked."""
    out = []
    for v in raw_list:
        if isinstance(v, int):
            out.append(v)
        else:  # packed: length-delimited run of varints
            pos = 0
            while pos < len(v):
                x, pos = _read_varint(v, pos)
                out.append(x)
    return [x - (1 << 64) if x >= (1 << 63) else x for x in out]


def _write_varint(out, value):
    if value < 0:
        value += 1 << 64
    while True:
        b = value & 0x7F
        value >>= 7
        if value:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


def _emit(out, fnum, wtype, payload):
    _write_varint(out, (fnum << 3) | wtype)
    if wtype == 0:
        _write_varint(out, payload)
    elif wtype == 2:
        _write_varint(out, len(payload))
        out.extend(payload)
    else:
        raise ValueError(wtype)


def _msg(pairs):
    """Encode [(field_num, wire_type, value_or_bytes), ...] to bytes."""
    out = bytearray()
    for fnum, wtype, val in pairs:
        _emit(out, fnum, wtype, val)
    return bytes(out)


# ---------------------------------------------------------------------------
# LoDTensor binary records (lod_tensor.cc:246 / tensor_util.cc:620)
# ---------------------------------------------------------------------------

def load_fluid_lod_tensor(stream):
    """Read ONE LoDTensor record from a binary stream; returns (ndarray,
    lod) where lod is a list of per-level offset lists."""
    version = struct.unpack('<I', stream.read(4))[0]
    if version != 0:
        raise ValueError(f"unsupported LoDTensor version {version}")
    lod_level = struct.unpack('<Q', stream.read(8))[0]
    lod = []
    for _ in range(lod_level):
        nbytes = struct.unpack('<Q', stream.read(8))[0]
        lod.append(list(np.frombuffer(stream.read(nbytes), np.uint64)))
    t_version = struct.unpack('<I', stream.read(4))[0]
    if t_version != 0:
        raise ValueError(f"unsupported Tensor version {t_version}")
    desc_size = struct.unpack('<i', stream.read(4))[0]
    desc = _parse_fields(stream.read(desc_size))
    dtype = _FLUID_DTYPES[desc[1][0]]            # TensorDesc.data_type = 1
    dims = _varints(desc.get(2, []))             # TensorDesc.dims = 2
    count = int(np.prod(dims)) if dims else 1
    data = stream.read(count * np.dtype(dtype).itemsize)
    arr = np.frombuffer(data, dtype).reshape(dims).copy()
    return arr, lod


def save_fluid_lod_tensor(stream, array, lod=()):
    """Write ONE LoDTensor record in the reference layout (used by the
    round-trip tests and the committed fixture generator)."""
    array = np.ascontiguousarray(array)
    stream.write(struct.pack('<I', 0))
    stream.write(struct.pack('<Q', len(lod)))
    for level in lod:
        level = np.asarray(level, np.uint64)
        stream.write(struct.pack('<Q', level.nbytes))
        stream.write(level.tobytes())
    stream.write(struct.pack('<I', 0))
    desc = bytearray()
    _emit(desc, 1, 0, _FLUID_DTYPE_OF[array.dtype.name])
    for d in array.shape:
        _emit(desc, 2, 0, int(d))
    stream.write(struct.pack('<i', len(desc)))
    stream.write(bytes(desc))
    stream.write(array.tobytes())


# ---------------------------------------------------------------------------
# ProgramDesc parsing (framework.proto:212)
# ---------------------------------------------------------------------------

def _parse_attr(buf):
    """OpDesc.Attr (framework.proto:44)."""
    f = _parse_fields(buf)
    name = f[1][0].decode()
    atype = f[2][0]
    # AttrType enum: INT=0 FLOAT=1 STRING=2 INTS=3 FLOATS=4 STRINGS=5
    # BOOLEAN=6 BOOLEANS=7 BLOCK=8 LONG=9 BLOCKS=10 LONGS=11
    if atype == 0:
        val = _varints(f[3])[0]
    elif atype == 1:
        raw = f[4][0]
        val = struct.unpack('<f', struct.pack('<i', raw))[0] \
            if isinstance(raw, int) else raw
    elif atype == 2:
        val = f[5][0].decode()
    elif atype == 3:
        val = [int(np.int32(v)) for v in _varints(f.get(6, []))]
    elif atype == 4:
        vals = []
        for raw in f.get(7, []):
            if isinstance(raw, bytes):   # packed floats
                vals.extend(np.frombuffer(raw, '<f4').tolist())
            else:
                vals.append(struct.unpack('<f', struct.pack('<i', raw))[0])
        val = vals
    elif atype == 5:
        val = [s.decode() for s in f.get(8, [])]
    elif atype == 6:
        val = bool(f[10][0])
    elif atype == 7:
        val = [bool(v) for v in _varints(f.get(11, []))]
    elif atype == 9:
        val = _varints(f[13])[0]
    elif atype == 11:
        val = _varints(f.get(15, []))
    else:                               # BLOCK/BLOCKS: keep raw index
        val = _varints(f.get(12, []) + f.get(14, []))
    return name, val


def _parse_op(buf):
    f = _parse_fields(buf)
    op = {'type': f[3][0].decode(), 'inputs': {}, 'outputs': {}, 'attrs': {}}
    for which, key in ((1, 'inputs'), (2, 'outputs')):
        for raw in f.get(which, []):
            vf = _parse_fields(raw)
            pname = vf[1][0].decode()
            op[key][pname] = [a.decode() for a in vf.get(2, [])]
    for raw in f.get(4, []):
        name, val = _parse_attr(raw)
        op['attrs'][name] = val
    return op


def _parse_var(buf):
    f = _parse_fields(buf)
    var = {'name': f[1][0].decode(),
           'persistable': bool(_varints(f.get(3, [0]))[0]),
           'shape': None, 'dtype': None}
    tf = _parse_fields(f[2][0])                  # VarDesc.type (VarType)
    var['type_id'] = _varints(tf.get(1, [7]))[0]
    lod_raw = tf.get(3, [])                      # VarType.lod_tensor = 3
    if lod_raw:
        lt = _parse_fields(lod_raw[0])
        td = _parse_fields(lt[1][0])             # LoDTensorDesc.tensor = 1
        var['dtype'] = _FLUID_DTYPES.get(_varints(td.get(1, [5]))[0])
        var['shape'] = _varints(td.get(2, []))
    return var


def parse_program_desc(data):
    """Parse a serialized framework.proto ProgramDesc into
    {'blocks': [{'vars': {name: var}, 'ops': [op]}]}."""
    f = _parse_fields(data)
    blocks = []
    for raw in f.get(1, []):                     # ProgramDesc.blocks = 1
        bf = _parse_fields(raw)
        vars_ = {}
        for vraw in bf.get(3, []):               # BlockDesc.vars = 3
            v = _parse_var(vraw)
            vars_[v['name']] = v
        ops = [_parse_op(oraw) for oraw in bf.get(4, [])]  # BlockDesc.ops=4
        blocks.append({'vars': vars_, 'ops': ops})
    return {'blocks': blocks}


# ---------------------------------------------------------------------------
# ProgramDesc -> jnp forward translator
# ---------------------------------------------------------------------------

def _op_handlers():
    import jax
    import jax.numpy as jnp

    def _mul(env, op):
        x, y = env[op['inputs']['X'][0]], env[op['inputs']['Y'][0]]
        xnc = op['attrs'].get('x_num_col_dims', 1)
        x2 = x.reshape(int(np.prod(x.shape[:xnc])), -1)
        out = x2 @ y.reshape(y.shape[0], -1)
        env[op['outputs']['Out'][0]] = out.reshape(
            tuple(x.shape[:xnc]) + tuple(y.shape[1:]))

    def _matmul(env, op):
        x, y = env[op['inputs']['X'][0]], env[op['inputs']['Y'][0]]
        if op['attrs'].get('transpose_X'):
            x = jnp.swapaxes(x, -1, -2)
        if op['attrs'].get('transpose_Y'):
            y = jnp.swapaxes(y, -1, -2)
        out = jnp.matmul(x, y) * op['attrs'].get('alpha', 1.0)
        env[op['outputs']['Out'][0]] = out

    def _elem(fn):
        def h(env, op):
            x, y = env[op['inputs']['X'][0]], env[op['inputs']['Y'][0]]
            axis = op['attrs'].get('axis', -1)
            if y.ndim < x.ndim and axis != -1:
                y = y.reshape(y.shape + (1,) * (x.ndim - axis - y.ndim))
            env[op['outputs']['Out'][0]] = fn(x, y)
        return h

    def _unary(fn):
        def h(env, op):
            env[op['outputs']['Out'][0]] = fn(env[op['inputs']['X'][0]])
        return h

    def _softmax(env, op):
        x = env[op['inputs']['X'][0]]
        env[op['outputs']['Out'][0]] = jax.nn.softmax(
            x, axis=op['attrs'].get('axis', -1))

    def _scale(env, op):
        x = env[op['inputs']['X'][0]]
        s, b = op['attrs'].get('scale', 1.0), op['attrs'].get('bias', 0.0)
        if op['attrs'].get('bias_after_scale', True):
            out = x * s + b
        else:
            out = (x + b) * s
        env[op['outputs']['Out'][0]] = out

    def _reshape(env, op):
        x = env[op['inputs']['X'][0]]
        shape = [int(s) for s in op['attrs']['shape']]
        shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
        env[op['outputs']['Out'][0]] = x.reshape(shape)

    def _transpose(env, op):
        x = env[op['inputs']['X'][0]]
        env[op['outputs']['Out'][0]] = jnp.transpose(
            x, op['attrs']['axis'])

    def _concat(env, op):
        xs = [env[n] for n in op['inputs']['X']]
        env[op['outputs']['Out'][0]] = jnp.concatenate(
            xs, axis=op['attrs'].get('axis', 0))

    def _dropout(env, op):
        # inference semantics: downgrade_in_infer scales by (1-p),
        # upscale_in_train is identity at test time
        x = env[op['inputs']['X'][0]]
        impl = op['attrs'].get('dropout_implementation', 'downgrade_in_infer')
        p = op['attrs'].get('dropout_prob', 0.5)
        out = x if impl == 'upscale_in_train' else x * (1.0 - p)
        env[op['outputs']['Out'][0]] = out

    def _require_nchw(op):
        layout = op['attrs'].get('data_layout',
                                 op['attrs'].get('data_format', 'NCHW'))
        if layout not in ('NCHW', 'AnyLayout'):
            raise NotImplementedError(
                f"fluid op '{op['type']}' with data layout {layout!r}: only "
                f"NCHW translations are implemented")

    def _batch_norm(env, op):
        _require_nchw(op)
        x = env[op['inputs']['X'][0]]
        scale = env[op['inputs']['Scale'][0]]
        bias = env[op['inputs']['Bias'][0]]
        mean = env[op['inputs']['Mean'][0]]
        var = env[op['inputs']['Variance'][0]]
        eps = op['attrs'].get('epsilon', 1e-5)
        shape = (1, -1) + (1,) * (x.ndim - 2)    # NCHW
        out = (x - mean.reshape(shape)) / jnp.sqrt(
            var.reshape(shape) + eps) * scale.reshape(shape) + \
            bias.reshape(shape)
        env[op['outputs']['Y'][0]] = out

    def _conv2d(env, op):
        from jax import lax
        _require_nchw(op)
        x = env[op['inputs']['Input'][0]]
        w = env[op['inputs']['Filter'][0]]
        a = op['attrs']
        pads = a.get('paddings', [0, 0])
        if len(pads) == 2:
            pads = [(pads[0], pads[0]), (pads[1], pads[1])]
        else:
            pads = [(pads[0], pads[1]), (pads[2], pads[3])]
        out = lax.conv_general_dilated(
            x, w, window_strides=a.get('strides', [1, 1]), padding=pads,
            rhs_dilation=a.get('dilations', [1, 1]),
            feature_group_count=a.get('groups', 1),
            dimension_numbers=('NCHW', 'OIHW', 'NCHW'))
        env[op['outputs']['Output'][0]] = out

    def _pool2d(env, op):
        from jax import lax
        _require_nchw(op)
        x = env[op['inputs']['X'][0]]
        a = op['attrs']
        ks = a.get('ksize', [2, 2])
        st = a.get('strides', ks)
        pd = a.get('paddings', [0, 0])
        if a.get('global_pooling', False):
            red = jnp.max if a.get('pooling_type', 'max') == 'max' \
                else jnp.mean
            env[op['outputs']['Out'][0]] = red(
                x, axis=(2, 3), keepdims=True)
            return
        pads = ((0, 0), (0, 0), (pd[0], pd[0]), (pd[1], pd[1]))
        window = (1, 1) + tuple(ks)
        strides = (1, 1) + tuple(st)
        if a.get('pooling_type', 'max') == 'max':
            out = lax.reduce_window(x, -jnp.inf, lax.max, window, strides,
                                    pads)
        else:
            s = lax.reduce_window(x, 0.0, lax.add, window, strides, pads)
            if a.get('exclusive', True):
                # reference default: padding cells don't count in the divisor
                ones = jnp.ones_like(x)
                denom = lax.reduce_window(ones, 0.0, lax.add, window,
                                          strides, pads)
                out = s / denom
            else:
                out = s / (ks[0] * ks[1])
        env[op['outputs']['Out'][0]] = out

    def _lookup_table(env, op):
        w = env[op['inputs']['W'][0]]
        ids = env[op['inputs']['Ids'][0]]
        if ids.ndim >= 2 and ids.shape[-1] == 1:
            ids = ids[..., 0]
        out = w[ids.astype(jnp.int32)]
        pad = op['attrs'].get('padding_idx', -1)
        if pad is not None and pad >= 0:
            out = out * (ids != pad)[..., None].astype(out.dtype)
        env[op['outputs']['Out'][0]] = out

    def _cast(env, op):
        x = env[op['inputs']['X'][0]]
        env[op['outputs']['Out'][0]] = x.astype(
            _FLUID_DTYPES[op['attrs']['out_dtype']])

    def _reduce(fn):
        def h(env, op):
            x = env[op['inputs']['X'][0]]
            dims = tuple(op['attrs'].get('dim', [0]))
            if op['attrs'].get('reduce_all', False):
                dims = None
            env[op['outputs']['Out'][0]] = fn(
                x, axis=dims, keepdims=op['attrs'].get('keep_dim', False))
        return h

    return {
        'mul': _mul, 'matmul': _matmul,
        'elementwise_add': _elem(jnp.add),
        'elementwise_sub': _elem(jnp.subtract),
        'elementwise_mul': _elem(jnp.multiply),
        'elementwise_div': _elem(jnp.divide),
        'relu': _unary(jax.nn.relu), 'sigmoid': _unary(jax.nn.sigmoid),
        'tanh': _unary(jnp.tanh), 'exp': _unary(jnp.exp),
        'sqrt': _unary(jnp.sqrt), 'abs': _unary(jnp.abs),
        'softmax': _softmax, 'scale': _scale,
        'reshape': _reshape, 'reshape2': _reshape,
        'transpose': _transpose, 'transpose2': _transpose,
        'concat': _concat, 'dropout': _dropout, 'batch_norm': _batch_norm,
        'conv2d': _conv2d, 'pool2d': _pool2d,
        'lookup_table': _lookup_table, 'lookup_table_v2': _lookup_table,
        'cast': _cast,
        'reduce_sum': _reduce(jnp.sum), 'reduce_mean': _reduce(jnp.mean),
    }


class FluidProgram:
    """A parsed 1.8 ProgramDesc translated to ONE jittable jnp forward.

    feed_names/fetch_names come from the program's feed/fetch ops; weights
    are the loaded persistables. The translated forward is compiled by XLA
    as a single computation (the package's Executor design, applied to a
    foreign program)."""

    def __init__(self, program, params=None):
        self.program = program
        block = program['blocks'][0]
        self.feed_names = []
        self.fetch_names = []
        self._body = []
        self._jitted = None
        handlers = _op_handlers()
        for op in block['ops']:
            if op['type'] == 'feed':
                self.feed_names.append(op['outputs']['Out'][0])
            elif op['type'] == 'fetch':
                self.fetch_names.append(op['inputs']['X'][0])
            elif op['type'] in handlers:
                self._body.append((handlers[op['type']], op))
            else:
                raise NotImplementedError(
                    f"fluid op '{op['type']}' has no TPU translation yet "
                    f"(supported: {sorted(handlers)})")
        self.persistable_names = [
            n for n, v in block['vars'].items()
            if v['persistable'] and v['type_id'] == 7
            and n not in ('feed', 'fetch')]
        self.set_params(params or {})

    def set_params(self, params):
        import jax.numpy as jnp
        self.params = {k: jnp.asarray(v) for k, v in params.items()}
        self._jitted = None

    def _forward(self, params, feed):
        env = dict(params)
        env.update(feed)
        for fn, op in self._body:
            fn(env, op)
        return [env[n] for n in self.fetch_names]

    def run(self, feed, fetch_list=None):
        """Execute the translated forward as ONE jitted XLA computation;
        feed: {name: array}. Returns numpy arrays for ``fetch_list`` (names,
        default: the program's fetch targets in order)."""
        import jax
        import jax.numpy as jnp
        for name in self.feed_names:
            if name not in feed:
                raise KeyError(f"missing feed '{name}'")
        if self._jitted is None:
            self._jitted = jax.jit(self._forward)
        outs = self._jitted(self.params,
                            {k: jnp.asarray(v) for k, v in feed.items()})
        by_name = dict(zip(self.fetch_names, outs))
        names = fetch_list if fetch_list is not None else self.fetch_names
        return [np.asarray(by_name[getattr(n, 'name', n)]) for n in names]


# ---------------------------------------------------------------------------
# public loaders
# ---------------------------------------------------------------------------

def load_fluid_persistables(dirname, var_names=None, filename=None):
    """Load persistable vars a real Paddle 1.8 saved (io.py:141).

    - filename=None: one LoDTensor file per var in ``dirname`` (file name ==
      var name); ``var_names`` selects which (default: every regular file).
    - filename='...': a save_combine file holding the vars back-to-back in
      ``var_names`` order (required then).
    Returns {name: ndarray}.
    """
    import os
    out = {}
    if filename is not None:
        if var_names is None:
            raise ValueError("var_names is required for a combined file "
                             "(the format stores no names)")
        with open(os.path.join(dirname, filename), 'rb') as f:
            for name in var_names:
                out[name], _ = load_fluid_lod_tensor(f)
        return out
    names = var_names if var_names is not None else sorted(
        n for n in os.listdir(dirname)
        if os.path.isfile(os.path.join(dirname, n))
        and not n.startswith('__model__'))
    for name in names:
        with open(os.path.join(dirname, name), 'rb') as f:
            out[name], _ = load_fluid_lod_tensor(f)
    return out


def load_fluid_inference_model(dirname, model_filename=None,
                               params_filename=None):
    """Load an inference model saved by real Paddle 1.8's
    save_inference_model (io.py:1034): parse __model__ (ProgramDesc), load
    the persistables, translate to a jittable forward. Returns
    (FluidProgram, feed_names, fetch_names)."""
    import os
    model_path = os.path.join(dirname, model_filename or '__model__')
    with open(model_path, 'rb') as f:
        program = parse_program_desc(f.read())
    prog = FluidProgram(program)
    # save_vars writes combined files in sorted-name order (io.py:344)
    prog.set_params(load_fluid_persistables(
        dirname, var_names=sorted(prog.persistable_names),
        filename=params_filename))
    return prog, prog.feed_names, prog.fetch_names
