"""Static graph IR: Program / Block / Variable / Operator.

Parity: python/paddle/fluid/framework.py (Program, Block, Operator, Variable)
and paddle/fluid/framework/program_desc.h. TPU-first redesign: instead of a
protobuf ProgramDesc interpreted op-by-op by a C++ executor, a Program is a
topological list of pure-JAX closures captured through the SAME apply_op
chokepoint the eager path uses — the Executor lowers the whole list into one
jax.jit'ed XLA computation (shape inference via jax.eval_shape at capture
time). One op library, three execution modes (eager / to_static / Program).
"""
import contextlib
import itertools

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter, set_symbolic_handler
from ..core.dtypes import convert_dtype

_var_counter = itertools.count()


class Variable(Tensor):
    """Symbolic tensor in a Program. `_value` holds a ShapeDtypeStruct."""
    __slots__ = ('_symbolic', 'block', 'op', 'is_data', 'concrete',
                 '_dynamic_dims')

    def __init__(self, aval, name=None, is_data=False, concrete=None):
        super().__init__(aval, stop_gradient=not (concrete is not None and
                                                  isinstance(concrete, Parameter)))
        self._symbolic = True
        self.name = name or f"_var_{next(_var_counter)}"
        self.is_data = is_data
        self.concrete = concrete  # backing Tensor for params/persistables
        self.op = None

    @property
    def shape(self):
        return [int(s) for s in self._value.shape]

    def numpy(self):
        if self.concrete is not None:
            return self.concrete.numpy()
        raise RuntimeError(
            f"Variable {self.name} is symbolic; run it through Executor.run "
            "fetch_list to get values")

    def __repr__(self):
        return (f"Variable(name={self.name}, shape={self.shape}, "
                f"dtype={np.dtype(self.dtype).name})")


class Operator:
    __slots__ = ('fn', 'inputs', 'outputs', 'n_outputs', 'type', 'eval_fn')

    def __init__(self, fn, inputs, outputs, type='jax_op', eval_fn=None):
        self.fn = fn
        self.inputs = inputs
        self.outputs = outputs
        self.n_outputs = len(outputs)
        self.type = type
        # test-mode variant (same arity/outputs): swapped in by
        # Program.clone(for_test=True) so a training capture of dropout/BN
        # gets true eval semantics (parity: the reference rewrites is_test)
        self.eval_fn = eval_fn


class Block:
    def __init__(self, program, idx=0):
        self.program = program
        self.idx = idx
        self.ops = []
        self.vars = {}

    def var(self, name):
        if name not in self.vars:
            raise ValueError(f"var {name} not in block")
        return self.vars[name]

    def has_var(self, name):
        return name in self.vars

    def all_parameters(self):
        return [v for v in self.vars.values()
                if v.concrete is not None and isinstance(v.concrete, Parameter)]

    def create_var(self, name=None, shape=None, dtype='float32', **kwargs):
        aval = jax.ShapeDtypeStruct(tuple(abs(int(s)) if s != -1 else 1
                                          for s in (shape or ())),
                                    convert_dtype(dtype))
        v = Variable(aval, name=name)
        self.vars[v.name] = v
        return v

    def concrete_var(self, t):
        """The ONE Variable wrapping a concrete Tensor in this block —
        cached by tensor identity so every read and in-place write-back of
        the same tensor shares a single env slot (the classic control-flow
        classes rely on this invariant)."""
        cache = getattr(self, '_concrete_cache', None)
        if cache is None:
            cache = self._concrete_cache = {}
        v = cache.get(id(t))
        if v is None:
            v = Variable(jax.ShapeDtypeStruct(tuple(t.shape),
                                              t._value.dtype),
                         name=getattr(t, 'name', None), concrete=t)
            self.vars[v.name] = v
            cache[id(t)] = v
        return v


class Program:
    """Parity: fluid.Program. Captured op list + feed/fetch metadata."""

    def __init__(self):
        self.blocks = [Block(self, 0)]
        self.random_seed = 0
        self._train_spec = None  # (loss_var, optimizer) for minimize()
        self._fingerprint = next(_var_counter)

    @property
    def global_block(self):
        return self.blocks[0]

    def current_block(self):
        return self.blocks[0]

    def block(self, idx):
        return self.blocks[idx]

    @property
    def num_blocks(self):
        return len(self.blocks)

    def all_parameters(self):
        return self.global_block.all_parameters()

    def list_vars(self):
        return list(self.global_block.vars.values())

    def clone(self, for_test=False):
        p = Program.__new__(Program)
        if for_test:
            # genuine eval semantics: ops carrying a test-mode variant
            # (dropout/BN capture one) are swapped; Variables are shared so
            # feeds/fetches/params keep their identity slots
            nb = Block(p, 0)
            src = self.global_block
            nb.vars = src.vars
            # the concrete-tensor cache must be the SAME dict as the source
            # block's (created here if the source never wrapped a concrete
            # tensor): if the clone got a fresh copy, a tensor wrapped after
            # cloning would land in two different env slots and in-graph
            # writes would be invisible across the train/test pair
            cache = getattr(src, '_concrete_cache', None)
            if cache is None:
                cache = src._concrete_cache = {}
            nb._concrete_cache = cache
            nb.ops = [op if op.eval_fn is None else
                      Operator(op.eval_fn, op.inputs, op.outputs,
                               type=op.type + '_eval')
                      for op in src.ops]
            p.blocks = [nb]
        else:
            p.blocks = self.blocks  # shared capture
        p.random_seed = self.random_seed
        p._train_spec = None if for_test else self._train_spec
        p._dp = getattr(self, '_dp', False)
        p._fingerprint = next(_var_counter)
        return p

    def verify(self, fetch_list=None):
        """Static verification of the captured op list (analysis engine 2).

        Returns a list of ``analysis.Finding`` — empty when well-formed.
        Checks: dangling op inputs (GV001), duplicate var names (GV002),
        dtype/shape drift between recorded outputs and declared vars
        (GV003/GV004), undeclared outputs (GV005), dead ops/vars
        (GV006/GV007, warnings) and — when ``fetch_list`` is given —
        unfetchable targets (GV008). ``Executor.run(..., verify=True)`` (or
        ``PADDLE_TPU_VERIFY=1``) runs this before compiling.
        """
        from ..analysis.verify import verify_program
        return verify_program(self, fetch_list=fetch_list)

    def to_string(self, throw_on_error=False, with_details=False):
        block = self.global_block
        lines = [f"Program(ops={len(block.ops)}, vars={len(block.vars)})"]
        written = set()
        for op in block.ops:
            written.update(id(v) for v in op.outputs)
            ins = ','.join(v.name for v in op.inputs)
            outs = ','.join(v.name for v in op.outputs)
            lines.append(f"  {op.type}({ins}) -> {outs}")
        if with_details:
            for name in sorted(block.vars):
                v = block.vars[name]
                if v.is_data:
                    kind = 'data'
                elif v.concrete is not None:
                    kind = ('param' if isinstance(v.concrete, Parameter)
                            else 'persistable')
                elif id(v) in written:
                    kind = 'tmp'
                else:
                    # created but never written: verify() flags this as
                    # GV007 — keep it visible in dumps too
                    kind = 'never-written'
                if throw_on_error and kind == 'never-written':
                    raise ValueError(
                        f"Program.to_string(throw_on_error=True): var "
                        f"'{name}' is created but never written by any op")
                lines.append(
                    f"  var {name} : shape={v.shape} "
                    f"dtype={np.dtype(v.dtype).name} [{kind}]")
        return '\n'.join(lines)

    def __str__(self):
        return self.to_string()


_default_main = [Program()]
_default_startup = [Program()]
_capturing = [None]  # Program being built under program_guard


def default_main_program():
    return _default_main[0]


def default_startup_program():
    return _default_startup[0]


def switch_main_program(p):
    old = _default_main[0]
    _default_main[0] = p
    return old


@contextlib.contextmanager
def program_guard(main_program, startup_program=None):
    old_main = switch_main_program(main_program)
    old_start = _default_startup[0]
    if startup_program is not None:
        _default_startup[0] = startup_program
    old_cap = _capturing[0]
    _capturing[0] = main_program
    try:
        yield
    finally:
        switch_main_program(old_main)
        _default_startup[0] = old_start
        _capturing[0] = old_cap


def current_capture_program():
    from ..framework import in_static_mode
    if _capturing[0] is not None:
        return _capturing[0]
    if in_static_mode():
        return _default_main[0]
    return None


def _symbolic_apply(fn, tensors, n_outputs, differentiable, eval_fn=None):
    """The apply_op hook: append an Operator; infer shapes via eval_shape."""
    prog = current_capture_program()
    if prog is None:
        raise RuntimeError("symbolic Variable used outside static mode")
    block = prog.global_block
    ins = []
    for t in tensors:
        if isinstance(t, Variable):
            ins.append(t)
        elif isinstance(t, Tensor):
            # concrete tensor (e.g. a Parameter created eagerly): wrap as a
            # persistable var bound to it, via the block's identity cache
            ins.append(block.concrete_var(t))
        else:
            arr = jnp.asarray(t)
            c = Tensor(arr)
            v = Variable(jax.ShapeDtypeStruct(tuple(arr.shape), arr.dtype),
                         concrete=c)
            block.vars[v.name] = v
            ins.append(v)

    avals = [jax.ShapeDtypeStruct(tuple(v._value.shape), v._value.dtype)
             for v in ins]
    out_avals = jax.eval_shape(fn, *avals)
    if n_outputs == 1:
        out_avals = [out_avals]
    outs = []
    stop = all(v.stop_gradient for v in ins) or not differentiable
    for av in out_avals:
        ov = Variable(jax.ShapeDtypeStruct(tuple(av.shape), av.dtype))
        ov.stop_gradient = stop
        block.vars[ov.name] = ov
        outs.append(ov)
    op = Operator(fn, ins, outs, type=getattr(fn, '__name__', 'jax_op'),
                  eval_fn=eval_fn)
    for ov in outs:
        ov.op = op
    block.ops.append(op)
    return outs[0] if n_outputs == 1 else tuple(outs)


set_symbolic_handler(_symbolic_apply)


def data(name, shape, dtype='float32', lod_level=0):
    """paddle.static.data — feed placeholder."""
    prog = current_capture_program() or default_main_program()
    dynamic = tuple(i for i, s in enumerate(shape)
                    if s is None or s == -1)
    shape = tuple(1 if (s is None or s == -1) else int(s) for s in shape)
    v = Variable(jax.ShapeDtypeStruct(shape, convert_dtype(dtype)), name=name,
                 is_data=True)
    v._dynamic_dims = dynamic   # which dims were None/-1 (batch-symbolic)
    v.stop_gradient = True
    prog.global_block.vars[name] = v
    return v
