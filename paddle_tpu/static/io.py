"""Static-graph save/load. Parity: python/paddle/fluid/io.py."""
import os
import pickle

import numpy as np

from ..core.tensor import Tensor, Parameter
from .graph import default_main_program


def _collect_params(program):
    program = program or default_main_program()
    out = {}
    for v in program.list_vars():
        if v.concrete is not None and v.concrete.persistable:
            out[v.name] = np.asarray(v.concrete.numpy())
    return out


def save_persistables(executor, dirname, main_program=None, filename=None):
    from ..resilience.atomic_io import atomic_pickle_dump
    os.makedirs(dirname, exist_ok=True)
    params = _collect_params(main_program)
    path = os.path.join(dirname, filename or '__persistables__')
    atomic_pickle_dump(params, path)


save_params = save_persistables
save_vars = save_persistables


def load_persistables(executor, dirname, main_program=None, filename=None):
    import jax.numpy as jnp
    program = main_program or default_main_program()
    path = os.path.join(dirname, filename or '__persistables__')
    if not os.path.exists(path) or (
            filename and not _is_pickle(path)):
        # reference 1.8 layout: one LoDTensor file per var (or a
        # save_combine file) written by real Paddle's save_persistables
        from .fluid_format import load_fluid_persistables
        names = [v.name for v in program.list_vars()
                 if v.concrete is not None and v.concrete.persistable]
        if filename:
            # reference save_vars sorts names before save_combine
            # (io.py:141: `for name in sorted(save_var_map.keys())`)
            params = load_fluid_persistables(dirname,
                                             var_names=sorted(names),
                                             filename=filename)
        else:
            on_disk = [n for n in names
                       if os.path.isfile(os.path.join(dirname, n))]
            params = load_fluid_persistables(dirname, var_names=on_disk)
    else:
        with open(path, 'rb') as f:
            params = pickle.load(f)
    for v in program.list_vars():
        if v.name in params and v.concrete is not None:
            v.concrete._inplace_value(jnp.asarray(params[v.name]))


def _is_pickle(path):
    with open(path, 'rb') as f:
        return f.read(1) == b'\x80'


load_params = load_persistables
load_vars = load_persistables


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, **kwargs):
    """Saves program description + params + a portable serialized export.

    The fetch subgraph is jax.export'ed with symbolic batch dims (every
    None/-1 feed dim), so inference.Predictor can run the model in a fresh
    process with no Program rebuild — the TPU-first analogue of the
    reference's self-contained __model__ ProgramDesc.
    """
    os.makedirs(dirname, exist_ok=True)
    program = main_program or default_main_program()
    params = _collect_params(program)
    meta = {
        'feed_names': list(feeded_var_names),
        'fetch_names': [t.name for t in target_vars],
        'program_repr': str(program),
    }
    try:
        meta['exported'] = _export_portable(program, list(feeded_var_names),
                                            list(target_vars))
    except Exception as e:     # pragma: no cover - diagnostic path
        import warnings
        warnings.warn(
            "save_inference_model: portable export failed (%r) — the model "
            "dir will load via Executor in-process but inference.Predictor "
            "cannot serve it standalone" % (e,))
        meta['export_error'] = repr(e)
    from ..resilience.atomic_io import atomic_pickle_dump
    atomic_pickle_dump(meta, os.path.join(dirname,
                                          model_filename or '__model__'))
    atomic_pickle_dump(params, os.path.join(dirname,
                                            params_filename or '__params__'))
    return [t.name for t in target_vars]


def _export_portable(program, feed_names, fetch_vars):
    """jax.export the fetch subgraph: returns {blob, param_names}."""
    import jax
    import jax.export  # noqa: F401 — lazy submodule; bare `import jax`
    # does not bind it and the whole export degrades to export_error
    import numpy as np
    from .executor import program_infer_fn
    from ..core.dtypes import convert_dtype
    fn, params = program_infer_fn(program, feed_names, fetch_vars)
    block = program.global_block
    scope = jax.export.SymbolicScope()
    feed_specs = []
    feed_dtypes = []
    for i, n in enumerate(feed_names):
        v = block.var(n)
        dyn = set(getattr(v, '_dynamic_dims', ()))
        # dynamic dim 0 shares one 'batch' symbol across every feed (ops
        # combining feeds must agree on it; shape-poly can't infer that),
        # other dynamic positions get per-feed symbols
        dims = []
        for j, d in enumerate(v.shape):
            if j in dyn or d is None or int(d) < 0:
                dims.append('batch' if j == 0 else 'b%d_%d' % (i, j))
            else:
                dims.append(str(d))
        shape = jax.export.symbolic_shape(','.join(dims), scope=scope)
        dt = np.dtype(convert_dtype(v.dtype))
        feed_dtypes.append(dt.name)
        feed_specs.append(jax.ShapeDtypeStruct(shape, dt))
    param_specs = [jax.ShapeDtypeStruct(tuple(p.concrete._value.shape),
                                        p.concrete._value.dtype)
                   for p in params]
    exported = jax.export.export(jax.jit(fn))(feed_specs, param_specs)
    return {'blob': exported.serialize(),
            'param_names': [p.name for p in params],
            'feed_dtypes': feed_dtypes}


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, **kwargs):
    model_path = os.path.join(dirname, model_filename or '__model__')
    with open(model_path, 'rb') as f:
        head = f.read(2)
    if head[:1] != b'\x80':
        # not our pickle format: a framework.proto ProgramDesc written by
        # real Paddle 1.8 (save_inference_model) — translate it
        # (fluid_format.py) and return the runnable FluidProgram
        from .fluid_format import load_fluid_inference_model
        prog, feed_names, fetch_names = load_fluid_inference_model(
            dirname, model_filename=model_filename,
            params_filename=params_filename)
        return prog, feed_names, fetch_names
    with open(model_path, 'rb') as f:
        meta = pickle.load(f)
    with open(os.path.join(dirname, params_filename or '__params__'),
              'rb') as f:
        params = pickle.load(f)
    program = default_main_program()
    import jax.numpy as jnp
    for v in program.list_vars():
        if v.name in params and v.concrete is not None:
            v.concrete._inplace_value(jnp.asarray(params[v.name]))
    fetch_vars = [program.global_block.vars.get(n)
                  for n in meta['fetch_names']]
    return program, meta['feed_names'], fetch_vars
