"""Static-graph save/load. Parity: python/paddle/fluid/io.py."""
import os
import pickle

import numpy as np

from ..core.tensor import Tensor, Parameter
from .graph import default_main_program


def _collect_params(program):
    program = program or default_main_program()
    out = {}
    for v in program.list_vars():
        if v.concrete is not None and v.concrete.persistable:
            out[v.name] = np.asarray(v.concrete.numpy())
    return out


def save_persistables(executor, dirname, main_program=None, filename=None):
    os.makedirs(dirname, exist_ok=True)
    params = _collect_params(main_program)
    path = os.path.join(dirname, filename or '__persistables__')
    with open(path, 'wb') as f:
        pickle.dump(params, f)


save_params = save_persistables
save_vars = save_persistables


def load_persistables(executor, dirname, main_program=None, filename=None):
    path = os.path.join(dirname, filename or '__persistables__')
    with open(path, 'rb') as f:
        params = pickle.load(f)
    import jax.numpy as jnp
    program = main_program or default_main_program()
    for v in program.list_vars():
        if v.name in params and v.concrete is not None:
            v.concrete._inplace_value(jnp.asarray(params[v.name]))


load_params = load_persistables
load_vars = load_persistables


def save_inference_model(dirname, feeded_var_names, target_vars, executor,
                         main_program=None, model_filename=None,
                         params_filename=None, **kwargs):
    """Saves program description + params; exports StableHLO text."""
    os.makedirs(dirname, exist_ok=True)
    program = main_program or default_main_program()
    params = _collect_params(program)
    meta = {
        'feed_names': list(feeded_var_names),
        'fetch_names': [t.name for t in target_vars],
        'program_repr': str(program),
    }
    with open(os.path.join(dirname, model_filename or '__model__'), 'wb') as f:
        pickle.dump(meta, f)
    with open(os.path.join(dirname, params_filename or '__params__'),
              'wb') as f:
        pickle.dump(params, f)
    return [t.name for t in target_vars]


def load_inference_model(dirname, executor, model_filename=None,
                         params_filename=None, **kwargs):
    with open(os.path.join(dirname, model_filename or '__model__'), 'rb') as f:
        meta = pickle.load(f)
    with open(os.path.join(dirname, params_filename or '__params__'),
              'rb') as f:
        params = pickle.load(f)
    program = default_main_program()
    import jax.numpy as jnp
    for v in program.list_vars():
        if v.name in params and v.concrete is not None:
            v.concrete._inplace_value(jnp.asarray(params[v.name]))
    fetch_vars = [program.global_block.vars.get(n)
                  for n in meta['fetch_names']]
    return program, meta['feed_names'], fetch_vars
