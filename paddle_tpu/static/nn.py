"""paddle.static.nn: graph-building layer functions.

Parity: python/paddle/static/nn/__init__.py (the 21-name surface) +
python/paddle/fluid/layers/nn.py's fc/conv2d/... — thin wrappers that
instantiate the SAME nn.Layer modules under static capture (the apply_op
chokepoint records their ops into the Program).
"""
from .. import nn as _nn

__all__ = ['fc', 'batch_norm', 'embedding', 'bilinear_tensor_product',
           'conv2d', 'conv2d_transpose', 'conv3d', 'conv3d_transpose',
           'create_parameter', 'crf_decoding', 'data_norm',
           'deformable_conv', 'group_norm', 'hsigmoid', 'instance_norm',
           'layer_norm', 'multi_box_head', 'nce', 'prelu', 'row_conv',
           'spectral_norm']


def fc(x=None, size=None, num_flatten_dims=1, weight_attr=None,
       bias_attr=None, activation=None, name=None, input=None,
       param_attr=None, act=None):
    # accept both the 2.0 (x/weight_attr/activation) and the 1.8 fluid
    # (input/param_attr/act) keyword spellings
    if x is None:
        x = input
    weight_attr = weight_attr if weight_attr is not None else param_attr
    activation = activation if activation is not None else act
    in_features = 1
    for s in x.shape[num_flatten_dims:]:
        in_features *= s
    if x.ndim > num_flatten_dims + 1:
        x = x.flatten(num_flatten_dims)
    layer = _nn.Linear(in_features, size, weight_attr=weight_attr,
                       bias_attr=bias_attr)
    out = layer(x)
    if activation:
        out = getattr(_nn.functional, activation)(out)
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           data_format="NCHW", name=None):
    in_ch = input.shape[1] if data_format == "NCHW" else input.shape[-1]
    layer = _nn.Conv2D(in_ch, num_filters, filter_size, stride=stride,
                       padding=padding, dilation=dilation, groups=groups,
                       weight_attr=param_attr, bias_attr=bias_attr,
                       data_format=data_format)
    out = layer(input)
    if act:
        out = getattr(_nn.functional, act)(out)
    return out


def batch_norm(input, act=None, momentum=0.9, epsilon=1e-5, param_attr=None,
               bias_attr=None, data_layout='NCHW', is_test=False, name=None,
               **kwargs):
    ch = input.shape[1] if data_layout == 'NCHW' else input.shape[-1]
    layer = _nn.BatchNorm(ch, act=act, momentum=momentum, epsilon=epsilon,
                          param_attr=param_attr, bias_attr=bias_attr,
                          data_layout=data_layout)
    if is_test:
        layer.eval()
    return layer(input)


def embedding(input, size, is_sparse=False, padding_idx=None, param_attr=None,
              dtype='float32'):
    layer = _nn.Embedding(size[0], size[1], padding_idx=padding_idx,
                          sparse=is_sparse, weight_attr=param_attr)
    return layer(input)


# the rest of the 21-name static.nn surface: aliases over the classic
# fluid.layers implementations (imported lazily at module bottom to avoid
# the fluid.layers <-> static.nn import cycle)
def __getattr__(name):
    _aliases = {'bilinear_tensor_product', 'conv2d_transpose', 'conv3d',
                'conv3d_transpose', 'create_parameter', 'crf_decoding',
                'data_norm', 'deformable_conv', 'group_norm', 'hsigmoid',
                'instance_norm', 'layer_norm', 'multi_box_head', 'nce',
                'prelu', 'row_conv', 'spectral_norm'}
    if name in _aliases:
        from ..fluid import layers as _L
        return getattr(_L, name)
    raise AttributeError(f"module 'paddle.static.nn' has no attribute "
                         f"{name!r}")
