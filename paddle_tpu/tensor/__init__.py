"""Functional tensor op library (parity: python/paddle/tensor/*)."""
from .creation import *  # noqa
from .math import *  # noqa
from .manipulation import *  # noqa
from .linalg import *  # noqa
from .logic import *  # noqa
from .search import *  # noqa
from .stat import *  # noqa
from .random import *  # noqa
from .attribute import *  # noqa
from .einsum import einsum  # noqa

# -- 2.0-beta fluid-holdover names at tensor level ---------------------------
from ..fluid.layers import (crop_tensor, fill_constant,  # noqa: F401,E402
                            has_inf, has_nan, reduce_all, reduce_any,
                            reduce_max, reduce_mean, reduce_min,
                            reduce_prod, reduce_sum, sums,
                            unique_with_counts, mul)
from ..framework import save, load  # noqa: F401,E402


def addcmul(input, tensor1, tensor2, value=1.0, name=None):
    return input + tensor1 * tensor2 * value


def elementwise_sum(inputs, name=None):
    return sums(inputs)


def inverse(x, name=None):
    import jax.numpy as _jnp
    from ..core.tensor import apply_op as _ap
    from ._helpers import _t as _tt
    return _ap(lambda v: _jnp.linalg.inv(v), (_tt(x),))


def shuffle(x, name=None):
    import jax as _jax
    from ..core.rng import next_key as _nk
    from ..core.tensor import apply_op as _ap
    from ._helpers import _t as _tt
    key = _nk()
    return _ap(lambda v: v[_jax.random.permutation(key, v.shape[0])],
               (_tt(x),))
