import numbers
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, to_tensor, register_method
from ..core.dtypes import convert_dtype, get_default_dtype

__all__ = []


def _t(x):
    return x if isinstance(x, Tensor) else to_tensor(x)


def _axes(axis):
    if axis is None:
        return None
    if isinstance(axis, (list, tuple)):
        return tuple(int(a) for a in axis)
    if isinstance(axis, Tensor):
        return tuple(int(a) for a in axis.numpy().reshape(-1))
    return int(axis)


def _shape(shape):
    """Normalize shape arg (int list / tensor of ints / list w/ scalar tensors)."""
    if isinstance(shape, Tensor):
        return tuple(int(s) for s in shape.numpy().reshape(-1))
    if isinstance(shape, numbers.Integral):
        return (int(shape),)
    out = []
    for s in shape:
        out.append(int(s.item()) if isinstance(s, Tensor) else int(s))
    return tuple(out)


def unary(jnp_fn, differentiable=True):
    def op(x, name=None):
        return apply_op(jnp_fn, (_t(x),), differentiable=differentiable)
    return op


def binary(jnp_fn, differentiable=True):
    def op(x, y, name=None):
        return apply_op(jnp_fn, (_t(x), _t(y)), differentiable=differentiable)
    return op
