"""Attribute helpers. Parity: python/paddle/tensor/attribute.py."""
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..core.dtypes import is_complex, is_floating, is_integer
from ._helpers import _t

__all__ = ['shape', 'rank', 'is_complex', 'is_floating_point', 'is_integer_t', 'imag_t', 'real_t']


def shape(input):
    """fluid.layers.shape — returns the shape as an int tensor."""
    return Tensor(jnp.asarray(_t(input).shape, dtype=jnp.int32))


def rank(input):
    return Tensor(jnp.asarray(_t(input).ndim, dtype=jnp.int32))


def is_floating_point(x):
    return is_floating(_t(x).dtype)


def is_integer_t(x):
    return is_integer(_t(x).dtype)


def real_t(x, name=None):
    return apply_op(jnp.real, (_t(x),))


def imag_t(x, name=None):
    return apply_op(jnp.imag, (_t(x),))
