"""Creation ops. Parity: python/paddle/tensor/creation.py (+ fluid/layers/tensor.py)."""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, to_tensor
from ..core.dtypes import convert_dtype, get_default_dtype
from ._helpers import _t, _shape

__all__ = [
    'to_tensor', 'zeros', 'ones', 'full', 'zeros_like', 'ones_like', 'full_like',
    'arange', 'linspace', 'logspace', 'eye', 'empty', 'empty_like', 'tril', 'triu',
    'meshgrid', 'diag', 'diagflat', 'assign', 'clone', 'numel', 'create_tensor',
]


def _dt(dtype, default=None):
    d = convert_dtype(dtype)
    return d if d is not None else (default or get_default_dtype())


def zeros(shape, dtype=None, name=None):
    return Tensor(jnp.zeros(_shape(shape), dtype=_dt(dtype)))


def ones(shape, dtype=None, name=None):
    return Tensor(jnp.ones(_shape(shape), dtype=_dt(dtype)))


def full(shape, fill_value, dtype=None, name=None):
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return Tensor(jnp.full(_shape(shape), fill_value, dtype=_dt(dtype)))


def zeros_like(x, dtype=None, name=None):
    x = _t(x)
    dt = convert_dtype(dtype)
    return apply_op(lambda v: jnp.zeros_like(v, dtype=dt), (x,), differentiable=False)


def ones_like(x, dtype=None, name=None):
    x = _t(x)
    dt = convert_dtype(dtype)
    return apply_op(lambda v: jnp.ones_like(v, dtype=dt), (x,), differentiable=False)


def full_like(x, fill_value, dtype=None, name=None):
    x = _t(x)
    dt = convert_dtype(dtype)
    if isinstance(fill_value, Tensor):
        fill_value = fill_value.item()
    return apply_op(lambda v: jnp.full_like(v, fill_value, dtype=dt), (x,),
                    differentiable=False)


def empty(shape, dtype=None, name=None):
    return zeros(shape, dtype)


def empty_like(x, dtype=None, name=None):
    return zeros_like(x, dtype)


def arange(start=0, end=None, step=1, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    start, end, step = val(start), val(end), val(step)
    if end is None:
        start, end = 0, start
    dt = convert_dtype(dtype)
    if dt is None:
        dt = (get_default_dtype()
              if any(isinstance(v, float) for v in (start, end, step)) else jnp.int64)
    return Tensor(jnp.arange(start, end, step, dtype=dt))


def linspace(start, stop, num, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.linspace(val(start), val(stop), int(val(num)), dtype=_dt(dtype)))


def logspace(start, stop, num, base=10.0, dtype=None, name=None):
    def val(v):
        return v.item() if isinstance(v, Tensor) else v
    return Tensor(jnp.logspace(val(start), val(stop), int(val(num)), base=val(base),
                               dtype=_dt(dtype)))


def eye(num_rows, num_columns=None, dtype=None, name=None):
    return Tensor(jnp.eye(int(num_rows),
                          int(num_columns) if num_columns is not None else None,
                          dtype=_dt(dtype)))


def tril(x, diagonal=0, name=None):
    return apply_op(lambda v: jnp.tril(v, k=int(diagonal)), (_t(x),))


def triu(x, diagonal=0, name=None):
    return apply_op(lambda v: jnp.triu(v, k=int(diagonal)), (_t(x),))


def meshgrid(*args, **kwargs):
    if len(args) == 1 and isinstance(args[0], (list, tuple)):
        args = args[0]
    ts = tuple(_t(a) for a in args)
    return list(apply_op(lambda *vs: tuple(jnp.meshgrid(*vs, indexing='ij')),
                         ts, n_outputs=len(ts)))


def diag(x, offset=0, padding_value=0, name=None):
    x = _t(x)
    k = int(offset)
    if x.ndim == 1:
        def fn(v):
            out = jnp.diag(v, k=k)
            if padding_value != 0:
                mask = jnp.diag(jnp.ones_like(v), k=k).astype(bool)
                out = jnp.where(mask, out, jnp.asarray(padding_value, out.dtype))
            return out
        return apply_op(fn, (x,))
    return apply_op(lambda v: jnp.diagonal(v, offset=k), (x,))


def diagflat(x, offset=0, name=None):
    return apply_op(lambda v: jnp.diagflat(v, k=int(offset)), (_t(x),))


def assign(x, output=None):
    """fluid.layers.assign — copies input into output (or a fresh tensor)."""
    if isinstance(x, (np.ndarray, list, tuple, int, float)):
        x = to_tensor(np.asarray(x))
    out = apply_op(lambda v: v + 0 if np.issubdtype(np.dtype(v.dtype), np.inexact) else v,
                   (_t(x),))
    if output is not None:
        output._inplace_value(out._value)
        return output
    return out


def clone(x, name=None):
    return _t(x).clone()


def numel(x, name=None):
    return Tensor(jnp.asarray(_t(x).size, dtype=jnp.int64))


def create_tensor(dtype='float32', name=None, persistable=False):
    t = Tensor(jnp.zeros((), dtype=convert_dtype(dtype)), name=name)
    t.persistable = persistable
    return t
