"""einsum. Parity: paddle.einsum (2.x) / reference contrib."""
import jax.numpy as jnp

from ..core.tensor import apply_op
from ._helpers import _t


def einsum(equation, *operands):
    ts = tuple(_t(o) for o in operands)
    return apply_op(lambda *vs: jnp.einsum(equation, *vs), ts)
