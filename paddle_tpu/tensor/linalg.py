"""Linear algebra ops. Parity: python/paddle/tensor/linalg.py."""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, register_method
from ._helpers import _t, _axes

__all__ = ['matmul', 'dot', 'bmm', 'mv', 'norm', 'dist', 't', 'cholesky',
           'cross', 'histogram', 'bincount', 'mm', 'multi_dot', 'matrix_power',
           'solve', 'inv', 'pinv', 'det', 'slogdet', 'svd', 'qr', 'eigh',
           'matrix_norm', 'vector_norm', 'triangular_solve', 'lstsq', 'matrix_rank', 'cov', 'corrcoef']

from .math import matmul  # shared impl


def dot(x, y, name=None):
    def fn(a, b):
        if a.ndim == 1:
            return jnp.dot(a, b)
        return jnp.sum(a * b, axis=-1, keepdims=False)
    return apply_op(fn, (_t(x), _t(y)))


def bmm(x, y, name=None):
    return apply_op(jnp.matmul, (_t(x), _t(y)))


mm = bmm


def mv(x, vec, name=None):
    return apply_op(jnp.matmul, (_t(x), _t(vec)))


def t(input, name=None):
    x = _t(input)
    if x.ndim > 2:
        raise ValueError("paddle.t expects ndim <= 2")
    return apply_op(lambda v: v.T, (x,))


def norm(x, p='fro', axis=None, keepdim=False, name=None):
    x = _t(x)
    ax = _axes(axis)
    def fn(v):
        if p == 'fro' or (p == 2 and ax is None):
            return jnp.sqrt(jnp.sum(v * v, axis=ax, keepdims=keepdim))
        if p in (np.inf, float('inf'), 'inf'):
            return jnp.max(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p in (-np.inf, float('-inf'), '-inf'):
            return jnp.min(jnp.abs(v), axis=ax, keepdims=keepdim)
        if p == 0:
            return jnp.sum((v != 0).astype(v.dtype), axis=ax, keepdims=keepdim)
        return jnp.power(jnp.sum(jnp.power(jnp.abs(v), p), axis=ax, keepdims=keepdim),
                         1.0 / p)
    return apply_op(fn, (x,))


def vector_norm(x, p=2.0, axis=None, keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def matrix_norm(x, p='fro', axis=(-2, -1), keepdim=False, name=None):
    return norm(x, p=p, axis=axis, keepdim=keepdim)


def dist(x, y, p=2, name=None):
    return norm(_t(x) - _t(y), p=float(p))


def cholesky(x, upper=False, name=None):
    def fn(v):
        l = jnp.linalg.cholesky(v)
        return jnp.swapaxes(l, -1, -2) if upper else l
    return apply_op(fn, (_t(x),))


def cross(x, y, axis=None, name=None):
    ax = 0 if axis is None else axis
    x = _t(x)
    if axis is None:
        # paddle: first axis with dim 3
        for i, s in enumerate(x.shape):
            if s == 3:
                ax = i
                break
    return apply_op(lambda a, b: jnp.cross(a, b, axis=ax), (x, _t(y)))


def histogram(input, bins=100, min=0, max=0, name=None):
    x = _t(input)
    def fn(v):
        lo, hi = (min, max) if (min != 0 or max != 0) else (v.min(), v.max())
        h, _ = jnp.histogram(v.reshape(-1), bins=bins, range=(lo, hi))
        return h
    return apply_op(fn, (x,), differentiable=False)


def bincount(x, weights=None, minlength=0, name=None):
    x = _t(x)
    n = int(np.asarray(x.numpy()).max()) + 1 if x.size else 0
    length = builtins_max(n, minlength)
    if weights is None:
        return apply_op(lambda v: jnp.bincount(v.reshape(-1), length=length),
                        (x,), differentiable=False)
    return apply_op(lambda v, w: jnp.bincount(v.reshape(-1), weights=w.reshape(-1),
                                              length=length),
                    (x, _t(weights)), differentiable=False)


import builtins as _b
builtins_max = _b.max


def multi_dot(x, name=None):
    ts = tuple(_t(i) for i in x)
    return apply_op(lambda *vs: jnp.linalg.multi_dot(vs), ts)


def matrix_power(x, n, name=None):
    return apply_op(lambda v: jnp.linalg.matrix_power(v, n), (_t(x),))


def solve(x, y, name=None):
    return apply_op(jnp.linalg.solve, (_t(x), _t(y)))


def triangular_solve(x, y, upper=True, transpose=False, unitriangular=False, name=None):
    from jax.scipy.linalg import solve_triangular
    def fn(a, b):
        return solve_triangular(a, b, lower=not upper, trans=1 if transpose else 0,
                                unit_diagonal=unitriangular)
    return apply_op(fn, (_t(x), _t(y)))


def inv(x, name=None):
    return apply_op(jnp.linalg.inv, (_t(x),))


def pinv(x, rcond=1e-15, hermitian=False, name=None):
    return apply_op(lambda v: jnp.linalg.pinv(v, rtol=rcond, hermitian=hermitian), (_t(x),))


def det(x, name=None):
    return apply_op(jnp.linalg.det, (_t(x),))


def slogdet(x, name=None):
    outs = apply_op(lambda v: tuple(jnp.linalg.slogdet(v)), (_t(x),), n_outputs=2)
    return list(outs)


def svd(x, full_matrices=False, name=None):
    outs = apply_op(lambda v: tuple(jnp.linalg.svd(v, full_matrices=full_matrices)),
                    (_t(x),), n_outputs=3)
    return tuple(outs)


def qr(x, mode='reduced', name=None):
    outs = apply_op(lambda v: tuple(jnp.linalg.qr(v, mode=mode)), (_t(x),), n_outputs=2)
    return tuple(outs)


def eigh(x, UPLO='L', name=None):
    outs = apply_op(lambda v: tuple(jnp.linalg.eigh(v, UPLO=UPLO)), (_t(x),), n_outputs=2)
    return tuple(outs)


def lstsq(x, y, rcond=None, driver=None, name=None):
    def fn(a, b):
        sol, res, rank, sv = jnp.linalg.lstsq(a, b, rcond=rcond)
        return (sol, res, rank, sv)
    return tuple(apply_op(fn, (_t(x), _t(y)), n_outputs=4))


def matrix_rank(x, tol=None, hermitian=False, name=None):
    return apply_op(lambda v: jnp.linalg.matrix_rank(v, rtol=tol), (_t(x),),
                    differentiable=False)


def cov(x, rowvar=True, ddof=True, fweights=None, aweights=None, name=None):
    return apply_op(lambda v: jnp.cov(v, rowvar=rowvar, ddof=1 if ddof else 0),
                    (_t(x),))


def corrcoef(x, rowvar=True, name=None):
    return apply_op(lambda v: jnp.corrcoef(v, rowvar=rowvar), (_t(x),))


for _name in ['dot', 'bmm', 'mv', 'norm', 'dist', 't', 'cholesky', 'cross',
              'histogram', 'bincount', 'inner', 'matrix_power', 'solve', 'inv']:
    if _name in globals():
        register_method(_name, globals()[_name])
