"""Logic/comparison ops. Parity: python/paddle/tensor/logic.py."""
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, register_method
from ._helpers import _t, binary

__all__ = ['equal', 'not_equal', 'greater_than', 'greater_equal', 'less_than',
           'less_equal', 'equal_all', 'logical_and', 'logical_or', 'logical_not',
           'logical_xor', 'bitwise_and', 'bitwise_or', 'bitwise_not', 'bitwise_xor',
           'allclose', 'isclose', 'isnan', 'isinf', 'isfinite', 'is_empty', 'is_tensor']

equal = binary(jnp.equal, differentiable=False)
not_equal = binary(jnp.not_equal, differentiable=False)
greater_than = binary(jnp.greater, differentiable=False)
greater_equal = binary(jnp.greater_equal, differentiable=False)
less_than = binary(jnp.less, differentiable=False)
less_equal = binary(jnp.less_equal, differentiable=False)
logical_and = binary(jnp.logical_and, differentiable=False)
logical_or = binary(jnp.logical_or, differentiable=False)
logical_xor = binary(jnp.logical_xor, differentiable=False)
bitwise_and = binary(jnp.bitwise_and, differentiable=False)
bitwise_or = binary(jnp.bitwise_or, differentiable=False)
bitwise_xor = binary(jnp.bitwise_xor, differentiable=False)


def logical_not(x, out=None, name=None):
    return apply_op(jnp.logical_not, (_t(x),), differentiable=False)


def bitwise_not(x, out=None, name=None):
    return apply_op(jnp.bitwise_not, (_t(x),), differentiable=False)


def equal_all(x, y, name=None):
    return apply_op(lambda a, b: jnp.array_equal(a, b), (_t(x), _t(y)),
                    differentiable=False)


def allclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(lambda a, b: jnp.allclose(a, b, rtol=float(rtol),
                                              atol=float(atol), equal_nan=equal_nan),
                    (_t(x), _t(y)), differentiable=False)


def isclose(x, y, rtol=1e-05, atol=1e-08, equal_nan=False, name=None):
    return apply_op(lambda a, b: jnp.isclose(a, b, rtol=float(rtol),
                                             atol=float(atol), equal_nan=equal_nan),
                    (_t(x), _t(y)), differentiable=False)


def isnan(x, name=None):
    return apply_op(jnp.isnan, (_t(x),), differentiable=False)


def isinf(x, name=None):
    return apply_op(jnp.isinf, (_t(x),), differentiable=False)


def isfinite(x, name=None):
    return apply_op(jnp.isfinite, (_t(x),), differentiable=False)


def is_empty(x, name=None):
    return Tensor(jnp.asarray(_t(x).size == 0))


def is_tensor(x):
    return isinstance(x, Tensor)


for _name in ['equal', 'not_equal', 'greater_than', 'greater_equal', 'less_than',
              'less_equal', 'logical_and', 'logical_or', 'logical_not',
              'logical_xor', 'allclose', 'isclose', 'isnan', 'isinf', 'isfinite',
              'equal_all', 'bitwise_and', 'bitwise_or', 'bitwise_not', 'bitwise_xor']:
    register_method(_name, globals()[_name])
