"""Shape/layout manipulation ops. Parity: python/paddle/tensor/manipulation.py."""
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply_op, register_method
from ..core.dtypes import convert_dtype
from ._helpers import _t, _axes, _shape

__all__ = [
    'concat', 'split', 'stack', 'unstack', 'squeeze', 'unsqueeze', 'reshape',
    'flatten', 'transpose', 'expand', 'expand_as', 'tile', 'broadcast_to',
    'broadcast_tensors', 'gather', 'gather_nd', 'scatter', 'scatter_nd',
    'scatter_nd_add', 'slice', 'strided_slice', 'index_select', 'index_sample',
    'masked_select', 'roll', 'flip', 'rot90', 'unique', 'unique_consecutive',
    'unbind', 'chunk', 'shard_index', 'cast', 'crop', 'pad_seq', 'reverse',
    'moveaxis', 'swapaxes', 'take_along_axis', 'put_along_axis', 'repeat_interleave',
    'as_real', 'as_complex', 'tensordot', 'atleast_1d', 'atleast_2d', 'atleast_3d',
]


def concat(x, axis=0, name=None):
    ts = tuple(_t(i) for i in x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op(lambda *vs: jnp.concatenate(vs, axis=axis), ts)


def split(x, num_or_sections, axis=0, name=None):
    x = _t(x)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    dim = x.shape[axis]
    if isinstance(num_or_sections, int):
        sizes = [dim // num_or_sections] * num_or_sections
    else:
        sizes = [int(s.item()) if isinstance(s, Tensor) else int(s)
                 for s in num_or_sections]
        n_neg = sum(1 for s in sizes if s < 0)
        if n_neg:
            rest = dim - sum(s for s in sizes if s >= 0)
            sizes = [rest if s < 0 else s for s in sizes]
    offsets = np.cumsum([0] + sizes[:-1]).tolist()
    def fn(v):
        return tuple(lax.slice_in_dim(v, o, o + s, axis=axis)
                     for o, s in zip(offsets, sizes))
    return list(apply_op(fn, (x,), n_outputs=len(sizes)))


def chunk(x, chunks, axis=0, name=None):
    return split(x, chunks, axis)


def stack(x, axis=0, name=None):
    ts = tuple(_t(i) for i in x)
    return apply_op(lambda *vs: jnp.stack(vs, axis=axis), ts)


def unstack(x, axis=0, num=None):
    x = _t(x)
    n = num if num is not None else x.shape[axis]
    def fn(v):
        return tuple(jnp.squeeze(s, axis=axis)
                     for s in jnp.split(v, n, axis=axis))
    return list(apply_op(fn, (x,), n_outputs=n))


def unbind(input, axis=0):
    return unstack(input, axis)


def squeeze(x, axis=None, name=None):
    return _t(x).squeeze(axis)


def unsqueeze(x, axis, name=None):
    return _t(x).unsqueeze(axis)


def reshape(x, shape, name=None):
    return _t(x).reshape(_shape(shape))


def flatten(x, start_axis=0, stop_axis=-1, name=None):
    return _t(x).flatten(start_axis, stop_axis)


def transpose(x, perm, name=None):
    return _t(x).transpose(perm)


def moveaxis(x, source, destination, name=None):
    return apply_op(lambda v: jnp.moveaxis(v, source, destination), (_t(x),))


def swapaxes(x, axis0, axis1, name=None):
    return apply_op(lambda v: jnp.swapaxes(v, axis0, axis1), (_t(x),))


def expand(x, shape, name=None):
    shp = _shape(shape)
    x = _t(x)
    def fn(v):
        tgt = list(shp)
        # -1 entries keep the original dim
        off = len(tgt) - v.ndim
        for i, s in enumerate(tgt):
            if s == -1:
                tgt[i] = v.shape[i - off]
        return jnp.broadcast_to(v, tuple(tgt))
    return apply_op(fn, (x,))


def expand_as(x, y, name=None):
    tgt = tuple(_t(y).shape)
    return apply_op(lambda v: jnp.broadcast_to(v, tgt), (_t(x),))


def broadcast_to(x, shape, name=None):
    return apply_op(lambda v: jnp.broadcast_to(v, _shape(shape)), (_t(x),))


def broadcast_tensors(input, name=None):
    ts = tuple(_t(i) for i in input)
    return list(apply_op(lambda *vs: tuple(jnp.broadcast_arrays(*vs)), ts,
                         n_outputs=len(ts)))


def tile(x, repeat_times, name=None):
    reps = _shape(repeat_times)
    return apply_op(lambda v: jnp.tile(v, reps), (_t(x),))


def gather(x, index, axis=0, name=None):
    x, index = _t(x), _t(index)
    if isinstance(axis, Tensor):
        axis = int(axis.item())
    return apply_op(lambda v, i: jnp.take(v, i.reshape(-1) if i.ndim > 1 else i,
                                          axis=axis), (x, index))


def gather_nd(x, index, name=None):
    x, index = _t(x), _t(index)
    def fn(v, idx):
        k = idx.shape[-1]
        return v[tuple(jnp.moveaxis(idx, -1, 0))] if k > 0 else v
    return apply_op(fn, (x, index))


def scatter(x, index, updates, overwrite=True, name=None):
    x, index, updates = _t(x), _t(index), _t(updates)
    def fn(v, i, u):
        i = i.reshape(-1)
        if overwrite:
            return v.at[i].set(u)
        # paddle semantics: zero out target rows then accumulate
        z = v.at[i].set(jnp.zeros_like(u))
        return z.at[i].add(u)
    return apply_op(fn, (x, index, updates))


def scatter_nd_add(x, index, updates, name=None):
    x, index, updates = _t(x), _t(index), _t(updates)
    def fn(v, i, u):
        return v.at[tuple(jnp.moveaxis(i, -1, 0))].add(u)
    return apply_op(fn, (x, index, updates))


def scatter_nd(index, updates, shape, name=None):
    from .creation import zeros
    z = zeros(shape, dtype=_t(updates).dtype)
    return scatter_nd_add(z, index, updates)


def slice(input, axes, starts, ends, name=None):
    x = _t(input)
    def get(v):
        return int(v.item()) if isinstance(v, Tensor) else int(v)
    axes = [get(a) for a in axes]
    starts = [get(s) for s in starts]
    ends = [get(e) for e in ends]
    def fn(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e in zip(axes, starts, ends):
            d = v.shape[a]
            s2 = max(s + d, 0) if s < 0 else min(s, d)
            e2 = max(e + d, 0) if e < 0 else min(e, d)
            idx[a] = builtins_slice(s2, e2)
        return v[tuple(idx)]
    return apply_op(fn, (x,))


import builtins as _builtins
builtins_slice = _builtins.slice


def strided_slice(x, axes, starts, ends, strides, name=None):
    x = _t(x)
    def get(v):
        return int(v.item()) if isinstance(v, Tensor) else int(v)
    axes = [get(a) for a in axes]
    starts = [get(s) for s in starts]
    ends = [get(e) for e in ends]
    strides = [get(s) for s in strides]
    def fn(v):
        idx = [builtins_slice(None)] * v.ndim
        for a, s, e, st in zip(axes, starts, ends, strides):
            idx[a] = builtins_slice(s, e, st)
        return v[tuple(idx)]
    return apply_op(fn, (x,))


def index_select(x, index, axis=0, name=None):
    return apply_op(lambda v, i: jnp.take(v, i, axis=axis), (_t(x), _t(index)))


def index_sample(x, index):
    """x: (B, N), index: (B, M) -> (B, M); parity: fluid index_sample op."""
    return apply_op(lambda v, i: jnp.take_along_axis(v, i, axis=1),
                    (_t(x), _t(index)))


def take_along_axis(arr, indices, axis, name=None):
    return apply_op(lambda v, i: jnp.take_along_axis(v, i, axis=axis),
                    (_t(arr), _t(indices)))


def put_along_axis(arr, indices, values, axis, reduce='assign', name=None):
    arr, indices = _t(arr), _t(indices)
    values = _t(values)
    def fn(v, i, u):
        u = jnp.broadcast_to(u, i.shape).astype(v.dtype)
        idx = [jnp.arange(s).reshape([-1 if d == k else 1 for d in range(i.ndim)])
               for k, s in enumerate(i.shape)]
        idx[axis] = i
        if reduce == 'add':
            return v.at[tuple(idx)].add(u)
        if reduce == 'multiply' or reduce == 'mul':
            return v.at[tuple(idx)].multiply(u)
        return v.at[tuple(idx)].set(u)
    return apply_op(fn, (arr, indices, values))


def masked_select(x, mask, name=None):
    """Dynamic-size output: host fallback (not jittable) — documented divergence."""
    x, mask = _t(x), _t(mask)
    xv, mv = np.asarray(x.numpy()), np.asarray(mask.numpy())
    return Tensor(jnp.asarray(np.broadcast_to(xv, np.broadcast(xv, mv).shape)[
        np.broadcast_to(mv, np.broadcast(xv, mv).shape)]))


def roll(x, shifts, axis=None, name=None):
    return apply_op(lambda v: jnp.roll(v, shifts, axis=axis), (_t(x),))


def flip(x, axis, name=None):
    ax = _axes(axis)
    return apply_op(lambda v: jnp.flip(v, axis=ax), (_t(x),))


def reverse(x, axis, name=None):
    return flip(x, axis)


def rot90(x, k=1, axes=(0, 1), name=None):
    return apply_op(lambda v: jnp.rot90(v, k=k, axes=tuple(axes)), (_t(x),))


def unique(x, return_index=False, return_inverse=False, return_counts=False,
           axis=None, dtype='int64', name=None):
    """Dynamic-size output: computed on host (documented divergence)."""
    xv = np.asarray(_t(x).numpy())
    res = np.unique(xv, return_index=return_index, return_inverse=return_inverse,
                    return_counts=return_counts, axis=axis)
    if not isinstance(res, tuple):
        return Tensor(jnp.asarray(res))
    outs = [Tensor(jnp.asarray(r)) for r in res]
    return tuple(outs)


def unique_consecutive(x, return_inverse=False, return_counts=False, axis=None,
                       dtype='int64', name=None):
    xv = np.asarray(_t(x).numpy())
    flat = xv.reshape(-1) if axis is None else xv
    keep = np.ones(len(flat), dtype=bool)
    keep[1:] = flat[1:] != flat[:-1]
    out = [Tensor(jnp.asarray(flat[keep]))]
    if return_inverse:
        out.append(Tensor(jnp.asarray(np.cumsum(keep) - 1)))
    if return_counts:
        idx = np.nonzero(keep)[0]
        counts = np.diff(np.append(idx, len(flat)))
        out.append(Tensor(jnp.asarray(counts)))
    return out[0] if len(out) == 1 else tuple(out)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    x = _t(input)
    size = index_num // nshards
    def fn(v):
        shard = v // size
        local = v % size
        return jnp.where(shard == shard_id, local, ignore_value)
    return apply_op(fn, (x,), differentiable=False)


def cast(x, dtype):
    return _t(x).astype(dtype)


def crop(x, shape=None, offsets=None, name=None):
    x = _t(x)
    shp = _shape(shape)
    offs = _shape(offsets) if offsets is not None else tuple([0] * x.ndim)
    def fn(v):
        return lax.dynamic_slice(v, offs, shp)
    return apply_op(fn, (x,))


def pad_seq(x, paddings, pad_value=0.0, name=None):
    x = _t(x)
    pairs = [(paddings[2 * i], paddings[2 * i + 1]) for i in range(len(paddings) // 2)]
    return apply_op(lambda v: jnp.pad(v, pairs, constant_values=pad_value), (x,))


def repeat_interleave(x, repeats, axis=None, name=None):
    if isinstance(repeats, Tensor):
        reps = repeats.numpy()
        return apply_op(lambda v: jnp.repeat(v, reps, axis=axis), (_t(x),))
    return apply_op(lambda v: jnp.repeat(v, repeats, axis=axis), (_t(x),))


def as_complex(x, name=None):
    return apply_op(lambda v: lax.complex(v[..., 0], v[..., 1]), (_t(x),))


def as_real(x, name=None):
    return apply_op(lambda v: jnp.stack([jnp.real(v), jnp.imag(v)], axis=-1), (_t(x),))


def tensordot(x, y, axes=2, name=None):
    return apply_op(lambda a, b: jnp.tensordot(a, b, axes=axes), (_t(x), _t(y)))


def atleast_1d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_1d, (_t(i),)) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_2d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_2d, (_t(i),)) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


def atleast_3d(*inputs, name=None):
    outs = [apply_op(jnp.atleast_3d, (_t(i),)) for i in inputs]
    return outs[0] if len(outs) == 1 else outs


for _name in ['concat', 'split', 'stack', 'unstack', 'gather', 'gather_nd',
              'scatter', 'scatter_nd_add', 'index_select', 'index_sample',
              'masked_select', 'roll', 'flip', 'unique', 'unbind', 'chunk',
              'expand', 'expand_as', 'broadcast_to', 'tile', 'tensordot',
              'take_along_axis', 'put_along_axis', 'repeat_interleave', 'rot90']:
    register_method(_name, globals()[_name])
