"""Math ops. Parity: python/paddle/tensor/math.py (+ fluid/layers/ops.py, nn.py)."""
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply_op, register_method
from ..core.dtypes import convert_dtype, is_floating, get_default_dtype
from ._helpers import _t, _axes, unary, binary

__all__ = [
    'add', 'subtract', 'multiply', 'divide', 'floor_divide', 'remainder', 'mod',
    'floor_mod', 'pow', 'matmul', 'maximum', 'minimum', 'fmax', 'fmin',
    'exp', 'expm1', 'log', 'log2', 'log10', 'log1p', 'sqrt', 'rsqrt', 'abs',
    'neg', 'sign', 'floor', 'ceil', 'round', 'trunc', 'sin', 'cos', 'tan',
    'asin', 'acos', 'atan', 'atan2', 'sinh', 'cosh', 'tanh', 'asinh', 'acosh', 'atanh',
    'reciprocal', 'square', 'erf', 'erfinv', 'rint', 'digamma', 'lgamma',
    'sum', 'mean', 'max', 'min', 'prod', 'cumsum', 'cumprod', 'logsumexp',
    'logcumsumexp', 'amax', 'amin', 'clip', 'scale', 'increment', 'stanh',
    'addmm', 'kron', 'trace', 'multiplex', 'inner', 'outer', 'isfinite_v',
    'elementwise_add', 'elementwise_sub', 'elementwise_mul', 'elementwise_div',
    'elementwise_max', 'elementwise_min', 'elementwise_mod', 'elementwise_pow',
    'elementwise_floordiv', 'log_softmax_v', 'multiply_', 'add_n', 'nan_to_num',
    'deg2rad', 'rad2deg', 'angle', 'conj', 'real', 'imag', 'lerp', 'frac', 'gcd', 'lcm',
]

# -- simple elementwise ---------------------------------------------------
add = binary(jnp.add)
subtract = binary(jnp.subtract)
multiply = binary(jnp.multiply)
divide = binary(jnp.true_divide)
floor_divide = binary(jnp.floor_divide)
remainder = binary(jnp.mod)
mod = remainder
floor_mod = remainder
maximum = binary(jnp.maximum)
minimum = binary(jnp.minimum)
fmax = binary(jnp.fmax)
fmin = binary(jnp.fmin)
atan2 = binary(jnp.arctan2)
gcd = binary(jnp.gcd, differentiable=False)
lcm = binary(jnp.lcm, differentiable=False)

exp = unary(jnp.exp)
expm1 = unary(jnp.expm1)
log = unary(jnp.log)
log2 = unary(jnp.log2)
log10 = unary(jnp.log10)
log1p = unary(jnp.log1p)
sqrt = unary(jnp.sqrt)
rsqrt = unary(lambda x: lax.rsqrt(x))
abs = unary(jnp.abs)
neg = unary(jnp.negative)
sign = unary(jnp.sign, differentiable=False)
floor = unary(jnp.floor)
ceil = unary(jnp.ceil)
round = unary(jnp.round)
rint = unary(jnp.rint)
trunc = unary(jnp.trunc)
sin = unary(jnp.sin)
cos = unary(jnp.cos)
tan = unary(jnp.tan)
asin = unary(jnp.arcsin)
acos = unary(jnp.arccos)
atan = unary(jnp.arctan)
sinh = unary(jnp.sinh)
cosh = unary(jnp.cosh)
tanh = unary(jnp.tanh)
asinh = unary(jnp.arcsinh)
acosh = unary(jnp.arccosh)
atanh = unary(jnp.arctanh)
reciprocal = unary(jnp.reciprocal)
square = unary(jnp.square)
deg2rad = unary(jnp.deg2rad)
rad2deg = unary(jnp.rad2deg)
angle = unary(jnp.angle)
conj = unary(jnp.conj)
real = unary(jnp.real)
imag = unary(jnp.imag)
frac = unary(lambda x: x - jnp.trunc(x))


def erf(x, name=None):
    return apply_op(lambda v: lax.erf(v), (_t(x),))


def erfinv(x, name=None):
    return apply_op(lambda v: lax.erf_inv(v), (_t(x),))


def digamma(x, name=None):
    from jax.scipy.special import digamma as _dg
    return apply_op(_dg, (_t(x),))


def lgamma(x, name=None):
    from jax.scipy.special import gammaln
    return apply_op(gammaln, (_t(x),))


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return apply_op(lambda v: scale_b * jnp.tanh(scale_a * v), (_t(x),))


# -- pow / matmul ---------------------------------------------------------
def pow(x, y, name=None):
    return apply_op(jnp.power, (_t(x), _t(y)))


def matmul(x, y, transpose_x=False, transpose_y=False, name=None):
    def fn(a, b):
        if transpose_x:
            a = jnp.swapaxes(a, -1, -2) if a.ndim >= 2 else a
        if transpose_y:
            b = jnp.swapaxes(b, -1, -2) if b.ndim >= 2 else b
        return jnp.matmul(a, b)
    return apply_op(fn, (_t(x), _t(y)))


# fluid elementwise_* compat (axis broadcasting in the 1.8 style)
def _fluid_elementwise(jfn):
    def op(x, y, axis=-1, act=None, name=None):
        x, y = _t(x), _t(y)
        def fn(a, b):
            if axis != -1 and b.ndim < a.ndim:
                shp = [1] * a.ndim
                shp[axis:axis + b.ndim] = b.shape
                b = jnp.reshape(b, shp)
            out = jfn(a, b)
            return out
        out = apply_op(fn, (x, y))
        if act is not None:
            from ..nn import functional as F
            out = getattr(F, act)(out)
        return out
    return op


elementwise_add = _fluid_elementwise(jnp.add)
elementwise_sub = _fluid_elementwise(jnp.subtract)
elementwise_mul = _fluid_elementwise(jnp.multiply)
elementwise_div = _fluid_elementwise(jnp.true_divide)
elementwise_max = _fluid_elementwise(jnp.maximum)
elementwise_min = _fluid_elementwise(jnp.minimum)
elementwise_mod = _fluid_elementwise(jnp.mod)
elementwise_pow = _fluid_elementwise(jnp.power)
elementwise_floordiv = _fluid_elementwise(jnp.floor_divide)


# -- reductions -----------------------------------------------------------
def _reduce(jfn, x, axis, keepdim, dtype=None):
    ax = _axes(axis)
    dt = convert_dtype(dtype)
    def fn(v):
        out = jfn(v, axis=ax, keepdims=keepdim)
        if dt is not None:
            out = out.astype(dt)
        return out
    return apply_op(fn, (_t(x),))


def sum(x, axis=None, dtype=None, keepdim=False, name=None):
    x = _t(x)
    if dtype is None and np.dtype(x.dtype) == np.bool_:
        dtype = 'int64'
    return _reduce(jnp.sum, x, axis, keepdim, dtype)


def mean(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.mean, x, axis, keepdim)


def max(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.max, x, axis, keepdim)


def min(x, axis=None, keepdim=False, name=None):
    return _reduce(jnp.min, x, axis, keepdim)


amax = max
amin = min


def prod(x, axis=None, keepdim=False, dtype=None, name=None):
    return _reduce(jnp.prod, x, axis, keepdim, dtype)


def cumsum(x, axis=None, dtype=None, name=None):
    x = _t(x)
    dt = convert_dtype(dtype)
    def fn(v):
        if axis is None:
            v = v.reshape(-1)
            out = jnp.cumsum(v)
        else:
            out = jnp.cumsum(v, axis=int(axis))
        return out.astype(dt) if dt is not None else out
    return apply_op(fn, (x,))


def cumprod(x, dim=None, dtype=None, name=None):
    dt = convert_dtype(dtype)
    def fn(v):
        out = jnp.cumprod(v, axis=int(dim) if dim is not None else None)
        return out.astype(dt) if dt is not None else out
    return apply_op(fn, (_t(x),))


def logsumexp(x, axis=None, keepdim=False, name=None):
    from jax.scipy.special import logsumexp as _lse
    ax = _axes(axis)
    return apply_op(lambda v: _lse(v, axis=ax, keepdims=keepdim), (_t(x),))


def logcumsumexp(x, axis=None, name=None):
    def fn(v):
        if axis is None:
            v = v.reshape(-1)
            ax = 0
        else:
            ax = int(axis)
        m = jnp.max(v, axis=ax, keepdims=True)
        return jnp.log(jnp.cumsum(jnp.exp(v - m), axis=ax)) + m
    return apply_op(fn, (_t(x),))


def clip(x, min=None, max=None, name=None):
    lo = min.item() if isinstance(min, Tensor) else min
    hi = max.item() if isinstance(max, Tensor) else max
    return apply_op(lambda v: jnp.clip(v, lo, hi), (_t(x),))


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None, name=None):
    s = scale.item() if isinstance(scale, Tensor) else scale
    def fn(v):
        if bias_after_scale:
            return v * s + bias
        return (v + bias) * s
    out = apply_op(fn, (_t(x),))
    if act is not None:
        from ..nn import functional as F
        out = getattr(F, act)(out)
    return out


def increment(x, value=1.0, name=None):
    out = apply_op(lambda v: v + jnp.asarray(value, v.dtype), (_t(x),))
    if isinstance(x, Tensor):
        x._inplace_value(out._value)
        return x
    return out


def add_n(inputs, name=None):
    if isinstance(inputs, Tensor):
        return inputs
    ts = tuple(_t(i) for i in inputs)
    return apply_op(lambda *vs: jnp.sum(jnp.stack(vs), axis=0)
                    if len(vs) > 1 else vs[0], ts)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    return apply_op(lambda i, a, b: beta * i + alpha * jnp.matmul(a, b),
                    (_t(input), _t(x), _t(y)))


def kron(x, y, name=None):
    return apply_op(jnp.kron, (_t(x), _t(y)))


def trace(x, offset=0, axis1=0, axis2=1, name=None):
    return apply_op(lambda v: jnp.trace(v, offset=offset, axis1=axis1, axis2=axis2),
                    (_t(x),))


def inner(x, y, name=None):
    return apply_op(jnp.inner, (_t(x), _t(y)))


def outer(x, y, name=None):
    return apply_op(lambda a, b: jnp.outer(a, b), (_t(x), _t(y)))


def multiplex(inputs, index, name=None):
    ts = tuple(_t(i) for i in inputs) + (_t(index),)
    def fn(*args):
        idx = args[-1].reshape(-1).astype(jnp.int32)
        stacked = jnp.stack(args[:-1])  # (n, batch, ...)
        rows = jnp.arange(stacked.shape[1])
        return stacked[idx, rows]
    return apply_op(fn, ts)


def nan_to_num(x, nan=0.0, posinf=None, neginf=None, name=None):
    return apply_op(lambda v: jnp.nan_to_num(v, nan=nan, posinf=posinf, neginf=neginf),
                    (_t(x),))


def lerp(x, y, weight, name=None):
    if isinstance(weight, (int, float)):
        return apply_op(lambda a, b: a + weight * (b - a), (_t(x), _t(y)))
    return apply_op(lambda a, b, w: a + w * (b - a), (_t(x), _t(y), _t(weight)))


def isfinite_v(x, name=None):
    return apply_op(jnp.isfinite, (_t(x),), differentiable=False)


def log_softmax_v(x, axis=-1):
    from jax.nn import log_softmax as _ls
    return apply_op(lambda v: _ls(v, axis=axis), (_t(x),))


def multiply_(x, y):
    out = multiply(x, y)
    x._inplace_value(out._value)
    return x


# -- attach methods -------------------------------------------------------
_METHODS = [
    'add', 'subtract', 'multiply', 'divide', 'floor_divide', 'remainder', 'mod',
    'pow', 'matmul', 'maximum', 'minimum', 'exp', 'log', 'log2', 'log10', 'log1p',
    'sqrt', 'rsqrt', 'abs', 'sign', 'floor', 'ceil', 'round', 'trunc', 'sin',
    'cos', 'tan', 'asin', 'acos', 'atan', 'sinh', 'cosh', 'tanh', 'reciprocal',
    'square', 'erf', 'sum', 'mean', 'max', 'min', 'prod', 'cumsum', 'cumprod',
    'logsumexp', 'clip', 'scale', 'trace', 'kron', 'addmm', 'inner', 'outer',
    'lerp', 'nan_to_num', 'expm1', 'digamma', 'lgamma', 'atan2', 'neg', 'conj',
    'real', 'imag', 'angle', 'frac',
]
_g = globals()
for _name in _METHODS:
    register_method(_name, _g[_name])
