"""Random ops. Parity: python/paddle/tensor/random.py.

All sampling pulls a key from the active Generator (core/rng.py). Inside a
``rng.key_scope`` (used by jitted train steps) keys derive from an explicit
traced key, keeping compiled functions pure and reproducible.
"""
import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, register_method
from ..core.dtypes import convert_dtype, get_default_dtype
from ..core import rng as _rng
from ._helpers import _t, _shape

__all__ = ['uniform', 'normal', 'gaussian', 'standard_normal', 'randn', 'rand',
           'randint', 'randint_like', 'randperm', 'bernoulli', 'multinomial',
           'poisson', 'uniform_', 'normal_', 'exponential_']


def _key():
    return _rng.next_key()


def uniform(shape, dtype=None, min=-1.0, max=1.0, seed=0, name=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    key = jax.random.PRNGKey(seed) if seed else _key()
    return Tensor(jax.random.uniform(key, _shape(shape), dtype=dt,
                                     minval=float(min), maxval=float(max)))


def rand(shape, dtype=None, name=None):
    return uniform(shape, dtype, 0.0, 1.0)


def normal(mean=0.0, std=1.0, shape=None, name=None):
    if isinstance(mean, Tensor) or isinstance(std, Tensor):
        m, s = _t(mean), _t(std)
        key = _key()
        def fn(mv, sv):
            shp = jnp.broadcast_shapes(mv.shape, sv.shape)
            return mv + sv * jax.random.normal(key, shp, dtype=mv.dtype)
        return apply_op(fn, (m, s))
    dt = get_default_dtype()
    return Tensor(float(mean) + float(std) *
                  jax.random.normal(_key(), _shape(shape), dtype=dt))


def gaussian(shape, mean=0.0, std=1.0, dtype=None, name=None):
    dt = convert_dtype(dtype) or get_default_dtype()
    return Tensor(float(mean) + float(std) *
                  jax.random.normal(_key(), _shape(shape), dtype=dt))


def standard_normal(shape, dtype=None, name=None):
    return gaussian(shape, 0.0, 1.0, dtype)


def randn(shape, dtype=None, name=None):
    return standard_normal(shape, dtype)


def randint(low=0, high=None, shape=[1], dtype=None, name=None):
    if high is None:
        low, high = 0, low
    dt = convert_dtype(dtype) or jnp.int64
    return Tensor(jax.random.randint(_key(), _shape(shape), int(low), int(high),
                                     dtype=jnp.int32).astype(dt))


def randint_like(x, low=0, high=None, dtype=None, name=None):
    x = _t(x)
    return randint(low, high, x.shape, dtype or x.dtype)


def randperm(n, dtype='int64', name=None):
    dt = convert_dtype(dtype)
    return Tensor(jax.random.permutation(_key(), int(n)).astype(dt))


def bernoulli(x, name=None):
    x = _t(x)
    key = _key()
    return apply_op(lambda v: jax.random.bernoulli(key, v).astype(v.dtype), (x,),
                    differentiable=False)


def multinomial(x, num_samples=1, replacement=False, name=None):
    x = _t(x)
    key = _key()
    def fn(v):
        logits = jnp.log(jnp.maximum(v, 1e-30))
        if replacement:
            return jax.random.categorical(
                key, logits, axis=-1,
                shape=(num_samples,) if v.ndim == 1 else (num_samples, v.shape[0])
            ).T if v.ndim > 1 else jax.random.categorical(
                key, logits, shape=(num_samples,))
        # without replacement: Gumbel top-k trick
        g = jax.random.gumbel(key, v.shape, dtype=logits.dtype)
        _, idx = jax.lax.top_k(logits + g, num_samples)
        return idx
    out = apply_op(lambda v: fn(v).astype(jnp.int64), (x,), differentiable=False)
    return out


def poisson(x, name=None):
    x = _t(x)
    key = _key()
    return apply_op(lambda v: jax.random.poisson(key, v).astype(v.dtype), (x,),
                    differentiable=False)


def uniform_(x, min=-1.0, max=1.0, name=None):
    x._inplace_value(jax.random.uniform(_key(), tuple(x.shape), dtype=x.dtype,
                                        minval=float(min), maxval=float(max)))
    return x


def normal_(x, mean=0.0, std=1.0, name=None):
    x._inplace_value(float(mean) + float(std) *
                     jax.random.normal(_key(), tuple(x.shape), dtype=x.dtype))
    return x


def exponential_(x, lam=1.0, name=None):
    x._inplace_value(jax.random.exponential(_key(), tuple(x.shape),
                                            dtype=x.dtype) / float(lam))
    return x
