"""Search/sort ops. Parity: python/paddle/tensor/search.py."""
import numpy as np
import jax.numpy as jnp
from jax import lax

from ..core.tensor import Tensor, apply_op, register_method
from ..core.dtypes import convert_dtype
from ._helpers import _t

__all__ = ['argmax', 'argmin', 'argsort', 'sort', 'topk', 'where', 'nonzero',
           'index_sample', 'masked_select', 'kthvalue', 'mode', 'searchsorted']

from .manipulation import index_sample, masked_select  # re-export (paddle puts them here too)


def argmax(x, axis=None, keepdim=False, dtype='int64', name=None):
    dt = convert_dtype(dtype)
    def fn(v):
        out = jnp.argmax(v.reshape(-1) if axis is None else v,
                         axis=None if axis is None else int(axis),
                         keepdims=keepdim if axis is not None else False)
        return out.astype(dt)
    return apply_op(fn, (_t(x),), differentiable=False)


def argmin(x, axis=None, keepdim=False, dtype='int64', name=None):
    dt = convert_dtype(dtype)
    def fn(v):
        out = jnp.argmin(v.reshape(-1) if axis is None else v,
                         axis=None if axis is None else int(axis),
                         keepdims=keepdim if axis is not None else False)
        return out.astype(dt)
    return apply_op(fn, (_t(x),), differentiable=False)


def argsort(x, axis=-1, descending=False, name=None):
    def fn(v):
        idx = jnp.argsort(v, axis=axis, descending=descending)
        return idx.astype(jnp.int64)
    return apply_op(fn, (_t(x),), differentiable=False)


def sort(x, axis=-1, descending=False, name=None):
    def fn(v):
        out = jnp.sort(v, axis=axis, descending=descending)
        return out
    return apply_op(fn, (_t(x),))


def topk(x, k, axis=None, largest=True, sorted=True, name=None):
    if isinstance(k, Tensor):
        k = int(k.item())
    x = _t(x)
    ax = -1 if axis is None else int(axis)
    def fn(v):
        vm = jnp.moveaxis(v, ax, -1)
        if largest:
            vals, idx = lax.top_k(vm, k)
        else:
            vals, idx = lax.top_k(-vm, k)
            vals = -vals
        return (jnp.moveaxis(vals, -1, ax), jnp.moveaxis(idx, -1, ax).astype(jnp.int64))
    vals, idx = apply_op(fn, (x,), n_outputs=2)
    return vals, idx


def where(condition, x=None, y=None, name=None):
    if x is None and y is None:
        return nonzero(condition, as_tuple=False)
    return apply_op(lambda c, a, b: jnp.where(c, a, b),
                    (_t(condition), _t(x), _t(y)))


def nonzero(x, as_tuple=False):
    """Dynamic-size output: host fallback (documented divergence from jit path)."""
    xv = np.asarray(_t(x).numpy())
    nz = np.nonzero(xv)
    if as_tuple:
        return tuple(Tensor(jnp.asarray(n.reshape(-1, 1))) for n in nz)
    return Tensor(jnp.asarray(np.stack(nz, axis=1).astype(np.int64)))


def kthvalue(x, k, axis=-1, keepdim=False, name=None):
    x = _t(x)
    def fn(v):
        sorted_v = jnp.sort(v, axis=axis)
        idx_sorted = jnp.argsort(v, axis=axis)
        vals = jnp.take(sorted_v, k - 1, axis=axis)
        idx = jnp.take(idx_sorted, k - 1, axis=axis)
        if keepdim:
            vals = jnp.expand_dims(vals, axis)
            idx = jnp.expand_dims(idx, axis)
        return (vals, idx.astype(jnp.int64))
    return tuple(apply_op(fn, (x,), n_outputs=2))


def mode(x, axis=-1, keepdim=False, name=None):
    xv = np.asarray(_t(x).numpy())
    from scipy import stats  # available in image? fall back if not
    try:
        m = stats.mode(xv, axis=axis, keepdims=keepdim)
        vals, counts = m.mode, m.count
    except Exception:
        vals = np.apply_along_axis(lambda a: np.bincount(a.astype(np.int64)).argmax(),
                                   axis, xv)
        counts = vals
    idx = np.argmax(xv == np.expand_dims(vals, axis) if not keepdim else xv == vals,
                    axis=axis)
    if keepdim:
        idx = np.expand_dims(idx, axis)
    return Tensor(jnp.asarray(vals)), Tensor(jnp.asarray(idx.astype(np.int64)))


def searchsorted(sorted_sequence, values, out_int32=False, right=False, name=None):
    side = 'right' if right else 'left'
    dt = jnp.int32 if out_int32 else jnp.int64
    def fn(seq, v):
        if seq.ndim == 1:
            return jnp.searchsorted(seq, v, side=side).astype(dt)
        import jax
        return jax.vmap(lambda s, q: jnp.searchsorted(s, q, side=side))(
            seq, v).astype(dt)
    return apply_op(fn, (_t(sorted_sequence), _t(values)), differentiable=False)


for _name in ['argmax', 'argmin', 'argsort', 'sort', 'topk', 'where', 'nonzero',
              'kthvalue', 'searchsorted']:
    register_method(_name, globals()[_name])
