"""Statistics ops. Parity: python/paddle/tensor/stat.py."""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, register_method
from ._helpers import _t, _axes

__all__ = ['mean', 'std', 'var', 'median', 'nanmedian', 'quantile', 'nanmean', 'numel']

from .math import mean
from .creation import numel


def var(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axes(axis)
    ddof = 1 if unbiased else 0
    return apply_op(lambda v: jnp.var(v, axis=ax, ddof=ddof, keepdims=keepdim), (_t(x),))


def std(x, axis=None, unbiased=True, keepdim=False, name=None):
    ax = _axes(axis)
    ddof = 1 if unbiased else 0
    return apply_op(lambda v: jnp.std(v, axis=ax, ddof=ddof, keepdims=keepdim), (_t(x),))


def median(x, axis=None, keepdim=False, name=None):
    ax = _axes(axis)
    return apply_op(lambda v: jnp.median(v, axis=ax, keepdims=keepdim), (_t(x),))


def nanmedian(x, axis=None, keepdim=False, name=None):
    ax = _axes(axis)
    return apply_op(lambda v: jnp.nanmedian(v, axis=ax, keepdims=keepdim), (_t(x),))


def nanmean(x, axis=None, keepdim=False, name=None):
    ax = _axes(axis)
    return apply_op(lambda v: jnp.nanmean(v, axis=ax, keepdims=keepdim), (_t(x),))


def quantile(x, q, axis=None, keepdim=False, name=None):
    ax = _axes(axis)
    qv = q.numpy() if isinstance(q, Tensor) else q
    return apply_op(lambda v: jnp.quantile(v, jnp.asarray(qv), axis=ax,
                                           keepdims=keepdim), (_t(x),))


for _name in ['std', 'var', 'median', 'quantile', 'nanmean', 'nanmedian']:
    register_method(_name, globals()[_name])
