"""NLP models + datasets. Parity: python/paddle/text/__init__.py."""
from . import datasets
from .bert import (BertConfig, BertModel, BertForPretraining,
                   BertPretrainingHeads, bert_base, bert_large)
from .ernie import (ErnieModel, ErnieForPretraining, ErnieConfig,
                    ernie_knowledge_mask, ernie_mask_batch)
from .gpt import GPTConfig, GPTModel, gpt_small
from .seq2seq import Seq2SeqTransformer
from .word2vec import SkipGram, Word2Vec
from .lm import LSTMLanguageModel
from .._native.tokenizer import Tokenizer
from .layers import (RNNCell, BasicLSTMCell, BasicGRUCell, RNN,
                     BidirectionalRNN, StackedRNNCell, StackedLSTMCell,
                     LSTM, BidirectionalLSTM, StackedGRUCell, GRU,
                     BidirectionalGRU, DynamicDecode, BeamSearchDecoder,
                     Conv1dPoolLayer, CNNEncoder, MultiHeadAttention, FFN,
                     TransformerEncoderLayer, TransformerEncoder,
                     TransformerDecoderLayer, TransformerDecoder,
                     TransformerCell, TransformerBeamSearchDecoder,
                     LinearChainCRF, CRFDecoding, SequenceTagging)

# dataset classes at the paddle.text top level (reference text/__init__.py)
from .datasets import (Conll05st, Imdb, Imikolov, Movielens,  # noqa: F401
                       UCIHousing, WMT14, WMT16)
from .datasets import Sentiment as MovieReviews  # noqa: F401
# (the reference's movie_reviews.py NLTK polarity set; one loader, 1.8
# name Sentiment + 2.0-beta name MovieReviews)
