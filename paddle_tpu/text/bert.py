"""BERT / ERNIE model family — the flagship benchmark model.

Parity: the reference trains BERT via transformer ops (softmax_with_cross_
entropy, layer_norm, matmul fused kernels) + Fleet allreduce; ERNIE shares
the architecture with different pretraining data masking. TPU-first: built on
nn.TransformerEncoder (flash-attention path), bf16-friendly, and shardable
tp/dp/sp via distributed.sharding rules (see bert_shard_rules).
"""
import numpy as np
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..tensor.creation import arange, zeros, ones

__all__ = ['BertConfig', 'BertModel', 'BertPretrainingHeads',
           'BertForPretraining', 'bert_base', 'bert_large',
           'bert_shard_rules']


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act="gelu",
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, pad_token_id=0):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        self.pad_token_id = pad_token_id


class BertEmbeddings(nn.Layer):
    def __init__(self, config):
        super().__init__()
        init = nn.initializer.Normal(0., config.initializer_range)
        attr = nn.ParamAttr(initializer=init)
        self.word_embeddings = nn.Embedding(config.vocab_size,
                                            config.hidden_size,
                                            weight_attr=attr)
        self.position_embeddings = nn.Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=attr)
        self.token_type_embeddings = nn.Embedding(config.type_vocab_size,
                                                  config.hidden_size,
                                                  weight_attr=attr)
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=1e-12)
        self.dropout = nn.Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        B, L = input_ids.shape
        if position_ids is None:
            position_ids = arange(0, L, dtype='int64').unsqueeze(0) \
                .expand([B, L])
        if token_type_ids is None:
            token_type_ids = zeros([B, L], dtype='int64')
        emb = (self.word_embeddings(input_ids) +
               self.position_embeddings(position_ids) +
               self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(emb))


class BertPooler(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.dense = nn.Linear(config.hidden_size, config.hidden_size)
        self.activation = nn.Tanh()

    def forward(self, hidden_states):
        return self.activation(self.dense(hidden_states[:, 0]))


class BertModel(nn.Layer):
    def __init__(self, config=None, **kwargs):
        super().__init__()
        config = config or BertConfig(**kwargs)
        self.config = config
        self.embeddings = BertEmbeddings(config)
        enc_layer = nn.TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, dropout=config.hidden_dropout_prob,
            activation=config.hidden_act,
            attn_dropout=config.attention_probs_dropout_prob,
            act_dropout=0.0)
        self.encoder = nn.TransformerEncoder(enc_layer,
                                             config.num_hidden_layers)
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is not None and attention_mask.ndim == 2:
            # (B, L) padding mask -> (B, 1, 1, L) additive
            am = (1.0 - attention_mask.astype('float32')) * -1e4
            attention_mask = am.unsqueeze(1).unsqueeze(1)
        emb = self.embeddings(input_ids, token_type_ids, position_ids)
        seq = self.encoder(emb, attention_mask)
        pooled = self.pooler(seq)
        return seq, pooled


class BertPretrainingHeads(nn.Layer):
    """MLM head (tied decoder) + NSP head."""

    def __init__(self, config, embedding_weights=None):
        super().__init__()
        self.transform = nn.Linear(config.hidden_size, config.hidden_size)
        self.activation = getattr(nn.functional, config.hidden_act)
        self.layer_norm = nn.LayerNorm(config.hidden_size, epsilon=1e-12)
        self.decoder_weight = embedding_weights  # tied (vocab, hidden)
        self.decoder_bias = self.create_parameter([config.vocab_size],
                                                  is_bias=True)
        self.seq_relationship = nn.Linear(config.hidden_size, 2)

    def forward(self, sequence_output, pooled_output, masked_positions=None):
        if masked_positions is not None:
            from ..tensor.manipulation import gather_nd, concat
            B, K = masked_positions.shape
            batch_idx = arange(0, B, dtype='int64').unsqueeze(1) \
                .expand([B, K]).unsqueeze(-1)
            idx = concat([batch_idx,
                          masked_positions.astype('int64').unsqueeze(-1)],
                         axis=-1)
            sequence_output = gather_nd(sequence_output, idx)
        h = self.layer_norm(self.activation(self.transform(sequence_output)))
        logits = h.matmul(self.decoder_weight, transpose_y=True) + \
            self.decoder_bias
        nsp_logits = self.seq_relationship(pooled_output)
        return logits, nsp_logits


class BertForPretraining(nn.Layer):
    def __init__(self, config=None, **kwargs):
        super().__init__()
        self.bert = BertModel(config, **kwargs)
        self.cls = BertPretrainingHeads(
            self.bert.config, self.bert.embeddings.word_embeddings.weight)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                masked_positions=None):
        seq, pooled = self.bert(input_ids, token_type_ids,
                                attention_mask=attention_mask)
        return self.cls(seq, pooled, masked_positions)

    def pretraining_loss(self, prediction_logits, nsp_logits, masked_labels,
                         next_sentence_labels):
        mlm = nn.functional.cross_entropy(
            prediction_logits.reshape([-1, prediction_logits.shape[-1]]),
            masked_labels.reshape([-1]), ignore_index=-1)
        nsp = nn.functional.cross_entropy(nsp_logits,
                                          next_sentence_labels.reshape([-1]))
        return mlm + nsp


def bert_base(**kwargs):
    return BertConfig(hidden_size=768, num_hidden_layers=12,
                      num_attention_heads=12, intermediate_size=3072, **kwargs)


def bert_large(**kwargs):
    return BertConfig(hidden_size=1024, num_hidden_layers=24,
                      num_attention_heads=16, intermediate_size=4096, **kwargs)


def bert_shard_rules(axis_model='model'):
    """PartitionSpec rules for tp-sharding a BertModel (megatron layout)."""
    from jax.sharding import PartitionSpec as P
    return {
        # attention: qkv column-parallel, out row-parallel
        'q_proj.weight': P(None, axis_model),
        'k_proj.weight': P(None, axis_model),
        'v_proj.weight': P(None, axis_model),
        'q_proj.bias': P(axis_model),
        'k_proj.bias': P(axis_model),
        'v_proj.bias': P(axis_model),
        'out_proj.weight': P(axis_model, None),
        # ffn: in column-parallel, out row-parallel
        'linear1.weight': P(None, axis_model),
        'linear1.bias': P(axis_model),
        'linear2.weight': P(axis_model, None),
        # embeddings: vocab-parallel
        'word_embeddings.weight': P(axis_model, None),
    }
