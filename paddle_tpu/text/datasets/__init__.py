"""Text datasets (synthetic fallbacks; no network egress).

Parity: python/paddle/text/datasets/ (Imdb, Imikolov, Movielens, UCIHousing,
WMT14/16, Conll05).
"""
from .synthetic import (Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
                        Conll05st)
