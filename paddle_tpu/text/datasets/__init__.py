"""Text datasets (synthetic fallbacks; no network egress).

Parity: python/paddle/text/datasets/ + python/paddle/dataset/ (Imdb,
Imikolov, Movielens, UCIHousing, WMT14/16, Conll05, MQ2007, Sentiment).
"""
from .synthetic import (Imdb, Imikolov, Movielens, UCIHousing, WMT14, WMT16,
                        Conll05st, MQ2007, Sentiment)
