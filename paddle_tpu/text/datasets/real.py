"""Local-file loaders for text datasets (no network egress).

Activated when the expected files exist under $PADDLE_TPU_DATA_HOME (default
~/.cache/paddle_tpu). Formats follow the reference datasets
(python/paddle/text/datasets/uci_housing.py, imdb.py, imikolov.py): same
tarball/file layouts a user of the reference would already have on disk.
"""
import os
import re
import tarfile

import numpy as np

DATA_HOME = os.environ.get(
    'PADDLE_TPU_DATA_HOME', os.path.expanduser('~/.cache/paddle_tpu'))


def data_path(*parts):
    return os.path.join(DATA_HOME, *parts)


def load_uci_housing(mode='train', split=0.8):
    """housing.data: whitespace-separated floats, 13 features + MEDV target.
    Returns (x, y) float32 arrays or None when the file is absent."""
    path = data_path('uci_housing', 'housing.data')
    if not os.path.exists(path):
        return None
    raw = np.loadtxt(path).astype(np.float32)
    feats, target = raw[:, :-1], raw[:, -1:]
    # feature-wise (x - avg) / (max - min) over the FULL dataset, matching
    # the reference loader (uci_housing.py feature_range over whole matrix)
    n_train = int(len(raw) * split)
    mx = feats.max(axis=0)
    mn = feats.min(axis=0)
    avg = feats.mean(axis=0)
    feats = (feats - avg) / np.maximum(mx - mn, 1e-6)
    if mode == 'train':
        return feats[:n_train], target[:n_train]
    return feats[n_train:], target[n_train:]


_TOKENIZE = re.compile(r"[a-z]+|[^a-z\s]")


def _tokenize(line):
    return _TOKENIZE.findall(line.lower())


def load_imdb(mode='train', cutoff=150):
    """aclImdb_v1.tar.gz: pos/neg review text -> (word-id docs, labels).

    Builds the word dict from the train split with frequency cutoff.
    Single streaming pass over the tarball: token lists are kept per split
    and id-converted at the end (the archive is ~80MB gz — decompressing it
    repeatedly per construction would dominate load time).
    """
    path = data_path('imdb', 'aclImdb_v1.tar.gz')
    if not os.path.exists(path):
        return None
    pat = re.compile(r'aclImdb/(train|test)/(pos|neg)/.*\.txt$')
    freq = {}
    token_docs, labels = [], []
    with tarfile.open(path) as tf:
        for m in tf:
            mm = pat.match(m.name)
            if not mm:
                continue
            toks = _tokenize(tf.extractfile(m).read().decode(
                'utf-8', 'ignore'))
            # dict counts BOTH splits (reference imdb.py word_dict pattern
            # covers train|test), keeping ids compatible with the reference
            for w in toks:
                freq[w] = freq.get(w, 0) + 1
            if mm.group(1) == mode:
                token_docs.append(toks)
                labels.append(0 if mm.group(2) == 'pos' else 1)
    word_idx = {w: i for i, (w, c) in enumerate(
        sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])))
        if c >= cutoff}
    unk = len(word_idx)
    docs = [np.array([word_idx.get(w, unk) for w in toks], dtype=np.int64)
            for toks in token_docs]
    return docs, np.asarray(labels, np.int64), word_idx


def _imikolov_dict_from(tf, min_word_freq):
    """Word dict from the open tarball's ptb.train.txt. Follows the
    reference imikolov.py: lines wrapped with <s>/<e> markers before
    counting, words kept when freq > min_word_freq (strict), <unk> last."""
    freq = {}
    f = tf.extractfile('./simple-examples/data/ptb.train.txt')
    for line in f.read().decode('utf-8').splitlines():
        for w in ['<s>'] + line.strip().split() + ['<e>']:
            freq[w] = freq.get(w, 0) + 1
    freq = {w: c for w, c in freq.items()
            if c > min_word_freq and w != '<unk>'}
    word_idx = {w: i for i, (w, c) in enumerate(
        sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])))}
    word_idx['<unk>'] = len(word_idx)
    return word_idx


def load_imikolov_dict(min_word_freq=50):
    path = data_path('imikolov', 'simple-examples.tgz')
    if not os.path.exists(path):
        return None
    with tarfile.open(path) as tf:
        return _imikolov_dict_from(tf, min_word_freq)


def load_imikolov(mode='train', data_type='NGRAM', window_size=5,
                  min_word_freq=50):
    """PTB ngrams/sequences from simple-examples.tgz, or None if absent.

    NGRAM: windows over <s> line <e>; SEQ: (src, trg) = (<s>+ids, ids+<e>)
    pairs, both per the reference imikolov.py. One decompression pass: the
    dict is built from the same open tarball as the data read.
    """
    path = data_path('imikolov', 'simple-examples.tgz')
    if not os.path.exists(path):
        return None
    fname = ('./simple-examples/data/ptb.train.txt' if mode == 'train'
             else './simple-examples/data/ptb.valid.txt')
    data = []
    with tarfile.open(path) as tf:
        word_idx = _imikolov_dict_from(tf, min_word_freq)
        unk = word_idx['<unk>']
        f = tf.extractfile(fname)
        for line in f.read().decode('utf-8').splitlines():
            words = ['<s>'] + line.strip().split() + ['<e>']
            ids = [word_idx.get(w, unk) for w in words]
            if data_type.upper() == 'NGRAM':
                if len(ids) >= window_size:
                    for i in range(window_size, len(ids) + 1):
                        data.append(np.array(ids[i - window_size:i],
                                             dtype=np.int64))
            else:
                src = np.array(ids[:-1], dtype=np.int64)
                trg = np.array(ids[1:], dtype=np.int64)
                data.append((src, trg))
    return data


# ---------------------------------------------------------------------------
# Machine translation: WMT14 (shrunk set) and WMT16 (Multi30k)
# ---------------------------------------------------------------------------

_WMT_START, _WMT_END, _WMT_UNK = '<s>', '<e>', '<unk>'
_WMT14_UNK_IDX = 2


def load_wmt14(mode='train', dict_size=30000):
    """wmt14.tgz (reference dataset/wmt14.py layout: members ending in
    src.dict / trg.dict plus train/train, test/test, gen/gen tab-separated
    parallel text). Returns (pairs, src_dict, trg_dict) or None when absent;
    pairs are (src_ids, trg_ids, trg_ids_next) int64 arrays with the
    reference's <s>/<e> wrapping and the >80-token filter."""
    path = data_path('wmt14', 'wmt14.tgz')
    if not os.path.exists(path):
        return None
    member = {'train': 'train/train', 'test': 'test/test',
              'gen': 'gen/gen'}[mode]

    def to_dict(f, size):
        d = {}
        for i, line in enumerate(f):
            if i >= size:
                break
            d[line.strip().decode('utf-8')] = i
        return d

    pairs = []
    with tarfile.open(path) as tf:
        src_name = [m.name for m in tf if m.name.endswith('src.dict')][0]
        trg_name = [m.name for m in tf if m.name.endswith('trg.dict')][0]
        src_dict = to_dict(tf.extractfile(src_name), dict_size)
        trg_dict = to_dict(tf.extractfile(trg_name), dict_size)
        data_names = [m.name for m in tf if m.name.endswith(member)]
        for name in data_names:
            for line in tf.extractfile(name):
                parts = line.decode('utf-8', 'ignore').strip().split('\t')
                if len(parts) != 2:
                    continue
                src_ids = [src_dict.get(w, _WMT14_UNK_IDX)
                           for w in [_WMT_START] + parts[0].split() +
                           [_WMT_END]]
                trg = [trg_dict.get(w, _WMT14_UNK_IDX)
                       for w in parts[1].split()]
                if len(src_ids) > 80 or len(trg) > 80:
                    continue
                pairs.append((
                    np.array(src_ids, np.int64),
                    np.array([trg_dict[_WMT_START]] + trg, np.int64),
                    np.array(trg + [trg_dict[_WMT_END]], np.int64)))
    return pairs, src_dict, trg_dict


def _wmt16_build_dict(tf, dict_size, lang):
    """Freq-sorted dict from wmt16/train with <s>/<e>/<unk> at ids 0/1/2
    (reference wmt16.py __build_dict; tie-break by word for determinism)."""
    col = 0 if lang == 'en' else 1
    freq = {}
    for line in tf.extractfile('wmt16/train'):
        parts = line.decode('utf-8', 'ignore').strip().split('\t')
        if len(parts) != 2:
            continue
        for w in parts[col].split():
            freq[w] = freq.get(w, 0) + 1
    words = [_WMT_START, _WMT_END, _WMT_UNK]
    for w, c in sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])):
        if len(words) >= dict_size:
            break
        words.append(w)
    return {w: i for i, w in enumerate(words)}


def load_wmt16(mode='train', src_dict_size=10000, trg_dict_size=10000,
               src_lang='en'):
    """wmt16.tar.gz (Multi30k; reference dataset/wmt16.py layout: members
    wmt16/train, wmt16/test, wmt16/val with en<TAB>de lines). Returns
    (pairs, src_dict, trg_dict) or None; pairs as in load_wmt14 (no length
    filter, per the reference)."""
    path = data_path('wmt16', 'wmt16.tar.gz')
    if not os.path.exists(path):
        return None
    member = {'train': 'wmt16/train', 'test': 'wmt16/test',
              'val': 'wmt16/val'}[mode]
    src_col = 0 if src_lang == 'en' else 1
    pairs = []
    with tarfile.open(path) as tf:
        src_dict = _wmt16_build_dict(tf, src_dict_size, src_lang)
        trg_dict = _wmt16_build_dict(
            tf, trg_dict_size, 'de' if src_lang == 'en' else 'en')
        start, end, unk = (src_dict[_WMT_START], src_dict[_WMT_END],
                           src_dict[_WMT_UNK])
        for line in tf.extractfile(member):
            parts = line.decode('utf-8', 'ignore').strip().split('\t')
            if len(parts) != 2:
                continue
            src_ids = [start] + [src_dict.get(w, unk)
                                 for w in parts[src_col].split()] + [end]
            trg = [trg_dict.get(w, unk) for w in parts[1 - src_col].split()]
            pairs.append((np.array(src_ids, np.int64),
                          np.array([start] + trg, np.int64),
                          np.array(trg + [end], np.int64)))
    return pairs, src_dict, trg_dict


# ---------------------------------------------------------------------------
# Conll05 SRL
# ---------------------------------------------------------------------------

def _conll05_parse_props(labels):
    """One predicate's prop column -> BIO tags (reference conll05.py
    corpus_reader bracket-walk)."""
    cur, inside, out = 'O', False, []
    for l in labels:
        if l == '*':
            out.append('I-' + cur if inside else 'O')
        elif l == '*)':
            out.append('I-' + cur)
            inside = False
        elif '(' in l and ')' in l:
            cur = l[1:l.find('*')]
            out.append('B-' + cur)
            inside = False
        elif '(' in l:
            cur = l[1:l.find('*')]
            out.append('B-' + cur)
            inside = True
        else:
            raise ValueError('unexpected SRL label: %r' % l)
    return out


def load_conll05_dicts():
    """wordDict.txt / verbDict.txt / targetDict.txt under conll05/, or None.
    Label dict is built B-*/I-* interleaved then O last, like the
    reference's load_label_dict."""
    base = data_path('conll05')
    paths = [os.path.join(base, n) for n in
             ('wordDict.txt', 'verbDict.txt', 'targetDict.txt')]
    if not all(os.path.exists(p) for p in paths):
        return None

    def load_dict(p):
        with open(p) as f:
            return {line.strip(): i for i, line in enumerate(f)}

    word_dict, verb_dict = load_dict(paths[0]), load_dict(paths[1])
    tags = []
    with open(paths[2]) as f:
        for line in f:
            line = line.strip()
            if line.startswith(('B-', 'I-')) and line[2:] not in tags:
                tags.append(line[2:])
    # B-t/I-t get adjacent ids per tag type, O last (the reference's
    # load_label_dict layout; iteration order here is first-appearance,
    # deterministic, where the reference iterates an unordered set)
    label_dict = {}
    for t in tags:
        label_dict['B-' + t] = len(label_dict)
        label_dict['I-' + t] = len(label_dict)
    label_dict['O'] = len(label_dict)
    return word_dict, verb_dict, label_dict


def load_conll05(words_name='conll05st-release/test.wsj/words/test.wsj.words.gz',
                 props_name='conll05st-release/test.wsj/props/test.wsj.props.gz'):
    """conll05st-tests.tar.gz + dict files -> SRL samples, or None.

    Each sample mirrors the reference reader_creator's 9 slots:
    (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_ids, mark,
    label_ids) — the five ctx features are the predicate+-2 window words
    broadcast over the sentence, mark flags the window positions.
    """
    import gzip
    path = data_path('conll05', 'conll05st-tests.tar.gz')
    dicts = load_conll05_dicts()
    if not os.path.exists(path) or dicts is None:
        return None
    word_dict, verb_dict, label_dict = dicts
    unk = 0
    samples = []
    def emit(sentence, seg):
        """One sample per predicate of a finished sentence."""
        if not seg:
            return
        cols = list(zip(*seg))         # transpose to per-column
        verbs = [v for v in cols[0] if v != '-']
        for i, col in enumerate(cols[1:]):
            tags = _conll05_parse_props(col)
            v_idx = tags.index('B-V')
            n = len(sentence)
            mark = [0] * n
            ctx = []
            for off, fallback in ((-2, 'bos'), (-1, 'bos'), (0, None),
                                  (1, 'eos'), (2, 'eos')):
                j = v_idx + off
                if 0 <= j < n:
                    mark[j] = 1
                    ctx.append(sentence[j])
                else:
                    ctx.append(fallback)
            word_ids = [word_dict.get(w, unk) for w in sentence]
            ctx_ids = [[word_dict.get(c, unk)] * n for c in ctx]
            samples.append(tuple(
                np.array(a, np.int64) for a in (
                    [word_ids] + ctx_ids +
                    [[verb_dict.get(verbs[i], unk)] * n,
                     mark,
                     [label_dict[t] for t in tags]])))

    with tarfile.open(path) as tf:
        with gzip.GzipFile(fileobj=tf.extractfile(words_name)) as wf, \
                gzip.GzipFile(fileobj=tf.extractfile(props_name)) as pf:
            sentence, seg = [], []
            for wline, pline in zip(wf, pf):
                word = wline.decode('utf-8').strip()
                props = pline.decode('utf-8').strip().split()
                if props:
                    sentence.append(word)
                    seg.append(props)
                    continue
                emit(sentence, seg)    # sentence boundary
                sentence, seg = [], []
            emit(sentence, seg)        # corpus without a trailing blank line
    return samples


# ---------------------------------------------------------------------------
# Movielens ml-1m
# ---------------------------------------------------------------------------

def load_movielens(mode='train', test_ratio=0.1, rand_seed=0):
    """ml-1m.zip (reference dataset/movielens.py: ratings/users/movies .dat
    with :: separators). Returns (features, meta) or None.

    features: list of (user_id, gender, age_idx, job_id, movie_id,
    category_ids, title_ids, rating) — ints/int64 arrays + float32 rating;
    meta: dict with category/title vocabularies. The train/test split uses
    a seeded RNG draw per rating row like the reference's __reader__.
    """
    import random as _random
    import zipfile
    path = data_path('movielens', 'ml-1m.zip')
    if not os.path.exists(path):
        return None
    ages = {'1': 0, '18': 1, '25': 2, '35': 3, '45': 4, '50': 5, '56': 6}
    categories, title_vocab = {}, {}
    movies, users = {}, {}
    with zipfile.ZipFile(path) as z:
        with z.open('ml-1m/movies.dat') as f:
            for line in f.read().decode('latin1').splitlines():
                mid, title, cats = line.strip().split('::')
                cat_ids = [categories.setdefault(c, len(categories))
                           for c in cats.split('|')]
                tit_ids = [title_vocab.setdefault(w.lower(), len(title_vocab))
                           for w in title.split()]
                movies[mid] = (int(mid), np.array(cat_ids, np.int64),
                               np.array(tit_ids, np.int64))
        with z.open('ml-1m/users.dat') as f:
            for line in f.read().decode('latin1').splitlines():
                uid, gender, age, job, _zip = line.strip().split('::')
                users[uid] = (int(uid), 0 if gender == 'M' else 1,
                              ages.get(age, 0), int(job))
        rng = _random.Random(rand_seed)
        feats = []
        with z.open('ml-1m/ratings.dat') as f:
            for line in f.read().decode('latin1').splitlines():
                uid, mid, rating, _ts = line.strip().split('::')
                is_test = rng.random() < test_ratio
                if is_test != (mode == 'test'):
                    continue
                if uid not in users or mid not in movies:
                    continue
                u = users[uid]
                m = movies[mid]
                feats.append(u + m + (np.float32(rating),))
    meta = {'categories': categories, 'title_vocab': title_vocab,
            'n_users': max(u[0] for u in users.values()) + 1,
            'n_movies': max(m[0] for m in movies.values()) + 1}
    return feats, meta


# ---------------------------------------------------------------------------
# MQ2007 (LETOR 4.0 learning-to-rank)
# ---------------------------------------------------------------------------

def load_mq2007(mode='pointwise', path_name='Querylevelnorm.txt'):
    """LETOR MQ2007 querylevelnorm lines (reference dataset/mq2007.py):
    ``rel qid:Q 1:v 2:v ... 46:v #docid = ...``. Returns samples per mode,
    or None when the file is absent:

    - pointwise: (relevance, feature[46]) per document;
    - pairwise: (label=1, feat_hi, feat_lo) for every in-query document
      pair with differing relevance (higher first, the reference's
      C(n,2) full partial order);
    - listwise: (relevance_list, feature_matrix) per query.
    """
    path = data_path('mq2007', path_name)
    if not os.path.exists(path):
        return None
    queries = {}
    order = []
    with open(path) as f:
        for line in f:
            body = line.split('#', 1)[0].strip()
            if not body:
                continue
            parts = body.split()
            rel = int(parts[0])
            qid = int(parts[1].split(':')[1])
            feat = np.zeros(46, np.float32)
            for p in parts[2:]:
                k, v = p.split(':')
                feat[int(k) - 1] = float(v)
            if qid not in queries:
                queries[qid] = []
                order.append(qid)
            queries[qid].append((rel, feat))
    return mq2007_samples((queries[qid] for qid in order), mode)


def mq2007_samples(query_groups, mode):
    """[(rel, feat[46])] per query -> mode-specific samples; the single
    implementation of the pointwise/pairwise/listwise generators (shared
    by the real loader and the synthetic fallback)."""
    out = []
    for docs in query_groups:
        if mode == 'pointwise':
            out.extend((np.int64(rel), feat) for rel, feat in docs)
        elif mode == 'pairwise':
            for i in range(len(docs)):
                for j in range(i + 1, len(docs)):
                    ri, fi = docs[i]
                    rj, fj = docs[j]
                    if ri == rj:
                        continue
                    hi, lo = (fi, fj) if ri > rj else (fj, fi)
                    out.append((np.int64(1), hi, lo))
        elif mode == 'listwise':
            out.append((np.asarray([r for r, _ in docs], np.int64),
                        np.stack([f for _, f in docs])))
        else:
            raise ValueError("mq2007 mode must be pointwise/pairwise/"
                             "listwise, got %r" % mode)
    return out


# ---------------------------------------------------------------------------
# Sentiment (NLTK movie_reviews layout)
# ---------------------------------------------------------------------------

_SENTIMENT_CORPUS = {}   # base path -> (per_file, freq); tokenizing 2000
                         # reviews is the expensive part, do it once


def _sentiment_corpus(base, test_ratio):
    key = (base, test_ratio)
    if key in _SENTIMENT_CORPUS:
        return _SENTIMENT_CORPUS[key]
    freq = {}
    per_file = []
    for label, cat in ((0, 'pos'), (1, 'neg')):
        cat_dir = os.path.join(base, cat)
        if not os.path.isdir(cat_dir):
            return None
        for i, fname in enumerate(sorted(os.listdir(cat_dir))):
            with open(os.path.join(cat_dir, fname), errors='ignore') as f:
                toks = _tokenize(f.read())
            for w in toks:
                freq[w] = freq.get(w, 0) + 1
            is_test = (i % int(round(1 / test_ratio)) == 0)
            per_file.append((toks, label, is_test))
    _SENTIMENT_CORPUS[key] = (per_file, freq)
    return per_file, freq


def load_sentiment(mode='train', cutoff=0, test_ratio=0.1):
    """movie_reviews/{pos,neg}/*.txt (reference dataset/sentiment.py via
    NLTK). Returns (docs, labels, word_idx) or None; label 0 = pos,
    1 = neg (the reference's ordering). Deterministic round-robin split:
    every 10th file per class is held out for test. The parsed corpus is
    cached so train+test loads tokenize the files once."""
    base = data_path('sentiment', 'movie_reviews')
    if not os.path.isdir(base):
        return None
    corpus = _sentiment_corpus(base, test_ratio)
    if corpus is None:
        return None
    per_file, freq = corpus
    word_idx = {w: i for i, (w, c) in enumerate(
        sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])))
        if c >= cutoff}
    unk = len(word_idx)
    docs, labels = [], []
    want_test = (mode == 'test')
    for toks, label, is_test in per_file:
        if is_test != want_test:
            continue
        docs.append(np.asarray([word_idx.get(w, unk) for w in toks],
                               np.int64))
        labels.append(label)
    return docs, np.asarray(labels, np.int64), word_idx
