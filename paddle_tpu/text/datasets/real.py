"""Local-file loaders for text datasets (no network egress).

Activated when the expected files exist under $PADDLE_TPU_DATA_HOME (default
~/.cache/paddle_tpu). Formats follow the reference datasets
(python/paddle/text/datasets/uci_housing.py, imdb.py, imikolov.py): same
tarball/file layouts a user of the reference would already have on disk.
"""
import os
import re
import tarfile

import numpy as np

DATA_HOME = os.environ.get(
    'PADDLE_TPU_DATA_HOME', os.path.expanduser('~/.cache/paddle_tpu'))


def data_path(*parts):
    return os.path.join(DATA_HOME, *parts)


def load_uci_housing(mode='train', split=0.8):
    """housing.data: whitespace-separated floats, 13 features + MEDV target.
    Returns (x, y) float32 arrays or None when the file is absent."""
    path = data_path('uci_housing', 'housing.data')
    if not os.path.exists(path):
        return None
    raw = np.loadtxt(path).astype(np.float32)
    feats, target = raw[:, :-1], raw[:, -1:]
    # feature-wise (x - avg) / (max - min) over the FULL dataset, matching
    # the reference loader (uci_housing.py feature_range over whole matrix)
    n_train = int(len(raw) * split)
    mx = feats.max(axis=0)
    mn = feats.min(axis=0)
    avg = feats.mean(axis=0)
    feats = (feats - avg) / np.maximum(mx - mn, 1e-6)
    if mode == 'train':
        return feats[:n_train], target[:n_train]
    return feats[n_train:], target[n_train:]


_TOKENIZE = re.compile(r"[a-z]+|[^a-z\s]")


def _tokenize(line):
    return _TOKENIZE.findall(line.lower())


def load_imdb(mode='train', cutoff=150):
    """aclImdb_v1.tar.gz: pos/neg review text -> (word-id docs, labels).

    Builds the word dict from the train split with frequency cutoff.
    Single streaming pass over the tarball: token lists are kept per split
    and id-converted at the end (the archive is ~80MB gz — decompressing it
    repeatedly per construction would dominate load time).
    """
    path = data_path('imdb', 'aclImdb_v1.tar.gz')
    if not os.path.exists(path):
        return None
    pat = re.compile(r'aclImdb/(train|test)/(pos|neg)/.*\.txt$')
    freq = {}
    token_docs, labels = [], []
    with tarfile.open(path) as tf:
        for m in tf:
            mm = pat.match(m.name)
            if not mm:
                continue
            toks = _tokenize(tf.extractfile(m).read().decode(
                'utf-8', 'ignore'))
            # dict counts BOTH splits (reference imdb.py word_dict pattern
            # covers train|test), keeping ids compatible with the reference
            for w in toks:
                freq[w] = freq.get(w, 0) + 1
            if mm.group(1) == mode:
                token_docs.append(toks)
                labels.append(0 if mm.group(2) == 'pos' else 1)
    word_idx = {w: i for i, (w, c) in enumerate(
        sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])))
        if c >= cutoff}
    unk = len(word_idx)
    docs = [np.array([word_idx.get(w, unk) for w in toks], dtype=np.int64)
            for toks in token_docs]
    return docs, np.asarray(labels, np.int64), word_idx


def _imikolov_dict_from(tf, min_word_freq):
    """Word dict from the open tarball's ptb.train.txt. Follows the
    reference imikolov.py: lines wrapped with <s>/<e> markers before
    counting, words kept when freq > min_word_freq (strict), <unk> last."""
    freq = {}
    f = tf.extractfile('./simple-examples/data/ptb.train.txt')
    for line in f.read().decode('utf-8').splitlines():
        for w in ['<s>'] + line.strip().split() + ['<e>']:
            freq[w] = freq.get(w, 0) + 1
    freq = {w: c for w, c in freq.items()
            if c > min_word_freq and w != '<unk>'}
    word_idx = {w: i for i, (w, c) in enumerate(
        sorted(freq.items(), key=lambda kv: (-kv[1], kv[0])))}
    word_idx['<unk>'] = len(word_idx)
    return word_idx


def load_imikolov_dict(min_word_freq=50):
    path = data_path('imikolov', 'simple-examples.tgz')
    if not os.path.exists(path):
        return None
    with tarfile.open(path) as tf:
        return _imikolov_dict_from(tf, min_word_freq)


def load_imikolov(mode='train', data_type='NGRAM', window_size=5,
                  min_word_freq=50):
    """PTB ngrams/sequences from simple-examples.tgz, or None if absent.

    NGRAM: windows over <s> line <e>; SEQ: (src, trg) = (<s>+ids, ids+<e>)
    pairs, both per the reference imikolov.py. One decompression pass: the
    dict is built from the same open tarball as the data read.
    """
    path = data_path('imikolov', 'simple-examples.tgz')
    if not os.path.exists(path):
        return None
    fname = ('./simple-examples/data/ptb.train.txt' if mode == 'train'
             else './simple-examples/data/ptb.valid.txt')
    data = []
    with tarfile.open(path) as tf:
        word_idx = _imikolov_dict_from(tf, min_word_freq)
        unk = word_idx['<unk>']
        f = tf.extractfile(fname)
        for line in f.read().decode('utf-8').splitlines():
            words = ['<s>'] + line.strip().split() + ['<e>']
            ids = [word_idx.get(w, unk) for w in words]
            if data_type.upper() == 'NGRAM':
                if len(ids) >= window_size:
                    for i in range(window_size, len(ids) + 1):
                        data.append(np.array(ids[i - window_size:i],
                                             dtype=np.int64))
            else:
                src = np.array(ids[:-1], dtype=np.int64)
                trg = np.array(ids[1:], dtype=np.int64)
                data.append((src, trg))
    return data
