"""Synthetic text datasets (deterministic; stand in for downloads).

Parity surface: python/paddle/text/datasets/*.py. Real corpora load from
PADDLE_TPU_DATA_HOME when present.
"""
import os

import numpy as np

from ...io import Dataset

__all__ = ['Imdb', 'Imikolov', 'Movielens', 'UCIHousing', 'WMT14', 'WMT16',
           'Conll05st', 'MQ2007', 'Sentiment']


class _SyntheticSeqDataset(Dataset):
    VOCAB = 5000
    SEQ = 128
    N_TRAIN = 2048
    N_TEST = 256
    NUM_CLASSES = 2

    def __init__(self, mode='train', **kwargs):
        import zlib
        # crc32, not hash(): str hashing is salted per process, and the
        # synthetic data must be identical across runs
        seed = zlib.crc32(
            ('%s:%s' % (type(self).__name__, mode)).encode()) % (2 ** 31)
        rng = np.random.RandomState(seed)
        n = self.N_TRAIN if mode == 'train' else self.N_TEST
        self.docs = rng.randint(1, self.VOCAB, size=(n, self.SEQ)).astype(
            np.int64)
        self.labels = rng.randint(0, self.NUM_CLASSES, size=n).astype(np.int64)
        self.synthetic = True

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)


class Imdb(_SyntheticSeqDataset):
    VOCAB = 5147

    def __init__(self, mode='train', cutoff=150, **kwargs):
        from . import real
        loaded = real.load_imdb(mode, cutoff)
        if loaded is not None:
            self.docs, self.labels, self.word_idx = loaded
            self.synthetic = False
            return
        super().__init__(mode, **kwargs)


class Imikolov(_SyntheticSeqDataset):
    """N-gram LM data: returns (context, next word)."""
    VOCAB = 2000
    SEQ = 5

    def __init__(self, mode='train', data_type='NGRAM', window_size=5,
                 min_word_freq=50, **kwargs):
        from . import real
        loaded = real.load_imikolov(mode, data_type, window_size,
                                    min_word_freq)
        if loaded is not None:
            self.docs = loaded
            self.synthetic = False
            return
        # synthetic n-grams must honor the requested window, or a model
        # built for n-grams gets wrong context widths
        self.SEQ = int(window_size)
        super().__init__(mode, **kwargs)

    def __getitem__(self, idx):
        seq = self.docs[idx]
        if isinstance(seq, tuple):      # real SEQ mode: (src, trg) pair
            return seq
        return seq[:-1], seq[-1:]


class Movielens(Dataset):
    """ml-1m ratings. Real loader (PADDLE_TPU_DATA_HOME/movielens/ml-1m.zip)
    yields full (uid, gender, age, job, movie, categories, title, rating)
    feature rows; the synthetic fallback keeps the 3-tuple shape."""

    def __init__(self, mode='train', test_ratio=0.1, rand_seed=0, **kwargs):
        from . import real
        loaded = real.load_movielens(mode, test_ratio, rand_seed)
        if loaded is not None:
            self.feats, self.meta = loaded
            self.synthetic = False
            return
        rng = np.random.RandomState(7 if mode == 'train' else 8)
        n = 4096 if mode == 'train' else 512
        self.users = rng.randint(0, 6040, n).astype(np.int64)
        self.movies = rng.randint(0, 3952, n).astype(np.int64)
        self.ratings = rng.randint(1, 6, n).astype(np.float32)
        self.synthetic = True

    def __getitem__(self, idx):
        if not self.synthetic:
            return self.feats[idx]
        return (self.users[idx], self.movies[idx], self.ratings[idx])

    def __len__(self):
        return len(self.feats) if not self.synthetic else len(self.users)


class UCIHousing(Dataset):
    def __init__(self, mode='train', **kwargs):
        from . import real
        loaded = real.load_uci_housing(mode)
        if loaded is not None:
            self.x, self.y = loaded
            self.synthetic = False
            return
        rng = np.random.RandomState(9 if mode == 'train' else 10)
        n = 404 if mode == 'train' else 102
        self.x = rng.randn(n, 13).astype(np.float32)
        w = np.linspace(-1, 1, 13).astype(np.float32)
        self.y = (self.x @ w + 0.1 * rng.randn(n)).astype(
            np.float32).reshape(-1, 1)
        self.synthetic = True

    def __getitem__(self, idx):
        return self.x[idx], self.y[idx]

    def __len__(self):
        return len(self.x)


class WMT14(_SyntheticSeqDataset):
    """Translation pairs: (src_ids, trg_ids, trg_next_ids). Real loader
    reads PADDLE_TPU_DATA_HOME/wmt14/wmt14.tgz (reference wmt14.py layout)."""
    VOCAB = 30000
    SEQ = 32

    def __init__(self, mode='train', dict_size=30000, **kwargs):
        loaded = self._load_real(mode, dict_size, **kwargs)
        if loaded is not None:
            self.pairs, self.src_dict, self.trg_dict = loaded
            self.synthetic = False
            return
        # synthetic ids must respect the requested dict size, or a model
        # sized to it would gather out of bounds
        self.VOCAB = min(type(self).VOCAB, dict_size)
        super().__init__(mode, **kwargs)

    def _load_real(self, mode, dict_size, **kwargs):
        from . import real
        return real.load_wmt14(mode, dict_size)

    def __getitem__(self, idx):
        if not self.synthetic:
            return self.pairs[idx]
        src = self.docs[idx]
        trg = np.roll(src, 1)
        return src, trg, np.roll(trg, -1)

    def __len__(self):
        return len(self.pairs) if not self.synthetic else len(self.docs)


class WMT16(WMT14):
    """Multi30k en-de. Real loader reads
    PADDLE_TPU_DATA_HOME/wmt16/wmt16.tar.gz (reference wmt16.py layout)."""

    def __init__(self, mode='train', src_dict_size=10000,
                 trg_dict_size=10000, src_lang='en', **kwargs):
        self._cfg = (src_dict_size, trg_dict_size, src_lang)
        super().__init__(mode, dict_size=min(src_dict_size, trg_dict_size),
                         **kwargs)

    def _load_real(self, mode, dict_size, **kwargs):
        from . import real
        src_size, trg_size, src_lang = self._cfg
        return real.load_wmt16(mode, src_size, trg_size, src_lang)


class Conll05st(_SyntheticSeqDataset):
    """SRL. Real loader (PADDLE_TPU_DATA_HOME/conll05/) yields the
    reference's 9-slot samples (words, 5 ctx windows, predicate, mark,
    labels); synthetic fallback keeps the 3-tuple shape."""
    VOCAB = 44068
    SEQ = 64
    NUM_CLASSES = 67

    def __init__(self, mode='train', **kwargs):
        from . import real
        loaded = real.load_conll05()
        if loaded is not None:
            self.samples = loaded
            self.synthetic = False
            return
        super().__init__(mode, **kwargs)

    def __getitem__(self, idx):
        if not self.synthetic:
            return self.samples[idx]
        words = self.docs[idx]
        labels = (words % self.NUM_CLASSES).astype(np.int64)
        pred = words[:1]
        return words, pred, labels

    def __len__(self):
        return len(self.samples) if not self.synthetic else len(self.docs)


class MQ2007(Dataset):
    """LETOR MQ2007 learning-to-rank. Real loader reads
    PADDLE_TPU_DATA_HOME/mq2007/Querylevelnorm.txt; synthetic fallback
    generates query groups with a linear-in-features relevance rule.
    mode: pointwise | pairwise | listwise (reference mq2007.py gens)."""

    def __init__(self, mode='pointwise', **kwargs):
        from . import real
        loaded = real.load_mq2007(mode)
        if loaded is not None:
            self.samples = loaded
            self.synthetic = False
            return
        from .real import mq2007_samples
        rng = np.random.RandomState(11)
        w = rng.randn(46).astype(np.float32)
        groups = []
        for qid in range(64):
            n = rng.randint(4, 12)
            feats = rng.rand(n, 46).astype(np.float32)
            rel = np.clip((feats @ w / 4 + rng.randn(n) * 0.2) + 1, 0, 2) \
                .astype(np.int64)
            groups.append(list(zip(rel, feats)))
        self.samples = mq2007_samples(groups, mode)
        self.synthetic = True

    def __getitem__(self, idx):
        return self.samples[idx]

    def __len__(self):
        return len(self.samples)


class Sentiment(Dataset):
    """NLTK movie_reviews polarity. Real loader reads
    PADDLE_TPU_DATA_HOME/sentiment/movie_reviews/{pos,neg}/*.txt;
    label 0 = pos, 1 = neg (reference sentiment.py)."""
    VOCAB = 4000

    def __init__(self, mode='train', **kwargs):
        from . import real
        loaded = real.load_sentiment(mode)
        if loaded is not None:
            self.docs, self.labels, self.word_idx = loaded
            self.synthetic = False
            return
        rng = np.random.RandomState(13 if mode == 'train' else 14)
        n = 1024 if mode == 'train' else 128
        self.labels = rng.randint(0, 2, n).astype(np.int64)
        # class-dependent token distribution so models can learn
        self.docs = [rng.randint(lab * 100, self.VOCAB - (1 - lab) * 100,
                                 size=rng.randint(20, 120)).astype(np.int64)
                     for lab in self.labels]
        self.synthetic = True

    def __getitem__(self, idx):
        return self.docs[idx], self.labels[idx]

    def __len__(self):
        return len(self.docs)
