"""ERNIE 1.0 pretraining path.

ERNIE 1.0 (Baidu) is architecturally a BERT encoder; what distinguishes it is
the *knowledge masking* pretraining strategy: instead of masking independent
wordpieces, whole words and multi-word phrases/entities are masked as units,
so the model must recover them from context rather than from their own
subword fragments. Parity target: the masking stage of ERNIE's pretraining
data pipeline (reference repo's batching.py knowledge-masking); the encoder
itself reuses ``BertModel`` (same call stack as
/root/reference/python/paddle/fluid/contrib tests exercise for BERT).

TPU-first notes: the generator emits fixed-width ``(masked_positions,
masked_labels)`` of ``max_predictions`` per sample (padded with -1 labels so
the MLM loss's ignore_index drops them) — static shapes for XLA.
"""
import numpy as np

from .bert import (BertConfig, BertModel, BertForPretraining,
                   BertPretrainingHeads)

__all__ = ['ErnieModel', 'ErnieForPretraining', 'ernie_knowledge_mask',
           'ernie_mask_batch', 'ErnieConfig']

ErnieConfig = BertConfig


class ErnieModel(BertModel):
    """ERNIE 1.0 encoder — shares BERT's architecture; the ERNIE-specific
    pretraining masking lives in :func:`ernie_knowledge_mask` /
    :class:`ErnieForPretraining`."""


class ErnieForPretraining(BertForPretraining):
    """MLM(+NSP) pretraining over knowledge-masked batches.

    Use :func:`ernie_knowledge_mask` to build ``(input_ids,
    masked_positions, masked_labels)`` and feed them exactly like the BERT
    pretraining path — the heads/loss are shared, the masking unit is not.
    """


def ernie_knowledge_mask(token_ids, word_boundaries, vocab_size,
                         max_predictions=20, mask_token_id=103,
                         masked_lm_prob=0.15, phrase_spans=None,
                         pad_token_id=0, rng=None):
    """Knowledge masking for one tokenized sequence.

    Args:
        token_ids: 1-D int array/list of wordpiece ids (already padded or not).
        word_boundaries: 1-D array, same length, giving the *word index* of
            every token (continuation wordpieces share their word's index;
            padding should carry -1). Masking decisions are made per word, and
            a selected word is masked in full — never a fragment.
        vocab_size: for the 10% random-replacement branch.
        max_predictions: static width K of the emitted position/label arrays.
        phrase_spans: optional list of ``(word_lo, word_hi)`` half-open word
            ranges marking entities/phrases; a selected phrase is masked as a
            single unit (ERNIE's phrase/entity-level masking).
        rng: ``numpy.random.Generator`` (defaults to a fresh one).

    Returns:
        ``(input_ids, masked_positions, masked_labels)`` numpy arrays; the
        last two have length ``max_predictions``, padded with position 0 and
        label -1 (the MLM loss ignore_index).
    """
    rng = rng or np.random.default_rng()
    if mask_token_id >= vocab_size:
        raise ValueError(
            "mask_token_id %d is outside vocab_size %d — pass the [MASK] id "
            "of your vocab" % (mask_token_id, vocab_size))
    ids = np.asarray(token_ids, dtype=np.int64).copy()
    words = np.asarray(word_boundaries, dtype=np.int64)
    if ids.shape != words.shape:
        raise ValueError("token_ids and word_boundaries length mismatch: "
                         "%s vs %s" % (ids.shape, words.shape))

    # group tokens into maskable units: phrases swallow their member words;
    # pad tokens are unmaskable whether marked by word index -1 or by id
    maskable = (words >= 0) & (ids != pad_token_id)
    valid_words = sorted(set(int(w) for w in words[maskable]))
    in_phrase = set()
    units = []   # each unit: list of word indices masked together
    for lo, hi in (phrase_spans or []):
        span = [w for w in valid_words if lo <= w < hi]
        if span:
            units.append(span)
            in_phrase.update(span)
    units.extend([[w] for w in valid_words if w not in in_phrase])

    target = max(1, int(round(masked_lm_prob * len(valid_words))))
    order = rng.permutation(len(units))
    positions, labels = [], []
    covered = 0
    for ui in order:
        if covered >= target or len(positions) >= max_predictions:
            break
        unit_words = units[ui]
        tok_pos = np.flatnonzero(np.isin(words, unit_words) & maskable)
        if len(positions) + len(tok_pos) > max_predictions:
            continue
        covered += len(unit_words)
        # 80/10/10 decided once per unit so a word is replaced coherently
        roll = rng.random()
        for p in tok_pos:
            positions.append(int(p))
            labels.append(int(ids[p]))
            if roll < 0.8:
                ids[p] = mask_token_id
            elif roll < 0.9:
                ids[p] = int(rng.integers(0, vocab_size))
            # else: keep original token

    k = max_predictions
    pos_out = np.zeros(k, dtype=np.int64)
    lab_out = np.full(k, -1, dtype=np.int64)
    pos_out[:len(positions)] = positions
    lab_out[:len(labels)] = labels
    return ids, pos_out, lab_out


def ernie_mask_batch(batch_token_ids, batch_word_boundaries, vocab_size,
                     max_predictions=20, phrase_spans=None, seed=None,
                     **kwargs):
    """Vectorized-batch convenience over :func:`ernie_knowledge_mask`;
    returns stacked ``(input_ids, masked_positions, masked_labels)``."""
    rng = np.random.default_rng(seed)
    outs = [ernie_knowledge_mask(
        t, b, vocab_size, max_predictions=max_predictions,
        phrase_spans=(phrase_spans[i] if phrase_spans else None),
        rng=rng, **kwargs)
        for i, (t, b) in enumerate(zip(batch_token_ids,
                                       batch_word_boundaries))]
    return tuple(np.stack(x) for x in zip(*outs))
