"""Autoregressive generation utilities: greedy / top-k / top-p sampling.

Parity role: the reference's sampling story lives in beam_search ops and
contrib samplers (/root/reference/python/paddle/fluid/layers/rnn.py:3040);
modern top-k/top-p is capability parity for the GPT zoo. TPU-first: pure
jnp filters usable inside a jit-compiled decode step (static shapes, no
data-dependent python control flow).
"""
import jax
import jax.numpy as jnp

__all__ = ['top_k_logits', 'top_p_logits', 'sample_token', 'greedy_token']

_NEG = -1e9


def top_k_logits(logits, k):
    """Mask all but the k largest logits to -inf. logits: (..., V)."""
    if k is None or k <= 0:
        return logits
    k = min(int(k), logits.shape[-1])
    kth = jax.lax.top_k(logits, k)[0][..., -1:]
    return jnp.where(logits < kth, jnp.full_like(logits, _NEG), logits)


def top_p_logits(logits, p):
    """Nucleus filtering: keep the smallest prefix of the sorted vocab whose
    cumulative probability reaches p. logits: (..., V)."""
    if p is None or p >= 1.0:
        return logits
    sorted_logits = jnp.sort(logits, axis=-1)[..., ::-1]
    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    # keep tokens whose cumulative mass *before* them is < p (always >= 1 kept)
    keep = cum - probs < p
    cutoff_idx = jnp.sum(keep, axis=-1, keepdims=True) - 1
    cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx, axis=-1)
    return jnp.where(logits < cutoff, jnp.full_like(logits, _NEG), logits)


def greedy_token(logits):
    return jnp.argmax(logits, axis=-1).astype(jnp.int32)


def sample_token(logits, key, temperature=1.0, top_k=None, top_p=None):
    """Sample one token per row from filtered logits. logits: (B, V)."""
    logits = logits.astype(jnp.float32)
    if temperature is not None and temperature != 1.0:
        logits = logits / jnp.maximum(temperature, 1e-6)
    logits = top_k_logits(logits, top_k)
    logits = top_p_logits(logits, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
