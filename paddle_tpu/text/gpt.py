"""GPT-style decoder LM — long-context flagship (ring attention capable).

No direct reference equivalent at v1.8 (the reference's LM story is RNN/ERNIE);
included for capability parity with modern long-sequence training: causal
flash attention (pallas) single-chip, ring attention over the 'seq' mesh axis
multi-chip.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..tensor.creation import arange

__all__ = ['GPTConfig', 'GPTModel', 'gpt_small']


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, max_seq_len=1024, dropout=0.1,
                 use_ring_attention=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.use_ring_attention = use_ring_attention


class CausalSelfAttention(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.num_heads = config.num_heads
        self.hidden = config.hidden_size
        self.use_ring = config.use_ring_attention
        self.qkv = nn.Linear(config.hidden_size, 3 * config.hidden_size)
        self.proj = nn.Linear(config.hidden_size, config.hidden_size)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x, cache=None, pos=None):
        B, L, E = x.shape
        qkv = self.qkv(x).reshape([B, L, 3, self.num_heads, E // self.num_heads])
        from ..tensor.manipulation import unstack
        q, k, v = unstack(qkv, axis=2)
        if cache is not None:
            out, cache = self._cached_attention(q, k, v, cache, pos)
            out = out.reshape([B, L, E])
            return self.dropout(self.proj(out)), cache
        if self.use_ring:
            from ..distributed.ring_attention import ring_attention
            from ..core.tensor import apply_op
            # (B, L, H, D) -> (B, H, L, D)
            def fn(qq, kk, vv):
                qq, kk, vv = (jnp.swapaxes(t, 1, 2) for t in (qq, kk, vv))
                out = ring_attention(qq, kk, vv, causal=True)
                return jnp.swapaxes(out, 1, 2)
            out = apply_op(fn, (q, k, v))
        else:
            out = nn.functional.scaled_dot_product_attention(
                q, k, v, is_causal=True, training=self.training)
        out = out.reshape([B, L, E])
        return self.dropout(self.proj(out))

    def _cached_attention(self, q, k, v, cache, pos):
        """Fixed-size KV-cache attention (jit-safe incremental decode).

        cache = (k_buf, v_buf) each (B, T, H, D) preallocated to the full
        target length; q/k/v are the current chunk (B, S, H, D) with S the
        prompt length at prefill and 1 per decode step. ``pos`` is the write
        offset (scalar). The write is a lax.dynamic_update_slice and the
        causal mask is computed against absolute positions, so shapes stay
        static across the whole decode loop.
        """
        from ..core.tensor import apply_op
        k_buf, v_buf = cache
        scale = 1.0 / math.sqrt(q.shape[-1])

        def fn(qv, kv, vv, kb, vb, p):
            p = p.astype(jnp.int32)
            kb = jax.lax.dynamic_update_slice(
                kb, kv.astype(kb.dtype), (0, p, 0, 0))
            vb = jax.lax.dynamic_update_slice(
                vb, vv.astype(vb.dtype), (0, p, 0, 0))
            qh = jnp.swapaxes(qv, 1, 2)          # (B, H, S, D)
            kh = jnp.swapaxes(kb, 1, 2)          # (B, H, T, D)
            vh = jnp.swapaxes(vb, 1, 2)
            scores = jnp.einsum('bhsd,bhtd->bhst', qh, kh) * scale
            S, T = scores.shape[2], scores.shape[3]
            qpos = p + jnp.arange(S)
            mask = jnp.arange(T)[None, :] <= qpos[:, None]
            scores = jnp.where(mask[None, None], scores, -1e9)
            attn = jax.nn.softmax(scores.astype(jnp.float32), axis=-1)
            out = jnp.einsum('bhst,bhtd->bhsd', attn.astype(vh.dtype), vh)
            return jnp.swapaxes(out, 1, 2), kb, vb

        out, k_buf, v_buf = apply_op(fn, (q, k, v, k_buf, v_buf, pos),
                                     n_outputs=3)
        return out, (k_buf, v_buf)


class GPTBlock(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.ln1 = nn.LayerNorm(config.hidden_size)
        self.attn = CausalSelfAttention(config)
        self.ln2 = nn.LayerNorm(config.hidden_size)
        self.mlp = nn.Sequential(
            nn.Linear(config.hidden_size, 4 * config.hidden_size),
            nn.GELU(),
            nn.Linear(4 * config.hidden_size, config.hidden_size),
            nn.Dropout(config.dropout))

    def forward(self, x, cache=None, pos=None):
        if cache is not None:
            a, cache = self.attn(self.ln1(x), cache, pos)
            x = x + a
            x = x + self.mlp(self.ln2(x))
            return x, cache
        x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        return x


class GPTModel(nn.Layer):
    def __init__(self, config=None, **kwargs):
        super().__init__()
        config = config or GPTConfig(**kwargs)
        self.config = config
        attr = nn.ParamAttr(initializer=nn.initializer.Normal(0., 0.02))
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size,
                                weight_attr=attr)
        self.wpe = nn.Embedding(config.max_seq_len, config.hidden_size,
                                weight_attr=attr)
        self.drop = nn.Dropout(config.dropout)
        self.blocks = nn.LayerList([GPTBlock(config)
                                    for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size)

    def forward(self, input_ids, caches=None, pos=None):
        B, L = input_ids.shape
        if caches is None:
            p = arange(0, L, dtype='int64').unsqueeze(0)
            x = self.drop(self.wte(input_ids) + self.wpe(p))
            for blk in self.blocks:
                x = blk(x)
            x = self.ln_f(x)
            return x.matmul(self.wte.weight, transpose_y=True)
        # incremental decode: absolute positions pos..pos+L-1
        from ..core.tensor import apply_op
        pos_ids = apply_op(
            lambda pp: (pp.astype(jnp.int32) + jnp.arange(L))[None, :],
            (pos,), differentiable=False)
        x = self.drop(self.wte(input_ids) + self.wpe(pos_ids))
        new_caches = []
        for blk, cache in zip(self.blocks, caches):
            x, cache = blk(x, cache, pos)
            new_caches.append(cache)
        x = self.ln_f(x)
        return x.matmul(self.wte.weight, transpose_y=True), new_caches

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        return nn.functional.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]))

    def init_caches(self, batch_size, max_len, dtype=jnp.float32):
        """Preallocate fixed-size KV buffers: per layer (k, v) (B, T, H, D)."""
        H = self.config.num_heads
        D = self.config.hidden_size // H
        shape = (batch_size, max_len, H, D)
        return [(Tensor(jnp.zeros(shape, dtype)), Tensor(jnp.zeros(shape, dtype)))
                for _ in range(self.config.num_layers)]

    def generate(self, input_ids, max_new_tokens=32, do_sample=False,
                 temperature=1.0, top_k=None, top_p=None, eos_token_id=None,
                 seed=None):
        """Autoregressive generation with a fixed-size KV cache.

        The entire decode (prefill + ``lax.while_loop`` over single-token
        steps) compiles to ONE XLA computation, cached per
        (batch, prompt_len, max_new_tokens, sampling config). Finished rows
        (hit ``eos_token_id``) emit eos and the loop exits early when every
        row is done. Parity role: reference beam_search/sampling decode
        (fluid/layers/rnn.py:1779 GreedyEmbeddingHelper et al).
        """
        from ..core import rng
        from ..core import autograd

        input_ids = input_ids if isinstance(input_ids, Tensor) else Tensor(
            jnp.asarray(np.asarray(input_ids), jnp.int32))
        B, L0 = input_ids.shape
        T = L0 + int(max_new_tokens)
        if T > self.config.max_seq_len:
            raise ValueError(
                f"generate length {T} exceeds max_seq_len "
                f"{self.config.max_seq_len}")
        was_training = self.training
        self.eval()
        try:
            key = rng._make_key(seed) if seed is not None else rng.next_key()
            eos = -1 if eos_token_id is None else int(eos_token_id)

            gen_fn = self._generate_fn(L0, int(max_new_tokens), bool(do_sample),
                                       1.0 if temperature is None
                                       else float(temperature),
                                       None if top_k is None else int(top_k),
                                       None if top_p is None else float(top_p),
                                       eos)
            from ..nn.layer_base import state_values
            with autograd.no_grad():
                out = gen_fn(state_values(self), input_ids._value, key)
            return Tensor(out)
        finally:
            if was_training:
                self.train()

    def _generate_fn(self, prompt_len, max_new, do_sample, temperature,
                     top_k, top_p, eos):
        """Build (and cache) the jitted whole-decode function."""
        sig = (prompt_len, max_new, do_sample, temperature, top_k, top_p, eos)
        cache = getattr(self, '_gen_cache', None)
        if cache is None:
            cache = self._gen_cache = {}
        fn = cache.get(sig)
        if fn is not None:
            return fn

        from .generation import sample_token, greedy_token
        from ..nn.layer_base import functional_call

        H = self.config.num_heads
        D = self.config.hidden_size // H
        n_layers = self.config.num_layers

        def decode(state, prompt, key):
            def model_step(ids_val, caches_vals, pos_val):
                """Run the eager layer graph on traced values (params come
                from ``state`` so they are jit inputs, not baked constants)."""
                caches_t = [(Tensor(k), Tensor(v)) for k, v in caches_vals]
                (logits_t, new_caches_t), _ = functional_call(
                    self, state, Tensor(ids_val), caches_t, Tensor(pos_val))
                return logits_t._value, [(k._value, v._value)
                                         for k, v in new_caches_t]

            B = prompt.shape[0]
            T = prompt_len + max_new
            # KV buffers built inside the traced fn: XLA materialises them
            # in-place, no host alloc or input copy per call
            cache_vals = [(jnp.zeros((B, T, H, D), jnp.float32),
                           jnp.zeros((B, T, H, D), jnp.float32))
                          for _ in range(n_layers)]
            logits, cache_vals = model_step(
                prompt, cache_vals, jnp.asarray(0, jnp.int32))
            last = logits[:, -1, :]

            out_buf = jnp.zeros((B, T), jnp.int32)
            out_buf = jax.lax.dynamic_update_slice(out_buf, prompt, (0, 0))
            finished0 = jnp.zeros((B,), jnp.bool_)

            def pick(lg, kk, step):
                if do_sample:
                    return sample_token(lg, jax.random.fold_in(kk, step),
                                        temperature, top_k, top_p)
                return greedy_token(lg)

            def cond(carry):
                i, _, _, _, fin = carry
                return (i < max_new) & ~jnp.all(fin)

            def body(carry):
                i, buf, cv, lg, fin = carry
                tok = pick(lg, key, i)
                tok = jnp.where(fin, jnp.full_like(tok, max(eos, 0)), tok)
                fin = fin | (tok == eos)
                pos = prompt_len + i
                buf = jax.lax.dynamic_update_slice(
                    buf, tok[:, None], (0, pos))
                # skip the transformer forward when no further token will be
                # sampled (last step / all rows finished)
                new_logits, cv = jax.lax.cond(
                    (i + 1 < max_new) & ~jnp.all(fin),
                    lambda c: model_step(tok[:, None], c, pos),
                    lambda c: (lg[:, None, :], c), cv)
                return (i + 1, buf, cv, new_logits[:, -1, :], fin)

            carry = (jnp.asarray(0, jnp.int32), out_buf, cache_vals, last,
                     finished0)
            _, out_buf, _, _, _ = jax.lax.while_loop(cond, body, carry)
            if eos >= 0:
                # pad everything after each row's first eos (early loop exit
                # leaves those slots unwritten)
                gen = jnp.arange(T)[None, :] >= prompt_len
                is_eos = (out_buf == eos) & gen
                after = (jnp.cumsum(is_eos.astype(jnp.int32), axis=1)
                         - is_eos.astype(jnp.int32)) > 0
                out_buf = jnp.where(after & gen, eos, out_buf)
            return out_buf

        jitted = jax.jit(decode)
        cache[sig] = jitted
        return jitted


def gpt_small(**kwargs):
    return GPTModel(GPTConfig(hidden_size=768, num_layers=12, num_heads=12,
                              **kwargs))
