"""GPT-style decoder LM — long-context flagship (ring attention capable).

No direct reference equivalent at v1.8 (the reference's LM story is RNN/ERNIE);
included for capability parity with modern long-sequence training: causal
flash attention (pallas) single-chip, ring attention over the 'seq' mesh axis
multi-chip.
"""
import math

import numpy as np
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..tensor.creation import arange

__all__ = ['GPTConfig', 'GPTModel', 'gpt_small']


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768, num_layers=12,
                 num_heads=12, max_seq_len=1024, dropout=0.1,
                 use_ring_attention=False):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.num_heads = num_heads
        self.max_seq_len = max_seq_len
        self.dropout = dropout
        self.use_ring_attention = use_ring_attention


class CausalSelfAttention(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.num_heads = config.num_heads
        self.hidden = config.hidden_size
        self.use_ring = config.use_ring_attention
        self.qkv = nn.Linear(config.hidden_size, 3 * config.hidden_size)
        self.proj = nn.Linear(config.hidden_size, config.hidden_size)
        self.dropout = nn.Dropout(config.dropout)

    def forward(self, x):
        B, L, E = x.shape
        qkv = self.qkv(x).reshape([B, L, 3, self.num_heads, E // self.num_heads])
        from ..tensor.manipulation import unstack
        q, k, v = unstack(qkv, axis=2)
        if self.use_ring:
            from ..distributed.ring_attention import ring_attention
            from ..core.tensor import apply_op
            # (B, L, H, D) -> (B, H, L, D)
            def fn(qq, kk, vv):
                qq, kk, vv = (jnp.swapaxes(t, 1, 2) for t in (qq, kk, vv))
                out = ring_attention(qq, kk, vv, causal=True)
                return jnp.swapaxes(out, 1, 2)
            out = apply_op(fn, (q, k, v))
        else:
            out = nn.functional.scaled_dot_product_attention(
                q, k, v, is_causal=True, training=self.training)
        out = out.reshape([B, L, E])
        return self.dropout(self.proj(out))


class GPTBlock(nn.Layer):
    def __init__(self, config):
        super().__init__()
        self.ln1 = nn.LayerNorm(config.hidden_size)
        self.attn = CausalSelfAttention(config)
        self.ln2 = nn.LayerNorm(config.hidden_size)
        self.mlp = nn.Sequential(
            nn.Linear(config.hidden_size, 4 * config.hidden_size),
            nn.GELU(),
            nn.Linear(4 * config.hidden_size, config.hidden_size),
            nn.Dropout(config.dropout))

    def forward(self, x):
        x = x + self.attn(self.ln1(x))
        x = x + self.mlp(self.ln2(x))
        return x


class GPTModel(nn.Layer):
    def __init__(self, config=None, **kwargs):
        super().__init__()
        config = config or GPTConfig(**kwargs)
        self.config = config
        attr = nn.ParamAttr(initializer=nn.initializer.Normal(0., 0.02))
        self.wte = nn.Embedding(config.vocab_size, config.hidden_size,
                                weight_attr=attr)
        self.wpe = nn.Embedding(config.max_seq_len, config.hidden_size,
                                weight_attr=attr)
        self.drop = nn.Dropout(config.dropout)
        self.blocks = nn.LayerList([GPTBlock(config)
                                    for _ in range(config.num_layers)])
        self.ln_f = nn.LayerNorm(config.hidden_size)

    def forward(self, input_ids):
        B, L = input_ids.shape
        pos = arange(0, L, dtype='int64').unsqueeze(0)
        x = self.drop(self.wte(input_ids) + self.wpe(pos))
        for blk in self.blocks:
            x = blk(x)
        x = self.ln_f(x)
        # tied LM head
        logits = x.matmul(self.wte.weight, transpose_y=True)
        return logits

    def loss(self, input_ids, labels):
        logits = self(input_ids)
        return nn.functional.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]), labels.reshape([-1]))


def gpt_small(**kwargs):
    return GPTModel(GPTConfig(hidden_size=768, num_layers=12, num_heads=12,
                              **kwargs))
