"""paddle.text layer zoo: the reusable seq-modeling layer library.

Parity: /root/reference/python/paddle/text/text.py (RNNCell:67,
BasicLSTMCell:186, BasicGRUCell:321, RNN:476, StackedRNNCell:639,
StackedLSTMCell:734, LSTM:886, BidirectionalRNN:1006,
BidirectionalLSTM:1144, StackedGRUCell:1337, GRU:1470,
BidirectionalGRU:1581, DynamicDecode:1762, Conv1dPoolLayer:1980,
CNNEncoder:2109, TransformerCell:2252, TransformerBeamSearchDecoder:2421,
PrePostProcessLayer:2609, MultiHeadAttention:2687, FFN:2900,
TransformerEncoderLayer:2957, TransformerEncoder:3061,
TransformerDecoderLayer:3170, TransformerDecoder:3314, LinearChainCRF:3506,
CRFDecoding:3655, SequenceTagging:3832).

TPU-first notes: recurrences lower through the nn cell machinery
(lax.scan); CRF layers wrap the log-space scan + Viterbi functionals. The
transformer incremental caches here GROW by concat along the time dim
(`var_dim_in_state`) — faithful to the reference API and fine in eager
decode loops, but not traceable under jit (XLA needs static shapes); for
compiled generation use the preallocated-KV-cache path (text.gpt
GPT.generate / nn.decode), which is the production TPU design.
"""
import numpy as np
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..nn.layer_base import Layer
from ..nn import Linear, Embedding, LayerList, Dropout, LayerNorm
from ..nn import functional as F
from ..nn.layer.rnn import LSTMCell as _NNLSTMCell, GRUCell as _NNGRUCell
from ..nn.decode import (BeamSearchDecoder, dynamic_decode)
from ..tensor.manipulation import concat, stack, transpose

__all__ = [
    'RNNCell', 'BasicLSTMCell', 'BasicGRUCell', 'RNN', 'BidirectionalRNN',
    'StackedRNNCell', 'StackedLSTMCell', 'LSTM', 'BidirectionalLSTM',
    'StackedGRUCell', 'GRU', 'BidirectionalGRU', 'DynamicDecode',
    'BeamSearchDecoder', 'Conv1dPoolLayer', 'CNNEncoder',
    'MultiHeadAttention', 'FFN', 'TransformerEncoderLayer',
    'TransformerEncoder', 'TransformerDecoderLayer', 'TransformerDecoder',
    'TransformerCell', 'TransformerBeamSearchDecoder', 'LinearChainCRF',
    'CRFDecoding', 'SequenceTagging',
]


class RNNCell(Layer):
    """Base cell: forward(inputs, states) -> (outputs, new_states)
    (text.py:67)."""

    def get_initial_states(self, batch_ref, shape=None, dtype='float32',
                           init_value=0.0, batch_dim_idx=0):
        from ..tensor.creation import full
        shapes = self.state_shape if shape is None else shape
        B = batch_ref.shape[batch_dim_idx]

        def build(s):
            dims = [B] + [int(d) for d in
                          (s if isinstance(s, (list, tuple)) else [s])]
            return full(dims, init_value, dtype=dtype)

        if isinstance(shapes, (list, tuple)) and shapes and \
                isinstance(shapes[0], (list, tuple)):
            return [build(s) for s in shapes]
        return build(shapes)

    @property
    def state_shape(self):
        raise NotImplementedError


class BasicLSTMCell(RNNCell):
    """Single LSTM cell with forget-gate bias (text.py:186)."""

    def __init__(self, input_size, hidden_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 forget_bias=1.0, dtype='float32'):
        super().__init__()
        self.hidden_size = hidden_size
        self._cell = _NNLSTMCell(input_size, hidden_size,
                                 weight_ih_attr=param_attr,
                                 weight_hh_attr=param_attr,
                                 bias_ih_attr=bias_attr,
                                 bias_hh_attr=bias_attr)
        if forget_bias and self._cell.bias_ih is not None:
            b = self._cell.bias_ih._value
            h = hidden_size
            self._cell.bias_ih._inplace_value(
                b.at[h:2 * h].add(jnp.asarray(forget_bias, b.dtype)))

    def forward(self, inputs, states):
        h, c = states
        out, (nh, nc) = self._cell(inputs, (h, c))
        return out, [nh, nc]

    @property
    def state_shape(self):
        return [[self.hidden_size], [self.hidden_size]]


class BasicGRUCell(RNNCell):
    """Single GRU cell (text.py:321)."""

    def __init__(self, input_size, hidden_size, param_attr=None,
                 bias_attr=None, gate_activation=None, activation=None,
                 dtype='float32'):
        super().__init__()
        self.hidden_size = hidden_size
        self._cell = _NNGRUCell(input_size, hidden_size,
                                weight_ih_attr=param_attr,
                                weight_hh_attr=param_attr,
                                bias_ih_attr=bias_attr,
                                bias_hh_attr=bias_attr)

    def forward(self, inputs, states):
        out, nh = self._cell(inputs, states)
        return out, nh

    @property
    def state_shape(self):
        return [self.hidden_size]


class RNN(Layer):
    """Drive a cell over the time dim (text.py:476)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        from ..fluid.rnn_tail import rnn as _rnn_drive
        cell = self.cell
        # adapt Layer-style cells to the fluid driver's call protocol
        class _Adapter:
            def call(self, x, s):
                return cell(x, s)

            def get_initial_states(self, x):
                return cell.get_initial_states(x)
        outs, states = _rnn_drive(_Adapter(), inputs, initial_states,
                                  sequence_length,
                                  time_major=self.time_major,
                                  is_reverse=self.is_reverse, **kwargs)
        return outs, states


class StackedRNNCell(RNNCell):
    """Stack cells into one multi-layer cell (text.py:639)."""

    def __init__(self, cells):
        super().__init__()
        self.cells = LayerList(cells)

    def forward(self, inputs, states, **kwargs):
        new_states = []
        out = inputs
        for cell, s in zip(self.cells, states):
            out, ns = cell(out, s)
            new_states.append(ns)
        return out, new_states

    def get_initial_states(self, batch_ref, **kw):
        return [c.get_initial_states(batch_ref, **kw) for c in self.cells]

    @staticmethod
    def stack_param_attr(param_attr, n):
        return [param_attr] * n


class StackedLSTMCell(StackedRNNCell):
    """num_layers LSTM cells with inter-layer dropout (text.py:734)."""

    def __init__(self, input_size, hidden_size, gate_activation=None,
                 activation=None, forget_bias=1.0, num_layers=1,
                 dropout=0.0, param_attr=None, bias_attr=None,
                 dtype="float32"):
        cells = []
        for i in range(num_layers):
            cells.append(BasicLSTMCell(
                input_size if i == 0 else hidden_size, hidden_size,
                param_attr, bias_attr, gate_activation, activation,
                forget_bias, dtype))
        super().__init__(cells)
        self.dropout = dropout
        self.num_layers = num_layers

    def forward(self, inputs, states):
        new_states = []
        out = inputs
        for i, (cell, s) in enumerate(zip(self.cells, states)):
            out, ns = cell(out, s)
            if self.dropout and i < self.num_layers - 1 and self.training:
                out = F.dropout(out, p=self.dropout)
            new_states.append(ns)
        return out, new_states


class StackedGRUCell(StackedRNNCell):
    """num_layers GRU cells with inter-layer dropout (text.py:1337)."""

    def __init__(self, input_size, hidden_size, gate_activation=None,
                 activation=None, num_layers=1, dropout=0.0,
                 param_attr=None, bias_attr=None, dtype="float32"):
        cells = []
        for i in range(num_layers):
            cells.append(BasicGRUCell(
                input_size if i == 0 else hidden_size, hidden_size,
                param_attr, bias_attr, gate_activation, activation, dtype))
        super().__init__(cells)
        self.dropout = dropout
        self.num_layers = num_layers

    def forward(self, inputs, states):
        new_states = []
        out = inputs
        for i, (cell, s) in enumerate(zip(self.cells, states)):
            out, ns = cell(out, s)
            if self.dropout and i < self.num_layers - 1 and self.training:
                out = F.dropout(out, p=self.dropout)
            new_states.append(ns)
        return out, new_states


class LSTM(Layer):
    """Multi-layer LSTM over sequences (text.py:886)."""

    def __init__(self, input_size, hidden_size, gate_activation=None,
                 activation=None, forget_bias=1.0, num_layers=1,
                 dropout=0.0, is_reverse=False, time_major=False,
                 param_attr=None, bias_attr=None, dtype='float32'):
        super().__init__()
        self.cell = StackedLSTMCell(input_size, hidden_size,
                                    gate_activation, activation,
                                    forget_bias, num_layers, dropout,
                                    param_attr, bias_attr, dtype)
        self.rnn = RNN(self.cell, is_reverse, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        return self.rnn(inputs, initial_states, sequence_length)


class GRU(Layer):
    """Multi-layer GRU over sequences (text.py:1470)."""

    def __init__(self, input_size, hidden_size, gate_activation=None,
                 activation=None, num_layers=1, dropout=0.0,
                 is_reverse=False, time_major=False, param_attr=None,
                 bias_attr=None, dtype='float32'):
        super().__init__()
        self.cell = StackedGRUCell(input_size, hidden_size,
                                   gate_activation, activation, num_layers,
                                   dropout, param_attr, bias_attr, dtype)
        self.rnn = RNN(self.cell, is_reverse, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        return self.rnn(inputs, initial_states, sequence_length)


class BidirectionalRNN(Layer):
    """Forward + backward cells, outputs merged (text.py:1006)."""

    def __init__(self, cell_fw, cell_bw, merge_mode='concat',
                 time_major=False, cell_cls=None, **kwargs):
        super().__init__()
        self.cell_fw = cell_fw
        self.cell_bw = cell_bw
        self.merge_mode = merge_mode
        self.rnn_fw = RNN(cell_fw, is_reverse=False, time_major=time_major)
        self.rnn_bw = RNN(cell_bw, is_reverse=True, time_major=time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None,
                **kwargs):
        if initial_states is None:
            init_fw = init_bw = None
        elif isinstance(initial_states, (list, tuple)) and \
                len(initial_states) == 2:
            init_fw, init_bw = initial_states
        else:
            init_fw = init_bw = initial_states
        out_fw, st_fw = self.rnn_fw(inputs, init_fw, sequence_length)
        out_bw, st_bw = self.rnn_bw(inputs, init_bw, sequence_length)
        if self.merge_mode == 'concat':
            out = concat([out_fw, out_bw], axis=-1)
        elif self.merge_mode == 'sum':
            out = out_fw + out_bw
        elif self.merge_mode == 'ave':
            out = (out_fw + out_bw) * 0.5
        elif self.merge_mode == 'mul':
            out = out_fw * out_bw
        elif self.merge_mode == 'zip':
            out = (out_fw, out_bw)
        else:
            out = (out_fw, out_bw)
        return out, (st_fw, st_bw)


class BidirectionalLSTM(Layer):
    """(text.py:1144). merge_each_layer=False runs one bi-RNN over the
    whole stacked cell; True merges per layer."""

    def __init__(self, input_size, hidden_size, gate_activation=None,
                 activation=None, forget_bias=1.0, num_layers=1,
                 dropout=0.0, merge_mode='concat', merge_each_layer=False,
                 time_major=False, param_attr=None, bias_attr=None,
                 dtype='float32'):
        super().__init__()
        self.merge_each_layer = merge_each_layer
        if not merge_each_layer:
            cf = StackedLSTMCell(input_size, hidden_size, gate_activation,
                                 activation, forget_bias, num_layers,
                                 dropout, param_attr, bias_attr, dtype)
            cb = StackedLSTMCell(input_size, hidden_size, gate_activation,
                                 activation, forget_bias, num_layers,
                                 dropout, param_attr, bias_attr, dtype)
            self.birnn = BidirectionalRNN(cf, cb, merge_mode, time_major)
        else:
            self.layers = LayerList()
            for i in range(num_layers):
                in_sz = input_size if i == 0 else (
                    hidden_size * 2 if merge_mode == 'concat'
                    else hidden_size)
                cf = BasicLSTMCell(in_sz, hidden_size, param_attr,
                                   bias_attr, gate_activation, activation,
                                   forget_bias, dtype)
                cb = BasicLSTMCell(in_sz, hidden_size, param_attr,
                                   bias_attr, gate_activation, activation,
                                   forget_bias, dtype)
                self.layers.append(BidirectionalRNN(cf, cb, merge_mode,
                                                    time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if not self.merge_each_layer:
            return self.birnn(inputs, initial_states, sequence_length)
        out = inputs
        states = []
        for layer in self.layers:
            out, st = layer(out, None, sequence_length)
            states.append(st)
        return out, states


class BidirectionalGRU(Layer):
    """(text.py:1581)."""

    def __init__(self, input_size, hidden_size, gate_activation=None,
                 activation=None, forget_bias=1.0, num_layers=1,
                 dropout=0.0, merge_mode='concat', merge_each_layer=False,
                 time_major=False, param_attr=None, bias_attr=None,
                 dtype='float32'):
        super().__init__()
        self.merge_each_layer = merge_each_layer
        if not merge_each_layer:
            cf = StackedGRUCell(input_size, hidden_size, gate_activation,
                                activation, num_layers, dropout,
                                param_attr, bias_attr, dtype)
            cb = StackedGRUCell(input_size, hidden_size, gate_activation,
                                activation, num_layers, dropout,
                                param_attr, bias_attr, dtype)
            self.birnn = BidirectionalRNN(cf, cb, merge_mode, time_major)
        else:
            self.layers = LayerList()
            for i in range(num_layers):
                in_sz = input_size if i == 0 else (
                    hidden_size * 2 if merge_mode == 'concat'
                    else hidden_size)
                cf = BasicGRUCell(in_sz, hidden_size, param_attr,
                                  bias_attr, gate_activation, activation,
                                  dtype)
                cb = BasicGRUCell(in_sz, hidden_size, param_attr,
                                  bias_attr, gate_activation, activation,
                                  dtype)
                self.layers.append(BidirectionalRNN(cf, cb, merge_mode,
                                                    time_major))

    def forward(self, inputs, initial_states=None, sequence_length=None):
        if not self.merge_each_layer:
            return self.birnn(inputs, initial_states, sequence_length)
        out = inputs
        states = []
        for layer in self.layers:
            out, st = layer(out, None, sequence_length)
            states.append(st)
        return out, states


class DynamicDecode(Layer):
    """Layer wrapper over dynamic_decode (text.py:1762)."""

    def __init__(self, decoder, max_step_num=None, output_time_major=False,
                 impute_finished=False, is_test=False, return_length=False):
        super().__init__()
        self.decoder = decoder
        self.max_step_num = max_step_num
        self.output_time_major = output_time_major
        self.impute_finished = impute_finished
        self.is_test = is_test
        self.return_length = return_length

    def forward(self, inits=None, **kwargs):
        return dynamic_decode(self.decoder, inits,
                              max_step_num=self.max_step_num,
                              output_time_major=self.output_time_major,
                              impute_finished=self.impute_finished,
                              is_test=self.is_test,
                              return_length=self.return_length, **kwargs)


class Conv1dPoolLayer(Layer):
    """conv1d + pool1d block (text.py:1980)."""

    def __init__(self, num_channels, num_filters, filter_size, pool_size,
                 conv_stride=1, pool_stride=1, conv_padding=0,
                 pool_padding=0, act=None, pool_type='max',
                 global_pooling=False, dilation=1, groups=None,
                 ceil_mode=False, exclusive=True, use_cudnn=False,
                 param_attr=None, bias_attr=None):
        super().__init__()
        from .. import nn as _nn
        self.conv = _nn.Conv1D(num_channels, num_filters, filter_size,
                               stride=conv_stride, padding=conv_padding,
                               dilation=dilation, groups=groups or 1,
                               weight_attr=param_attr, bias_attr=bias_attr)
        self.act = act
        self.pool_type = pool_type
        self.pool_size = pool_size
        self.pool_stride = pool_stride
        self.pool_padding = pool_padding
        self.global_pooling = global_pooling
        self.ceil_mode = ceil_mode

    def forward(self, input):
        out = self.conv(input)
        if self.act:
            out = getattr(F, self.act)(out)
        if self.global_pooling:
            return F.global_pool(out, 'avg' if self.pool_type == 'avg'
                                 else 'max', 'NCL')
        fn = F.max_pool1d if self.pool_type == 'max' else F.avg_pool1d
        return fn(out, self.pool_size, self.pool_stride, self.pool_padding,
                  ceil_mode=self.ceil_mode)


class CNNEncoder(Layer):
    """Parallel Conv1dPoolLayers, outputs concatenated on the channel axis
    (text.py:2109)."""

    def __init__(self, num_channels, num_filters, filter_size, pool_size,
                 num_layers=1, conv_stride=1, pool_stride=1,
                 conv_padding=0, pool_padding=0, act=None, pool_type='max',
                 global_pooling=False, use_cudnn=False):
        super().__init__()

        def listify(v):
            return v if isinstance(v, (list, tuple)) else [v] * num_layers
        self.convs = LayerList([
            Conv1dPoolLayer(nc, nf, fs, ps, conv_stride=cs,
                            pool_stride=pst, conv_padding=cp,
                            pool_padding=pp, act=a, pool_type=pt,
                            global_pooling=global_pooling)
            for nc, nf, fs, ps, cs, pst, cp, pp, a, pt in zip(
                listify(num_channels), listify(num_filters),
                listify(filter_size), listify(pool_size),
                listify(conv_stride), listify(pool_stride),
                listify(conv_padding), listify(pool_padding),
                listify(act), listify(pool_type))])

    def forward(self, input):
        outs = [conv(input) for conv in self.convs]
        return concat(outs, axis=1)


# ---------------------------------------------------------------------------
# transformer family (pre/post-process command style)
# ---------------------------------------------------------------------------

class PrePostProcessLayer(Layer):
    """Apply a command string: a=residual add, n=layer norm, d=dropout
    (text.py:2609)."""

    def __init__(self, process_cmd, d_model, dropout_rate=0.1):
        super().__init__()
        self.process_cmd = process_cmd
        self.dropout_rate = dropout_rate
        self.norms = LayerList([LayerNorm([d_model])
                                for c in process_cmd if c == 'n'])

    def forward(self, x, residual=None):
        ni = 0
        for cmd in self.process_cmd:
            if cmd == 'a':
                x = x + residual if residual is not None else x
            elif cmd == 'n':
                x = self.norms[ni](x)
                ni += 1
            elif cmd == 'd':
                if self.dropout_rate and self.training:
                    x = F.dropout(x, p=self.dropout_rate)
        return x


class MultiHeadAttention(Layer):
    """Q/K/V projection attention with optional cache (text.py:2687)."""

    def __init__(self, d_key, d_value, d_model, n_head, dropout_rate=0.1):
        super().__init__()
        self.n_head = n_head
        self.d_key = d_key
        self.d_value = d_value
        self.q_fc = Linear(d_model, d_key * n_head, bias_attr=False)
        self.k_fc = Linear(d_model, d_key * n_head, bias_attr=False)
        self.v_fc = Linear(d_model, d_value * n_head, bias_attr=False)
        self.proj_fc = Linear(d_value * n_head, d_model, bias_attr=False)
        self.dropout_rate = dropout_rate

    def _prepare_qkv(self, queries, keys, values, cache=None):
        cross = keys is not None
        if cache is not None and cross and 'static_k' in cache:
            # precomputed cross-attention K/V (prepare_static_cache): skip
            # the per-step K/V projection over the full encoder output
            q = self.q_fc(queries)

            def split_q(x):
                B, T = x.shape[0], x.shape[1]
                return transpose(x.reshape([B, T, self.n_head, self.d_key]),
                                 [0, 2, 1, 3])
            return split_q(q), cache['static_k'], cache['static_v']
        if keys is None:
            keys, values = queries, queries
        q = self.q_fc(queries)
        k = self.k_fc(keys)
        v = self.v_fc(values)

        def split_heads(x, d):
            B, T = x.shape[0], x.shape[1]
            return transpose(x.reshape([B, T, self.n_head, d]),
                             [0, 2, 1, 3])
        q = split_heads(q, self.d_key)
        k = split_heads(k, self.d_key)
        v = split_heads(v, self.d_value)
        if cache is not None:
            k = concat([cache['k'], k], axis=2)
            v = concat([cache['v'], v], axis=2)
            cache['k'], cache['v'] = k, v
        return q, k, v

    def forward(self, queries, keys=None, values=None, attn_bias=None,
                cache=None):
        q, k, v = self._prepare_qkv(queries, keys, values, cache)
        product = (q @ transpose(k, [0, 1, 3, 2])) * \
            (self.d_key ** -0.5)
        if attn_bias is not None:
            product = product + attn_bias
        weights = F.softmax(product, axis=-1)
        if self.dropout_rate and self.training:
            weights = F.dropout(weights, p=self.dropout_rate)
        out = weights @ v
        B, T = out.shape[0], out.shape[2]
        out = transpose(out, [0, 2, 1, 3]).reshape(
            [B, T, self.n_head * self.d_value])
        return self.proj_fc(out)

    def cal_kv(self, keys, values):
        """Precompute cross-attention K/V (static cache)."""
        k = self.k_fc(keys)
        v = self.v_fc(values)

        def split_heads(x, d):
            B, T = x.shape[0], x.shape[1]
            return transpose(x.reshape([B, T, self.n_head, d]),
                             [0, 2, 1, 3])
        return split_heads(k, self.d_key), split_heads(v, self.d_value)


class FFN(Layer):
    """Position-wise feed-forward (text.py:2900)."""

    def __init__(self, d_inner_hid, d_model, dropout_rate=0.1,
                 fc1_act="relu"):
        super().__init__()
        self.fc1 = Linear(d_model, d_inner_hid)
        self.fc2 = Linear(d_inner_hid, d_model)
        self.fc1_act = fc1_act
        self.dropout_rate = dropout_rate

    def forward(self, x):
        hidden = getattr(F, self.fc1_act)(self.fc1(x))
        if self.dropout_rate and self.training:
            hidden = F.dropout(hidden, p=self.dropout_rate)
        return self.fc2(hidden)


class TransformerEncoderLayer(Layer):
    """(text.py:2957)."""

    def __init__(self, n_head, d_key, d_value, d_model, d_inner_hid,
                 prepostprocess_dropout=0.1, attention_dropout=0.1,
                 relu_dropout=0.1, preprocess_cmd="n", postprocess_cmd="da",
                 ffn_fc1_act="relu"):
        super().__init__()
        self.preprocesser1 = PrePostProcessLayer(preprocess_cmd, d_model,
                                                 prepostprocess_dropout)
        self.self_attn = MultiHeadAttention(d_key, d_value, d_model, n_head,
                                            attention_dropout)
        self.postprocesser1 = PrePostProcessLayer(postprocess_cmd, d_model,
                                                  prepostprocess_dropout)
        self.preprocesser2 = PrePostProcessLayer(preprocess_cmd, d_model,
                                                 prepostprocess_dropout)
        self.ffn = FFN(d_inner_hid, d_model, relu_dropout, ffn_fc1_act)
        self.postprocesser2 = PrePostProcessLayer(postprocess_cmd, d_model,
                                                  prepostprocess_dropout)

    def forward(self, enc_input, attn_bias=None):
        attn_output = self.self_attn(self.preprocesser1(enc_input), None,
                                     None, attn_bias)
        attn_output = self.postprocesser1(attn_output, enc_input)
        ffn_output = self.ffn(self.preprocesser2(attn_output))
        return self.postprocesser2(ffn_output, attn_output)


class TransformerEncoder(Layer):
    """(text.py:3061)."""

    def __init__(self, n_layer, n_head, d_key, d_value, d_model,
                 d_inner_hid, prepostprocess_dropout=0.1,
                 attention_dropout=0.1, relu_dropout=0.1,
                 preprocess_cmd="n", postprocess_cmd="da",
                 ffn_fc1_act="relu"):
        super().__init__()
        self.encoder_layers = LayerList([
            TransformerEncoderLayer(n_head, d_key, d_value, d_model,
                                    d_inner_hid, prepostprocess_dropout,
                                    attention_dropout, relu_dropout,
                                    preprocess_cmd, postprocess_cmd,
                                    ffn_fc1_act)
            for _ in range(n_layer)])
        self.processer = PrePostProcessLayer(preprocess_cmd, d_model,
                                             prepostprocess_dropout)

    def forward(self, enc_input, attn_bias=None):
        for layer in self.encoder_layers:
            enc_input = layer(enc_input, attn_bias)
        return self.processer(enc_input)


class TransformerDecoderLayer(Layer):
    """(text.py:3170)."""

    def __init__(self, n_head, d_key, d_value, d_model, d_inner_hid,
                 prepostprocess_dropout=0.1, attention_dropout=0.1,
                 relu_dropout=0.1, preprocess_cmd="n", postprocess_cmd="da",
                 ffn_fc1_act="relu"):
        super().__init__()
        self.preprocesser1 = PrePostProcessLayer(preprocess_cmd, d_model,
                                                 prepostprocess_dropout)
        self.self_attn = MultiHeadAttention(d_key, d_value, d_model,
                                            n_head, attention_dropout)
        self.postprocesser1 = PrePostProcessLayer(postprocess_cmd, d_model,
                                                  prepostprocess_dropout)
        self.preprocesser2 = PrePostProcessLayer(preprocess_cmd, d_model,
                                                 prepostprocess_dropout)
        self.cross_attn = MultiHeadAttention(d_key, d_value, d_model,
                                             n_head, attention_dropout)
        self.postprocesser2 = PrePostProcessLayer(postprocess_cmd, d_model,
                                                  prepostprocess_dropout)
        self.preprocesser3 = PrePostProcessLayer(preprocess_cmd, d_model,
                                                 prepostprocess_dropout)
        self.ffn = FFN(d_inner_hid, d_model, relu_dropout, ffn_fc1_act)
        self.postprocesser3 = PrePostProcessLayer(postprocess_cmd, d_model,
                                                  prepostprocess_dropout)

    def forward(self, dec_input, enc_output, self_attn_bias=None,
                cross_attn_bias=None, cache=None):
        self_attn_output = self.self_attn(
            self.preprocesser1(dec_input), None, None, self_attn_bias,
            cache)
        self_attn_output = self.postprocesser1(self_attn_output, dec_input)
        cross_attn_output = self.cross_attn(
            self.preprocesser2(self_attn_output), enc_output, enc_output,
            cross_attn_bias,
            cache if (cache and 'static_k' in cache) else None)
        cross_attn_output = self.postprocesser2(cross_attn_output,
                                                self_attn_output)
        ffn_output = self.ffn(self.preprocesser3(cross_attn_output))
        return self.postprocesser3(ffn_output, cross_attn_output)


class TransformerDecoder(Layer):
    """(text.py:3314)."""

    def __init__(self, n_layer, n_head, d_key, d_value, d_model,
                 d_inner_hid, prepostprocess_dropout=0.1,
                 attention_dropout=0.1, relu_dropout=0.1,
                 preprocess_cmd="n", postprocess_cmd="da",
                 ffn_fc1_act="relu"):
        super().__init__()
        self.decoder_layers = LayerList([
            TransformerDecoderLayer(n_head, d_key, d_value, d_model,
                                    d_inner_hid, prepostprocess_dropout,
                                    attention_dropout, relu_dropout,
                                    preprocess_cmd, postprocess_cmd,
                                    ffn_fc1_act)
            for _ in range(n_layer)])
        self.processer = PrePostProcessLayer(preprocess_cmd, d_model,
                                             prepostprocess_dropout)

    def forward(self, dec_input, enc_output, self_attn_bias=None,
                cross_attn_bias=None, caches=None):
        for i, layer in enumerate(self.decoder_layers):
            dec_input = layer(dec_input, enc_output, self_attn_bias,
                              cross_attn_bias,
                              None if caches is None else caches[i])
        return self.processer(dec_input)

    def prepare_static_cache(self, enc_output):
        return [{'static_k': k, 'static_v': v}
                for k, v in (layer.cross_attn.cal_kv(enc_output, enc_output)
                             for layer in self.decoder_layers)]

    def prepare_incremental_cache(self, enc_output):
        B = enc_output.shape[0]
        from ..core.tensor import to_tensor
        n_head = self.decoder_layers[0].self_attn.n_head
        d_key = self.decoder_layers[0].self_attn.d_key
        d_value = self.decoder_layers[0].self_attn.d_value
        return [{'k': to_tensor(np.zeros((B, n_head, 0, d_key),
                                         np.float32)),
                 'v': to_tensor(np.zeros((B, n_head, 0, d_value),
                                         np.float32))}
                for _ in self.decoder_layers]


class TransformerCell(RNNCell):
    """Wrap a TransformerDecoder as a step cell producing logits
    (text.py:2252). states are the per-layer incremental caches."""

    def __init__(self, decoder, embedding_fn=None, output_fn=None):
        super().__init__()
        self.decoder = decoder
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def forward(self, inputs, states=None, enc_output=None,
                trg_slf_attn_bias=None, trg_src_attn_bias=None,
                static_caches=[]):
        word, position = inputs
        if self.embedding_fn is not None:
            inp = self.embedding_fn(word, position)
        else:
            inp = word
        if states is not None and static_caches:
            caches = [dict(inc, **st) for inc, st in zip(states,
                                                         static_caches)]
        else:
            caches = states
        out = self.decoder(inp, enc_output, trg_slf_attn_bias,
                           trg_src_attn_bias, caches)
        if self.output_fn is not None:
            out = self.output_fn(out)
        if out.ndim == 3 and out.shape[1] == 1:
            out = out.squeeze(1)
        new_states = [{'k': c['k'], 'v': c['v']} for c in caches] \
            if caches else states
        return out, new_states


class TransformerBeamSearchDecoder(BeamSearchDecoder):
    """Beam search adapted to transformer caches (text.py:2421).

    TPU-first: nn.decode's beam machinery already carries nested cache
    states through the while_loop; the transformer quirks handled here are
    the [B*beam, 1] 2-D step inputs and the growing cache dim —
    `var_dim_in_state` marks it (kept for API parity; the dense design
    reindexes the whole cache by beam, which is correct for any dim)."""

    def __init__(self, cell, start_token, end_token, beam_size,
                 var_dim_in_state):
        super().__init__(cell, start_token, end_token, beam_size)
        self.var_dim_in_state = var_dim_in_state

    @staticmethod
    def tile_beam_merge_with_batch(x, beam_size):
        return BeamSearchDecoder.tile_beam_merge_with_batch(x, beam_size)

    def step(self, time, inputs, states, **kwargs):
        # same flow as BeamSearchDecoder.step, with the transformer shims:
        # ids reshaped to [B*beam, 1] and a position input filled with
        # `time` (a traced loop counter — threaded through apply_op)
        from ..nn.decode import _map_structure
        from ..core.tensor import apply_op
        from ..tensor._helpers import _t

        inputs = _map_structure(self._merge_batch_beams, inputs)
        word = inputs.unsqueeze(-1) if inputs.ndim == 1 else inputs
        pos = apply_op(
            lambda w, tt: jnp.full(w.shape, tt.astype(jnp.int32),
                                   jnp.int32),
            (_t(word), _t(time)), differentiable=False)
        cell_states = _map_structure(self._merge_batch_beams,
                                     states['cell_states'])
        cell_outputs, next_cell_states = self.cell((word, pos),
                                                   cell_states, **kwargs)
        cell_outputs = _map_structure(self._split_batch_beams,
                                      cell_outputs)
        next_cell_states = _map_structure(self._split_batch_beams,
                                          next_cell_states)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        output, state = self._beam_search_step(
            time, cell_outputs, next_cell_states, states)
        finished = state['finished']
        next_inputs = output['predicted_ids']
        return output, state, next_inputs, finished


# ---------------------------------------------------------------------------
# CRF layers + SequenceTagging
# ---------------------------------------------------------------------------

class LinearChainCRF(Layer):
    """CRF NLL cost layer holding the transition parameter (text.py:3506);
    transition is [(size+2), size] (rows 0/1 = start/stop)."""

    def __init__(self, size, param_attr=None, dtype='float32'):
        super().__init__()
        from ..nn.initializer import ParamAttr, Normal
        a = ParamAttr._to_attr(param_attr)
        init = a.initializer or Normal(0.0, 0.1)
        from ..core.tensor import Parameter
        self.transition = Parameter(
            jnp.asarray(init([size + 2, size], dtype=dtype)),
            name=a.name or 'crf_transition')
        self.add_parameter('transition', self.transition)

    @property
    def weight(self):
        return self.transition

    def forward(self, input, label, length):
        return F.linear_chain_crf(input, label, self.transition,
                                  length=length)


class CRFDecoding(Layer):
    """Viterbi decoding layer sharing the CRF transition (text.py:3655)."""

    def __init__(self, size, param_attr=None, dtype='float32'):
        super().__init__()
        from ..nn.initializer import ParamAttr, Normal
        a = ParamAttr._to_attr(param_attr)
        init = a.initializer or Normal(0.0, 0.1)
        from ..core.tensor import Parameter
        self.transition = Parameter(
            jnp.asarray(init([size + 2, size], dtype=dtype)),
            name=a.name or 'crfw')
        self.add_parameter('transition', self.transition)

    @property
    def weight(self):
        return self.transition

    def forward(self, input, length, label=None):
        return F.crf_decoding(input, self.transition, length=length,
                              label=label)


class _GRUEncoder(Layer):
    """Stacked (bi-)GRU encoder used by SequenceTagging (text.py:3773)."""

    def __init__(self, input_dim, grnn_hidden_dim, init_bound,
                 num_layers=1, is_bidirection=False):
        super().__init__()
        self.num_layers = num_layers
        self.is_bidirection = is_bidirection
        self.gru_list = LayerList()
        from ..nn.initializer import Uniform, ParamAttr
        attr = ParamAttr(initializer=Uniform(-init_bound, init_bound))
        for i in range(num_layers):
            in_dim = input_dim if i == 0 else (
                grnn_hidden_dim * 2 if is_bidirection else grnn_hidden_dim)
            if is_bidirection:
                self.gru_list.append(BidirectionalGRU(
                    in_dim, grnn_hidden_dim, num_layers=1,
                    param_attr=attr))
            else:
                self.gru_list.append(GRU(in_dim, grnn_hidden_dim,
                                         num_layers=1, param_attr=attr))

    def forward(self, input_feature, h0=None):
        out = input_feature
        for gru in self.gru_list:
            out, _ = gru(out)
        return out


class SequenceTagging(Layer):
    """BiGRU-CRF sequence tagging network (text.py:3832): embedding ->
    stacked bi-GRU -> emission fc -> CRF. forward(word, lengths, target):
    with target returns (crf_cost, decoded); else decoded paths."""

    def __init__(self, vocab_size, num_labels, word_emb_dim=128,
                 grnn_hidden_dim=128, emb_learning_rate=0.1,
                 crf_learning_rate=0.1, bigru_num=2, init_bound=0.1):
        super().__init__()
        self.word_embedding = Embedding(vocab_size, word_emb_dim)
        self.gru_encoder = _GRUEncoder(word_emb_dim, grnn_hidden_dim,
                                       init_bound, num_layers=bigru_num,
                                       is_bidirection=True)
        self.fc = Linear(grnn_hidden_dim * 2, num_labels)
        self.linear_chain_crf = LinearChainCRF(num_labels)
        self.crf_decoding = CRFDecoding(num_labels)
        # decoding shares the training transition (the reference ties them
        # through the shared crfw parameter)
        self.crf_decoding.transition = self.linear_chain_crf.transition

    def forward(self, word, lengths, target=None):
        emb = self.word_embedding(word)
        enc = self.gru_encoder(emb)
        emission = self.fc(enc)
        if target is not None:
            crf_cost = self.linear_chain_crf(emission, target, lengths)
            decoded = F.crf_decoding(
                emission, self.linear_chain_crf.transition, length=lengths)
            return crf_cost, decoded
        return F.crf_decoding(
            emission, self.linear_chain_crf.transition, length=lengths)
