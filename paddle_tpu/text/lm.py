"""LSTM language model (PTB-style).

Parity: the reference LSTM LM (ptb_lm example: fluid.layers.lstm stack +
softmax over vocab, truncated BPTT). TPU-first: nn.LSTM lowers to lax.scan
(one compiled loop, weights stay in registers/HBM across steps); logits tie
optionally to the input embedding.
"""
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor

__all__ = ['LSTMLanguageModel']


class LSTMLanguageModel(nn.Layer):
    def __init__(self, vocab_size, hidden_size=200, num_layers=2,
                 dropout=0.0, tie_weights=False):
        super().__init__()
        self.embedding = nn.Embedding(vocab_size, hidden_size)
        self.lstm = nn.LSTM(hidden_size, hidden_size, num_layers=num_layers,
                            dropout=dropout)
        self.dropout = nn.Dropout(dropout)
        self.tie_weights = tie_weights
        if tie_weights:
            # output projection reuses the [vocab, hidden] embedding table
            # (transposed matmul); only a bias is learned separately
            self.out_bias = self.create_parameter(
                [vocab_size], is_bias=True)
        else:
            self.fc = nn.Linear(hidden_size, vocab_size)
        self.hidden_size = hidden_size
        self.num_layers = num_layers

    def forward(self, ids, state=None):
        """ids: int [batch, seq]. Returns (logits [b, s, vocab], state)."""
        x = self.dropout(self.embedding(ids))
        out, state = self.lstm(x, state)
        out = self.dropout(out)
        if self.tie_weights:
            logits = out.matmul(self.embedding.weight.T) + self.out_bias
        else:
            logits = self.fc(out)
        return logits, state

    def loss(self, logits, targets):
        return nn.functional.cross_entropy(
            logits.reshape([-1, logits.shape[-1]]), targets.reshape([-1]))
