"""Transformer seq2seq for translation. Parity: reference transformer model
(WMT) built on nn.Transformer."""
from .. import nn
from ..tensor.creation import arange

__all__ = ['Seq2SeqTransformer']


class Seq2SeqTransformer(nn.Layer):
    def __init__(self, src_vocab_size, trg_vocab_size, d_model=512, nhead=8,
                 num_encoder_layers=6, num_decoder_layers=6,
                 dim_feedforward=2048, dropout=0.1, max_length=256):
        super().__init__()
        self.src_emb = nn.Embedding(src_vocab_size, d_model)
        self.trg_emb = nn.Embedding(trg_vocab_size, d_model)
        self.pos_emb = nn.Embedding(max_length, d_model)
        self.transformer = nn.Transformer(
            d_model, nhead, num_encoder_layers, num_decoder_layers,
            dim_feedforward, dropout)
        self.out_proj = nn.Linear(d_model, trg_vocab_size)

    def _embed(self, ids, emb):
        B, L = ids.shape
        pos = arange(0, L, dtype='int64').unsqueeze(0)
        return emb(ids) + self.pos_emb(pos)

    def forward(self, src_ids, trg_ids):
        src = self._embed(src_ids, self.src_emb)
        trg = self._embed(trg_ids, self.trg_emb)
        L = trg_ids.shape[1]
        tgt_mask = nn.Transformer.generate_square_subsequent_mask(L)
        out = self.transformer(src, trg, tgt_mask=tgt_mask)
        return self.out_proj(out)

    def translate(self, src_ids, bos_id=0, eos_id=1, beam_size=4,
                  max_len=64):
        """Beam-search translation (parity: reference transformer infer
        program, fluid/layers/rnn.py:856 BeamSearchDecoder usage).

        Encodes once, then decodes with nn.BeamSearchDecoder over an
        incremental decoder cache. Returns int ids (B, T, beam) ranked
        best-first along the beam axis.
        """
        from ..nn.decode import BeamSearchDecoder, dynamic_decode
        from ..core import autograd
        max_pos = self.pos_emb.weight.shape[0]
        if max_len > max_pos:
            raise ValueError(
                f"translate max_len {max_len} exceeds positional table "
                f"size {max_pos}")
        was_training = self.training
        self.eval()
        try:
            with autograd.no_grad():
                memory = self.transformer.encoder(
                    self._embed(src_ids, self.src_emb))
                cache = self.transformer.decoder.gen_cache(memory)
                cell = _TransformerDecodeCell(self)
                decoder = BeamSearchDecoder(cell, start_token=bos_id,
                                            end_token=eos_id,
                                            beam_size=beam_size)
                from ..core.tensor import Tensor
                import jax.numpy as jnp
                B = src_ids.shape[0]
                pos0 = Tensor(jnp.zeros((B,), jnp.int32))
                outputs, _ = dynamic_decode(
                    decoder, inits={'cache': cache, 'pos': pos0},
                    max_step_num=max_len, is_test=True)
                return outputs  # (B, T, beam) ids after gather_tree+transpose
        finally:
            if was_training:
                self.train()


class _TransformerDecodeCell:
    """RNNCell-style adapter over the TransformerDecoder incremental cache.

    State = {'cache': decoder cache (incremental + static per layer),
    'pos': (N,) int32 absolute position}. The static (cross-attention) cache
    carries the projected encoder memory, so the memory argument to the
    decoder is never re-read during decode.
    """

    def __init__(self, model):
        self.model = model

    def __call__(self, inputs, states):
        import jax.numpy as jnp
        cache, pos = states['cache'], states['pos']
        ids = inputs.unsqueeze(1)                      # (N, 1)
        x = self.model.trg_emb(ids) + self.model.pos_emb(pos.unsqueeze(1))
        out, new_cache = self.model.transformer.decoder(
            x, x, None, None, cache)                   # memory unused w/ static cache
        logits = self.model.out_proj(out.squeeze(1))   # (N, V)
        from ..core.tensor import apply_op
        new_pos = apply_op(lambda p: p + 1, (pos,), differentiable=False)
        return logits, {'cache': new_cache, 'pos': new_pos}
