"""Transformer seq2seq for translation. Parity: reference transformer model
(WMT) built on nn.Transformer."""
from .. import nn
from ..tensor.creation import arange

__all__ = ['Seq2SeqTransformer']


class Seq2SeqTransformer(nn.Layer):
    def __init__(self, src_vocab_size, trg_vocab_size, d_model=512, nhead=8,
                 num_encoder_layers=6, num_decoder_layers=6,
                 dim_feedforward=2048, dropout=0.1, max_length=256):
        super().__init__()
        self.src_emb = nn.Embedding(src_vocab_size, d_model)
        self.trg_emb = nn.Embedding(trg_vocab_size, d_model)
        self.pos_emb = nn.Embedding(max_length, d_model)
        self.transformer = nn.Transformer(
            d_model, nhead, num_encoder_layers, num_decoder_layers,
            dim_feedforward, dropout)
        self.out_proj = nn.Linear(d_model, trg_vocab_size)

    def _embed(self, ids, emb):
        B, L = ids.shape
        pos = arange(0, L, dtype='int64').unsqueeze(0)
        return emb(ids) + self.pos_emb(pos)

    def forward(self, src_ids, trg_ids):
        src = self._embed(src_ids, self.src_emb)
        trg = self._embed(trg_ids, self.trg_emb)
        L = trg_ids.shape[1]
        tgt_mask = nn.Transformer.generate_square_subsequent_mask(L)
        out = self.transformer(src, trg, tgt_mask=tgt_mask)
        return self.out_proj(out)
