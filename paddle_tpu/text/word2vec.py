"""word2vec: skip-gram with negative sampling.

Parity: the reference word2vec example trains skip-gram over Imikolov with
hierarchical-softmax/NCE ops on a parameter server. TPU-first: in-batch
negative sampling — one [batch, dim] x [dim, 1+k] matmul per center word,
static shapes, no hsigmoid tree walk.
"""
import numpy as np
import jax.numpy as jnp

from .. import nn
from ..core.tensor import Tensor
from ..tensor.random import randint

__all__ = ['SkipGram', 'Word2Vec']


class SkipGram(nn.Layer):
    def __init__(self, vocab_size, embedding_dim=128, neg_samples=5):
        super().__init__()
        self.vocab_size = vocab_size
        self.neg_samples = neg_samples
        self.in_embed = nn.Embedding(vocab_size, embedding_dim)
        self.out_embed = nn.Embedding(vocab_size, embedding_dim)

    def forward(self, center, context, negatives=None):
        """center/context: int [batch]; negatives: int [batch, k] (sampled
        uniformly if not given). Returns scalar NEG loss."""
        if negatives is None:
            negatives = randint(0, self.vocab_size,
                                [center.shape[0], self.neg_samples])
        c = self.in_embed(center)                       # [b, d]
        pos = self.out_embed(context)                   # [b, d]
        neg = self.out_embed(negatives)                 # [b, k, d]
        pos_score = (c * pos).sum(axis=-1)              # [b]
        neg_score = (neg * c.unsqueeze(1)).sum(axis=-1)  # [b, k]
        pos_loss = nn.functional.log_sigmoid(pos_score)
        neg_loss = nn.functional.log_sigmoid(-neg_score).sum(axis=-1)
        return -(pos_loss + neg_loss).mean()

    def embedding(self):
        return self.in_embed.weight


Word2Vec = SkipGram
