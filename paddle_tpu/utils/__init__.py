"""Utilities. Parity: python/paddle/utils/."""
from . import unique_name
from .lazy_import import try_import
from .deprecated import deprecated

__all__ = ['unique_name', 'try_import', 'deprecated', 'run_check',
           'check_numerics', 'enable_check_nan_inf', 'divergence_check',
           'deterministic_guard']


def run_check():
    from .install_check import run_check as _rc
    return _rc()

from . import debug
from .debug import (check_numerics, enable_check_nan_inf,
                    divergence_check, deterministic_guard)
from . import download  # noqa: E402,F401
from . import profiler  # noqa: E402,F401
from .profiler import Profiler, ProfilerOptions, get_profiler  # noqa: E402,F401
from . import image_util  # noqa: E402,F401
__all__ += ['download', 'profiler', 'Profiler', 'ProfilerOptions',
            'get_profiler', 'image_util']
from .download import get_weights_path_from_url  # noqa: E402,F401
__all__ += ['get_weights_path_from_url']
