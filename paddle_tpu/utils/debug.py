"""Debug utilities: NaN/Inf checking, determinism verification, graph export.

Parity targets in the reference:
- FLAGS_check_nan_inf + framework/details/nan_inf_utils (per-op NaN screens)
- the race-condition story: the reference's ParallelExecutor races are
  C++-level; the TPU-first analogue is nondeterminism across identical runs
  (unseeded RNG, async reduction order), checked by ``divergence_check``;
- debugger/graphviz (python/paddle/fluid/net_drawer.py + debugger.draw_block_
  graphviz): here ``draw_program`` / ``draw_tape`` emit Graphviz dot.
"""
import os

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ['check_numerics', 'enable_check_nan_inf', 'nan_inf_enabled',
           'divergence_check', 'deterministic_guard', 'draw_program',
           'draw_tape']

_check_nan = [bool(int(os.environ.get('PADDLE_TPU_CHECK_NAN_INF', '0')))]


def _nan_hook(fn, out_vals):
    name = getattr(fn, '__name__', 'op')
    vals = out_vals if isinstance(out_vals, (tuple, list)) else [out_vals]
    for i, v in enumerate(vals):
        if isinstance(v, jax.core.Tracer):
            continue  # traced region: screen applies to eager payloads only
        a = np.asarray(jax.device_get(v))
        if a.dtype.kind in 'fc' and not np.isfinite(a).all():
            raise FloatingPointError(
                f"NaN/Inf produced by op '{name}' (output {i}, shape "
                f"{list(a.shape)} {a.dtype}) — check_nan_inf mode")


def enable_check_nan_inf(flag=True):
    """Global per-op NaN/Inf screening on the eager path (debug mode; forces
    a host sync per op — the analogue of FLAGS_check_nan_inf)."""
    from ..core import tensor as tensor_mod
    prev = _check_nan[0]
    _check_nan[0] = bool(flag)
    tensor_mod.set_nan_check_hook(_nan_hook if flag else None)
    return prev


if _check_nan[0]:   # honor PADDLE_TPU_CHECK_NAN_INF=1 at import
    enable_check_nan_inf(True)


def nan_inf_enabled():
    return _check_nan[0]


def _leaves_with_paths(value, root):
    """[(path_str, host ndarray)] for every Tensor/array leaf; traced leaves
    are skipped (they cannot be inspected host-side)."""
    from ..core.tensor import Tensor
    from jax.tree_util import tree_flatten_with_path, keystr
    flat, _ = tree_flatten_with_path(
        value, is_leaf=lambda v: isinstance(v, Tensor))
    out = []
    for path, v in flat:
        if v is None:
            continue
        arr = v._value if isinstance(v, Tensor) else v
        if isinstance(arr, jax.core.Tracer):
            continue
        out.append((root + keystr(path),
                    np.asarray(jax.device_get(arr))))
    return out


def check_numerics(value, name="tensor"):
    """Raise FloatingPointError if ``value`` (Tensor/array/pytree) contains
    NaN/Inf. Returns the value for chaining."""
    for path, a in _leaves_with_paths(value, name):
        if a.dtype.kind in 'fc':
            bad_nan = int(np.isnan(a).sum())
            bad_inf = int(np.isinf(a).sum())
            if bad_nan or bad_inf:
                raise FloatingPointError(
                    f"check_numerics failed for '{path}': {bad_nan} NaN, "
                    f"{bad_inf} Inf in shape {list(a.shape)} {a.dtype}")
    return value


def divergence_check(fn, *args, runs=2, rtol=0.0, atol=0.0, verbose=False):
    """Run ``fn(*args)`` ``runs`` times and compare outputs (bitwise by
    default). Returns True when all runs agree; raises AssertionError with
    the first divergent leaf otherwise.

    This is the TPU-first analogue of a race detector: with seeded RNG and
    XLA's deterministic executables, ANY cross-run divergence indicates
    nondeterminism (unseeded host RNG, data-order dependence, or
    atomics/reduction-order effects in custom kernels).
    """
    def snapshot(out):
        return _leaves_with_paths(out, "out")

    base = snapshot(fn(*args))
    for r in range(1, runs):
        cur = snapshot(fn(*args))
        if len(cur) != len(base):
            raise AssertionError(
                f"divergence_check: run {r} produced {len(cur)} leaves vs "
                f"{len(base)}")
        for (p0, a0), (p1, a1) in zip(base, cur):
            same = (np.allclose(a0, a1, rtol=rtol, atol=atol, equal_nan=True)
                    if (rtol or atol) else np.array_equal(
                        a0, a1, equal_nan=(a0.dtype.kind in 'fc')))
            if not same:
                diff = np.max(np.abs(a0.astype(np.float64) -
                                     a1.astype(np.float64))) \
                    if a0.dtype.kind in 'fiu' else 'n/a'
                raise AssertionError(
                    f"divergence_check: output '{p0}' differs between run 0 "
                    f"and run {r} (max abs diff {diff})")
        if verbose:
            print(f"divergence_check: run {r} identical")
    return True


class deterministic_guard:
    """Context manager: seeds global RNG on entry, restores state on exit.

    with deterministic_guard(1234):
        out1 = train_step(...)
    """

    def __init__(self, seed=0):
        self.seed = seed

    def __enter__(self):
        from ..core import rng
        self._state = rng.get_rng_state()
        rng.seed(self.seed)
        return self

    def __exit__(self, *exc):
        from ..core import rng
        rng.set_rng_state(self._state)
        return False


def _dot_escape(s):
    return str(s).replace('"', r'\"')


def draw_program(program, path=None):
    """Graphviz dot for a static Program's op/var graph (parity:
    fluid.debugger.draw_block_graphviz). Returns the dot source; writes to
    ``path`` when given."""
    lines = ['digraph program {', '  rankdir=TB;',
             '  node [shape=record, fontsize=10];']
    seen_vars = set()
    for b, block in enumerate(program.blocks):
        for i, op in enumerate(block.ops):
            op_id = f"op_{b}_{i}"
            lines.append(
                f'  {op_id} [label="{_dot_escape(op.type)}", '
                f'style=filled, fillcolor=lightblue];')
            for v in op.inputs:
                name = getattr(v, 'name', str(v))
                vid = f'var_{_dot_escape(name)}'
                if name not in seen_vars:
                    seen_vars.add(name)
                    lines.append(f'  "{vid}" [label="{_dot_escape(name)}"];')
                lines.append(f'  "{vid}" -> {op_id};')
            for v in op.outputs:
                name = getattr(v, 'name', str(v))
                vid = f'var_{_dot_escape(name)}'
                if name not in seen_vars:
                    seen_vars.add(name)
                    lines.append(f'  "{vid}" [label="{_dot_escape(name)}"];')
                lines.append(f'  {op_id} -> "{vid}";')
    lines.append('}')
    dot = '\n'.join(lines)
    if path:
        with open(path, 'w') as f:
            f.write(dot)
    return dot


def draw_tape(tensor, path=None, max_nodes=500):
    """Graphviz dot of the autograd tape reaching ``tensor`` (eager-mode
    analogue of the reference's graph visualizer)."""
    lines = ['digraph tape {', '  rankdir=BT;',
             '  node [shape=record, fontsize=10];']
    visited = {}
    stack = [tensor._node] if tensor._node is not None else []
    count = 0
    while stack and count < max_nodes:
        node = stack.pop()
        if node is None or id(node) in visited or node.released:
            continue
        nid = f"n{len(visited)}"
        visited[id(node)] = nid
        count += 1
        fname = getattr(node.fn, '__name__', 'op')
        outs = ','.join(str(list(o._value.shape)) for o in node.outputs)
        lines.append(f'  {nid} [label="{_dot_escape(fname)}|{outs}"];')
        for t in node.inputs:
            if t._node is not None and not t._node.released:
                stack.append(t._node)
    # second pass: edges
    def nid_of(node):
        return visited.get(id(node))
    stack = [tensor._node] if tensor._node is not None else []
    seen = set()
    while stack:
        node = stack.pop()
        if node is None or id(node) in seen or id(node) not in visited:
            continue
        seen.add(id(node))
        for t in node.inputs:
            if t._node is not None and id(t._node) in visited:
                lines.append(f'  {nid_of(t._node)} -> {nid_of(node)};')
                stack.append(t._node)
    lines.append('}')
    dot = '\n'.join(lines)
    if path:
        with open(path, 'w') as f:
            f.write(dot)
    return dot
