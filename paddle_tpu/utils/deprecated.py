"""Parity: python/paddle/utils/deprecated.py."""
import functools
import warnings


def deprecated(update_to="", since="", reason=""):
    def decorator(func):
        @functools.wraps(func)
        def wrapper(*args, **kwargs):
            msg = f"API {func.__name__} is deprecated since {since}"
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f". Reason: {reason}"
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return func(*args, **kwargs)
        return wrapper
    return decorator
