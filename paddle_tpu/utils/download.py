"""Weights-by-URL cache resolution. Parity:
python/paddle/utils/download.py:58 (get_weights_path_from_url).

TPU-first divergence: this build is hermetic (zero-egress) BY DEFAULT — see
utils/hermetic.allow_egress(). In hermetic mode the function resolves the URL
to the reference cache layout (~/.cache/paddle/weights/<basename>) and
returns the path when the file is pre-seeded; otherwise it raises with the
exact path to provision. With PADDLE_TPU_ALLOW_EGRESS=1 it downloads through
bounded retry (exponential backoff + jitter, resilience.retry) and commits
the file atomically so a killed download never leaves a torn cache entry.
"""
import hashlib
import http.client
import os

from .hermetic import allow_egress
from ..resilience.atomic_io import atomic_write
from ..resilience.retry import retry

__all__ = ['get_weights_path_from_url']

WEIGHTS_HOME = os.path.expanduser('~/.cache/paddle/weights')

# seam for tests/faultinject: patched to a fake opener so retry behavior is
# testable without egress. Returns a file-like with .read().
def _open_url(url, timeout=30.0):
    import urllib.request
    return urllib.request.urlopen(url, timeout=timeout)


# http.client.HTTPException covers mid-body failures (IncompleteRead on a
# dropped connection) that are NOT OSError subclasses but just as transient
@retry(max_attempts=4, backoff=0.5, factor=2.0, jitter=0.5,
       retry_on=(OSError, ConnectionError, TimeoutError,
                 http.client.HTTPException))
def _fetch(url, dest):
    """One bounded-retry download, streamed in chunks (constant memory for
    multi-GB weights) and committed via atomic replace."""
    import urllib.error
    try:
        resp = _open_url(url)
    except urllib.error.HTTPError as e:
        if e.code < 500 and e.code not in (408, 429):
            # permanent client error (404/403/...): HTTPError subclasses
            # OSError, so re-type it or retry() would hammer the server
            # with a request that can never succeed. 408 (timeout) and 429
            # (throttled fleet stampede) ARE transient — exactly what the
            # backoff+jitter here is for — and stay retryable.
            raise RuntimeError(
                "download of %r failed with HTTP %s %s — not retrying a "
                "permanent client error" % (url, e.code, e.reason)) from e
        raise
    try:
        atomic_write(dest, resp)   # file-like: streamed to the staged temp
    finally:
        close = getattr(resp, 'close', None)
        if close:
            close()
    return dest


def _md5_of(path):
    digest = hashlib.md5()
    with open(path, 'rb') as f:
        for chunk in iter(lambda: f.read(1 << 20), b''):
            digest.update(chunk)
    return digest.hexdigest()


def get_weights_path_from_url(url, md5sum=None):
    fname = os.path.basename(url.split('?')[0])
    path = os.path.join(WEIGHTS_HOME, fname)
    if os.path.exists(path):
        if md5sum is not None:
            got = _md5_of(path)
            if got != md5sum:
                raise RuntimeError(
                    f"cached weights at {path!r} fail the md5 check "
                    f"(expected {md5sum}, got {got}): the "
                    f"pre-seeded file is stale or corrupt — replace it")
        return path
    if not allow_egress():
        raise RuntimeError(
            f"weights for {url!r} not present at {path!r}: this environment "
            f"has no network egress — place the file there (or point "
            f"model code at a local checkpoint via paddle.load) and retry, "
            f"or set PADDLE_TPU_ALLOW_EGRESS=1 to enable downloads")
    _fetch(url, path)
    if md5sum is not None and _md5_of(path) != md5sum:
        os.unlink(path)
        raise RuntimeError(
            f"downloaded weights for {url!r} fail the md5 check "
            f"(expected {md5sum}) — the source is corrupt; not caching it")
    return path
