"""Weights-by-URL cache resolution. Parity:
python/paddle/utils/download.py:58 (get_weights_path_from_url).

TPU-first divergence: this build runs in zero-egress environments, so no
network fetch is attempted. The function resolves the URL to the same
cache layout the reference uses (~/.cache/paddle/weights/<basename>) and
returns the path when the file is already present (pre-seeded caches,
mounted volumes); otherwise it raises with the exact path to provision.
"""
import os

__all__ = ['get_weights_path_from_url']

WEIGHTS_HOME = os.path.expanduser('~/.cache/paddle/weights')


def get_weights_path_from_url(url, md5sum=None):
    fname = os.path.basename(url.split('?')[0])
    path = os.path.join(WEIGHTS_HOME, fname)
    if os.path.exists(path):
        if md5sum is not None:
            import hashlib
            digest = hashlib.md5()
            with open(path, 'rb') as f:
                for chunk in iter(lambda: f.read(1 << 20), b''):
                    digest.update(chunk)
            if digest.hexdigest() != md5sum:
                raise RuntimeError(
                    f"cached weights at {path!r} fail the md5 check "
                    f"(expected {md5sum}, got {digest.hexdigest()}): the "
                    f"pre-seeded file is stale or corrupt — replace it")
        return path
    raise RuntimeError(
        f"weights for {url!r} not present at {path!r}: this environment "
        f"has no network egress — place the file there (or point "
        f"model code at a local checkpoint via paddle.load) and retry")
