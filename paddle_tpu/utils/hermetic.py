"""Hermetic child-process environments for the driver harness.

The axon TPU plugin installs a sitecustomize (under a `.axon*` site dir on
PYTHONPATH) that dials the TPU relay at interpreter startup; when the tunnel
is wedged, every child that inherits it hangs before running a line of user
code. CPU-only children must drop that site dir and pin JAX_PLATFORMS=cpu
BEFORE jax initializes.

Shared by bench.py and __graft_entry__.py so the two drivers can't diverge.
IMPORTANT: those parents must load this file BY PATH (see load_hermetic in
bench.py) — `import paddle_tpu.utils.hermetic` would run the package
__init__, which initializes the JAX backend and hangs on a wedged tunnel.
This module therefore imports nothing beyond the stdlib.
"""
import os


def clean_cpu_env(extra_path=None, base_env=None):
    """Environment for a CPU-only child interpreter.

    Strips `.axon*` site dirs from PYTHONPATH (matching the path component,
    not a bare substring — '/home/jaxon/libs' must survive) and pins the CPU
    backend. `extra_path` entries are prepended to PYTHONPATH.
    """
    env = dict(os.environ if base_env is None else base_env)
    kept = []
    for p in env.get('PYTHONPATH', '').split(os.pathsep):
        if not p:
            continue
        parts = os.path.normpath(p).split(os.sep)
        if any(seg.startswith('.axon') for seg in parts):
            continue
        kept.append(p)
    pre = list(extra_path or [])
    env['PYTHONPATH'] = os.pathsep.join(pre + kept)
    env['JAX_PLATFORMS'] = 'cpu'
    return env


def allow_egress(base_env=None):
    """True when this process may attempt network fetches.

    The build is hermetic (zero-egress) BY DEFAULT: TPU pods and the test
    harness run without internet, so code that could fetch (utils/download)
    must check this gate and fall back to pre-seeded caches when it is off.
    Opt in with PADDLE_TPU_ALLOW_EGRESS=1.
    """
    env = os.environ if base_env is None else base_env
    return str(env.get('PADDLE_TPU_ALLOW_EGRESS', '')).lower() in (
        '1', 'true', 'yes', 'on')
