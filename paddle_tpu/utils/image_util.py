"""paddle.utils.image_util — classic image batch/crop/flip helpers.

Parity: /root/reference/python/paddle/utils/image_util.py:1 (resize_image,
flip, crop_img, decode_jpeg, preprocess_img, load_meta, load_image,
oversample, ImageTransformer). Pure numpy port with PIL used only where
the reference decodes/loads files (gated by try_import so the numpy ops
work without PIL).
"""
import io

import numpy as np

__all__ = ['resize_image', 'flip', 'crop_img', 'decode_jpeg',
           'preprocess_img', 'load_meta', 'load_image', 'oversample',
           'ImageTransformer']


def _pil():
    from .lazy_import import try_import
    return try_import('PIL.Image')


def resize_image(img, target_size):
    """Resize a PIL image so the shorter edge equals target_size."""
    Image = _pil()
    percent = target_size / float(min(img.size[0], img.size[1]))
    resized = (int(round(img.size[0] * percent)),
               int(round(img.size[1] * percent)))
    resample = getattr(Image, 'LANCZOS', getattr(Image, 'ANTIALIAS', 1))
    return img.resize(resized, resample)


def flip(im):
    """Horizontal flip of a (K, H, W) or (H, W) ndarray."""
    if im.ndim == 3:
        return im[:, :, ::-1]
    return im[:, ::-1]


def crop_img(im, inner_size, color=True, test=True):
    """Center (test) or random (train, + random flip) inner_size crop of a
    (K, H, W) / (H, W) ndarray, zero-padded up to inner_size if smaller."""
    im = im.astype('float32')
    if color:
        height = max(inner_size, im.shape[1])
        width = max(inner_size, im.shape[2])
        padded = np.zeros((3, height, width), np.float32)
        sy = (height - im.shape[1]) // 2
        sx = (width - im.shape[2]) // 2
        padded[:, sy:sy + im.shape[1], sx:sx + im.shape[2]] = im
    else:
        height = max(inner_size, im.shape[0])
        width = max(inner_size, im.shape[1])
        padded = np.zeros((height, width), np.float32)
        sy = (height - im.shape[0]) // 2
        sx = (width - im.shape[1]) // 2
        padded[sy:sy + im.shape[0], sx:sx + im.shape[1]] = im
    if test:
        sy = (height - inner_size) // 2
        sx = (width - inner_size) // 2
    else:
        sy = np.random.randint(0, height - inner_size + 1)
        sx = np.random.randint(0, width - inner_size + 1)
    pic = padded[..., sy:sy + inner_size, sx:sx + inner_size]
    if not test and np.random.randint(2) == 0:
        pic = flip(pic)
    return pic


def decode_jpeg(jpeg_string):
    """Decode JPEG bytes to a (K, H, W) ndarray."""
    Image = _pil()
    arr = np.array(Image.open(io.BytesIO(jpeg_string)))
    if arr.ndim == 3:
        arr = np.transpose(arr, (2, 0, 1))
    return arr


def preprocess_img(im, img_mean, crop_size, is_train, color=True):
    """Crop (+train-time flip), subtract the mean image, flatten."""
    im = im.astype('float32')
    pic = crop_img(im, crop_size, color, test=not is_train)
    pic -= img_mean
    return pic.flatten()


def load_meta(meta_path, mean_img_size, crop_size, color=True):
    """Load the dataset mean image and center-crop it to crop_size."""
    mean = np.load(meta_path)['data_mean']
    border = (mean_img_size - crop_size) // 2
    if color:
        assert mean_img_size * mean_img_size * 3 == mean.shape[0]
        mean = mean.reshape(3, mean_img_size, mean_img_size)
        mean = mean[:, border:border + crop_size,
                    border:border + crop_size]
    else:
        assert mean_img_size * mean_img_size == mean.shape[0]
        mean = mean.reshape(mean_img_size, mean_img_size)
        mean = mean[border:border + crop_size, border:border + crop_size]
    return mean.astype('float32')


def load_image(img_path, is_color=True):
    """Open an image file (PIL)."""
    Image = _pil()
    img = Image.open(img_path)
    img.load()
    return img


def oversample(img, crop_dims):
    """Ten-crop a batch: 4 corners + center, plus horizontal mirrors.
    img: iterable of (H, W, K) ndarrays; returns (10*N, ch, cw, K)."""
    im_shape = np.array(img[0].shape)
    crop_dims = np.array(crop_dims)
    im_center = im_shape[:2] / 2.0
    h_indices = (0, im_shape[0] - crop_dims[0])
    w_indices = (0, im_shape[1] - crop_dims[1])
    crops_ix = np.empty((5, 4), dtype=int)
    curr = 0
    for i in h_indices:
        for j in w_indices:
            crops_ix[curr] = (i, j, i + crop_dims[0], j + crop_dims[1])
            curr += 1
    crops_ix[4] = np.tile(im_center, (1, 2)) + np.concatenate(
        [-crop_dims / 2.0, crop_dims / 2.0])
    crops_ix = np.tile(crops_ix, (2, 1))
    crops = np.empty(
        (10 * len(img), crop_dims[0], crop_dims[1], im_shape[-1]),
        dtype=np.float32)
    ix = 0
    for im in img:
        for crop in crops_ix:
            crops[ix] = im[crop[0]:crop[2], crop[1]:crop[3], :]
            ix += 1
        crops[ix - 5:ix] = crops[ix - 5:ix, :, ::-1, :]
    return crops


class ImageTransformer:
    """Channel transpose / swap / mean-subtract pipeline (reference :183)."""

    def __init__(self, transpose=None, channel_swap=None, mean=None,
                 is_color=True):
        self.is_color = is_color
        self.set_transpose(transpose)
        self.set_channel_swap(channel_swap)
        self.set_mean(mean)

    def set_transpose(self, order):
        if order is not None and self.is_color:
            assert len(order) == 3
        self.transpose = order

    def set_channel_swap(self, order):
        if order is not None and self.is_color:
            assert len(order) == 3
        self.channel_swap = order

    def set_mean(self, mean):
        if mean is not None and mean.ndim == 1:
            mean = mean[:, np.newaxis, np.newaxis]
        self.mean = mean

    def transformer(self, data):
        if self.transpose is not None:
            data = data.transpose(self.transpose)
        if self.channel_swap is not None:
            data = data[self.channel_swap, :, :]
        if self.mean is not None:
            data = data.astype('float32')
            data -= self.mean
        return data
