"""Parity: python/paddle/fluid/install_check.py — sanity check the install."""
import numpy as np


def run_check():
    import paddle_tpu as paddle
    from paddle_tpu import nn
    x = paddle.to_tensor(np.random.rand(4, 8).astype('float32'),
                         stop_gradient=False)
    fc = nn.Linear(8, 2)
    loss = (fc(x) ** 2).mean()
    loss.backward()
    assert fc.weight.grad is not None
    import jax
    devs = jax.devices()
    print(f"paddle_tpu is installed successfully! devices: {devs}")
    if len(devs) > 1:
        print(f"multi-device OK: {len(devs)} devices visible")
    return True
