"""Parity: python/paddle/utils/lazy_import.py."""
import importlib


def try_import(module_name, err_msg=None):
    try:
        return importlib.import_module(module_name)
    except ImportError:
        if err_msg is None:
            err_msg = f"Failed importing {module_name}. Install it to use this feature."
        raise ImportError(err_msg)
