"""Profiler. Parity: python/paddle/fluid/profiler.py.

TPU-first: wraps jax.profiler — traces go to TensorBoard-compatible xplane
dumps; scoped annotations map to TraceAnnotation.
"""
import contextlib
import cProfile
import io
import pstats

import jax

__all__ = ['profiler', 'start_profiler', 'stop_profiler', 'profile_scope',
           'annotate', 'get_hlo']

_active = {'dir': None, 'py': None}


def start_profiler(state='All', tracer_option='Default',
                   log_dir='/tmp/paddle_tpu_profile'):
    try:
        jax.profiler.start_trace(log_dir)
        _active['dir'] = log_dir
    except Exception:
        _active['py'] = cProfile.Profile()
        _active['py'].enable()


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    if _active['dir'] is not None:
        jax.profiler.stop_trace()
        print(f"profile trace written to {_active['dir']}")
        _active['dir'] = None
    if _active['py'] is not None:
        _active['py'].disable()
        s = io.StringIO()
        pstats.Stats(_active['py'], stream=s).sort_stats('cumulative') \
            .print_stats(30)
        print(s.getvalue())
        _active['py'] = None


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path='/tmp/profile',
             tracer_option='Default'):
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


profile_scope = profiler


def annotate(name):
    """Named trace region (shows up in xplane/TensorBoard)."""
    return jax.profiler.TraceAnnotation(name)


def get_hlo(fn, *args, optimized=False):
    """Dump HLO for a jitted callable — debugging/tracing parity."""
    lowered = jax.jit(fn).lower(*args)
    if optimized:
        return lowered.compile().as_text()
    return lowered.as_text()
