"""Profiler. Parity: python/paddle/fluid/profiler.py.

TPU-first: wraps jax.profiler — traces go to TensorBoard-compatible xplane
dumps; scoped annotations map to TraceAnnotation.
"""
import contextlib
import cProfile
import io
import os
import pstats

import jax

__all__ = ['profiler', 'start_profiler', 'stop_profiler', 'profile_scope',
           'annotate', 'get_hlo']

_active = {'dir': None, 'py': None}


def start_profiler(state='All', tracer_option='Default',
                   log_dir='/tmp/paddle_tpu_profile'):
    try:
        jax.profiler.start_trace(log_dir)
        _active['dir'] = log_dir
    except Exception:
        _active['py'] = cProfile.Profile()
        _active['py'].enable()


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    """Stop profiling and print a sorted per-op time table (the reference
    profiler.py contract: sorted_key in calls/total/max/min/ave)."""
    if sorted_key not in _SORT_FIELD:
        raise ValueError(
            f"sorted_key must be one of "
            f"{sorted(k for k in _SORT_FIELD if isinstance(k, str))} or "
            f"None, got {sorted_key!r}")
    table = None
    if _active['dir'] is not None:
        jax.profiler.stop_trace()
        log_dir = _active['dir']
        _active['dir'] = None
        print(f"profile trace written to {log_dir}")
        table = _op_summary(log_dir, sorted_key)
        if table:
            print(table)
    # always clear a cProfile fallback too (a failed double-start can leave
    # one enabled alongside an active trace)
    if _active['py'] is not None:
        _active['py'].disable()
        s = io.StringIO()
        pstats.Stats(_active['py'], stream=s).sort_stats('cumulative') \
            .print_stats(30)
        print(s.getvalue())
        _active['py'] = None
    return table


_SORT_FIELD = {'total': 'total_ms', 'calls': 'calls', 'max': 'max_ms',
               'min': 'min_ms', 'ave': 'ave_ms', None: 'total_ms',
               'default': 'total_ms'}


def _op_summary(log_dir, sorted_key=None, limit=40):
    """Aggregate the xplane dump under log_dir into the reference-style
    per-op table string ('Event / Calls / Total / Max / Min / Ave')."""
    import glob
    from . import xplane
    paths = glob.glob(os.path.join(log_dir, '**', '*.xplane.pb'),
                      recursive=True)
    if not paths:
        return None
    # newest dump wins (each start/stop cycle writes a new timestamp dir)
    path = max(paths, key=os.path.getmtime)
    ops = xplane.op_table(path)
    if not ops:
        return None
    field = _SORT_FIELD.get(sorted_key, 'total_ms')
    rows = sorted(ops.items(), key=lambda kv: -kv[1][field])[:limit]
    width = max([len('Event')] + [len(k) for k, _ in rows])
    lines = [f"{'Event':<{width}}  {'Calls':>6} {'Total(ms)':>10} "
             f"{'Max(ms)':>9} {'Min(ms)':>9} {'Ave(ms)':>9}"]
    for op, a in rows:
        lines.append(
            f"{op:<{width}}  {a['calls']:>6} {a['total_ms']:>10.4f} "
            f"{a['max_ms']:>9.4f} {a['min_ms']:>9.4f} {a['ave_ms']:>9.4f}")
    return "\n".join(lines)


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path='/tmp/profile',
             tracer_option='Default'):
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


profile_scope = profiler


def annotate(name):
    """Named trace region (shows up in xplane/TensorBoard)."""
    return jax.profiler.TraceAnnotation(name)


def get_hlo(fn, *args, optimized=False):
    """Dump HLO for a jitted callable — debugging/tracing parity."""
    lowered = jax.jit(fn).lower(*args)
    if optimized:
        return lowered.compile().as_text()
    return lowered.as_text()
