"""Profiler. Parity: python/paddle/fluid/profiler.py.

TPU-first: wraps jax.profiler — traces go to TensorBoard-compatible xplane
dumps; scoped annotations map to TraceAnnotation.
"""
import contextlib
import cProfile
import io
import os
import pstats

import jax

__all__ = ['profiler', 'start_profiler', 'stop_profiler', 'profile_scope',
           'annotate', 'get_hlo']

_active = {'dir': None, 'py': None}


def start_profiler(state='All', tracer_option='Default',
                   log_dir='/tmp/paddle_tpu_profile'):
    from .. import observability as _obs
    try:
        jax.profiler.start_trace(log_dir)
        _active['dir'] = log_dir
        _obs.event('profiler.start_trace', log_dir=log_dir)
    except Exception as e:
        # device trace unavailable (or already running): cProfile fallback
        # still gives a host-side picture. stop_profiler clears BOTH states,
        # so a failed double-start cannot leak an enabled profile.
        _active['py'] = cProfile.Profile()
        _active['py'].enable()
        _obs.event('profiler.fallback_cprofile', error=repr(e))


def stop_profiler(sorted_key=None, profile_path='/tmp/profile'):
    """Stop profiling and print a sorted per-op time table (the reference
    profiler.py contract: sorted_key in calls/total/max/min/ave)."""
    if sorted_key not in _SORT_FIELD:
        raise ValueError(
            f"sorted_key must be one of "
            f"{sorted(k for k in _SORT_FIELD if isinstance(k, str))} or "
            f"None, got {sorted_key!r}")
    table = None
    if _active['dir'] is not None:
        jax.profiler.stop_trace()
        log_dir = _active['dir']
        _active['dir'] = None
        from .. import observability as _obs
        _obs.event('profiler.stop_trace', log_dir=log_dir)
        print(f"profile trace written to {log_dir}")
        table = _op_summary(log_dir, sorted_key)
        if table:
            print(table)
    # always clear a cProfile fallback too (a failed double-start can leave
    # one enabled alongside an active trace)
    if _active['py'] is not None:
        _active['py'].disable()
        s = io.StringIO()
        pstats.Stats(_active['py'], stream=s).sort_stats('cumulative') \
            .print_stats(30)
        print(s.getvalue())
        _active['py'] = None
    return table


_SORT_FIELD = {'total': 'total_ms', 'calls': 'calls', 'max': 'max_ms',
               'min': 'min_ms', 'ave': 'ave_ms', None: 'total_ms',
               'default': 'total_ms'}


def _op_summary(log_dir, sorted_key=None, limit=40):
    """Aggregate the xplane dump under log_dir into the reference-style
    per-op table string ('Event / Calls / Total / Max / Min / Ave')."""
    import glob
    from . import xplane
    paths = glob.glob(os.path.join(log_dir, '**', '*.xplane.pb'),
                      recursive=True)
    if not paths:
        return None
    # newest dump wins (each start/stop cycle writes a new timestamp dir)
    path = max(paths, key=os.path.getmtime)
    ops = xplane.op_table(path)
    if not ops:
        return None
    field = _SORT_FIELD.get(sorted_key, 'total_ms')
    rows = sorted(ops.items(), key=lambda kv: -kv[1][field])[:limit]
    width = max([len('Event')] + [len(k) for k, _ in rows])
    lines = [f"{'Event':<{width}}  {'Calls':>6} {'Total(ms)':>10} "
             f"{'Max(ms)':>9} {'Min(ms)':>9} {'Ave(ms)':>9}"]
    for op, a in rows:
        lines.append(
            f"{op:<{width}}  {a['calls']:>6} {a['total_ms']:>10.4f} "
            f"{a['max_ms']:>9.4f} {a['min_ms']:>9.4f} {a['ave_ms']:>9.4f}")
    return "\n".join(lines)


@contextlib.contextmanager
def profiler(state='All', sorted_key=None, profile_path='/tmp/profile',
             tracer_option='Default'):
    start_profiler(state, tracer_option)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


profile_scope = profiler


def annotate(name):
    """Named trace region. Shows up in the xplane/TensorBoard dump while a
    device trace is active (the observability span bridges into
    ``jax.profiler.TraceAnnotation`` then) AND in the telemetry Chrome trace
    whenever ``PADDLE_TPU_TELEMETRY=1`` — one annotation, both viewers."""
    from .. import observability as _obs
    if _active['dir'] is None and not _obs.enabled():
        # no device trace, no telemetry: keep the raw TraceAnnotation so
        # user-driven jax.profiler workflows see the region regardless
        return jax.profiler.TraceAnnotation(name)
    return _obs.span(name)


def get_hlo(fn, *args, optimized=False):
    """Dump HLO for a jitted callable — debugging/tracing parity."""
    lowered = jax.jit(fn).lower(*args)
    if optimized:
        return lowered.compile().as_text()
    return lowered.as_text()


# -- utils-level Profiler wrapper (parity: python/paddle/utils/profiler.py:
# ProfilerOptions:26, Profiler:63, get_profiler:131) ----------------------
class ProfilerOptions:
    def __init__(self, options=None):
        self.options = {
            'state': 'All',
            'sorted_key': 'default',
            'tracer_level': 'Default',
            'batch_range': [0, 2 ** 31 - 1],
            'output_thread_detail': False,
            'profile_path': 'none',
            'timeline_path': 'none',
            'op_summary_path': 'none',
        }
        if options is not None:
            for key in self.options:
                if options.get(key, None) is not None:
                    self.options[key] = options[key]

    def with_state(self, state):
        self.options['state'] = state
        return self

    def __getitem__(self, name):
        if name not in self.options:
            raise ValueError(
                "ProfilerOptions does not have an option named %s." % name)
        value = self.options[name]
        return None if isinstance(value, str) and value == 'none' else value


_current_profiler = None


class Profiler:
    """Batch-range-aware profiler driver over start/stop_profiler (the
    reference's utils.Profiler contract: context manager + record_step)."""

    def __init__(self, enabled=True, options=None):
        self.profiler_options = (options if options is not None
                                 else ProfilerOptions())
        self.batch_id = 0
        self.enabled = enabled
        self._running = False

    def __enter__(self):
        global _current_profiler
        self.previous_profiler = _current_profiler
        _current_profiler = self
        if self.enabled and self.profiler_options['batch_range'][0] == 0:
            self.start()
        return self

    def __exit__(self, exception_type, exception_value, traceback):
        global _current_profiler
        _current_profiler = self.previous_profiler
        if self.enabled:
            self.stop()

    def start(self):
        if self.enabled and not self._running:
            start_profiler(state=self.profiler_options['state'],
                           tracer_option=self.profiler_options[
                               'tracer_level'])
            self._running = True

    def stop(self):
        if self.enabled and self._running:
            stop_profiler(
                # __getitem__ converts the 'none' sentinel to None for
                # sorted_key the same as every other option
                sorted_key=self.profiler_options['sorted_key'],
                profile_path=self.profiler_options['profile_path']
                or '/tmp/profile')
            self._running = False

    def reset(self):
        """The xplane trace has no in-flight reset: restart the window."""
        if self.enabled and self._running:
            self.stop()
            self.start()

    def record_step(self, change_profiler_status=True):
        if not self.enabled:
            return
        self.batch_id += 1
        if change_profiler_status:
            if self.batch_id == self.profiler_options['batch_range'][0]:
                self.reset() if self._running else self.start()
            if self.batch_id == self.profiler_options['batch_range'][1]:
                self.stop()


def get_profiler():
    global _current_profiler
    if _current_profiler is None:
        _current_profiler = Profiler()
    return _current_profiler


__all__ += ['Profiler', 'ProfilerOptions', 'get_profiler']
