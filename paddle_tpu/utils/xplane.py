"""Minimal XSpace (xplane.pb) parser + per-op aggregation. No TF deps.

Parity context: the reference profiler (python/paddle/fluid/profiler.py)
prints a sorted per-op time table from its C++ event collector. Here the
events come from jax.profiler's TensorBoard xplane dump: on TPU the
device plane's 'XLA Ops' line, on CPU the PjRt client runtime line
(tf_XLAPjRtCpuClient/...). The protobuf wire walking is hand-rolled so no
tensorflow/tensorboard import is needed.
"""
import collections
import struct

__all__ = ['op_table', 'parse_planes']


def _varint(buf, i):
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def _fields(buf, start=0, end=None):
    """Yield (field_no, wire_type, value_or_span) over a message buffer."""
    i = start
    end = len(buf) if end is None else end
    while i < end:
        tag, i = _varint(buf, i)
        fno, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = _varint(buf, i)
            yield fno, wt, v
        elif wt == 2:
            ln, i = _varint(buf, i)
            yield fno, wt, (i, i + ln)
            i += ln
        elif wt == 5:
            yield fno, wt, struct.unpack_from('<f', buf, i)[0]
            i += 4
        elif wt == 1:
            yield fno, wt, struct.unpack_from('<d', buf, i)[0]
            i += 8
        else:
            raise ValueError(f"wire type {wt}")


def parse_planes(path):
    """Yield (plane_name, lines, event_metadata, stat_metadata, buf) per
    XPlane; lines are [(line_name, [event spans])]."""
    with open(path, 'rb') as f:
        buf = f.read()
    for fno, wt, v in _fields(buf):
        if fno != 1 or wt != 2:
            continue
        ps, pe = v
        name = ''
        lines = []
        ev_meta = {}
        stat_meta = {}
        for f1, w1, v1 in _fields(buf, ps, pe):
            if f1 == 2 and w1 == 2:
                name = buf[v1[0]:v1[1]].decode('utf-8', 'replace')
            elif f1 == 3 and w1 == 2:
                lname = ''
                events = []
                for f2, w2, v2 in _fields(buf, v1[0], v1[1]):
                    if f2 == 2 and w2 == 2:
                        lname = buf[v2[0]:v2[1]].decode('utf-8', 'replace')
                    elif f2 == 4 and w2 == 2:
                        events.append(v2)
                lines.append((lname, events))
            elif f1 in (4, 5) and w1 == 2:
                k = None
                span = None
                for f2, w2, v2 in _fields(buf, v1[0], v1[1]):
                    if f2 == 1 and w2 == 0:
                        k = v2
                    elif f2 == 2 and w2 == 2:
                        span = v2
                if span is None:
                    continue
                mname = ''
                for f3, w3, v3 in _fields(buf, span[0], span[1]):
                    if f3 == 2 and w3 == 2:
                        mname = buf[v3[0]:v3[1]].decode('utf-8', 'replace')
                (ev_meta if f1 == 4 else stat_meta)[k] = mname
        yield name, lines, ev_meta, stat_meta, buf


def _is_op_line(plane_name, line_name):
    if line_name == 'XLA Ops':                  # TPU/GPU device planes
        return True
    # CPU runtime thread lines: jax has spelled these tf_XLAPjRtCpuClient,
    # tf_XLATfrtCpuClient, and tf_XLAEigen across releases — match the
    # stable prefix, not one release's runtime name
    return line_name.startswith('tf_XLA')


def op_table(path):
    """Aggregate per-op execution stats across every op line in the dump.

    Returns {op_name: {'total_ms', 'calls', 'max_ms', 'min_ms', 'ave_ms'}}.
    """
    agg = collections.defaultdict(
        lambda: {'total_ms': 0.0, 'calls': 0, 'max_ms': 0.0,
                 'min_ms': float('inf')})
    for name, lines, ev_meta, _stat, buf in parse_planes(path):
        for lname, events in lines:
            if not _is_op_line(name, lname):
                continue
            for (es, ee) in events:
                mid = 0
                dur = 0
                for f2, w2, v2 in _fields(buf, es, ee):
                    if f2 == 1 and w2 == 0:
                        mid = v2
                    elif f2 == 3 and w2 == 0:
                        dur = v2
                op = ev_meta.get(mid, str(mid))
                if op.startswith('end: '):      # CPU runtime end markers
                    continue
                if '::' in op:                  # runtime bookkeeping rows
                    continue                    # (ThunkExecutor::Execute,
                                                # ThreadpoolListener::Record)
                ms = dur / 1e9                  # ps -> ms
                a = agg[op]
                a['total_ms'] += ms
                a['calls'] += 1
                a['max_ms'] = max(a['max_ms'], ms)
                a['min_ms'] = min(a['min_ms'], ms)
    for a in agg.values():
        a['ave_ms'] = a['total_ms'] / a['calls'] if a['calls'] else 0.0
        if a['min_ms'] == float('inf'):
            a['min_ms'] = 0.0
    return dict(agg)
