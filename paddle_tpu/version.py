"""Version info. Parity: python/paddle/version.py (generated)."""
full_version = '1.8.0+tpu.r1'
major, minor, patch = '1', '8', '0'
rc = '0'
istaged = True
commit = 'tpu-native'
with_gpu = 'OFF'
with_tpu = 'ON'


def show():
    print('commit:', commit)
    print('version:', full_version)
    print('with_tpu:', with_tpu)


def mkl():
    return 'OFF'
