"""paddle_tpu.vision. Parity: python/paddle/vision/__init__.py."""
from . import models
from . import datasets
from . import transforms
from . import ops

# 2.0-beta top-level re-exports (reference vision/__init__.py lifts the
# transforms / datasets / models into paddle.vision directly)
from .models import *  # noqa: F401,F403
from .datasets import *  # noqa: F401,F403
from .transforms import *  # noqa: F401,F403
from . import detection_train  # noqa: F401
from .detection_train import *  # noqa: F401,F403
# the star imports rebind the `transforms`/`datasets`/`models` names to
# same-named inner modules; restore the subPACKAGE bindings from
# sys.modules (a `from . import X` would just re-read the clobbered attr)
import sys as _sys  # noqa: E402
models = _sys.modules[__name__ + '.models']
datasets = _sys.modules[__name__ + '.datasets']
transforms = _sys.modules[__name__ + '.transforms']
