"""paddle_tpu.vision. Parity: python/paddle/vision/__init__.py."""
from . import models
from . import datasets
from . import transforms
from . import ops
