"""Vision datasets. Parity: python/paddle/vision/datasets/__init__.py."""
from .mnist import MNIST, FashionMNIST
from .cifar import Cifar10, Cifar100
from .folder import DatasetFolder, ImageFolder
from .flowers import Flowers
from .voc2012 import VOC2012
