"""CIFAR10/100. Parity: python/paddle/vision/datasets/cifar.py.

Local pickle archives if present; deterministic synthetic fallback otherwise.
"""
import os
import pickle
import tarfile

import numpy as np

from ...io import Dataset

__all__ = ['Cifar10', 'Cifar100']


def _synthetic(n, num_classes, seed):
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, num_classes, size=n).astype(np.int64)
    images = np.zeros((n, 32, 32, 3), dtype=np.uint8)
    yy, xx = np.mgrid[0:32, 0:32]
    for i in range(n):
        c = labels[i] % 16
        base = np.stack([
            np.sin(xx * (c + 1) * 0.2),
            np.cos(yy * (c + 2) * 0.2),
            np.sin((xx + yy) * (c + 3) * 0.1)], axis=-1)
        img = (base + 1) / 2 + rng.rand(32, 32, 3) * 0.2
        images[i] = (img / img.max() * 255).astype(np.uint8)
    return images, labels


class Cifar10(Dataset):
    NUM_CLASSES = 10

    def __init__(self, data_file=None, mode='train', transform=None,
                 download=True, backend='cv2'):
        self.mode = mode.lower()
        self.transform = transform
        self.synthetic = False
        root = os.environ.get('PADDLE_TPU_DATA_HOME',
                              os.path.expanduser('~/.cache/paddle_tpu'))
        archive = data_file or os.path.join(
            root, 'cifar',
            'cifar-10-python.tar.gz' if self.NUM_CLASSES == 10 else
            'cifar-100-python.tar.gz')
        if os.path.exists(archive):
            self.images, self.labels = self._load_archive(archive)
        else:
            n = 2048 if self.mode == 'train' else 512
            self.images, self.labels = _synthetic(
                n, self.NUM_CLASSES, 0 if self.mode == 'train' else 1)
            self.synthetic = True

    def _load_archive(self, path):
        images, labels = [], []
        with tarfile.open(path) as tf:
            for member in tf.getmembers():
                name = os.path.basename(member.name)
                want = ('data_batch' in name if self.mode == 'train'
                        else 'test_batch' in name) if self.NUM_CLASSES == 10 \
                    else (name == ('train' if self.mode == 'train' else 'test'))
                if not want:
                    continue
                d = pickle.load(tf.extractfile(member), encoding='bytes')
                images.append(d[b'data'])
                key = b'labels' if b'labels' in d else b'fine_labels'
                labels.extend(d[key])
        data = np.concatenate(images).reshape(-1, 3, 32, 32)
        return data.transpose(0, 2, 3, 1), np.asarray(labels, dtype=np.int64)

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, np.asarray(label, dtype=np.int64)

    def __len__(self):
        return len(self.images)


class Cifar100(Cifar10):
    NUM_CLASSES = 100
