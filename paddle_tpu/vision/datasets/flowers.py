"""Flowers dataset. Parity: python/paddle/vision/datasets/flowers.py.

Synthetic fallback (no network egress in this environment)."""
import numpy as np

from ...io import Dataset
from .cifar import _synthetic

__all__ = ['Flowers']


class Flowers(Dataset):
    def __init__(self, data_file=None, label_file=None, setid_file=None,
                 mode='train', transform=None, download=True, backend='cv2'):
        self.transform = transform
        self.synthetic = True
        n = 1024 if mode == 'train' else 256
        # distinct seed per mode string: valid and test must not be the
        # same byte-for-byte samples
        seed = {'train': 2, 'test': 3, 'valid': 6}.get(mode, 7)
        imgs, labels = _synthetic(n, 102, seed)
        # upsample to a flower-ish resolution
        self.images = np.repeat(np.repeat(imgs, 7, axis=1), 7, axis=2)
        self.labels = labels

    def __getitem__(self, idx):
        img, label = self.images[idx], self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, np.asarray(label, dtype=np.int64)

    def __len__(self):
        return len(self.images)
