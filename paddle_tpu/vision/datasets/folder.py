"""DatasetFolder/ImageFolder. Parity: python/paddle/vision/datasets/folder.py."""
import os

import numpy as np

from ...io import Dataset

__all__ = ['DatasetFolder', 'ImageFolder']

IMG_EXTENSIONS = ('.jpg', '.jpeg', '.png', '.ppm', '.bmp', '.npy')


def _default_loader(path):
    if path.endswith('.npy'):
        return np.load(path)
    try:
        from PIL import Image
        with Image.open(path) as img:
            return np.asarray(img.convert('RGB'))
    except ImportError:
        raise RuntimeError("PIL unavailable; use .npy images")


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or IMG_EXTENSIONS
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.classes = classes
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        self.samples = []
        for c in classes:
            d = os.path.join(root, c)
            for dirpath, _, filenames in sorted(os.walk(d)):
                for fn in sorted(filenames):
                    path = os.path.join(dirpath, fn)
                    ok = is_valid_file(path) if is_valid_file else \
                        fn.lower().endswith(extensions)
                    if ok:
                        self.samples.append((path, self.class_to_idx[c]))

    def __getitem__(self, index):
        path, target = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return sample, target

    def __len__(self):
        return len(self.samples)


class ImageFolder(Dataset):
    def __init__(self, root, loader=None, extensions=None, transform=None,
                 is_valid_file=None):
        self.root = root
        self.transform = transform
        self.loader = loader or _default_loader
        extensions = extensions or IMG_EXTENSIONS
        self.samples = []
        for dirpath, _, filenames in sorted(os.walk(root)):
            for fn in sorted(filenames):
                path = os.path.join(dirpath, fn)
                ok = is_valid_file(path) if is_valid_file else \
                    fn.lower().endswith(extensions)
                if ok:
                    self.samples.append(path)

    def __getitem__(self, index):
        path = self.samples[index]
        sample = self.loader(path)
        if self.transform is not None:
            sample = self.transform(sample)
        return [sample]

    def __len__(self):
        return len(self.samples)
