"""MNIST / FashionMNIST. Parity: python/paddle/vision/datasets/mnist.py.

Reads local IDX files if present (image has no network egress; no download).
Falls back to a deterministic synthetic set so tests and examples run
hermetically — flagged via ``.synthetic``.
"""
import gzip
import os
import struct

import numpy as np

from ...io import Dataset

__all__ = ['MNIST', 'FashionMNIST']


def _load_idx_images(path):
    opener = gzip.open if path.endswith('.gz') else open
    with opener(path, 'rb') as f:
        magic, n, rows, cols = struct.unpack('>IIII', f.read(16))
        data = np.frombuffer(f.read(), dtype=np.uint8)
        return data.reshape(n, rows, cols)


def _load_idx_labels(path):
    opener = gzip.open if path.endswith('.gz') else open
    with opener(path, 'rb') as f:
        magic, n = struct.unpack('>II', f.read(8))
        return np.frombuffer(f.read(), dtype=np.uint8)


def _synthetic_mnist(n, seed):
    """Deterministic digit-like images: class-dependent stripe patterns."""
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.int64)
    images = np.zeros((n, 28, 28), dtype=np.uint8)
    yy, xx = np.mgrid[0:28, 0:28]
    for i in range(n):
        c = labels[i]
        base = (np.sin(xx * (c + 1) * 0.35) * np.cos(yy * (c + 2) * 0.25) + 1)
        noise = rng.rand(28, 28) * 0.3
        img = (base / 2 + noise)
        img = (img / img.max() * 255).astype(np.uint8)
        images[i] = img
    return images, labels


class MNIST(Dataset):
    NAME = 'mnist'

    def __init__(self, image_path=None, label_path=None, mode='train',
                 transform=None, download=True, backend='cv2'):
        self.mode = mode.lower()
        self.transform = transform
        self.synthetic = False
        root = os.environ.get('PADDLE_TPU_DATA_HOME',
                              os.path.expanduser('~/.cache/paddle_tpu'))
        prefix = 'train' if self.mode == 'train' else 't10k'
        candidates = [
            (image_path, label_path),
            (os.path.join(root, self.NAME, f'{prefix}-images-idx3-ubyte.gz'),
             os.path.join(root, self.NAME, f'{prefix}-labels-idx1-ubyte.gz')),
            (os.path.join(root, self.NAME, f'{prefix}-images-idx3-ubyte'),
             os.path.join(root, self.NAME, f'{prefix}-labels-idx1-ubyte')),
        ]
        for ip, lp in candidates:
            if ip and lp and os.path.exists(ip) and os.path.exists(lp):
                self.images = _load_idx_images(ip)
                self.labels = _load_idx_labels(lp).astype(np.int64)
                break
        else:
            n = 2048 if self.mode == 'train' else 512
            self.images, self.labels = _synthetic_mnist(
                n, seed=0 if self.mode == 'train' else 1)
            self.synthetic = True

    def __getitem__(self, idx):
        img = self.images[idx]
        label = self.labels[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32)[None, :, :] / 255.0
        return img, np.asarray(label, dtype=np.int64)

    def __len__(self):
        return len(self.images)


class FashionMNIST(MNIST):
    NAME = 'fashion-mnist'
