"""VOC2012 segmentation. Parity: python/paddle/vision/datasets/voc2012.py.

Synthetic fallback: random images + blob masks."""
import numpy as np

from ...io import Dataset

__all__ = ['VOC2012']


class VOC2012(Dataset):
    def __init__(self, data_file=None, mode='train', transform=None,
                 download=True, backend='cv2'):
        self.transform = transform
        self.synthetic = True
        # distinct seed per mode string (val vs test must differ)
        rng = np.random.RandomState(
            {'train': 4, 'test': 5, 'valid': 8}.get(mode, 9))
        n = 256 if mode == 'train' else 64
        self.images = (rng.rand(n, 128, 128, 3) * 255).astype(np.uint8)
        masks = np.zeros((n, 128, 128), dtype=np.uint8)
        for i in range(n):
            cx, cy = rng.randint(32, 96, 2)
            r = rng.randint(10, 30)
            yy, xx = np.mgrid[0:128, 0:128]
            masks[i][(yy - cy) ** 2 + (xx - cx) ** 2 < r * r] = \
                rng.randint(1, 21)
        self.masks = masks

    def __getitem__(self, idx):
        img, mask = self.images[idx], self.masks[idx]
        if self.transform is not None:
            img = self.transform(img)
        else:
            img = img.astype(np.float32).transpose(2, 0, 1) / 255.0
        return img, mask.astype(np.int64)

    def __len__(self):
        return len(self.images)
