"""Classic detection TRAINING ops (SSD / YOLOv3 / Faster-RCNN era),
TPU-first masked-dense.

Parity: /root/reference/python/paddle/fluid/layers/detection.py
(bipartite_match:1317, target_assign:1402, ssd_loss:1517,
detection_output:620, rpn_target_assign:310, retinanet_target_assign:110,
sigmoid_focal_loss:559, yolov3_loss:1003, matrix_nms:3542,
locality_aware_nms:3438, generate_proposals:2887,
generate_proposal_labels:2464, generate_mask_labels:2606,
polygon_box_transform:957, retinanet_detection_output:3679,
distribute_fpn_proposals:3857, collect_fpn_proposals:3954,
box_decoder_and_assign:3790, multi_box_head:2042) and the C++ kernels under
/root/reference/paddle/fluid/operators/detection/ (bipartite_match_op.cc,
mine_hard_examples_op.cc, yolov3_loss_op.h, matrix_nms_op.cc,
polygon_box_transform_op.cc, sigmoid_focal_loss_op.*).

TPU-first redesign: LoD ground-truth batches become dense padded
(B, G, ...) tensors — a gt row is VALID iff its label >= 0 (or its box has
positive area, matching yolov3_loss_op.h GtValid). Dynamic-size outputs
(sampled fg/bg sets, per-level FPN splits) become fixed-size padded tensors
plus counts/weights. Host-side sampling generators
(generate_proposal_labels / generate_mask_labels) run eagerly in numpy —
the reference also pins those ops to CPU.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op, to_tensor
from ..tensor._helpers import _t
from .ops import _pairwise_iou, box_coder, multiclass_nms, _nms_single

__all__ = [
    'bipartite_match', 'target_assign', 'ssd_loss', 'detection_output',
    'rpn_target_assign', 'retinanet_target_assign', 'sigmoid_focal_loss',
    'yolov3_loss', 'matrix_nms', 'locality_aware_nms', 'polygon_box_transform',
    'generate_proposals', 'generate_proposal_labels', 'generate_mask_labels',
    'retinanet_detection_output', 'distribute_fpn_proposals',
    'collect_fpn_proposals', 'box_decoder_and_assign', 'multi_box_head',
    'roi_perspective_transform', 'roi_pool', 'psroi_pool', 'prroi_pool',
    'deformable_conv', 'deformable_roi_pooling',
]


# ---------------------------------------------------------------------------
# matching / target assignment
# ---------------------------------------------------------------------------

def _bipartite_match_single(dist, valid_rows):
    """Greedy bipartite match (bipartite_match_op.cc BipartiteMatch): pick
    the global max repeatedly, retiring its row and column. dist: (G, P);
    valid_rows: (G,) bool. Returns (match (P,), matched_dist (P,))."""
    G, P = dist.shape
    NEG = jnp.asarray(-1e30, dist.dtype)
    d0 = jnp.where(valid_rows[:, None], dist, NEG)

    def body(carry, _):
        d, match, mdist = carry
        flat = jnp.argmax(d)
        g, p = flat // P, flat % P
        best = d[g, p]
        ok = best > NEG / 2
        match = jnp.where(ok, match.at[p].set(g.astype(jnp.int32)), match)
        mdist = jnp.where(ok, mdist.at[p].set(dist[g, p]), mdist)
        d = jnp.where(ok, d.at[g, :].set(NEG).at[:, p].set(NEG), d)
        return (d, match, mdist), None

    init = (d0, jnp.full((P,), -1, jnp.int32), jnp.zeros((P,), dist.dtype))
    (d, match, mdist), _ = jax.lax.scan(body, init, None, length=G)
    return match, mdist


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    """Greedy bipartite matching (detection.py:1317). dist_matrix:
    (B, G, P) or (G, P) similarity; returns (match_indices (B, P) int32
    with -1 for unmatched, matched_distance (B, P)). match_type
    'per_prediction' additionally matches any unmatched column to its
    argmax row when that distance >= dist_threshold (default 0.5)."""
    d = _t(dist_matrix)
    squeeze = d.ndim == 2
    thr = 0.5 if dist_threshold is None else float(dist_threshold)
    per_pred = match_type == 'per_prediction'

    def fn(dv):
        if dv.ndim == 2:
            dv = dv[None]

        def one(dmat):
            valid = jnp.any(dmat > 0, axis=1)
            match, mdist = _bipartite_match_single(dmat, valid)
            if per_pred:
                best_row = jnp.argmax(
                    jnp.where(valid[:, None], dmat, -jnp.inf), axis=0)
                best_val = jnp.max(
                    jnp.where(valid[:, None], dmat, -jnp.inf), axis=0)
                extra = (match < 0) & (best_val >= thr)
                match = jnp.where(extra, best_row.astype(jnp.int32), match)
                mdist = jnp.where(extra, best_val, mdist)
            return match, mdist

        m, md = jax.vmap(one)(dv)
        return m, md

    m, md = apply_op(fn, (d,), n_outputs=2, differentiable=False)
    if squeeze:
        return m, md
    return m, md


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=None, name=None):
    """Gather targets by match indices (detection.py:1402). input:
    (B, G, K) per-image candidate rows; matched_indices: (B, P) row index
    or -1. out[b, p] = input[b, match[b, p]] (mismatch_value rows where
    unmatched), weight 1.0 where matched else 0.0. negative_indices
    (B, P) bool/0-1 mask (dense replacement of the reference's LoD neg-index
    list) forces weight 1 with mismatch_value content."""
    x = _t(input)
    mi = _t(matched_indices)
    mm = 0.0 if mismatch_value is None else float(mismatch_value)
    tensors = [x, mi]
    if negative_indices is not None:
        tensors.append(_t(negative_indices))

    def fn(xv, mv, *rest):
        midx = mv.astype(jnp.int32)
        matched = midx >= 0
        safe = jnp.maximum(midx, 0)
        out = jnp.take_along_axis(xv, safe[..., None], axis=1)
        out = jnp.where(matched[..., None], out,
                        jnp.asarray(mm, xv.dtype))
        w = matched.astype(xv.dtype)[..., None]
        if rest:
            neg = rest[0] != 0
            w = jnp.maximum(w, neg.astype(xv.dtype)[..., None])
        return out, w

    return apply_op(fn, tuple(tensors), n_outputs=2, differentiable=False)


# ---------------------------------------------------------------------------
# SSD loss
# ---------------------------------------------------------------------------

def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type='per_prediction',
             mining_type='max_negative', normalize=True, sample_size=None):
    """Full SSD multibox loss (detection.py:1517): IoU -> bipartite match ->
    hard-negative mining (mine_hard_examples_op.cc max_negative) -> smooth-L1
    loc + softmax conf, normalized by the number of matched priors.

    Dense contract: gt_box (B, G, 4) normalized xyxy padded with zero-area
    rows; gt_label (B, G) or (B, G, 1) padded with -1. location
    (B, P, 4), confidence (B, P, C), prior_box (P, 4). Returns (B, 1).
    """
    if mining_type != 'max_negative':
        raise ValueError("Only mining_type='max_negative' is supported "
                         "(same restriction as the reference)")
    loc = _t(location)
    conf = _t(confidence)
    gb = _t(gt_box)
    gl = _t(gt_label)
    pb = _t(prior_box)
    pbv = _t(prior_box_var) if prior_box_var is not None else None
    thr = overlap_threshold if overlap_threshold is not None else 0.5

    tensors = [loc, conf, gb, gl, pb] + ([pbv] if pbv is not None else [])

    def fn(locv, confv, gbv, glv, pbv_, *rest):
        varv = rest[0] if rest else None
        B, P, C = confv.shape
        glv = glv.reshape(B, -1).astype(jnp.int32)
        G = glv.shape[1]
        area = (gbv[..., 2] - gbv[..., 0]) * (gbv[..., 3] - gbv[..., 1])
        valid = (glv >= 0) & (area > 0)

        def one(loc_i, conf_i, gt_i, lab_i, val_i):
            iou = jnp.where(val_i[:, None],
                            _pairwise_iou(gt_i, pbv_), 0.0)   # (G, P)
            match, mdist = _bipartite_match_single(iou, val_i)
            if match_type == 'per_prediction':
                best_row = jnp.argmax(
                    jnp.where(val_i[:, None], iou, -jnp.inf), axis=0)
                best_val = jnp.max(
                    jnp.where(val_i[:, None], iou, -jnp.inf), axis=0)
                extra = (match < 0) & (best_val >= thr)
                match = jnp.where(extra, best_row.astype(jnp.int32), match)
                mdist = jnp.where(extra, best_val, mdist)
            pos = match >= 0
            n_pos = pos.sum()

            # conf loss vs target labels (background where unmatched)
            safe = jnp.maximum(match, 0)
            t_label = jnp.where(pos, lab_i[safe], background_label)
            logp = jax.nn.log_softmax(conf_i, axis=-1)
            conf_l = -jnp.take_along_axis(logp, t_label[:, None],
                                          axis=1)[:, 0]          # (P,)

            # hard negative mining: candidates are unmatched priors with
            # matched_dist < neg_overlap, ranked by conf loss
            neg_cand = (~pos) & (mdist < neg_overlap)
            n_neg = jnp.minimum(
                (n_pos * neg_pos_ratio).astype(jnp.int32),
                neg_cand.sum().astype(jnp.int32))
            if sample_size is not None:
                n_neg = jnp.minimum(n_neg, int(sample_size))
            cand_loss = jnp.where(neg_cand, conf_l, -jnp.inf)
            order = jnp.argsort(-cand_loss)
            rank = jnp.zeros((P,), jnp.int32).at[order].set(
                jnp.arange(P, dtype=jnp.int32))
            neg_sel = neg_cand & (rank < n_neg)

            conf_w = pos.astype(locv.dtype) + neg_sel.astype(locv.dtype)

            # loc loss: smooth-L1 vs encoded gt offsets on positives
            gt_m = gt_i[safe]                                    # (P, 4)
            pw = pbv_[:, 2] - pbv_[:, 0]
            ph = pbv_[:, 3] - pbv_[:, 1]
            px = (pbv_[:, 0] + pbv_[:, 2]) * 0.5
            py = (pbv_[:, 1] + pbv_[:, 3]) * 0.5
            gw = jnp.maximum(gt_m[:, 2] - gt_m[:, 0], 1e-9)
            gh = jnp.maximum(gt_m[:, 3] - gt_m[:, 1], 1e-9)
            gx = (gt_m[:, 0] + gt_m[:, 2]) * 0.5
            gy = (gt_m[:, 1] + gt_m[:, 3]) * 0.5
            v = varv if varv is not None else \
                jnp.full((P, 4), 1.0, locv.dtype)
            t0 = (gx - px) / jnp.maximum(pw, 1e-9) / v[:, 0]
            t1 = (gy - py) / jnp.maximum(ph, 1e-9) / v[:, 1]
            t2 = jnp.log(gw / jnp.maximum(pw, 1e-9)) / v[:, 2]
            t3 = jnp.log(gh / jnp.maximum(ph, 1e-9)) / v[:, 3]
            target = jnp.stack([t0, t1, t2, t3], axis=1)
            diff = loc_i - target
            ad = jnp.abs(diff)
            sl1 = jnp.where(ad < 1.0, 0.5 * diff * diff, ad - 0.5).sum(1)
            loc_w = pos.astype(locv.dtype)

            total = (conf_loss_weight * conf_l * conf_w +
                     loc_loss_weight * sl1 * loc_w)
            loss_i = total.sum()
            if normalize:
                loss_i = loss_i / jnp.maximum(loc_w.sum(), 1.0)
            return loss_i

        losses = jax.vmap(one)(locv, confv, gbv, glv, valid)
        return losses[:, None]

    return apply_op(fn, tuple(tensors))


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     return_index=False):
    """Decode + multiclass NMS (detection.py:620). loc: (B, P, 4) deltas;
    scores: (B, P, C); returns the padded (B, keep_top_k, 6) NMS output
    (+ counts via multiclass_nms contract)."""
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type='decode_center_size', axis=0)
    from ..tensor.manipulation import transpose
    sc = transpose(scores, [0, 2, 1])       # (B, C, P)
    return multiclass_nms(decoded, sc, score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold,
                          background_label=background_label,
                          nms_eta=nms_eta, return_index=return_index)


# ---------------------------------------------------------------------------
# focal loss + RPN / RetinaNet target assign
# ---------------------------------------------------------------------------

def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25):
    """Per-element focal loss (sigmoid_focal_loss_op.h): x (N, C) logits,
    label (N, 1) in [0, C] (0 = background; class c hits column c-1),
    normalized by fg_num. Returns (N, C)."""
    def fn(xv, lv, fg):
        N, C = xv.shape
        lab = lv.reshape(-1).astype(jnp.int32)
        c_idx = jnp.arange(1, C + 1)[None, :]
        t = (lab[:, None] == c_idx).astype(xv.dtype)
        p = jax.nn.sigmoid(xv)
        ce = jnp.maximum(xv, 0.0) - xv * t + jnp.log1p(
            jnp.exp(-jnp.abs(xv)))
        p_t = p * t + (1.0 - p) * (1.0 - t)
        a_t = alpha * t + (1.0 - alpha) * (1.0 - t)
        loss = a_t * ((1.0 - p_t) ** gamma) * ce
        return loss / jnp.maximum(fg.astype(xv.dtype).reshape(()), 1.0)

    return apply_op(fn, (_t(x), _t(label), _t(fg_num)))


def _label_anchors(anchors, gt, valid_gt, pos_thr, neg_thr):
    """Shared anchor labeling: 1 fg / 0 bg / -1 ignore, plus matched gt
    index. Every gt's best anchor is forced fg (the rpn_target_assign
    rule)."""
    iou = jnp.where(valid_gt[:, None], _pairwise_iou(gt, anchors), 0.0)
    best_gt = jnp.argmax(iou, axis=0)                  # per anchor
    best_iou = jnp.max(iou, axis=0)
    labels = jnp.full((anchors.shape[0],), -1, jnp.int32)
    labels = jnp.where(best_iou < neg_thr, 0, labels)
    labels = jnp.where(best_iou >= pos_thr, 1, labels)
    # force each valid gt's argmax anchor to fg
    gt_best_anchor = jnp.argmax(iou, axis=1)           # (G,)
    force = jnp.zeros((anchors.shape[0],), bool).at[gt_best_anchor].set(
        valid_gt)
    labels = jnp.where(force, 1, labels)
    return labels, best_gt.astype(jnp.int32), best_iou


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True):
    """RPN anchor target assignment (detection.py:310). Dense contract:
    one image — bbox_pred (A, 4), cls_logits (A, 1), anchor_box (A, 4),
    gt_boxes (G, 4) zero-area-padded. Returns fixed-size
    (score_pred (S, 1), loc_pred (S, 4), score_target (S, 1),
    loc_target (S, 4), bbox_inside_weight (S, 4)) with
    S = rpn_batch_size_per_im; rows beyond the sampled count have zero
    weight. Sampling is deterministic top-ranked (use_random is accepted
    but maps to deterministic selection — seeded subsample on TPU would
    recompile per seed)."""
    bp = _t(bbox_pred)
    cl = _t(cls_logits)
    an = _t(anchor_box)
    gb = _t(gt_boxes)
    S = int(rpn_batch_size_per_im)

    def fn(bpv, clv, anv, gbv):
        A = anv.shape[0]
        area = (gbv[:, 2] - gbv[:, 0]) * (gbv[:, 3] - gbv[:, 1])
        valid = area > 0
        labels, matched, best_iou = _label_anchors(
            anv, gbv, valid, rpn_positive_overlap, rpn_negative_overlap)
        n_fg_cap = int(rpn_fg_fraction * S)
        fg = labels == 1
        bg = labels == 0
        # rank fg by IoU desc, bg by IoU asc; take caps
        fg_order = jnp.argsort(-jnp.where(fg, best_iou, -jnp.inf))
        n_fg = jnp.minimum(fg.sum(), n_fg_cap).astype(jnp.int32)
        bg_order = jnp.argsort(jnp.where(bg, best_iou, jnp.inf))
        n_bg = jnp.minimum(bg.sum().astype(jnp.int32), S - n_fg)

        slots = jnp.arange(S)
        take_fg = slots < n_fg
        idx = jnp.where(take_fg, fg_order[jnp.minimum(slots, A - 1)],
                        bg_order[jnp.minimum(
                            jnp.maximum(slots - n_fg, 0), A - 1)])
        used = slots < (n_fg + n_bg)
        sel_lab = jnp.where(take_fg, 1, 0)

        score_pred = clv[idx]
        loc_pred = bpv[idx]
        score_tgt = sel_lab[:, None].astype(jnp.int32)
        # loc targets: encode matched gt vs anchor (center-size)
        a = anv[idx]
        g = gbv[jnp.clip(matched[idx], 0, gbv.shape[0] - 1)]
        aw = jnp.maximum(a[:, 2] - a[:, 0], 1e-9)
        ah = jnp.maximum(a[:, 3] - a[:, 1], 1e-9)
        ax = (a[:, 0] + a[:, 2]) * 0.5
        ay = (a[:, 1] + a[:, 3]) * 0.5
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-9)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-9)
        gx = (g[:, 0] + g[:, 2]) * 0.5
        gy = (g[:, 1] + g[:, 3]) * 0.5
        loc_tgt = jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                             jnp.log(gw / aw), jnp.log(gh / ah)], axis=1)
        w = (take_fg & used).astype(bpv.dtype)[:, None]
        inside_w = jnp.broadcast_to(w, (S, 4))
        loc_tgt = loc_tgt * w
        return score_pred, loc_pred, score_tgt, loc_tgt, inside_w

    return apply_op(fn, (bp, cl, an, gb), n_outputs=5,
                    differentiable=False)


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None, im_info=None,
                            num_classes=1, positive_overlap=0.5,
                            negative_overlap=0.4):
    """RetinaNet target assignment (detection.py:110): every anchor is
    used (no subsample); returns (score_pred, loc_pred, score_target,
    loc_target, bbox_inside_weight, fg_num). score_target is the CLASS id
    (0 bg, 1..K fg); anchors in the ignore band get weight 0 via
    bbox_inside_weight's first column semantics — here all A rows are kept
    (dense) with inside weights zeroed for non-fg."""
    bp, cl, an, gb, glab = (_t(bbox_pred), _t(cls_logits), _t(anchor_box),
                            _t(gt_boxes), _t(gt_labels))

    def fn(bpv, clv, anv, gbv, glv):
        A = anv.shape[0]
        area = (gbv[:, 2] - gbv[:, 0]) * (gbv[:, 3] - gbv[:, 1])
        valid = area > 0
        labels, matched, best_iou = _label_anchors(
            anv, gbv, valid, positive_overlap, negative_overlap)
        fg = labels == 1
        cls_t = jnp.where(fg, glv.reshape(-1)[
            jnp.clip(matched, 0, glv.size - 1)].astype(jnp.int32), 0)
        a = anv
        g = gbv[jnp.clip(matched, 0, gbv.shape[0] - 1)]
        aw = jnp.maximum(a[:, 2] - a[:, 0], 1e-9)
        ah = jnp.maximum(a[:, 3] - a[:, 1], 1e-9)
        ax = (a[:, 0] + a[:, 2]) * 0.5
        ay = (a[:, 1] + a[:, 3]) * 0.5
        gw = jnp.maximum(g[:, 2] - g[:, 0], 1e-9)
        gh = jnp.maximum(g[:, 3] - g[:, 1], 1e-9)
        gx = (g[:, 0] + g[:, 2]) * 0.5
        gy = (g[:, 1] + g[:, 3]) * 0.5
        loc_t = jnp.stack([(gx - ax) / aw, (gy - ay) / ah,
                           jnp.log(gw / aw), jnp.log(gh / ah)], axis=1)
        w = fg.astype(bpv.dtype)[:, None]
        fg_num = fg.sum().astype(jnp.int32).reshape(1, 1)
        return (clv, bpv, cls_t[:, None], loc_t * w,
                jnp.broadcast_to(w, (A, 4)), fg_num)

    return apply_op(fn, (bp, cl, an, gb, glab), n_outputs=6,
                    differentiable=False)


# ---------------------------------------------------------------------------
# YOLOv3 loss (vectorized port of yolov3_loss_op.h)
# ---------------------------------------------------------------------------

def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None, scale_x_y=1.0):
    """YOLOv3 training loss (yolov3_loss_op.h, exact algorithm):
    x (B, M*(5+K), H, W); gt_box (B, G, 4) cxcywh normalized, zero-area
    padded; gt_label (B, G) int. Returns per-image loss (B,)."""
    xv_ = _t(x)
    gb = _t(gt_box)
    gl = _t(gt_label)
    anchors = [int(a) for a in anchors]
    anchor_mask = [int(a) for a in anchor_mask]
    an_num = len(anchors) // 2
    mask_num = len(anchor_mask)
    K = int(class_num)
    tensors = [xv_, gb, gl]
    if gt_score is not None:
        tensors.append(_t(gt_score))

    def fn(xv, gbv, glv, *rest):
        B, C, H, W = xv.shape
        input_size = downsample_ratio * H
        scale = scale_x_y
        bias = -0.5 * (scale - 1.0)
        G = gbv.shape[1]
        score = rest[0] if rest else jnp.ones((B, G), xv.dtype)
        x5 = xv.reshape(B, mask_num, 5 + K, H, W)
        glv = glv.reshape(B, G).astype(jnp.int32)

        anc = jnp.asarray(anchors, xv.dtype).reshape(an_num, 2)
        mask_anc = anc[jnp.asarray(anchor_mask)]           # (M, 2)

        if use_label_smooth:
            sw = min(1.0 / K, 1.0 / 40)
            pos_l, neg_l = 1.0 - sw, sw
        else:
            pos_l, neg_l = 1.0, 0.0

        def sce(z, t):
            return jnp.maximum(z, 0.0) - z * t + jnp.log1p(
                jnp.exp(-jnp.abs(z)))

        def one(xi, gti, labi, sci):
            valid = (gti[:, 2] > 1e-6) & (gti[:, 3] > 1e-6)
            # --- decode all predicted boxes (M, H, W) ---
            gx = jnp.arange(W, dtype=xi.dtype)[None, None, :]
            gy = jnp.arange(H, dtype=xi.dtype)[None, :, None]
            px = (gx + jax.nn.sigmoid(xi[:, 0]) * scale + bias) / W
            py = (gy + jax.nn.sigmoid(xi[:, 1]) * scale + bias) / H
            pw = jnp.exp(xi[:, 2]) * mask_anc[:, 0][:, None, None] \
                / input_size
            ph = jnp.exp(xi[:, 3]) * mask_anc[:, 1][:, None, None] \
                / input_size

            # IoU of every pred vs every gt (cxcywh)
            def iou_cxcywh(x1, y1, w1, h1, x2, y2, w2, h2):
                iw = jnp.minimum(x1 + w1 / 2, x2 + w2 / 2) - \
                    jnp.maximum(x1 - w1 / 2, x2 - w2 / 2)
                ih = jnp.minimum(y1 + h1 / 2, y2 + h2 / 2) - \
                    jnp.maximum(y1 - h1 / 2, y2 - h2 / 2)
                inter = jnp.where((iw < 0) | (ih < 0), 0.0, iw * ih)
                return inter / jnp.maximum(w1 * h1 + w2 * h2 - inter, 1e-10)

            ious = jax.vmap(
                lambda g: iou_cxcywh(px, py, pw, ph,
                                     g[0], g[1], g[2], g[3]))(gti)  # (G,M,H,W)
            ious = jnp.where(valid[:, None, None, None], ious, 0.0)
            best_iou = ious.max(axis=0)                     # (M, H, W)
            ignore = best_iou > ignore_thresh

            # --- per-gt best anchor over ALL anchors (shifted IoU) ---
            inter_w = jnp.minimum(anc[None, :, 0] / input_size,
                                  gti[:, None, 2])
            inter_h = jnp.minimum(anc[None, :, 1] / input_size,
                                  gti[:, None, 3])
            inter = inter_w * inter_h
            union = (anc[None, :, 0] * anc[None, :, 1] / input_size ** 2 +
                     (gti[:, 2] * gti[:, 3])[:, None] - inter)
            an_iou = inter / jnp.maximum(union, 1e-10)       # (G, an_num)
            best_n = jnp.argmax(an_iou, axis=1)              # (G,)
            mask_lookup = jnp.full((an_num,), -1, jnp.int32)
            for mi, a in enumerate(anchor_mask):
                mask_lookup = mask_lookup.at[a].set(mi)
            mask_idx = mask_lookup[best_n]                   # (G,)
            resp = valid & (mask_idx >= 0)

            gi = jnp.clip((gti[:, 0] * W).astype(jnp.int32), 0, W - 1)
            gj = jnp.clip((gti[:, 1] * H).astype(jnp.int32), 0, H - 1)
            mi_safe = jnp.clip(mask_idx, 0, mask_num - 1)

            # gather predictions at responsible cells (G, 5+K)
            pred = x5_i = xi[mi_safe, :, gj, gi]             # (G, 5+K)
            tx = gti[:, 0] * W - gi
            ty = gti[:, 1] * H - gj
            tw = jnp.log(jnp.maximum(
                gti[:, 2] * input_size, 1e-9) /
                anc[jnp.clip(best_n, 0, an_num - 1), 0])
            th = jnp.log(jnp.maximum(
                gti[:, 3] * input_size, 1e-9) /
                anc[jnp.clip(best_n, 0, an_num - 1), 1])
            lscale = (2.0 - gti[:, 2] * gti[:, 3]) * sci
            loc = (sce(pred[:, 0], tx) + sce(pred[:, 1], ty) +
                   jnp.abs(pred[:, 2] - tw) + jnp.abs(pred[:, 3] - th))
            loc_loss = jnp.where(resp, loc * lscale, 0.0).sum()

            cls_t = (jnp.arange(K)[None, :] ==
                     labi[:, None]).astype(xi.dtype)
            cls_t = cls_t * pos_l + (1 - cls_t) * neg_l
            cls = sce(pred[:, 5:], cls_t).sum(axis=1)
            cls_loss = jnp.where(resp, cls * sci, 0.0).sum()

            # objness mask: score at responsible cells, -1 at ignored
            obj = jnp.zeros((mask_num, H, W), xi.dtype)
            obj = jnp.where(ignore, -1.0, obj)
            obj = obj.at[mi_safe, gj, gi].set(
                jnp.where(resp, sci, obj[mi_safe, gj, gi]))
            po = xi[:, 4]
            obj_loss = jnp.where(
                obj > 1e-5, sce(po, 1.0) * obj,
                jnp.where(obj > -0.5, sce(po, 0.0), 0.0)).sum()

            return loc_loss + cls_loss + obj_loss

        return jax.vmap(one)(x5, gbv, glv, score)

    return apply_op(fn, tuple(tensors))


# ---------------------------------------------------------------------------
# matrix / locality-aware NMS, polygon transform
# ---------------------------------------------------------------------------

def matrix_nms(bboxes, scores, score_threshold, post_threshold=0.0,
               nms_top_k=400, keep_top_k=200, use_gaussian=False,
               gaussian_sigma=2.0, background_label=0, normalized=True,
               return_index=False, return_rois_num=True, name=None):
    """Matrix NMS (matrix_nms_op.cc / SOLOv2): per class, sort by score,
    decay_j = min_i f(iou_ij)/f(max-overlap_i); suppression is a score
    decay instead of a hard drop. bboxes (B, M, 4); scores (B, C, M).
    Returns padded (B, keep_top_k, 6) [label, score, x1 y1 x2 y2] with -1
    pad rows, plus valid counts (B,)."""
    bb = _t(bboxes)
    sc = _t(scores)

    def fn(bv, sv):
        B, M, _ = bv.shape
        C = sv.shape[1]
        k = min(nms_top_k, M)

        def per_image(boxes, scores_cm):
            if background_label >= 0:
                scores_cm = scores_cm.at[background_label].set(-jnp.inf)

            def per_class(s_c):
                order = jnp.argsort(-s_c)[:k]
                s = s_c[order]
                b = boxes[order]
                live = s > score_threshold
                iou = _pairwise_iou(b, b)
                tri = jnp.tril(jnp.ones((k, k), bool), -1)  # i < j pairs
                iou = jnp.where(tri.T, iou, 0.0)            # iou[i, j], i<j
                max_over = jnp.max(iou, axis=0)             # per j: max iou
                comp = jnp.max(iou * tri.T, axis=0)
                # per i: its own max overlap with any higher-scored box
                iou_cmax = jnp.max(jnp.where(tri, iou.T, 0.0), axis=1)
                if use_gaussian:
                    decay = jnp.exp(-(iou ** 2 - iou_cmax[:, None] ** 2)
                                    / gaussian_sigma)
                else:
                    decay = (1.0 - iou) / jnp.maximum(
                        1.0 - iou_cmax[:, None], 1e-10)
                decay = jnp.where(tri.T, decay, jnp.inf)
                decay_j = jnp.min(decay, axis=0)
                decay_j = jnp.where(jnp.isinf(decay_j), 1.0, decay_j)
                new_s = jnp.where(live, s * decay_j, -jnp.inf)
                new_s = jnp.where(new_s > post_threshold, new_s, -jnp.inf)
                return new_s, b

            cls_scores, cls_boxes = jax.vmap(per_class)(scores_cm)
            flat_s = cls_scores.reshape(-1)
            flat_b = cls_boxes.reshape(-1, 4)
            labels = jnp.repeat(jnp.arange(C), k)
            kk = min(keep_top_k, flat_s.shape[0])
            top = jnp.argsort(-flat_s)[:kk]
            s = flat_s[top]
            ok = jnp.isfinite(s)
            out = jnp.concatenate([
                jnp.where(ok, labels[top], -1).astype(bv.dtype)[:, None],
                jnp.where(ok, s, -1.0)[:, None],
                jnp.where(ok[:, None], flat_b[top], -1.0)], axis=1)
            return out, ok.sum().astype(jnp.int32)

        return jax.vmap(per_image)(bv, sv)

    out, counts = apply_op(fn, (bb, sc), n_outputs=2, differentiable=False)
    if return_rois_num:
        return out, counts
    return out


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    """Locality-aware NMS (EAST text detection, detection.py:3438): boxes
    overlapping above the threshold are first MERGED by score-weighted
    average, then standard multiclass NMS runs. Dense redesign: each box is
    merged with all boxes it overlaps (one pass), then NMS."""
    bb = _t(bboxes)
    sc = _t(scores)

    def fn(bv, sv):
        def per_image(boxes, scores_cm):
            s = jnp.max(scores_cm, axis=0)               # (M,)
            iou = _pairwise_iou(boxes, boxes)
            near = (iou >= nms_threshold) & (s[None, :] > score_threshold)
            w = jnp.where(near, s[None, :], 0.0)
            denom = jnp.maximum(w.sum(axis=1, keepdims=True), 1e-10)
            merged = (w @ boxes) / denom
            keep_orig = s[:, None] <= 0
            return jnp.where(keep_orig, boxes, merged)

        merged = jax.vmap(per_image)(bv, sv)
        return merged

    merged = apply_op(fn, (bb, sc), differentiable=False)
    return multiclass_nms(merged, sc, score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold, normalized=normalized,
                          nms_eta=nms_eta, background_label=background_label)


def polygon_box_transform(input, name=None):
    """Offset-to-coordinate transform (polygon_box_transform_op.cc): for
    channel c at (h, w): out = (w if c even else h) * 4 - in."""
    def fn(v):
        B, C, H, W = v.shape
        widx = jnp.arange(W, dtype=v.dtype)[None, None, None, :]
        hidx = jnp.arange(H, dtype=v.dtype)[None, None, :, None]
        even = (jnp.arange(C) % 2 == 0)[None, :, None, None]
        base = jnp.where(even, widx * 4.0, hidx * 4.0)
        return base - v

    return apply_op(fn, (_t(input),))


# ---------------------------------------------------------------------------
# proposal generation (RPN) + FPN routing
# ---------------------------------------------------------------------------

def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=True, name=None):
    """RPN proposal generation (detection.py:2887): decode anchor deltas,
    clip to image, drop tiny boxes, top-k, NMS. Dense contract: scores
    (B, A, H, W); bbox_deltas (B, 4A, H, W); anchors/variances (H, W, A, 4)
    or (A', 4). Returns (rois (B, post_nms_top_n, 4), roi_probs
    (B, post_nms_top_n, 1)[, rois_num (B,)]) — fixed shape, zero rows past
    each image's count."""
    sc = _t(scores)
    bd = _t(bbox_deltas)
    im = _t(im_info)
    an = _t(anchors)
    va = _t(variances)

    def fn(sv, dv, imv, anv, vav):
        B = sv.shape[0]
        A4 = anv.reshape(-1, 4)
        V4 = vav.reshape(-1, 4)
        N = A4.shape[0]
        pre = min(pre_nms_top_n, N)
        post = min(post_nms_top_n, pre)

        def one(s_i, d_i, info):
            s = s_i.transpose(1, 2, 0).reshape(-1)       # (H*W*A,)
            d = d_i.reshape(-1, 4, *d_i.shape[1:3]) if False else \
                d_i.transpose(1, 2, 0).reshape(-1, 4)
            # decode center-size with variances
            aw = A4[:, 2] - A4[:, 0] + 1.0
            ah = A4[:, 3] - A4[:, 1] + 1.0
            ax = A4[:, 0] + aw * 0.5
            ay = A4[:, 1] + ah * 0.5
            cx = V4[:, 0] * d[:, 0] * aw + ax
            cy = V4[:, 1] * d[:, 1] * ah + ay
            w = jnp.exp(jnp.minimum(V4[:, 2] * d[:, 2],
                                    math.log(1000.0 / 16))) * aw
            h = jnp.exp(jnp.minimum(V4[:, 3] * d[:, 3],
                                    math.log(1000.0 / 16))) * ah
            boxes = jnp.stack([cx - w / 2, cy - h / 2,
                               cx + w / 2, cy + h / 2], axis=1)
            H_im, W_im = info[0], info[1]
            boxes = jnp.stack([
                jnp.clip(boxes[:, 0], 0, W_im - 1),
                jnp.clip(boxes[:, 1], 0, H_im - 1),
                jnp.clip(boxes[:, 2], 0, W_im - 1),
                jnp.clip(boxes[:, 3], 0, H_im - 1)], axis=1)
            ms = min_size * info[2]
            keep = ((boxes[:, 2] - boxes[:, 0] + 1 >= ms) &
                    (boxes[:, 3] - boxes[:, 1] + 1 >= ms))
            s = jnp.where(keep, s, -jnp.inf)
            top = jnp.argsort(-s)[:pre]
            tb, ts = boxes[top], s[top]
            order, alive = _nms_single(tb, ts, nms_thresh, post,
                                       -jnp.inf, False)
            rb = jnp.where(alive[:, None], tb[order], 0.0)
            rs = jnp.where(alive, ts[order], 0.0)
            return rb, rs[:, None], alive.sum().astype(jnp.int32)

        rois, probs, counts = jax.vmap(one)(sv, dv, imv)
        return rois, probs, counts

    rois, probs, counts = apply_op(fn, (sc, bd, im, an, va), n_outputs=3,
                                   differentiable=False)
    if return_rois_num:
        return rois, probs, counts
    return rois, probs


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    """Route RoIs to FPN levels by scale (detection.py:3857):
    level = floor(log2(sqrt(area) / refer_scale + 1e-6)) + refer_level,
    clipped to [min_level, max_level]. Dense: returns one (R, 4) tensor per
    level with non-member rows zeroed, a per-level mask-count list, and the
    restore index (R, 1) mapping sorted-by-level order back to input."""
    fr = _t(fpn_rois)
    n_levels = max_level - min_level + 1

    def fn(rv):
        R = rv.shape[0]
        area = jnp.maximum((rv[:, 2] - rv[:, 0]) *
                           (rv[:, 3] - rv[:, 1]), 0.0)
        scale = jnp.sqrt(area)
        lvl = jnp.floor(jnp.log2(scale / refer_scale + 1e-6)) + refer_level
        lvl = jnp.clip(lvl, min_level, max_level).astype(jnp.int32)
        outs = []
        for L in range(min_level, max_level + 1):
            m = (lvl == L)
            outs.append(jnp.where(m[:, None], rv, 0.0))
            outs.append(m.sum().astype(jnp.int32))
        order = jnp.argsort(lvl, stable=True)
        restore = jnp.zeros((R,), jnp.int32).at[order].set(
            jnp.arange(R, dtype=jnp.int32))
        outs.append(restore[:, None])
        return tuple(outs)

    res = apply_op(fn, (fr,), n_outputs=2 * n_levels + 1,
                   differentiable=False)
    multi_rois = [res[2 * i] for i in range(n_levels)]
    counts = [res[2 * i + 1] for i in range(n_levels)]
    restore_ind = res[-1]
    if rois_num is not None:
        return multi_rois, restore_ind, counts
    return multi_rois, restore_ind


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    """Concat per-level RoIs and keep the global top-k by score
    (detection.py:3954). Dense: returns (post_nms_top_n, 4) (+ count)."""
    rois = [_t(r) for r in multi_rois]
    scores = [_t(s) for s in multi_scores]

    def fn(*vals):
        n = len(vals) // 2
        rv = jnp.concatenate(vals[:n], axis=0)
        sv = jnp.concatenate([v.reshape(-1) for v in vals[n:]], axis=0)
        k = min(post_nms_top_n, sv.shape[0])
        top = jnp.argsort(-sv)[:k]
        return rv[top], sv[top][:, None]

    return apply_op(fn, tuple(rois + scores), n_outputs=2,
                    differentiable=False)


def box_decoder_and_assign(prior_box, prior_box_var, target_box, box_score,
                           box_clip, name=None):
    """Per-class box decode + best-class assignment (detection.py:3790).
    prior_box (P, 4); target_box (P, 4*C) per-class deltas; box_score
    (P, C). Returns (decoded (P, 4*C), assigned (P, 4))."""
    pb = _t(prior_box)
    pv = _t(prior_box_var)
    tb = _t(target_box)
    bs = _t(box_score)

    def fn(p, v, t, s):
        P = p.shape[0]
        C = s.shape[1]
        pw = p[:, 2] - p[:, 0] + 1.0
        ph = p[:, 3] - p[:, 1] + 1.0
        px = p[:, 0] + pw * 0.5
        py = p[:, 1] + ph * 0.5
        d = t.reshape(P, C, 4)
        cx = v[:, None, 0] * d[:, :, 0] * pw[:, None] + px[:, None]
        cy = v[:, None, 1] * d[:, :, 1] * ph[:, None] + py[:, None]
        w = jnp.exp(jnp.minimum(v[:, None, 2] * d[:, :, 2], box_clip)) \
            * pw[:, None]
        h = jnp.exp(jnp.minimum(v[:, None, 3] * d[:, :, 3], box_clip)) \
            * ph[:, None]
        dec = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - 1, cy + h / 2 - 1], axis=2)
        best = jnp.argmax(s[:, 1:], axis=1) + 1   # skip background col 0
        assigned = jnp.take_along_axis(
            dec, best[:, None, None].repeat(4, 2), axis=1)[:, 0]
        return dec.reshape(P, 4 * C), assigned

    return apply_op(fn, (pb, pv, tb, bs), n_outputs=2,
                    differentiable=False)


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """RetinaNet inference output (detection.py:3679): decode each FPN
    level's deltas vs its anchors, concat levels, class-wise NMS. bboxes /
    scores / anchors are per-level lists; returns the padded multiclass_nms
    output."""
    from ..tensor.manipulation import concat, transpose
    decoded = []
    for bb, an in zip(bboxes, anchors):
        dec = box_coder(an, [1.0, 1.0, 1.0, 1.0], bb,
                        code_type='decode_center_size', axis=0)
        decoded.append(dec)
    all_boxes = concat(decoded, axis=1)                 # (B, sumA, 4)
    all_scores = concat(list(scores), axis=1)           # (B, sumA, C)
    sc = transpose(all_scores, [0, 2, 1])
    return multiclass_nms(all_boxes, sc, score_threshold=score_threshold,
                          nms_top_k=nms_top_k, keep_top_k=keep_top_k,
                          nms_threshold=nms_threshold, nms_eta=nms_eta,
                          background_label=-1)


# ---------------------------------------------------------------------------
# host-side sampling generators (reference pins these to CPU too)
# ---------------------------------------------------------------------------

def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.5,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=81, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False):
    """Fast R-CNN training ROI sampling (detection.py:2464). EAGER host op
    (dynamic sampling; the reference's generate_proposal_labels_op is
    CPU-only as well). One image: rpn_rois (R, 4), gt_* dense padded.
    Returns fixed-size (rois (S, 4), labels_int32 (S, 1), bbox_targets
    (S, 4*class_nums), bbox_inside_weights, bbox_outside_weights) with
    S = batch_size_per_im; unused rows zero."""
    rois = np.asarray(_t(rpn_rois).numpy())
    gtc = np.asarray(_t(gt_classes).numpy()).reshape(-1)
    gtb = np.asarray(_t(gt_boxes).numpy()).reshape(-1, 4)
    valid = (gtb[:, 2] - gtb[:, 0]) * (gtb[:, 3] - gtb[:, 1]) > 0
    gtb, gtc = gtb[valid], gtc[valid]
    S = int(batch_size_per_im)
    rng = np.random.RandomState(0 if not use_random else None)

    allr = np.concatenate([rois, gtb], axis=0) if len(gtb) else rois
    if len(gtb):
        x11, y11 = allr[:, 0:1], allr[:, 1:2]
        x12, y12 = allr[:, 2:3], allr[:, 3:4]
        x21, y21 = gtb[:, 0], gtb[:, 1]
        x22, y22 = gtb[:, 2], gtb[:, 3]
        iw = np.minimum(x12, x22[None, :]) - np.maximum(x11, x21[None, :])
        ih = np.minimum(y12, y22[None, :]) - np.maximum(y11, y21[None, :])
        inter = np.clip(iw, 0, None) * np.clip(ih, 0, None)
        a1 = (x12 - x11) * (y12 - y11)
        a2 = ((x22 - x21) * (y22 - y21))[None, :]
        iou = inter / np.maximum(a1 + a2 - inter, 1e-10)
        max_iou = iou.max(axis=1)
        argmax = iou.argmax(axis=1)
    else:
        max_iou = np.zeros(len(allr))
        argmax = np.zeros(len(allr), np.int64)

    fg = np.where(max_iou >= fg_thresh)[0]
    bg = np.where((max_iou < bg_thresh_hi) & (max_iou >= bg_thresh_lo))[0]
    n_fg = min(int(fg_fraction * S), len(fg))
    n_bg = min(S - n_fg, len(bg))
    if use_random:
        fg = rng.permutation(fg)
        bg = rng.permutation(bg)
    sel = np.concatenate([fg[:n_fg], bg[:n_bg]])

    out_rois = np.zeros((S, 4), np.float32)
    labels = np.zeros((S, 1), np.int32)
    targets = np.zeros((S, 4 * class_nums), np.float32)
    in_w = np.zeros_like(targets)
    for i, r in enumerate(sel):
        out_rois[i] = allr[r]
        if i < n_fg and len(gtb):
            g = argmax[r]
            cls = int(gtc[g]) if not is_cls_agnostic else 1
            labels[i] = cls
            rw = max(allr[r, 2] - allr[r, 0], 1e-9)
            rh = max(allr[r, 3] - allr[r, 1], 1e-9)
            rx = allr[r, 0] + rw * 0.5
            ry = allr[r, 1] + rh * 0.5
            gw = max(gtb[g, 2] - gtb[g, 0], 1e-9)
            gh = max(gtb[g, 3] - gtb[g, 1], 1e-9)
            gx = gtb[g, 0] + gw * 0.5
            gy = gtb[g, 1] + gh * 0.5
            t = np.array([(gx - rx) / rw / bbox_reg_weights[0],
                          (gy - ry) / rh / bbox_reg_weights[1],
                          np.log(gw / rw) / bbox_reg_weights[2],
                          np.log(gh / rh) / bbox_reg_weights[3]],
                         np.float32)
            targets[i, 4 * cls:4 * cls + 4] = t
            in_w[i, 4 * cls:4 * cls + 4] = 1.0
    out_w = (in_w > 0).astype(np.float32)
    return (to_tensor(out_rois), to_tensor(labels), to_tensor(targets),
            to_tensor(in_w), to_tensor(out_w))


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution):
    """Mask R-CNN mask-target rasterization (detection.py:2606). EAGER host
    op. gt_segms: (G, P, 2) polygon points (dense-padded; NaN/zero rows
    ignored). Returns (mask_rois (S, 4), roi_has_mask_int32 (S, 1),
    mask_int32 (S, num_classes * resolution**2))."""
    rois_np = np.asarray(_t(rois).numpy())
    labs = np.asarray(_t(labels_int32).numpy()).reshape(-1)
    segs = np.asarray(_t(gt_segms).numpy())
    S = len(rois_np)
    res = int(resolution)
    masks = np.zeros((S, num_classes * res * res), np.int32)
    has = np.zeros((S, 1), np.int32)
    for i in range(S):
        c = int(labs[i])
        if c <= 0:
            continue
        has[i] = 1
        x1, y1, x2, y2 = rois_np[i]
        if x2 <= x1 or y2 <= y1 or len(segs) == 0:
            continue
        poly = segs[min(i, len(segs) - 1)].reshape(-1, 2)
        poly = poly[np.isfinite(poly).all(axis=1)]
        if len(poly) < 3:
            continue
        ys = (np.arange(res) + 0.5) / res * (y2 - y1) + y1
        xs = (np.arange(res) + 0.5) / res * (x2 - x1) + x1
        gx, gy = np.meshgrid(xs, ys)
        inside = _points_in_poly(gx.ravel(), gy.ravel(), poly)
        masks[i, c * res * res:(c + 1) * res * res] = \
            inside.astype(np.int32)
    return to_tensor(rois_np), to_tensor(has), to_tensor(masks)


def _points_in_poly(px, py, poly):
    """Even-odd rule point-in-polygon (host)."""
    n = len(poly)
    inside = np.zeros(len(px), bool)
    j = n - 1
    for i in range(n):
        xi, yi = poly[i]
        xj, yj = poly[j]
        crosses = ((yi > py) != (yj > py)) & \
            (px < (xj - xi) * (py - yi) / (yj - yi + 1e-12) + xi)
        inside ^= crosses
        j = i
    return inside


# ---------------------------------------------------------------------------
# SSD head builder
# ---------------------------------------------------------------------------

def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD multibox head (detection.py:2042): per feature map, a prior_box
    + 3x3 conv loc/conf heads; outputs concatenated across maps. Returns
    (mbox_locs (B, P, 4), mbox_confs (B, P, C), boxes (P, 4),
    variances (P, 4))."""
    from ..static.nn import conv2d as _conv2d
    from ..tensor.manipulation import concat, transpose, reshape
    from .ops import prior_box as _prior_box
    n_layer = len(inputs)
    if min_sizes is None:
        # the reference's ratio interpolation (detection.py:2198)
        min_sizes, max_sizes = [], []
        step = int(math.floor((max_ratio - min_ratio) /
                              max(n_layer - 2, 1)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.10] + min_sizes
        max_sizes = [base_size * 0.20] + max_sizes

    locs, confs, boxes_l, vars_l = [], [], [], []
    for i, feat in enumerate(inputs):
        ar = aspect_ratios[i] if isinstance(aspect_ratios[i],
                                            (list, tuple)) \
            else [aspect_ratios[i]]
        mn = min_sizes[i] if not isinstance(min_sizes[i], (list, tuple)) \
            else min_sizes[i]
        mx = max_sizes[i] if max_sizes else None
        box, var = _prior_box(
            feat, image, [mn] if not isinstance(mn, list) else mn,
            [mx] if (mx and not isinstance(mx, list)) else mx,
            ar, variance, flip, clip,
            steps=[steps[i], steps[i]] if steps else [0.0, 0.0],
            offset=offset,
            min_max_aspect_ratios_order=min_max_aspect_ratios_order)
        n_boxes = int(np.prod(box.shape[:-1]))
        n_per_cell = n_boxes // (feat.shape[2] * feat.shape[3])
        loc = _conv2d(feat, n_per_cell * 4, kernel_size, stride=stride,
                      padding=pad)
        conf = _conv2d(feat, n_per_cell * num_classes, kernel_size,
                       stride=stride, padding=pad)
        locs.append(reshape(transpose(loc, [0, 2, 3, 1]),
                            [loc.shape[0], -1, 4]))
        confs.append(reshape(transpose(conf, [0, 2, 3, 1]),
                             [conf.shape[0], -1, num_classes]))
        boxes_l.append(reshape(box, [-1, 4]))
        vars_l.append(reshape(var, [-1, 4]))
    return (concat(locs, axis=1), concat(confs, axis=1),
            concat(boxes_l, axis=0), concat(vars_l, axis=0))


# ---------------------------------------------------------------------------
# RoI pooling family + deformable ops
# ---------------------------------------------------------------------------

def _roi_batch_idx(rois_num, R):
    if rois_num is None:
        return None
    return _t(rois_num)


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    """RoI MAX pooling (nn.py:6860 / roi_pool_op): input (B, C, H, W);
    rois (R, 4) absolute xyxy; rois_num (B,) rois per image. Quantized bin
    edges (rounded), max within each bin — the Fast R-CNN original.
    Returns (R, C, ph, pw)."""
    x = _t(input)
    r = _t(rois)
    R = r.shape[0]
    ph, pw = int(pooled_height), int(pooled_width)
    rn = _roi_batch_idx(rois_num, R)

    def fn(xv, rv, *rest):
        B, C, H, W = xv.shape
        if rest:
            bounds = jnp.cumsum(rest[0].astype(jnp.int32))
            bidx = jnp.searchsorted(bounds, jnp.arange(R, dtype=jnp.int32),
                                    side='right').astype(jnp.int32)
        else:
            bidx = jnp.zeros((R,), jnp.int32)

        def one(roi, b):
            x1 = jnp.round(roi[0] * spatial_scale).astype(jnp.int32)
            y1 = jnp.round(roi[1] * spatial_scale).astype(jnp.int32)
            x2 = jnp.round(roi[2] * spatial_scale).astype(jnp.int32)
            y2 = jnp.round(roi[3] * spatial_scale).astype(jnp.int32)
            rw = jnp.maximum(x2 - x1 + 1, 1)
            rh = jnp.maximum(y2 - y1 + 1, 1)
            img = xv[b]                                     # (C, H, W)
            yy = jnp.arange(H)
            xx = jnp.arange(W)

            def bin_val(py, px):
                hs = y1 + (py * rh) // ph
                he = y1 + ((py + 1) * rh + ph - 1) // ph
                ws = x1 + (px * rw) // pw
                we = x1 + ((px + 1) * rw + pw - 1) // pw
                hs = jnp.clip(hs, 0, H)
                he = jnp.clip(he, 0, H)
                ws = jnp.clip(ws, 0, W)
                we = jnp.clip(we, 0, W)
                m = ((yy[:, None] >= hs) & (yy[:, None] < he) &
                     (xx[None, :] >= ws) & (xx[None, :] < we))
                empty = ~m.any()
                v = jnp.where(m[None], img, -jnp.inf).max(axis=(1, 2))
                return jnp.where(empty, 0.0, v)

            grid = jnp.stack([jnp.stack([bin_val(py, px)
                                         for px in range(pw)], axis=-1)
                              for py in range(ph)], axis=-2)
            return grid                                      # (C, ph, pw)

        return jax.vmap(one)(rv, bidx)

    tensors = (x, r) + ((rn,) if rn is not None else ())
    return apply_op(fn, tensors)


def psroi_pool(input, rois, output_channels, spatial_scale, pooled_height,
               pooled_width, rois_num=None, name=None):
    """Position-sensitive RoI AVG pooling (nn.py:13732 / R-FCN): input
    channels = output_channels * ph * pw; bin (i, j) of output channel c
    averages input channel c*ph*pw + i*pw + j over that bin. Returns
    (R, output_channels, ph, pw)."""
    x = _t(input)
    r = _t(rois)
    R = r.shape[0]
    ph, pw = int(pooled_height), int(pooled_width)
    oc = int(output_channels)
    rn = _roi_batch_idx(rois_num, R)

    def fn(xv, rv, *rest):
        B, C, H, W = xv.shape
        if rest:
            bounds = jnp.cumsum(rest[0].astype(jnp.int32))
            bidx = jnp.searchsorted(bounds, jnp.arange(R, dtype=jnp.int32),
                                    side='right').astype(jnp.int32)
        else:
            bidx = jnp.zeros((R,), jnp.int32)

        def one(roi, b):
            x1 = jnp.round(roi[0]) * spatial_scale
            y1 = jnp.round(roi[1]) * spatial_scale
            x2 = jnp.round(roi[2] + 1.0) * spatial_scale
            y2 = jnp.round(roi[3] + 1.0) * spatial_scale
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            img = xv[b]
            yy = jnp.arange(H)
            xx = jnp.arange(W)

            def bin_val(py, px):
                hs = jnp.floor(y1 + py * rh / ph).astype(jnp.int32)
                he = jnp.ceil(y1 + (py + 1) * rh / ph).astype(jnp.int32)
                ws = jnp.floor(x1 + px * rw / pw).astype(jnp.int32)
                we = jnp.ceil(x1 + (px + 1) * rw / pw).astype(jnp.int32)
                hs = jnp.clip(hs, 0, H)
                he = jnp.clip(he, 0, H)
                ws = jnp.clip(ws, 0, W)
                we = jnp.clip(we, 0, W)
                m = ((yy[:, None] >= hs) & (yy[:, None] < he) &
                     (xx[None, :] >= ws) & (xx[None, :] < we))
                cnt = jnp.maximum(m.sum(), 1)
                chans = jnp.arange(oc) * (ph * pw) + py * pw + px
                sel = img[chans]                            # (oc, H, W)
                return jnp.where(m[None], sel, 0.0).sum(axis=(1, 2)) / cnt

            grid = jnp.stack([jnp.stack([bin_val(py, px)
                                         for px in range(pw)], axis=-1)
                              for py in range(ph)], axis=-2)
            return grid                                     # (oc, ph, pw)

        return jax.vmap(one)(rv, bidx)

    tensors = (x, r) + ((rn,) if rn is not None else ())
    return apply_op(fn, tensors)


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, batch_roi_nums=None, name=None):
    """Precise RoI pooling (nn.py prroi_pool): exact bilinear-integral
    average per bin. Computed with a dense 4x4-per-bin integration grid —
    converges to the closed-form integral and stays fully differentiable
    (the op's main point vs quantized roi_pool)."""
    from .ops import roi_align
    return roi_align(input, rois, pooled_height, pooled_width,
                     spatial_scale, sampling_ratio=4,
                     rois_num=batch_roi_nums)


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              name=None):
    """Perspective-warp quadrilateral rois to a fixed rectangle
    (detection.py:3100, OCR east): rois (R, 8) four corner points.
    Bilinear sampling along the homography from the output rectangle to
    the roi quad. Returns (R, C, th, tw) (+ mask/matrix outputs omitted:
    the dense caller uses the warped patches)."""
    x = _t(input)
    r = _t(rois)
    th, tw = int(transformed_height), int(transformed_width)

    def fn(xv, rv):
        B, C, H, W = xv.shape

        def one(roi):
            pts = roi.reshape(4, 2) * spatial_scale   # tl, tr, br, bl
            u = (jnp.arange(tw, dtype=xv.dtype) + 0.5) / tw
            v = (jnp.arange(th, dtype=xv.dtype) + 0.5) / th
            uu, vv = jnp.meshgrid(u, v)               # (th, tw)
            top = pts[0][None, None] * (1 - uu[..., None]) + \
                pts[1][None, None] * uu[..., None]
            bot = pts[3][None, None] * (1 - uu[..., None]) + \
                pts[2][None, None] * uu[..., None]
            p = top * (1 - vv[..., None]) + bot * vv[..., None]
            px, py = p[..., 0], p[..., 1]
            px = jnp.clip(px, 0.0, W - 1.0)
            py = jnp.clip(py, 0.0, H - 1.0)
            x0 = jnp.floor(px).astype(jnp.int32)
            y0 = jnp.floor(py).astype(jnp.int32)
            x1 = jnp.minimum(x0 + 1, W - 1)
            y1 = jnp.minimum(y0 + 1, H - 1)
            wx = px - x0
            wy = py - y0
            img = xv[0]
            g = lambda yi, xi: img[:, yi, xi]          # (C, th, tw)
            return (g(y0, x0) * ((1 - wy) * (1 - wx))[None] +
                    g(y0, x1) * ((1 - wy) * wx)[None] +
                    g(y1, x0) * (wy * (1 - wx))[None] +
                    g(y1, x1) * (wy * wx)[None])

        return jax.vmap(one)(rv)

    return apply_op(fn, (x, r))


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=1,
                    deformable_groups=1, im2col_step=1, param_attr=None,
                    bias_attr=None, modulated=True, name=None):
    """Deformable convolution v1/v2 (nn.py:14234) as a dense offset-gather:
    for each output position and kernel tap, bilinear-sample the input at
    (base + dilation*tap + offset), multiply by the modulation mask (v2),
    then contract with the weights — one big matmul for the MXU instead of
    the reference's im2col + GEMM CUDA kernel.

    input (B, Cin, H, W); offset (B, 2*dg*kh*kw, Hout, Wout) packed
    [y0, x0, y1, x1, ...]; mask (B, dg*kh*kw, Hout, Wout) (modulated=True).
    """
    from ..fluid.layers_tail import _op_param
    from ..nn.initializer import XavierUniform, Constant
    x = _t(input)
    off = _t(offset)
    ks = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    kh, kw = int(ks[0]), int(ks[1])
    s = stride if isinstance(stride, (list, tuple)) else (stride, stride)
    p = padding if isinstance(padding, (list, tuple)) \
        else (padding, padding)
    d = dilation if isinstance(dilation, (list, tuple)) \
        else (dilation, dilation)
    Cin = x.shape[1]
    w = _op_param([num_filters, Cin // groups, kh, kw], param_attr,
                  XavierUniform(), 'deformable_conv_w')
    tensors = [x, off, w]
    if bias_attr is not False:
        tensors.append(_op_param([num_filters], bias_attr, Constant(0.0),
                                 'deformable_conv_b'))
    if modulated:
        if mask is None:
            raise ValueError("modulated deformable_conv (v2) needs mask")
        tensors.append(_t(mask))

    def fn2(xv, ov, wv, *rest):
        rest = list(rest)
        bv = rest.pop(0) if bias_attr is not False else None
        mv = rest.pop(0) if modulated else None
        B = xv.shape[0]
        outs = []
        for b in range(B):
            outs.append(_deform_one(xv[b], ov[b], wv,
                                    None if mv is None else mv[b],
                                    kh, kw, s, p, d, groups))
        out = jnp.stack(outs)
        if bv is not None:
            out = out + bv[None, :, None, None]
        return out

    return apply_op(fn2, tuple(tensors))


def _deform_one(img, off, wv, msk, kh, kw, s, p, d, groups):
    """Deformable conv for ONE image (see deformable_conv)."""
    C, H, W = img.shape
    # the offset tensor is authoritative for the output spatial dims
    # (reference contract: offset is (2*dg*kh*kw, Hout, Wout))
    Ho, Wo = off.shape[-2], off.shape[-1]
    dg = off.shape[0] // (2 * kh * kw)
    cpg = C // dg
    oy = jnp.arange(Ho) * s[0] - p[0]
    ox = jnp.arange(Wo) * s[1] - p[1]
    ky = jnp.arange(kh) * d[0]
    kx = jnp.arange(kw) * d[1]
    off = off.reshape(dg, kh * kw, 2, Ho, Wo)
    sy = (oy[None, None, :, None] + ky[None, :, None, None]
          ).reshape(1, kh, 1, Ho, 1) + 0.0
    sy = jnp.broadcast_to(sy, (dg, kh, kw, Ho, Wo)) + \
        off[:, :, 0].reshape(dg, kh, kw, Ho, Wo)
    sx = (ox[None, None, None, :] + kx[None, None, :, None]
          ).reshape(1, 1, kw, 1, Wo)
    sx = jnp.broadcast_to(sx, (dg, kh, kw, Ho, Wo)) + \
        off[:, :, 1].reshape(dg, kh, kw, Ho, Wo)
    inb = (sy > -1.0) & (sy < H) & (sx > -1.0) & (sx < W)
    syc = jnp.clip(sy, 0.0, H - 1.0)
    sxc = jnp.clip(sx, 0.0, W - 1.0)
    y0 = jnp.floor(syc).astype(jnp.int32)
    x0 = jnp.floor(sxc).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, H - 1)
    x1 = jnp.minimum(x0 + 1, W - 1)
    wy = syc - y0
    wx = sxc - x0

    flat = img.reshape(dg, cpg, H * W)

    def sample(yi, xi):
        idx = (yi * W + xi).reshape(dg, 1, -1)
        out = jnp.take_along_axis(flat, jnp.broadcast_to(
            idx, (dg, cpg, idx.shape[-1])), axis=2)
        return out.reshape(C, kh, kw, Ho, Wo)

    def rep(a):
        return jnp.broadcast_to(a[:, None], (dg, cpg) + a.shape[1:]) \
            .reshape(C, kh, kw, Ho, Wo)

    v = (sample(y0, x0) * rep((1 - wy) * (1 - wx)) +
         sample(y0, x1) * rep((1 - wy) * wx) +
         sample(y1, x0) * rep(wy * (1 - wx)) +
         sample(y1, x1) * rep(wy * wx))
    v = v * rep(inb.astype(v.dtype))
    if msk is not None:
        v = v * rep(msk.reshape(dg, kh, kw, Ho, Wo))
    if groups == 1:
        return jnp.einsum('cklhw,fckl->fhw', v, wv)
    Fg = wv.shape[0] // groups
    vg = v.reshape(groups, C // groups, kh, kw, Ho, Wo)
    wg = wv.reshape(groups, Fg, C // groups, kh, kw)
    return jnp.einsum('gcklhw,gfckl->gfhw', vg, wg).reshape(-1, Ho, Wo)


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1, part_size=None,
                           sample_per_part=1, trans_std=0.1,
                           position_sensitive=False, name=None):
    """Deformable RoI pooling (nn.py:14391): average-pool each bin at
    trans-shifted positions via bilinear sampling. input (B, C, H, W);
    rois (R, 4); trans (R, 2, ph, pw) normalized bin shifts."""
    x = _t(input)
    r = _t(rois)
    tr = _t(trans)
    ph, pw = int(pooled_height), int(pooled_width)
    spp = max(int(sample_per_part), 1)

    def fn2(xv, rv, tv):
        B, C, H, W = xv.shape

        def one(roi, t):
            x1 = roi[0] * spatial_scale - 0.5
            y1 = roi[1] * spatial_scale - 0.5
            x2 = (roi[2] + 1.0) * spatial_scale - 0.5
            y2 = (roi[3] + 1.0) * spatial_scale - 0.5
            rw = jnp.maximum(x2 - x1, 0.1)
            rh = jnp.maximum(y2 - y1, 0.1)
            bin_w = rw / pw
            bin_h = rh / ph
            img = xv[0]
            outs = []
            for py in range(ph):
                row = []
                for px in range(pw):
                    dy = 0.0 if no_trans else t[0, py, px] * trans_std * rh
                    dx = 0.0 if no_trans else t[1, py, px] * trans_std * rw
                    sub = (jnp.arange(spp, dtype=xv.dtype) + 0.5) / spp
                    yy = y1 + (py + sub) * bin_h + dy
                    xx = x1 + (px + sub) * bin_w + dx
                    yy = jnp.clip(yy, 0.0, H - 1.0)
                    xx = jnp.clip(xx, 0.0, W - 1.0)
                    y0 = jnp.floor(yy).astype(jnp.int32)
                    x0 = jnp.floor(xx).astype(jnp.int32)
                    y1i = jnp.minimum(y0 + 1, H - 1)
                    x1i = jnp.minimum(x0 + 1, W - 1)
                    wy = yy - y0
                    wx = xx - x0
                    g = lambda yi, xi: img[:, yi, :][:, :, xi]
                    v = (g(y0, x0) * ((1 - wy)[:, None] *
                                      (1 - wx)[None, :])[None] +
                         g(y0, x1i) * ((1 - wy)[:, None] *
                                       wx[None, :])[None] +
                         g(y1i, x0) * (wy[:, None] *
                                       (1 - wx)[None, :])[None] +
                         g(y1i, x1i) * (wy[:, None] * wx[None, :])[None])
                    row.append(v.mean(axis=(1, 2)))
                outs.append(jnp.stack(row, axis=-1))
            return jnp.stack(outs, axis=-2)       # (C, ph, pw)

        return jax.vmap(one)(rv, tv)

    return apply_op(fn2, (x, r, tr))
