"""ResNet family. Parity: python/paddle/vision/models/resnet.py."""
import functools

from ... import nn
from ...nn import functional as F

__all__ = ['ResNet', 'resnet18', 'resnet34', 'resnet50', 'resnet101',
           'resnet152']


class BasicBlock(nn.Layer):
    expansion = 1

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None,
                 data_format='NCHW'):
        super().__init__()
        df = data_format
        # bind data_format only into the default norm: a caller-supplied
        # norm_layer keeps its own signature
        norm_layer = norm_layer or functools.partial(nn.BatchNorm2D,
                                                     data_format=df)
        self.conv1 = nn.Conv2D(inplanes, planes, 3, padding=1, stride=stride,
                               bias_attr=False, data_format=df)
        self.bn1 = norm_layer(planes)
        self.relu = nn.ReLU()
        self.conv2 = nn.Conv2D(planes, planes, 3, padding=1, bias_attr=False,
                               data_format=df)
        self.bn2 = norm_layer(planes)
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.bn2(self.conv2(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class BottleneckBlock(nn.Layer):
    expansion = 4

    def __init__(self, inplanes, planes, stride=1, downsample=None, groups=1,
                 base_width=64, dilation=1, norm_layer=None,
                 data_format='NCHW'):
        super().__init__()
        df = data_format
        norm_layer = norm_layer or functools.partial(nn.BatchNorm2D,
                                                     data_format=df)
        width = int(planes * (base_width / 64.)) * groups
        self.conv1 = nn.Conv2D(inplanes, width, 1, bias_attr=False,
                               data_format=df)
        self.bn1 = norm_layer(width)
        self.conv2 = nn.Conv2D(width, width, 3, padding=dilation,
                               stride=stride, groups=groups, dilation=dilation,
                               bias_attr=False, data_format=df)
        self.bn2 = norm_layer(width)
        self.conv3 = nn.Conv2D(width, planes * self.expansion, 1,
                               bias_attr=False, data_format=df)
        self.bn3 = norm_layer(planes * self.expansion)
        self.relu = nn.ReLU()
        self.downsample = downsample
        self.stride = stride

    def forward(self, x):
        identity = x
        out = self.relu(self.bn1(self.conv1(x)))
        out = self.relu(self.bn2(self.conv2(out)))
        out = self.bn3(self.conv3(out))
        if self.downsample is not None:
            identity = self.downsample(x)
        return self.relu(out + identity)


class ResNet(nn.Layer):
    def __init__(self, block, depth, num_classes=1000, with_pool=True,
                 data_format='NCHW', space_to_depth_stem=False):
        """data_format='NHWC' puts channels on the TPU lane dimension —
        the layout XLA's conv/BN emitters want (SURVEY §6: NCHW accepted,
        NHWC preferred).

        space_to_depth_stem=True (NHWC only) computes the 7x7/stride-2 stem
        conv as an EXACTLY equivalent 4x4/stride-1 conv on 2x2-space-to-depth
        packed input (12 channels instead of 3). A 3-channel conv wastes the
        TPU MXU's 128-wide input-channel lanes; the packed form quadruples
        the stem's arithmetic intensity (the classic MLPerf TPU ResNet
        layout trick). The parameter stays the canonical [64, 3, 7, 7]
        weight — the repack happens in forward, so state dicts and
        pretrained checkpoints are unaffected."""
        super().__init__()
        layer_cfg = {18: [2, 2, 2, 2], 34: [3, 4, 6, 3], 50: [3, 4, 6, 3],
                     101: [3, 4, 23, 3], 152: [3, 8, 36, 3]}
        layers = layer_cfg[depth]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.data_format = data_format
        if space_to_depth_stem and data_format != 'NHWC':
            raise ValueError("space_to_depth_stem requires data_format="
                             "'NHWC' (it is a TPU lane-packing optimization)")
        self.space_to_depth_stem = space_to_depth_stem
        self._norm_layer = functools.partial(nn.BatchNorm2D,
                                             data_format=data_format)
        self.inplanes = 64
        self.dilation = 1
        self.conv1 = nn.Conv2D(3, self.inplanes, kernel_size=7, stride=2,
                               padding=3, bias_attr=False,
                               data_format=data_format)
        self.bn1 = self._norm_layer(self.inplanes)
        self.relu = nn.ReLU()
        self.maxpool = nn.MaxPool2D(kernel_size=3, stride=2, padding=1,
                                    data_format=data_format)
        self.layer1 = self._make_layer(block, 64, layers[0])
        self.layer2 = self._make_layer(block, 128, layers[1], stride=2)
        self.layer3 = self._make_layer(block, 256, layers[2], stride=2)
        self.layer4 = self._make_layer(block, 512, layers[3], stride=2)
        if with_pool:
            self.avgpool = nn.AdaptiveAvgPool2D((1, 1),
                                                data_format=data_format)
        if num_classes > 0:
            self.fc = nn.Linear(512 * block.expansion, num_classes)

    def _make_layer(self, block, planes, blocks, stride=1, dilate=False):
        norm_layer = self._norm_layer
        downsample = None
        df = self.data_format
        if stride != 1 or self.inplanes != planes * block.expansion:
            downsample = nn.Sequential(
                nn.Conv2D(self.inplanes, planes * block.expansion, 1,
                          stride=stride, bias_attr=False, data_format=df),
                norm_layer(planes * block.expansion))
        layers = [block(self.inplanes, planes, stride, downsample, 1, 64,
                        self.dilation, norm_layer, data_format=df)]
        self.inplanes = planes * block.expansion
        for _ in range(1, blocks):
            layers.append(block(self.inplanes, planes,
                                norm_layer=norm_layer, data_format=df))
        return nn.Sequential(*layers)

    def _stem_s2d(self, x):
        """7x7/s2 stem as a 4x4/s1 conv on 2x2-packed input; exact rewrite.

        Derivation: output O[i,j,o] reads input rows 2i-3..2i+3. Packed row
        p holds rows {2p, 2p+1}, so O[i] needs p in {i-2..i+1}: kernel 4,
        stride 1, pad (2,1). Tap (u,ry) maps to dy = 2(u-2)+ry+3, i.e. the
        8th tap (dy=-1) is zero — hence the front zero-pad of the 7x7
        weight to 8x8 before the [4,2,4,2,...] reshape. Channel packing
        order (ry, rx, c) matches the input reshape below."""
        B, H, W, C = x.shape
        if H % 2 or W % 2:
            raise ValueError(
                f"space_to_depth_stem needs even input H and W (got "
                f"{H}x{W}); pad the input or disable the packed stem")
        x2 = x.reshape([B, H // 2, 2, W // 2, 2, C]) \
              .transpose([0, 1, 3, 2, 4, 5]) \
              .reshape([B, H // 2, W // 2, 4 * C])
        x2 = F.pad(x2, [2, 1, 2, 1], data_format='NHWC')
        w = self.conv1.weight                      # [O, C, 7, 7]
        w = w.transpose([2, 3, 1, 0])              # [7, 7, C, O]
        w = F.pad(w, [1, 0, 1, 0, 0, 0, 0, 0])     # [8, 8, C, O], front pad
        O = w.shape[-1]
        w2 = w.reshape([4, 2, 4, 2, C, O]) \
              .transpose([0, 2, 1, 3, 4, 5]) \
              .reshape([4, 4, 4 * C, O]) \
              .transpose([3, 2, 0, 1])             # [O, 4C, 4, 4]
        return F.conv2d(x2, w2, stride=1, padding=0, data_format='NHWC')

    def forward(self, x):
        if self.space_to_depth_stem:
            x = self.relu(self.bn1(self._stem_s2d(x)))
        else:
            x = self.relu(self.bn1(self.conv1(x)))
        x = self.maxpool(x)
        x = self.layer1(x)
        x = self.layer2(x)
        x = self.layer3(x)
        x = self.layer4(x)
        if self.with_pool:
            x = self.avgpool(x)
        if self.num_classes > 0:
            x = x.flatten(1)
            x = self.fc(x)
        return x


def _resnet(arch, Block, depth, pretrained, **kwargs):
    model = ResNet(Block, depth, **kwargs)
    if pretrained:
        raise RuntimeError("pretrained weights unavailable offline; "
                           "load a state dict with model.set_state_dict")
    return model


def resnet18(pretrained=False, **kwargs):
    return _resnet('resnet18', BasicBlock, 18, pretrained, **kwargs)


def resnet34(pretrained=False, **kwargs):
    return _resnet('resnet34', BasicBlock, 34, pretrained, **kwargs)


def resnet50(pretrained=False, **kwargs):
    return _resnet('resnet50', BottleneckBlock, 50, pretrained, **kwargs)


def resnet101(pretrained=False, **kwargs):
    return _resnet('resnet101', BottleneckBlock, 101, pretrained, **kwargs)


def resnet152(pretrained=False, **kwargs):
    return _resnet('resnet152', BottleneckBlock, 152, pretrained, **kwargs)
