"""Detection / box ops, TPU-first.

Parity: python/paddle/fluid/layers/detection.py — iou_similarity (:763),
box_coder (:817), yolo_box (:1133), prior_box (:1768), density_prior_box
(:1930), anchor_generator (:2403), multiclass_nms (:3257), box_clip (:3037);
and paddle/fluid/operators/roi_align_op.* for roi_align.

TPU-first redesign: every op returns FIXED-shape dense tensors (XLA static
shapes) — variable-length results (NMS keep lists) become padded top-k arrays
plus a valid-count, instead of the reference's LoD outputs. All ops are pure
jax under the hood and jit/grad-compatible where meaningful.
"""
import math

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, apply_op
from ..tensor._helpers import _t

__all__ = ['iou_similarity', 'box_coder', 'prior_box', 'density_prior_box',
           'anchor_generator', 'yolo_box', 'multiclass_nms', 'roi_align',
           'box_clip', 'nms']


# ---------------------------------------------------------------------------
# IoU / box coding
# ---------------------------------------------------------------------------

def _pairwise_iou(x, y, box_normalized=True):
    """x: (N, 4), y: (M, 4) xyxy -> (N, M) IoU."""
    # graftlint: disable=GL006 — box_normalized is a static Python bool
    # config flag (never a tracer); the branch picks a compile-time constant
    off = 0.0 if box_normalized else 1.0
    ax1, ay1, ax2, ay2 = [x[:, i] for i in range(4)]
    bx1, by1, bx2, by2 = [y[:, i] for i in range(4)]
    area_x = (ax2 - ax1 + off) * (ay2 - ay1 + off)
    area_y = (bx2 - bx1 + off) * (by2 - by1 + off)
    ix1 = jnp.maximum(ax1[:, None], bx1[None, :])
    iy1 = jnp.maximum(ay1[:, None], by1[None, :])
    ix2 = jnp.minimum(ax2[:, None], bx2[None, :])
    iy2 = jnp.minimum(ay2[:, None], by2[None, :])
    iw = jnp.maximum(ix2 - ix1 + off, 0.0)
    ih = jnp.maximum(iy2 - iy1 + off, 0.0)
    inter = iw * ih
    union = area_x[:, None] + area_y[None, :] - inter
    return jnp.where(union > 0, inter / jnp.maximum(union, 1e-10), 0.0)


def iou_similarity(x, y, box_normalized=True, name=None):
    """Pairwise IoU of (N, 4) vs (M, 4) xyxy boxes -> (N, M).

    Parity: fluid.layers.iou_similarity (detection.py:763).
    """
    return apply_op(
        lambda a, b: _pairwise_iou(a, b, box_normalized), (_t(x), _t(y)))


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True, name=None,
              axis=0):
    """Encode/decode target boxes against priors.

    Parity: fluid.layers.box_coder (detection.py:817). encode: target (N, 4),
    prior (M, 4) -> (N, M, 4). decode: target (N, M, 4), prior (N|M, 4)
    broadcast along `axis` -> (N, M, 4).
    prior_box_var: None | (M, 4) tensor | 4-list.
    """
    p = _t(prior_box)
    t = _t(target_box)
    var_t = None
    var_const = None
    if prior_box_var is not None:
        if isinstance(prior_box_var, (list, tuple)):
            var_const = np.asarray(prior_box_var, np.float32)
        else:
            var_t = _t(prior_box_var)
    # graftlint: disable=GL006 — box_normalized is a static Python bool
    # config flag (never a tracer); the branch picks a compile-time constant
    off = 0.0 if box_normalized else 1.0
    encode = code_type.lower() in ("encode_center_size", "encode")

    def _centers(b):
        w = b[..., 2] - b[..., 0] + off
        h = b[..., 3] - b[..., 1] + off
        cx = b[..., 0] + 0.5 * w
        cy = b[..., 1] + 0.5 * h
        return cx, cy, w, h

    def fn(p, t, *var):
        if var:
            v = var[0]
        elif var_const is not None:
            v = jnp.asarray(var_const)
        else:
            v = None
        pcx, pcy, pw, ph = _centers(p)            # (M,)
        if encode:
            tcx, tcy, tw, th = _centers(t)        # (N,)
            ox = (tcx[:, None] - pcx[None, :]) / pw[None, :]
            oy = (tcy[:, None] - pcy[None, :]) / ph[None, :]
            ow = jnp.log(jnp.abs(tw[:, None] / pw[None, :]))
            oh = jnp.log(jnp.abs(th[:, None] / ph[None, :]))
            out = jnp.stack([ox, oy, ow, oh], axis=-1)   # (N, M, 4)
            if v is not None:
                v = jnp.broadcast_to(v.reshape((1, -1, 4))
                                     if v.ndim == 2 else v.reshape((1, 1, 4)),
                                     out.shape)
                out = out / v
            return out
        # decode: t is (N, M, 4) offsets, p broadcasts along axis
        if axis == 0:
            pcx, pcy, pw, ph = (a[None, :] for a in (pcx, pcy, pw, ph))
            if v is not None and v.ndim == 2:
                v = v[None, :, :]
        else:
            pcx, pcy, pw, ph = (a[:, None] for a in (pcx, pcy, pw, ph))
            if v is not None and v.ndim == 2:
                v = v[:, None, :]
        if v is None:
            v = jnp.ones((1, 1, 4), t.dtype)
        elif v.ndim == 1:
            v = v.reshape((1, 1, 4))
        dcx = v[..., 0] * t[..., 0] * pw + pcx
        dcy = v[..., 1] * t[..., 1] * ph + pcy
        dw = jnp.exp(v[..., 2] * t[..., 2]) * pw
        dh = jnp.exp(v[..., 3] * t[..., 3]) * ph
        return jnp.stack([dcx - dw / 2, dcy - dh / 2,
                          dcx + dw / 2 - off, dcy + dh / 2 - off], axis=-1)

    tensors = (p, t) + ((var_t,) if var_t is not None else ())
    return apply_op(fn, tensors)


def box_clip(input, im_info, name=None):
    """Clip xyxy boxes to image bounds.

    Parity: fluid.layers.box_clip (detection.py:3037). im_info: (B, 3)
    [h, w, scale]; boxes are clipped to [0, w/scale - 1] x [0, h/scale - 1].
    """
    def fn(b, info):
        im_h = info[..., 0] / info[..., 2] - 1.0
        im_w = info[..., 1] / info[..., 2] - 1.0
        while im_h.ndim < b.ndim - 1:
            im_h = im_h[..., None]
            im_w = im_w[..., None]
        x1 = jnp.clip(b[..., 0], 0.0, im_w)
        y1 = jnp.clip(b[..., 1], 0.0, im_h)
        x2 = jnp.clip(b[..., 2], 0.0, im_w)
        y2 = jnp.clip(b[..., 3], 0.0, im_h)
        return jnp.stack([x1, y1, x2, y2], axis=-1)
    return apply_op(fn, (_t(input), _t(im_info)))


# ---------------------------------------------------------------------------
# prior / anchor generation (host-side numpy: shapes + contents are static
# functions of the feature-map geometry, so they fold into constants)
# ---------------------------------------------------------------------------

def _expand_list(v):
    return list(v) if isinstance(v, (list, tuple)) else [v]


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.0,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5, name=None,
              min_max_aspect_ratios_order=False):
    """SSD prior boxes for one feature map.

    Parity: fluid.layers.prior_box (detection.py:1768). input: (B, C, H, W)
    feature map; image: (B, C, IH, IW). Returns (boxes, variances), each
    (H, W, num_priors, 4); boxes are normalized xyxy.
    """
    fh, fw = _t(input).shape[2], _t(input).shape[3]
    ih, iw = _t(image).shape[2], _t(image).shape[3]
    min_sizes = [float(s) for s in _expand_list(min_sizes)]
    max_sizes = [float(s) for s in _expand_list(max_sizes)] if max_sizes else []
    ars = [1.0]
    for ar in _expand_list(aspect_ratios):
        ar = float(ar)
        if any(abs(ar - e) < 1e-6 for e in ars):
            continue
        ars.append(ar)
        if flip:
            ars.append(1.0 / ar)

    step_w = float(steps[0]) if steps[0] else iw / fw
    step_h = float(steps[1]) if steps[1] else ih / fh

    whs = []  # (w, h) per prior, in pixels
    for ms in min_sizes:
        if min_max_aspect_ratios_order:
            whs.append((ms, ms))
            if max_sizes:
                big = math.sqrt(ms * max_sizes[min_sizes.index(ms)])
                whs.append((big, big))
            for ar in ars:
                if abs(ar - 1.0) < 1e-6:
                    continue
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
        else:
            for ar in ars:
                whs.append((ms * math.sqrt(ar), ms / math.sqrt(ar)))
            if max_sizes:
                big = math.sqrt(ms * max_sizes[min_sizes.index(ms)])
                whs.append((big, big))
    whs = np.asarray(whs, np.float32)            # (P, 2)

    cx = (np.arange(fw, dtype=np.float32) + offset) * step_w
    cy = (np.arange(fh, dtype=np.float32) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)               # (H, W)
    boxes = np.empty((fh, fw, len(whs), 4), np.float32)
    boxes[..., 0] = (cxg[..., None] - whs[None, None, :, 0] / 2) / iw
    boxes[..., 1] = (cyg[..., None] - whs[None, None, :, 1] / 2) / ih
    boxes[..., 2] = (cxg[..., None] + whs[None, None, :, 0] / 2) / iw
    boxes[..., 3] = (cyg[..., None] + whs[None, None, :, 1] / 2) / ih
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    variances = np.broadcast_to(
        np.asarray(variance, np.float32), boxes.shape).copy()
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(variances))


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    """Densified prior boxes (face-detection SSD variant).

    Parity: fluid.layers.density_prior_box (detection.py:1930). For each
    (density, fixed_size) pair and each fixed_ratio, lays a density x density
    grid of shifted centers inside each step cell.
    """
    fh, fw = _t(input).shape[2], _t(input).shape[3]
    ih, iw = _t(image).shape[2], _t(image).shape[3]
    densities = [int(d) for d in _expand_list(densities)]
    fixed_sizes = [float(s) for s in _expand_list(fixed_sizes)]
    fixed_ratios = [float(r) for r in _expand_list(fixed_ratios)]
    step_w = float(steps[0]) if steps[0] else iw / fw
    step_h = float(steps[1]) if steps[1] else ih / fh

    all_boxes = []
    cx = (np.arange(fw, dtype=np.float32) + offset) * step_w
    cy = (np.arange(fh, dtype=np.float32) + offset) * step_h
    cxg, cyg = np.meshgrid(cx, cy)
    for density, fs in zip(densities, fixed_sizes):
        for ratio in fixed_ratios:
            w = fs * math.sqrt(ratio)
            h = fs / math.sqrt(ratio)
            shift_w = step_w / density
            shift_h = step_h / density
            for di in range(density):
                for dj in range(density):
                    ccx = cxg - step_w / 2. + shift_w / 2. + dj * shift_w
                    ccy = cyg - step_h / 2. + shift_h / 2. + di * shift_h
                    all_boxes.append(np.stack([
                        (ccx - w / 2.) / iw, (ccy - h / 2.) / ih,
                        (ccx + w / 2.) / iw, (ccy + h / 2.) / ih], axis=-1))
    boxes = np.stack(all_boxes, axis=2).astype(np.float32)  # (H, W, P, 4)
    if clip:
        boxes = np.clip(boxes, 0.0, 1.0)
    variances = np.broadcast_to(
        np.asarray(variance, np.float32), boxes.shape).copy()
    if flatten_to_2d:
        boxes = boxes.reshape(-1, 4)
        variances = variances.reshape(-1, 4)
    return Tensor(jnp.asarray(boxes)), Tensor(jnp.asarray(variances))


def anchor_generator(input, anchor_sizes=(64., 128., 256., 512.),
                     aspect_ratios=(0.5, 1.0, 2.0),
                     variance=(0.1, 0.1, 0.2, 0.2), stride=(16.0, 16.0),
                     offset=0.5, name=None):
    """RPN anchors for one feature map.

    Parity: fluid.layers.anchor_generator (detection.py:2403). Returns
    (anchors, variances), each (H, W, num_anchors, 4), anchors in ABSOLUTE
    xyxy pixels.
    """
    fh, fw = _t(input).shape[2], _t(input).shape[3]
    sizes = [float(s) for s in _expand_list(anchor_sizes)]
    ars = [float(r) for r in _expand_list(aspect_ratios)]
    sw, sh = float(stride[0]), float(stride[1])

    # reference recipe (anchor_generator_op.h): snap a stride-area cell to the
    # aspect ratio, then scale to anchor_size
    whs = []
    for ar in ars:
        for s in sizes:
            base_w = round(math.sqrt(sw * sh / ar))
            base_h = round(base_w * ar)
            whs.append((s / sw * base_w, s / sh * base_h))
    whs = np.asarray(whs, np.float32)  # (A, 2): (w, h)

    cx = np.arange(fw, dtype=np.float32) * sw + offset * (sw - 1)
    cy = np.arange(fh, dtype=np.float32) * sh + offset * (sh - 1)
    cxg, cyg = np.meshgrid(cx, cy)
    anchors = np.empty((fh, fw, len(whs), 4), np.float32)
    anchors[..., 0] = cxg[..., None] - 0.5 * (whs[None, None, :, 0] - 1)
    anchors[..., 1] = cyg[..., None] - 0.5 * (whs[None, None, :, 1] - 1)
    anchors[..., 2] = cxg[..., None] + 0.5 * (whs[None, None, :, 0] - 1)
    anchors[..., 3] = cyg[..., None] + 0.5 * (whs[None, None, :, 1] - 1)
    variances = np.broadcast_to(
        np.asarray(variance, np.float32), anchors.shape).copy()
    return Tensor(jnp.asarray(anchors)), Tensor(jnp.asarray(variances))


# ---------------------------------------------------------------------------
# YOLO decode
# ---------------------------------------------------------------------------

def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio=32, clip_bbox=True, name=None, scale_x_y=1.0):
    """Decode YOLOv3 head output into boxes + per-class scores.

    Parity: fluid.layers.yolo_box (detection.py:1133). x: (B, A*(5+C), H, W);
    img_size: (B, 2) [h, w]. Returns boxes (B, H*W*A, 4) absolute xyxy and
    scores (B, H*W*A, C). Low-confidence boxes are zeroed (the reference's
    conf_thresh gating) so shapes stay static.
    """
    anchors = [float(a) for a in anchors]
    na = len(anchors) // 2
    cnum = int(class_num)

    def fn(xv, imgs):
        b, _, h, w = xv.shape
        xv = xv.reshape(b, na, 5 + cnum, h, w)
        grid_x = jnp.arange(w, dtype=jnp.float32)[None, None, None, :]
        grid_y = jnp.arange(h, dtype=jnp.float32)[None, None, :, None]
        aw = jnp.asarray(anchors[0::2], jnp.float32)[None, :, None, None]
        ah = jnp.asarray(anchors[1::2], jnp.float32)[None, :, None, None]

        sig = jax.nn.sigmoid
        alpha, beta = scale_x_y, -0.5 * (scale_x_y - 1.0)
        bx = (sig(xv[:, :, 0]) * alpha + beta + grid_x) / w     # center, norm
        by = (sig(xv[:, :, 1]) * alpha + beta + grid_y) / h
        bw = jnp.exp(xv[:, :, 2]) * aw / (w * downsample_ratio)
        bh = jnp.exp(xv[:, :, 3]) * ah / (h * downsample_ratio)
        conf = sig(xv[:, :, 4])
        probs = sig(xv[:, :, 5:]) * conf[:, :, None]            # (B,A,C,H,W)

        im_h = imgs[:, 0].astype(jnp.float32)[:, None, None, None]
        im_w = imgs[:, 1].astype(jnp.float32)[:, None, None, None]
        x1 = (bx - bw / 2.) * im_w
        y1 = (by - bh / 2.) * im_h
        x2 = (bx + bw / 2.) * im_w
        y2 = (by + bh / 2.) * im_h
        if clip_bbox:
            x1 = jnp.clip(x1, 0., im_w - 1.)
            y1 = jnp.clip(y1, 0., im_h - 1.)
            x2 = jnp.clip(x2, 0., im_w - 1.)
            y2 = jnp.clip(y2, 0., im_h - 1.)
        keep = (conf >= conf_thresh).astype(jnp.float32)
        boxes = jnp.stack([x1, y1, x2, y2], axis=2) * keep[:, :, None]
        # (B, A, 4, H, W) -> (B, H*W*A, 4): reference emits row-major HW x A
        boxes = boxes.transpose(0, 3, 4, 1, 2).reshape(b, -1, 4)
        probs = probs * keep[:, :, None]
        scores = probs.transpose(0, 3, 4, 1, 2).reshape(b, -1, cnum)
        return boxes, scores

    return apply_op(fn, (_t(x), _t(img_size)), n_outputs=2)


# ---------------------------------------------------------------------------
# NMS — fixed-shape padded formulation
# ---------------------------------------------------------------------------

def _nms_single(boxes, scores, iou_threshold, top_k, score_threshold,
                normalized=True):
    """boxes (M, 4), scores (M,) -> (keep_idx (top_k,), keep_mask (top_k,)).

    Greedy hard-NMS as an O(top_k) lax loop over a precomputed IoU matrix
    slice — fixed shapes throughout (TPU-first replacement for the
    reference's dynamic keep list).
    """
    M = boxes.shape[0]
    k = min(top_k, M)
    scores = jnp.where(scores > score_threshold, scores, -jnp.inf)
    order = jnp.argsort(-scores)[:k]             # candidates by score
    cand_boxes = boxes[order]
    cand_scores = scores[order]
    iou = _pairwise_iou(cand_boxes, cand_boxes, normalized)   # (k, k)

    def body(i, alive):
        # kill every lower-scored candidate overlapping candidate i IF i is
        # itself still alive
        kill = (iou[i] > iou_threshold) & (jnp.arange(k) > i) & alive[i]
        return alive & ~kill

    alive = jnp.isfinite(cand_scores)
    alive = jax.lax.fori_loop(0, k, body, alive)
    return order, alive


def nms(boxes, scores, iou_threshold=0.3, top_k=64, score_threshold=-1e30,
        normalized=True):
    """Single-class NMS: returns (indices, valid_mask) both shaped (top_k,).

    Padded-output TPU formulation; `indices[i]` is only meaningful where
    `valid_mask[i]`.
    """
    def fn(b, s):
        return _nms_single(b, s, iou_threshold, top_k, score_threshold,
                           normalized)
    return apply_op(fn, (_t(boxes), _t(scores)), n_outputs=2)


def multiclass_nms(bboxes, scores, score_threshold=0.0, nms_top_k=64,
                   keep_top_k=100, nms_threshold=0.3, normalized=True,
                   nms_eta=1.0, background_label=0, name=None,
                   return_index=False):
    """Multi-class NMS with FIXED-shape padded output.

    Parity: fluid.layers.multiclass_nms (detection.py:3257), TPU-first:
    returns `out` of shape (B, keep_top_k, 6) [label, score, x1, y1, x2, y2]
    padded with -1 rows, plus `valid_counts` (B,) — instead of the
    reference's LoD tensor. bboxes: (B, M, 4); scores: (B, C, M). With
    ``return_index`` also returns the selected per-image box row indices
    (B, keep_top_k) int32, -1 where padded (the multiclass_nms2 contract).
    """
    def fn(bb, sc):
        B, M, _ = bb.shape
        C = sc.shape[1]
        k = min(nms_top_k, M) if nms_top_k > 0 else M

        def per_image(boxes, scores_cm):
            if background_label >= 0:
                # exclude background by sinking its scores below threshold
                scores_cm = scores_cm.at[background_label].set(-jnp.inf)

            def per_class(scores_c):
                order, alive = _nms_single(
                    boxes, scores_c, nms_threshold, k, score_threshold,
                    normalized)
                s = jnp.where(alive, scores_c[order], -jnp.inf)
                return s, boxes[order], jnp.where(alive, order, -1)

            ss, bsel, osel = jax.vmap(per_class)(scores_cm)  # (C,k) ...
            labels = jnp.broadcast_to(
                jnp.arange(C, dtype=boxes.dtype)[:, None], (C, k))
            allc = jnp.concatenate(
                [labels[..., None], ss[..., None], bsel],
                axis=-1).reshape(C * k, 6)
            flat_idx = osel.reshape(C * k)
            kk = min(keep_top_k, C * k)
            top = jnp.argsort(-allc[:, 1])[:kk]
            sel = allc[top]
            idx = flat_idx[top]
            valid = jnp.isfinite(sel[:, 1])
            sel = jnp.where(valid[:, None], sel, -1.0)
            idx = jnp.where(valid, idx, -1).astype(jnp.int32)
            count = jnp.sum(valid.astype(jnp.int32))
            pad = keep_top_k - kk
            if pad > 0:
                sel = jnp.concatenate(
                    [sel, jnp.full((pad, 6), -1.0, sel.dtype)], axis=0)
                idx = jnp.concatenate(
                    [idx, jnp.full((pad,), -1, jnp.int32)], axis=0)
            return sel, idx, count

        return jax.vmap(per_image)(bb, sc)

    sel, idx, counts = apply_op(fn, (_t(bboxes), _t(scores)), n_outputs=3)
    if return_index:
        return sel, idx, counts
    return sel, counts


# ---------------------------------------------------------------------------
# RoI align
# ---------------------------------------------------------------------------

def roi_align(input, rois, pooled_height=1, pooled_width=1, spatial_scale=1.0,
              sampling_ratio=-1, rois_num=None, name=None):
    """RoI align (Mask R-CNN) with bilinear sampling.

    Parity: paddle/fluid/operators/roi_align_op.* semantics. input:
    (B, C, H, W); rois: (R, 4) absolute xyxy in input-image coordinates;
    rois_num: (B,) boxes per image (defaults to all rois on image 0).
    Returns (R, C, pooled_height, pooled_width).
    """
    x = _t(input)
    r = _t(rois)
    R = r.shape[0]
    ph, pw = int(pooled_height), int(pooled_width)

    # batch index per roi — jit-safe: a traced rois_num is mapped to batch
    # indices with searchsorted over its cumsum (no host sync at trace time)
    if rois_num is None:
        rn_t, prexpanded = None, False
    elif isinstance(rois_num, (list, tuple, np.ndarray)):
        batch_idx_np = np.repeat(
            np.arange(len(rois_num)),
            np.asarray(rois_num, np.int64)).astype(np.int32)
        rn_t, prexpanded = Tensor(jnp.asarray(batch_idx_np)), True
    else:
        rn_t, prexpanded = _t(rois_num), False

    def _batch_idx(rn):
        if rn is None:
            return jnp.zeros((R,), jnp.int32)
        if prexpanded:            # already per-roi indices
            return rn.astype(jnp.int32)
        bounds = jnp.cumsum(rn.astype(jnp.int32))
        return jnp.searchsorted(bounds, jnp.arange(R, dtype=jnp.int32),
                                side='right').astype(jnp.int32)

    def fn(xv, rv, *rest):
        batch_idx = _batch_idx(rest[0] if rest else None)
        H, W = xv.shape[2], xv.shape[3]

        def one_roi(roi, bidx):
            x1, y1, x2, y2 = roi * spatial_scale
            rw = jnp.maximum(x2 - x1, 1.0)
            rh = jnp.maximum(y2 - y1, 1.0)
            bin_w = rw / pw
            bin_h = rh / ph
            sr = sampling_ratio if sampling_ratio > 0 else 2
            # sample grid: (ph*sr, pw*sr) bilinear taps, averaged per bin
            ys = y1 + (jnp.arange(ph * sr) + 0.5) * (rh / (ph * sr))
            xs = x1 + (jnp.arange(pw * sr) + 0.5) * (rw / (pw * sr))

            def bilinear(img, yy, xx):           # img (C, H, W)
                yy = jnp.clip(yy, 0.0, H - 1.0)
                xx = jnp.clip(xx, 0.0, W - 1.0)
                y0 = jnp.floor(yy).astype(jnp.int32)
                x0 = jnp.floor(xx).astype(jnp.int32)
                y1i = jnp.minimum(y0 + 1, H - 1)
                x1i = jnp.minimum(x0 + 1, W - 1)
                wy = yy - y0
                wx = xx - x0
                g = lambda yi, xi: img[:, yi, :][:, :, xi]   # (C, Sy, Sx)
                v = (g(y0, x0) * ((1 - wy)[:, None] * (1 - wx)[None, :])[None]
                     + g(y0, x1i) * ((1 - wy)[:, None] * wx[None, :])[None]
                     + g(y1i, x0) * (wy[:, None] * (1 - wx)[None, :])[None]
                     + g(y1i, x1i) * (wy[:, None] * wx[None, :])[None])
                return v                          # (C, Sy, Sx)

            img = xv[bidx]
            samples = bilinear(img, ys, xs)       # (C, ph*sr, pw*sr)
            C = samples.shape[0]
            samples = samples.reshape(C, ph, sr, pw, sr)
            return samples.mean(axis=(2, 4))      # (C, ph, pw)

        return jax.vmap(one_roi)(rv, batch_idx)

    tensors = (x, r) + ((rn_t,) if rn_t is not None else ())
    return apply_op(fn, tensors)
