"""Vision transforms. Parity: python/paddle/vision/transforms/__init__.py."""
from .transforms import *  # noqa
from . import functional
# beta re-exports the functional forms at the transforms level
from .functional import (resize, pad, rotate, to_grayscale,  # noqa: F401
                         normalize, crop, center_crop, hflip, vflip)


def flip(image, code):
    """cv2-style flip (beta functional): code 0 vertical, >0 horizontal,
    <0 both."""
    from . import functional as F
    if code == 0:
        return F.vflip(image)
    if code > 0:
        return F.hflip(image)
    return F.hflip(F.vflip(image))
