"""Vision transforms. Parity: python/paddle/vision/transforms/__init__.py."""
from .transforms import *  # noqa
from . import functional
