"""Transform functionals on numpy HWC images.

Parity: python/paddle/vision/transforms/functional.py (cv2-based in the
reference; pure-numpy here — no cv2 dependency in the image).
"""
import numbers

import numpy as np

__all__ = ['to_tensor', 'resize', 'crop', 'center_crop', 'hflip', 'vflip',
           'normalize', 'pad', 'rotate', 'adjust_brightness', 'adjust_contrast',
           'adjust_saturation', 'adjust_hue', 'to_grayscale', 'transpose_img']


def _as_np(img):
    if hasattr(img, 'convert'):  # PIL
        return np.asarray(img)
    return np.asarray(img)


def to_tensor(pic, data_format='CHW'):
    img = _as_np(pic).astype(np.float32)
    if img.ndim == 2:
        img = img[:, :, None]
    if img.max() > 1.5:
        img = img / 255.0
    if data_format == 'CHW':
        img = img.transpose(2, 0, 1)
    return img


def _resize_np(img, size):
    """Bilinear resize HWC uint8/float numpy."""
    h, w = img.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(size * w / h)
        else:
            nh, nw = int(size * h / w), size
    else:
        nh, nw = size
    if (nh, nw) == (h, w):
        return img
    ys = np.linspace(0, h - 1, nh)
    xs = np.linspace(0, w - 1, nw)
    y0 = np.floor(ys).astype(int)
    x0 = np.floor(xs).astype(int)
    y1 = np.minimum(y0 + 1, h - 1)
    x1 = np.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[:, None, None] if img.ndim == 3 else (ys - y0)[:, None]
    wx = (xs - x0)[None, :, None] if img.ndim == 3 else (xs - x0)[None, :]
    im = img.astype(np.float32)
    top = im[y0][:, x0] * (1 - wx) + im[y0][:, x1] * wx
    bot = im[y1][:, x0] * (1 - wx) + im[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    return out.astype(img.dtype) if img.dtype == np.uint8 else out


def resize(img, size, interpolation='bilinear'):
    return _resize_np(_as_np(img), size)


def crop(img, top, left, height, width):
    return _as_np(img)[top:top + height, left:left + width]


def center_crop(img, output_size):
    img = _as_np(img)
    if isinstance(output_size, numbers.Number):
        output_size = (int(output_size), int(output_size))
    h, w = img.shape[:2]
    th, tw = output_size
    i = int(round((h - th) / 2.))
    j = int(round((w - tw) / 2.))
    return crop(img, i, j, th, tw)


def hflip(img):
    return _as_np(img)[:, ::-1]


def vflip(img):
    return _as_np(img)[::-1]


def normalize(img, mean, std, data_format='CHW', to_rgb=False):
    img = np.asarray(img, dtype=np.float32)
    mean = np.asarray(mean, dtype=np.float32)
    std = np.asarray(std, dtype=np.float32)
    if data_format == 'CHW':
        return (img - mean[:, None, None]) / std[:, None, None]
    return (img - mean) / std


def pad(img, padding, fill=0, padding_mode='constant'):
    img = _as_np(img)
    if isinstance(padding, int):
        pl = pr = pt = pb = padding
    elif len(padding) == 2:
        pl, pt = padding
        pr, pb = padding
    else:
        pl, pt, pr, pb = padding
    spec = [(pt, pb), (pl, pr)] + ([(0, 0)] if img.ndim == 3 else [])
    mode = {'constant': 'constant', 'edge': 'edge', 'reflect': 'reflect',
            'symmetric': 'symmetric'}[padding_mode]
    if mode == 'constant':
        return np.pad(img, spec, mode=mode, constant_values=fill)
    return np.pad(img, spec, mode=mode)


def rotate(img, angle, interpolation='nearest', expand=False, center=None,
           fill=0):
    """Nearest-neighbor rotation (pure numpy)."""
    img = _as_np(img)
    h, w = img.shape[:2]
    cy, cx = ((h - 1) / 2., (w - 1) / 2.) if center is None else center[::-1]
    a = np.deg2rad(angle)
    cos_a, sin_a = np.cos(a), np.sin(a)
    yy, xx = np.mgrid[0:h, 0:w]
    ys = cos_a * (yy - cy) + sin_a * (xx - cx) + cy
    xs = -sin_a * (yy - cy) + cos_a * (xx - cx) + cx
    yi = np.round(ys).astype(int)
    xi = np.round(xs).astype(int)
    valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
    out = np.full_like(img, fill)
    out[valid] = img[yi[valid], xi[valid]]
    return out


def adjust_brightness(img, brightness_factor):
    img = _as_np(img).astype(np.float32)
    out = img * brightness_factor
    return np.clip(out, 0, 255).astype(np.uint8) if img.max() > 1.5 else out


def adjust_contrast(img, contrast_factor):
    img = _as_np(img).astype(np.float32)
    mean = img.mean()
    out = (img - mean) * contrast_factor + mean
    return np.clip(out, 0, 255).astype(np.uint8) if img.max() > 1.5 else out


def adjust_saturation(img, saturation_factor):
    img = _as_np(img).astype(np.float32)
    gray = img.mean(axis=-1, keepdims=True)
    out = (img - gray) * saturation_factor + gray
    return np.clip(out, 0, 255).astype(np.uint8) if img.max() > 1.5 else out


def adjust_hue(img, hue_factor):
    """Approximate hue rotation in RGB space."""
    img = _as_np(img).astype(np.float32)
    cos_h = np.cos(2 * np.pi * hue_factor)
    sin_h = np.sin(2 * np.pi * hue_factor)
    m = np.array([[0.299, 0.587, 0.114]] * 3) + \
        cos_h * (np.eye(3) - np.array([[0.299, 0.587, 0.114]] * 3)) + \
        sin_h * np.array([[0.701, -0.587, -0.114],
                          [-0.299, 0.413, -0.114],
                          [-0.299, -0.587, 0.886]])
    out = img @ m.T
    return np.clip(out, 0, 255).astype(np.uint8) if img.max() > 1.5 else out


def to_grayscale(img, num_output_channels=1):
    img = _as_np(img).astype(np.float32)
    if img.ndim == 2:
        g = img
    else:
        g = img @ np.array([0.299, 0.587, 0.114], dtype=np.float32)
    if num_output_channels == 3:
        g = np.stack([g] * 3, axis=-1)
    else:
        g = g[..., None]
    return g.astype(np.uint8) if img.max() > 1.5 else g


def transpose_img(img, order):
    return _as_np(img).transpose(order)
