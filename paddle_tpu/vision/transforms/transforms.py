"""Transform classes. Parity: python/paddle/vision/transforms/transforms.py."""
import numbers
import random

import numpy as np

from . import functional as Fv

__all__ = ['Compose', 'BaseTransform', 'ToTensor', 'Resize', 'RandomResizedCrop',
           'CenterCrop', 'RandomHorizontalFlip', 'RandomVerticalFlip',
           'Transpose', 'Normalize', 'BrightnessTransform', 'SaturationTransform',
           'ContrastTransform', 'HueTransform', 'ColorJitter', 'RandomCrop',
           'Pad', 'RandomRotation', 'Grayscale', 'Permute', 'RandomRotate', 'BatchCompose', 'CenterCropResize', 'GaussianNoise', 'RandomErasing']


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, data):
        for t in self.transforms:
            data = t(data)
        return data


class BaseTransform:
    def __init__(self, keys=None):
        self.keys = keys

    def __call__(self, inputs):
        return self._apply_image(inputs)

    def _apply_image(self, img):
        raise NotImplementedError


class ToTensor(BaseTransform):
    def __init__(self, data_format='CHW', keys=None):
        super().__init__(keys)
        self.data_format = data_format

    def _apply_image(self, img):
        return Fv.to_tensor(img, self.data_format)


class Resize(BaseTransform):
    def __init__(self, size, interpolation='bilinear', keys=None):
        super().__init__(keys)
        self.size = size
        self.interpolation = interpolation

    def _apply_image(self, img):
        return Fv.resize(img, self.size, self.interpolation)


class RandomResizedCrop(BaseTransform):
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3. / 4, 4. / 3),
                 interpolation='bilinear', keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, int) else tuple(size)
        self.scale = scale
        self.ratio = ratio

    def _apply_image(self, img):
        img = np.asarray(img)
        h, w = img.shape[:2]
        area = h * w
        for _ in range(10):
            target_area = random.uniform(*self.scale) * area
            aspect = np.exp(random.uniform(np.log(self.ratio[0]),
                                           np.log(self.ratio[1])))
            cw = int(round(np.sqrt(target_area * aspect)))
            ch = int(round(np.sqrt(target_area / aspect)))
            if cw <= w and ch <= h:
                i = random.randint(0, h - ch)
                j = random.randint(0, w - cw)
                patch = Fv.crop(img, i, j, ch, cw)
                return Fv.resize(patch, self.size)
        return Fv.resize(Fv.center_crop(img, min(h, w)), self.size)


class CenterCrop(BaseTransform):
    def __init__(self, size, keys=None):
        super().__init__(keys)
        self.size = size

    def _apply_image(self, img):
        return Fv.center_crop(img, self.size)


class RandomCrop(BaseTransform):
    def __init__(self, size, padding=None, pad_if_needed=False, fill=0,
                 padding_mode='constant', keys=None):
        super().__init__(keys)
        self.size = (size, size) if isinstance(size, numbers.Number) else \
            tuple(size)
        self.padding = padding
        self.pad_if_needed = pad_if_needed
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        img = np.asarray(img)
        if self.padding is not None:
            img = Fv.pad(img, self.padding, self.fill, self.padding_mode)
        h, w = img.shape[:2]
        th, tw = self.size
        if self.pad_if_needed and (h < th or w < tw):
            img = Fv.pad(img, (max(tw - w, 0), max(th - h, 0)), self.fill,
                         self.padding_mode)
            h, w = img.shape[:2]
        i = random.randint(0, h - th)
        j = random.randint(0, w - tw)
        return Fv.crop(img, i, j, th, tw)


class RandomHorizontalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return Fv.hflip(img)
        return np.asarray(img)


class RandomVerticalFlip(BaseTransform):
    def __init__(self, prob=0.5, keys=None):
        super().__init__(keys)
        self.prob = prob

    def _apply_image(self, img):
        if random.random() < self.prob:
            return Fv.vflip(img)
        return np.asarray(img)


class Transpose(BaseTransform):
    def __init__(self, order=(2, 0, 1), keys=None):
        super().__init__(keys)
        self.order = order

    def _apply_image(self, img):
        img = np.asarray(img)
        if img.ndim == 2:
            img = img[..., None]
        return img.transpose(self.order)


class Normalize(BaseTransform):
    def __init__(self, mean=0.0, std=1.0, data_format='CHW', to_rgb=False,
                 keys=None):
        super().__init__(keys)
        if isinstance(mean, numbers.Number):
            mean = [mean] * 3
        if isinstance(std, numbers.Number):
            std = [std] * 3
        self.mean = mean
        self.std = std
        self.data_format = data_format

    def _apply_image(self, img):
        return Fv.normalize(img, self.mean, self.std, self.data_format)


class BrightnessTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return Fv.adjust_brightness(img, f)


class ContrastTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return Fv.adjust_contrast(img, f)


class SaturationTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        f = random.uniform(max(0, 1 - self.value), 1 + self.value)
        return Fv.adjust_saturation(img, f)


class HueTransform(BaseTransform):
    def __init__(self, value, keys=None):
        super().__init__(keys)
        self.value = float(value)

    def _apply_image(self, img):
        if self.value == 0:
            return np.asarray(img)
        f = random.uniform(-self.value, self.value)
        return Fv.adjust_hue(img, f)


class ColorJitter(BaseTransform):
    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0,
                 keys=None):
        super().__init__(keys)
        self.transforms = [BrightnessTransform(brightness),
                           ContrastTransform(contrast),
                           SaturationTransform(saturation),
                           HueTransform(hue)]

    def _apply_image(self, img):
        order = list(range(4))
        random.shuffle(order)
        for i in order:
            img = self.transforms[i](img)
        return img


class Pad(BaseTransform):
    def __init__(self, padding, fill=0, padding_mode='constant', keys=None):
        super().__init__(keys)
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def _apply_image(self, img):
        return Fv.pad(img, self.padding, self.fill, self.padding_mode)


class RandomRotation(BaseTransform):
    def __init__(self, degrees, interpolation='nearest', expand=False,
                 center=None, fill=0, keys=None):
        super().__init__(keys)
        if isinstance(degrees, numbers.Number):
            degrees = (-degrees, degrees)
        self.degrees = degrees
        self.expand = expand
        self.center = center
        self.fill = fill

    def _apply_image(self, img):
        angle = random.uniform(*self.degrees)
        return Fv.rotate(img, angle, expand=self.expand, center=self.center,
                         fill=self.fill)


class Grayscale(BaseTransform):
    def __init__(self, num_output_channels=1, keys=None):
        super().__init__(keys)
        self.num_output_channels = num_output_channels

    def _apply_image(self, img):
        return Fv.to_grayscale(img, self.num_output_channels)


# -- 2.0-beta transform tail --------------------------------------------------

Permute = Transpose          # beta name for HWC->CHW
RandomRotate = RandomRotation


class BatchCompose:
    """Compose applied per batch (reference transforms.BatchCompose)."""

    def __init__(self, transforms=[]):
        self.transforms = transforms

    def __call__(self, data):
        for f in self.transforms:
            data = [f(d) for d in data]
        return data


class CenterCropResize(BaseTransform):
    """Center-crop to the largest square scaled by crop_padding, then
    resize (reference transforms.CenterCropResize)."""

    def __init__(self, size, crop_padding=32, interpolation='bilinear'):
        self.size = (size, size) if isinstance(size, int) else size
        self.crop_padding = crop_padding
        self.interpolation = interpolation

    def _apply_image(self, img):
        import numpy as _np
        arr = Fv._as_np(img)
        h, w = arr.shape[:2]
        c = min(self.size)
        side = int(c / (c + self.crop_padding) * min(h, w))
        top = (h - side) // 2
        left = (w - side) // 2
        cropped = arr[top:top + side, left:left + side]
        return Fv.resize(cropped, self.size, self.interpolation)

    __call__ = _apply_image


class GaussianNoise(BaseTransform):
    """Additive gaussian pixel noise (reference transforms.GaussianNoise)."""

    def __init__(self, mean=0.0, variance=1.0):
        self.mean = mean
        self.std = variance ** 0.5

    def _apply_image(self, img):
        import numpy as _np
        arr = Fv._as_np(img).astype('float32')
        noise = _np.random.normal(self.mean, self.std, arr.shape)
        return (arr + noise).astype('float32')

    __call__ = _apply_image


class RandomErasing(BaseTransform):
    """Random rectangular erase (reference transforms.RandomErasing /
    the cutout augmentation)."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def _apply_image(self, img):
        import numpy as _np
        arr = Fv._as_np(img).copy()
        if _np.random.rand() > self.prob:
            return arr
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * _np.random.uniform(*self.scale)
            aspect = _np.random.uniform(*self.ratio)
            eh = int(round((target * aspect) ** 0.5))
            ew = int(round((target / aspect) ** 0.5))
            if eh < h and ew < w:
                top = _np.random.randint(0, h - eh)
                left = _np.random.randint(0, w - ew)
                arr[top:top + eh, left:left + ew] = self.value
                break
        return arr

    __call__ = _apply_image
