import os

# Force CPU with 8 virtual devices so mesh/distributed tests run hermetically.
# The axon sitecustomize registers the TPU PJRT plugin at interpreter start and
# overrides JAX_PLATFORMS, so env vars alone are not enough — jax.config wins.
if not os.environ.get("PADDLE_TPU_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not os.environ.get("PADDLE_TPU_TEST_TPU"):
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "fault: fault-injection / resilience tests (deterministic "
        "write failures, corruption, SIGTERM, NaN injection)")
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1")
    config.addinivalue_line(
        "markers", "lint: static-analysis gates (graftlint over the repo; "
        "pure AST, no tracing)")
    config.addinivalue_line(
        "markers", "obs: observability/telemetry tests (metrics registry, "
        "spans, step events, interposed counters)")
    config.addinivalue_line(
        "markers", "serving: serving-runtime tests (bucketing, continuous "
        "batching, KV-cache decode, deadlines/load shedding, retrace "
        "flatness)")
    config.addinivalue_line(
        "markers", "sharding: FSDP/tensor-parallel sharded-training tests "
        "(2D-mesh parameter/optimizer-state sharding through the unified "
        "train step)")


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(1234)
    np.random.seed(1234)
    yield


@pytest.fixture(autouse=True, scope="module")
def _module_telemetry_isolation():
    """Reset the observability spine between test MODULES.

    Tier-1 runs alphabetically (-p no:randomly): a module that enables
    telemetry, installs crash hooks, or leaves counters/cost-ledger
    entries behind silently changes what the next module observes — e.g.
    test_mission_control installing the flight recorder's excepthooks
    made test_cost_flight's install_crash_hooks() a no-op, so its
    monkeypatched threading.excepthook clobbered the live hook and
    load_dump() returned None. Module scope keeps intra-module state
    (many modules share setup within themselves) while giving every
    module a pristine spine."""
    yield
    import paddle_tpu.observability as obs
    from paddle_tpu.observability import endpoint, flush, timeseries
    flush.stop_rank_flusher(final_flush=False)
    timeseries.clear()
    endpoint.stop_active_server()
    obs.flight.uninstall_crash_hooks()
    obs.reset()
    from paddle_tpu.serving import admission
    admission.reset_tenant_stats()
    if os.environ.get("PADDLE_TPU_TELEMETRY") != "1":
        obs.disable()
