import os

# Force CPU with 8 virtual devices so mesh/distributed tests run hermetically.
# The axon sitecustomize registers the TPU PJRT plugin at interpreter start and
# overrides JAX_PLATFORMS, so env vars alone are not enough — jax.config wins.
if not os.environ.get("PADDLE_TPU_TEST_TPU"):
    os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

if not os.environ.get("PADDLE_TPU_TEST_TPU"):
    jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "fault: fault-injection / resilience tests (deterministic "
        "write failures, corruption, SIGTERM, NaN injection)")
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from tier-1")
    config.addinivalue_line(
        "markers", "lint: static-analysis gates (graftlint over the repo; "
        "pure AST, no tracing)")
    config.addinivalue_line(
        "markers", "obs: observability/telemetry tests (metrics registry, "
        "spans, step events, interposed counters)")
    config.addinivalue_line(
        "markers", "serving: serving-runtime tests (bucketing, continuous "
        "batching, KV-cache decode, deadlines/load shedding, retrace "
        "flatness)")
    config.addinivalue_line(
        "markers", "sharding: FSDP/tensor-parallel sharded-training tests "
        "(2D-mesh parameter/optimizer-state sharding through the unified "
        "train step)")


@pytest.fixture(autouse=True)
def _seed():
    import paddle_tpu as paddle
    paddle.seed(1234)
    np.random.seed(1234)
    yield
