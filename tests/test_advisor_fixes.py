"""Regression tests for the round-1/2 advisor findings.

Covers: (a) worker-death detection in the process pool, (b) one-shot
batch_sampler probing in DataLoader, (c) tokenizer ASCII/Unicode parity,
(d) pipeline data-axis sharding on multi-axis meshes, (e) jit-safe
sequence_mask, (f) class_center_sample.
"""
import os
import signal
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu._native import available as native_available


@pytest.mark.skipif(not native_available(), reason="no native lib")
def test_worker_death_raises_instead_of_hanging():
    """Kill ONE worker while its sibling lives: iteration must raise
    promptly, not spin on ring timeouts forever (advisor finding a)."""
    from paddle_tpu.io import Dataset, DataLoader

    class Slow(Dataset):
        def __len__(self):
            return 64

        def __getitem__(self, i):
            time.sleep(0.05)
            return np.full((4,), i, np.float32)

    # worker_max_restarts=0 disables the PR 5 pool self-healing: with the
    # default budget the pool RESPAWNS the killed worker (by design) and
    # iteration completes instead of raising, which is the healing
    # contract's own test — this one pins the raise-don't-hang contract
    dl = DataLoader(Slow(), batch_size=4, num_workers=2, shuffle=False,
                    worker_max_restarts=0)
    it = iter(dl)
    next(it)   # pool is up and producing
    pools = [o for o in _live_pools()]
    assert pools, "expected a live ProcessWorkerPool"
    pool = pools[-1]
    victim = pool._procs[0]
    os.kill(victim.pid, signal.SIGKILL)
    t0 = time.monotonic()
    with pytest.raises(RuntimeError, match="worker"):
        for _ in it:
            pass
    assert time.monotonic() - t0 < 30, "death detection took too long"


def _live_pools():
    import gc
    from paddle_tpu._native.process_pool import ProcessWorkerPool
    return [o for o in gc.get_objects()
            if isinstance(o, ProcessWorkerPool) and not o._closed]


def test_one_shot_batch_sampler_keeps_first_batch():
    """A generator batch_sampler must not lose its first batch to the
    shm-compatibility probe (advisor finding b)."""
    from paddle_tpu.io import Dataset, DataLoader

    class D(Dataset):
        def __len__(self):
            return 12

        def __getitem__(self, i):
            return np.full((2,), i, np.float32)

    def gen_sampler():
        for s in range(0, 12, 3):
            yield [s, s + 1, s + 2]

    dl = DataLoader(D(), batch_sampler=gen_sampler(), num_workers=2)
    firsts = [float(np.asarray(b.numpy())[0, 0]) for b in dl]
    assert firsts == [0.0, 3.0, 6.0, 9.0], firsts


class TestTokenizerUnicode:
    def _vocab(self):
        toks = ['[UNK]', 'the', 'cat', '.', 'café', 'naïve',
                'foo', 'bar', '_', '—', 'x']
        return {t: i for i, t in enumerate(toks)}

    def test_native_delegates_unicode_to_python(self):
        from paddle_tpu._native.tokenizer import Tokenizer
        t = Tokenizer(self._vocab())
        p = Tokenizer(self._vocab())
        p._cvocab = None
        # em-dash splits as punctuation, accents stay in words — identical
        # ids whichever entry path is taken
        for text in ('café—naïve', 'the café cat.',
                     'ÉX x'):
            np.testing.assert_array_equal(t.encode(text), p.encode(text))

    def test_underscore_parity(self):
        from paddle_tpu._native.tokenizer import Tokenizer
        t = Tokenizer(self._vocab())
        p = Tokenizer(self._vocab())
        p._cvocab = None
        # '_' must split as punctuation on BOTH paths (BERT basic tokenizer)
        np.testing.assert_array_equal(t.encode('foo_bar'),
                                      p.encode('foo_bar'))
        ids = p.encode('foo_bar')
        v = self._vocab()
        assert ids.tolist() == [v['foo'], v['_'], v['bar']]


class TestPipelineDataAxis:
    def test_dp_pp_mesh_batch_sharded(self):
        """pipeline_apply on a dp×pp mesh: batch shards over 'data', result
        matches the sequential stage stack (advisor finding d)."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from paddle_tpu.distributed.pipeline import (
            pipeline_apply, stack_stage_params)

        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ('data', 'pipe'))

        S, B, F = 4, 8, 16
        rng = np.random.default_rng(0)
        per_stage = [{'w': jnp.asarray(
            rng.standard_normal((F, F)).astype('float32') * 0.3)}
            for _ in range(S)]
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.standard_normal((B, F)).astype('float32'))

        def stage_fn(p, mb):
            return jnp.tanh(mb @ p['w'])

        out = pipeline_apply(stage_fn, stacked, x, n_microbatches=4,
                             mesh=mesh)
        ref = x
        for p in per_stage:
            ref = jnp.tanh(ref @ p['w'])
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-6)

    def test_dp_pp_gradient_parity(self):
        import jax
        import jax.numpy as jnp
        from jax.sharding import Mesh
        from paddle_tpu.distributed.pipeline import (
            pipeline_apply, stack_stage_params)

        devs = np.array(jax.devices()[:8]).reshape(2, 4)
        mesh = Mesh(devs, ('data', 'pipe'))
        S, B, F = 4, 8, 8
        rng = np.random.default_rng(1)
        per_stage = [{'w': jnp.asarray(
            rng.standard_normal((F, F)).astype('float32') * 0.3)}
            for _ in range(S)]
        stacked = stack_stage_params(per_stage)
        x = jnp.asarray(rng.standard_normal((B, F)).astype('float32'))

        def stage_fn(p, mb):
            return jnp.tanh(mb @ p['w'])

        def loss_pipe(sp):
            return (pipeline_apply(stage_fn, sp, x, 4, mesh=mesh) ** 2).sum()

        def loss_ref(stages):
            h = x
            for p in stages:
                h = jnp.tanh(h @ p['w'])
            return (h ** 2).sum()

        g_pipe = jax.grad(loss_pipe)(stacked)
        g_ref = jax.grad(loss_ref)(per_stage)
        for i in range(S):
            np.testing.assert_allclose(np.asarray(g_pipe['w'][i]),
                                       np.asarray(g_ref[i]['w']),
                                       rtol=3e-4, atol=3e-5)


class TestSequenceMaskJit:
    def test_eager_maxlen_none(self):
        import paddle_tpu.nn.functional as F
        m = F.sequence_mask(paddle.to_tensor([2, 3, 1]))
        assert m.shape == [3, 3]
        np.testing.assert_array_equal(
            m.numpy(), [[1, 1, 0], [1, 1, 1], [1, 0, 0]])

    def test_traced_maxlen_none_raises_clearly(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.jit import to_static

        @to_static
        def f(x):
            return F.sequence_mask(x)

        with pytest.raises(Exception, match="maxlen"):
            f(paddle.to_tensor([2, 3, 1]))

    def test_traced_with_maxlen_works(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.jit import to_static

        @to_static
        def f(x):
            return F.sequence_mask(x, maxlen=4)

        m = f(paddle.to_tensor([2, 4, 1]))
        np.testing.assert_array_equal(
            m.numpy(), [[1, 1, 0, 0], [1, 1, 1, 1], [1, 0, 0, 0]])


class TestClassCenterSample:
    def test_positives_always_kept_and_remapped(self):
        import paddle_tpu.nn.functional as F
        paddle.seed(11)
        label = paddle.to_tensor(np.array([3, 7, 3, 42, 99], dtype='int64'))
        remapped, sampled = F.class_center_sample(label, 100, 10)
        s = sampled.numpy()
        assert len(s) == 10 and sorted(s.tolist()) == s.tolist()
        for cls in (3, 7, 42, 99):
            assert cls in s
        r = remapped.numpy()
        for lab, rm in zip([3, 7, 3, 42, 99], r):
            assert s[rm] == lab
        # negatives differ across seeds (it actually samples)
        paddle.seed(12)
        _, sampled2 = F.class_center_sample(label, 100, 10)
        assert not np.array_equal(s, sampled2.numpy())

    def test_all_classes_when_samples_exceed(self):
        import paddle_tpu.nn.functional as F
        label = paddle.to_tensor(np.array([1, 2], dtype='int64'))
        remapped, sampled = F.class_center_sample(label, 8, 8)
        np.testing.assert_array_equal(sampled.numpy(), np.arange(8))
        np.testing.assert_array_equal(remapped.numpy(), [1, 2])

    def test_jit_safe(self):
        import paddle_tpu.nn.functional as F
        from paddle_tpu.jit import to_static

        @to_static
        def f(label):
            return F.class_center_sample(label, 50, 5)

        remapped, sampled = f(
            paddle.to_tensor(np.array([4, 9], dtype='int64')))
        s = sampled.numpy()
        assert 4 in s and 9 in s and len(s) == 5


class TestGPTRingAttention:
    """Long-context flagship: GPT with ring attention over the 'seq' mesh
    axis must match the plain-attention GPT bit-for-bit (fwd + grads)."""

    def _models(self):
        import paddle_tpu as paddle
        from paddle_tpu.text import GPTConfig, GPTModel
        kw = dict(vocab_size=128, hidden_size=32, num_layers=2,
                  num_heads=2, max_seq_len=64, dropout=0.0)
        paddle.seed(21)
        plain = GPTModel(GPTConfig(**kw))
        paddle.seed(21)
        ring = GPTModel(GPTConfig(use_ring_attention=True, **kw))
        ring.set_state_dict(plain.state_dict())
        return plain, ring

    def test_forward_and_grad_parity_on_seq_mesh(self):
        import jax
        import paddle_tpu as paddle
        from paddle_tpu.distributed import env as denv
        from paddle_tpu import nn
        prev = denv.get_mesh()
        denv.init_parallel_env((8,), ('seq',))
        try:
            plain, ring = self._models()
            ids = np.random.default_rng(0).integers(
                0, 128, (2, 64)).astype('int64')
            x = paddle.to_tensor(ids)
            lp = nn.functional.cross_entropy(
                plain(x).reshape([-1, 128]),
                paddle.to_tensor(ids.reshape(-1)))
            lr = nn.functional.cross_entropy(
                ring(x).reshape([-1, 128]),
                paddle.to_tensor(ids.reshape(-1)))
            np.testing.assert_allclose(float(lp.numpy()),
                                       float(lr.numpy()), rtol=2e-5)
            lp.backward()
            lr.backward()
            gp = {n: p.grad.numpy() for n, p in plain.named_parameters()
                  if p.grad is not None}
            gr = {n: p.grad.numpy() for n, p in ring.named_parameters()
                  if p.grad is not None}
            assert gp.keys() == gr.keys() and len(gp) > 0
            for n in gp:
                np.testing.assert_allclose(gr[n], gp[n], rtol=2e-4,
                                           atol=2e-5)
        finally:
            denv.set_mesh(prev)
