"""paddle_tpu.analysis: AST lint rules (GL) + Program verifier (GV).

Acceptance anchor: >= 10 distinct rule IDs fire on seeded fixtures
(>= 5 AST rules, >= 5 verifier checks), each with file:line findings and
JSON reporter output; Executor.run(verify=True) turns structural errors
into actionable ProgramVerificationError before compilation.
"""
import json
import os

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
from paddle_tpu import analysis
from paddle_tpu.analysis import (Finding, ProgramVerificationError,
                                 lint_paths, lint_source, render_json,
                                 verify_program)
from paddle_tpu.analysis.config import (Config, load_config, parse_toml_min)
from paddle_tpu.analysis.testing import KINDS, malform, well_formed_program

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# Engine 1: AST rules on seeded fixtures
# ---------------------------------------------------------------------------

# one fixture snippet per rule: (rule id, source, substring of the flagged
# line) — the line number assertion pins findings to real locations
AST_FIXTURES = {
    'GL001': ("import jax, numpy as np\n"
              "@jax.jit\n"
              "def f(x):\n"
              "    return np.asarray(x)\n", "np.asarray"),
    'GL002': ("import jax\n"
              "@jax.jit\n"
              "def f(x):\n"
              "    return float(x)\n", "float(x)"),
    'GL003': ("import jax\n"
              "@jax.jit\n"
              "def f(x):\n"
              "    return jax.device_get(x)\n", "jax.device_get"),
    'GL004': ("import jax\n"
              "@jax.jit\n"
              "def f(x, opts=[]):\n"
              "    return x\n", "opts=[]"),
    'GL005': ("import jax\n"
              "def g(x):\n"
              "    return x\n"
              "fast = jax.jit(g)\n"
              "def use():\n"
              "    return fast([1, 2])\n", "fast([1, 2])"),
    'GL006': ("import jax\n"
              "@jax.jit\n"
              "def f(x):\n"
              "    if x:\n"
              "        return x\n"
              "    return x\n", "if x:"),
    'GL007': ("import jax, time\n"
              "@jax.jit\n"
              "def f(x):\n"
              "    return x + time.time()\n", "time.time"),
    'GL008': ("import jax\n"
              "import numpy as np\n"
              "@jax.jit\n"
              "def f(x):\n"
              "    return x + np.random.rand(3)\n", "np.random.rand"),
    'GL009': ("import jax\n"
              "def f(x):\n"
              "    jax.debug.print('x={}', x)\n"
              "    return x\n", "jax.debug.print"),
    'GL010': ("def save(path, blob):\n"
              "    with open(path, 'wb') as f:\n"
              "        f.write(blob)\n", "open(path, 'wb')"),
    'GL011': ("import time\n"
              "def run_step(fn):\n"
              "    t0 = time.perf_counter()\n"
              "    fn()\n"
              "    return time.perf_counter() - t0\n", "time.perf_counter"),
    'GL012': ("import queue\n"
              "def consume():\n"
              "    q = queue.Queue()\n"
              "    return q.get()\n", "q.get()"),
    'GL013': ("import jax\n"
              "import numpy as np\n"
              "def model(x):\n"
              "    return x * 2\n"
              "predict = jax.jit(model)\n"
              "def serve(batch):\n"
              "    n = len(batch)\n"
              "    arr = np.zeros((n, 8), np.float32)\n"
              "    return predict(arr)\n", "predict(arr)"),
    'GL014': ("def train_step(loss, step_ms):\n"
              "    print(f'step loss {loss:.4f} in {step_ms:.1f} ms')\n",
              "print(f'step loss"),
    'GL015': ("import jax\n"
              "def train_step(params, opt_state, batch):\n"
              "    return params, opt_state\n"
              "step = jax.jit(train_step)\n", "jax.jit(train_step)"),
    'GL016': ("import jax\n"
              "def place(params):\n"
              "    return jax.device_put(params)\n",
              "jax.device_put(params)"),
    'GL017': ("import jax\n"
              "@jax.jit\n"
              "def f(x):\n"
              "    mask = x > 0\n"
              "    return x[mask].sum()\n", "x[mask]"),
    'GL018': ("import jax\n"
              "def trace_step(fn):\n"
              "    jax.profiler.start_trace('/tmp/x')\n"
              "    fn()\n"
              "    jax.profiler.stop_trace()\n", "start_trace"),
    'GL019': ("def dispatch_all(replicas, req):\n"
              "    for r in replicas:\n"
              "        try:\n"
              "            return r.submit(req)\n"
              "        except Exception:\n"
              "            pass\n", "except Exception"),
    'GL020': ("_LOG = []\n"
              "def poll(events):\n"
              "    for e in events:\n"
              "        _LOG.append(e)\n", "_LOG.append(e)"),
    'GL022': ("import time\n"
              "def wait_ready(client):\n"
              "    while not client.ready():\n"
              "        time.sleep(0.5)\n", "time.sleep(0.5)"),
}


@pytest.mark.parametrize('rule_id', sorted(AST_FIXTURES))
def test_ast_rule_fires_with_location(rule_id, tmp_path):
    source, needle = AST_FIXTURES[rule_id]
    # GL010 is scoped to checkpoint-path modules: use a matching filename
    name = 'framework.py' if rule_id == 'GL010' else 'fix.py'
    path = tmp_path / name
    path.write_text(source)
    findings, n = lint_paths([str(path)], scan_root=str(tmp_path))
    assert n == 1
    hits = [f for f in findings if f.rule == rule_id]
    assert hits, f"{rule_id} did not fire; got {[f.rule for f in findings]}"
    f = hits[0]
    assert f.path == str(path) and f.line >= 1
    # the finding points at the line containing the anti-pattern
    assert needle in source.splitlines()[f.line - 1]
    assert f.source == 'ast' and f.severity == 'error'


def test_traced_scope_excludes_host_code():
    # the same host-sync calls OUTSIDE traced code are legal
    src = ("import numpy as np\n"
           "def loader(batch):\n"
           "    return np.asarray(batch)\n")
    findings = lint_source('loader.py', src)
    assert [f for f in findings if f.rule == 'GL001'] == []


def test_local_traced_value_is_tainted():
    # GL002 must catch casts on LOCALS derived from traced params, not just
    # the params themselves (the float(loss) pattern)
    src = ("import jax\n"
           "import jax.numpy as jnp\n"
           "@jax.jit\n"
           "def step(params, batch):\n"
           "    logits = batch @ params\n"
           "    loss = jnp.mean(logits)\n"
           "    return float(loss)\n")
    findings = lint_source('step.py', src)
    assert any(f.rule == 'GL002' and f.line == 7 for f in findings)


def test_is_none_flag_is_static_not_tainted():
    # `w is not None` is a host bool — branching on it is the sanctioned
    # static-specialization idiom, not GL006
    src = ("import jax\n"
           "@jax.jit\n"
           "def norm(x, w):\n"
           "    has_w = w is not None\n"
           "    if has_w:\n"
           "        x = x * w\n"
           "    return x\n")
    findings = lint_source('norm.py', src)
    assert [f for f in findings if f.rule == 'GL006'] == []


def test_transitive_traced_helper_is_flagged():
    src = ("import jax\n"
           "def helper(v):\n"
           "    return float(v)\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    return helper(x)\n")
    findings = lint_source('helper.py', src)
    assert any(f.rule == 'GL002' and f.line == 3 for f in findings)


def test_host_callback_is_sanctioned_escape():
    src = ("import jax\n"
           "import numpy as np\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    def report(v):\n"
           "        print(np.asarray(v))\n"
           "    jax.debug.callback(report, x)\n"
           "    return x\n")
    findings = lint_source('cb.py', src)
    assert [f for f in findings if f.rule == 'GL001'] == []


def test_inline_waiver_suppresses_and_records_reason(tmp_path):
    p = tmp_path / 'fix.py'
    p.write_text("import jax, time\n"
                 "@jax.jit\n"
                 "def f(x):\n"
                 "    # graftlint: disable=GL007 — trace-time stamp wanted\n"
                 "    return x + time.time()\n")
    findings, _ = lint_paths([str(p)])
    hits = [f for f in findings if f.rule == 'GL007']
    assert len(hits) == 1 and hits[0].waived
    # waived findings don't count as active
    from paddle_tpu.analysis.finding import active
    assert active(hits) == []


def test_multiline_waiver_comment_block(tmp_path):
    p = tmp_path / 'fix.py'
    p.write_text("import jax, time\n"
                 "@jax.jit\n"
                 "def f(x):\n"
                 "    # graftlint: disable=GL007 — a justification that\n"
                 "    # wraps over two comment lines\n"
                 "    return x + time.time()\n")
    findings, _ = lint_paths([str(p)])
    assert all(f.waived for f in findings if f.rule == 'GL007')


def test_waiver_typos_do_not_blanket_waive(tmp_path):
    # 'disabled' is not a waiver; 'disable=<garbage>' waives nothing;
    # lowercase ids are normalized, not silently widened
    src = ("import jax, time\n@jax.jit\ndef f(x):\n"
           "    {}\n    return x + time.time()\n")
    for comment, waived in [
            ('# graftlint: disabled for now', False),
            ('# graftlint: disable=GL0x7', False),
            ('# graftlint: disable=gl007 — ok lowercase', True),
            ('# graftlint: disable', True)]:
        p = tmp_path / 'fix.py'
        p.write_text(src.format(comment))
        findings, _ = lint_paths([str(p)])
        hits = [f for f in findings if f.rule == 'GL007']
        assert len(hits) == 1 and hits[0].waived is waived, comment


def test_gl010_scope_without_config(tmp_path):
    # GL010's checkpoint scope must survive config-less runs: the scope
    # root defaults to the parent of the path argument
    pkg = tmp_path / 'paddle_tpu' / 'hapi'
    pkg.mkdir(parents=True)
    (pkg / 'model.py').write_text(
        "def save(p):\n    with open(p, 'wb') as f:\n        f.write(b'x')\n")
    findings, _ = lint_paths([str(tmp_path / 'paddle_tpu')])
    assert any(f.rule == 'GL010' for f in findings)


TIMING_SRC = ("import time\n"
              "def f():\n"
              "    return time.perf_counter()\n")


def test_gl011_exempts_tests_tools_bench_and_observability(tmp_path):
    # tests/tools/bench harnesses and the telemetry package itself may read
    # raw clocks; library code may not
    for sub in ('tests', 'tools', 'paddle_tpu/observability'):
        d = tmp_path / sub
        d.mkdir(parents=True, exist_ok=True)
        (d / 'mod.py').write_text(TIMING_SRC)
        findings, _ = lint_paths([str(d / 'mod.py')],
                                 scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL011'] == [], sub
    (tmp_path / 'bench_thing.py').write_text(TIMING_SRC)
    findings, _ = lint_paths([str(tmp_path / 'bench_thing.py')],
                             scan_root=str(tmp_path))
    assert [f for f in findings if f.rule == 'GL011'] == []
    lib = tmp_path / 'paddle_tpu'
    (lib / 'mod.py').write_text(TIMING_SRC)
    findings, _ = lint_paths([str(lib / 'mod.py')],
                             scan_root=str(tmp_path))
    hits = [f for f in findings if f.rule == 'GL011']
    assert len(hits) == 1 and hits[0].line == 3
    assert 'observability.timer' in hits[0].message


def test_gl011_allows_monotonic_deadlines(tmp_path):
    # timeout/deadline math is not duration measurement
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'deadline.py').write_text(
        "import time\n"
        "def wait(timeout):\n"
        "    deadline = time.monotonic() + timeout\n"
        "    return deadline\n")
    findings, _ = lint_paths([str(lib / 'deadline.py')],
                             scan_root=str(tmp_path))
    assert [f for f in findings if f.rule == 'GL011'] == []


_WAIT_SRC = ("import queue, threading, subprocess\n"
             "def pipeline():\n"
             "    q = queue.Queue()\n"
             "    q.get()\n"                          # flagged
             "    q.get(timeout=1)\n"                 # bounded: fine
             "    q.get_nowait()\n"                   # non-blocking: fine
             "    threads = [threading.Thread(target=print)\n"
             "               for _ in range(2)]\n"
             "    for t in threads:\n"
             "        t.join()\n"                     # flagged (container)
             "    p = subprocess.Popen(['ls'])\n"
             "    p.wait()\n"                         # flagged
             "    p.wait(5)\n")                       # bounded: fine


def test_gl012_flags_only_unbounded_waits(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'pipe.py').write_text(_WAIT_SRC)
    findings, _ = lint_paths([str(lib / 'pipe.py')],
                             scan_root=str(tmp_path))
    hits = sorted(f.line for f in findings if f.rule == 'GL012')
    lines = _WAIT_SRC.splitlines()
    assert len(hits) == 3, [(f.rule, f.line) for f in findings]
    assert 'q.get()' in lines[hits[0] - 1]
    assert 't.join()' in lines[hits[1] - 1]
    assert 'p.wait()' in lines[hits[2] - 1]
    msg = [f for f in findings if f.rule == 'GL012'][0].message
    assert 'watchdog' in msg     # fix-it points at the bounded helpers


def test_gl012_exempts_tests_tools_and_watchdog(tmp_path):
    # harnesses and the watchdog module itself may use raw waits
    for rel in ('tests/mod.py', 'tools/mod.py',
                'paddle_tpu/resilience/watchdog.py'):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(_WAIT_SRC)
        findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL012'] == [], rel
    # ...but sibling resilience modules may not
    p = tmp_path / 'paddle_tpu/resilience/other.py'
    p.write_text(_WAIT_SRC)
    findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
    assert [f for f in findings if f.rule == 'GL012'] != []


_DYNSHAPE_SRC = (
    "import jax\n"
    "import numpy as np\n"
    "def model(x):\n"
    "    return x * 2\n"
    "predict = jax.jit(model)\n"
    "def serve_ctor(batch):\n"
    "    n = len(batch)\n"
    "    arr = np.zeros((n, 8), np.float32)\n"
    "    return predict(arr)\n"                       # flagged (dyn ctor)
    "def serve_slice(batch, buf):\n"
    "    return predict(buf[:len(batch)])\n"          # flagged (dyn slice)
    "def serve_scalar(batch, arr):\n"
    "    return predict(arr, len(batch))\n")          # scalar len(): fine


def test_gl013_flags_dynamic_shapes_not_scalars(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'serve.py').write_text(_DYNSHAPE_SRC)
    findings, _ = lint_paths([str(lib / 'serve.py')],
                             scan_root=str(tmp_path))
    hits = sorted(f.line for f in findings if f.rule == 'GL013')
    lines = _DYNSHAPE_SRC.splitlines()
    assert len(hits) == 2, [(f.rule, f.line) for f in findings]
    assert 'predict(arr)' in lines[hits[0] - 1]
    assert 'predict(buf[:len(batch)])' in lines[hits[1] - 1]
    msg = [f for f in findings if f.rule == 'GL013'][0].message
    # fix-it points at the serving bucketing helpers
    assert 'serving.bucketing' in msg and 'pad_to_bucket' in msg


def test_gl013_bucketed_code_is_sanctioned(tmp_path):
    src = (
        "import jax\n"
        "import numpy as np\n"
        "from paddle_tpu.serving.bucketing import (pad_to_bucket,\n"
        "    select_bucket, stack_examples)\n"
        "def model(x):\n"
        "    return x * 2\n"
        "predict = jax.jit(model)\n"
        "def serve(batch):\n"
        "    b = select_bucket(len(batch), (1, 2, 4))\n"
        "    arr = stack_examples(batch, b)\n"
        "    return predict(arr)\n"
        "def serve2(batch):\n"
        "    padded = pad_to_bucket(np.stack(batch), 4)\n"
        "    return predict(padded)\n")
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'bucketed.py').write_text(src)
    findings, _ = lint_paths([str(lib / 'bucketed.py')],
                             scan_root=str(tmp_path))
    assert [f for f in findings if f.rule == 'GL013'] == []


def test_gl013_exempts_tests_and_tools(tmp_path):
    for rel in ('tests/mod.py', 'tools/mod.py', 'bench_load.py'):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(_DYNSHAPE_SRC)
        findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL013'] == [], rel


_EMIT_SRC = (
    "import logging\n"
    "logger = logging.getLogger(__name__)\n"
    "def report(loss, qps, epoch):\n"
    "    print(f'loss {loss:.4f}')\n"                   # flagged (f-string)
    "    logger.info('qps %.2f', qps)\n"                # flagged (%-format)
    "    print('epoch', epoch)\n"                       # narrative: fine
    "    print('done: {} items'.format(epoch))\n")      # no float spec: fine


def test_gl014_flags_metrics_shaped_emission_only(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'emit.py').write_text(_EMIT_SRC)
    findings, _ = lint_paths([str(lib / 'emit.py')],
                             scan_root=str(tmp_path))
    hits = sorted(f.line for f in findings if f.rule == 'GL014')
    assert hits == [4, 5], [(f.rule, f.line) for f in findings]
    msg = [f for f in findings if f.rule == 'GL014'][0].message
    # fix-it points at the telemetry spine
    assert 'observability.event' in msg


def test_gl014_exempts_tests_tools_bench_and_waiver(tmp_path):
    for rel in ('tests/mod.py', 'tools/mod.py', 'bench_load.py',
                'paddle_tpu/observability/exporter.py'):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(_EMIT_SRC)
        findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL014'] == [], rel
    # inline waiver honored
    lib = tmp_path / 'paddle_tpu'
    (lib / 'waived.py').write_text(
        "def report(loss):\n"
        "    # graftlint: disable=GL014 — user-facing verbose output\n"
        "    print(f'loss {loss:.4f}')\n")
    findings, _ = lint_paths([str(lib / 'waived.py')],
                             scan_root=str(tmp_path))
    live = [f for f in findings
            if f.rule == 'GL014' and not getattr(f, 'waived', False)]
    assert live == []


def test_unresolvable_fetch_does_not_flood_gv006():
    prog, _final = well_formed_program(seed=9)
    fs = verify_program(prog, fetch_list=['typo_name'])
    assert {f.rule for f in fs if f.severity == 'error'} == {'GV008'}
    assert [f for f in fs if f.rule == 'GV006'] == []


def test_toml_config_waiver_and_exclude(tmp_path):
    (tmp_path / 'graftlint.toml').write_text(
        '[graftlint]\n'
        'exclude = ["skipme/*"]\n'
        '[[graftlint.waiver]]\n'
        'rule = "GL007"\n'
        'path = "timed.py"\n'
        'reason = "benchmark stub"\n')
    skip = tmp_path / 'skipme'
    skip.mkdir()
    (skip / 'bad.py').write_text("import jax, time\n@jax.jit\n"
                                 "def f(x):\n    return x + time.time()\n")
    (tmp_path / 'timed.py').write_text("import jax, time\n@jax.jit\n"
                                       "def f(x):\n"
                                       "    return x + time.time()\n")
    cfg = load_config(str(tmp_path / 'graftlint.toml'))
    findings, n = lint_paths([str(tmp_path)], config=cfg)
    assert n == 1   # skipme/bad.py never scanned
    hits = [f for f in findings if f.rule == 'GL007']
    assert len(hits) == 1 and hits[0].waived
    assert hits[0].waive_reason == 'benchmark stub'


def test_toml_waiver_requires_reason(tmp_path):
    from paddle_tpu.analysis.config import ConfigError
    (tmp_path / 'graftlint.toml').write_text(
        '[[graftlint.waiver]]\nrule = "GL001"\npath = "x.py"\n')
    with pytest.raises(ConfigError):
        load_config(str(tmp_path / 'graftlint.toml'))


def test_parse_toml_min_subset():
    data = parse_toml_min('# c\n[a]\nx = "s"  # trailing\n'
                          'y = ["p", "q"]\nz = true\n'
                          '[[a.w]]\nr = "1"\n[[a.w]]\nr = "2"\n')
    assert data == {'a': {'x': 's', 'y': ['p', 'q'], 'z': True,
                          'w': [{'r': '1'}, {'r': '2'}]}}


# ---------------------------------------------------------------------------
# Engine 2: verifier on seeded malformed Programs
# ---------------------------------------------------------------------------

ERROR_KINDS = ['dangling_input', 'duplicate_var', 'dtype_mismatch',
               'shape_mismatch', 'undeclared_output', 'bad_fetch']
WARNING_KINDS = ['dead_op', 'unused_var']


def _run_malform(kind, seed):
    res = malform(kind, seed=seed)
    if kind == 'bad_fetch':
        prog, fetch, expect = res
        return verify_program(prog, fetch_list=fetch), expect
    prog, expect = res
    return verify_program(prog), expect


@pytest.mark.parametrize('kind', ERROR_KINDS)
@pytest.mark.parametrize('seed', [0, 7])
def test_verifier_error_kinds_fire_exactly(kind, seed):
    findings, expect = _run_malform(kind, seed)
    errs = [f for f in findings if f.severity == 'error']
    assert {f.rule for f in errs} == {expect}, \
        f"{kind}: expected only {expect}, got {[f.rule for f in errs]}"
    # findings are op-indexed and actionable
    assert all(f.source == 'ir' and f.path == '<program>' for f in errs)
    assert any('block 0' in f.message or 'fetch target' in f.message
               for f in errs)


@pytest.mark.parametrize('kind', WARNING_KINDS)
@pytest.mark.parametrize('seed', [0, 7])
def test_verifier_warning_kinds_fire_exactly(kind, seed):
    findings, expect = _run_malform(kind, seed)
    assert {f.rule for f in findings} == {expect}
    assert all(f.severity == 'warning' for f in findings)


def test_well_formed_program_verifies_clean():
    prog, final = well_formed_program(seed=5)
    assert verify_program(prog, fetch_list=[final]) == []
    assert prog.verify(fetch_list=[final]) == []


_UNDONATED_SRC = (
    "import jax\n"
    "import functools\n"
    "def train_step(params, opt_state, batch):\n"
    "    return params, opt_state\n"
    "step = jax.jit(train_step)\n"                            # flagged
    "donated = jax.jit(train_step, donate_argnums=(0, 1))\n"  # donated: fine
    "@jax.jit\n"
    "def update_step(params, opt_state):\n"                   # flagged
    "    return params, opt_state\n"
    "@functools.partial(jax.jit, donate_argnums=(0,))\n"
    "def third_step(params, opt_state):\n"                    # donated: fine
    "    return params, opt_state\n"
    "def eval_step(params, opt_state):\n"
    "    return params\n"
    "ev = jax.jit(eval_step)\n"                               # name-exempt
    "def forward(params, batch):\n"
    "    return params\n"
    "fw = jax.jit(forward)\n"                 # no opt-state pytree: fine
    "def scan_step(params, opt_state):\n"
    "    return params, opt_state\n"
    "ps = functools.partial(jax.jit, static_argnums=())(scan_step)\n")
    # ^ flagged: the partial(jax.jit, ...) wrapper spelling


def test_gl015_flags_undonated_train_steps(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'steps.py').write_text(_UNDONATED_SRC)
    findings, _ = lint_paths([str(lib / 'steps.py')],
                             scan_root=str(tmp_path))
    hits = sorted(f.line for f in findings if f.rule == 'GL015')
    lines = _UNDONATED_SRC.splitlines()
    assert len(hits) == 3, [(f.rule, f.line) for f in findings]
    assert 'jax.jit(train_step)' in lines[hits[0] - 1]
    assert '@jax.jit' in lines[hits[1] - 1]
    assert 'functools.partial(jax.jit' in lines[hits[2] - 1]
    msg = [f for f in findings if f.rule == 'GL015'][0].message
    # the fix-it points at the unified step builder
    assert 'engine.build_train_step' in msg and 'donate_argnums' in msg


def test_gl015_exempts_engine_tests_tools(tmp_path):
    # the engine package is the sanctioned builder (donation decided at
    # runtime behind the backend gate); harnesses measure, they don't ship
    for rel in ('paddle_tpu/engine/builder.py', 'tests/mod.py',
                'tools/mod.py', 'bench.py'):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(_UNDONATED_SRC)
        findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL015'] == [], rel
    # ...but sibling library packages may not roll their own
    p = tmp_path / 'paddle_tpu/kernels/steps.py'
    p.parent.mkdir(parents=True, exist_ok=True)
    p.write_text(_UNDONATED_SRC)
    findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
    assert [f for f in findings if f.rule == 'GL015'] != []


_DEVICE_PUT_SRC = (
    "import jax\n"
    "from jax.sharding import NamedSharding, PartitionSpec as P\n"
    "def replicate_all(params):\n"
    "    return jax.device_put(params)\n"                  # flagged
    "def pin_one(opt_state):\n"
    "    return jax.device_put(opt_state, jax.devices()[0])\n"  # flagged
    "def upload(state, mesh):\n"
    "    sh = NamedSharding(mesh, P('data'))\n"
    "    return jax.device_put(state, sh)\n"               # sanctioned
    "def upload_batch(x):\n"
    "    return jax.device_put(x)\n")                      # not a pytree


def test_gl016_flags_unsharded_param_device_put(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'place.py').write_text(_DEVICE_PUT_SRC)
    findings, _ = lint_paths([str(lib / 'place.py')],
                             scan_root=str(tmp_path))
    hits = sorted(f.line for f in findings if f.rule == 'GL016')
    lines = _DEVICE_PUT_SRC.splitlines()
    assert len(hits) == 2, [(f.rule, f.line) for f in findings]
    assert 'jax.device_put(params)' in lines[hits[0] - 1]
    assert 'jax.devices()[0]' in lines[hits[1] - 1]
    msg = [f for f in findings if f.rule == 'GL016'][0].message
    # fix-it points at the sharding surface
    assert 'shard_tensor' in msg and 'fsdp_pspecs' in msg
    assert 'build_train_step' in msg


def test_gl016_exempts_harnesses(tmp_path):
    for rel in ('tests/mod.py', 'tools/mod.py', 'bench.py'):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(_DEVICE_PUT_SRC)
        findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL016'] == [], rel


_MASK_INDEX_SRC = (
    "import jax\n"
    "import jax.numpy as jnp\n"
    "@jax.jit\n"
    "def inline_mask(x):\n"
    "    return x[x > 0]\n"                                 # flagged
    "@jax.jit\n"
    "def named_mask(x, lo):\n"
    "    keep = x > lo\n"
    "    return x[keep]\n"                                  # flagged
    "@jax.jit\n"
    "def dyn_nonzero(x):\n"
    "    return jnp.nonzero(x)\n"                           # flagged
    "@jax.jit\n"
    "def one_arg_where(x):\n"
    "    return jnp.where(x > 0)\n"                         # flagged
    "@jax.jit\n"
    "def sized_nonzero(x):\n"
    "    return jnp.nonzero(x, size=8)\n"                   # size= pins shape
    "@jax.jit\n"
    "def three_arg_where(x):\n"
    "    return jnp.where(x > 0, x, 0.0)\n"                 # in-place select
    "@jax.jit\n"
    "def page_gather(cache, block_tables):\n"
    "    return cache[block_tables]\n"                      # fixed-shape gather
    "@jax.jit\n"
    "def where_gather(x, i, j):\n"
    "    return x[jnp.where(x > 0, i, j)]\n"   # the fix-it's OWN pattern
    "@jax.jit\n"
    "def where_gather_named(x, i, j):\n"
    "    idx = jnp.where(x > 0, i, j)\n"
    "    return x[idx]\n"                      # same, via a name
    "def host_filter(x):\n"
    "    return x[x > 0]\n")                                # not traced


def test_gl017_flags_mask_indexing_and_nonzero_in_traced_code(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'masks.py').write_text(_MASK_INDEX_SRC)
    findings, _ = lint_paths([str(lib / 'masks.py')],
                             scan_root=str(tmp_path))
    hits = sorted(f.line for f in findings if f.rule == 'GL017')
    lines = _MASK_INDEX_SRC.splitlines()
    assert len(hits) == 4, [(f.rule, f.line) for f in findings]
    assert 'x[x > 0]' in lines[hits[0] - 1]
    assert 'x[keep]' in lines[hits[1] - 1]
    assert 'jnp.nonzero(x)' in lines[hits[2] - 1]
    assert 'jnp.where(x > 0)' in lines[hits[3] - 1]
    msg = [f for f in findings if f.rule == 'GL017'][0].message
    # fix-it points at the fixed-shape gather / page-index pattern
    assert 'paged_kv' in msg and 'jnp.where' in msg


def test_gl017_exempts_harnesses_and_host_code(tmp_path):
    for rel in ('tests/mod.py', 'tools/mod.py', 'bench.py'):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(_MASK_INDEX_SRC)
        findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL017'] == [], rel
    # the same mask indexing outside any traced function never fires
    host_only = ("import numpy as np\n"
                 "def pick(x):\n"
                 "    mask = x > 0\n"
                 "    return x[mask]\n")
    p = tmp_path / 'lib.py'
    p.write_text(host_only)
    findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
    assert [f for f in findings if f.rule == 'GL017'] == []


def test_gl017_inline_waiver(tmp_path):
    src = ("import jax\n"
           "@jax.jit\n"
           "def f(x):\n"
           "    # graftlint: disable=GL017 — eager-only debug helper\n"
           "    return x[x > 0]\n")
    p = tmp_path / 'lib.py'
    p.write_text(src)
    findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
    hits = [f for f in findings if f.rule == 'GL017']
    assert len(hits) == 1 and hits[0].waived
    from paddle_tpu.analysis.finding import active
    assert active(hits) == []


_PROFILER_SRC = (
    "import jax\n"
    "from paddle_tpu import observability\n"
    "def leaky_trace(fn):\n"
    "    jax.profiler.start_trace('/tmp/x')\n"            # flagged: stop not
    "    fn()\n"                                          # in a finally
    "    jax.profiler.stop_trace()\n"
    "def owned_trace(fn):\n"
    "    jax.profiler.start_trace('/tmp/x')\n"            # sanctioned
    "    try:\n"
    "        fn()\n"
    "    finally:\n"
    "        jax.profiler.stop_trace()\n"
    "def serve_profiler():\n"
    "    jax.profiler.start_server(9999)\n"               # flagged always
    "def leaky_span(fn):\n"
    "    s = observability.span('step')\n"
    "    s.__enter__()\n"                                 # flagged: exit not
    "    fn()\n"                                          # exception-safe
    "    s.__exit__(None, None, None)\n"
    "def owned_span(fn):\n"
    "    s = observability.span('step')\n"
    "    s.__enter__()\n"                                 # sanctioned
    "    try:\n"
    "        fn()\n"
    "    finally:\n"
    "        s.__exit__(None, None, None)\n"
    "def with_span(fn):\n"
    "    with observability.span('step'):\n"              # the fix-it itself
    "        fn()\n")


def test_gl018_flags_unpaired_profiler_and_span_starts(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'prof.py').write_text(_PROFILER_SRC)
    findings, _ = lint_paths([str(lib / 'prof.py')],
                             scan_root=str(tmp_path))
    hits = sorted(f.line for f in findings if f.rule == 'GL018')
    lines = _PROFILER_SRC.splitlines()
    assert len(hits) == 3, [(f.rule, f.line) for f in findings]
    assert 'start_trace' in lines[hits[0] - 1]
    assert 'start_server' in lines[hits[1] - 1]
    assert '__enter__' in lines[hits[2] - 1]
    msg = [f for f in findings if f.rule == 'GL018'][0].message
    # fix-it points at the with-span spelling
    assert 'observability.span' in msg and 'finally' in msg


def test_gl018_exempts_harnesses_and_profiler_wrappers(tmp_path):
    for rel in ('tests/mod.py', 'tools/mod.py', 'bench_x.py',
                'paddle_tpu/observability/mod.py',
                'paddle_tpu/utils/profiler.py'):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(_PROFILER_SRC)
        findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL018'] == [], rel


def test_gl018_inline_waiver(tmp_path):
    src = ("import jax\n"
           "def trace_window(fn):\n"
           "    # graftlint: disable=GL018 — harness owns the stop\n"
           "    jax.profiler.start_trace('/tmp/x')\n"
           "    fn()\n")
    p = tmp_path / 'lib.py'
    p.write_text(src)
    findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
    hits = [f for f in findings if f.rule == 'GL018']
    assert len(hits) == 1 and hits[0].waived
    from paddle_tpu.analysis.finding import active
    assert active(hits) == []


_SWALLOW_SRC = (
    "from paddle_tpu import observability as obs\n"
    "def silent_failover(replicas, req):\n"
    "    for r in replicas:\n"
    "        try:\n"
    "            return r.submit(req)\n"
    "        except Exception:\n"                # flagged: nothing recorded
    "            pass\n"
    "def silent_bare(queue):\n"
    "    while True:\n"
    "        try:\n"
    "            queue.drain()\n"
    "        except:\n"                          # flagged: bare + continue
    "            continue\n"
    "def counted_failover(replicas, req):\n"
    "    for r in replicas:\n"
    "        try:\n"
    "            return r.submit(req)\n"
    "        except Exception:\n"                # sanctioned: emits a counter
    "            obs.counter('dispatch.failed').inc()\n"
    "def narrow_failover(replicas, req):\n"
    "    for r in replicas:\n"
    "        try:\n"
    "            return r.submit(req)\n"
    "        except ConnectionError:\n"          # sanctioned: narrow type
    "            pass\n"
    "def fallback_loop(items):\n"
    "    out = []\n"
    "    for it in items:\n"
    "        try:\n"
    "            v = it.decode()\n"
    "        except Exception:\n"                # sanctioned: fallback assign
    "            v = None\n"
    "        out.append(v)\n"
    "    return out\n"
    "def reraise_last(replicas, req):\n"
    "    for r in replicas:\n"
    "        try:\n"
    "            return r.submit(req)\n"
    "        except Exception:\n"                # sanctioned: re-raises
    "            raise\n"
    "def outside_loop(r, req):\n"
    "    try:\n"
    "        return r.submit(req)\n"
    "    except Exception:\n"                    # sanctioned: not in a loop
    "        pass\n")


def test_gl019_flags_silent_swallow_in_loops(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'disp.py').write_text(_SWALLOW_SRC)
    findings, _ = lint_paths([str(lib / 'disp.py')],
                             scan_root=str(tmp_path))
    hits = sorted(f.line for f in findings if f.rule == 'GL019')
    lines = _SWALLOW_SRC.splitlines()
    assert len(hits) == 2, [(f.rule, f.line) for f in findings]
    assert 'except Exception' in lines[hits[0] - 1]
    assert 'except:' in lines[hits[1] - 1]
    msg = [f for f in findings if f.rule == 'GL019'][0].message
    # fix-it points at the sanctioned retry helper
    assert 'resilience.retry' in msg


def test_gl019_exempts_harnesses(tmp_path):
    for rel in ('tests/mod.py', 'tools/mod.py', 'bench_x.py'):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(_SWALLOW_SRC)
        findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL019'] == [], rel


def test_gl019_inline_waiver(tmp_path):
    src = ("def sweep(items):\n"
           "    for it in items:\n"
           "        try:\n"
           "            it.close()\n"
           "        # graftlint: disable=GL019 — best-effort cleanup\n"
           "        except Exception:\n"
           "            pass\n")
    p = tmp_path / 'lib.py'
    p.write_text(src)
    findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
    hits = [f for f in findings if f.rule == 'GL019']
    assert len(hits) == 1 and hits[0].waived
    from paddle_tpu.analysis.finding import active
    assert active(hits) == []


# ---------------------------------------------------------------------------
# GL020: unbounded in-memory accumulation in library code
# ---------------------------------------------------------------------------

_ACCUM_SRC = (
    "_LOG = []\n"                                  # firing: module global
    "_REG = {}\n"                                  # firing: dict-of-lists
    "def poll(events):\n"
    "    for e in events:\n"
    "        _LOG.append(e)\n"
    "        _REG.setdefault(e, []).append(e)\n"
    "class Hook:\n"
    "    def __init__(self):\n"
    "        self._hist = []\n"
    "    def on_batch_end(self, logs):\n"          # firing: per-step hook
    "        self._hist.append(logs)\n")


def test_gl020_flags_unbounded_accumulation(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'acc.py').write_text(_ACCUM_SRC)
    findings, _ = lint_paths([str(lib / 'acc.py')],
                             scan_root=str(tmp_path))
    hits = sorted(f.line for f in findings if f.rule == 'GL020')
    lines = _ACCUM_SRC.splitlines()
    assert len(hits) == 3, [(f.rule, f.line) for f in findings]
    assert '_LOG.append' in lines[hits[0] - 1]
    # setdefault(...).append(...) is two grow tails on one container —
    # a single finding, not two
    assert '_REG.setdefault' in lines[hits[1] - 1]
    assert 'self._hist.append' in lines[hits[2] - 1]
    msg = [f for f in findings if f.rule == 'GL020'][0].message
    # fix-it points at the bounded spellings
    assert 'deque(maxlen' in msg


def test_gl020_sanctioned_bounded_spellings(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    src = (
        "import collections\n"
        "_RING = collections.deque(maxlen=10)\n"   # structural bound
        "_CAP = []\n"
        "class Hook:\n"
        "    def __init__(self):\n"
        "        self._hist = []\n"
        "    def on_batch_end(self, logs):\n"
        "        self._hist.append(logs)\n"
        "        self._hist[:] = self._hist[-100:]\n"  # slice rotation
        "class Builder:\n"
        "    def __init__(self, items):\n"
        "        self.rows = []\n"
        "        for it in items:\n"               # workload-proportional
        "            self.rows.append(it)\n"
        "def poll(events):\n"
        "    for e in events:\n"
        "        _RING.append(e)\n"
        "        if len(_CAP) < 100:\n"            # len() guard
        "            _CAP.append(e)\n")
    (lib / 'ok.py').write_text(src)
    findings, _ = lint_paths([str(lib / 'ok.py')],
                             scan_root=str(tmp_path))
    assert [f for f in findings if f.rule == 'GL020'] == [], \
        [(f.rule, f.line) for f in findings]


def test_gl020_exempts_harnesses_and_waiver(tmp_path):
    for rel in ('tests/mod.py', 'tools/mod.py', 'bench_x.py'):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(_ACCUM_SRC)
        findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL020'] == [], rel
    # inline waiver honored and excluded from the active set
    p = tmp_path / 'lib.py'
    p.write_text(
        "_LOG = []\n"
        "def poll(events):\n"
        "    for e in events:\n"
        "        _LOG.append(e)"
        "  # graftlint: disable=GL020 — drained by caller each round\n")
    findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
    hits = [f for f in findings if f.rule == 'GL020']
    assert len(hits) == 1 and hits[0].waived
    from paddle_tpu.analysis.finding import active
    assert active(hits) == []


# ---------------------------------------------------------------------------
# GL021: cache-blind serving warmup (raw jax.jit under a warmup class)
# ---------------------------------------------------------------------------

_CACHE_BLIND_SRC = (
    "import jax\n"
    "class Runner:\n"
    "    def __init__(self, spec, jit_compile=True):\n"
    "        self._prefill = jax.jit(spec.prefill)\n"          # flagged
    "        self._decode = jax.jit(spec.decode) if jit_compile \\\n"
    "            else spec.decode\n"                           # flagged
    "        self.helper = spec.helper\n"       # not a serving program
    "    def warmup(self):\n"
    "        return 0\n"
    "class NotARunner:\n"                       # no warmup(): out of shape
    "    def __init__(self, spec):\n"
    "        self._prefill = jax.jit(spec.prefill)\n")


def test_gl021_flags_cache_blind_warmup(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'runner.py').write_text(_CACHE_BLIND_SRC)
    findings, _ = lint_paths([str(lib / 'runner.py')],
                             scan_root=str(tmp_path))
    hits = sorted(f.line for f in findings if f.rule == 'GL021')
    lines = _CACHE_BLIND_SRC.splitlines()
    assert len(hits) == 2, [(f.rule, f.line) for f in findings]
    assert 'self._prefill' in lines[hits[0] - 1]
    assert 'self._decode' in lines[hits[1] - 1]
    msg = [f for f in findings if f.rule == 'GL021'][0].message
    # fix-it points at the persistent compile tier surfaces
    assert 'CachedJit' in msg and 'artifact_dir' in msg


def test_gl021_cache_aware_module_is_sanctioned(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    src = (
        "import jax\n"
        "from paddle_tpu import compilecache as _cc\n"
        "class Runner:\n"
        "    def __init__(self, spec):\n"
        "        self._prefill = _cc.CachedJit(spec.prefill)\n"
        "        self._decode = jax.jit(spec.aux)\n"  # cache-aware module
        "    def warmup(self):\n"
        "        return self._prefill.warm('x')\n")
    (lib / 'ok.py').write_text(src)
    findings, _ = lint_paths([str(lib / 'ok.py')],
                             scan_root=str(tmp_path))
    assert [f for f in findings if f.rule == 'GL021'] == [], \
        [(f.rule, f.line) for f in findings]


def test_gl021_exempts_harnesses_and_waiver(tmp_path):
    for rel in ('tests/mod.py', 'tools/mod.py', 'bench_x.py',
                'paddle_tpu/compilecache/wrap.py'):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(_CACHE_BLIND_SRC)
        findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL021'] == [], rel
    # inline waiver honored and excluded from the active set
    p = tmp_path / 'lib.py'
    p.write_text(
        "import jax\n"
        "class R:\n"
        "    def __init__(self, spec):\n"
        "        self._decode = jax.jit(spec.d)"
        "  # graftlint: disable=GL021 — one-off tool runner\n"
        "    def warmup(self):\n"
        "        return 0\n")
    findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
    hits = [f for f in findings if f.rule == 'GL021']
    assert len(hits) == 1 and hits[0].waived
    from paddle_tpu.analysis.finding import active
    assert active(hits) == []


def test_gl021_repo_serving_runners_lint_clean():
    """The real runners route through CachedJit — the rule must agree."""
    targets = [os.path.join(REPO, 'paddle_tpu', 'serving', f)
               for f in ('runners.py', 'paged_runner.py')]
    findings, n = lint_paths(targets, scan_root=REPO)
    assert n == 2
    assert [f for f in findings if f.rule == 'GL021'] == [], \
        [(f.path, f.line) for f in findings if f.rule == 'GL021']


# ---------------------------------------------------------------------------
# GL022: bare time.sleep retry/poll loop (unbounded, no backoff)
# ---------------------------------------------------------------------------

_BARE_SLEEP_SRC = (
    "import time\n"
    "def wait_ready(client):\n"
    "    while not client.ready():\n"
    "        time.sleep(0.5)\n"                          # flagged
    "def poll_file(path, items):\n"
    "    for _ in range(10):\n"
    "        time.sleep(1.0)\n"                          # flagged too
    "def once():\n"
    "    time.sleep(0.5)\n")                 # not in a loop: out of shape


def test_gl022_flags_bare_sleep_loops(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    (lib / 'mod.py').write_text(_BARE_SLEEP_SRC)
    findings, _ = lint_paths([str(lib / 'mod.py')],
                             scan_root=str(tmp_path))
    hits = sorted(f.line for f in findings if f.rule == 'GL022')
    assert len(hits) == 2, [(f.rule, f.line) for f in findings]
    lines = _BARE_SLEEP_SRC.splitlines()
    assert all('time.sleep' in lines[ln - 1] for ln in hits)
    msg = [f for f in findings if f.rule == 'GL022'][0].message
    # fix-it points at the bounded machinery
    assert 'resilience.retry' in msg and 'WatchdogTimeout' in msg


def test_gl022_deadline_bounded_loop_is_sanctioned(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    src = (
        "import time\n"
        "def wait_ready(client, timeout=5.0):\n"
        "    deadline = time.monotonic() + timeout\n"
        "    while not client.ready():\n"
        "        if time.monotonic() >= deadline:\n"
        "            raise TimeoutError('never became ready')\n"
        "        time.sleep(0.1)\n")
    (lib / 'ok.py').write_text(src)
    findings, _ = lint_paths([str(lib / 'ok.py')],
                             scan_root=str(tmp_path))
    assert [f for f in findings if f.rule == 'GL022'] == [], \
        [(f.rule, f.line) for f in findings]


def test_gl022_backoff_and_retry_aware_are_sanctioned(tmp_path):
    lib = tmp_path / 'paddle_tpu'
    lib.mkdir(exist_ok=True)
    # backoff-shaped delay: arithmetic — it grows, the fix's whole point
    (lib / 'backoff.py').write_text(
        "import time\n"
        "def wait_ready(client):\n"
        "    delay = 0.05\n"
        "    while not client.ready():\n"
        "        time.sleep(delay * 2)\n")
    # module routes retries through the sanctioned machinery
    (lib / 'aware.py').write_text(
        "import time\n"
        "from paddle_tpu.resilience import retry\n"
        "def wait_ready(client):\n"
        "    while not client.ready():\n"
        "        time.sleep(0.5)\n")
    for name in ('backoff.py', 'aware.py'):
        findings, _ = lint_paths([str(lib / name)],
                                 scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL022'] == [], name


def test_gl022_exempts_harnesses_and_waiver(tmp_path):
    for rel in ('tests/mod.py', 'tools/mod.py', 'bench_x.py',
                'paddle_tpu/resilience/mod.py'):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(_BARE_SLEEP_SRC)
        findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        assert [f for f in findings if f.rule == 'GL022'] == [], rel
    # inline waiver honored and excluded from the active set
    p = tmp_path / 'lib.py'
    p.write_text(
        "import time\n"
        "def wait_ready(client):\n"
        "    while not client.ready():\n"
        "        time.sleep(0.5)"
        "  # graftlint: disable=GL022 — caller holds the deadline\n")
    findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
    hits = [f for f in findings if f.rule == 'GL022']
    assert len(hits) == 1 and hits[0].waived
    from paddle_tpu.analysis.finding import active
    assert active(hits) == []


def test_gl022_repo_lints_clean():
    """Every in-tree sleep loop is deadline-bounded (router drain/response
    waits, launch joins, process-pool error drain) — the rule must agree."""
    findings, _ = lint_paths([os.path.join(REPO, 'paddle_tpu')],
                             scan_root=REPO)
    active_hits = [f for f in findings
                   if f.rule == 'GL022' and not f.waived]
    assert active_hits == [], \
        [(f.path, f.line) for f in active_hits]


def test_ten_distinct_rule_ids_on_seeded_fixtures(tmp_path):
    """The acceptance criterion, asserted directly: >=5 AST + >=5 verifier
    rule IDs fire, each finding carrying a location, and the JSON reporter
    round-trips all of them."""
    all_findings = []
    for rule_id, (source, _) in AST_FIXTURES.items():
        name = 'framework.py' if rule_id == 'GL010' else f"{rule_id}.py"
        p = tmp_path / name
        p.write_text(source)
        fs, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        all_findings.extend(fs)
    for kind in KINDS:
        fs, _expect = _run_malform(kind, seed=11)
        all_findings.extend(fs)
    ast_ids = {f.rule for f in all_findings if f.source == 'ast'}
    ir_ids = {f.rule for f in all_findings if f.source == 'ir'}
    assert len(ast_ids) >= 5, ast_ids
    assert len(ir_ids) >= 5, ir_ids
    assert len(ast_ids | ir_ids) >= 10
    assert all(f.line >= 1 for f in all_findings if f.source == 'ast')
    payload = json.loads(render_json(all_findings))
    assert payload['version'] == 1
    assert len(payload['findings']) == len(all_findings)
    got = {f['rule'] for f in payload['findings']}
    assert ast_ids | ir_ids <= got


# ---------------------------------------------------------------------------
# Executor integration: verify-then-run
# ---------------------------------------------------------------------------

@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


def test_executor_run_verify_true_on_malformed():
    prog, expect = malform('dangling_input', seed=2)
    exe = static.Executor()
    fetch = prog.global_block.ops[-1].outputs[0]
    with pytest.raises(ProgramVerificationError) as ei:
        exe.run(prog, feed={}, fetch_list=[fetch], verify=True)
    msg = str(ei.value)
    assert 'GV001' in msg and 'dangling' in msg
    assert 'PADDLE_TPU_VERIFY' in msg     # tells the user how to bypass


def test_executor_run_verify_env_default(monkeypatch):
    prog, expect = malform('dangling_input', seed=2)
    exe = static.Executor()
    fetch = prog.global_block.ops[-1].outputs[0]
    monkeypatch.setenv('PADDLE_TPU_VERIFY', '1')
    with pytest.raises(ProgramVerificationError):
        exe.run(prog, feed={}, fetch_list=[fetch])
    monkeypatch.setenv('PADDLE_TPU_VERIFY', '0')
    # explicit verify=False always wins
    prog2, final2 = well_formed_program(seed=3)
    xvar = prog2.global_block.vars['x_3']
    exe.run(prog2, feed={'x_3': np.ones(xvar.shape, np.float32)},
            fetch_list=[final2], verify=False)


def test_set_always_verify_flag():
    prog, _ = malform('undeclared_output', seed=4)
    exe = static.Executor()
    fetch = prog.global_block.ops[-1].outputs[0]
    old = analysis.set_always_verify(True)
    try:
        with pytest.raises(ProgramVerificationError):
            exe.run(prog, feed={}, fetch_list=[fetch])
    finally:
        analysis.set_always_verify(old)


def test_verified_run_of_real_program_passes(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [4, 8], 'float32')
        y = x * 2.0 + 1.0
    exe = static.Executor()
    xv = np.random.RandomState(0).rand(4, 8).astype(np.float32)
    out = exe.run(main, feed={'x': xv}, fetch_list=[y], verify=True)[0]
    np.testing.assert_allclose(out, xv * 2.0 + 1.0, rtol=1e-6)


def test_verify_accepts_string_and_missing_fetch(static_mode):
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [2, 2], 'float32')
        y = x + 1.0
    fs = main.verify(fetch_list=[y.name])
    assert [f for f in fs if f.severity == 'error'] == []
    fs = main.verify(fetch_list=['definitely_not_there'])
    assert any(f.rule == 'GV008' for f in fs)


# ---------------------------------------------------------------------------
# Reporters / Finding
# ---------------------------------------------------------------------------

def test_finding_render_and_location():
    f = Finding(rule='GL001', message='m', path='a.py', line=3, col=1)
    assert f.location == 'a.py:3'
    assert 'GL001' in f.render() and 'a.py:3' in f.render()
    g = Finding(rule='GV001', message='m', source='ir')
    assert g.location == '<program>'


def test_render_text_tally_and_waived_hidden():
    fs = [Finding(rule='GL001', message='a', path='x.py', line=1),
          Finding(rule='GL007', message='b', path='x.py', line=2,
                  waived=True, waive_reason='why')]
    txt = analysis.render_text(fs)
    assert '1 error(s)' in txt and '1 waived' in txt
    assert 'GL007' not in txt
    assert 'GL007' in analysis.render_text(fs, show_waived=True)


# ---------------------------------------------------------------------------
# Engine 3: concurrency rules (GC001..GC006) on seeded fixtures
# ---------------------------------------------------------------------------

from paddle_tpu.analysis.testing import (CONCURRENCY_KINDS,
                                         concurrency_fixture)


@pytest.mark.parametrize('kind', sorted(CONCURRENCY_KINDS))
def test_concurrency_rule_fires_with_location(kind, tmp_path):
    source, rule, line = concurrency_fixture(kind, seed=5)
    p = tmp_path / 'fabric.py'
    p.write_text(source)
    findings, n = lint_paths([str(p)], scan_root=str(tmp_path))
    assert n == 1
    gc = [f for f in findings if f.rule.startswith('GC')]
    hits = [f for f in gc if f.rule == rule]
    assert hits, f"{rule} did not fire; got {[f.rule for f in findings]}"
    # the fixture trips exactly its own rule, nothing else in the family
    assert {f.rule for f in gc} == {rule}
    f = hits[0]
    assert f.path == str(p) and f.source == 'ast' and f.severity == 'error'
    if line is not None:   # GC002 anchors on whichever acquire closes
        assert any(h.line == line for h in hits), \
            f"{rule} anchored at {[h.line for h in hits]}, wanted {line}"
    else:
        assert all(h.line >= 1 for h in hits)


@pytest.mark.parametrize('kind', sorted(CONCURRENCY_KINDS))
def test_concurrency_sanctioned_variant_is_clean(kind, tmp_path):
    source, _, _ = concurrency_fixture(kind, seed=5, sanctioned=True)
    p = tmp_path / 'fabric.py'
    p.write_text(source)
    findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
    assert [f for f in findings if f.rule.startswith('GC')] == [], \
        [f.render() for f in findings]


@pytest.mark.parametrize('kind', sorted(CONCURRENCY_KINDS))
def test_concurrency_inline_waiver(kind, tmp_path):
    source, rule, line = concurrency_fixture(kind, seed=5)
    lines = source.splitlines()
    if line is None:
        # GC002: waive every acquire line in the cycle-closing function
        lines = [ln + f'  # graftlint: disable={rule} — fixture'
                 if 'with lock_' in ln else ln for ln in lines]
    else:
        lines[line - 1] += f'  # graftlint: disable={rule} — fixture'
    p = tmp_path / 'fabric.py'
    p.write_text('\n'.join(lines) + '\n')
    findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
    hits = [f for f in findings if f.rule == rule]
    assert hits and all(f.waived for f in hits), \
        [(f.rule, f.line, f.waived) for f in findings]
    assert all(f.waive_reason == 'inline disable' for f in hits)
    from paddle_tpu.analysis.finding import active
    assert [f for f in active(findings) if f.rule.startswith('GC')] == []


def test_concurrency_exempts_tests_tools_bench(tmp_path):
    source, _, _ = concurrency_fixture('unguarded_counter', seed=5)
    for rel in ('tests/fix.py', 'tools/fix.py', 'bench_fabric.py'):
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(source)
        findings, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        assert [f for f in findings if f.rule.startswith('GC')] == [], rel


def test_select_gc_family_expansion(tmp_path):
    """--select GC expands to the whole family; exact ids still work;
    unknown families stay a usage error."""
    from paddle_tpu.analysis.cli import main
    from paddle_tpu.analysis.rules import expand_select
    expanded, unknown = expand_select({'GC'})
    assert expanded == {'GC001', 'GC002', 'GC003', 'GC004', 'GC005',
                        'GC006'} and unknown == set()
    expanded, unknown = expand_select({'GC003', 'GL007'})
    assert expanded == {'GC003', 'GL007'} and unknown == set()
    _, unknown = expand_select({'GX'})
    assert unknown == {'GX'}
    source, _, _ = concurrency_fixture('sleep_under_lock', seed=5)
    p = tmp_path / 'fabric.py'
    p.write_text(source)
    assert main(['--no-config', '--select', 'GC', str(p)]) == 1
    assert main(['--no-config', '--select', 'GL', str(p)]) == 0
    assert main(['--no-config', '--select', 'GX', str(p)]) == 2


def test_concurrency_json_reporter(tmp_path, capsys):
    source, rule, line = concurrency_fixture('unjoined_thread', seed=5)
    p = tmp_path / 'fabric.py'
    p.write_text(source)
    from paddle_tpu.analysis.cli import main
    rc = main(['--json', '--no-config', '--select', 'GC', str(p)])
    payload = json.loads(capsys.readouterr().out)
    assert rc == 1 and payload['errors'] == 1
    f = payload['findings'][0]
    assert f['rule'] == rule and f['line'] == line
    assert f['path'] == str(p) and f['severity'] == 'error'


def test_all_six_concurrency_rules_on_seeded_fixtures(tmp_path):
    """Engine-3 acceptance: GC001..GC006 each demonstrated (firing +
    sanctioned) and the JSON reporter round-trips the lot."""
    all_findings = []
    for kind in CONCURRENCY_KINDS:
        src, rule, _ = concurrency_fixture(kind, seed=9)
        p = tmp_path / f'{kind}.py'
        p.write_text(src)
        fs, _ = lint_paths([str(p)], scan_root=str(tmp_path))
        all_findings.extend(fs)
    fired = {f.rule for f in all_findings if f.rule.startswith('GC')}
    assert fired == set(CONCURRENCY_KINDS.values())
    payload = json.loads(render_json(all_findings))
    assert fired <= {f['rule'] for f in payload['findings']}
