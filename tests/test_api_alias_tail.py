"""2.0-beta top-level alias tail + hapi Model inference export."""
import os
import tempfile

import numpy as np

import paddle_tpu as paddle


class TestTopLevelAliases:
    def test_reduce_family(self):
        x = paddle.to_tensor(np.array([[1.0, 2.0], [3.0, 4.0]], np.float32))
        assert float(paddle.reduce_sum(x).numpy()) == 10.0
        assert float(paddle.reduce_prod(x).numpy()) == 24.0
        assert float(paddle.reduce_max(x).numpy()) == 4.0

    def test_inverse_and_addcmul(self):
        m = paddle.to_tensor(np.array([[2.0, 0], [0, 4.0]], np.float32))
        np.testing.assert_allclose(paddle.inverse(m).numpy(),
                                   np.diag([0.5, 0.25]), rtol=1e-5)
        a = paddle.to_tensor(np.ones(3, np.float32))
        out = paddle.addcmul(a, a * 2, a * 3, value=0.5)
        np.testing.assert_allclose(out.numpy(), 1 + 0.5 * 6, rtol=1e-6)

    def test_shuffle_reverse(self):
        x = paddle.to_tensor(np.arange(8, dtype=np.float32))
        s = paddle.shuffle(x)
        assert sorted(s.numpy().tolist()) == list(range(8))
        r = paddle.reverse(x, axis=0)
        np.testing.assert_allclose(r.numpy(), np.arange(8)[::-1])

    def test_lr_decay_factories(self):
        s = paddle.ExponentialDecay(0.1, decay_steps=10, decay_rate=0.5)
        for _ in range(10):
            s.step()
        np.testing.assert_allclose(s.last_lr, 0.05, rtol=1e-6)
        c = paddle.CosineDecay(1.0, step_each_epoch=1, epochs=10)
        assert 0 < c.last_lr <= 1.0

    def test_rng_state_roundtrip(self):
        st = paddle.get_cuda_rng_state()
        a = paddle.rand([4]).numpy()
        paddle.set_cuda_rng_state(st)
        b = paddle.rand([4]).numpy()
        np.testing.assert_allclose(a, b)

    def test_to_variable_and_manual_seed(self):
        v = paddle.to_variable(np.ones(3, np.float32))
        np.testing.assert_allclose(v.numpy(), 1.0)
        paddle.manual_seed(123)


class TestModelInferenceExport:
    def test_save_training_false_is_runnable(self, tmp_path):
        from paddle_tpu.static import InputSpec
        import paddle_tpu.jit as jit
        net = paddle.nn.Sequential(paddle.nn.Linear(4, 8),
                                   paddle.nn.ReLU(),
                                   paddle.nn.Linear(8, 2))
        m = paddle.Model(net, inputs=[InputSpec([None, 4], 'float32')])
        m.prepare(optimizer=paddle.optimizer.Adam(
            parameters=net.parameters()),
            loss=paddle.nn.CrossEntropyLoss())
        path = str(tmp_path / "infer")
        m.save(path, training=False)
        loaded = jit.load(path)
        x = np.random.RandomState(0).randn(3, 4).astype(np.float32)
        net.eval()
        np.testing.assert_allclose(loaded(paddle.to_tensor(x)).numpy(),
                                   net(paddle.to_tensor(x)).numpy(),
                                   rtol=1e-5)

    def test_test_batch_alias(self):
        net = paddle.nn.Linear(4, 2)
        m = paddle.Model(net)
        m.prepare()
        out = m.test_batch([np.zeros((2, 4), np.float32)])
        assert np.asarray(out).shape[-1] == 2
