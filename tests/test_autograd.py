"""Autograd engine tests (parity model: reference OpTest grad checks +
imperative/test_imperative_basic.py)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.core import autograd


def test_backward_simple():
    x = paddle.to_tensor([[1., 2.], [3., 4.]], stop_gradient=False)
    y = (x * x).sum()
    y.backward()
    assert np.allclose(x.grad.numpy(), 2 * x.numpy())


def test_backward_chain():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x
    z = y * x  # x^3
    z.backward()
    assert abs(float(x.grad.numpy()) - 12.0) < 1e-5


def test_backward_accumulates():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    (x * 2).backward()
    (x * 3).backward()
    assert abs(float(x.grad.numpy()) - 5.0) < 1e-6


def test_stop_gradient_blocks():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    y = paddle.to_tensor(2.0, stop_gradient=True)
    z = x * y
    z.backward()
    assert x.grad is not None
    assert y.grad is None


def test_detach():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = x * 2
    d = y.detach()
    assert d.stop_gradient
    z = d * x
    z.backward()
    assert abs(float(x.grad.numpy()) - 6.0) < 1e-5


def test_no_grad_context():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    with paddle.no_grad():
        y = x * 2
    assert y.stop_gradient


def test_grad_api():
    x = paddle.to_tensor([1., 2., 3.], stop_gradient=False)
    y = (x ** 2).sum()
    (g,) = autograd.grad(y, x)
    assert np.allclose(g.numpy(), 2 * x.numpy())


def test_double_grad():
    x = paddle.to_tensor(3.0, stop_gradient=False)
    y = x * x * x
    (g,) = autograd.grad(y, x, create_graph=True)
    (gg,) = autograd.grad(g, x)
    assert abs(float(gg.numpy()) - 18.0) < 1e-4


def test_grad_unused_raises():
    x = paddle.to_tensor(1.0, stop_gradient=False)
    z = paddle.to_tensor(1.0, stop_gradient=False)
    y = x * 2
    with pytest.raises(RuntimeError):
        autograd.grad(y, z)
    (g,) = autograd.grad(y, [z], allow_unused=True)
    assert g is None


def test_multi_output_op_grads():
    from paddle_tpu.tensor.manipulation import split, concat
    x = paddle.to_tensor(np.arange(4.0, dtype='float32'), stop_gradient=False)
    a, b = split(x, 2)
    y = (a * 2).sum() + (b * 3).sum()
    y.backward()
    assert np.allclose(x.grad.numpy(), [2, 2, 3, 3])


def test_retain_graph():
    x = paddle.to_tensor(2.0, stop_gradient=False)
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    assert abs(float(x.grad.numpy()) - 8.0) < 1e-5


def test_backward_matmul_matches_finite_diff():
    rng = np.random.RandomState(0)
    a_np = rng.randn(3, 4).astype('float32')
    b_np = rng.randn(4, 2).astype('float32')
    a = paddle.to_tensor(a_np, stop_gradient=False)
    b = paddle.to_tensor(b_np, stop_gradient=False)
    (a @ b).sum().backward()
    eps = 1e-3
    i, j = 1, 2
    ap = a_np.copy(); ap[i, j] += eps
    am = a_np.copy(); am[i, j] -= eps
    fd = ((ap @ b_np).sum() - (am @ b_np).sum()) / (2 * eps)
    assert abs(a.grad.numpy()[i, j] - fd) < 1e-2


def test_rng_next_key_no_tracer_leak_under_trace():
    """Drawing dropout keys inside a traced region must not poison the
    global generator state for later (eager or traced) calls."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.core.tensor import Tensor

    x = jnp.ones((4, 8), jnp.float32)

    def f(v):
        return F.dropout(Tensor(v), p=0.5, training=True)._value

    jax.make_jaxpr(f)(x)          # trace once: keys drawn inside the trace
    out = jax.jit(f)(x)           # re-trace + run: must not see leaked tracer
    assert np.isfinite(np.asarray(out)).all()
    eager = F.dropout(paddle.to_tensor(np.ones((4, 8), np.float32)),
                      p=0.5, training=True)
    assert np.isfinite(eager.numpy()).all()
