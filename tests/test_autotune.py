"""Attention autotune harness (CPU-testable parts; the flash candidates
themselves only run on TPU hardware)."""
import json
import os

import numpy as np
import pytest

from paddle_tpu.kernels import autotune as at


@pytest.fixture(autouse=True)
def isolated_cache(tmp_path, monkeypatch):
    monkeypatch.setenv('PADDLE_TPU_AUTOTUNE_CACHE',
                       str(tmp_path / 'autotune.json'))
    at.clear_cache()
    yield
    at.clear_cache()


def test_candidate_blocks_divisibility():
    cands = at._candidate_blocks(512, has_kpad=False)
    assert (512, 512) in cands and (256, 128) in cands
    assert all(512 % bq == 0 and 512 % bk == 0 for bq, bk in cands)
    # kpad pins block_k to the full row
    kcands = at._candidate_blocks(512, has_kpad=True)
    assert kcands and all(bk == 512 for _, bk in kcands)
    # non-power-of-two seq: only divisors survive
    assert at._candidate_blocks(384, has_kpad=False) == [(128, 128)]


def test_autotune_records_and_caches(tmp_path):
    dec = at.autotune_attention(2, 2, 128, 16, dtype='float32',
                                budget_s=30.0)
    assert dec is not None and dec['mode'] in ('xla', 'flash')
    sig = at.attention_signature(2, 2, 128, 16, False, False, 0.0,
                                 dtype='float32')
    assert at._CACHE[sig] == dec
    # persisted to disk
    data = json.load(open(os.environ['PADDLE_TPU_AUTOTUNE_CACHE']))
    assert sig in data
    # a fresh process (cache cleared) warm-starts from disk
    at.clear_cache()
    assert at.lookup(2, 2, 128, 16, False, False, 0.0,
                     dtype='float32') == dec


def test_lookup_none_when_untuned():
    assert at.lookup(1, 1, 64, 8, False, False, 0.0) is None


def test_second_call_does_no_timing_work(monkeypatch):
    at.autotune_attention(1, 1, 128, 8, dtype='float32', budget_s=30.0)
    timed = []
    monkeypatch.setattr(at, '_time_step',
                        lambda *a, **k: timed.append(1) or 0.0)
    at.autotune_attention(1, 1, 128, 8, dtype='float32', budget_s=30.0)
    assert timed == []   # pure cache hit, no candidates re-timed


def test_dispatch_skips_lookup_when_ineligible(monkeypatch):
    calls = []
    real_lookup = at.lookup

    def spy(*args, **kw):
        calls.append(args)
        return real_lookup(*args, **kw)

    import paddle_tpu.nn.functional.transformer as tr
    monkeypatch.setattr('paddle_tpu.kernels.autotune.lookup', spy)
    import paddle_tpu as paddle
    q = paddle.to_tensor(np.ones((2, 64, 2, 8), 'float32'))
    out = tr.scaled_dot_product_attention(q, q, q)
    assert tuple(out.shape) == (2, 64, 2, 8)
    # on CPU flash is never eligible, so lookup is skipped entirely
    assert calls == []


class TestDispatchOverride:
    """Force flash-eligibility on CPU (stub backend + stub kernel) and
    check the tuned decision really drives the dispatch."""

    @pytest.fixture
    def flashable(self, monkeypatch):
        import paddle_tpu.nn.functional.transformer as tr
        import paddle_tpu.kernels.flash_attention as fa
        import jax.numpy as jnp
        monkeypatch.setattr(tr.jax, 'default_backend', lambda: 'tpu')
        kernel_calls = []

        def stub_kernel(q, k, v, causal=False, scale=None, kpad_bias=None,
                        dropout_p=0.0, dropout_seed=None,
                        block_q=512, block_k=512, interpret=False):
            kernel_calls.append({'block_q': block_q, 'block_k': block_k})
            s = jnp.einsum('bhqd,bhkd->bhqk', q, k) / np.sqrt(q.shape[-1])
            return jnp.einsum('bhqk,bhkd->bhqd',
                              jnp.asarray(np.ones(1, 'float32')) * 0 +                               jnp.exp(s - s.max(-1, keepdims=True)) /
                              jnp.exp(s - s.max(-1, keepdims=True))
                              .sum(-1, keepdims=True), v)

        monkeypatch.setattr(fa, 'flash_attention_bhld', stub_kernel)
        return tr, kernel_calls

    def _q(self):
        import paddle_tpu as paddle
        return paddle.to_tensor(
            np.random.default_rng(0).standard_normal((2, 1024, 2, 8))
            .astype('float32'))

    def test_tuned_xla_disables_flash(self, flashable):
        tr, kernel_calls = flashable
        sig = at.attention_signature(2, 2, 1024, 8, False, False, 0.0,
                                     dtype='float32')
        at._CACHE[sig] = {'mode': 'xla', 'block_q': 0, 'block_k': 0}
        q = self._q()
        tr.scaled_dot_product_attention(q, q, q, training=False)
        assert kernel_calls == []        # flash suppressed by tuned 'xla'

    def test_tuned_flash_blocks_passed_through(self, flashable):
        tr, kernel_calls = flashable
        sig = at.attention_signature(2, 2, 1024, 8, False, False, 0.0,
                                     dtype='float32')
        at._CACHE[sig] = {'mode': 'flash', 'block_q': 256, 'block_k': 128}
        q = self._q()
        tr.scaled_dot_product_attention(q, q, q, training=False)
        assert kernel_calls and kernel_calls[0] == {'block_q': 256,
                                                    'block_k': 128}

    def test_malformed_cache_entry_falls_back(self, flashable):
        tr, kernel_calls = flashable
        sig = at.attention_signature(2, 2, 1024, 8, False, False, 0.0,
                                     dtype='float32')
        at._CACHE[sig] = {'mode': 'flash'}    # missing block fields
        q = self._q()
        out = tr.scaled_dot_product_attention(q, q, q, training=False)
        # treated as untuned: static heuristic (seq 1024 >= 512 -> flash
        # with default blocks), and no crash
        assert tuple(out.shape) == (2, 1024, 2, 8)
        assert kernel_calls and kernel_calls[0] == {'block_q': 512,
                                                    'block_k': 512}


def test_invalid_flash_blocks_treated_untuned():
    sig = at.attention_signature(2, 2, 1024, 8, False, False, 0.0,
                                 dtype='float32')
    for bad in ({'mode': 'flash', 'block_q': 0, 'block_k': 0},
                {'mode': 'flash', 'block_q': 384, 'block_k': 512},
                {'mode': 'flash', 'block_q': 2048, 'block_k': 512}):
        at._CACHE[sig] = bad
        assert at.lookup(2, 2, 1024, 8, False, False, 0.0,
                         dtype='float32') is None
    at._CACHE[sig] = {'mode': 'flash', 'block_q': 256, 'block_k': 512}
    assert at.lookup(2, 2, 1024, 8, False, False, 0.0,
                     dtype='float32') is not None
