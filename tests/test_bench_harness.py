"""The bench.py orchestrator's partial-result contract.

The accel child prints a CUMULATIVE result line after each completed
section and marks the final line ``"complete": true``; the parent
(_run_child) must (a) salvage the last line when the child times out or
crashes mid-run, annotating it as partial, and (b) NOT annotate a result
whose final complete line was printed (teardown noise after the real
result). A cold compile over the remote tunnel can outlive any budget, so
this is the difference between BENCH_r{N}.json carrying real measurements
and losing everything to one slow section.
"""
import json
import subprocess
import sys

sys.path.insert(0, __file__.rsplit('/', 2)[0])

import bench  # noqa: E402


def _line(value, complete=False, **extras):
    obj = {"metric": "m", "value": value, "unit": "s",
           "vs_baseline": value / 10.0}
    if extras:
        obj["extras"] = extras
    if complete:
        obj["complete"] = True
    return json.dumps(obj)


def _with_fake_run(fake, *args):
    real = subprocess.run
    subprocess.run = fake
    try:
        return bench._run_child(*args)
    finally:
        subprocess.run = real


def test_tail_json_picks_last_parseable_line():
    text = "\n".join([_line(1.0), "garbage {not json", _line(2.0), "trail"])
    assert bench._tail_json(text)["value"] == 2.0
    assert bench._tail_json("no json here") is None


def test_timeout_salvages_partial_and_annotates():
    out = (_line(1.0) + "\n" + _line(2.0, seq512_samples_per_sec=88.0)
           + "\n").encode()

    def fake(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get('timeout'), output=out)

    obj, err = _with_fake_run(fake, 'accel', 'bert', 123.0)
    assert err is None
    assert obj["value"] == 2.0
    assert obj["extras"]["seq512_samples_per_sec"] == 88.0
    assert "partial results" in obj["error"]


def test_timeout_after_complete_line_is_not_partial():
    out = (_line(2.0, complete=True) + "\n").encode()

    def fake(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get('timeout'), output=out)

    obj, err = _with_fake_run(fake, 'accel', 'bert', 60.0)
    assert err is None and "error" not in obj


def test_timeout_with_no_output_is_an_error():
    def fake(cmd, **kw):
        raise subprocess.TimeoutExpired(cmd, kw.get('timeout'), output=b"")

    obj, err = _with_fake_run(fake, 'accel', 'bert', 5.0)
    assert obj is None and "timed out" in err


def test_crash_after_partial_line_is_annotated():
    def fake(cmd, **kw):
        cp = subprocess.CompletedProcess(cmd, 1)
        cp.stdout = _line(3.0) + "\n"
        cp.stderr = "boom"
        return cp

    obj, err = _with_fake_run(fake, 'accel', 'bert', 60.0)
    assert err is None
    assert obj["value"] == 3.0
    assert "crashed rc=1" in obj["error"]


def test_crash_after_complete_line_is_teardown_noise():
    def fake(cmd, **kw):
        cp = subprocess.CompletedProcess(cmd, 1)
        cp.stdout = _line(3.0, complete=True) + "\n"
        cp.stderr = "teardown noise"
        return cp

    obj, err = _with_fake_run(fake, 'accel', 'bert', 60.0)
    assert err is None and "error" not in obj


def test_onchip_history_fallback(tmp_path, monkeypatch):
    """With the tunnel wedged, the freshest recorded on-chip measurements
    (stage entries and accel-child cumulative lines) become the result —
    labeled with measurement time — instead of a CPU smoke number."""
    monkeypatch.setattr(bench, 'ONCHIP_HISTORY',
                        str(tmp_path / 'hist.jsonl'))
    assert bench._result_from_history([]) is None  # no file -> no result
    bench.record_onchip({'stage': 'bert128', 'samples_per_sec': 480.5})
    bench.record_onchip({'stage': 'bert512', 'samples_per_sec': 92.1})
    bench.record_onchip({'stage': 'resnet50', 'images_per_sec': 2600.0})
    bench.record_onchip({'stage': 'resnet50', 'images_per_sec': 2700.0})
    r = bench._result_from_history(['probe hung'])
    assert r['value'] == 480.5
    assert r['vs_baseline'] == round(480.5 / bench.BASELINE_SAMPLES_PER_SEC,
                                     4)
    assert r['extras']['seq512_samples_per_sec'] == 92.1
    # same-ts tie goes to the later line
    assert r['extras']['resnet50_images_per_sec'] == 2700.0
    assert 'onchip_history' in r['source'] and 'git' in r['source']
    assert 'probe hung' in r['error']
    # a newer accel-child cumulative line outranks the stage entries
    bench.record_onchip({
        'metric': 'bert_large_pretrain_samples_per_sec_per_chip',
        'value': 500.0, 'extras': {'seq512_samples_per_sec': 95.0}})
    assert bench._result_from_history([])['value'] == 500.0
