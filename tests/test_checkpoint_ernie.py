"""Async checkpoint/resume + ERNIE knowledge-masking tests."""
import os
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.incubate.checkpoint import (
    AutoCheckpoint, AsyncCheckpointer, save_checkpoint, load_checkpoint)


def _tiny_model_and_opt():
    paddle.seed(7)
    m = nn.Linear(4, 3)
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=m.parameters())
    return m, opt


def _train_steps(m, opt, n, seed=0):
    rng = np.random.default_rng(seed)
    for _ in range(n):
        x = paddle.to_tensor(rng.standard_normal((8, 4)).astype('float32'))
        y = paddle.to_tensor(rng.standard_normal((8, 3)).astype('float32'))
        loss = ((m(x) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()


class TestCheckpoint:
    def test_sync_roundtrip(self, tmp_path):
        m, opt = _tiny_model_and_opt()
        _train_steps(m, opt, 3)
        save_checkpoint(str(tmp_path), m, opt, step=3)
        m2, opt2 = _tiny_model_and_opt()
        meta = load_checkpoint(str(tmp_path), m2, opt2)
        assert meta['step'] == 3
        for (k, a), (_, b) in zip(sorted(m.state_dict().items()),
                                  sorted(m2.state_dict().items())):
            np.testing.assert_allclose(a.numpy(), b.numpy())

    def test_async_overlaps_and_snapshot_isolated(self, tmp_path):
        """The async save snapshots state at save() time: training continues
        mutating params, yet the checkpoint on disk holds the old values."""
        m, opt = _tiny_model_and_opt()
        _train_steps(m, opt, 2)
        frozen = {k: v.numpy().copy() for k, v in m.state_dict().items()}
        ck = save_checkpoint(str(tmp_path), m, opt, step=2, async_save=True)
        _train_steps(m, opt, 5, seed=1)   # mutate AFTER snapshot
        ck.wait_until_finished()
        m2, _ = _tiny_model_and_opt()
        meta = load_checkpoint(str(tmp_path), m2)
        assert meta['step'] == 2
        for k, v in m2.state_dict().items():
            np.testing.assert_allclose(v.numpy(), frozen[k])
        # and the live model really did move on
        assert not np.allclose(m.state_dict()['weight'].numpy(),
                               frozen['weight'])

    def test_async_writes_on_background_thread(self, tmp_path):
        m, opt = _tiny_model_and_opt()
        seen = []
        orig = os.rename

        def spy(src, dst):
            seen.append(threading.current_thread().name)
            return orig(src, dst)

        os.rename = spy
        try:
            ck = AsyncCheckpointer(str(tmp_path))
            ck.save(m, opt, step=1)
            ck.wait_until_finished()
        finally:
            os.rename = orig
        assert any('paddle-tpu-ckpt' in n for n in seen)

    def test_resume_mid_training(self, tmp_path):
        """Crash after step 10, resume, continue — matches an uninterrupted
        run bit-for-bit (data replay keyed off the restored step)."""
        def run(upto, auto):
            m, opt = _tiny_model_and_opt()
            auto.layer, auto.optimizer = m, opt
            start = auto.resume()
            rng = np.random.default_rng(123)
            for s in range(upto):
                x = rng.standard_normal((8, 4)).astype('float32')
                y = rng.standard_normal((8, 3)).astype('float32')
                if s < start:
                    continue   # replay RNG stream only
                loss = ((m(paddle.to_tensor(x)) - paddle.to_tensor(y)) ** 2
                        ).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                auto.step = s + 1
                if auto.step % auto.save_every == 0:
                    auto._ck.save(m, opt, auto.step)
            auto.wait_until_finished()
            return m

        p = str(tmp_path / 'auto')
        # interrupted run: 12 steps, checkpoints every 5 -> latest is step 10
        run(12, AutoCheckpoint(p, save_every=5))
        resumed = run(20, AutoCheckpoint(p, save_every=5))
        clean = _tiny_model_and_opt()
        m_clean, opt_clean = clean
        rng = np.random.default_rng(123)
        for _ in range(20):
            x = rng.standard_normal((8, 4)).astype('float32')
            y = rng.standard_normal((8, 3)).astype('float32')
            loss = ((m_clean(paddle.to_tensor(x)) -
                     paddle.to_tensor(y)) ** 2).mean()
            loss.backward()
            opt_clean.step()
            opt_clean.clear_grad()
        np.testing.assert_allclose(resumed.state_dict()['weight'].numpy(),
                                   m_clean.state_dict()['weight'].numpy(),
                                   rtol=1e-5, atol=1e-6)

    def test_partial_write_invisible(self, tmp_path):
        """A torn write (tmp dir left behind, no rename) must not be seen."""
        m, opt = _tiny_model_and_opt()
        save_checkpoint(str(tmp_path), m, opt, step=5)
        torn = tmp_path / '.tmp-ckpt-9-999'
        torn.mkdir()
        (torn / 'meta.json').write_text('{"step": 9}')
        meta = load_checkpoint(str(tmp_path))
        assert meta['step'] == 5

    def test_max_keep_prunes(self, tmp_path):
        m, opt = _tiny_model_and_opt()
        ck = AsyncCheckpointer(str(tmp_path), max_keep=2)
        for s in (1, 2, 3, 4):
            ck.save(m, opt, step=s)
        ck.wait_until_finished()
        kept = sorted(d for d in os.listdir(str(tmp_path))
                      if d.startswith('ckpt-'))
        assert kept == ['ckpt-3', 'ckpt-4']

    def test_worker_error_surfaces(self, tmp_path):
        target = tmp_path / 'file_not_dir'   # unwritable checkpoint root
        target.write_text('x')
        ck = AsyncCheckpointer(str(target))
        ck.save(step=1)
        with pytest.raises(Exception):
            ck.wait_until_finished()


class TestErnieMasking:
    def _sample(self):
        # words:  tok: [w0, w0, w1, w2, w2, w2, w3, pad]
        ids = np.array([11, 12, 13, 14, 15, 16, 17, 0])
        words = np.array([0, 0, 1, 2, 2, 2, 3, -1])
        return ids, words

    def test_whole_word_units(self):
        from paddle_tpu.text import ernie_knowledge_mask
        rng = np.random.default_rng(0)
        for _ in range(50):
            ids, words = self._sample()
            out, pos, lab = ernie_knowledge_mask(
                ids, words, vocab_size=100, max_predictions=8, mask_token_id=99,
                masked_lm_prob=0.4, rng=rng)
            k = int((lab >= 0).sum())
            masked_words = set(int(words[p]) for p in pos[:k])
            # every masked word is masked completely
            for w in masked_words:
                toks = np.flatnonzero(words == w)
                assert set(toks) <= set(int(p) for p in pos[:k])
            # labels record the original ids
            for p, l in zip(pos[:k], lab[:k]):
                assert int(l) == int(ids[p])
            # padding (-1 word) is never masked
            assert all(words[p] >= 0 for p in pos[:k])

    def test_phrase_span_masked_as_unit(self):
        from paddle_tpu.text import ernie_knowledge_mask
        ids, words = self._sample()
        hit = False
        rng = np.random.default_rng(3)
        for _ in range(60):
            out, pos, lab = ernie_knowledge_mask(
                ids, words, vocab_size=100, max_predictions=8, mask_token_id=99,
                masked_lm_prob=0.3, phrase_spans=[(1, 3)], rng=rng)
            k = int((lab >= 0).sum())
            mw = set(int(words[p]) for p in pos[:k])
            if 1 in mw or 2 in mw:
                assert {1, 2} <= mw   # phrase words always fall together
                hit = True
        assert hit

    def test_static_output_shapes(self):
        from paddle_tpu.text import ernie_mask_batch
        ids, words = self._sample()
        bi, bp, bl = ernie_mask_batch([ids, ids], [words, words],
                                      vocab_size=100, max_predictions=6,
                                      mask_token_id=99, seed=0)
        assert bi.shape == (2, 8) and bp.shape == (2, 6) \
            and bl.shape == (2, 6)

    def test_pretrain_forward_on_masked_batch(self):
        from paddle_tpu.text import ErnieForPretraining, ErnieConfig, \
            ernie_mask_batch
        cfg = ErnieConfig(vocab_size=100, hidden_size=32,
                          num_hidden_layers=1, num_attention_heads=2,
                          intermediate_size=64, max_position_embeddings=16)
        model = ErnieForPretraining(cfg)
        ids, words = self._sample()
        bi, bp, bl = ernie_mask_batch([ids, ids], [words, words],
                                      vocab_size=100, max_predictions=4,
                                      mask_token_id=99, seed=1)
        logits, nsp = model(paddle.to_tensor(bi),
                            masked_positions=paddle.to_tensor(bp))
        assert tuple(logits.shape) == (2, 4, 100)
        loss = model.pretraining_loss(
            logits, nsp, paddle.to_tensor(bl),
            paddle.to_tensor(np.zeros((2, 1), dtype='int64')))
        assert np.isfinite(float(loss.numpy()))
