"""Zero-compile fleet boot (ISSUE 19): the persistent compile cache.

Acceptance anchors:

- a fresh subprocess registering a model against a POPULATED cache dir
  serves its first request with ``jax.compiles == 0`` and
  ``compilecache.hit_rate == 1.0`` (the headline: second boot compiles
  nothing);
- corrupt / version-skewed entries fall back to live compilation —
  counted as ``incompat``, request still succeeds, never fatal;
- cache-loaded outputs are bitwise-equal to freshly compiled ones;
- the doctor's ``cold_compile_storm`` detector fires on the
  faultinject-reproduced poisoned-cache shape and stays quiet on
  healthy boots;
- ``tools/compilecache.py`` lists/verifies/GCs the cache from the
  manifest alone (stdlib-only);
- ``engine.fit(serve_artifacts=...)`` exports the serving program set a
  replica then boots from, and ``FleetSupervisor(artifact_dir=...)``
  relaunches without recompiling.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import jax.numpy as jnp

from paddle_tpu import compilecache as cc
from paddle_tpu import observability as obs
from paddle_tpu.observability import doctor as doc
from paddle_tpu.resilience import faultinject

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _cc_isolation():
    cc.reset_stats()
    yield
    cc.disable()
    cc.reset_stats()


def _warm_one(root, label='t.double', n=8):
    """One CachedJit program warmed against ``root``; returns the output."""
    cc.enable(root)
    cj = cc.CachedJit(lambda x: x * 2.0 + 1.0)
    return np.asarray(cj.warm(label, jnp.asarray(np.arange(n, dtype=np.float32))))


# ---------------------------------------------------------------------------
# round-trip + bitwise parity
# ---------------------------------------------------------------------------

class TestRoundTrip:
    def test_second_bind_hits_and_is_bitwise_equal(self, tmp_path):
        fresh = _warm_one(str(tmp_path))
        assert cc.stats()['misses'] == 1 and cc.stats()['stores'] == 1
        cc.reset_stats()
        loaded = _warm_one(str(tmp_path))      # fresh CompileCache binding
        st = cc.stats()
        assert st['hits'] == 1 and st['misses'] == 0
        assert cc.hit_rate() == 1.0
        # bitwise, not allclose: the deserialized executable IS the
        # compiled program, same bytes out
        assert fresh.tobytes() == loaded.tobytes()

    def test_no_cache_bound_is_bypassing_noop(self):
        cc.disable()
        cj = cc.CachedJit(lambda x: x + 1.0)
        out = cj.warm('t.off', jnp.asarray(np.ones((4,), np.float32)))
        assert np.allclose(np.asarray(out), 2.0)
        st = cc.stats()
        assert st['hits'] == st['misses'] == st['stores'] == 0

    def test_signature_mismatch_is_a_distinct_key(self, tmp_path):
        _warm_one(str(tmp_path), n=8)
        cc.reset_stats()
        _warm_one(str(tmp_path), n=16)         # same label, new shape
        st = cc.stats()
        assert st['hits'] == 0 and st['misses'] == 1


# ---------------------------------------------------------------------------
# fallback: corrupt bytes / version skew are counted, never fatal
# ---------------------------------------------------------------------------

class TestFallback:
    def test_corrupt_entry_falls_back_to_live_compile(self, tmp_path):
        want = _warm_one(str(tmp_path))
        damaged = faultinject.corrupt_compile_cache(str(tmp_path))
        assert damaged, 'fault injector found no entries to corrupt'
        cc.reset_stats()
        got = _warm_one(str(tmp_path))
        st = cc.stats()
        assert st['incompat'] >= 1, st      # CRC rejected the torn bytes
        assert st['hits'] == 0
        assert st['stores'] >= 1            # recompiled AND re-committed
        assert got.tobytes() == want.tobytes()

    def test_version_skew_falls_back_to_live_compile(self, tmp_path):
        want = _warm_one(str(tmp_path))
        faultinject.corrupt_compile_cache(str(tmp_path), mode='skew')
        cc.reset_stats()
        got = _warm_one(str(tmp_path))
        st = cc.stats()
        assert st['incompat'] >= 1 and st['hits'] == 0, st
        assert got.tobytes() == want.tobytes()

    def test_truncated_entry_falls_back(self, tmp_path):
        _warm_one(str(tmp_path))
        faultinject.corrupt_compile_cache(str(tmp_path), mode='truncate')
        cc.reset_stats()
        got = _warm_one(str(tmp_path))
        assert cc.stats()['incompat'] >= 1
        assert np.allclose(got, np.arange(8) * 2.0 + 1.0)

    def test_unreadable_manifest_disables_hits_not_boot(self, tmp_path):
        _warm_one(str(tmp_path))
        with open(os.path.join(str(tmp_path), cc.MANIFEST_NAME), 'w') as f:
            f.write('{not json')
        cc.reset_stats()
        got = _warm_one(str(tmp_path))
        st = cc.stats()
        assert st['hits'] == 0 and st['incompat'] >= 1
        assert np.allclose(got, np.arange(8) * 2.0 + 1.0)


# ---------------------------------------------------------------------------
# the headline: a second serving boot compiles ZERO programs
# ---------------------------------------------------------------------------

_BOOT_CHILD = r"""
import json, os, sys
os.environ.setdefault('JAX_PLATFORMS', 'cpu')
import numpy as np
from paddle_tpu import compilecache as cc
from paddle_tpu import observability as obs
from paddle_tpu import serving

lm = serving.TinyCausalLM.random(vocab=64, embed=32, num_heads=4,
                                 max_batch=8, max_seq=64,
                                 prompt_buckets=(4, 8), seed=0)
obs.enable()   # weight build above is the checkpoint-load analogue
eng = serving.ServingEngine()
ep = eng.register('lm', generative=lm, page_size=8, num_pages=17,
                  artifact_dir=sys.argv[1])
eng.warmup()
fut = ep.submit({'tokens': np.array([3, 1, 4], np.int32)},
                max_new_tokens=4)
eng.run_until_idle()
resp = fut.result(timeout=60)
print(json.dumps({
    'ok': bool(resp.ok),
    'tokens': [int(t) for t in np.asarray(resp.outputs['tokens']).ravel()],
    'jax_compiles': obs.snapshot()['counters'].get('jax.compiles', 0),
    'cache': cc.stats(),
}))
"""


def _boot(cache_dir):
    env = dict(os.environ, JAX_PLATFORMS='cpu',
               PYTHONPATH=REPO + os.pathsep + os.environ.get('PYTHONPATH', ''))
    proc = subprocess.run([sys.executable, '-c', _BOOT_CHILD, cache_dir],
                          capture_output=True, text=True, timeout=240,
                          env=env, cwd=REPO)
    for line in reversed(proc.stdout.strip().splitlines()):
        if line.startswith('{'):
            return json.loads(line)
    raise AssertionError(f'boot child rc={proc.returncode}: '
                         f'{proc.stderr[-800:]}')


class TestColdBoot:
    def test_second_boot_compiles_zero_programs(self, tmp_path):
        b1 = _boot(str(tmp_path))
        assert b1['ok'] and b1['jax_compiles'] > 0     # populate pass paid
        assert b1['cache']['stores'] == b1['cache']['misses'] > 0
        b2 = _boot(str(tmp_path))
        assert b2['ok']
        # THE acceptance criterion: zero compiles, all hits
        assert b2['jax_compiles'] == 0, b2
        assert b2['cache']['hit_rate'] == 1.0, b2['cache']
        assert b2['cache']['misses'] == 0
        # and the cache-loaded program generates the same tokens
        assert b2['tokens'] == b1['tokens']


# ---------------------------------------------------------------------------
# doctor: cold_compile_storm
# ---------------------------------------------------------------------------

class TestDoctor:
    def test_registered_for_cli_gate(self):
        # tools/doctor.py --fail-on validates names against DETECTORS
        assert 'cold_compile_storm' in doc.DETECTORS
        assert doc.DETECTORS['cold_compile_storm'] \
            is doc.detect_cold_compile_storm

    def test_fires_critical_on_poisoned_cache(self):
        snap = {'counters': {'compilecache.hits': 0,
                             'compilecache.misses': 1,
                             'compilecache.incompat': 4,
                             'jax.compiles': 5},
                'gauges': {'compilecache.entries': 5}}
        hits = list(doc.detect_cold_compile_storm(snapshot=snap))
        assert len(hits) == 1 and hits[0]['severity'] == 'critical'
        assert hits[0]['cause'] == 'cold_compile_storm'
        # fix-it names the CLI and the env knob
        assert 'tools/compilecache.py' in hits[0]['fix']
        assert 'PADDLE_TPU_COMPILE_CACHE' in hits[0]['fix']

    def test_fires_warning_on_missing_against_populated_dir(self):
        snap = {'counters': {'compilecache.hits': 1,
                             'compilecache.misses': 9,
                             'jax.compiles': 9},
                'gauges': {'compilecache.entries': 40}}
        hits = list(doc.detect_cold_compile_storm(snapshot=snap))
        assert len(hits) == 1 and hits[0]['severity'] == 'warning'

    def test_quiet_on_healthy_and_first_boot(self):
        # healthy: everything hit
        snap = {'counters': {'compilecache.hits': 9, 'jax.compiles': 0},
                'gauges': {'compilecache.entries': 9}}
        assert not list(doc.detect_cold_compile_storm(snapshot=snap))
        # first boot against an empty dir: misses ARE the populate pass
        snap = {'counters': {'compilecache.misses': 9, 'jax.compiles': 9},
                'gauges': {'compilecache.entries': 9}}
        assert not list(doc.detect_cold_compile_storm(snapshot=snap))
        # no cache bound at all: not this detector's business
        assert not list(doc.detect_cold_compile_storm(
            snapshot={'counters': {'jax.compiles': 50}}))

    @pytest.mark.obs
    def test_deterministic_repro_via_faultinject(self, tmp_path):
        """The documented repro: populate, poison every entry, reboot —
        the live counters drive the detector to critical."""
        _warm_one(str(tmp_path), label='storm.a')
        _warm_one(str(tmp_path), label='storm.b')
        faultinject.corrupt_compile_cache(str(tmp_path))
        obs.reset()
        obs.enable()
        try:
            cc.reset_stats()
            _warm_one(str(tmp_path), label='storm.a')
            _warm_one(str(tmp_path), label='storm.b')
            hits = list(doc.detect_cold_compile_storm(
                snapshot=obs.snapshot()))
        finally:
            obs.disable()
            obs.reset()
        assert len(hits) == 1 and hits[0]['severity'] == 'critical'
        assert hits[0]['evidence']['incompat'] >= 2

    def test_doctor_cli_gates_on_run_dir(self, tmp_path):
        """``tools/doctor.py <run_dir> --fail-on cold_compile_storm``
        fires from a rank telemetry head: the head's ``metrics`` field
        carries the full dotted-counter registry snapshot, and the CLI
        must feed it to the snapshot-based detectors."""
        head = {
            'rank': 0, 'pid': 1, 'host': 'h', 'ts': 1.0,
            'metrics': {
                'counters': {'compilecache.hits': 0,
                             'compilecache.misses': 1,
                             'compilecache.incompat': 4,
                             'jax.compiles': 5},
                'gauges': {'compilecache.entries': 5},
                'histograms': {},
            },
            'counters': {'jax_compiles': 5},
        }
        (tmp_path / 'telemetry_rank0.json').write_text(json.dumps(head))
        proc = subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools', 'doctor.py'),
             str(tmp_path), '--fail-on', 'cold_compile_storm'],
            capture_output=True, text=True, timeout=60)
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert 'cold_compile_storm' in proc.stdout


# ---------------------------------------------------------------------------
# tools/compilecache.py (stdlib CLI)
# ---------------------------------------------------------------------------

def _cli(*args):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, 'tools', 'compilecache.py')]
        + list(args), capture_output=True, text=True, timeout=60)
    return proc.returncode, proc.stdout, proc.stderr


class TestCli:
    def _populated(self, tmp_path):
        for i, n in enumerate((4, 8, 16)):
            _warm_one(str(tmp_path), label=f'cli.t{i}', n=n)
        return str(tmp_path)

    def test_list_and_json(self, tmp_path):
        root = self._populated(tmp_path)
        rc, out, _ = _cli(root)
        assert rc == 0 and 'cli.t0' in out and '3 entries' in out
        rc, out, _ = _cli(root, '--json')
        assert rc == 0
        doc_ = json.loads(out)
        assert len(doc_['entries']) == 3
        row = doc_['entries'][0]
        for field in ('key', 'label', 'bytes', 'jax', 'backend', 'sig'):
            assert field in row, row

    def test_verify_catches_corruption(self, tmp_path):
        root = self._populated(tmp_path)
        rc, _, _ = _cli(root, '--verify')
        assert rc == 0
        faultinject.corrupt_compile_cache(root, n=1)
        rc, out, _ = _cli(root, '--verify')
        assert rc == 1 and 'BAD' in out

    def test_gc_evicts_lru_down_to_budget(self, tmp_path):
        root = self._populated(tmp_path)
        # touch t2 so t0 (oldest mtime) is the LRU victim
        man = json.load(open(os.path.join(root, 'manifest.json')))
        by_label = {e['label']: e for e in man['entries'].values()}
        os.utime(os.path.join(root, by_label['cli.t0']['file']),
                 (1, 1))     # force-oldest
        total = sum(e['bytes'] for e in man['entries'].values())
        rc, out, _ = _cli(root, '--gc', '--keep-bytes',
                          str(total - 1), '--json')
        assert rc == 0
        rep = json.loads(out)
        assert rep['gc']['kept'] == 2
        assert [r for r in rep['gc']['removed']
                if r.get('label') == 'cli.t0'], rep['gc']
        # the evicted entry is gone from BOTH manifest and disk
        man2 = json.load(open(os.path.join(root, 'manifest.json')))
        assert len(man2['entries']) == 2
        assert not os.path.exists(
            os.path.join(root, by_label['cli.t0']['file']))
        # and the survivors still verify + still hit
        rc, _, _ = _cli(root, '--verify')
        assert rc == 0
        cc.reset_stats()
        _warm_one(root, label='cli.t1', n=8)
        assert cc.stats()['hits'] == 1

    def test_gc_requires_budget_and_bad_dir_errors(self, tmp_path):
        rc, _, err = _cli(str(tmp_path), '--gc')
        assert rc == 2 and 'keep-bytes' in err
        rc, _, err = _cli(str(tmp_path / 'nope'))
        assert rc == 2


# ---------------------------------------------------------------------------
# train→serve handoff + fleet relaunch
# ---------------------------------------------------------------------------

class TestWarmHandoff:
    def test_fit_exports_and_replica_boots_on_hits(self, tmp_path):
        import paddle_tpu as paddle
        import paddle_tpu.nn as nn
        from paddle_tpu import engine, serving

        net = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        rng = np.random.RandomState(0)
        data = [([rng.rand(4, 4).astype(np.float32)],
                 [np.zeros((4, 2), np.float32)]) for _ in range(3)]
        spec = serving.TinyCausalLM.random(
            vocab=64, embed=32, num_heads=4, max_batch=8, max_seq=64,
            prompt_buckets=(4, 8), seed=0)
        report = engine.fit(net, nn.MSELoss(), opt, data, epochs=1,
                            prefetch=0, serve_artifacts=str(tmp_path),
                            serve_generative=('lm', spec))
        art = report['serve_artifacts']
        assert art['dir'] == str(tmp_path)
        assert art['generative'] == 'lm'
        # the infer forward + the paged runner's closed set all landed
        assert art['programs'] >= 4
        man = json.load(open(os.path.join(str(tmp_path), 'manifest.json')))
        assert len(man['entries']) == art['programs']
        labels = {e['label'] for e in man['entries'].values()}
        assert any(lbl.startswith('engine.infer.') for lbl in labels)
        assert any('serving.lm.prefill' in lbl for lbl in labels)

        # a serving replica registering under the SAME name boots on hits
        cc.reset_stats()
        eng = serving.ServingEngine()
        ep = eng.register('lm', generative=spec, artifact_dir=str(tmp_path))
        eng.warmup()
        st = cc.stats()
        # every runner program hits (the leftover artifact is the
        # engine.infer forward, which generative serving never asks for)
        assert st['hits'] == art['programs'] - 1 and st['misses'] == 0, st
        fut = ep.submit({'tokens': np.array([5, 2], np.int32)},
                        max_new_tokens=3)
        eng.run_until_idle()
        assert fut.result(timeout=30).ok

    def test_fleet_supervisor_relaunches_from_artifacts(self, tmp_path):
        from paddle_tpu import serving

        spec = serving.TinyCausalLM.random(
            vocab=64, embed=32, num_heads=4, max_batch=8, max_seq=64,
            prompt_buckets=(4,), seed=0)

        def factory(name):
            eng = serving.ServingEngine()
            eng.register('lm', generative=spec)
            return eng

        # first boot populates the artifact dir
        with cc.use(str(tmp_path)):
            first = factory('r0')
            first.warmup()
        assert cc.stats()['stores'] > 0

        router = serving.FleetRouter(serving.RouterPolicy())
        router.add_replica('r0', first)
        first.kill()
        sup = serving.FleetSupervisor(router, factory, max_restarts=2,
                                      artifact_dir=str(tmp_path))
        cc.reset_stats()
        assert sup.check_once() == ['r0']
        st = cc.stats()
        # the relaunch deserialized its whole program set: no compile storm
        assert st['hits'] > 0 and st['misses'] == 0, st
