"""fluid.contrib.decoder: the fluid-era seq2seq decoder classes.

Parity: /root/reference/python/paddle/fluid/contrib/decoder/
beam_search_decoder.py — a reference-style script builds a StateCell with a
custom updater, unrolls it with TrainingDecoder through the static
Executor, and generates with BeamSearchDecoder.decode().
"""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
from paddle_tpu.fluid.contrib import (BeamSearchDecoder, InitState,
                                      StateCell, TrainingDecoder)

V, D, H = 12, 6, 8   # vocab, word dim, hidden


def test_state_cell_standalone_eager():
    rs = np.random.RandomState(0)
    h0 = paddle.to_tensor(rs.randn(3, H).astype(np.float32))
    cell = StateCell(inputs={'x': None},
                     states={'h': InitState(init=h0)}, out_state='h')

    @cell.state_updater
    def updater(sc):
        x = sc.get_input('x')
        h = sc.get_state('h')
        sc.set_state('h', paddle.tanh(x + h))

    x = paddle.to_tensor(rs.randn(3, H).astype(np.float32))
    cell.compute_state(inputs={'x': x})
    expect = np.tanh(x.numpy() + h0.numpy())
    np.testing.assert_allclose(cell.get_state('h').numpy(), expect,
                               rtol=1e-6)
    assert cell.out_state().numpy().shape == (3, H)
    with pytest.raises(ValueError, match='Unknown input'):
        cell.compute_state(inputs={'bogus': x})


def test_state_cell_validation():
    with pytest.raises(ValueError, match='InitState'):
        StateCell(inputs={}, states={'h': 3}, out_state='h')
    h0 = paddle.to_tensor(np.zeros((1, 2), np.float32))
    with pytest.raises(ValueError, match='out_state'):
        StateCell(inputs={}, states={'h': InitState(init=h0)},
                  out_state='nope')


def test_init_state_from_boot():
    boot = paddle.to_tensor(np.zeros((5, 3), np.float32))
    st = InitState(shape=[-1, H], value=0.0, init_boot=boot)
    assert list(st.value.shape) == [5, H]
    with pytest.raises(ValueError, match='init_boot'):
        InitState(shape=[-1, H])


def test_training_decoder_reference_script_through_executor():
    """The reference docstring script (:384): step_input + compute_state +
    fc softmax + update_states + output, run via Executor."""
    rs = np.random.RandomState(1)
    B, T = 4, 5
    paddle.enable_static()
    try:
        main = fluid.Program()
        startup = fluid.Program()
        with fluid.program_guard(main, startup):
            trg = fluid.layers.data(name='trg_emb', shape=[-1, T, D],
                                    dtype='float32')
            boot = fluid.layers.data(name='boot', shape=[-1, H],
                                     dtype='float32')
            hidden = InitState(init=boot)
            state_cell = StateCell(inputs={'x': None},
                                   states={'h': hidden}, out_state='h')

            @state_cell.state_updater
            def updater(sc):
                x = sc.get_input('x')
                h = sc.get_state('h')
                new_h = fluid.layers.fc(input=fluid.layers.concat(
                    [x, h], axis=1), size=H, act='tanh')
                sc.set_state('h', new_h)

            decoder = TrainingDecoder(state_cell)
            with decoder.block():
                current_word = decoder.step_input(trg)
                state_cell.compute_state(inputs={'x': current_word})
                current_score = fluid.layers.fc(
                    input=state_cell.get_state('h'), size=V, act='softmax')
                state_cell.update_states()
                decoder.output(current_score)
            out = decoder()

            exe = fluid.Executor(fluid.CPUPlace())
            exe.run(startup)
            feed = {'trg_emb': rs.randn(B, T, D).astype(np.float32),
                    'boot': rs.randn(B, H).astype(np.float32)}
            res = exe.run(main, feed=feed, fetch_list=[out])[0]
        assert res.shape == (B, T, V)
        np.testing.assert_allclose(res.sum(-1), np.ones((B, T)), rtol=1e-4)
        # scores vary across time steps (the scan actually advances state)
        assert np.abs(res[:, 0] - res[:, 1]).max() > 1e-6
    finally:
        paddle.disable_static()


def test_training_decoder_block_protocol():
    h0 = paddle.to_tensor(np.zeros((2, H), np.float32))
    cell = StateCell(inputs={'x': None}, states={'h': InitState(init=h0)},
                     out_state='h')
    dec = TrainingDecoder(cell)
    with pytest.raises(ValueError, match='inside block'):
        dec.step_input(paddle.to_tensor(np.zeros((2, 3, D), np.float32)))
    with pytest.raises(ValueError, match='outside the block'):
        dec()


def _greedy_reference(h0, emb, fc_w, fc_b, upd_w, start_id, end_id, T):
    """Pure-numpy greedy (beam=1) rollout of the tanh(x+h@U) cell."""
    h = h0.copy()
    ids = []
    cur = start_id
    for _ in range(T):
        x = emb[cur]
        h = np.tanh(x + h @ upd_w)
        p = h @ fc_w + fc_b
        e = np.exp(p - p.max())
        probs = e / e.sum()
        cur = int(np.argmax(probs))
        ids.append(cur)
        if cur == end_id:
            break
    return ids


def test_beam_search_decoder_matches_greedy_rollout():
    rs = np.random.RandomState(7)
    emb = rs.randn(V, H).astype(np.float32)   # word_dim == H for x + h
    fc_w = rs.randn(H, V).astype(np.float32) * 2.0
    fc_b = rs.randn(V).astype(np.float32)
    upd_w = (np.eye(H) + 0.1 * rs.randn(H, H)).astype(np.float32)
    h0 = rs.randn(1, H).astype(np.float32)
    end_id = 1
    upd_t = paddle.to_tensor(upd_w)

    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    cell = StateCell(inputs={'x': None},
                     states={'h': InitState(
                         init=paddle.to_tensor(h0))}, out_state='h')

    @cell.state_updater
    def updater(sc):
        x = sc.get_input('x')
        h = sc.get_state('h')
        sc.set_state('h', paddle.tanh(x + paddle.matmul(h, upd_t)))

    dec = BeamSearchDecoder(
        state_cell=cell,
        init_ids=paddle.to_tensor(np.array([[0]], np.int64)),
        init_scores=paddle.to_tensor(np.array([[0.0]], np.float32)),
        target_dict_dim=V, word_dim=H, beam_size=1, max_len=6, end_id=end_id,
        embedding_param_attr=ParamAttr(
            initializer=NumpyArrayInitializer(emb)),
        fc_param_attr=ParamAttr(initializer=NumpyArrayInitializer(fc_w)),
        fc_bias_attr=ParamAttr(initializer=NumpyArrayInitializer(fc_b)))
    dec.decode()
    seqs, scores = dec()
    got = seqs.numpy()[:, 0, 0].tolist()
    expect = _greedy_reference(h0, emb, fc_w, fc_b, upd_w, 0, end_id, 6)
    assert got[:len(expect)] == expect


def test_beam_search_decoder_wider_beam_scores_monotonic():
    rs = np.random.RandomState(3)
    cell = StateCell(inputs={'x': None},
                     states={'h': InitState(init=paddle.to_tensor(
                         rs.randn(2, H).astype(np.float32)))},
                     out_state='h')

    @cell.state_updater
    def updater(sc):
        sc.set_state('h', paddle.tanh(sc.get_input('x') +
                                      sc.get_state('h')))

    dec = BeamSearchDecoder(
        state_cell=cell,
        init_ids=paddle.to_tensor(np.zeros((2, 1), np.int64)),
        init_scores=paddle.to_tensor(np.zeros((2, 1), np.float32)),
        target_dict_dim=V, word_dim=H, beam_size=3, max_len=4, end_id=1)
    dec.decode()
    seqs, scores = dec()
    T, B, W = seqs.numpy().shape
    assert (B, W) == (2, 3)
    s = scores.numpy()
    # within each step, beams are sorted best-first
    assert np.all(np.diff(s[-1], axis=-1) <= 1e-5)
    # custom block() is explicitly unsupported with guidance
    with pytest.raises(NotImplementedError, match='dynamic_decode'):
        dec.block()


def test_contrib_decoder_namespace():
    import paddle_tpu.fluid as fl
    for name in ('InitState', 'StateCell', 'TrainingDecoder',
                 'BeamSearchDecoder'):
        assert hasattr(fl.contrib, name)
        assert hasattr(fl.contrib.decoder, name)
    # the canonical 1.8 import path
    from paddle_tpu.fluid.contrib.decoder.beam_search_decoder import (
        BeamSearchDecoder as B2, InitState as I2)
    assert B2 is BeamSearchDecoder and I2 is InitState
