"""fluid.contrib.layers op zoo: value checks against independent numpy ports.

Parity target: /root/reference/python/paddle/fluid/contrib/layers/nn.py,
rnn_impl.py, metric_op.py. Every op is checked against a plain-numpy
re-derivation of its reference semantics (not against the jnp code paths).
"""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.fluid.contrib import layers as cl

rs = np.random.RandomState(7)


def _tt(a):
    return paddle.to_tensor(np.asarray(a))


# ---------------------------------------------------------------------------
# elementwise / slicing ops
# ---------------------------------------------------------------------------

def test_fused_elemwise_activation():
    x = rs.randn(4, 5).astype(np.float32)
    y = rs.randn(4, 5).astype(np.float32)
    out = cl.fused_elemwise_activation(_tt(x), _tt(y),
                                       ['elementwise_add', 'relu'])
    np.testing.assert_allclose(out.numpy(), x + np.maximum(y, 0), rtol=1e-6)
    out = cl.fused_elemwise_activation(_tt(x), _tt(y),
                                       ['relu', 'elementwise_add'])
    np.testing.assert_allclose(out.numpy(), np.maximum(x + y, 0), rtol=1e-6)
    out = cl.fused_elemwise_activation(_tt(x), _tt(y),
                                       ['elementwise_mul', 'scale'],
                                       scale=0.5)
    np.testing.assert_allclose(out.numpy(), x * (y * 0.5), rtol=1e-6)
    with pytest.raises(ValueError):
        cl.fused_elemwise_activation(_tt(x), _tt(y), ['relu', 'tanh'])


def test_partial_concat_and_sum():
    a = rs.randn(3, 6).astype(np.float32)
    b = rs.randn(3, 6).astype(np.float32)
    out = cl.partial_concat([_tt(a), _tt(b)], start_index=1, length=3)
    np.testing.assert_allclose(out.numpy(),
                               np.concatenate([a[:, 1:4], b[:, 1:4]], 1))
    out = cl.partial_sum([_tt(a), _tt(b)], start_index=2, length=-1)
    np.testing.assert_allclose(out.numpy(), a[:, 2:] + b[:, 2:], rtol=1e-6)


def test_shuffle_batch_is_permutation():
    x = np.arange(24, dtype=np.float32).reshape(8, 3)
    out = cl.shuffle_batch(_tt(x), seed=3).numpy()
    assert sorted(map(tuple, out)) == sorted(map(tuple, x))
    out2 = cl.shuffle_batch(_tt(x), seed=3).numpy()
    np.testing.assert_allclose(out, out2)  # same seed -> same permutation


# ---------------------------------------------------------------------------
# matching / pooling ops
# ---------------------------------------------------------------------------

def test_match_matrix_tensor_vs_numpy():
    B, n, m, h, c = 2, 4, 5, 3, 2
    x = rs.randn(B, n, h).astype(np.float32)
    y = rs.randn(B, m, h).astype(np.float32)
    out, tmp = cl.match_matrix_tensor(_tt(x), _tt(y), channel_num=c)
    w = None
    # recover the created parameter from tmp: tmp = einsum('bnh,hcg->bncg')
    # instead, independently recompute with the op's own weight tensor
    # (exposed via the autograd graph is awkward) — recreate via param_attr
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    w = rs.randn(h, c, h).astype(np.float32)
    out, tmp = cl.match_matrix_tensor(
        _tt(x), _tt(y), channel_num=c,
        param_attr=ParamAttr(initializer=NumpyArrayInitializer(w)))
    expect = np.einsum('bnh,hcg,bmg->bcnm', x, w, y)
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(tmp.numpy(), np.einsum('bnh,hcg->bncg', x, w),
                               rtol=1e-4, atol=1e-5)
    # masked variant: invalid rows/cols must be zero
    out_m, _ = cl.match_matrix_tensor(
        _tt(x), _tt(y), channel_num=c,
        param_attr=ParamAttr(initializer=NumpyArrayInitializer(w)),
        x_len=_tt(np.array([2, 4])), y_len=_tt(np.array([5, 3])))
    got = out_m.numpy()
    assert np.all(got[0, :, 2:, :] == 0)
    assert np.all(got[1, :, :, 3:] == 0)
    np.testing.assert_allclose(got[0, :, :2, :], expect[0, :, :2, :],
                               rtol=1e-4, atol=1e-5)


def test_sequence_topk_avg_pooling_vs_numpy():
    B, C, H, W = 2, 2, 4, 6
    topks = [1, 3]
    x = rs.randn(B, C, H, W).astype(np.float32)
    row = np.array([3, 4], np.int32)
    col = np.array([5, 2], np.int32)
    out = cl.sequence_topk_avg_pooling(_tt(x), _tt(row), _tt(col), topks, C)
    got = out.numpy()
    expect = np.zeros((B, H, len(topks) * C), np.float32)
    for b in range(B):
        for i in range(row[b]):
            for ki, k in enumerate(topks):
                for c in range(C):
                    vals = np.sort(x[b, c, i, :col[b]])[::-1]
                    expect[b, i, ki * C + c] = vals[:k].sum() / k
    np.testing.assert_allclose(got, expect, rtol=1e-5, atol=1e-6)


def test_var_conv_2d_masks_invalid_region():
    B, C, H, W = 2, 3, 6, 8
    x = rs.randn(B, C, H, W).astype(np.float32)
    row = np.array([4, 6], np.int32)
    col = np.array([8, 5], np.int32)
    out = cl.var_conv_2d(_tt(x), _tt(row), _tt(col), input_channel=C,
                         output_channel=4, filter_size=3, stride=2)
    got = out.numpy()
    assert tuple(got.shape) == (B, 4, 3, 4)
    # sample 0: valid output 2x4 (ceil(4/2), ceil(8/2)) -> row 2 zeroed
    assert np.all(got[0, :, 2:, :] == 0)
    assert np.any(got[0, :, :2, :] != 0)
    # sample 1: valid 3x3 -> col 3 zeroed
    assert np.all(got[1, :, :, 3:] == 0)


# ---------------------------------------------------------------------------
# embedding ops
# ---------------------------------------------------------------------------

def test_fused_embedding_seq_pool_vs_numpy():
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    V, D = 10, 4
    w = rs.randn(V, D).astype(np.float32)
    ids = np.array([[1, 2, 0, 0], [3, 0, 0, 0]], np.int64)
    out = cl.fused_embedding_seq_pool(
        _tt(ids[..., None]), [V, D], padding_idx=0,
        param_attr=ParamAttr(initializer=NumpyArrayInitializer(w)))
    expect = np.stack([w[1] + w[2], w[3]])
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-6)


def test_sparse_embedding_lookup():
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    V, D = 8, 3
    w = rs.randn(V, D).astype(np.float32)
    ids = np.array([[1], [5], [0]], np.int64)
    out = cl.sparse_embedding(
        _tt(ids), [V, D], padding_idx=0,
        param_attr=ParamAttr(initializer=NumpyArrayInitializer(w)))
    got = out.numpy()
    np.testing.assert_allclose(got[0], w[1], rtol=1e-6)
    np.testing.assert_allclose(got[2], np.zeros(D))


def test_pull_box_extended_sparse_shapes_and_determinism():
    ids = np.array([[3], [3], [9]], np.int64)
    emb, ext = cl._pull_box_extended_sparse(_tt(ids), size=6, extend_size=8)
    assert tuple(emb.shape) == (3, 6) and tuple(ext.shape) == (3, 8)
    np.testing.assert_allclose(emb.numpy()[0], emb.numpy()[1])  # same id


def test_search_pyramid_hash_properties():
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    ids = np.array([[1, 2, 3, 4], [1, 2, 3, 4]], np.int64)
    table = rs.randn(1000).astype(np.float32)
    pa = ParamAttr(initializer=NumpyArrayInitializer(table))
    out = cl.search_pyramid_hash(
        _tt(ids), num_emb=8, space_len=1000, pyramid_layer=3, rand_len=4,
        drop_out_percent=0, is_training=False, use_filter=False,
        white_list_len=0, black_list_len=0, seed=5, lr=1.0, param_attr=pa)
    got = out.numpy()
    assert got.shape == (2, 4, 8)
    np.testing.assert_allclose(got[0], got[1])   # same ids -> same hashes
    # masked variant: positions past length give zero
    out2 = cl.search_pyramid_hash(
        _tt(ids), num_emb=8, space_len=1000, pyramid_layer=3, rand_len=4,
        drop_out_percent=0, is_training=False, use_filter=False,
        white_list_len=0, black_list_len=0, seed=5, lr=1.0, param_attr=pa,
        length=_tt(np.array([4, 2])))
    g2 = out2.numpy()
    assert np.all(g2[1, 2:] == 0)
    np.testing.assert_allclose(g2[0], got[0])


# ---------------------------------------------------------------------------
# TDM ops
# ---------------------------------------------------------------------------

TREE_INFO = np.array(
    [[0, 0, 0, 1, 2],
     [0, 1, 0, 3, 4],
     [0, 1, 0, 5, 6],
     [0, 2, 1, 0, 0],
     [1, 2, 1, 0, 0],
     [2, 2, 2, 0, 0],
     [3, 2, 2, 0, 0]], np.int32)


def test_tdm_child_reference_example():
    # the exact worked example from nn.py:1018's docstring
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    x = np.array([[2], [3]], np.int32)
    child, leaf_mask = cl.tdm_child(
        _tt(x), node_nums=7, child_nums=2,
        param_attr=ParamAttr(initializer=NumpyArrayInitializer(
            TREE_INFO.astype(np.float32))))
    np.testing.assert_array_equal(child.numpy(), [[5, 6], [0, 0]])
    np.testing.assert_array_equal(leaf_mask.numpy(), [[1, 1], [0, 0]])


def test_tdm_sampler_reference_example():
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    travel = np.array([[1, 3], [1, 4], [2, 5], [2, 6]], np.float32)
    layer = np.array([[1], [2], [3], [4], [5], [6]], np.float32)
    x = np.array([[0], [1], [2], [3]], np.int32)
    out, labels, mask = cl.tdm_sampler(
        _tt(x), [0, 0], [2, 4], 4,
        tree_travel_attr=ParamAttr(
            initializer=NumpyArrayInitializer(travel)),
        tree_layer_attr=ParamAttr(initializer=NumpyArrayInitializer(layer)),
        output_positive=True, output_list=False, seed=0)
    np.testing.assert_array_equal(out.numpy(),
                                  [[1, 3], [1, 4], [2, 5], [2, 6]])
    np.testing.assert_array_equal(labels.numpy(), np.ones((4, 2)))
    np.testing.assert_array_equal(mask.numpy(), np.ones((4, 2)))


def test_tdm_sampler_negatives_and_list_output():
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    travel = np.array([[1, 3], [1, 4], [2, 5], [2, 6]], np.float32)
    layer = np.array([[1], [2], [3], [4], [5], [6]], np.float32)
    x = np.array([[0], [2]], np.int32)
    outs, labels, masks = cl.tdm_sampler(
        _tt(x), [1, 2], [2, 4], 4,
        tree_travel_attr=ParamAttr(
            initializer=NumpyArrayInitializer(travel)),
        tree_layer_attr=ParamAttr(initializer=NumpyArrayInitializer(layer)),
        output_positive=True, output_list=True, seed=11)
    assert len(outs) == 2 and tuple(outs[0].shape) == (2, 2, 1) \
        and tuple(outs[1].shape) == (2, 3, 1)
    o0 = outs[0].numpy()[..., 0]
    l0 = labels[0].numpy()[..., 0]
    # positive first, correct path node; negative differs from positive
    assert o0[0, 0] == 1 and o0[1, 0] == 2
    assert l0[0, 0] == 1 and l0[0, 1] == 0
    assert o0[0, 1] != o0[0, 0] and o0[0, 1] in (1, 2)
    o1 = outs[1].numpy()[..., 0]
    assert o1[0, 0] == 3 and o1[1, 0] == 5
    for b in range(2):
        negs = o1[b, 1:]
        assert all(n in (3, 4, 5, 6) and n != o1[b, 0] for n in negs)
        assert negs[0] != negs[1]  # without replacement


# ---------------------------------------------------------------------------
# CTR ops
# ---------------------------------------------------------------------------

def test_rank_attention_vs_numpy():
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    B, D, out_col, max_rank = 3, 2, 4, 3
    x = rs.randn(B, D).astype(np.float32)
    w = rs.randn(max_rank * max_rank * D, out_col).astype(np.float32)
    # instance 0: rank 1, relations (rank1->idx0, rank2->idx1)
    # instance 1: rank 2, relation (rank1->idx0); instance 2: invalid rank
    ro = np.array([[1, 1, 0, 2, 1, 0, 0],
                   [2, 1, 0, 0, 0, 0, 0],
                   [0, 0, 0, 0, 0, 0, 0]], np.int32)
    out = cl.rank_attention(
        _tt(x), _tt(ro), [max_rank * max_rank * D, out_col],
        ParamAttr(initializer=NumpyArrayInitializer(w)), max_rank=max_rank)
    wb = w.reshape(max_rank * max_rank, D, out_col)
    expect = np.zeros((B, out_col), np.float32)
    for i in range(B):
        lower = ro[i, 0] - 1
        for k in range(max_rank):
            faster = ro[i, 2 * k + 1] - 1
            idx = ro[i, 2 * k + 2]
            if lower < 0 or faster < 0:
                continue
            expect[i] += x[idx] @ wb[lower * max_rank + faster]
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4, atol=1e-5)


def test_batch_fc_vs_numpy():
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    S, B, I, O = 2, 3, 4, 5
    x = rs.randn(S, B, I).astype(np.float32)
    w = rs.randn(S, I, O).astype(np.float32)
    b = rs.randn(S, O).astype(np.float32)
    out = cl.batch_fc(
        _tt(x), [S, I, O],
        ParamAttr(initializer=NumpyArrayInitializer(w)), [S, O],
        ParamAttr(initializer=NumpyArrayInitializer(b)), act='relu')
    expect = np.maximum(np.einsum('sbi,sio->sbo', x, w) + b[:, None], 0)
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4, atol=1e-5)


def test_ctr_metric_bundle_vs_numpy():
    p = rs.rand(6, 1).astype(np.float32)
    l = (rs.rand(6, 1) > 0.5).astype(np.float32)
    sqrerr, abserr, prob, q, pos, ins = cl.ctr_metric_bundle(_tt(p), _tt(l))
    np.testing.assert_allclose(sqrerr.numpy(), [((p - l) ** 2).sum()],
                               rtol=1e-5)
    np.testing.assert_allclose(abserr.numpy(), [np.abs(p - l).sum()],
                               rtol=1e-5)
    np.testing.assert_allclose(prob.numpy(), [p.sum()], rtol=1e-5)
    np.testing.assert_allclose(q.numpy(), [(1 / (1 + np.exp(-p))).sum()],
                               rtol=1e-5)
    np.testing.assert_allclose(pos.numpy(), [l.sum()], rtol=1e-5)
    np.testing.assert_allclose(ins.numpy(), [6.0])


# ---------------------------------------------------------------------------
# vision ops
# ---------------------------------------------------------------------------

def test_multiclass_nms2_returns_indices():
    # 1 image, 3 boxes, 2 classes (class 0 = background)
    boxes = np.array([[[0, 0, 10, 10], [0, 0, 10.5, 10.5], [20, 20, 30, 30]]],
                     np.float32)
    scores = np.array([[[0.9, 0.8, 0.7],      # background, ignored
                        [0.95, 0.6, 0.8]]], np.float32)
    out, idx = cl.multiclass_nms2(_tt(boxes), _tt(scores),
                                  score_threshold=0.1, nms_top_k=3,
                                  keep_top_k=3, nms_threshold=0.5,
                                  background_label=0, return_index=True)
    o, i = out.numpy()[0], idx.numpy()[0]
    valid = o[:, 1] >= 0
    assert valid.sum() == 2  # box1 suppressed by box0 (IoU>0.5), box2 kept
    kept = set(i[valid].tolist())
    assert kept == {0, 2}
    # every kept row's index points at the box whose coords it carries
    for r in np.where(valid)[0]:
        np.testing.assert_allclose(o[r, 2:], boxes[0, i[r]])


def test_bilateral_slice_constant_grid():
    # a grid holding constant affine coeffs must apply that exact affine
    N, C, H, W, gd, gh, gw = 1, 2, 4, 4, 2, 3, 3
    out_c = 2
    x = rs.rand(N, C, H, W).astype(np.float32)
    guide = rs.rand(N, H, W).astype(np.float32)
    gc = out_c * (C + 1)
    coeffs = rs.randn(gc).astype(np.float32)
    grid = np.tile(coeffs[None, :, None, None, None], (N, 1, gd, gh, gw))
    out = cl.bilateral_slice(_tt(x), _tt(guide), _tt(grid), has_offset=True)
    cf = coeffs.reshape(out_c, C + 1)
    expect = np.einsum('oc,nchw->nohw', cf[:, :C], x) + \
        cf[:, C][None, :, None, None]
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4, atol=1e-4)


def test_correlation_vs_numpy():
    N, C, H, W = 1, 2, 5, 5
    x = rs.randn(N, C, H, W).astype(np.float32)
    y = rs.randn(N, C, H, W).astype(np.float32)
    pad, ks, md, s1, s2 = 1, 1, 1, 1, 1
    out = cl.correlation(_tt(x), _tt(y), pad, ks, md, s1, s2).numpy()
    xp = np.pad(x, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    yp = np.pad(y, ((0, 0), (0, 0), (pad, pad), (pad, pad)))
    border = md
    out_h = out_w = H + 2 * pad - 2 * border
    gw = 2 * md + 1
    expect = np.zeros((N, gw * gw, out_h, out_w), np.float32)
    for dj in range(-md, md + 1):
        for di in range(-md, md + 1):
            ch = (dj + md) * gw + (di + md)
            for i in range(out_h):
                for j in range(out_w):
                    a = xp[:, :, border + i, border + j]
                    b = yp[:, :, border + i + dj, border + j + di]
                    expect[:, ch, i, j] = (a * b).sum(1) / C
    np.testing.assert_allclose(out, expect, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# tree_conv
# ---------------------------------------------------------------------------

def test_tree_conv_root_only_matches_numpy():
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    # two isolated nodes (no edges): patch = self with eta_t=1, eta_l=eta_r
    # computed at depth 0, pclen 1 -> (1, 0, 0) weights? depth0: eta_t=1,
    # tmp=0.5, eta_l=(1-1)*0.5=0, eta_r=0.
    B, N, F, out_sz, nf = 1, 2, 3, 2, 1
    nodes = rs.randn(B, N, F).astype(np.float32)
    edges = np.zeros((B, 1, 2), np.int32)
    w = rs.randn(F, 3, out_sz, nf).astype(np.float32)
    out = cl.tree_conv(_tt(nodes), _tt(edges), out_sz, nf, max_depth=2,
                       act=None,
                       param_attr=ParamAttr(
                           initializer=NumpyArrayInitializer(w)),
                       bias_attr=False)
    expect = np.einsum('bnf,fo->bno', nodes, w[:, 0, :, 0])[..., None]
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4, atol=1e-5)


def test_tree_conv_parent_child_weights():
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    # 1 -> 2 edge, max_depth 2: node1's patch = {1:(1,0,0), 2:(0.5, eta_l,
    # eta_r)}; node2's patch = itself only.
    B, N, F, out_sz = 1, 2, 2, 1
    nodes = rs.randn(B, N, F).astype(np.float32)
    edges = np.array([[[1, 2]]], np.int32)
    w = rs.randn(F, 3, out_sz, 1).astype(np.float32)
    out = cl.tree_conv(_tt(nodes), _tt(edges), out_sz, 1, max_depth=2,
                       act=None,
                       param_attr=ParamAttr(
                           initializer=NumpyArrayInitializer(w)),
                       bias_attr=False).numpy()
    # node1 patch: self (eta 1,0,0) + child at depth1 index1 pclen1:
    # eta_t=(2-1)/2=0.5, tmp=0.5, eta_l=0.25, eta_r=0.25
    p1 = nodes[0, 0] @ w[:, 0, :, 0] + \
        nodes[0, 1] @ (0.5 * w[:, 0, :, 0] + 0.25 * w[:, 1, :, 0] +
                       0.25 * w[:, 2, :, 0])
    p2 = nodes[0, 1] @ w[:, 0, :, 0]
    np.testing.assert_allclose(out[0, 0, :, 0], p1, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(out[0, 1, :, 0], p2, rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# basic_gru / basic_lstm
# ---------------------------------------------------------------------------

def _np_gru_step(x, h, gw, gb, cw, cb, H):
    gate = np.concatenate([x, h], -1) @ gw + gb
    gate = 1 / (1 + np.exp(-gate))
    r, u = gate[..., :H], gate[..., H:]
    c = np.tanh(np.concatenate([x, r * h], -1) @ cw + cb)
    return u * h + (1 - u) * c


def test_basic_gru_unit_vs_numpy():
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    B, I, H = 2, 3, 4
    gw = rs.randn(I + H, 2 * H).astype(np.float32)
    cw = rs.randn(I + H, H).astype(np.float32)
    unit = cl.BasicGRUUnit('gru', H)
    x = rs.randn(B, I).astype(np.float32)
    h = rs.randn(B, H).astype(np.float32)
    unit._build_once(_tt(x))
    unit.gate_weight._inplace_value(__import__('jax.numpy', fromlist=['x'])
                                    .asarray(gw))
    unit.candidate_weight._inplace_value(
        __import__('jax.numpy', fromlist=['x']).asarray(cw))
    out = unit(_tt(x), _tt(h))
    expect = _np_gru_step(x, h, gw, np.zeros(2 * H, np.float32), cw,
                          np.zeros(H, np.float32), H)
    np.testing.assert_allclose(out.numpy(), expect, rtol=1e-4, atol=1e-5)


def test_basic_gru_sequence_vs_numpy():
    from paddle_tpu.nn.initializer import NumpyArrayInitializer, ParamAttr
    T, B, I, H = 4, 2, 3, 5
    x = rs.randn(T, B, I).astype(np.float32)
    gw = rs.randn(I + H, 2 * H).astype(np.float32)
    cw = rs.randn(I + H, H).astype(np.float32)
    pa = ParamAttr(initializer=NumpyArrayInitializer(gw))
    # param_attr is shared across the two weights; NumpyArrayInitializer
    # shape mismatch would throw — so run with default weights and compare
    # against the module's own parameters is circular. Instead: 1 layer,
    # check masking semantics + shapes with random init, and value-check
    # the unit (above) which shares the step math.
    seq_len = np.array([4, 2], np.int64)
    out, last = cl.basic_gru(_tt(x), None, H, num_layers=1,
                             sequence_length=_tt(seq_len))
    assert tuple(out.shape) == (T, B, H) and tuple(last.shape) == (1, B, H)
    o = out.numpy()
    # sample 1 is length 2: outputs at t>=2 are zero, last == output at t=1
    assert np.all(o[2:, 1, :] == 0)
    assert np.any(o[:2, 1, :] != 0)
    np.testing.assert_allclose(last.numpy()[0, 1], o[1, 1], rtol=1e-5)
    # bidirectional doubles the feature dim
    out2, last2 = cl.basic_gru(_tt(x), None, H, num_layers=2,
                               bidirectional=True)
    assert tuple(out2.shape) == (T, B, 2 * H) and tuple(last2.shape) == (4, B, H)
    # batch_first round trip
    out3, _ = cl.basic_gru(_tt(x.transpose(1, 0, 2)), None, H,
                           batch_first=True)
    assert tuple(out3.shape) == (B, T, H)


def test_basic_lstm_masking_and_shapes():
    T, B, I, H = 5, 3, 2, 4
    x = rs.randn(T, B, I).astype(np.float32)
    seq_len = np.array([5, 3, 1], np.int64)
    out, lh, lc = cl.basic_lstm(_tt(x), None, None, H,
                                sequence_length=_tt(seq_len))
    assert tuple(out.shape) == (T, B, H)
    assert tuple(lh.shape) == (1, B, H) and tuple(lc.shape) == (1, B, H)
    o = out.numpy()
    assert np.all(o[3:, 1, :] == 0) and np.all(o[1:, 2, :] == 0)
    np.testing.assert_allclose(lh.numpy()[0, 1], o[2, 1], rtol=1e-5)


def test_basic_lstm_unit_vs_numpy():
    B, I, H = 2, 3, 4
    w = rs.randn(I + H, 4 * H).astype(np.float32)
    unit = cl.BasicLSTMUnit('lstm', H, forget_bias=1.0)
    x = rs.randn(B, I).astype(np.float32)
    h = rs.randn(B, H).astype(np.float32)
    c = rs.randn(B, H).astype(np.float32)
    unit._build_once(_tt(x))
    import jax.numpy as jnp
    unit.weight._inplace_value(jnp.asarray(w))
    nh, nc = unit(_tt(x), _tt(h), _tt(c))
    gate = np.concatenate([x, h], -1) @ w
    sig = lambda v: 1 / (1 + np.exp(-v))
    i_, j, f, o = np.split(gate, 4, -1)
    e_c = c * sig(f + 1.0) + sig(i_) * np.tanh(j)
    e_h = np.tanh(e_c) * sig(o)
    np.testing.assert_allclose(nc.numpy(), e_c, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(nh.numpy(), e_h, rtol=1e-4, atol=1e-5)


def test_contrib_namespace_resolution():
    """>=90% of the reference contrib/layers __all__ resolves (VERDICT #2)."""
    import paddle_tpu.fluid as fluid
    ref_all = ['fused_elemwise_activation', 'sequence_topk_avg_pooling',
               'var_conv_2d', 'match_matrix_tensor', 'tree_conv',
               'fused_embedding_seq_pool', 'multiclass_nms2',
               'search_pyramid_hash', 'shuffle_batch', 'partial_concat',
               'sparse_embedding', 'partial_sum', 'tdm_child',
               'rank_attention', 'tdm_sampler', 'batch_fc',
               '_pull_box_extended_sparse', 'bilateral_slice', 'correlation',
               'BasicGRUUnit', 'basic_gru', 'BasicLSTMUnit', 'basic_lstm',
               'ctr_metric_bundle']
    missing = [n for n in ref_all
               if not hasattr(fluid.contrib.layers, n)]
    assert not missing, missing
    # eager binding (VERDICT weak #6) + submodule paths
    assert hasattr(fluid, 'contrib')
    assert hasattr(fluid.contrib, 'memory_usage')
    assert hasattr(fluid.contrib, 'mixed_precision')
    assert hasattr(fluid.contrib.layers, 'nn')
    assert hasattr(fluid.contrib.layers.rnn_impl, 'basic_gru')
    assert hasattr(fluid.contrib.layers.metric_op, 'ctr_metric_bundle')
