"""Classic 1.8 control-flow classes (While/Switch/IfElse/StaticRNN/
DynamicRNN/Print/Assert) running verbatim-style scripts through Executor."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid
import paddle_tpu.fluid.layers as layers
import paddle_tpu.static as static


@pytest.fixture
def static_mode():
    paddle.enable_static()
    yield
    paddle.disable_static()


class TestWhile:
    def test_counter_loop(self, static_mode):
        """The canonical 1.8 While example (control_flow.py:992)."""
        prog = static.Program()
        with static.program_guard(prog):
            i = layers.fill_constant(shape=[1], dtype='int32', value=0)
            loop_len = layers.fill_constant(shape=[1], dtype='int32',
                                            value=10)
            cond = layers.less_than(x=i, y=loop_len)
            while_op = layers.While(cond=cond)
            with while_op.block():
                i = layers.increment(x=i, value=1, in_place=True)
                layers.less_than(x=i, y=loop_len, cond=cond)
            exe = static.Executor()
            out = exe.run(prog, fetch_list=[i])
        assert int(out[0][0]) == 10

    def test_accumulator_loop(self, static_mode):
        """Loop-carried float accumulation via assign(output=...)."""
        prog = static.Program()
        with static.program_guard(prog):
            i = layers.fill_constant(shape=[1], dtype='int32', value=0)
            n = layers.fill_constant(shape=[1], dtype='int32', value=5)
            acc = layers.fill_constant(shape=[1], dtype='float32', value=0.0)
            cond = layers.less_than(x=i, y=n)
            w = layers.While(cond=cond)
            with w.block():
                new_acc = acc + 2.5
                layers.assign(new_acc, output=acc)
                i = layers.increment(x=i, value=1, in_place=True)
                layers.less_than(x=i, y=n, cond=cond)
            exe = static.Executor()
            out = exe.run(prog, fetch_list=[acc])
        np.testing.assert_allclose(out[0], [12.5])


class TestSwitch:
    def test_lr_switch(self, static_mode):
        """The canonical Switch use: piecewise value by global step."""
        prog = static.Program()
        with static.program_guard(prog):
            lr = layers.create_global_var(shape=[1], value=0.0,
                                          dtype='float32', persistable=True,
                                          name='sw_lr')
            step = static.data('step', [1], 'float32')
            one = layers.fill_constant([1], 'float32', 1.0)
            two = layers.fill_constant([1], 'float32', 2.0)
            with layers.Switch() as switch:
                with switch.case(layers.less_than(step, one)):
                    layers.assign(layers.fill_constant([1], 'float32', 0.1),
                                  output=lr)
                with switch.case(layers.less_than(step, two)):
                    layers.assign(layers.fill_constant([1], 'float32', 0.05),
                                  output=lr)
                with switch.default():
                    layers.assign(layers.fill_constant([1], 'float32', 0.01),
                                  output=lr)
            exe = static.Executor()
            for s, expect in [(0.5, 0.1), (1.5, 0.05), (5.0, 0.01)]:
                out = exe.run(prog, feed={'step': np.array([s], np.float32)},
                              fetch_list=[lr])
                np.testing.assert_allclose(out[0], [expect], rtol=1e-6)


class TestIfElse:
    def test_rowwise_branches(self, static_mode):
        """The reference's doc example: x>y rows minus 10, others plus 10
        (control_flow.py:2779)."""
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data('x', [4, 1], 'float32')
            y = static.data('y', [4, 1], 'float32')
            cond = layers.greater_than(x, y)
            ie = layers.IfElse(cond)
            with ie.true_block():
                out_1 = ie.input(x)
                out_1 = out_1 - 10
                ie.output(out_1)
            with ie.false_block():
                out_1 = ie.input(x)
                out_1 = out_1 + 10
                ie.output(out_1)
            merged = ie()[0]
            exe = static.Executor()
            out = exe.run(
                prog,
                feed={'x': np.array([[3], [1], [-2], [-3]], np.float32),
                      'y': np.zeros((4, 1), np.float32)},
                fetch_list=[merged])
        np.testing.assert_allclose(out[0].reshape(-1), [-7, -9, 8, 7])


class TestStaticRNN:
    def test_accumulating_rnn(self, static_mode):
        """StaticRNN whose memory accumulates step inputs: final outputs
        are prefix sums (verifiable analytically)."""
        T, B, D = 4, 2, 3
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data('x', [T, B, D], 'float32')
            rnn = layers.StaticRNN()
            with rnn.step():
                word = rnn.step_input(x)
                prev = rnn.memory(shape=[-1, D], batch_ref=word)
                hidden = prev + word
                rnn.update_memory(prev, hidden)
                rnn.step_output(hidden)
            result = rnn()
            exe = static.Executor()
            xv = np.random.RandomState(0).randn(T, B, D).astype(np.float32)
            out = exe.run(prog, feed={'x': xv}, fetch_list=[result])
        np.testing.assert_allclose(out[0], np.cumsum(xv, axis=0), rtol=1e-5)

    def test_rnn_with_fc(self, static_mode):
        """The docstring-style recipe: fc over [word, prev] per step."""
        T, B, D, H = 3, 2, 4, 5
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data('x', [T, B, D], 'float32')
            rnn = layers.StaticRNN()
            with rnn.step():
                word = rnn.step_input(x)
                prev = rnn.memory(shape=[-1, H], batch_ref=word)
                joint = layers.concat([word, prev], axis=1)
                hidden = layers.fc(joint, size=H, activation='relu')
                rnn.update_memory(prev, hidden)
                rnn.step_output(hidden)
            result = rnn()
            exe = static.Executor()
            xv = np.random.RandomState(0).randn(T, B, D).astype(np.float32)
            out = exe.run(prog, feed={'x': xv}, fetch_list=[result])
        assert out[0].shape == (T, B, H)
        assert np.isfinite(out[0]).all()
        assert (out[0] >= 0).all()        # relu


class TestDynamicRNN:
    def test_masked_lengths(self, static_mode):
        """DynamicRNN freezes memories and zeroes outputs past each row's
        length (the dense analogue of LoD shrinking)."""
        B, T, D = 2, 4, 3
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data('x', [B, T, D], 'float32')
            lens = static.data('lens', [B], 'int32')
            drnn = layers.DynamicRNN()
            with drnn.block():
                w = drnn.step_input(x, length=lens)
                prev = drnn.memory(shape=[D])
                h = prev + w
                drnn.update_memory(prev, h)
                drnn.output(h)
            res = drnn()
            exe = static.Executor()
            xv = np.ones((B, T, D), np.float32)
            lv = np.array([2, 4], np.int32)
            out = exe.run(prog, feed={'x': xv, 'lens': lv},
                          fetch_list=[res])
        o = out[0]
        assert o.shape == (B, T, D)
        np.testing.assert_allclose(o[0, :2], np.cumsum(xv[0, :2], 0))
        np.testing.assert_allclose(o[0, 2:], 0.0)        # past length
        np.testing.assert_allclose(o[1], np.cumsum(xv[1], 0))


class TestPrintAssert:
    def test_print_passthrough(self, static_mode):
        prog = static.Program()
        with static.program_guard(prog):
            x = static.data('x', [2], 'float32')
            y = layers.Print(x, message='dbg') * 2.0
            exe = static.Executor()
            out = exe.run(prog, feed={'x': np.array([1.0, 2.0], np.float32)},
                          fetch_list=[y])
        np.testing.assert_allclose(out[0], [2.0, 4.0])

    def test_assert_raises(self):
        x = paddle.to_tensor(np.array([0.0], np.float32))
        with pytest.raises(Exception):
            layers.Assert(x > 1.0)

    def test_assert_passes(self):
        x = paddle.to_tensor(np.array([2.0], np.float32))
        layers.Assert(x > 1.0)   # no raise

    def test_reorder_identity(self):
        x = paddle.to_tensor(np.ones((2, 3), np.float32))
        out = layers.reorder_lod_tensor_by_rank(x, None)
        np.testing.assert_allclose(out.numpy(), x.numpy())


class TestEagerWriterOps:
    def test_increment_eager_inplace(self):
        x = paddle.to_tensor(np.array([1.0], np.float32))
        layers.increment(x, 2.0)
        np.testing.assert_allclose(x.numpy(), [3.0])

    def test_cmp_eager(self):
        a = paddle.to_tensor(np.array([1.0], np.float32))
        b = paddle.to_tensor(np.array([2.0], np.float32))
        assert bool(layers.less_than(a, b).numpy()[0])
        assert not bool(layers.greater_than(a, b).numpy()[0])
        assert bool(layers.not_equal(a, b).numpy()[0])
