"""Model-level convergence (SURVEY §4 E2E promises): LeNet/MNIST accuracy,
BERT-tiny pretrain loss strictly decreasing, Wide&Deep AUC improving."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_lenet_mnist_converges_above_95():
    """LeNet on (synthetic) MNIST through the real Dataset/DataLoader/hapi
    stack reaches >95% train-split accuracy within two epochs."""
    from paddle_tpu.vision.datasets import MNIST
    from paddle_tpu.vision.models import LeNet
    from paddle_tpu.io import DataLoader

    paddle.seed(42)
    train = MNIST(mode='train', backend=None)

    class Wrapped(paddle.io.Dataset):
        """MNIST items are already float32 (1, 28, 28) in [0, 1]."""

        def __len__(self):
            return len(train)

        def __getitem__(self, i):
            img, lab = train[i]
            return np.asarray(img, np.float32).reshape(1, 28, 28), \
                np.int64(lab)

    loader = DataLoader(Wrapped(), batch_size=64, shuffle=True)
    model = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                parameters=model.parameters())
    for epoch in range(2):
        model.train()
        for x, y in loader:
            loss = nn.functional.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
    model.eval()
    correct = total = 0
    for x, y in loader:
        pred = model(x).numpy().argmax(-1)
        correct += int((pred == y.numpy()).sum())
        total += len(pred)
    acc = correct / total
    assert acc > 0.95, f"LeNet train accuracy {acc:.3f} <= 0.95"


def test_bert_tiny_pretrain_loss_strictly_decreases():
    """BERT-tiny MLM+NSP pretraining: smoothed loss strictly decreases
    across thirds of the run."""
    from paddle_tpu.text import BertConfig, BertForPretraining

    paddle.seed(0)
    cfg = BertConfig(vocab_size=200, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=128,
                     max_position_embeddings=32)
    model = BertForPretraining(cfg)
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=5e-4,
                                 parameters=model.parameters())
    rng = np.random.default_rng(1)
    B, L, K = 16, 24, 4
    losses = []
    for step in range(30):
        ids = rng.integers(4, 200, (B, L)).astype('int64')
        pos = np.stack([rng.choice(L, K, replace=False)
                        for _ in range(B)]).astype('int64')
        labels = np.take_along_axis(ids, pos, axis=1)
        masked = ids.copy()
        np.put_along_axis(masked, pos, 3, axis=1)    # [MASK]=3
        nsp = rng.integers(0, 2, (B, 1)).astype('int64')
        logits, nsp_logits = model(
            paddle.to_tensor(masked),
            masked_positions=paddle.to_tensor(pos))
        loss = model.pretraining_loss(
            logits, nsp_logits, paddle.to_tensor(labels),
            paddle.to_tensor(nsp))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    thirds = [np.mean(losses[:10]), np.mean(losses[10:20]),
              np.mean(losses[20:])]
    assert thirds[0] > thirds[1] > thirds[2], thirds
    assert all(np.isfinite(losses))


def test_wide_deep_auc_improves():
    """Wide&Deep on synthetic CTR data: held-out AUC after training beats
    the untrained model by a wide margin."""
    from paddle_tpu.rec import WideDeep
    from paddle_tpu.metric import auc

    paddle.seed(5)
    rng = np.random.default_rng(2)
    slots = [50, 30, 20]
    n = 2048
    sparse = np.stack([rng.integers(0, v, n) for v in slots],
                      axis=1).astype('int64')
    dense = rng.standard_normal((n, 8)).astype('float32')
    # clickiness depends on slot-0 id parity and dense[0]
    score = (sparse[:, 0] % 2) * 1.5 + dense[:, 0] - 0.75
    y = (score + rng.normal(0, 0.3, n) > 0).astype('int64')
    n_train = 1536
    model = WideDeep(slots, dense_dim=8, embedding_dim=8,
                     hidden_sizes=(64, 32))

    def eval_auc():
        model.eval()
        logits = model(paddle.to_tensor(sparse[n_train:]),
                       paddle.to_tensor(dense[n_train:]))
        p = 1.0 / (1.0 + np.exp(-logits.numpy().reshape(-1)))
        return float(auc(p, y[n_train:]).numpy())

    auc_before = eval_auc()
    opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                parameters=model.parameters())
    model.train()
    for step in range(60):
        idx = rng.integers(0, n_train, 256)
        logits = model(paddle.to_tensor(sparse[idx]),
                       paddle.to_tensor(dense[idx]))
        loss = nn.functional.binary_cross_entropy_with_logits(
            logits.reshape([-1]),
            paddle.to_tensor(y[idx].astype('float32')))
        loss.backward()
        opt.step()
        opt.clear_grad()
    auc_after = eval_auc()
    assert auc_after > max(auc_before + 0.1, 0.8), \
        f"AUC {auc_before:.3f} -> {auc_after:.3f}"
