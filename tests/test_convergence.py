"""Model-level convergence (SURVEY §4 E2E promises): LeNet/MNIST accuracy,
BERT-tiny pretrain loss strictly decreasing, Wide&Deep AUC improving."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn


def test_lenet_mnist_converges_above_95():
    """LeNet on (synthetic) MNIST through the real Dataset/DataLoader/hapi
    stack reaches >95% train-split accuracy within two epochs."""
    from paddle_tpu.vision.datasets import MNIST
    from paddle_tpu.vision.models import LeNet
    from paddle_tpu.io import DataLoader

    paddle.seed(42)
    train = MNIST(mode='train', backend=None)

    class Wrapped(paddle.io.Dataset):
        """MNIST items are already float32 (1, 28, 28) in [0, 1]."""

        def __len__(self):
            return len(train)

        def __getitem__(self, i):
            img, lab = train[i]
            return np.asarray(img, np.float32).reshape(1, 28, 28), \
                np.int64(lab)

    loader = DataLoader(Wrapped(), batch_size=64, shuffle=True)
    model = LeNet()
    opt = paddle.optimizer.Adam(learning_rate=2e-3,
                                parameters=model.parameters())
    for epoch in range(2):
        model.train()
        for x, y in loader:
            loss = nn.functional.cross_entropy(model(x), y)
            loss.backward()
            opt.step()
            opt.clear_grad()
    model.eval()
    correct = total = 0
    for x, y in loader:
        pred = model(x).numpy().argmax(-1)
        correct += int((pred == y.numpy()).sum())
        total += len(pred)
    acc = correct / total
    assert acc > 0.95, f"LeNet train accuracy {acc:.3f} <= 0.95"


def test_bert_tiny_pretrain_loss_strictly_decreases():
    """BERT-tiny MLM+NSP pretraining: smoothed loss strictly decreases
    across thirds of the run."""
    from paddle_tpu.text import BertConfig, BertForPretraining

    paddle.seed(0)
    cfg = BertConfig(vocab_size=200, hidden_size=64, num_hidden_layers=2,
                     num_attention_heads=2, intermediate_size=128,
                     max_position_embeddings=32)
    model = BertForPretraining(cfg)
    model.train()
    opt = paddle.optimizer.AdamW(learning_rate=5e-4,
                                 parameters=model.parameters())
    rng = np.random.default_rng(1)
    B, L, K = 16, 24, 4
    losses = []
    for step in range(30):
        ids = rng.integers(4, 200, (B, L)).astype('int64')
        pos = np.stack([rng.choice(L, K, replace=False)
                        for _ in range(B)]).astype('int64')
        labels = np.take_along_axis(ids, pos, axis=1)
        masked = ids.copy()
        np.put_along_axis(masked, pos, 3, axis=1)    # [MASK]=3
        nsp = rng.integers(0, 2, (B, 1)).astype('int64')
        logits, nsp_logits = model(
            paddle.to_tensor(masked),
            masked_positions=paddle.to_tensor(pos))
        loss = model.pretraining_loss(
            logits, nsp_logits, paddle.to_tensor(labels),
            paddle.to_tensor(nsp))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    thirds = [np.mean(losses[:10]), np.mean(losses[10:20]),
              np.mean(losses[20:])]
    assert thirds[0] > thirds[1] > thirds[2], thirds
    assert all(np.isfinite(losses))


def test_wide_deep_auc_improves():
    """Wide&Deep on synthetic CTR data: held-out AUC after training beats
    the untrained model by a wide margin."""
    from paddle_tpu.rec import WideDeep
    from paddle_tpu.metric import auc

    paddle.seed(5)
    rng = np.random.default_rng(2)
    slots = [50, 30, 20]
    n = 2048
    sparse = np.stack([rng.integers(0, v, n) for v in slots],
                      axis=1).astype('int64')
    dense = rng.standard_normal((n, 8)).astype('float32')
    # clickiness depends on slot-0 id parity and dense[0]
    score = (sparse[:, 0] % 2) * 1.5 + dense[:, 0] - 0.75
    y = (score + rng.normal(0, 0.3, n) > 0).astype('int64')
    n_train = 1536
    model = WideDeep(slots, dense_dim=8, embedding_dim=8,
                     hidden_sizes=(64, 32))

    def eval_auc():
        model.eval()
        logits = model(paddle.to_tensor(sparse[n_train:]),
                       paddle.to_tensor(dense[n_train:]))
        p = 1.0 / (1.0 + np.exp(-logits.numpy().reshape(-1)))
        return float(auc(p, y[n_train:]).numpy())

    auc_before = eval_auc()
    opt = paddle.optimizer.Adam(learning_rate=5e-3,
                                parameters=model.parameters())
    model.train()
    for step in range(60):
        idx = rng.integers(0, n_train, 256)
        logits = model(paddle.to_tensor(sparse[idx]),
                       paddle.to_tensor(dense[idx]))
        loss = nn.functional.binary_cross_entropy_with_logits(
            logits.reshape([-1]),
            paddle.to_tensor(y[idx].astype('float32')))
        loss.backward()
        opt.step()
        opt.clear_grad()
    auc_after = eval_auc()
    assert auc_after > max(auc_before + 0.1, 0.8), \
        f"AUC {auc_before:.3f} -> {auc_after:.3f}"


def test_resnet_tiny_images_loss_decreases():
    """ResNet-18 NHWC (the TPU conv layout) on a learnable synthetic
    image task: a large first->middle smoothed-loss drop that the tail
    HOLDS (batch-8 BN noise rules out strict monotonicity) — the BASELINE
    'ResNet-50 ImageNet' config's convergence smoke at CI scale."""
    from paddle_tpu.vision.models import resnet18
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    net = resnet18(num_classes=4, data_format='NHWC')
    net.train()
    opt = paddle.optimizer.Momentum(learning_rate=0.01, momentum=0.9,
                                    parameters=net.parameters())
    rng = np.random.default_rng(0)
    B = 8
    losses = []
    for step in range(18):
        labels = rng.integers(0, 4, (B,))
        # class k brightens quadrant k: a signal a conv stack learns fast
        imgs = rng.normal(0, 0.3, (B, 32, 32, 3)).astype('float32')
        for i, k in enumerate(labels):
            r, c = divmod(int(k), 2)
            imgs[i, r * 16:(r + 1) * 16, c * 16:(c + 1) * 16] += 1.0
        logits = net(paddle.to_tensor(imgs))
        loss = F.cross_entropy(logits,
                               paddle.to_tensor(labels.astype('int64')))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    thirds = [np.mean(losses[:6]), np.mean(losses[6:12]),
              np.mean(losses[12:])]
    # batch-8 BN makes the tail noisy: require a big first->middle drop and
    # the tail to HOLD the gain, not strict monotonicity
    assert thirds[1] < 0.5 * thirds[0], thirds
    assert thirds[2] < 0.5 * thirds[0], thirds
    assert all(np.isfinite(losses))


def test_ernie_finetune_dygraph_dynamic_shapes_converges():
    """ERNIE-tiny classification finetune in DYGRAPH mode with a different
    sequence length every step (the BASELINE 'ERNIE-large finetune
    (dygraph Tracer path, dynamic shapes)' config at CI scale): eager
    tensors retrace nothing, grads flow, smoothed loss decreases."""
    from paddle_tpu.text import ErnieConfig, ErnieModel
    import paddle_tpu.nn as nn
    import paddle_tpu.nn.functional as F

    paddle.seed(0)
    cfg = ErnieConfig(vocab_size=120, hidden_size=48, num_hidden_layers=2,
                      num_attention_heads=2, intermediate_size=96,
                      max_position_embeddings=48)
    encoder = ErnieModel(cfg)
    head = nn.Linear(48, 2)
    encoder.train()
    params = list(encoder.parameters()) + list(head.parameters())
    opt = paddle.optimizer.AdamW(learning_rate=1e-3, parameters=params)
    rng = np.random.default_rng(2)
    losses = []
    for step in range(24):
        L = int(rng.integers(8, 33))          # dynamic shapes every step
        ids = rng.integers(6, 120, (8, L)).astype('int64')  # never 5
        # balanced by construction: half the rows get token 5 planted at a
        # random position — the head cannot win on class prior alone, the
        # pooled output must actually mix sequence content
        labels = rng.permutation(np.repeat([0, 1], 4)).astype('int64')
        for i, y in enumerate(labels):
            if y:
                ids[i, rng.integers(0, L)] = 5
        _, pooled = encoder(paddle.to_tensor(ids))
        loss = F.cross_entropy(head(pooled), paddle.to_tensor(labels))
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss.numpy()))
    thirds = [np.mean(losses[:8]), np.mean(losses[8:16]),
              np.mean(losses[16:])]
    assert thirds[0] > thirds[2], thirds
    assert all(np.isfinite(losses))
