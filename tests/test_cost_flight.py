"""Cost explorer + flight recorder acceptance tests (ISSUE 13, marker
``obs``).

Covers: the cost ledger populated from all three compile paths (Executor
program cache, ``engine.build_train_step``, serving runner warmup) with
nonzero ``cost_analysis``/``memory_analysis`` numbers that stay stable
across cache hits (``jax.compiles`` flat — no recompiles added), the
roofline estimate, the ``/costs`` endpoint slice and ``telemetry_dump
--costs`` table; one serving request rendering as a connected async flow
in the merged Chrome trace; the SLO tracker + ``slo_burn`` and
``memory_pressure`` doctor detectors (and their ``--fail-on`` CI gates);
and the flight recorder — always-on bounded ring, atomic dumps that never
parse partially, dump-on-NaN-abort / SIGTERM / worker-exception /
watchdog-timeout, ``--merge`` carrying per-rank dumps, and
``tools/postmortem.py`` rendering + diagnosing a dump.
"""
import json
import os
import signal
import subprocess
import sys
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu import observability as obs
from paddle_tpu import serving
from paddle_tpu.observability import costs, flight, slo

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _telemetry_isolation():
    obs.disable()
    obs.reset()
    yield
    obs.disable()
    obs.close_sink()
    obs.reset()


def _compiles():
    return obs.snapshot()['counters'].get('jax.compiles', 0)


def _lm(seed=0, **kw):
    kw.setdefault('vocab', 32)
    kw.setdefault('embed', 16)
    kw.setdefault('num_heads', 2)
    kw.setdefault('max_batch', 2)
    kw.setdefault('max_seq', 32)
    kw.setdefault('prompt_buckets', (4, 8))
    return serving.TinyCausalLM.random(seed=seed, **kw)


# ---------------------------------------------------------------------------
# cost ledger: the three compile paths
# ---------------------------------------------------------------------------

class TestCostLedger:
    def test_executor_capture_nonzero_and_stable_across_cache_hits(self):
        obs.enable()
        obs.install_jax_hooks()
        import paddle_tpu.static as static
        paddle.enable_static()
        try:
            main, startup = static.Program(), static.Program()
            with static.program_guard(main, startup):
                x = static.data('x', shape=[-1, 8], dtype='float32')
                y = paddle.matmul(x, paddle.to_tensor(
                    np.ones((8, 4), np.float32)))
            exe = static.Executor()
            feed = {'x': np.ones((2, 8), np.float32)}
            exe.run(main, feed=feed, fetch_list=[y])
            entries = [e for e in costs.ledger()
                       if e['kind'] == 'executor.infer']
            assert len(entries) == 1
            e = entries[0]
            # cost_analysis + memory_analysis both nonzero on CPU
            assert e['flops'] > 0 and e['bytes_accessed'] > 0
            assert e['argument_bytes'] > 0 and e['output_bytes'] > 0
            assert e['peak_bytes'] >= e['argument_bytes'] + e['output_bytes']
            assert e['roofline']['bound'] in ('compute', 'memory')
            assert e['roofline']['est_ms'] > 0
            # cache hit: SAME numbers, a hit tick, and NO new compile
            warm = _compiles()
            exe.run(main, feed=feed, fetch_list=[y])
            assert _compiles() == warm, \
                "cost capture added a recompile on a program-cache hit"
            e2 = costs.entry(e['program'])
            assert e2['flops'] == e['flops']
            assert e2['peak_bytes'] == e['peak_bytes']
            assert e2['hits'] == 1
        finally:
            paddle.disable_static()

    def test_engine_train_step_capture_and_flat_compiles(self):
        obs.enable()
        obs.install_jax_hooks()
        from paddle_tpu.engine import build_train_step
        opt = paddle.optimizer.SGD(learning_rate=0.1)

        def loss_fn(params, buffers, batch, key):
            x, t = batch
            pred = x @ params['w']
            return jnp.mean((pred - t) ** 2), (pred,), buffers

        step = build_train_step(loss_fn=loss_fn, optimizer=opt)
        state = step.init_state({'w': jnp.ones((4, 2))})
        batch = (jnp.ones((3, 4)), jnp.zeros((3, 2)))
        state, _ = step(state, batch)
        ent = costs.entry(step.cost_label)
        assert ent is not None and ent['kind'] == 'train_step'
        assert ent['flops'] > 0 and ent['bytes_accessed'] > 0
        assert ent['peak_bytes'] > 0
        warm = _compiles()
        for _ in range(3):
            state, _ = step(state, batch)
        assert _compiles() == warm, \
            "train-step cost capture must not recompile after warmup"
        assert costs.entry(step.cost_label)['flops'] == ent['flops']

    def test_serving_warmup_populates_ledger_for_runner_programs(self):
        obs.enable()
        eng = serving.ServingEngine()
        eng.register('lm', generative=_lm(), page_size=4)
        eng.register('clf', example={'x': np.zeros((4,), np.float32)},
                     predict_fn=lambda feeds: feeds['x'] * 2.0,
                     bucket_spec=serving.BucketSpec((1, 2)))
        eng.warmup()
        programs = {e['program']: e for e in costs.ledger()}
        assert 'serving.lm.prefill4' in programs
        assert 'serving.lm.prefill8' in programs
        assert 'serving.lm.decode' in programs
        assert 'serving.clf.b1' in programs and 'serving.clf.b2' in programs
        assert all(e['flops'] > 0 for e in programs.values())

    def test_roofline_env_overrides_and_summary(self, monkeypatch):
        obs.enable()
        monkeypatch.setenv('PADDLE_TPU_DEVICE_PEAK_FLOPS', '1e9')
        monkeypatch.setenv('PADDLE_TPU_DEVICE_PEAK_BPS', '1e9')
        r = costs.roofline(2e9, 1e9)      # AI=2 >= ridge=1 -> compute-bound
        assert r['bound'] == 'compute' and r['est_ms'] == 2000.0
        r2 = costs.roofline(1e8, 1e9)     # AI=0.1 < 1 -> memory-bound
        assert r2['bound'] == 'memory'
        costs.record_costs('p1', 100.0, 50.0,
                           {'argument_bytes': 10, 'output_bytes': 5})
        s = costs.summary()
        assert s['programs'] == 1 and s['total_flops'] == 100.0
        assert s['max_peak_program'] == 'p1' and s['max_peak_bytes'] == 15

    def test_capture_off_when_telemetry_disabled(self):
        f = jax.jit(lambda x: x + 1)
        assert costs.capture('off.prog', f, jnp.ones(3)) is None
        assert costs.ledger() == []

    def test_costs_endpoint_slice(self):
        obs.enable()
        costs.record_costs('ep.prog', 42.0, 21.0,
                           {'argument_bytes': 8, 'output_bytes': 8})
        srv = obs.MetricsServer(host='127.0.0.1', port=0).start()
        try:
            from urllib.request import urlopen
            body = json.load(urlopen(f"{srv.url}/costs", timeout=10))
            assert body['summary']['programs'] == 1
            assert body['programs'][0]['program'] == 'ep.prog'
            # the route is advertised on 404s
            import urllib.error
            try:
                urlopen(f"{srv.url}/nope", timeout=10)
            except urllib.error.HTTPError as e:
                assert '/costs' in e.read().decode()
        finally:
            srv.stop()

    def test_telemetry_dump_costs_table(self, tmp_path):
        obs.enable()
        costs.record_costs('tbl.prog', 1e6, 5e5,
                           {'argument_bytes': 100, 'output_bytes': 50})
        log = tmp_path / 'events.jsonl'
        obs.dump_jsonl(str(log))
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools/telemetry_dump.py'),
             str(log), '--costs'], capture_output=True, text=True)
        assert out.returncode == 0
        assert 'tbl.prog' in out.stdout and 'MFLOP' in out.stdout


# ---------------------------------------------------------------------------
# per-request serving traces + SLO
# ---------------------------------------------------------------------------

class TestRequestTraces:
    def test_request_renders_as_connected_flow_in_merged_trace(self,
                                                               tmp_path):
        obs.enable()
        eng = serving.ServingEngine()
        ep = eng.register('lm', generative=_lm(), page_size=4)
        eng.warmup()
        f = ep.submit({'tokens': np.array([1, 2, 3], np.int32)},
                      max_new_tokens=4)
        eng.run_until_idle()
        r = f.result(10)
        assert r.ok
        # breakdown attributed per phase, mirrored onto the request event
        assert r.breakdown.get('prefill', 0) > 0
        assert r.breakdown.get('decode', 0) > 0
        ev = [e for e in obs.event_log() if e.get('ev') == 'serving.request']
        assert ev and 'prefill_ms' in ev[-1] and 'decode_ms' in ev[-1]
        # flush this rank's trace and merge it the mission-control way
        run_dir = tmp_path / 'run'
        from paddle_tpu.observability.flush import RankFlusher
        RankFlusher(str(run_dir), rank=0).flush_now()
        from paddle_tpu.observability import aggregate
        paths = aggregate.write_merged(str(run_dir))
        with open(paths['trace']) as fh:
            trace = json.load(fh)
        lane = [e for e in trace
                if e.get('cat') == 'serving.request'
                and e.get('id') == str(r.request_id)]
        phases = [e['ph'] for e in lane]
        assert phases[0] == 'b' and phases[-1] == 'e', phases
        assert phases.count('n') >= 2, phases   # prefill + decode milestones
        names = {e['name'] for e in lane}
        assert 'prefill_chunk' in names and 'decode' in names
        # one lane: every edge shares the (cat, id) pair Perfetto groups by
        assert {e['pid'] for e in lane} == {0}

    def test_slo_tracker_and_burn_detector(self):
        obs.enable()
        eng = serving.ServingEngine()
        # objective nothing can meet: every request violates
        ep = eng.register('lm', generative=_lm(), page_size=4,
                          slo_ms=0.0001)
        eng.warmup()
        futs = [ep.submit({'tokens': np.array([1, 2], np.int32)},
                          max_new_tokens=2) for _ in range(4)]
        eng.run_until_idle()
        assert all(f.result(10).ok for f in futs)
        burns = slo.burn_rates()
        assert burns['lm'] > 1.0
        snap = obs.snapshot()
        assert snap['counters'].get('slo.violations_total') == 4
        diags = obs.diagnose(events=obs.event_log(), snapshot=snap)
        burn = [d for d in diags if d['cause'] == 'slo_burn']
        assert burn and burn[0]['evidence']['model'] == 'lm'
        assert burn[0]['severity'] == 'critical'    # 100x burn

    def test_slo_objective_validation_and_ok_path(self):
        with pytest.raises(ValueError):
            slo.set_objective('m', 0)
        with pytest.raises(ValueError):
            slo.set_objective('m', 10, objective=1.5)
        slo.set_objective('m', 1e9, objective=0.5)
        assert slo.record('m', 'ok', 5.0) == 0.0
        assert slo.record('unregistered', 'ok', 5.0) is None

    def test_doctor_cli_fail_on_causes(self, tmp_path, monkeypatch):
        obs.enable()
        slo.set_objective('m', 0.001)
        for _ in range(3):
            slo.record('m', 'ok', 100.0)
        costs.record_costs('big.prog', 10.0, 5.0,
                           {'argument_bytes': 900, 'output_bytes': 200})
        log = tmp_path / 'events.jsonl'
        obs.dump_jsonl(str(log))
        env = dict(os.environ, PADDLE_TPU_HBM_BUDGET='1000')
        doctor_py = os.path.join(REPO, 'tools/doctor.py')
        out = subprocess.run(
            [sys.executable, doctor_py, str(log),
             '--fail-on', 'memory_pressure,slo_burn'],
            capture_output=True, text=True, env=env)
        assert out.returncode == 1, out.stdout + out.stderr
        assert 'slo_burn' in out.stdout and 'memory_pressure' in out.stdout
        # severity spelling still works, unknown causes are an error
        ok = subprocess.run(
            [sys.executable, doctor_py, str(log), '--fail-on', 'critical'],
            capture_output=True, text=True, env=env)
        assert ok.returncode == 1
        bad = subprocess.run(
            [sys.executable, doctor_py, str(log), '--fail-on', 'nonsense'],
            capture_output=True, text=True, env=env)
        assert bad.returncode == 2

    def test_memory_pressure_detector_thresholds(self):
        obs.enable()
        costs.record_costs('fits', 1.0, 1.0,
                           {'argument_bytes': 100, 'output_bytes': 0})
        from paddle_tpu.observability import doctor
        snap = obs.snapshot()
        # 10% of budget: silent
        assert list(doctor.detect_memory_pressure(
            snapshot=snap, hbm_budget=1000)) == []
        # 83%: warning
        warn = list(doctor.detect_memory_pressure(
            snapshot=snap, hbm_budget=120))
        assert warn and warn[0]['severity'] == 'warning'
        # over budget: critical
        crit = list(doctor.detect_memory_pressure(
            snapshot=snap, hbm_budget=80))
        assert crit and crit[0]['severity'] == 'critical'
        assert 'microbatch' in crit[0]['fix']
        # no budget -> no finding (CPU reports no bytes_limit)
        assert list(doctor.detect_memory_pressure(snapshot=snap)) == []


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

class TestFlightRecorder:
    def test_ring_is_always_on_and_bounded(self, tmp_path):
        assert not obs.enabled()            # telemetry OFF
        for i in range(flight.MAX_RECORDS * 3):
            flight.record('tick', i=i)
        recs = flight.records()
        assert len(recs) == flight.MAX_RECORDS     # bounded memory
        assert recs[-1]['i'] == flight.MAX_RECORDS * 3 - 1
        path = flight.dump('test', run_dir=str(tmp_path))
        doc = flight.load_dump(path)
        assert doc['reason'] == 'test'
        assert doc['telemetry_enabled'] is False
        assert len(doc['records']) == flight.MAX_RECORDS

    def test_events_mirror_into_ring_while_enabled(self):
        obs.enable()
        obs.event('step', step=7)
        assert any(r.get('ev') == 'step' and r.get('step') == 7
                   for r in flight.records())

    def test_dump_atomic_partial_write_never_parses(self, tmp_path,
                                                    monkeypatch):
        flight.record('x', a=1)
        target = flight.dump_path(run_dir=str(tmp_path))
        # a failed commit leaves NO target file (staged tmp, os.replace)
        real_replace = os.replace

        def boom(src, dst):
            raise OSError('injected')
        monkeypatch.setattr(os, 'replace', boom)
        assert flight.dump('crash', run_dir=str(tmp_path)) is None
        assert not os.path.exists(target)
        monkeypatch.setattr(os, 'replace', real_replace)
        # a torn file (simulated truncation) never parses as a dump
        path = flight.dump('crash', run_dir=str(tmp_path))
        with open(path) as f:
            whole = f.read()
        with open(path, 'w') as f:
            f.write(whole[:len(whole) // 2])
        assert flight.load_dump(path) is None
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools/postmortem.py'),
             path], capture_output=True, text=True)
        assert out.returncode == 2
        assert 'does not parse' in out.stderr

    def test_nan_abort_dumps_and_postmortem_diagnoses(self, tmp_path,
                                                      monkeypatch):
        monkeypatch.setenv('PADDLE_TPU_FLIGHT_DIR', str(tmp_path))
        obs.enable()
        from paddle_tpu.resilience import NanGuard, NanStepError, faultinject
        guard = NanGuard(max_consecutive_skips=2, verbose=False)

        def loss_fn():
            return 1.0
        poisoned = faultinject.poison_loss(loss_fn, at_steps=(0, 1, 2))
        with pytest.raises(NanStepError):
            for _ in range(3):
                guard.check(poisoned())
        path = flight.dump_path(run_dir=str(tmp_path))
        doc = flight.load_dump(path)
        assert doc['reason'] == 'nan_abort'
        assert doc['exception']['type'] == 'NanStepError'
        assert doc['extra']['consecutive'] == 2
        # the ring carries the skip events leading up to the abort
        assert any(r.get('ev') == 'nan_guard.skip' for r in doc['records'])
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools/postmortem.py'),
             path, '--tail', '5'], capture_output=True, text=True)
        assert out.returncode == 0
        assert "reason='nan_abort'" in out.stdout
        assert 'NanStepError' in out.stdout
        as_json = subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools/postmortem.py'),
             path, '--json'], capture_output=True, text=True)
        parsed = json.loads(as_json.stdout)
        assert parsed['dump']['reason'] == 'nan_abort'

    def test_engine_in_graph_nan_abort_dumps(self, tmp_path, monkeypatch):
        monkeypatch.setenv('PADDLE_TPU_FLIGHT_DIR', str(tmp_path))
        obs.enable()
        from paddle_tpu.engine import build_train_step
        from paddle_tpu.resilience import NanGuard, NanStepError
        opt = paddle.optimizer.SGD(learning_rate=0.1)

        def loss_fn(params, buffers, batch, key):
            return jnp.float32(np.nan), (), buffers

        step = build_train_step(loss_fn=loss_fn, optimizer=opt,
                                nan_guard=True)
        guard = NanGuard(max_consecutive_skips=2, verbose=False)
        state = step.init_state({'w': jnp.ones((2,))}, nan_guard=guard)
        with pytest.raises(NanStepError):
            for _ in range(3):
                state, _ = step(state, jnp.ones((1, 2)))
                step.sync(state, nan_guard=guard)
        doc = flight.load_dump(flight.dump_path(run_dir=str(tmp_path)))
        assert doc['reason'] == 'nan_abort'

    def test_sigterm_dump(self, tmp_path):
        code = (
            "import os, signal, sys\n"
            "sys.path.insert(0, %r)\n"
            "from paddle_tpu.observability import flight\n"
            "flight.record('about_to_die', step=3)\n"
            "assert flight.install_crash_hooks()\n"
            "os.kill(os.getpid(), signal.SIGTERM)\n"
            "print('UNREACHABLE')\n" % REPO)
        env = dict(os.environ, PADDLE_TPU_FLIGHT_DIR=str(tmp_path),
                   JAX_PLATFORMS='cpu')
        out = subprocess.run([sys.executable, '-c', code],
                             capture_output=True, text=True, env=env,
                             timeout=60)
        # the handler dumps, then re-delivers SIGTERM: default death
        assert out.returncode != 0 and 'UNREACHABLE' not in out.stdout
        dumps = [n for n in os.listdir(tmp_path)
                 if n.startswith('flight_rank')]
        assert dumps, 'SIGTERM left no flight dump'
        doc = flight.load_dump(os.path.join(tmp_path, dumps[0]))
        assert doc['reason'] == 'sigterm'
        assert any(r.get('ev') == 'about_to_die' for r in doc['records'])

    def test_worker_exception_dump(self, tmp_path, monkeypatch):
        monkeypatch.setenv('PADDLE_TPU_FLIGHT_DIR', str(tmp_path))
        # silence the chained default printer for the intentional crash
        monkeypatch.setattr(threading, 'excepthook', lambda args: None)
        flight.install_crash_hooks()
        try:
            t = threading.Thread(
                target=lambda: (_ for _ in ()).throw(
                    RuntimeError('worker boom')),
                name='doomed')
            t.start()
            t.join(10)
            doc = flight.load_dump(flight.dump_path(run_dir=str(tmp_path)))
            assert doc['reason'] == 'worker_exception'
            assert doc['exception']['message'] == 'worker boom'
            assert doc['extra']['thread'] == 'doomed'
        finally:
            flight.uninstall_crash_hooks()

    def test_watchdog_timeout_dumps_rate_limited_side_file(self, tmp_path,
                                                           monkeypatch):
        monkeypatch.setenv('PADDLE_TPU_FLIGHT_DIR', str(tmp_path))
        from paddle_tpu.resilience import watchdog
        monkeypatch.setattr(watchdog, '_last_flight_dump', [0.0])
        watchdog.WatchdogTimeout('late', what='test wait', waited=1.5)
        # the dump goes to a watchdog-specific SIDE file: a caught client
        # timeout must never clobber the primary black box
        side = os.path.join(str(tmp_path),
                            f'flight_rank{flight.rank_id()}_watchdog.json')
        assert not os.path.exists(flight.dump_path(run_dir=str(tmp_path)))
        doc = flight.load_dump(side)
        assert doc['reason'] == 'watchdog_timeout'
        assert doc['extra'] == {'what': 'test wait', 'waited': 1.5}
        # rate limit: an immediate second construction records into the
        # ring but does not rewrite the file
        before = os.path.getmtime(side)
        watchdog.WatchdogTimeout('late again', what='poll', waited=0.1)
        assert os.path.getmtime(side) == before
        assert any(r.get('ev') == 'watchdog_timeout' and
                   r.get('what') == 'poll' for r in flight.records())

    def test_slo_burn_snapshot_gauge_wins_over_stale_events(self):
        from paddle_tpu.observability import doctor
        # an old violation event says burn 10x, but the live gauge — which
        # every later good request updates — says 0.1x: no finding
        events = [{'ev': 'slo.violation', 'model': 'm', 'burn_rate': 10.0}]
        snap = {'gauges': {'slo.burn_rate{model=m}': 0.1},
                'counters': {'slo.violations{model=m}': 1}}
        assert list(doctor.detect_slo_burn(events=events,
                                           snapshot=snap)) == []
        # events alone (a bare log / flight dump) still fire, last wins,
        # and counts are not double-counted against the counter
        hot = list(doctor.detect_slo_burn(events=events * 3, snapshot=None))
        assert hot and hot[0]['evidence']['violations'] == 3

    def test_labeled_parse_survives_commas_in_program_labels(self):
        from paddle_tpu.observability import doctor
        snap = {'gauges': {
            'cost.peak_bytes{program=executor.p1[4x8,16x2]}': 900.0,
            'cost.peak_bytes{program=executor.p1[4x8,32x2]}': 100.0,
        }}
        got = doctor._labeled(snap['gauges'], 'cost.peak_bytes',
                              key='program')
        assert got == {'executor.p1[4x8,16x2]': 900.0,
                       'executor.p1[4x8,32x2]': 100.0}
        crit = list(doctor.detect_memory_pressure(snapshot=snap,
                                                  hbm_budget=500))
        assert crit and crit[0]['evidence']['program'] == \
            'executor.p1[4x8,16x2]'

    def test_merge_carries_flight_dumps_into_snapshot(self, tmp_path):
        obs.enable()
        run_dir = tmp_path / 'run'
        from paddle_tpu.observability.flush import RankFlusher
        RankFlusher(str(run_dir), rank=0).flush_now()
        flight.record('last_words')
        flight.dump('rank_failed', exc=RuntimeError('chip fell over'),
                    run_dir=str(run_dir))
        from paddle_tpu.observability import aggregate
        snap = aggregate.cluster_snapshot(str(run_dir))
        assert snap['flight_dumps'][0]['reason'] == 'rank_failed'
        assert snap['flight_dumps'][0]['exception']['type'] == \
            'RuntimeError'
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools/telemetry_dump.py'),
             str(run_dir), '--merge'], capture_output=True, text=True)
        assert out.returncode == 0
        assert 'rank_failed' in out.stdout and 'chip fell over' in out.stdout
        # postmortem over the whole run dir finds the per-rank dump
        pm = subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools/postmortem.py'),
             str(run_dir)], capture_output=True, text=True)
        assert pm.returncode == 0 and "rank_failed" in pm.stdout

    def test_flight_disabled_via_env(self, tmp_path):
        # the kill switch is read at import: simulate via a subprocess
        code = (
            "import sys\n"
            "sys.path.insert(0, %r)\n"
            "from paddle_tpu.observability import flight\n"
            "assert not flight.enabled()\n"
            "assert flight.record('x') is None\n"
            "assert flight.dump('r') is None\n"
            "assert not flight.install_crash_hooks()\n"
            "print('DISABLED_OK')\n" % REPO)
        env = dict(os.environ, PADDLE_TPU_FLIGHT='0',
                   PADDLE_TPU_FLIGHT_DIR=str(tmp_path),
                   JAX_PLATFORMS='cpu')
        out = subprocess.run([sys.executable, '-c', code],
                             capture_output=True, text=True, env=env,
                             timeout=60)
        assert 'DISABLED_OK' in out.stdout, out.stderr
        assert not os.listdir(tmp_path)
