"""CRF (log-likelihood + Viterbi) and fluid.metrics extras."""
import itertools

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn.functional as F


def _brute_force(emission, transition, length):
    """Enumerate all paths: returns (logZ, best_path, path_score_fn)."""
    start, stop, w = transition[0], transition[1], transition[2:]
    D = emission.shape[1]

    def score(path):
        s = start[path[0]] + emission[0, path[0]] + stop[path[-1]]
        for t in range(1, len(path)):
            s += w[path[t - 1], path[t]] + emission[t, path[t]]
        return s

    paths = list(itertools.product(range(D), repeat=length))
    scores = np.array([score(p) for p in paths])
    log_z = np.log(np.exp(scores - scores.max()).sum()) + scores.max()
    return log_z, list(paths[int(np.argmax(scores))]), score


class TestLinearChainCRF:
    def _setup(self, B=3, T=5, D=4, seed=0):
        rng = np.random.default_rng(seed)
        emission = rng.standard_normal((B, T, D)).astype('float32')
        transition = rng.standard_normal((D + 2, D)).astype('float32') * 0.5
        label = rng.integers(0, D, (B, T)).astype('int64')
        length = np.array([T, T - 2, 3, T - 1], dtype='int64')[:B]
        return emission, transition, label, length

    def test_nll_matches_brute_force(self):
        emission, transition, label, length = self._setup()
        nll = F.linear_chain_crf(
            paddle.to_tensor(emission), paddle.to_tensor(label),
            paddle.to_tensor(transition), paddle.to_tensor(length)).numpy()
        for b in range(len(length)):
            L = int(length[b])
            log_z, _, score = _brute_force(emission[b], transition, L)
            gold = score(label[b, :L].tolist())
            np.testing.assert_allclose(nll[b, 0], log_z - gold, rtol=1e-4)

    def test_gradients_vs_finite_differences(self):
        import jax
        emission, transition, label, length = self._setup(B=2, T=4, D=3)

        def loss_np(trans_flat):
            t = paddle.to_tensor(
                trans_flat.reshape(transition.shape).astype('float32'))
            return float(F.linear_chain_crf(
                paddle.to_tensor(emission), paddle.to_tensor(label),
                t, paddle.to_tensor(length)).numpy().mean())

        t = paddle.to_tensor(transition)
        t.stop_gradient = False
        e = paddle.to_tensor(emission)
        e.stop_gradient = False
        nll = F.linear_chain_crf(e, paddle.to_tensor(label), t,
                                 paddle.to_tensor(length)).mean()
        nll.backward()
        g = t.grad.numpy().reshape(-1)
        flat = transition.reshape(-1).astype('float64')
        eps = 1e-3
        for idx in [0, 3, 7, 11, len(flat) - 1]:
            up, dn = flat.copy(), flat.copy()
            up[idx] += eps
            dn[idx] -= eps
            fd = (loss_np(up) - loss_np(dn)) / (2 * eps)
            np.testing.assert_allclose(g[idx], fd, rtol=2e-2, atol=2e-3)
        assert e.grad is not None   # emission grads flow too

    def test_nll_positive_and_decreases_under_training(self):
        emission, transition, label, length = self._setup(B=4, T=6, D=5,
                                                          seed=3)
        t = paddle.to_tensor(transition)
        t.stop_gradient = False
        e = paddle.to_tensor(emission)
        e.stop_gradient = False
        first = None
        for step in range(30):   # manual SGD on emissions + transitions
            nll = F.linear_chain_crf(e, paddle.to_tensor(label), t,
                                     paddle.to_tensor(length)).mean()
            if first is None:
                first = float(nll.numpy())
            nll.backward()
            for p in (e, t):
                p._inplace_value(p._value - 0.1 * p.grad._value)
                p.clear_grad()
        assert float(nll.numpy()) < first * 0.5
        assert first > 0


class TestCRFDecoding:
    def test_viterbi_matches_brute_force(self):
        rng = np.random.default_rng(5)
        B, T, D = 4, 5, 3
        emission = rng.standard_normal((B, T, D)).astype('float32')
        transition = rng.standard_normal((D + 2, D)).astype('float32')
        length = np.array([5, 4, 2, 1], dtype='int64')
        path = F.crf_decoding(paddle.to_tensor(emission),
                              paddle.to_tensor(transition),
                              paddle.to_tensor(length)).numpy()
        for b in range(B):
            L = int(length[b])
            _, best, _ = _brute_force(emission[b], transition, L)
            np.testing.assert_array_equal(path[b, :L], best)
            np.testing.assert_array_equal(path[b, L:], 0)

    def test_error_mask_with_label(self):
        rng = np.random.default_rng(6)
        emission = rng.standard_normal((2, 4, 3)).astype('float32')
        transition = rng.standard_normal((5, 3)).astype('float32')
        length = np.array([4, 3], dtype='int64')
        path = F.crf_decoding(paddle.to_tensor(emission),
                              paddle.to_tensor(transition),
                              paddle.to_tensor(length)).numpy()
        label = path.copy()
        label[0, 1] = (label[0, 1] + 1) % 3    # one wrong tag
        err = F.crf_decoding(paddle.to_tensor(emission),
                             paddle.to_tensor(transition),
                             paddle.to_tensor(length),
                             label=paddle.to_tensor(label)).numpy()
        assert err[0].tolist() == [0, 1, 0, 0]
        assert err[1].tolist() == [0, 0, 0, 0]

    def test_jit_safe(self):
        from paddle_tpu.jit import to_static
        rng = np.random.default_rng(7)
        emission = rng.standard_normal((2, 4, 3)).astype('float32')
        transition = rng.standard_normal((5, 3)).astype('float32')

        @to_static
        def f(e, t):
            return F.crf_decoding(e, t)

        p1 = f(paddle.to_tensor(emission), paddle.to_tensor(transition))
        p2 = F.crf_decoding(paddle.to_tensor(emission),
                            paddle.to_tensor(transition))
        np.testing.assert_array_equal(p1.numpy(), p2.numpy())


class TestEditDistance:
    def test_known_distances(self):
        from paddle_tpu.metric import edit_distance
        # "kitten"->"sitting" = 3 ; identical = 0
        a = np.array([[1, 2, 3, 3, 4, 5, 0], [1, 2, 3, 0, 0, 0, 0]])
        b = np.array([[6, 2, 3, 3, 2, 5, 7], [1, 2, 3, 0, 0, 0, 0]])
        d, n = edit_distance(a, b, normalized=False,
                             input_length=np.array([6, 3]),
                             label_length=np.array([7, 3]))
        assert d.numpy()[0, 0] == 3.0 and d.numpy()[1, 0] == 0.0
        assert n.numpy()[0] == 2
        dn, _ = edit_distance(a, b, normalized=True,
                              input_length=np.array([6, 3]),
                              label_length=np.array([7, 3]))
        np.testing.assert_allclose(dn.numpy()[0, 0], 3.0 / 7.0, rtol=1e-6)

    def test_ignored_tokens_and_metric(self):
        from paddle_tpu.metric import edit_distance, EditDistance
        a = np.array([[1, 9, 2]])
        b = np.array([[1, 2, 9]])
        d, _ = edit_distance(a, b, normalized=False, ignored_tokens=[9])
        assert d.numpy()[0, 0] == 0.0
        m = EditDistance()
        m.update(np.array([2.0, 0.0, 1.0]))
        avg, err = m.accumulate()
        np.testing.assert_allclose(avg, 1.0)
        np.testing.assert_allclose(err, 2 / 3)


class TestChunkEval:
    def test_iob_scheme(self):
        from paddle_tpu.metric import chunk_eval, ChunkEvaluator
        # 2 chunk types; IOB: tags B-0=0 I-0=1 B-1=2 I-1=3, O=4
        label = np.array([[0, 1, 4, 2, 3, 4]])
        infer = np.array([[0, 1, 4, 2, 4, 4]])   # second chunk truncated
        p, r, f1, ni, nl, nc = chunk_eval(infer, label, 'IOB', 2)
        assert ni.numpy()[0] == 2 and nl.numpy()[0] == 2
        assert nc.numpy()[0] == 1
        np.testing.assert_allclose(p.numpy()[0], 0.5)
        ev = ChunkEvaluator()
        ev.update(ni, nl, nc)
        ev.update(ni, nl, nc)
        prec, rec, f = ev.accumulate()
        np.testing.assert_allclose(prec, 0.5)

    def test_iobes_scheme(self):
        from paddle_tpu.metric import chunk_eval
        # 1 type, IOBES: B=0 I=1 E=2 S=3, O=4
        label = np.array([[0, 1, 2, 4, 3]])   # chunk(0..3) + single(4)
        p, r, f1, ni, nl, nc = chunk_eval(label, label, 'IOBES', 1)
        assert ni.numpy()[0] == 2 and nc.numpy()[0] == 2
        np.testing.assert_allclose(f1.numpy()[0], 1.0)


class TestAucOp:
    def test_matches_sklearn_style_auc(self):
        from paddle_tpu.metric import auc
        rng = np.random.default_rng(0)
        n = 500
        y = rng.integers(0, 2, n)
        # informative scores: positives shifted up
        s = np.clip(rng.normal(0.35 + 0.3 * y, 0.2), 0, 1)
        probs = np.stack([1 - s, s], axis=1)
        a = float(auc(probs, y).numpy())
        # exact rank-based AUC
        pos = s[y == 1]
        neg = s[y == 0]
        exact = (pos[:, None] > neg[None, :]).mean() + \
            0.5 * (pos[:, None] == neg[None, :]).mean()
        np.testing.assert_allclose(a, exact, atol=5e-3)


class TestDetectionMAP:
    def test_perfect_and_missed_detections(self):
        from paddle_tpu.metric import detection_map, DetectionMAP
        gt_box = [np.array([[0, 0, 10, 10], [20, 20, 30, 30]], 'float32')]
        gt_label = [np.array([0, 1])]
        perfect = [np.array([[0, 0.9, 0, 0, 10, 10],
                             [1, 0.8, 20, 20, 30, 30]], 'float32')]
        assert float(detection_map(perfect, gt_label, gt_box, 2).numpy()) \
            == pytest.approx(1.0)
        missed = [np.array([[0, 0.9, 0, 0, 10, 10],
                            [1, 0.8, 50, 50, 60, 60]], 'float32')]
        m = float(detection_map(missed, gt_label, gt_box, 2).numpy())
        assert m == pytest.approx(0.5)   # class 0 AP=1, class 1 AP=0
        acc = DetectionMAP(class_num=2)
        acc.update(perfect, gt_label, gt_box)
        assert acc.accumulate() == pytest.approx(1.0)

    def test_11point_version(self):
        from paddle_tpu.metric import detection_map
        gt_box = [np.array([[0, 0, 10, 10]], 'float32')]
        gt_label = [np.array([0])]
        det = [np.array([[0, 0.9, 0, 0, 10, 10]], 'float32')]
        v = float(detection_map(det, gt_label, gt_box, 1,
                                ap_version='11point').numpy())
        assert v == pytest.approx(1.0)


def test_composite_metric():
    from paddle_tpu.metric import CompositeMetric, EditDistance
    c = CompositeMetric()
    e1, e2 = EditDistance(), EditDistance()
    c.add_metric(e1)
    c.add_metric(e2)
    c.update(np.array([1.0, 3.0]))
    (a1, _), (a2, _) = c.accumulate()
    assert a1 == a2 == 2.0
    c.reset()
    assert e1.seq_num == 0


def test_fluid_layers_exports():
    from paddle_tpu.fluid import layers as L
    for name in ('linear_chain_crf', 'crf_decoding', 'auc',
                 'edit_distance', 'chunk_eval', 'detection_map'):
        assert callable(getattr(L, name))
