"""Classic paddle.dataset reader-creator compat surface."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import dataset
from paddle_tpu.batch import batch


def _first(reader, n=3):
    out = []
    for s in reader():
        out.append(s)
        if len(out) >= n:
            break
    return out


def test_mnist_range_and_shapes():
    samples = _first(dataset.mnist.train())
    img, lab = samples[0]
    assert img.shape == (784,) and img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert 0 <= lab <= 9
    assert _first(dataset.mnist.test(), 1)


def test_cifar_variants():
    img, lab = _first(dataset.cifar.train10(), 1)[0]
    assert img.shape == (3072,) and 0.0 <= img.max() <= 1.0
    img, lab = _first(dataset.cifar.test100(), 1)[0]
    assert img.shape == (3072,)


def test_uci_housing():
    x, y = _first(dataset.uci_housing.train(), 1)[0]
    assert x.shape == (13,) and y.shape == (1,)
    assert dataset.uci_housing.feature_names[0] == 'CRIM'


def test_imdb_and_sentiment():
    w = dataset.imdb.word_dict()
    assert len(w) > 100
    doc, lab = _first(dataset.imdb.train(w), 1)[0]
    assert isinstance(doc, list) and lab in (0, 1)
    sw = dataset.sentiment.get_word_dict()
    doc, lab = _first(dataset.sentiment.train(), 1)[0]
    assert isinstance(doc, list) and lab in (0, 1)


def test_imikolov_ngrams():
    d = dataset.imikolov.build_dict()
    grams = _first(dataset.imikolov.train(d, 5), 2)
    assert all(len(g) == 5 for g in grams)


def test_translation_readers():
    s, t, nxt = _first(dataset.wmt14.train(1000), 1)[0]
    assert isinstance(s, list) and isinstance(t, list) and len(nxt) == len(t)
    src, trg = dataset.wmt14.get_dict(1000)
    assert len(src) > 0
    s, t, nxt = _first(dataset.wmt16.train(1000, 1000), 1)[0]
    assert isinstance(s, list)
    v = _first(dataset.wmt16.validation(1000, 1000), 1)
    assert v


def test_mq2007_and_conll05_and_vision():
    lab, hi, lo = _first(dataset.mq2007.train('pairwise'), 1)[0]
    assert hi.shape == (46,)
    with pytest.raises(ValueError):
        dataset.mq2007.train('bogus')
    sample = _first(dataset.conll05.test(), 1)[0]
    assert isinstance(sample, tuple)
    img, lab = _first(dataset.flowers.train(), 1)[0]
    assert img.ndim == 3
    img, seg = _first(dataset.voc2012.val(), 1)[0]
    assert img.ndim >= 2


def test_batch_composes_with_readers():
    """The classic fluid loop: paddle.batch over a dataset reader."""
    batches = _first(batch(dataset.uci_housing.train(), 32), 2)
    assert len(batches[0]) == 32
    xs = np.stack([s[0] for s in batches[0]])
    assert xs.shape == (32, 13)


def test_common_split_and_cluster_reader(tmp_path):
    import os
    tmpl = str(tmp_path / 'chunk-%05d.pickle')
    files = dataset.common.split(
        lambda: iter(range(10)), 4, suffix_template=tmpl)
    assert len(files) == 3
    r0 = dataset.common.cluster_files_reader(
        str(tmp_path / 'chunk-*.pickle'), 2, 0)
    r1 = dataset.common.cluster_files_reader(
        str(tmp_path / 'chunk-*.pickle'), 2, 1)
    assert sorted(list(r0()) + list(r1())) == list(range(10))
