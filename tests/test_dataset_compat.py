"""Classic paddle.dataset reader-creator compat surface."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import dataset
from paddle_tpu.batch import batch


def _first(reader, n=3):
    out = []
    for s in reader():
        out.append(s)
        if len(out) >= n:
            break
    return out


def test_mnist_range_and_shapes():
    samples = _first(dataset.mnist.train())
    img, lab = samples[0]
    assert img.shape == (784,) and img.dtype == np.float32
    assert -1.0 <= img.min() and img.max() <= 1.0
    assert 0 <= lab <= 9
    assert _first(dataset.mnist.test(), 1)


def test_cifar_variants():
    img, lab = _first(dataset.cifar.train10(), 1)[0]
    assert img.shape == (3072,) and 0.0 <= img.max() <= 1.0
    img, lab = _first(dataset.cifar.test100(), 1)[0]
    assert img.shape == (3072,)


def test_uci_housing():
    x, y = _first(dataset.uci_housing.train(), 1)[0]
    assert x.shape == (13,) and y.shape == (1,)
    assert dataset.uci_housing.feature_names[0] == 'CRIM'


def test_imdb_and_sentiment():
    w = dataset.imdb.word_dict()
    assert len(w) > 100
    doc, lab = _first(dataset.imdb.train(w), 1)[0]
    assert isinstance(doc, list) and lab in (0, 1)
    sw = dataset.sentiment.get_word_dict()
    doc, lab = _first(dataset.sentiment.train(), 1)[0]
    assert isinstance(doc, list) and lab in (0, 1)


def test_imikolov_ngrams():
    d = dataset.imikolov.build_dict()
    grams = _first(dataset.imikolov.train(d, 5), 2)
    assert all(len(g) == 5 for g in grams)


def test_translation_readers():
    s, t, nxt = _first(dataset.wmt14.train(1000), 1)[0]
    assert isinstance(s, list) and isinstance(t, list) and len(nxt) == len(t)
    src, trg = dataset.wmt14.get_dict(1000)
    assert len(src) > 0
    s, t, nxt = _first(dataset.wmt16.train(1000, 1000), 1)[0]
    assert isinstance(s, list)
    v = _first(dataset.wmt16.validation(1000, 1000), 1)
    assert v


def test_mq2007_and_conll05_and_vision():
    lab, hi, lo = _first(dataset.mq2007.train('pairwise'), 1)[0]
    assert hi.shape == (46,)
    with pytest.raises(ValueError):
        dataset.mq2007.train('bogus')
    sample = _first(dataset.conll05.test(), 1)[0]
    assert isinstance(sample, tuple)
    img, lab = _first(dataset.flowers.train(), 1)[0]
    assert img.ndim == 3
    img, seg = _first(dataset.voc2012.val(), 1)[0]
    assert img.ndim >= 2


def test_batch_composes_with_readers():
    """The classic fluid loop: paddle.batch over a dataset reader."""
    batches = _first(batch(dataset.uci_housing.train(), 32), 2)
    assert len(batches[0]) == 32
    xs = np.stack([s[0] for s in batches[0]])
    assert xs.shape == (32, 13)


def test_common_split_and_cluster_reader(tmp_path):
    import os
    tmpl = str(tmp_path / 'chunk-%05d.pickle')
    files = dataset.common.split(
        lambda: iter(range(10)), 4, suffix_template=tmpl)
    assert len(files) == 3
    r0 = dataset.common.cluster_files_reader(
        str(tmp_path / 'chunk-*.pickle'), 2, 0)
    r1 = dataset.common.cluster_files_reader(
        str(tmp_path / 'chunk-*.pickle'), 2, 1)
    assert sorted(list(r0()) + list(r1())) == list(range(10))


def test_image_utils():
    from paddle_tpu.dataset import image
    im = (np.arange(40 * 60 * 3) % 255).reshape(40, 60, 3).astype('uint8')
    r = image.resize_short(im, 30)
    assert min(r.shape[:2]) == 30 and r.shape[0] == 30
    c = image.center_crop(r, 24)
    assert c.shape[:2] == (24, 24)
    f = image.left_right_flip(c)
    np.testing.assert_array_equal(f[:, 0], c[:, -1])
    chw = image.to_chw(c)
    assert chw.shape == (3, 24, 24)
    out = image.simple_transform(im, 32, 24, is_train=False,
                                 mean=[1.0, 2.0, 3.0])
    assert out.shape == (3, 24, 24) and out.dtype == np.float32
    tr = image.simple_transform(im, 32, 24, is_train=True)
    assert tr.shape == (3, 24, 24)


def test_classic_fluid_layers_roundtrip():
    """The newly completed fluid.layers ops behave sanely end to end."""
    import paddle_tpu as paddle
    from paddle_tpu.fluid import layers as L
    c = L.fill_constant([2, 3], 'float32', 1.5)
    np.testing.assert_allclose(c.numpy(), np.full((2, 3), 1.5, 'float32'))
    u = L.uniform_random([4, 4], min=0.0, max=1.0)
    assert 0.0 <= float(u.numpy().min()) and float(u.numpy().max()) <= 1.0
    s = L.sums([c, c, c])
    np.testing.assert_allclose(s.numpy(), np.full((2, 3), 4.5, 'float32'))
    x = paddle.to_tensor(np.array([[0.5, -1.0]], 'float32'))
    lab = paddle.to_tensor(np.array([[1.0, 0.0]], 'float32'))
    bce = L.sigmoid_cross_entropy_with_logits(x, lab)
    ref = np.maximum(x.numpy(), 0) - x.numpy() * lab.numpy() + \
        np.log1p(np.exp(-np.abs(x.numpy())))
    np.testing.assert_allclose(bce.numpy(), ref, rtol=1e-6)
    h = paddle.to_tensor(np.random.default_rng(0)
                         .standard_normal((2, 5, 8)).astype('float32'))
    ln = L.layer_norm(h, begin_norm_axis=2)
    np.testing.assert_allclose(ln.numpy().mean(-1), 0.0, atol=1e-5)
    out, hh, cc = L.lstm(h, paddle.to_tensor(np.zeros((1, 2, 6), 'float32')),
                         paddle.to_tensor(np.zeros((1, 2, 6), 'float32')),
                         hidden_size=6)
    assert tuple(out.shape) == (2, 5, 6)
    seq, cell_seq = L.dynamic_lstm(h, size=24)
    assert tuple(seq.shape) == (2, 5, 6)
    assert tuple(cell_seq.shape) == (2, 5, 6)   # full per-step cell states


def test_fluid_era_activation_defaults():
    import paddle_tpu as paddle
    from paddle_tpu.fluid import layers as L
    x = paddle.to_tensor(np.array([-1.0, 2.0], 'float32'))
    np.testing.assert_allclose(L.leaky_relu(x).numpy(), [-0.02, 2.0],
                               rtol=1e-6)
    np.testing.assert_allclose(L.leaky_relu(x, alpha=0.1).numpy(),
                               [-0.1, 2.0], rtol=1e-6)
    # fluid hard_sigmoid: clip(slope*x + offset, 0, 1) with slope 0.2
    np.testing.assert_allclose(L.hard_sigmoid(x).numpy(), [0.3, 0.9],
                               rtol=1e-5)


def test_dynamic_lstm_reverse_and_cell_seq():
    import paddle_tpu as paddle
    from paddle_tpu.fluid import layers as L
    rng = np.random.default_rng(0)
    x = rng.standard_normal((2, 6, 4)).astype('float32')
    paddle.seed(3)
    h_f, c_f = L.dynamic_lstm(paddle.to_tensor(x), size=12)
    assert tuple(h_f.shape) == (2, 6, 3) and tuple(c_f.shape) == (2, 6, 3)
    # reverse really processes right-to-left: running it on the flipped
    # input with the same weights must equal the flipped forward output
    paddle.seed(3)
    h_r, c_r = L.dynamic_lstm(paddle.to_tensor(x[:, ::-1].copy()), size=12,
                              is_reverse=True)
    np.testing.assert_allclose(h_r.numpy()[:, ::-1], h_f.numpy(),
                               rtol=1e-5, atol=1e-6)


def test_flowers_cycle_and_mapper():
    from paddle_tpu import dataset
    it = dataset.flowers.train(mapper=lambda s: (s[0] * 0 + 1, s[1]),
                               cycle=True)()
    first = next(it)
    assert float(np.asarray(first[0]).max()) == 1.0   # mapper applied
    # cycle: pull more samples than one epoch holds
    n_epoch = 1024
    for _ in range(n_epoch + 2):
        next(it)   # does not StopIteration
