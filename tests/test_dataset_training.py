"""Fleet-dataset end-to-end: data_generator -> MultiSlot files ->
DatasetFactory -> Executor.train_from_dataset (native parser hot path)."""
import io
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.static as static
import paddle_tpu.distributed as dist


class TestMultiSlotParser:
    def test_native_matches_python(self):
        from paddle_tpu._native import multislot
        lines = ["3 1926 8 17 1 1", "2 5.5 6 1 0", "1 9 1 2"]
        v_n, c_n = multislot.parse_batch(lines, 2)
        v_p, c_p = multislot._parse_py("\n".join(lines), 2)
        np.testing.assert_allclose(v_n, v_p)
        np.testing.assert_array_equal(c_n, c_p)
        np.testing.assert_array_equal(c_n, [[3, 1], [2, 1], [1, 1]])

    def test_malformed_raises(self):
        from paddle_tpu._native import multislot
        with pytest.raises(ValueError):
            multislot.parse_batch(["2 1"], 1)   # promises 2 values, has 1


class TestTrainFromDataset:
    def test_linear_regression_over_multislot_files(self, tmp_path):
        """Generate MultiSlot lines with data_generator, train a linear
        model through train_from_dataset: loss must collapse."""
        from paddle_tpu.incubate.data_generator import MultiSlotDataGenerator

        rs = np.random.RandomState(0)
        w_true = np.array([2.0, -1.0, 0.5], np.float32)
        rows = []
        for _ in range(64):
            x = rs.rand(3).astype(np.float32)
            y = float(x @ w_true)
            rows.append((list(x), [y]))

        class Gen(MultiSlotDataGenerator):
            def generate_sample(self, line):
                def local_iter():
                    for x, y in rows:
                        yield [("x", [float(v) for v in x]), ("y", y)]
                return local_iter

        gen = Gen()
        buf = io.StringIO()
        old = sys.stdout
        sys.stdout = buf
        try:
            gen.run_from_memory()
        finally:
            sys.stdout = old
        data_file = tmp_path / "part-0.txt"
        data_file.write_text(buf.getvalue())

        paddle.enable_static()
        try:
            main = static.Program()
            with static.program_guard(main):
                x = static.data('x', [16, 3], 'float32')
                y = static.data('y', [16, 1], 'float32')
                pred = static.nn.fc(x, 1)
                from paddle_tpu.nn.functional import mse_loss
                loss = mse_loss(pred, y)
                paddle.optimizer.SGD(learning_rate=0.2).minimize(loss)

                ds = dist.DatasetFactory().create_dataset('InMemoryDataset')
                ds.set_batch_size(16)
                ds.set_use_var([x, y])
                ds.set_filelist([str(data_file)])
                ds.load_into_memory()

                exe = static.Executor()
                first = last = None
                for _ in range(30):   # epochs over the 4 batches
                    exe.train_from_dataset(main, ds, fetch_list=[loss],
                                           print_period=0)
                    (lv,) = exe.run(main, feed={
                        'x': np.asarray([r[0] for r in rows[:16]],
                                        np.float32),
                        'y': np.asarray([r[1] for r in rows[:16]],
                                        np.float32)},
                        fetch_list=[loss])
                    first = first if first is not None else float(lv)
                    last = float(lv)
            assert last < first * 0.05, (first, last)
        finally:
            paddle.disable_static()
