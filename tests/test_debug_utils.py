"""Debug/aux subsystems: nan check, determinism, graph export, custom ops."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.utils import debug


def test_check_nan_inf_mode():
    debug.enable_check_nan_inf(True)
    try:
        x = paddle.to_tensor(np.array([1.0, 0.0], 'float32'))
        with pytest.raises(FloatingPointError, match="true_divide"):
            x / 0.0
    finally:
        debug.enable_check_nan_inf(False)
    # off: silent inf
    y = x / 0.0
    assert np.isinf(y.numpy()).any()


def test_check_numerics():
    good = paddle.to_tensor(np.ones(3, 'float32'))
    assert debug.check_numerics(good, "g") is good
    bad = paddle.to_tensor(np.array([1.0, np.nan], 'float32'))
    with pytest.raises(FloatingPointError, match=r"b\[.x.\]"):
        debug.check_numerics({'x': bad}, "b")


def test_divergence_check_detects_unseeded_rng():
    net = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
    x = paddle.to_tensor(np.ones((2, 4), 'float32'))
    net.eval()
    assert debug.divergence_check(lambda: net(x), runs=3)
    net.train()   # fresh rng key each call -> divergence
    with pytest.raises(AssertionError, match="differs"):
        debug.divergence_check(lambda: net(x), runs=4)


def test_deterministic_guard():
    net = nn.Sequential(nn.Linear(4, 4), nn.Dropout(0.5))
    net.train()
    x = paddle.to_tensor(np.ones((2, 4), 'float32'))
    with debug.deterministic_guard(7):
        o1 = net(x).numpy()
    with debug.deterministic_guard(7):
        o2 = net(x).numpy()
    np.testing.assert_array_equal(o1, o2)


def test_draw_tape_and_program():
    lin = nn.Linear(4, 2)
    x = paddle.to_tensor(np.ones((2, 4), 'float32'))
    loss = (lin(x) ** 2).sum()
    dot = debug.draw_tape(loss)
    assert 'digraph tape' in dot and dot.count('->') >= 2

    paddle.enable_static()
    try:
        import paddle_tpu.static as static
        prog = static.Program()
        sp = static.Program()
        with static.program_guard(prog, sp):
            xd = static.data('x', [None, 4], 'float32')
            paddle.static.nn.fc(xd, 8)
        d = debug.draw_program(prog)
        assert 'digraph program' in d and 'fillcolor' in d
    finally:
        paddle.disable_static()


def test_custom_op_registration():
    from paddle_tpu.incubate import custom_op

    def triple(x):
        return 3.0 * x

    op = custom_op.register_op('triple_t', triple)
    t = paddle.to_tensor(np.array([2.0], 'float32'))
    t.stop_gradient = False
    y = op(t)
    y.backward()
    assert float(y.numpy()) == 6.0
    assert float(t.grad.numpy()) == 3.0
    assert 'triple_t' in custom_op.list_ops()
    with pytest.raises(custom_op.CustomOpError):
        custom_op.register_op('triple_t', triple)


def test_custom_op_custom_vjp():
    from paddle_tpu.incubate import register_op

    def sq2(x):
        return 2.0 * x * x

    def fwd(x):
        return sq2(x), (x,)

    def bwd(res, g):
        return (g * 4.0 * res[0],)

    op = register_op('sq2_t', sq2, vjp_fwd=fwd, vjp_bwd=bwd)
    t = paddle.to_tensor(np.array([3.0], 'float32'))
    t.stop_gradient = False
    y = op(t)
    y.backward()
    assert float(y.numpy()) == 18.0
    assert float(t.grad.numpy()) == 12.0
