"""Generation/decoding stack tests: beam search vs numpy reference,
GPT KV-cache generate parity, sampling filters, helper-based decode.

Parity model: /root/reference/python/paddle/fluid/layers/rnn.py decode tests
(test_rnn_decode_api.py style — numpy reference beam search)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
import jax.numpy as jnp


def _np_beam_search(step_logits_fn, init_state, batch, beam, vocab, bos, eos,
                    max_t):
    """Independent numpy beam search (log-softmax scores, finished->eos)."""
    KINF = 1e9
    log_probs = np.tile(np.array([[0.] + [-KINF] * (beam - 1)], np.float32),
                        (batch, 1))
    finished = np.zeros((batch, beam), bool)
    lengths = np.zeros((batch, beam), np.int32)
    state = init_state  # (B, W, ...) numpy
    tokens = np.full((batch, beam), bos, np.int32)
    pred_ids, parent_ids = [], []
    for t in range(max_t):
        logits, state_new = step_logits_fn(tokens, state)  # (B, W, V)
        m = logits.max(-1, keepdims=True)
        lp = logits - m - np.log(np.exp(logits - m).sum(-1, keepdims=True))
        noend = np.full((vocab,), -KINF, np.float32)
        noend[eos] = 0.
        lp = np.where(finished[..., None], noend, lp)
        total = lp + log_probs[..., None]
        flat = total.reshape(batch, beam * vocab)
        topk_idx = np.argsort(-flat, axis=1, kind='stable')[:, :beam]
        topk_scores = np.take_along_axis(flat, topk_idx, axis=1)
        beam_idx = topk_idx // vocab
        token_idx = (topk_idx % vocab).astype(np.int32)
        log_probs = topk_scores
        finished = np.take_along_axis(finished, beam_idx, axis=1)
        lengths = np.take_along_axis(lengths, beam_idx, axis=1)
        lengths = lengths + (~finished).astype(np.int32)
        finished = finished | (token_idx == eos)
        state = np.take_along_axis(
            state_new, beam_idx.reshape(beam_idx.shape + (1,) *
                                        (state_new.ndim - 2)), axis=1)
        pred_ids.append(token_idx)
        parent_ids.append(beam_idx)
        tokens = token_idx
        if finished.all():
            break
    # backtrace (gather_tree)
    T = len(pred_ids)
    out = np.zeros((T, batch, beam), np.int32)
    beams = np.tile(np.arange(beam), (batch, 1))
    for t in range(T - 1, -1, -1):
        out[t] = np.take_along_axis(pred_ids[t], beams, axis=1)
        beams = np.take_along_axis(parent_ids[t], beams, axis=1)
    return out, lengths


class _ToyCell:
    """Deterministic linear 'cell': logits = W[token] + U @ state."""

    def __init__(self, W, U, vocab, hidden):
        self.W, self.U = W, U
        self.vocab, self.hidden = vocab, hidden

    def __call__(self, inputs, states):
        from paddle_tpu.core.tensor import apply_op, Tensor
        W, U = self.W, self.U

        def fn(ids, st):
            logits = W[ids] + st @ U          # (N, V)
            new_state = jnp.tanh(st + 0.1 * logits[:, :st.shape[-1]])
            return logits, new_state
        logits, new_state = apply_op(fn, (inputs, states), n_outputs=2,
                                     differentiable=False)
        return logits, new_state


class TestBeamSearchVsNumpy:
    def test_beam_matches_numpy_reference(self):
        rng = np.random.RandomState(0)
        B, W, V, H, maxT = 2, 3, 11, 5, 12
        bos, eos = 0, 1
        Wm = rng.randn(V, V).astype(np.float32)
        Um = rng.randn(H, V).astype(np.float32)
        cell = _ToyCell(Wm, Um, V, H)
        init_state = rng.randn(B, H).astype(np.float32)

        decoder = nn.BeamSearchDecoder(cell, start_token=bos, end_token=eos,
                                       beam_size=W)
        outputs, _, seq_len = nn.dynamic_decode(
            decoder, inits=paddle.to_tensor(init_state), max_step_num=maxT,
            is_test=True, return_length=True)
        got = outputs.numpy()                      # (B, T, W)

        def np_step(tokens, state):
            # tokens (B, W), state (B, W, H) -> logits (B, W, V)
            ids = tokens.reshape(-1)
            st = state.reshape(-1, H)
            logits = Wm[ids] + st @ Um
            new_state = np.tanh(st + 0.1 * logits[:, :H])
            return (logits.reshape(B, W, V),
                    new_state.reshape(B, W, H))
        ref, ref_len = _np_beam_search(
            np_step, np.tile(init_state[:, None], (1, W, 1)), B, W, V, bos,
            eos, maxT)
        ref = ref.transpose(1, 0, 2)               # (B, T, W)
        T = min(got.shape[1], ref.shape[1])
        np.testing.assert_array_equal(got[:, :T, :], ref[:, :T, :])
        np.testing.assert_array_equal(seq_len.numpy(), ref_len)

    def test_beam_early_exit_all_finished(self):
        # vocab where eos always wins -> finishes on step 1, loop exits early
        V, H, B, W = 4, 3, 1, 2
        Wm = np.zeros((V, V), np.float32)
        Wm[:, 1] = 10.0                            # eos=1 dominates
        Um = np.zeros((H, V), np.float32)
        cell = _ToyCell(Wm, Um, V, H)
        decoder = nn.BeamSearchDecoder(cell, start_token=0, end_token=1,
                                       beam_size=W)
        outputs, states = nn.dynamic_decode(
            decoder, inits=paddle.to_tensor(np.zeros((B, H), np.float32)),
            max_step_num=50, is_test=True)
        assert bool(states['finished'].numpy().all())
        assert outputs.numpy()[0, 0, 0] == 1
        # unwritten tail slots must be padded with eos, not raw zeros
        assert (outputs.numpy()[0, 1:, 0] == 1).all()

    def test_early_exit_preserves_diverged_beams(self):
        # regression: beams diverge at step 0 (tokens 2 vs 3), both hit eos
        # at step 1; early exit must not collapse beam 1 onto beam 0
        V, H, B, W = 5, 3, 1, 2
        bos, eos = 0, 1
        Wm = np.full((V, V), -10.0, np.float32)
        Wm[bos, 2] = 5.0        # from bos: best tokens are 2 then 3
        Wm[bos, 3] = 4.0
        Wm[2, eos] = 8.0        # from 2 or 3: eos dominates
        Wm[3, eos] = 8.0
        Um = np.zeros((H, V), np.float32)
        cell = _ToyCell(Wm, Um, V, H)
        decoder = nn.BeamSearchDecoder(cell, start_token=bos, end_token=eos,
                                       beam_size=W)
        outputs, _ = nn.dynamic_decode(
            decoder, inits=paddle.to_tensor(np.zeros((B, H), np.float32)),
            max_step_num=10, is_test=True)
        ids = outputs.numpy()          # (B, T, W)
        np.testing.assert_array_equal(ids[0, :2, 0], [2, eos])
        np.testing.assert_array_equal(ids[0, :2, 1], [3, eos])


class TestGPTGenerate:
    @pytest.fixture(scope='class')
    def model(self):
        from paddle_tpu.text.gpt import GPTModel, GPTConfig
        paddle.seed(7)
        m = GPTModel(GPTConfig(vocab_size=37, hidden_size=32, num_layers=2,
                               num_heads=4, max_seq_len=64, dropout=0.0))
        m.eval()
        return m

    def test_kv_cache_greedy_matches_full_forward(self, model):
        ids = paddle.to_tensor(np.array([[1, 2, 3], [9, 8, 7]], np.int32))
        out = model.generate(ids, max_new_tokens=6)
        cur = ids.numpy()
        for _ in range(6):
            logits = model(paddle.to_tensor(cur)).numpy()
            nxt = logits[:, -1, :].argmax(-1).astype(np.int32)
            cur = np.concatenate([cur, nxt[:, None]], axis=1)
        np.testing.assert_array_equal(out.numpy(), cur)

    def test_generate_step_is_jit_compiled(self, model):
        ids = paddle.to_tensor(np.array([[4, 5]], np.int32))
        model.generate(ids, max_new_tokens=3)
        fn = model._gen_cache[(2, 3, False, 1.0, None, None, -1)]
        assert hasattr(fn, 'lower')  # a jax.jit-wrapped callable
        # second call reuses the compiled fn (no retrace) and is deterministic
        a = model.generate(ids, max_new_tokens=3).numpy()
        b = model.generate(ids, max_new_tokens=3).numpy()
        np.testing.assert_array_equal(a, b)

    def test_eos_early_stop(self, model):
        ids = paddle.to_tensor(np.array([[1, 2]], np.int32))
        base = model.generate(ids, max_new_tokens=8).numpy()
        eos = int(base[0, 2])      # force first generated token to be "eos"
        out = model.generate(ids, max_new_tokens=8, eos_token_id=eos).numpy()
        assert (out[0, 2:] == eos).all()

    def test_manual_incremental_decode_with_init_caches(self, model):
        """Public manual-decode path: init_caches + forward(ids, caches, pos)
        must reproduce the full non-cached forward logits step by step."""
        ids = np.array([[2, 4, 6, 8]], np.int32)
        T = ids.shape[1]
        caches = model.init_caches(batch_size=1, max_len=T)
        full = model(paddle.to_tensor(ids)).numpy()
        # prefill first 2 tokens, then decode the rest one at a time
        logits, caches = model(paddle.to_tensor(ids[:, :2]), caches,
                               paddle.to_tensor(np.int32(0)))
        np.testing.assert_allclose(logits.numpy(), full[:, :2], rtol=2e-4,
                                   atol=2e-5)
        for t in range(2, T):
            logits, caches = model(paddle.to_tensor(ids[:, t:t + 1]), caches,
                                   paddle.to_tensor(np.int32(t)))
            np.testing.assert_allclose(logits.numpy()[:, 0], full[:, t],
                                       rtol=2e-4, atol=2e-5)

    def test_sampling_deterministic_under_seed(self, model):
        ids = paddle.to_tensor(np.array([[3, 1, 4]], np.int32))
        a = model.generate(ids, max_new_tokens=5, do_sample=True, top_k=8,
                           seed=13).numpy()
        b = model.generate(ids, max_new_tokens=5, do_sample=True, top_k=8,
                           seed=13).numpy()
        np.testing.assert_array_equal(a, b)


class TestSamplingFilters:
    def test_top_k_filter(self):
        from paddle_tpu.text.generation import top_k_logits
        logits = jnp.array([[1., 5., 3., 2.]])
        out = np.asarray(top_k_logits(logits, 2))
        assert out[0, 1] == 5. and out[0, 2] == 3.
        assert out[0, 0] < -1e8 and out[0, 3] < -1e8

    def test_top_p_filter_keeps_minimal_nucleus(self):
        from paddle_tpu.text.generation import top_p_logits
        # probs ~ [0.6, 0.3, 0.08, 0.02]
        p = np.array([0.6, 0.3, 0.08, 0.02])
        logits = jnp.asarray(np.log(p)[None, :])
        out = np.asarray(top_p_logits(logits, 0.85))
        assert np.isfinite(out[0, 0]) and out[0, 0] > -1e8
        assert out[0, 1] > -1e8
        assert out[0, 2] < -1e8 and out[0, 3] < -1e8

    def test_top_p_always_keeps_one(self):
        from paddle_tpu.text.generation import top_p_logits
        logits = jnp.asarray(np.log([[0.9, 0.05, 0.05]]))
        out = np.asarray(top_p_logits(logits, 0.01))
        assert out[0, 0] > -1e8
        assert out[0, 1] < -1e8 and out[0, 2] < -1e8


class TestHelperDecode:
    def test_greedy_embedding_helper_decode(self):
        paddle.seed(3)
        V, E, H, B = 13, 8, 8, 2
        emb = nn.Embedding(V, E)
        cell = nn.GRUCell(E, H)
        proj = nn.Linear(H, V)
        helper = nn.GreedyEmbeddingHelper(lambda ids: emb(ids),
                                          start_tokens=np.zeros(B, np.int32),
                                          end_token=1)
        decoder = nn.BasicDecoder(cell, helper, output_fn=proj)
        h0 = paddle.to_tensor(np.zeros((B, H), np.float32))
        outputs, _, lengths = nn.dynamic_decode(
            decoder, inits=h0, max_step_num=7, is_test=True,
            return_length=True)
        ids = outputs['sample_ids'].numpy()
        assert ids.shape == (B, 7)
        # greedy must equal argmax of the recorded cell logits
        np.testing.assert_array_equal(
            ids, outputs['cell_outputs'].numpy().argmax(-1))

    def test_training_helper_teacher_forcing(self):
        paddle.seed(5)
        B, T, E, H, V = 2, 5, 4, 6, 9
        inputs = np.random.RandomState(0).randn(B, T, E).astype(np.float32)
        seq_len = np.array([5, 3], np.int64)
        cell = nn.GRUCell(E, H)
        proj = nn.Linear(H, V)
        helper = nn.TrainingHelper(paddle.to_tensor(inputs),
                                   paddle.to_tensor(seq_len))
        decoder = nn.BasicDecoder(cell, helper, output_fn=proj)
        h0 = paddle.to_tensor(np.zeros((B, H), np.float32))
        outputs, _, lengths = nn.dynamic_decode(
            decoder, inits=h0, max_step_num=T, return_length=True)
        assert outputs['cell_outputs'].shape == [B, T, V]
        np.testing.assert_array_equal(lengths.numpy(), [5, 3])


class TestSeq2SeqTranslate:
    def test_translate_shapes_and_beam_order(self):
        from paddle_tpu.text.seq2seq import Seq2SeqTransformer
        paddle.seed(11)
        m = Seq2SeqTransformer(src_vocab_size=17, trg_vocab_size=19,
                               d_model=16, nhead=2, num_encoder_layers=1,
                               num_decoder_layers=1, dim_feedforward=32,
                               dropout=0.0, max_length=32)
        src = paddle.to_tensor(np.array([[3, 4, 5, 6]], np.int32))
        out = m.translate(src, bos_id=0, eos_id=1, beam_size=3, max_len=8)
        ids = out.numpy()
        assert ids.shape[0] == 1 and ids.shape[2] == 3
        assert ids.dtype == np.int32
        # deterministic across calls
        ids2 = m.translate(src, bos_id=0, eos_id=1, beam_size=3,
                           max_len=8).numpy()
        np.testing.assert_array_equal(ids, ids2)


class TestBeamSearchOps:
    def test_beam_search_step_op(self):
        from paddle_tpu.fluid import layers
        B, W, V = 1, 2, 5
        pre_ids = paddle.to_tensor(np.array([[2, 3]], np.int32))
        pre_scores = paddle.to_tensor(np.array([[-0.5, -1.0]], np.float32))
        scores = np.full((B, W, V), -5.0, np.float32)
        scores[0, 0, 4] = -0.1      # best: beam 0 -> token 4
        scores[0, 1, 2] = -0.2      # second: beam 1 -> token 2
        tok, sc, parent = layers.beam_search(
            pre_ids, pre_scores, None, paddle.to_tensor(scores),
            beam_size=W, end_id=0, return_parent_idx=True)
        np.testing.assert_array_equal(tok.numpy(), [[4, 2]])
        np.testing.assert_array_equal(parent.numpy(), [[0, 1]])
        np.testing.assert_allclose(sc.numpy(), [[-0.1, -0.2]], rtol=1e-6)

    def test_beam_search_finished_propagates_end_id(self):
        from paddle_tpu.fluid import layers
        B, W, V = 1, 2, 4
        end = 1
        pre_ids = paddle.to_tensor(np.array([[end, 2]], np.int32))  # beam0 done
        pre_scores = paddle.to_tensor(np.array([[-0.1, -9.0]], np.float32))
        scores = np.full((B, W, V), -20.0, np.float32)
        scores[0, 1, 3] = -10.0
        tok, sc = layers.beam_search(pre_ids, pre_scores, None,
                                     paddle.to_tensor(scores),
                                     beam_size=W, end_id=end)
        # finished beam keeps emitting end_id with its frozen score on top
        assert tok.numpy()[0, 0] == end
        np.testing.assert_allclose(sc.numpy()[0, 0], -0.1, rtol=1e-6)

    def test_beam_search_decode_backtrace(self):
        from paddle_tpu.fluid import layers
        token_ids = np.array([[[5, 6]], [[7, 8]]], np.int32)   # (T=2, B=1, W=2)
        parent_ids = np.array([[[0, 0]], [[1, 0]]], np.int32)
        seqs, _ = layers.beam_search_decode(
            (paddle.to_tensor(token_ids), paddle.to_tensor(parent_ids)),
            paddle.to_tensor(np.zeros((2, 1, 2), np.float32)),
            beam_size=2, end_id=0)
        # beam 0 at t=1 has parent 1 -> sequence [6, 7]
        np.testing.assert_array_equal(seqs.numpy()[:, 0, 0], [6, 7])
