"""End-to-end convergence of the detection training losses: a tiny SSD
head and a tiny YOLOv3 head both fit a fixed batch."""
import numpy as np

import paddle_tpu as paddle


class TestSSDTrains:
    def test_ssd_loss_decreases(self):
        import paddle_tpu.fluid.layers as L
        rs = np.random.RandomState(0)
        B, P, C, G = 2, 12, 4, 2
        prior = np.sort(rs.rand(P, 4).astype(np.float32), axis=1)
        gt_box = np.tile(prior[None, :G] * 0.9 + 0.05, (B, 1, 1)) \
            .astype(np.float32)
        gt_label = rs.randint(1, C, (B, G)).astype(np.int64)

        feat = paddle.to_tensor(rs.randn(B, 16).astype(np.float32))
        loc_head = paddle.nn.Linear(16, P * 4)
        conf_head = paddle.nn.Linear(16, P * C)
        opt = paddle.optimizer.Adam(
            learning_rate=0.01,
            parameters=loc_head.parameters() + conf_head.parameters())
        losses = []
        for _ in range(25):
            loc = loc_head(feat).reshape([B, P, 4])
            conf = conf_head(feat).reshape([B, P, C])
            loss = L.ssd_loss(loc, conf, paddle.to_tensor(gt_box),
                              paddle.to_tensor(gt_label),
                              paddle.to_tensor(prior)).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


class TestYolov3Trains:
    def test_yolo_loss_decreases(self):
        import paddle_tpu.fluid.layers as L
        rs = np.random.RandomState(0)
        B, H, W, K = 1, 4, 4, 3
        anchors = [10, 13, 16, 30]
        mask = [0, 1]
        C = len(mask) * (5 + K)
        gt_box = np.array([[[0.5, 0.5, 0.3, 0.3],
                            [0.2, 0.8, 0.2, 0.15]]], np.float32)
        gt_label = np.array([[1, 2]], np.int32)

        feat = paddle.to_tensor(rs.randn(B, 8).astype(np.float32))
        head = paddle.nn.Linear(8, C * H * W)
        opt = paddle.optimizer.Adam(learning_rate=0.02,
                                    parameters=head.parameters())
        losses = []
        for _ in range(30):
            x = head(feat).reshape([B, C, H, W])
            loss = L.yolov3_loss(x, paddle.to_tensor(gt_box),
                                 paddle.to_tensor(gt_label), anchors, mask,
                                 K, ignore_thresh=0.5,
                                 downsample_ratio=8).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


class TestRetinanetFocalTrains:
    def test_focal_loss_decreases(self):
        import paddle_tpu.fluid.layers as L
        rs = np.random.RandomState(0)
        A, C = 16, 3
        anchors = np.sort(rs.rand(A, 4) * 10, axis=1).astype(np.float32)
        gt = anchors[:2].copy()
        glab = np.array([[1], [2]], np.int32)
        feat = paddle.to_tensor(rs.randn(A, 8).astype(np.float32))
        head = paddle.nn.Linear(8, C)
        opt = paddle.optimizer.Adam(learning_rate=0.05,
                                    parameters=head.parameters())
        # fixed assignment (targets don't depend on the head)
        _, _, st, _, _, fg_num = L.retinanet_target_assign(
            paddle.to_tensor(np.zeros((A, 4), np.float32)),
            paddle.to_tensor(np.zeros((A, C), np.float32)),
            paddle.to_tensor(anchors),
            paddle.to_tensor(np.ones((A, 4), np.float32)),
            paddle.to_tensor(gt), paddle.to_tensor(glab))
        losses = []
        for _ in range(25):
            logits = head(feat)
            loss = L.sigmoid_focal_loss(logits, st, fg_num).sum()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])
