"""Numpy-reference tests for the detection-op library (vision/ops.py).

Test style parity: /root/reference/python/paddle/fluid/tests/unittests/
test_multiclass_nms_op.py, test_box_coder_op.py, test_yolo_box_op.py,
test_roi_align_op.py — each op checked against an independent numpy
implementation."""
import math

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops


def _np_iou(a, b, normalized=True):
    off = 0.0 if normalized else 1.0
    area_a = (a[:, 2] - a[:, 0] + off) * (a[:, 3] - a[:, 1] + off)
    area_b = (b[:, 2] - b[:, 0] + off) * (b[:, 3] - b[:, 1] + off)
    ix1 = np.maximum(a[:, None, 0], b[None, :, 0])
    iy1 = np.maximum(a[:, None, 1], b[None, :, 1])
    ix2 = np.minimum(a[:, None, 2], b[None, :, 2])
    iy2 = np.minimum(a[:, None, 3], b[None, :, 3])
    iw = np.maximum(ix2 - ix1 + off, 0)
    ih = np.maximum(iy2 - iy1 + off, 0)
    inter = iw * ih
    union = area_a[:, None] + area_b[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-10), 0.0)


def _np_greedy_nms(boxes, scores, iou_thr, score_thr=-np.inf):
    """Plain python greedy NMS returning kept original indices in order."""
    idx = [i for i in np.argsort(-scores, kind='stable')
           if scores[i] > score_thr]
    keep = []
    while idx:
        i = idx.pop(0)
        keep.append(i)
        idx = [j for j in idx
               if _np_iou(boxes[i:i + 1], boxes[j:j + 1])[0, 0] <= iou_thr]
    return keep


class TestIoUSimilarity:
    def test_matches_numpy(self):
        rng = np.random.RandomState(0)
        a = np.sort(rng.rand(5, 2, 2), axis=1).transpose(0, 2, 1).reshape(5, 4)
        b = np.sort(rng.rand(7, 2, 2), axis=1).transpose(0, 2, 1).reshape(7, 4)
        a, b = a.astype(np.float32), b.astype(np.float32)
        got = ops.iou_similarity(paddle.to_tensor(a),
                                 paddle.to_tensor(b)).numpy()
        np.testing.assert_allclose(got, _np_iou(a, b), rtol=1e-5, atol=1e-6)

    def test_unnormalized_offset(self):
        a = np.array([[0., 0., 9., 9.]], np.float32)   # 10x10 px box
        got = ops.iou_similarity(paddle.to_tensor(a), paddle.to_tensor(a),
                                 box_normalized=False).numpy()
        np.testing.assert_allclose(got, [[1.0]], atol=1e-6)

    def test_disjoint_boxes_zero(self):
        a = np.array([[0., 0., 1., 1.]], np.float32)
        b = np.array([[5., 5., 6., 6.]], np.float32)
        got = ops.iou_similarity(paddle.to_tensor(a),
                                 paddle.to_tensor(b)).numpy()
        assert got[0, 0] == 0.0


class TestBoxCoder:
    def _np_encode(self, prior, target, var=None):
        pw = prior[:, 2] - prior[:, 0]
        ph = prior[:, 3] - prior[:, 1]
        pcx = prior[:, 0] + 0.5 * pw
        pcy = prior[:, 1] + 0.5 * ph
        tw = target[:, 2] - target[:, 0]
        th = target[:, 3] - target[:, 1]
        tcx = target[:, 0] + 0.5 * tw
        tcy = target[:, 1] + 0.5 * th
        out = np.stack([
            (tcx[:, None] - pcx[None, :]) / pw[None, :],
            (tcy[:, None] - pcy[None, :]) / ph[None, :],
            np.log(tw[:, None] / pw[None, :]),
            np.log(th[:, None] / ph[None, :])], axis=-1)
        if var is not None:
            out = out / var.reshape(1, -1, 4)
        return out

    def test_encode_matches_numpy(self):
        rng = np.random.RandomState(1)
        prior = np.abs(rng.rand(4, 4).astype(np.float32)) + \
            np.array([0, 0, 1, 1], np.float32)
        target = np.abs(rng.rand(3, 4).astype(np.float32)) + \
            np.array([0, 0, 1, 1], np.float32)
        got = ops.box_coder(paddle.to_tensor(prior), None,
                            paddle.to_tensor(target)).numpy()
        np.testing.assert_allclose(got, self._np_encode(prior, target),
                                   rtol=1e-5, atol=1e-6)

    def test_encode_decode_roundtrip(self):
        rng = np.random.RandomState(2)
        prior = rng.rand(5, 4).astype(np.float32)
        prior[:, 2:] = prior[:, :2] + 0.5 + rng.rand(5, 2).astype(np.float32)
        var = np.array([0.1, 0.1, 0.2, 0.2], np.float32)
        prior_var = np.tile(var, (5, 1))
        target = rng.rand(3, 4).astype(np.float32)
        target[:, 2:] = target[:, :2] + 0.5 + rng.rand(3, 2).astype(np.float32)

        enc = ops.box_coder(paddle.to_tensor(prior),
                            paddle.to_tensor(prior_var),
                            paddle.to_tensor(target),
                            code_type='encode_center_size')
        dec = ops.box_coder(paddle.to_tensor(prior),
                            paddle.to_tensor(prior_var), enc,
                            code_type='decode_center_size', axis=0).numpy()
        # decode(encode(t)) must reproduce the target boxes for every prior
        want = np.broadcast_to(target[:, None, :], dec.shape)
        np.testing.assert_allclose(dec, want, rtol=1e-4, atol=1e-5)

    def test_decode_var_as_list(self):
        prior = np.array([[0., 0., 2., 2.]], np.float32)
        offsets = np.zeros((1, 1, 4), np.float32)
        dec = ops.box_coder(paddle.to_tensor(prior), [0.1, 0.1, 0.2, 0.2],
                            paddle.to_tensor(offsets),
                            code_type='decode_center_size').numpy()
        # zero offsets decode to the prior itself
        np.testing.assert_allclose(dec[0, 0], prior[0], atol=1e-6)


class TestBoxClip:
    def test_clip_to_image(self):
        boxes = np.array([[[-5., -5., 30., 40.], [2., 3., 8., 9.]]],
                         np.float32)
        im_info = np.array([[20., 25., 1.]], np.float32)   # h=20 w=25
        got = ops.box_clip(paddle.to_tensor(boxes),
                           paddle.to_tensor(im_info)).numpy()
        np.testing.assert_allclose(
            got[0, 0], [0., 0., 24., 19.], atol=1e-6)
        np.testing.assert_allclose(got[0, 1], [2., 3., 8., 9.], atol=1e-6)


class TestPriorBox:
    def test_centers_and_sizes(self):
        feat = paddle.to_tensor(np.zeros((1, 8, 2, 2), np.float32))
        img = paddle.to_tensor(np.zeros((1, 3, 64, 64), np.float32))
        boxes, vars_ = ops.prior_box(feat, img, min_sizes=[16.],
                                     aspect_ratios=[1.0])
        b = boxes.numpy()
        assert b.shape == (2, 2, 1, 4)
        # step = 64/2 = 32; first center at (0.5*32, 0.5*32) = (16, 16)
        np.testing.assert_allclose(
            b[0, 0, 0], [(16 - 8) / 64, (16 - 8) / 64,
                         (16 + 8) / 64, (16 + 8) / 64], atol=1e-6)
        np.testing.assert_allclose(vars_.numpy()[0, 0, 0],
                                   [0.1, 0.1, 0.2, 0.2], atol=1e-6)

    def test_flip_and_max_size_prior_count(self):
        feat = paddle.to_tensor(np.zeros((1, 8, 3, 3), np.float32))
        img = paddle.to_tensor(np.zeros((1, 3, 96, 96), np.float32))
        boxes, _ = ops.prior_box(feat, img, min_sizes=[32.], max_sizes=[64.],
                                 aspect_ratios=[2.0], flip=True)
        # ars = {1, 2, 1/2} -> 3 + 1 (sqrt(min*max)) = 4 priors
        assert boxes.shape == [3, 3, 4, 4]
        ar2 = boxes.numpy()[0, 0, 1]               # second prior: ar=2
        # w/h must equal the aspect ratio 2.0
        np.testing.assert_allclose(
            (ar2[2] - ar2[0]) / (ar2[3] - ar2[1]), 2.0, rtol=1e-5)


class TestDensityPriorBox:
    def test_prior_count_and_size(self):
        feat = paddle.to_tensor(np.zeros((1, 8, 2, 2), np.float32))
        img = paddle.to_tensor(np.zeros((1, 3, 32, 32), np.float32))
        boxes, _ = ops.density_prior_box(
            feat, img, densities=[2], fixed_sizes=[8.], fixed_ratios=[1.0])
        b = boxes.numpy()
        assert b.shape == (2, 2, 4, 4)            # density^2 = 4 priors
        w = (b[0, 0, 0, 2] - b[0, 0, 0, 0]) * 32
        np.testing.assert_allclose(w, 8.0, rtol=1e-5)

    def test_flatten_to_2d(self):
        feat = paddle.to_tensor(np.zeros((1, 8, 2, 2), np.float32))
        img = paddle.to_tensor(np.zeros((1, 3, 32, 32), np.float32))
        boxes, vars_ = ops.density_prior_box(
            feat, img, densities=[1], fixed_sizes=[4.], fixed_ratios=[1.0],
            flatten_to_2d=True)
        assert boxes.shape == [4, 4] and vars_.shape == [4, 4]


class TestAnchorGenerator:
    def test_matches_reference_recipe(self):
        feat = paddle.to_tensor(np.zeros((1, 8, 2, 3), np.float32))
        anchors, vars_ = ops.anchor_generator(
            feat, anchor_sizes=[64.], aspect_ratios=[1.0],
            stride=[16., 16.], offset=0.5)
        a = anchors.numpy()
        assert a.shape == (2, 3, 1, 4)
        # reference recipe: base cell 16x16 snapped to ar=1 -> 16x16,
        # scaled by 64/16 -> 64x64, centered at (x*16 + 0.5*15)
        cx = 0 * 16 + 0.5 * 15
        np.testing.assert_allclose(
            a[0, 0, 0], [cx - 0.5 * 63, cx - 0.5 * 63,
                         cx + 0.5 * 63, cx + 0.5 * 63], atol=1e-4)


class TestYoloBox:
    def test_decode_matches_numpy(self):
        rng = np.random.RandomState(3)
        B, A, C, H, W = 1, 2, 3, 2, 2
        anchors = [10, 14, 23, 27]
        x = rng.randn(B, A * (5 + C), H, W).astype(np.float32)
        img_size = np.array([[64, 64]], np.int32)
        ds = 32
        boxes, scores = ops.yolo_box(
            paddle.to_tensor(x), paddle.to_tensor(img_size), anchors, C,
            conf_thresh=0.0, downsample_ratio=ds, clip_bbox=False)

        def sig(v):
            return 1 / (1 + np.exp(-v))
        xv = x.reshape(B, A, 5 + C, H, W)
        want_boxes = np.zeros((B, H, W, A, 4), np.float32)
        want_scores = np.zeros((B, H, W, A, C), np.float32)
        for b in range(B):
            for a in range(A):
                for i in range(H):
                    for j in range(W):
                        bx = (sig(xv[b, a, 0, i, j]) + j) / W
                        by = (sig(xv[b, a, 1, i, j]) + i) / H
                        bw = np.exp(xv[b, a, 2, i, j]) * anchors[2 * a] / (W * ds)
                        bh = np.exp(xv[b, a, 3, i, j]) * anchors[2 * a + 1] / (H * ds)
                        conf = sig(xv[b, a, 4, i, j])
                        want_boxes[b, i, j, a] = [
                            (bx - bw / 2) * 64, (by - bh / 2) * 64,
                            (bx + bw / 2) * 64, (by + bh / 2) * 64]
                        want_scores[b, i, j, a] = sig(xv[b, a, 5:, i, j]) * conf
        np.testing.assert_allclose(
            boxes.numpy(), want_boxes.reshape(B, -1, 4), rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(
            scores.numpy(), want_scores.reshape(B, -1, C), rtol=1e-4,
            atol=1e-5)

    def test_conf_thresh_zeroes_boxes(self):
        x = np.zeros((1, 1 * 6, 1, 1), np.float32)
        x[0, 4] = -10.0   # conf = sigmoid(-10) ~ 0
        boxes, scores = ops.yolo_box(
            paddle.to_tensor(x), paddle.to_tensor(np.array([[32, 32]], np.int32)),
            [10, 10], 1, conf_thresh=0.5, downsample_ratio=32)
        assert (boxes.numpy() == 0).all() and (scores.numpy() == 0).all()


class TestNMS:
    def test_matches_python_greedy(self):
        rng = np.random.RandomState(4)
        boxes = rng.rand(12, 4).astype(np.float32)
        boxes[:, 2:] = boxes[:, :2] + 0.3 + 0.4 * rng.rand(12, 2).astype(np.float32)
        scores = rng.rand(12).astype(np.float32)
        idx, mask = ops.nms(paddle.to_tensor(boxes), paddle.to_tensor(scores),
                            iou_threshold=0.4, top_k=12)
        got = idx.numpy()[mask.numpy()]
        want = _np_greedy_nms(boxes, scores, 0.4)
        np.testing.assert_array_equal(sorted(got.tolist()), sorted(want))
        # kept candidates are in descending score order in the padded output
        kept_scores = scores[got]
        assert (np.diff(kept_scores) <= 1e-7).all()

    def test_identical_boxes_keep_one(self):
        boxes = np.tile(np.array([[0., 0., 1., 1.]], np.float32), (5, 1))
        scores = np.array([0.9, 0.8, 0.7, 0.6, 0.5], np.float32)
        idx, mask = ops.nms(paddle.to_tensor(boxes), paddle.to_tensor(scores),
                            iou_threshold=0.5, top_k=5)
        kept = idx.numpy()[mask.numpy()]
        np.testing.assert_array_equal(kept, [0])

    def test_score_threshold_filters(self):
        boxes = np.array([[0., 0., 1., 1.], [5., 5., 6., 6.]], np.float32)
        scores = np.array([0.9, 0.05], np.float32)
        idx, mask = ops.nms(paddle.to_tensor(boxes), paddle.to_tensor(scores),
                            iou_threshold=0.5, top_k=2, score_threshold=0.1)
        kept = idx.numpy()[mask.numpy()]
        np.testing.assert_array_equal(kept, [0])

    def test_iou_exactly_at_threshold_survives(self):
        # IoU == threshold must NOT suppress (reference uses strict >)
        boxes = np.array([[0., 0., 1., 2.], [0., 1., 1., 3.]], np.float32)
        # IoU = 1/3 ≈ 0.3333; threshold exactly 1/3
        scores = np.array([0.9, 0.8], np.float32)
        thr = 1.0 / 3.0
        idx, mask = ops.nms(paddle.to_tensor(boxes), paddle.to_tensor(scores),
                            iou_threshold=thr, top_k=2)
        assert mask.numpy().sum() == 2


class TestMulticlassNMS:
    def _np_multiclass(self, boxes, scores, score_thr, nms_thr, keep_top_k,
                       background=0):
        C = scores.shape[0]
        entries = []
        for c in range(C):
            if c == background:
                continue
            keep = _np_greedy_nms(boxes, scores[c], nms_thr, score_thr)
            for i in keep:
                entries.append([c, scores[c][i], *boxes[i]])
        entries.sort(key=lambda e: -e[1])
        return np.asarray(entries[:keep_top_k], np.float32)

    def test_matches_python_reference(self):
        rng = np.random.RandomState(5)
        M, C = 10, 3
        boxes = rng.rand(1, M, 4).astype(np.float32)
        boxes[..., 2:] = boxes[..., :2] + 0.4
        scores = rng.rand(1, C, M).astype(np.float32)
        out, counts = ops.multiclass_nms(
            paddle.to_tensor(boxes), paddle.to_tensor(scores),
            score_threshold=0.3, nms_top_k=M, keep_top_k=20,
            nms_threshold=0.4, background_label=0)
        n = int(counts.numpy()[0])
        got = out.numpy()[0, :n]
        want = self._np_multiclass(boxes[0], scores[0], 0.3, 0.4, 20)
        assert n == len(want)
        np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)

    def test_padding_rows_are_minus_one(self):
        boxes = np.array([[[0., 0., 1., 1.]]], np.float32)
        scores = np.array([[[0.0], [0.9]]], np.float32)   # bg + 1 class
        out, counts = ops.multiclass_nms(
            paddle.to_tensor(boxes), paddle.to_tensor(scores),
            score_threshold=0.5, keep_top_k=4, background_label=0)
        o = out.numpy()[0]
        assert counts.numpy()[0] == 1
        assert (o[1:] == -1.0).all()
        np.testing.assert_allclose(o[0], [1., 0.9, 0., 0., 1., 1.],
                                   atol=1e-6)


class TestRoiAlign:
    def _np_roi_align(self, img, roi, ph, pw, scale, sr):
        """Python bilinear reference for a single image/roi."""
        C, H, W = img.shape
        x1, y1, x2, y2 = roi * scale
        rw = max(x2 - x1, 1.0)
        rh = max(y2 - y1, 1.0)
        out = np.zeros((C, ph, pw), np.float32)
        for pi in range(ph):
            for pj in range(pw):
                acc = np.zeros(C, np.float32)
                for si in range(sr):
                    for sj in range(sr):
                        yy = y1 + (pi * sr + si + 0.5) * rh / (ph * sr)
                        xx = x1 + (pj * sr + sj + 0.5) * rw / (pw * sr)
                        yy = min(max(yy, 0.0), H - 1.0)
                        xx = min(max(xx, 0.0), W - 1.0)
                        y0, x0 = int(np.floor(yy)), int(np.floor(xx))
                        y1i, x1i = min(y0 + 1, H - 1), min(x0 + 1, W - 1)
                        wy, wx = yy - y0, xx - x0
                        acc += (img[:, y0, x0] * (1 - wy) * (1 - wx)
                                + img[:, y0, x1i] * (1 - wy) * wx
                                + img[:, y1i, x0] * wy * (1 - wx)
                                + img[:, y1i, x1i] * wy * wx)
                out[:, pi, pj] = acc / (sr * sr)
        return out

    def test_matches_python_bilinear(self):
        rng = np.random.RandomState(6)
        x = rng.randn(2, 3, 8, 8).astype(np.float32)
        rois = np.array([[1., 1., 5., 5.], [0., 2., 6., 7.],
                         [2., 0., 7., 4.]], np.float32)
        rois_num = [2, 1]
        got = ops.roi_align(paddle.to_tensor(x), paddle.to_tensor(rois),
                            pooled_height=2, pooled_width=2,
                            spatial_scale=0.5, rois_num=rois_num).numpy()
        batch_of = [0, 0, 1]
        for r in range(3):
            want = self._np_roi_align(x[batch_of[r]], rois[r], 2, 2, 0.5, 2)
            np.testing.assert_allclose(got[r], want, rtol=1e-4, atol=1e-5)

    def test_jit_safe_with_traced_rois_num(self):
        """rois_num as a Tensor must not host-sync at trace time."""
        import jax
        import jax.numpy as jnp
        x = np.random.RandomState(7).randn(2, 2, 6, 6).astype(np.float32)
        rois = np.array([[0., 0., 4., 4.], [1., 1., 5., 5.]], np.float32)

        from paddle_tpu.core.tensor import Tensor

        def f(xv, rv, rn):
            out = ops.roi_align(Tensor(jnp.asarray(xv)), Tensor(jnp.asarray(rv)),
                                pooled_height=2, pooled_width=2,
                                rois_num=Tensor(jnp.asarray(rn)))
            return out._value

        eager = f(x, rois, np.array([1, 1], np.int32))
        jitted = jax.jit(f)(jnp.asarray(x), jnp.asarray(rois),
                            jnp.asarray(np.array([1, 1], np.int32)))
        np.testing.assert_allclose(np.asarray(jitted), np.asarray(eager),
                                   rtol=1e-5, atol=1e-6)

    def test_sampling_ratio_explicit(self):
        x = np.random.RandomState(8).randn(1, 1, 6, 6).astype(np.float32)
        rois = np.array([[0., 0., 5., 5.]], np.float32)
        got = ops.roi_align(paddle.to_tensor(x), paddle.to_tensor(rois),
                            pooled_height=3, pooled_width=3,
                            sampling_ratio=3).numpy()
        want = self._np_roi_align(x[0], rois[0], 3, 3, 1.0, 3)
        np.testing.assert_allclose(got[0], want, rtol=1e-4, atol=1e-5)
