"""Numpy-reference tests for the classic detection TRAINING suite."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid.layers as L
from paddle_tpu.core.tensor import to_tensor


def t(x, dtype=np.float32):
    return to_tensor(np.asarray(x, dtype=dtype))


class TestBipartiteMatch:
    def test_greedy_matching(self):
        # hand-verifiable: global max first, rows/cols retired
        dist = np.array([[[0.9, 0.1, 0.3],
                          [0.8, 0.7, 0.2]]], np.float32)   # (1, G=2, P=3)
        m, md = L.bipartite_match(t(dist))
        m, md = m.numpy()[0], md.numpy()[0]
        # gt0 takes prior0 (0.9); gt1 then takes prior1 (0.7)
        np.testing.assert_array_equal(m, [0, 1, -1])
        np.testing.assert_allclose(md, [0.9, 0.7, 0.0], rtol=1e-6)

    def test_per_prediction_extra_matches(self):
        dist = np.array([[[0.9, 0.6, 0.3]]], np.float32)    # one gt
        m, _ = L.bipartite_match(t(dist), match_type='per_prediction',
                                 dist_threshold=0.5)
        # prior0 matched greedily; prior1 also >= 0.5 -> matched to gt0
        np.testing.assert_array_equal(m.numpy()[0], [0, 0, -1])

    def test_padded_gt_rows_ignored(self):
        dist = np.array([[[0.9, 0.8], [0.0, 0.0]]], np.float32)
        m, _ = L.bipartite_match(t(dist))
        assert m.numpy()[0][0] == 0          # only the valid row matches


class TestTargetAssign:
    def test_gather_and_weights(self):
        x = np.arange(12, dtype=np.float32).reshape(1, 3, 4)
        mi = np.array([[2, -1, 0]], np.int32)
        out, w = L.target_assign(t(x), t(mi, np.int32), mismatch_value=7.0)
        np.testing.assert_allclose(out.numpy()[0, 0], x[0, 2])
        np.testing.assert_allclose(out.numpy()[0, 1], [7.0] * 4)
        np.testing.assert_allclose(out.numpy()[0, 2], x[0, 0])
        np.testing.assert_allclose(w.numpy()[0].reshape(-1), [1, 0, 1])


class TestSSDLoss:
    def test_loss_positive_and_backprop(self):
        rs = np.random.RandomState(0)
        B, P, C, G = 2, 8, 4, 3
        prior = np.sort(rs.rand(P, 4).astype(np.float32), axis=1)
        loc = paddle.to_tensor(rs.randn(B, P, 4).astype(np.float32))
        conf = paddle.to_tensor(rs.randn(B, P, C).astype(np.float32))
        loc.stop_gradient = False
        conf.stop_gradient = False
        gt_box = np.tile(prior[None, :G] * 0.9 + 0.05, (B, 1, 1))
        gt_label = rs.randint(1, C, (B, G)).astype(np.int64)
        loss = L.ssd_loss(loc, conf, t(gt_box), t(gt_label, np.int64),
                          t(prior))
        assert loss.shape == [B, 1]
        assert (loss.numpy() > 0).all()
        loss.sum().backward()
        assert np.isfinite(conf.grad.numpy()).all()
        assert np.abs(conf.grad.numpy()).sum() > 0

    def test_perfect_predictions_lower_loss(self):
        rs = np.random.RandomState(1)
        B, P, C = 1, 6, 3
        prior = np.sort(rs.rand(P, 4).astype(np.float32), axis=1)
        gt_box = prior[None, :2].copy()
        gt_label = np.array([[1, 2]], np.int64)
        # confident-correct confidences vs random
        good_conf = np.full((B, P, C), -6.0, np.float32)
        good_conf[:, :, 0] = 6.0          # background everywhere
        loc0 = np.zeros((B, P, 4), np.float32)
        bad_conf = rs.randn(B, P, C).astype(np.float32)
        l_good = L.ssd_loss(t(loc0), t(good_conf), t(gt_box),
                            t(gt_label, np.int64), t(prior))
        l_bad = L.ssd_loss(t(loc0), t(bad_conf), t(gt_box),
                           t(gt_label, np.int64), t(prior))
        # good conf is wrong on the 2 matched priors but right on
        # negatives; the loss must still be finite and differ
        assert np.isfinite(l_good.numpy()).all()
        assert not np.allclose(l_good.numpy(), l_bad.numpy())


class TestFocalLoss:
    def test_matches_numpy_reference(self):
        rs = np.random.RandomState(0)
        x = rs.randn(6, 3).astype(np.float32)
        lab = rs.randint(0, 4, (6, 1)).astype(np.int32)
        fg = np.array([2], np.int32)
        out = L.sigmoid_focal_loss(t(x), t(lab, np.int32),
                                   t(fg, np.int32)).numpy()
        # numpy reference (sigmoid_focal_loss_op.h)
        gamma, alpha = 2.0, 0.25
        p = 1 / (1 + np.exp(-x))
        ref = np.zeros_like(x)
        for i in range(6):
            for c in range(3):
                tgt = 1.0 if lab[i, 0] == c + 1 else 0.0
                ce = max(x[i, c], 0) - x[i, c] * tgt + \
                    np.log1p(np.exp(-abs(x[i, c])))
                p_t = p[i, c] * tgt + (1 - p[i, c]) * (1 - tgt)
                a_t = alpha * tgt + (1 - alpha) * (1 - tgt)
                ref[i, c] = a_t * (1 - p_t) ** gamma * ce / max(fg[0], 1)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-6)


class TestRPNTargetAssign:
    def test_shapes_and_fg_selection(self):
        rs = np.random.RandomState(0)
        A, S = 20, 8
        anchors = np.sort(rs.rand(A, 4) * 10, axis=1).astype(np.float32)
        gt = anchors[:2].copy()               # two perfect-overlap gts
        bbox_pred = rs.randn(A, 4).astype(np.float32)
        cls_logits = rs.randn(A, 1).astype(np.float32)
        sp, lp, st, lt, iw = L.rpn_target_assign(
            t(bbox_pred), t(cls_logits), t(anchors),
            t(np.ones((A, 4), np.float32)), t(gt),
            rpn_batch_size_per_im=S)
        assert sp.shape == [S, 1] and lp.shape == [S, 4]
        assert st.shape == [S, 1] and lt.shape == [S, 4]
        assert iw.shape == [S, 4]
        st_np = st.numpy().reshape(-1)
        assert st_np[:2].sum() >= 2           # the 2 exact-match anchors fg
        # fg rows have ~zero loc targets (gt == anchor)
        fg_rows = iw.numpy()[:, 0] > 0
        np.testing.assert_allclose(lt.numpy()[fg_rows], 0.0, atol=1e-5)


class TestRetinanetTargetAssign:
    def test_all_anchor_output(self):
        rs = np.random.RandomState(0)
        A = 12
        anchors = np.sort(rs.rand(A, 4) * 10, axis=1).astype(np.float32)
        gt = anchors[:1].copy()
        glab = np.array([[3]], np.int32)
        outs = L.retinanet_target_assign(
            t(rs.randn(A, 4)), t(rs.randn(A, 2)), t(anchors),
            t(np.ones((A, 4), np.float32)), t(gt), t(glab, np.int32))
        sp, lp, st, lt, iw, fg_num = outs
        assert st.shape == [A, 1]
        assert int(fg_num.numpy()[0, 0]) >= 1
        assert int(st.numpy()[0, 0]) == 3     # fg anchor carries class id


class TestYolov3Loss:
    def _numpy_ref(self, x, gt_box, gt_label, anchors, mask, K,
                   ignore_thresh, ds):
        """Direct port of yolov3_loss_op.h for the test."""
        B, C, H, W = x.shape
        an_num = len(anchors) // 2
        mn = len(mask)
        input_size = ds * H
        sw = min(1.0 / K, 1.0 / 40)
        pos_l, neg_l = 1.0 - sw, sw

        def sce(z, tv):
            return max(z, 0) - z * tv + np.log1p(np.exp(-abs(z)))

        def sig(z):
            return 1 / (1 + np.exp(-z))

        x5 = x.reshape(B, mn, 5 + K, H, W)
        loss = np.zeros(B)
        for i in range(B):
            obj_mask = np.zeros((mn, H, W))
            valid = [(gt_box[i, tt, 2] > 1e-6 and gt_box[i, tt, 3] > 1e-6)
                     for tt in range(gt_box.shape[1])]
            for j in range(mn):
                for k in range(H):
                    for l in range(W):
                        px = (l + sig(x5[i, j, 0, k, l])) / W
                        py = (k + sig(x5[i, j, 1, k, l])) / H
                        pw = np.exp(x5[i, j, 2, k, l]) * \
                            anchors[2 * mask[j]] / input_size
                        ph = np.exp(x5[i, j, 3, k, l]) * \
                            anchors[2 * mask[j] + 1] / input_size
                        best = 0.0
                        for tt in range(gt_box.shape[1]):
                            if not valid[tt]:
                                continue
                            g = gt_box[i, tt]
                            iw = min(px + pw / 2, g[0] + g[2] / 2) - \
                                max(px - pw / 2, g[0] - g[2] / 2)
                            ih = min(py + ph / 2, g[1] + g[3] / 2) - \
                                max(py - ph / 2, g[1] - g[3] / 2)
                            inter = 0.0 if iw < 0 or ih < 0 else iw * ih
                            u = pw * ph + g[2] * g[3] - inter
                            if inter / u > best:
                                best = inter / u
                        if best > ignore_thresh:
                            obj_mask[j, k, l] = -1
            for tt in range(gt_box.shape[1]):
                if not valid[tt]:
                    continue
                g = gt_box[i, tt]
                gi, gj = int(g[0] * W), int(g[1] * H)
                best_iou, best_n = 0, 0
                for a in range(an_num):
                    aw = anchors[2 * a] / input_size
                    ah = anchors[2 * a + 1] / input_size
                    iw = min(aw, g[2])
                    ih = min(ah, g[3])
                    inter = iw * ih
                    u = aw * ah + g[2] * g[3] - inter
                    if inter / u > best_iou:
                        best_iou, best_n = inter / u, a
                if best_n not in mask:
                    continue
                mi = mask.index(best_n)
                tx = g[0] * W - gi
                ty = g[1] * H - gj
                tw = np.log(g[2] * input_size / anchors[2 * best_n])
                th = np.log(g[3] * input_size / anchors[2 * best_n + 1])
                sc = 2.0 - g[2] * g[3]
                loss[i] += sce(x5[i, mi, 0, gj, gi], tx) * sc
                loss[i] += sce(x5[i, mi, 1, gj, gi], ty) * sc
                loss[i] += abs(x5[i, mi, 2, gj, gi] - tw) * sc
                loss[i] += abs(x5[i, mi, 3, gj, gi] - th) * sc
                obj_mask[mi, gj, gi] = 1.0
                lab = gt_label[i, tt]
                for c in range(K):
                    loss[i] += sce(x5[i, mi, 5 + c, gj, gi],
                                   pos_l if c == lab else neg_l)
            for j in range(mn):
                for k in range(H):
                    for l in range(W):
                        o = obj_mask[j, k, l]
                        if o > 1e-5:
                            loss[i] += sce(x5[i, j, 4, k, l], 1.0) * o
                        elif o > -0.5:
                            loss[i] += sce(x5[i, j, 4, k, l], 0.0)
        return loss

    def test_matches_numpy_port(self):
        rs = np.random.RandomState(0)
        B, H, W, K = 2, 4, 4, 3
        anchors = [10, 13, 16, 30, 33, 23]
        mask = [0, 1]
        C = len(mask) * (5 + K)
        x = (rs.randn(B, C, H, W) * 0.5).astype(np.float32)
        gt_box = np.zeros((B, 3, 4), np.float32)
        gt_box[0, 0] = [0.3, 0.4, 0.2, 0.25]
        gt_box[1, 0] = [0.7, 0.2, 0.1, 0.1]
        gt_box[1, 1] = [0.2, 0.8, 0.3, 0.2]
        gt_label = np.zeros((B, 3), np.int32)
        gt_label[0, 0] = 1
        gt_label[1, 0] = 2
        gt_label[1, 1] = 0
        out = L.yolov3_loss(t(x), t(gt_box), t(gt_label, np.int32),
                            anchors, mask, K, ignore_thresh=0.5,
                            downsample_ratio=8).numpy()
        ref = self._numpy_ref(x, gt_box, gt_label, anchors, mask, K,
                              0.5, 8)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)

    def test_backprop(self):
        rs = np.random.RandomState(0)
        x = paddle.to_tensor(
            (rs.randn(1, 16, 4, 4) * 0.5).astype(np.float32))
        x.stop_gradient = False
        gt_box = np.array([[[0.5, 0.5, 0.3, 0.3]]], np.float32)
        loss = L.yolov3_loss(x, t(gt_box), t([[1]], np.int32),
                             [10, 13, 16, 30], [0, 1], 3, 0.5, 8)
        loss.sum().backward()
        g = x.grad.numpy()
        assert np.isfinite(g).all() and np.abs(g).sum() > 0


class TestMatrixNMS:
    def test_decay_suppresses_overlaps(self):
        # two heavy-overlap boxes + one distant box, one class
        boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                           [50, 50, 60, 60]]], np.float32)
        scores = np.zeros((1, 2, 3), np.float32)
        scores[0, 1] = [0.9, 0.8, 0.7]       # class 1 (0 = background)
        out, counts = L.matrix_nms(t(boxes), t(scores),
                                   score_threshold=0.1,
                                   post_threshold=0.0, nms_top_k=3,
                                   keep_top_k=3)
        o = out.numpy()[0]
        assert int(counts.numpy()[0]) == 3
        # top box keeps its score; the overlapped one is decayed below it
        top = o[o[:, 1].argsort()[::-1]]
        np.testing.assert_allclose(top[0, 1], 0.9, rtol=1e-5)
        assert top[1, 1] < 0.8               # decayed (0.7 distant or 0.8*d)

    def test_gaussian_mode_runs(self):
        boxes = np.random.RandomState(0).rand(1, 5, 4).astype(np.float32)
        boxes[..., 2:] += 1.0
        scores = np.random.RandomState(1).rand(1, 2, 5).astype(np.float32)
        out, counts = L.matrix_nms(t(boxes), t(scores), 0.05, 0.0, 5, 5,
                                   use_gaussian=True)
        assert out.shape == [1, 5, 6]


class TestProposals:
    def test_generate_proposals_shapes(self):
        rs = np.random.RandomState(0)
        B, A, H, W = 1, 3, 4, 4
        scores = rs.rand(B, A, H, W).astype(np.float32)
        deltas = (rs.randn(B, 4 * A, H, W) * 0.1).astype(np.float32)
        im_info = np.array([[32.0, 32.0, 1.0]], np.float32)
        anchors = np.sort(rs.rand(H, W, A, 4) * 32, axis=-1) \
            .astype(np.float32)
        var = np.ones((H, W, A, 4), np.float32)
        rois, probs, counts = L.generate_proposals(
            t(scores), t(deltas), t(im_info), t(anchors), t(var),
            pre_nms_top_n=20, post_nms_top_n=10, nms_thresh=0.7)
        assert rois.shape == [B, 10, 4]
        assert probs.shape == [B, 10, 1]
        assert int(counts.numpy()[0]) > 0
        r = rois.numpy()[0]
        assert (r >= 0).all() and (r <= 31).all()

    def test_generate_proposal_labels_host(self):
        rs = np.random.RandomState(0)
        rois = np.sort(rs.rand(30, 4) * 50, axis=1).astype(np.float32)
        gt_boxes = rois[:3] + 0.5
        gt_classes = np.array([1, 2, 3], np.int32)
        outs = L.generate_proposal_labels(
            t(rois), t(gt_classes, np.int32),
            t(np.zeros(3, np.int32), np.int32), t(gt_boxes),
            t(np.array([[50, 50, 1.0]], np.float32)),
            batch_size_per_im=16, class_nums=5, use_random=False)
        srois, labels, targets, inw, outw = outs
        assert srois.shape == [16, 4]
        assert targets.shape == [16, 20]
        labs = labels.numpy().reshape(-1)
        assert (labs > 0).sum() >= 1          # some fg sampled
        # fg rows put targets in their class slot
        fg0 = np.where(labs > 0)[0][0]
        c = labs[fg0]
        assert np.abs(inw.numpy()[fg0, 4 * c:4 * c + 4]).sum() == 4

    def test_generate_mask_labels_host(self):
        rois = np.array([[0, 0, 10, 10]], np.float32)
        labels = np.array([[2]], np.int32)
        square = np.array([[[2, 2], [8, 2], [8, 8], [2, 8]]], np.float32)
        mrois, has, masks = L.generate_mask_labels(
            None, None, None, t(square), t(rois), t(labels, np.int32),
            num_classes=3, resolution=4)
        assert int(has.numpy()[0, 0]) == 1
        m = masks.numpy().reshape(3, 4, 4)
        assert m[2].sum() > 0 and m[0].sum() == 0 and m[1].sum() == 0


class TestFPNRouting:
    def test_distribute_and_restore(self):
        rois = np.array([[0, 0, 20, 20],      # small -> low level
                         [0, 0, 300, 300],    # large -> high level
                         [0, 0, 30, 30]], np.float32)
        multi, restore = L.distribute_fpn_proposals(
            t(rois), min_level=2, max_level=5, refer_level=4,
            refer_scale=224)
        assert len(multi) == 4
        lvl2 = multi[0].numpy()
        assert np.abs(lvl2[0]).sum() > 0      # roi0 at level 2
        assert np.abs(lvl2[1]).sum() == 0     # roi1 not at level 2
        r = restore.numpy().reshape(-1)
        assert sorted(r.tolist()) == [0, 1, 2]

    def test_collect_topk(self):
        r1 = np.array([[0, 0, 1, 1], [0, 0, 2, 2]], np.float32)
        r2 = np.array([[0, 0, 3, 3]], np.float32)
        s1 = np.array([[0.2], [0.9]], np.float32)
        s2 = np.array([[0.5]], np.float32)
        rois, scores = L.collect_fpn_proposals(
            [t(r1), t(r2)], [t(s1), t(s2)], 2, 3, post_nms_top_n=2)
        np.testing.assert_allclose(scores.numpy().reshape(-1), [0.9, 0.5])
        np.testing.assert_allclose(rois.numpy()[0], [0, 0, 2, 2])


class TestMiscDetection:
    def test_polygon_box_transform_exact(self):
        rs = np.random.RandomState(0)
        x = rs.randn(1, 4, 2, 3).astype(np.float32)
        out = L.polygon_box_transform(t(x)).numpy()
        for c in range(4):
            for h in range(2):
                for w in range(3):
                    base = w * 4 if c % 2 == 0 else h * 4
                    np.testing.assert_allclose(out[0, c, h, w],
                                               base - x[0, c, h, w],
                                               rtol=1e-5)

    def test_detection_output_pipeline(self):
        rs = np.random.RandomState(0)
        P, C = 6, 3
        prior = np.sort(rs.rand(P, 4), axis=1).astype(np.float32)
        var = np.full((P, 4), 0.1, np.float32)
        loc = (rs.randn(1, P, 4) * 0.1).astype(np.float32)
        scores = rs.rand(1, P, C).astype(np.float32)
        out, counts = L.detection_output(t(loc), t(scores), t(prior),
                                         t(var))
        assert out.shape[2] == 6

    def test_box_decoder_and_assign(self):
        prior = np.array([[0, 0, 10, 10]], np.float32)
        var = np.ones((1, 4), np.float32)
        tb = np.zeros((1, 8), np.float32)     # 2 classes, zero deltas
        score = np.array([[0.1, 0.9]], np.float32)
        dec, assigned = L.box_decoder_and_assign(
            t(prior), t(var), t(tb), t(score), box_clip=4.135)
        # zero deltas decode back to the prior (center-size w/ +1 conv)
        np.testing.assert_allclose(assigned.numpy()[0],
                                   [0, 0, 10, 10], atol=1e-4)

    def test_locality_aware_nms_runs(self):
        boxes = np.array([[[0, 0, 10, 10], [1, 1, 11, 11],
                           [40, 40, 50, 50]]], np.float32)
        scores = np.zeros((1, 2, 3), np.float32)
        scores[0, 1] = [0.9, 0.8, 0.7]
        out, counts = L.locality_aware_nms(t(boxes), t(scores), 0.1,
                                           nms_top_k=3, keep_top_k=3)
        assert out.shape == [1, 3, 6]

    def test_multi_box_head_builds(self):
        rs = np.random.RandomState(0)
        f1 = t(rs.randn(1, 8, 8, 8).astype(np.float32))
        f2 = t(rs.randn(1, 8, 4, 4).astype(np.float32))
        img = t(rs.randn(1, 3, 64, 64).astype(np.float32))
        locs, confs, boxes, vars_ = L.multi_box_head(
            [f1, f2], img, base_size=64, num_classes=4,
            aspect_ratios=[[2.0], [2.0]], min_ratio=20, max_ratio=90)
        assert locs.shape[2] == 4
        assert confs.shape[2] == 4
        assert boxes.shape[0] == locs.shape[1]
        assert vars_.shape == boxes.shape


class TestRoiPoolFamily:
    def test_roi_pool_exact_max(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        rois = np.array([[0, 0, 3, 3]], np.float32)
        out = L.roi_pool(t(x), t(rois), 2, 2, 1.0).numpy()
        # quantized bins: [[max of rows 0-1 cols 0-1, ...]]
        np.testing.assert_allclose(out[0, 0],
                                   [[5, 7], [13, 15]])

    def test_psroi_pool_exact(self):
        # C = oc*ph*pw = 1*2*2; each bin reads its own channel
        x = np.zeros((1, 4, 4, 4), np.float32)
        for c in range(4):
            x[0, c] = c + 1
        rois = np.array([[0, 0, 3, 3]], np.float32)
        out = L.psroi_pool(t(x), t(rois), output_channels=1,
                           spatial_scale=1.0, pooled_height=2,
                           pooled_width=2).numpy()
        np.testing.assert_allclose(out[0, 0], [[1, 2], [3, 4]])

    def test_prroi_pool_smooth(self):
        x = np.ones((1, 2, 6, 6), np.float32)
        rois = np.array([[1.0, 1.0, 4.0, 4.0]], np.float32)
        out = L.prroi_pool(t(x), t(rois), 1.0, 2, 2).numpy()
        np.testing.assert_allclose(out, np.ones_like(out), rtol=1e-5)

    def test_roi_perspective_transform(self):
        x = np.random.RandomState(0).rand(1, 1, 8, 8).astype(np.float32)
        # axis-aligned quad == crop
        rois = np.array([[1, 1, 5, 1, 5, 5, 1, 5]], np.float32)
        out = L.roi_perspective_transform(t(x), t(rois), 4, 4).numpy()
        assert out.shape == (1, 1, 4, 4)
        assert np.isfinite(out).all()


class TestDeformable:
    def test_zero_offset_equals_regular_conv(self):
        rs = np.random.RandomState(0)
        x = rs.randn(1, 2, 5, 5).astype(np.float32)
        kh = kw = 3
        offset = np.zeros((1, 2 * kh * kw, 5, 5), np.float32)
        mask = np.ones((1, kh * kw, 5, 5), np.float32)
        from paddle_tpu.nn.initializer import Assign
        w = rs.randn(3, 2, 3, 3).astype(np.float32)
        out = L.deformable_conv(t(x), t(offset), t(mask), 3, 3,
                                padding=1, param_attr=Assign(w),
                                bias_attr=False).numpy()
        # numpy direct conv with zero padding
        xp = np.pad(x, ((0, 0), (0, 0), (1, 1), (1, 1)))
        ref = np.zeros((1, 3, 5, 5), np.float32)
        for f in range(3):
            for i in range(5):
                for j in range(5):
                    ref[0, f, i, j] = (
                        xp[0, :, i:i + 3, j:j + 3] * w[f]).sum()
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)

    def test_modulation_mask_scales(self):
        rs = np.random.RandomState(0)
        x = rs.randn(1, 1, 4, 4).astype(np.float32)
        offset = np.zeros((1, 8, 3, 3), np.float32)
        from paddle_tpu.nn.initializer import Assign
        w = np.ones((1, 1, 2, 2), np.float32)
        full = L.deformable_conv(t(x), t(offset),
                                 t(np.ones((1, 4, 3, 3), np.float32)),
                                 1, 2, param_attr=Assign(w),
                                 bias_attr=False).numpy()
        half = L.deformable_conv(t(x), t(offset),
                                 t(np.full((1, 4, 3, 3), 0.5,
                                           np.float32)),
                                 1, 2, param_attr=Assign(w),
                                 bias_attr=False).numpy()
        np.testing.assert_allclose(half, full * 0.5, rtol=1e-4)

    def test_deformable_roi_pooling_no_trans(self):
        x = np.ones((1, 2, 6, 6), np.float32)
        rois = np.array([[0, 0, 5, 5]], np.float32)
        trans = np.zeros((1, 2, 2, 2), np.float32)
        out = L.deformable_roi_pooling(
            t(x), t(rois), t(trans), no_trans=True, pooled_height=2,
            pooled_width=2, sample_per_part=2).numpy()
        np.testing.assert_allclose(out, np.ones_like(out), rtol=1e-5)
