"""Distributed numerics on an 8-virtual-device CPU mesh.

SURVEY §4 promises: collective value checks, DataParallel grad sync parity,
tensor-parallel layer parity vs dense, ring attention vs full attention, FSDP
train-step parity. Parity targets: reference collective ops
(paddle/fluid/operators/collective/c_allreduce_op.h etc.) and
fluid/dygraph/parallel.py:DataParallel.
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

import pytest

import paddle_tpu as paddle
from paddle_tpu.core.tensor import Tensor
from paddle_tpu.distributed import env as denv
from paddle_tpu.distributed import collective
from paddle_tpu.distributed._compat import shard_map
from paddle_tpu.distributed.sharding import (ColumnParallelLinear,
                                             RowParallelLinear,
                                             VocabParallelEmbedding,
                                             fsdp_pspecs, param_pspecs)
from paddle_tpu.distributed.ring_attention import ring_attention
from paddle_tpu.kernels.flash_attention import _attn_reference
from paddle_tpu.nn.layer_base import functional_call, param_values

N_DEV = 8


def _mesh(axis='data', n=N_DEV):
    return Mesh(np.asarray(jax.devices()[:n]), (axis,))


@pytest.fixture
def data_mesh():
    mesh = _mesh('data')
    denv.set_mesh(mesh)
    yield mesh
    denv.set_mesh(None)
    denv._global['initialized'] = False


@pytest.fixture
def model_mesh():
    mesh = _mesh('model')
    denv.set_mesh(mesh)
    yield mesh
    denv.set_mesh(None)
    denv._global['initialized'] = False


# ---------------------------------------------------------------------------
# collective value checks (shard_map: genuinely distinct per-shard values)
# ---------------------------------------------------------------------------

def _per_shard(fn, x, mesh, in_spec=P('data'), out_spec=P('data')):
    return shard_map(fn, mesh=mesh, in_specs=(in_spec,), out_specs=out_spec,
                     check=False)(x)


def test_all_reduce_sum_max_min_prod_values(data_mesh):
    x = jnp.arange(1.0, N_DEV + 1.0)  # shard i holds i+1

    out = _per_shard(lambda s: lax.psum(s, 'data'), x, data_mesh)
    np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, 36.0))

    out = _per_shard(lambda s: lax.pmax(s, 'data'), x, data_mesh)
    np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, 8.0))

    out = _per_shard(lambda s: lax.pmin(s, 'data'), x, data_mesh)
    np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, 1.0))

    prod = collective._LAX_REDUCE[collective.ReduceOp.PROD]
    out = _per_shard(lambda s: prod(s, 'data'), x, data_mesh)
    np.testing.assert_allclose(np.asarray(out),
                               np.full(N_DEV, float(np.prod(np.arange(1, 9)))),
                               rtol=1e-5)


def test_prod_all_reduce_sign_and_zero_correct(data_mesh):
    # VERDICT r4 weak #3: exp(psum(log)) dropped signs and turned zeros into
    # 1e-30. The reduce must be exact for negative and zero shards.
    prod = collective._LAX_REDUCE[collective.ReduceOp.PROD]

    x = jnp.asarray([1.0, -2.0, 3.0, -4.0, 5.0, -6.0, 7.0, 1.0])
    out = _per_shard(lambda s: prod(s, 'data'), x, data_mesh)
    np.testing.assert_allclose(np.asarray(out),
                               np.full(N_DEV, float(np.prod(np.asarray(x)))),
                               rtol=1e-6)

    x = jnp.asarray([1.0, -2.0, 0.0, 4.0, 5.0, 6.0, 7.0, 8.0])
    out = _per_shard(lambda s: prod(s, 'data'), x, data_mesh)
    np.testing.assert_allclose(np.asarray(out), np.zeros(N_DEV))

    # integer dtype stays exact (log trick would have broken this too)
    xi = jnp.asarray([1, 2, 3, 1, 2, 1, 1, 2], jnp.int32)
    out = _per_shard(lambda s: prod(s, 'data'), xi, data_mesh)
    assert np.asarray(out).dtype == np.int32
    np.testing.assert_array_equal(np.asarray(out), np.full(N_DEV, 24))


def test_eager_all_reduce_string_ops_and_prod(data_mesh):
    # ADVICE r4 medium: fleet metrics pass op='sum'/'max'/'min' strings.
    t = paddle.to_tensor(np.array([2.0], np.float32))
    np.testing.assert_allclose(
        collective.all_reduce(t, op='sum').numpy(), [16.0])
    t = paddle.to_tensor(np.array([2.0], np.float32))
    np.testing.assert_allclose(
        collective.all_reduce(t, op='max').numpy(), [2.0])
    t = paddle.to_tensor(np.array([-2.0], np.float32))
    np.testing.assert_allclose(
        collective.all_reduce(t, op=collective.ReduceOp.PROD).numpy(), [256.0])
    with pytest.raises(ValueError, match="unknown reduce op"):
        collective.all_reduce(paddle.to_tensor(np.ones(1)), op='bogus')


def test_eager_all_reduce_sharded_input_reduces_shards(data_mesh):
    # A genuinely mesh-sharded value must reduce its distinct shards, not
    # apply the replicated closed form.
    vals = np.arange(1.0, N_DEV + 1.0, dtype=np.float32)
    arr = jax.device_put(jnp.asarray(vals),
                         NamedSharding(data_mesh, P('data')))
    out = collective.all_reduce(Tensor(arr), op=collective.ReduceOp.SUM)
    np.testing.assert_allclose(out.numpy(), np.full(N_DEV, 36.0))


def test_fleet_metrics_multiworker_string_ops(data_mesh, monkeypatch):
    # ADVICE r4 medium repro: PADDLE_TRAINERS_NUM>1 + initialized env used to
    # raise KeyError('sum') for every distributed metric.
    from paddle_tpu.distributed import metrics as dmetrics
    monkeypatch.setenv('PADDLE_TRAINERS_NUM', '8')
    denv._global['initialized'] = True
    assert dmetrics.sum(np.array([1.0, 2.0])) == pytest.approx(24.0)
    assert dmetrics.max(np.array([3.0])) == pytest.approx(3.0)
    assert dmetrics.min(np.array([-1.0, 4.0])) == pytest.approx(-1.0)
    # acc reduces correct & total identically so the ratio is worker-invariant
    assert dmetrics.acc(np.array([3.0]), np.array([4.0])) == pytest.approx(0.75)
    # trainers != mesh devices: scale by the WORKER count, never the mesh size
    monkeypatch.setenv('PADDLE_TRAINERS_NUM', '2')
    assert dmetrics.sum(np.array([1.0, 2.0])) == pytest.approx(6.0)
    assert dmetrics.max(np.array([3.0])) == pytest.approx(3.0)


def test_eager_all_reduce_nonleading_dim_sharding_reduces():
    # Value partitioned over the reduce axis along dim 1 (not dim 0) must
    # still reduce its distinct shards, not take the replicated closed form.
    mesh = _mesh('data')
    denv.set_mesh(mesh)
    try:
        arr = jax.device_put(
            jnp.arange(32.0).reshape(4, 8),
            NamedSharding(mesh, P(None, 'data')))
        out = collective.all_reduce(Tensor(arr)).numpy()
        # each width-1 column shard sums across the 8 shards: every column
        # of row r becomes sum(row r), i.e. 8r*8/... = row sum replicated
        expect = np.repeat(
            np.arange(32.0).reshape(4, 8).sum(1, keepdims=True), 8, 1)
        np.testing.assert_allclose(out, expect)
    finally:
        denv.set_mesh(None)
        denv._global['initialized'] = False


def test_fleet_metrics_multiaxis_mesh_uses_data_axis(monkeypatch):
    # n_workers must be compared against the axis actually reduced (the data
    # axis), not the total mesh size: Mesh (4,2) with trainers=8 used to
    # "match" on 8 total devices but reduce over only 4.
    from paddle_tpu.distributed import metrics as dmetrics
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(4, 2),
                ('data', 'model'))
    denv.set_mesh(mesh)
    try:
        monkeypatch.setenv('PADDLE_TRAINERS_NUM', '8')
        denv._global['initialized'] = True
        # mesh data axis is 4 != 8 workers -> closed form scales by 8
        assert dmetrics.sum(np.array([1.0, 2.0])) == pytest.approx(24.0)
    finally:
        denv.set_mesh(None)
        denv._global['initialized'] = False


def test_eager_all_reduce_other_axis_sharding_uses_closed_form():
    # A value sharded over a *different* mesh axis (or a non-leading dim) is
    # replicated w.r.t. 'data'; it must take the closed form, not get chunk-
    # summed along dim 0 by the sharded branch.
    mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(4, 2), ('data', 'model'))
    denv.set_mesh(mesh)
    try:
        arr = jax.device_put(jnp.ones((8, 4)),
                             NamedSharding(mesh, P(None, 'model')))
        out = collective.all_reduce(Tensor(arr), op=collective.ReduceOp.SUM)
        np.testing.assert_allclose(out.numpy(), np.full((8, 4), 4.0))

        with pytest.raises(ValueError, match="unknown reduce op"):
            collective.all_reduce(paddle.to_tensor(np.ones(1)), op=7)
    finally:
        denv.set_mesh(None)
        denv._global['initialized'] = False


def test_all_gather_values(data_mesh):
    x = jnp.arange(float(N_DEV * 2)).reshape(N_DEV, 2)

    def f(s):
        return lax.all_gather(s, 'data')  # (n, 1, 2) per shard

    out = shard_map(f, mesh=data_mesh, in_specs=(P('data'),),
                    out_specs=P(None, 'data'), check=False)(x)
    # every shard gathered the same full array: axis 0 = gathered rows,
    # axis 1 = which shard did the gathering
    got = np.asarray(out).reshape(N_DEV, N_DEV, 2)
    for j in range(N_DEV):
        np.testing.assert_allclose(got[:, j], np.asarray(x))


def test_reduce_scatter_values(data_mesh):
    # shard i holds row vector of length N_DEV, all ones * (i+1)
    x = jnp.repeat(jnp.arange(1.0, N_DEV + 1.0)[:, None], N_DEV, axis=1)
    x = x.reshape(N_DEV * N_DEV)

    def f(s):
        return lax.psum_scatter(s.reshape(N_DEV), 'data', tiled=True)

    out = _per_shard(f, x, data_mesh)
    # each element = sum over shards of that position = 36
    np.testing.assert_allclose(np.asarray(out), np.full(N_DEV, 36.0))


def test_all_to_all_values(data_mesh):
    # shard i holds [i*n .. i*n+n-1]; after all_to_all along axis 0,
    # shard i holds column i: [i, n+i, 2n+i, ...]
    x = jnp.arange(float(N_DEV * N_DEV))

    def f(s):
        return lax.all_to_all(s.reshape(N_DEV, 1), 'data',
                              split_axis=0, concat_axis=0).reshape(N_DEV)

    out = _per_shard(f, x, data_mesh)
    expect = np.arange(N_DEV * N_DEV).reshape(N_DEV, N_DEV).T.reshape(-1)
    np.testing.assert_allclose(np.asarray(out), expect.astype(np.float32))


def test_ppermute_ring_shift(data_mesh):
    x = jnp.arange(float(N_DEV))
    perm = [(i, (i + 1) % N_DEV) for i in range(N_DEV)]

    def f(s):
        return collective.ppermute(s, perm, axis='data')

    out = _per_shard(f, x, data_mesh)
    np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))


def test_eager_collective_api_values(data_mesh):
    # reference eager API semantics on the single-controller: every rank holds
    # the same tensor, all_reduce(SUM) -> n * x
    t = paddle.to_tensor(np.array([1.5, -2.0], np.float32))
    out = collective.all_reduce(t)
    np.testing.assert_allclose(out.numpy(), np.array([12.0, -16.0]), rtol=1e-6)

    t = paddle.to_tensor(np.array([3.0], np.float32))
    out = collective.all_reduce(t, op=collective.ReduceOp.MAX)
    np.testing.assert_allclose(out.numpy(), np.array([3.0]))

    gathered = []
    out = collective.all_gather(gathered, paddle.to_tensor(np.ones(2, np.float32)))
    assert len(gathered) == N_DEV
    np.testing.assert_allclose(gathered[0].numpy(), np.ones(2))


def test_unbound_axis_collective_raises_not_silently_skips(data_mesh):
    # VERDICT r1 weak #2: collectives must never silently no-op inside a
    # traced region where the axis is unbound.
    def f(x):
        return collective.all_reduce(Tensor(x))._value

    with pytest.raises(RuntimeError, match="not bound|unbound"):
        jax.jit(f)(jnp.ones(4))


# ---------------------------------------------------------------------------
# data-parallel gradient sync
# ---------------------------------------------------------------------------

def test_dp_grad_sync_matches_full_batch(data_mesh):
    """Per-shard grads + psum-mean == single-device full-batch grads."""
    rs = np.random.RandomState(0)
    w = jnp.asarray(rs.randn(6, 4), jnp.float32)
    x = jnp.asarray(rs.randn(N_DEV * 2, 6), jnp.float32)
    y = jnp.asarray(rs.randn(N_DEV * 2, 4), jnp.float32)

    def loss_fn(w, x, y):
        return jnp.mean((x @ w - y) ** 2)

    ref_grad = jax.grad(loss_fn)(w, x, y)

    def shard_step(w, x_s, y_s):
        g = jax.grad(loss_fn)(w, x_s, y_s)
        return collective.in_jit_all_reduce(g, 'data') / N_DEV

    g = shard_map(shard_step, mesh=data_mesh,
                  in_specs=(P(), P('data'), P('data')), out_specs=P(),
                  check=False)(w, x, y)
    np.testing.assert_allclose(np.asarray(g), np.asarray(ref_grad),
                               rtol=1e-5, atol=1e-6)


def test_dataparallel_wrapper_grad_parity(data_mesh):
    """DataParallel scale_loss + apply_collective_grads leaves full-batch
    grads intact on the single controller (n identical ranks)."""
    import paddle_tpu.nn as nn
    net = nn.Linear(5, 3)
    dp = paddle.DataParallel(net) if hasattr(paddle, 'DataParallel') else None
    if dp is None:
        from paddle_tpu.distributed.parallel import DataParallel
        dp = DataParallel(net)

    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 5).astype(np.float32))
    y = paddle.to_tensor(np.random.RandomState(1).randn(4, 3).astype(np.float32))

    out = dp(x)
    loss = ((out - y) ** 2).mean()
    ref = jax.grad(lambda w: jnp.mean((x._value @ w + net.bias._value
                                       - y._value) ** 2))(net.weight._value)

    scaled = dp.scale_loss(loss)
    scaled.backward()
    dp.apply_collective_grads()
    # scale 1/n then sum over n identical ranks == identity
    np.testing.assert_allclose(net.weight.grad.numpy(), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# tensor parallelism parity
# ---------------------------------------------------------------------------

def test_column_parallel_linear_shard_map_parity(model_mesh):
    net = ColumnParallelLinear(12, 16, gather_output=True)
    w = np.asarray(net.weight.numpy())
    b = np.asarray(net.bias.numpy())
    x = np.random.RandomState(0).randn(4, 12).astype(np.float32)
    ref = x @ w + b

    def f(x_l, w_l, b_l):
        out, _ = functional_call(net, {'weight': w_l, 'bias': b_l},
                                 Tensor(x_l))
        return out._value

    out = shard_map(f, mesh=model_mesh,
                    in_specs=(P(), P(None, 'model'), P('model')),
                    out_specs=P(), check=False)(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


def test_row_parallel_linear_shard_map_parity(model_mesh):
    net = RowParallelLinear(16, 12)
    w = np.asarray(net.weight.numpy())
    b = np.asarray(net.bias.numpy())
    x = np.random.RandomState(0).randn(4, 16).astype(np.float32)
    ref = x @ w + b

    def f(x_l, w_l, b_l):
        out, _ = functional_call(net, {'weight': w_l, 'bias': b_l},
                                 Tensor(x_l))
        return out._value

    out = shard_map(f, mesh=model_mesh,
                    in_specs=(P(None, 'model'), P('model', None), P()),
                    out_specs=P(), check=False)(
        jnp.asarray(x), jnp.asarray(w), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4, atol=1e-4)


def test_vocab_parallel_embedding_shard_map_parity(model_mesh):
    net = VocabParallelEmbedding(64, 8)
    w = np.asarray(net.weight.numpy())
    ids = np.random.RandomState(0).randint(0, 64, (4, 6))
    ref = w[ids]

    def f(ids_l, w_l):
        out, _ = functional_call(net, {'weight': w_l}, Tensor(ids_l))
        return out._value

    out = shard_map(f, mesh=model_mesh,
                    in_specs=(P(), P('model', None)), out_specs=P(),
                    check=False)(jnp.asarray(ids), jnp.asarray(w))
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-6)


def test_tp_layers_pjit_global_semantics_parity(model_mesh):
    """Under GSPMD (sharded weights, no shard_map) the layers must compute the
    same global result as dense — no manual collective double-counting."""
    col = ColumnParallelLinear(8, 16, gather_output=True)
    row = RowParallelLinear(16, 8)
    x = paddle.to_tensor(np.random.RandomState(0).randn(4, 8).astype(np.float32))

    h = col(x)
    out = row(h)
    ref = (x.numpy() @ col.weight.numpy() + col.bias.numpy())
    ref = ref @ row.weight.numpy() + row.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-4, atol=1e-4)

    emb = VocabParallelEmbedding(64, 8)
    ids = paddle.to_tensor(np.random.RandomState(1).randint(0, 64, (3, 5)))
    np.testing.assert_allclose(emb(ids).numpy(), emb.weight.numpy()[ids.numpy()],
                               rtol=1e-6)


def test_column_parallel_backward_parity(model_mesh):
    """Gradients through the shard_map TP forward match dense gradients."""
    net = ColumnParallelLinear(6, 8, gather_output=True)
    w = jnp.asarray(net.weight.numpy())
    b = jnp.asarray(net.bias.numpy())
    x = jnp.asarray(np.random.RandomState(0).randn(3, 6).astype(np.float32))

    def dense_loss(w, b):
        return jnp.sum((x @ w + b) ** 2)

    ref_gw, ref_gb = jax.grad(dense_loss, argnums=(0, 1))(w, b)

    def tp_loss(w, b):
        def f(x_l, w_l, b_l):
            out, _ = functional_call(net, {'weight': w_l, 'bias': b_l},
                                     Tensor(x_l))
            return out._value
        out = shard_map(f, mesh=model_mesh,
                        in_specs=(P(), P(None, 'model'), P('model')),
                        out_specs=P(), check=False)(x, w, b)
        return jnp.sum(out ** 2)

    gw, gb = jax.grad(tp_loss, argnums=(0, 1))(w, b)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(ref_gw),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(ref_gb),
                               rtol=1e-4, atol=1e-4)


# ---------------------------------------------------------------------------
# ring attention vs full attention
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_full(causal):
    mesh = _mesh('seq', 4)
    rs = np.random.RandomState(0)
    B, H, L, D = 2, 2, 32, 8
    q = jnp.asarray(rs.randn(B, H, L, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, H, L, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, H, L, D), jnp.float32)
    out = ring_attention(q, k, v, mesh=mesh, axis='seq', causal=causal)
    ref = _attn_reference(q, k, v, causal, 1.0 / np.sqrt(D))
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-5)


def test_ring_attention_backward_matches_full():
    mesh = _mesh('seq', 4)
    rs = np.random.RandomState(1)
    B, H, L, D = 1, 2, 16, 4
    q = jnp.asarray(rs.randn(B, H, L, D), jnp.float32)
    k = jnp.asarray(rs.randn(B, H, L, D), jnp.float32)
    v = jnp.asarray(rs.randn(B, H, L, D), jnp.float32)

    def ring_loss(q, k, v):
        return jnp.sum(ring_attention(q, k, v, mesh=mesh, axis='seq',
                                      causal=True) ** 2)

    def ref_loss(q, k, v):
        return jnp.sum(_attn_reference(q, k, v, True, 1.0 / np.sqrt(D)) ** 2)

    g_ring = jax.grad(ring_loss, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-4)


# ---------------------------------------------------------------------------
# FSDP / ZeRO sharded train step parity
# ---------------------------------------------------------------------------

def test_fsdp_train_step_parity(data_mesh):
    """One AdamW step with FSDP-sharded params == unsharded step."""
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer as opt_mod

    net = nn.Linear(16, 8)
    params = param_values(net, trainable_only=False)
    pspecs = fsdp_pspecs(net, axis='data', min_size=8)
    assert any(s != P() for s in pspecs.values()), "no param got sharded"

    x = jnp.asarray(np.random.RandomState(0).randn(8, 16), jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randn(8, 8), jnp.float32)

    opt = opt_mod.AdamW(learning_rate=1e-2)

    def train_step(params, opt_state):
        def loss_of(p):
            out, _ = functional_call(net, p, Tensor(x))
            return jnp.mean((out._value - y) ** 2)
        loss, grads = jax.value_and_grad(loss_of)(params)
        new_p, new_s = opt.functional_update(params, grads, opt_state)
        return new_p, new_s, loss

    # reference: unsharded
    s0 = opt.init_state_values(params)
    ref_p, _, ref_loss = jax.jit(train_step)(params, s0)

    # sharded: place params according to fsdp specs, jit with constraints
    sharded = {k: jax.device_put(v, NamedSharding(data_mesh, pspecs[k]))
               for k, v in params.items()}
    s1 = opt.init_state_values(sharded)
    new_p, _, loss = jax.jit(train_step)(sharded, s1)

    np.testing.assert_allclose(float(loss), float(ref_loss), rtol=1e-5)
    for k in params:
        np.testing.assert_allclose(np.asarray(new_p[k]),
                                   np.asarray(ref_p[k]),
                                   rtol=1e-4, atol=1e-5)


def test_fsdp_state_really_sharded_and_gathers_on_use(data_mesh):
    """ZeRO semantics, not just numerics: optimizer moments carry the same
    'data'-axis sharding as their params (1/n bytes per device), updated
    params STAY sharded after the step, and the compiled step contains a
    gather/collective for the sharded weight's use."""
    import paddle_tpu.nn as nn
    from paddle_tpu import optimizer as opt_mod

    net = nn.Linear(16, 8)
    params = param_values(net, trainable_only=False)
    pspecs = fsdp_pspecs(net, axis='data', min_size=8)
    sharded = {k: jax.device_put(v, NamedSharding(data_mesh, pspecs[k]))
               for k, v in params.items()}
    opt = opt_mod.AdamW(learning_rate=1e-2)
    state = opt.init_state_values(sharded)

    # 1) every per-element moment inherits the param's sharding: its
    # addressable shard holds 1/n of the rows, not a full replica
    w_key = next(k for k in params if pspecs[k] != P())
    n = data_mesh.shape['data']
    checked = 0
    for slot, sval in state[w_key].items():   # nested: param -> slot dict
        if np.ndim(sval) == np.ndim(params[w_key]):
            assert sval.sharding.spec == pspecs[w_key], (slot, sval.sharding)
            shard = sval.addressable_shards[0].data
            assert shard.shape[0] == sval.shape[0] // n
            checked += 1
    assert checked >= 2, "expected sharded moment1/moment2"

    x = jnp.asarray(np.random.RandomState(0).randn(8, 16), jnp.float32)
    y = jnp.asarray(np.random.RandomState(1).randn(8, 8), jnp.float32)

    def train_step(p, s):
        def loss_of(pv):
            out, _ = functional_call(net, pv, Tensor(x))
            return jnp.mean((out._value - y) ** 2)
        loss, grads = jax.value_and_grad(loss_of)(p)
        new_p, new_s = opt.functional_update(p, grads, s)
        return new_p, new_s, loss

    lowered = jax.jit(train_step).lower(sharded, state)
    hlo = lowered.compile().as_text()
    # 2) using the dim0-sharded weight in the matmul forces communication
    assert ('all-gather' in hlo) or ('all-reduce' in hlo) or \
        ('collective-permute' in hlo) or ('reduce-scatter' in hlo), \
        "no collective in compiled FSDP step — weight silently replicated?"
    new_p, new_s, _ = jax.jit(train_step)(sharded, state)
    # 3) updated params and moments keep the FSDP placement
    # (specs compare via equivalence: P('data',) == P('data', None))
    want = NamedSharding(data_mesh, pspecs[w_key])
    nd = np.ndim(params[w_key])
    assert new_p[w_key].sharding.is_equivalent_to(want, nd)
    for slot, sval in new_s[w_key].items():
        if np.ndim(sval) == nd:
            assert sval.sharding.is_equivalent_to(want, nd), slot
