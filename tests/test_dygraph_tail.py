"""fluid.dygraph namespace tail (NCE/GRUUnit/BilinearTensorProduct/
TreeConv/TracedLayer/decay aliases) + incubate.data_generator."""
import io
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid.dygraph as D


class TestDygraphLayers:
    def test_nce_layer_trains(self):
        rs = np.random.RandomState(0)
        nce = D.NCE(num_total_classes=50, dim=16, num_neg_samples=5,
                    seed=3)
        emb = paddle.nn.Embedding(100, 16)
        opt = paddle.optimizer.Adam(
            learning_rate=0.05,
            parameters=emb.parameters() + nce.parameters())
        ids = paddle.to_tensor(rs.randint(0, 100, (16,)).astype(np.int32))
        ctx = paddle.to_tensor(rs.randint(0, 50, (16, 1))
                               .astype(np.int32))
        losses = []
        for _ in range(15):
            loss = nce(emb(ids), ctx).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss.numpy()))
        assert losses[-1] < losses[0] * 0.6

    def test_18_cell_signatures_hidden_first(self):
        """1.8 dygraph cells take (hidden_size, input_size)."""
        rs = np.random.RandomState(0)
        cell = D.LSTMCell(128, 64)
        h, c = cell(paddle.to_tensor(rs.randn(2, 64).astype(np.float32)),
                    paddle.to_tensor(np.zeros((2, 128), np.float32)),
                    paddle.to_tensor(np.zeros((2, 128), np.float32)))
        assert list(h.shape) == [2, 128]
        g = D.GRUCell(32, 16)
        hn = g(paddle.to_tensor(rs.randn(2, 16).astype(np.float32)),
               paddle.to_tensor(np.zeros((2, 32), np.float32)))
        assert list(hn.shape) == [2, 32]

    def test_prelu_mode_string(self):
        rs = np.random.RandomState(0)
        p = D.PRelu('channel', channel=4)
        out = p(paddle.to_tensor(rs.randn(2, 4, 5, 5).astype(np.float32)))
        assert list(out.shape) == [2, 4, 5, 5]
        pa = D.PRelu('all')
        np.testing.assert_allclose(
            pa(paddle.to_tensor(np.array([-2.0, 3.0], np.float32)))
            .numpy(), [-0.5, 3.0], rtol=1e-6)

    def test_instance_norm_18_positional(self):
        rs = np.random.RandomState(0)
        inorm = D.InstanceNorm(4, 1e-5, None, None)
        out = inorm(paddle.to_tensor(rs.randn(2, 4, 6, 6)
                                     .astype(np.float32)))
        np.testing.assert_allclose(out.numpy().mean(axis=(2, 3)), 0.0,
                                   atol=1e-4)

    def test_nce_resamples_and_weights(self):
        rs = np.random.RandomState(0)
        nce = D.NCE(num_total_classes=50, dim=8, num_neg_samples=5, seed=3)
        x = paddle.to_tensor(rs.randn(4, 8).astype(np.float32))
        lab = paddle.to_tensor(rs.randint(0, 50, (4, 1)).astype(np.int32))
        l1, l2 = nce(x, lab).numpy(), nce(x, lab).numpy()
        assert not np.allclose(l1, l2)      # fresh negatives per call
        sw = np.array([[2.0], [0.0], [1.0], [1.0]], np.float32)
        assert nce(x, lab,
                   sample_weight=paddle.to_tensor(sw)).numpy()[1, 0] == 0.0

    def test_fluid_incubate_import_path(self):
        import paddle_tpu.fluid.incubate.data_generator as dg
        assert hasattr(dg, 'MultiSlotDataGenerator')

    def test_gru_unit_and_bilinear(self):
        rs = np.random.RandomState(0)
        g = D.GRUUnit(size=12)
        hn, rh, gate = g(paddle.to_tensor(rs.randn(3, 12)
                                          .astype(np.float32)),
                         paddle.to_tensor(rs.randn(3, 4)
                                          .astype(np.float32)))
        assert list(hn.shape) == [3, 4]
        b = D.BilinearTensorProduct(4, 5, 6)
        out = b(paddle.to_tensor(rs.randn(2, 4).astype(np.float32)),
                paddle.to_tensor(rs.randn(2, 5).astype(np.float32)))
        assert list(out.shape) == [2, 6]

    def test_tree_conv(self):
        rs = np.random.RandomState(0)
        tc = D.TreeConv(feature_size=8, output_size=4, num_filters=2)
        nodes = paddle.to_tensor(rs.randn(1, 5, 8).astype(np.float32))
        edges = paddle.to_tensor(
            np.array([[[0, 1], [0, 2], [1, 3], [1, 4]]], np.int32))
        out = tc(nodes, edges)
        assert list(out.shape) == [1, 5, 4, 2]
        assert np.isfinite(out.numpy()).all()

    def test_traced_layer_roundtrip(self, tmp_path):
        rs = np.random.RandomState(0)
        net = paddle.nn.Linear(4, 2)
        x = paddle.to_tensor(rs.randn(2, 4).astype(np.float32))
        outs, traced = D.TracedLayer.trace(net, [x])
        y = traced(x)
        np.testing.assert_allclose(np.asarray(y.numpy()),
                                   net(x).numpy(), rtol=1e-6)
        traced.save_inference_model(str(tmp_path / "traced"))
        import paddle_tpu.jit as jit
        loaded = jit.load(str(tmp_path / "traced"))
        np.testing.assert_allclose(loaded(x).numpy(), net(x).numpy(),
                                   rtol=1e-5)

    def test_decay_aliases_resolve(self):
        s = D.ExponentialDecay(0.1, decay_steps=10, decay_rate=0.5)
        for _ in range(10):
            s.step()
        np.testing.assert_allclose(s.last_lr, 0.05, rtol=1e-6)
        assert D.NoamDecay is not None and D.ReduceLROnPlateau is not None

    def test_mode_toggles(self):
        D.disable_dygraph()
        try:
            from paddle_tpu.framework import in_static_mode
            assert in_static_mode()
        finally:
            D.enable_dygraph()


class TestDataGenerator:
    def test_multislot_format(self):
        from paddle_tpu.incubate.data_generator import MultiSlotDataGenerator

        class MyData(MultiSlotDataGenerator):
            def generate_sample(self, line):
                def local_iter():
                    yield [("words", [1926, 8, 17]), ("label", [1])]
                return local_iter

        md = MyData()
        md.set_batch(2)
        buf = io.StringIO()
        old = sys.stdout
        sys.stdout = buf
        try:
            md.run_from_memory()
        finally:
            sys.stdout = old
        assert buf.getvalue() == "3 1926 8 17 1 1\n"
        assert md._proto_info == [("words", "uint64"), ("label", "uint64")]

    def test_string_generator(self):
        from paddle_tpu.incubate.data_generator import \
            MultiSlotStringDataGenerator
        g = MultiSlotStringDataGenerator()
        out = g._gen_str([("words", ["19", "26"]), ("label", ["1"])])
        assert out == "2 19 26 1 1\n"

    def test_field_count_mismatch_raises(self):
        from paddle_tpu.incubate.data_generator import MultiSlotDataGenerator
        g = MultiSlotDataGenerator()
        g._gen_str([("a", [1])])
        with pytest.raises(ValueError, match="field count"):
            g._gen_str([("a", [1]), ("b", [2])])
