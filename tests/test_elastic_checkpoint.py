"""Async resharding checkpoints + elastic world size (ISSUE 14).

Acceptance anchors (docs/RESILIENCE.md, "Elastic training"):

- async saves: ``checkpoint.save_stall_ms`` p50 <= 10% of the synchronous
  baseline under a ``faultinject.slow_fs`` disk; commit still atomic; a
  background failure surfaces on the next save/fence;
- sharded checkpoints: ENOSPC partway through a shard write leaves NO
  visible partial ``ckpt_<step>/`` and the previous checkpoint restorable;
  restore validates the merged CRC manifest before touching state;
- resharding restore matrix (mesh 1<->2<->4, FSDP and FSDP+TP,
  replicated<->sharded both directions): post-restore params/opt-state are
  BITWISE-equal to the saved state, and continued training tracks an
  uninterrupted run (bitwise on the same mesh, allclose across mesh sizes
  whose XLA programs reduce in different orders);
- the preemption fence: an async save in flight when SIGTERM fires is
  finished-or-abandoned BEFORE the preemption checkpoint starts
  (``faultinject.sigterm_at_step`` + ``slow_fs`` regression);
- elastic supervisor: a 4-rank spawn under chaos (rank SIGKILL +
  poisoned/hung DataLoader samples) with ``elastic=True`` completes after
  >= 1 downsize, with the restored boundary state bitwise-equal to the
  uninterrupted reference and the recovery-time histogram populated;
- doctor: ``checkpoint_stall`` (fix-it: async_=True) and
  ``elastic_downsize`` (names the dead rank) detectors, surfaced by
  ``tools/doctor.py --fail-on``; ``tools/ckpt.py`` inspects/verifies and
  dry-runs ``--compat`` resharding.
"""
import json
import os
import subprocess
import sys
import time
import zlib

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu import engine, nn
from paddle_tpu import observability as obs
from paddle_tpu.resilience import CheckpointManager
from paddle_tpu.resilience import async_checkpoint as ac
from paddle_tpu.resilience import faultinject as fi
from paddle_tpu.distributed.strategy import ShardingConfig

pytestmark = pytest.mark.fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def telemetry():
    os.environ['PADDLE_TPU_TELEMETRY'] = '1'
    obs.enable()
    obs.reset()
    yield obs
    obs.disable()
    obs.reset()
    os.environ.pop('PADDLE_TPU_TELEMETRY', None)


def _data(n=6, seed=0):
    rs = np.random.RandomState(seed)
    return [(rs.rand(8, 32).astype('f4'), rs.rand(8, 4).astype('f4'))
            for _ in range(n)]


def _net_opt(seed=7):
    paddle.seed(seed)
    net = nn.Sequential(nn.Linear(32, 64), nn.Tanh(), nn.Linear(64, 4))
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    return net, opt


def _state(nleaves=3, size=4096, seed=0):
    rs = np.random.RandomState(seed)
    return {'params': {('w%d' % i): rs.rand(size // 16, 16).astype('f4')
                       for i in range(nleaves)},
            'buffers': {}, 'opt': {}}


def _host_params(state):
    return {k: np.asarray(v) for k, v in state['params'].items()}


def _mesh_cfg(k, model=1, rules=None):
    if k is None:
        return None
    devs = np.asarray(jax.devices()[:k * model])
    if model > 1:
        mesh = Mesh(devs.reshape(k, model), ('data', 'model'))
    else:
        mesh = Mesh(devs, ('data',))
    return ShardingConfig(mesh=mesh, fsdp=True, min_size=64,
                          param_rules=rules,
                          tensor_parallel_degree=model)


# ---------------------------------------------------------------------------
# async saves
# ---------------------------------------------------------------------------

class TestAsyncSave:
    def test_async_stall_le_10pct_of_sync(self, tmp_path, telemetry):
        """The acceptance ratio: under a slow disk, the async save's
        training-thread stall is <= 10% of the synchronous save's."""
        state = _state(nleaves=4)
        mgr = CheckpointManager(tmp_path / 'sync', max_keep=2)

        def stalls(mgr, async_, compute_s=0.0):
            out = []
            with fi.FaultInjector().slow_fs(0.01, match='ckpt_'):
                for i in range(3):
                    t0 = time.perf_counter()
                    mgr.save(state, step=i, world=1, async_=async_)
                    out.append((time.perf_counter() - t0) * 1000.0)
                    if compute_s:
                        time.sleep(compute_s)
                mgr.fence()
            return sorted(out)[len(out) // 2]

        sync_p50 = stalls(mgr, async_=False)
        amgr = CheckpointManager(tmp_path / 'async', max_keep=2)
        async_p50 = stalls(amgr, async_=True,
                           compute_s=max(0.1, 1.5 * sync_p50 / 1000.0))
        assert async_p50 <= 0.10 * sync_p50, (async_p50, sync_p50)
        # both paths feed the stall histogram; commits recorded either way
        snap = obs.snapshot()['histograms']
        assert snap['checkpoint.save_stall_ms']['count'] == 6
        assert snap['checkpoint.commit_ms']['count'] == 6

    def test_async_commit_is_loadable_and_ordered(self, tmp_path):
        mgr = CheckpointManager(tmp_path, max_keep=10)
        for i in range(3):
            st = _state(seed=i)
            mgr.save(st, step=i, world=1, async_=True)
        mgr.fence()
        assert mgr.steps() == [0, 1, 2]
        got, _ = mgr.load(step=2)
        np.testing.assert_array_equal(got['params']['w0'],
                                      _state(seed=2)['params']['w0'])

    def test_default_step_numbers_see_inflight_commit(self, tmp_path):
        """Regression: save(step=None) must fence BEFORE reading
        latest_step(), or back-to-back async saves on a slow disk both
        pick the same number and silently overwrite each other."""
        mgr = CheckpointManager(tmp_path, max_keep=10)
        with fi.FaultInjector().slow_fs(0.01, match='ckpt_'):
            for i in range(3):
                mgr.save(_state(seed=i), world=1, async_=True)
            mgr.fence()
        assert mgr.steps() == [0, 1, 2]

    def test_background_failure_surfaces_on_fence(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        with fi.FaultInjector().disk_full(after_bytes=64,
                                          match='shard_rank'):
            mgr.save(_state(), step=5, world=1, async_=True)
            with pytest.raises(Exception) as ei:
                mgr.fence()
        assert 'atomic write' in str(ei.value) or 'space' in str(ei.value)
        assert 5 not in mgr.steps()

    def test_donation_secure_copies_jax_leaves(self, monkeypatch):
        monkeypatch.setenv('PADDLE_TPU_DONATE', '1')
        arr = jnp.arange(8.0)
        secured = ac.secure_for_async({'params': {'w': arr}})
        assert secured['params']['w'] is not arr
        np.testing.assert_array_equal(np.asarray(secured['params']['w']),
                                      np.asarray(arr))
        monkeypatch.setenv('PADDLE_TPU_DONATE', '0')
        same = ac.secure_for_async({'params': {'w': arr}})
        assert same['params']['w'] is arr


# ---------------------------------------------------------------------------
# sharded checkpoints: atomicity + validation
# ---------------------------------------------------------------------------

class TestShardedCheckpoint:
    def test_enospc_mid_shard_keeps_previous_restorable(self, tmp_path):
        """Satellite: disk_full partway through a shard write leaves no
        partial ckpt_<step> visible; the previous checkpoint restores."""
        mgr = CheckpointManager(tmp_path)
        first = _state(seed=1)
        mgr.save(first, step=0, world=2)
        with fi.FaultInjector().disk_full(after_bytes=128,
                                          match='shard_rank'):
            with pytest.raises(Exception):
                mgr.save(_state(seed=2), step=1, world=2)
        assert mgr.steps() == [0]
        assert not os.path.exists(tmp_path / 'ckpt_00000001')
        got, _ = mgr.load()
        np.testing.assert_array_equal(got['params']['w0'],
                                      first['params']['w0'])

    def test_corrupt_shard_falls_back_with_warning(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(_state(seed=1), step=0, world=2)
        mgr.save(_state(seed=2), step=1, world=2)
        fi.corrupt_file(tmp_path / 'ckpt_00000001' / 'shard_rank1.npz',
                        offset=-20, nbytes=4)
        with pytest.warns(UserWarning, match='CRC32 mismatch'):
            got, _meta = mgr.load()
        np.testing.assert_array_equal(got['params']['w0'],
                                      _state(seed=1)['params']['w0'])

    def test_truncated_manifest_is_invisible(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(_state(), step=0, world=1)
        man = tmp_path / 'ckpt_00000000' / 'manifest.json'
        fi.truncate_file(man, keep_bytes=20)
        with pytest.warns(UserWarning, match='unreadable manifest'):
            assert mgr.load() is None

    def test_per_rank_writes_and_rank0_barrier_commit(self, tmp_path):
        """Multi-process protocol in one process: ranks 1..3 write their
        shards + markers first; rank 0's save waits for the markers, CRCs
        every shard, and commits the merged manifest."""
        state = _state(nleaves=2, size=4096)
        mgr = CheckpointManager(tmp_path)
        for r in (1, 2, 3):
            assert mgr.save(state, step=7, world=4, rank=r) == 7
        assert mgr.steps() == []          # no manifest yet: invisible
        mgr.save(state, step=7, world=4, rank=0)
        assert mgr.steps() == [7]
        man = mgr.load_manifest(7)
        assert man['world'] == 4 and len(man['shards']) == 4
        # every rank's file really carries pieces (leaves split 4 ways)
        sharded = [leaf for leaf in man['leaves']
                   if len(leaf['pieces']) == 4]
        assert sharded, man['leaves']
        got, _ = mgr.load(step=7)
        for k in state['params']:
            np.testing.assert_array_equal(got['params'][k],
                                          state['params'][k])

    def test_rank0_barrier_times_out_loudly(self, tmp_path):
        from paddle_tpu.resilience.watchdog import WatchdogTimeout
        with pytest.raises(WatchdogTimeout, match='never committed'):
            ac.save_sharded(tmp_path, _state(), step=0, world=3, rank=0,
                            barrier_timeout=0.3)
        # no manifest: the step never became visible
        assert not os.path.exists(
            os.path.join(ac.step_dir(tmp_path, 0), 'manifest.json'))

    def test_rotation_removes_sharded_dirs(self, tmp_path):
        mgr = CheckpointManager(tmp_path, max_keep=2)
        for i in range(4):
            mgr.save(_state(seed=i), step=i, world=1)
        assert mgr.steps() == [2, 3]
        assert not os.path.exists(tmp_path / 'ckpt_00000000')


# ---------------------------------------------------------------------------
# resharding restore matrix
# ---------------------------------------------------------------------------

_TP_RULES = {'2.weight': P(None, 'model')}

# (save config spec, restore config spec): (data_degree|None, model_degree)
_MATRIX = [
    ((1, 1), (2, 1)),          # grow 1 -> 2
    ((2, 1), (4, 1)),          # grow 2 -> 4
    ((4, 1), (2, 1)),          # the elastic downsize: k -> k/2
    ((4, 1), (None, 1)),       # sharded -> replicated
    ((None, 1), (4, 1)),       # replicated -> sharded
    ((4, 1), (4, 1)),          # same mesh (control: bitwise throughout)
    ((2, 2), (1, 2)),          # FSDP+TP: data 2 -> 1, model axis kept
]


class TestReshardingMatrix:
    _cache = {}

    def _run(self, spec, epochs, ckpt_dir=None, resume_from=None, seed=7):
        """``epochs`` epochs over the same 6 batches under the config
        spec; returns (report, params, opt) with host copies.
        Uninterrupted runs are cached per (spec, epochs)."""
        key = (spec, epochs)
        cacheable = resume_from is None and ckpt_dir is None and seed == 7
        if cacheable and key in self._cache:
            return self._cache[key]
        k, model = spec
        cfg = _mesh_cfg(k, model, rules=_TP_RULES if model > 1 else None)
        net, opt = _net_opt(seed=seed)
        report = engine.fit(net, nn.MSELoss(), opt, _data(6),
                            epochs=epochs, prefetch=0, sharding=cfg,
                            checkpoint=ckpt_dir, checkpoint_every=0,
                            async_save=False, resume_from=resume_from,
                            preempt_save=False)
        out = (report, _host_params(report['state']),
               jax.tree_util.tree_map(np.asarray, report['state']['opt']))
        if cacheable:
            self._cache[key] = out
        return out

    @pytest.mark.parametrize('save_spec,restore_spec', _MATRIX,
                             ids=lambda s: 'x'.join(str(x) for x in s))
    def test_post_restore_bitwise_and_continued_loss(self, tmp_path,
                                                     save_spec,
                                                     restore_spec):
        # phase A: train 1 epoch (6 dispatches) under the SAVE config,
        # checkpointing at the epoch boundary
        _repA, paramsA, optA = self._run(save_spec, 1,
                                         ckpt_dir=str(tmp_path))
        mgr = CheckpointManager(str(tmp_path))
        k, model = restore_spec
        cfgB = _mesh_cfg(k, model, rules=_TP_RULES if model > 1 else None)

        # post-restore params/opt-state BITWISE vs the saved state
        got = mgr.restore(sharding=cfgB)
        assert got is not None
        stB, _meta = got
        for name in paramsA:
            np.testing.assert_array_equal(
                paramsA[name], np.asarray(stB['params'][name]),
                err_msg=f'param {name} not bitwise across '
                        f'{save_spec}->{restore_spec}')
        jax.tree_util.tree_map(
            lambda a, b: np.testing.assert_array_equal(a, np.asarray(b)),
            optA, stB['opt'])

        # continue for a second epoch under the RESTORE config, vs an
        # uninterrupted 2-epoch run: bitwise when the save config is the
        # same program; allclose across program boundaries (different
        # mesh sizes reduce grads in different orders, and Adam's lr-sized
        # steps amplify the ulps — the LOSS trajectory is what must track)
        _repU, paramsU, _optU = self._run(restore_spec, 2)
        repC, paramsC, _optC = self._run(restore_spec, 2,
                                         resume_from=str(tmp_path),
                                         seed=31)  # restore overwrites init
        assert repC['resumed_from'] == 6
        same_program = save_spec == restore_spec
        if same_program:
            for name in paramsU:
                np.testing.assert_array_equal(paramsU[name], paramsC[name],
                                              err_msg=name)
        else:
            lossU = self._run(restore_spec, 2)[0]['loss']
            lossC = repC['loss']
            # log points differ in count (the resumed run logs fewer
            # dispatches); compare the final logged losses
            np.testing.assert_allclose(lossU[-1], lossC[-1], rtol=5e-3)
            for name in paramsU:
                np.testing.assert_allclose(paramsU[name], paramsC[name],
                                           rtol=0.2, atol=5e-3,
                                           err_msg=name)
        assert all(np.isfinite(l) for l in repC['loss'])

    def test_tp_layout_survives_restore(self, tmp_path):
        """FSDP+TP: the rule-matched param comes back ON the model axis
        after a resharding restore (the layout IS the parallelism)."""
        self._run((2, 2), 6, ckpt_dir=str(tmp_path))
        cfgB = _mesh_cfg(1, 2, rules=_TP_RULES)
        stB, _ = CheckpointManager(str(tmp_path)).restore(sharding=cfgB)
        sh = stB['params']['2.weight'].sharding
        assert 'model' in (ax for part in sh.spec if part
                           for ax in (part if isinstance(part, tuple)
                                      else (part,)))


# ---------------------------------------------------------------------------
# the preemption fence (bugfix regression)
# ---------------------------------------------------------------------------

class TestPreemptionFence:
    def test_sigterm_fences_inflight_async_save(self, tmp_path, telemetry):
        """Regression: SIGTERM (sigterm_at_step) lands while an async save
        is still committing (slow_fs). The preemption checkpoint must
        fence it first — afterwards every visible ckpt dir is committed
        and the preemption checkpoint is the newest restorable state."""
        net, opt = _net_opt()
        src = fi.sigterm_at_step(_data(n=16), 6)
        with fi.FaultInjector().slow_fs(0.01, match='ckpt_'):
            report = engine.fit(net, nn.MSELoss(), opt, src, epochs=1,
                                prefetch=0, checkpoint=str(tmp_path),
                                checkpoint_every=2, async_save=True)
        assert report['preempted']
        assert report['dispatches'] < 16
        mgr = CheckpointManager(str(tmp_path))
        st, meta = mgr.restore()
        assert meta['dispatches'] == report['dispatches']
        # no partial dirs: everything visible has a committed manifest
        for name in os.listdir(tmp_path):
            if name.startswith('ckpt_'):
                assert os.path.exists(
                    os.path.join(tmp_path, name, 'manifest.json')), name
        # the fence really ran before the preemption save
        fences = [e for e in obs.event_log()
                  if e.get('ev') == 'checkpoint.fence']
        assert fences

    def test_hapi_checkpoint_saver_async_preempt(self, tmp_path):
        """CheckpointSaver(async_save=True): epoch saves ride the
        background thread; the SIGTERM save fences + commits sync and
        resume continues bitwise (the PR 1 contract, now async-safe)."""
        from paddle_tpu.hapi import Model
        from paddle_tpu.hapi.callbacks import CheckpointSaver
        from paddle_tpu.io.dataset import Dataset

        class Pair(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                rs = np.random.RandomState(i)
                return (rs.rand(4).astype('f4'),
                        rs.rand(2).astype('f4'))

        def build():
            paddle.seed(3)
            net = nn.Linear(4, 2)
            m = Model(net)
            m.prepare(optimizer=paddle.optimizer.SGD(
                learning_rate=0.1, parameters=net.parameters()),
                loss=nn.MSELoss())
            return m

        # reference: 3 uninterrupted epochs
        ref = build()
        ref.fit(Pair(), epochs=3, batch_size=4, verbose=0, shuffle=False)
        ref_w = {k: v.numpy().copy()
                 for k, v in ref.network.state_dict().items()}

        saver = CheckpointSaver(str(tmp_path), save_freq=1,
                                async_save=True)
        m = build()
        with fi.FaultInjector().slow_fs(0.005, match=str(tmp_path)):
            m.fit(Pair(), epochs=3, batch_size=4, verbose=0, shuffle=False,
                  callbacks=[saver, fi.PreemptAtStep(3)])
        assert saver.preempted
        m2 = build()
        m2.fit(Pair(), epochs=3, batch_size=4, verbose=0, shuffle=False,
               callbacks=[CheckpointSaver(str(tmp_path), save_freq=1)],
               resume_from=str(tmp_path))
        for k, v in m2.network.state_dict().items():
            np.testing.assert_array_equal(ref_w[k], v.numpy(), err_msg=k)

    def test_sync_save_fences_previous_async(self, tmp_path):
        """Ordering: a sync save issued while an async one is in flight
        waits for it — step N can never land after step N+1."""
        mgr = CheckpointManager(tmp_path, max_keep=10)
        with fi.FaultInjector().slow_fs(0.01, match='ckpt_'):
            mgr.save(_state(seed=0), step=0, world=1, async_=True)
            mgr.save(_state(seed=1), step=1, world=1)   # sync: must fence
        assert mgr.steps() == [0, 1]
        assert not mgr.in_flight()


# ---------------------------------------------------------------------------
# elastic supervisor: chaos soak + rejoin
# ---------------------------------------------------------------------------

def _soak_worker(ckpt_dir, kill_marker):
    """Chaos-soak rank: deterministic training via engine.fit with
    world-sharded async checkpoints, fed through a DataLoader whose
    dataset is poisoned (quarantined) and briefly hung (watchdog-sized);
    rank 1 SIGKILLs itself once at a mid-run step."""
    import numpy as np
    import zlib
    import paddle_tpu as paddle
    from paddle_tpu import engine as eng, nn as pnn
    from paddle_tpu.resilience import faultinject as f

    rank = int(os.environ.get('PADDLE_TRAINER_ID', '0'))
    world = int(os.environ.get('PADDLE_TRAINERS_NUM', '1'))
    gen = int(os.environ.get('PADDLE_TPU_ELASTIC_GENERATION', '0'))
    rs = np.random.RandomState(0)
    batches = [(rs.rand(8, 32).astype('f4'), rs.rand(8, 4).astype('f4'))
               for _ in range(6)]
    maybe_die = f.kill_rank_at_step(9, kill_marker, rank=1)
    seen = [0]

    class Chaos:
        def __iter__(self):
            for i, b in enumerate(batches):
                maybe_die(seen[0])
                seen[0] += 1
                if i == 2:
                    time.sleep(0.05)        # hung-worker flavor (bounded)
                yield b

    paddle.seed(7)
    net = pnn.Sequential(pnn.Linear(32, 64), pnn.Tanh(),
                         pnn.Linear(64, 4))
    opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                parameters=net.parameters())
    report = eng.fit(net, pnn.MSELoss(), opt, Chaos(), epochs=3,
                     prefetch=0, checkpoint=ckpt_dir, checkpoint_every=0,
                     async_save=True, resume_from=ckpt_dir, world=world,
                     rank=rank, preempt_save=False)
    crc = 0
    for k in sorted(report['state']['params']):
        crc = zlib.crc32(np.ascontiguousarray(
            np.asarray(report['state']['params'][k])).tobytes(), crc)
    return (rank, world, gen, crc & 0xFFFFFFFF,
            report['resumed_from'])


def _idle_worker(seconds):
    rank = int(os.environ.get('PADDLE_TRAINER_ID', '0'))
    gen = int(os.environ.get('PADDLE_TPU_ELASTIC_GENERATION', '0'))
    if rank == 1 and gen == 0:
        os._exit(17)
    for _ in range(int(seconds * 10)):
        time.sleep(0.1)
    return (rank, int(os.environ.get('PADDLE_TRAINERS_NUM', '1')), gen)


@pytest.mark.skipif(sys.platform == 'win32', reason='posix only')
class TestElasticSupervisor:
    def test_chaos_soak_downsizes_and_finishes_bitwise(self, tmp_path,
                                                       telemetry):
        """THE acceptance test: 4 ranks, rank 1 SIGKILLed mid-run, job
        completes on 3 survivors after one downsize; final params bitwise
        == an uninterrupted single-process reference; the state restored
        at the downsize boundary is bitwise-equal to the reference run at
        that step; recovery-time histogram populated."""
        import paddle_tpu.distributed as dist
        ckpt = str(tmp_path / 'ckpts')
        marker = str(tmp_path / 'killed')
        ctx = dist.spawn(_soak_worker, (ckpt, marker), nprocs=4,
                         backend='cpu', join=False, elastic=True,
                         max_restarts=2)
        results = ctx.join(timeout=240)
        sup = ctx._supervisor
        assert os.path.exists(marker)            # the kill really fired
        assert sup.downsizes >= 1
        assert len(results) == 3                 # world shrank 4 -> 3
        assert all(r is not None for r in results)
        crcs = {r[3] for r in results}
        assert len(crcs) == 1                    # survivors agree bitwise

        # uninterrupted reference (single process, no chaos, same math)
        ref_dir = str(tmp_path / 'ref')
        ref = _soak_worker(os.path.join(ref_dir, 'ck'),
                           os.path.join(ref_dir, 'killed'))
        assert ref[3] in crcs                    # bitwise vs uninterrupted

        # the downsize boundary: what generation 1 restored is bitwise
        # identical to the reference run's state at that checkpoint step
        resumed_step = results[0][4]
        assert resumed_step is not None
        restored, _meta = CheckpointManager(ckpt).restore(step=resumed_step)
        ref_ck, _ = CheckpointManager(
            os.path.join(ref_dir, 'ck')).restore(step=resumed_step)
        for k in restored['params']:
            np.testing.assert_array_equal(restored['params'][k],
                                          ref_ck['params'][k], err_msg=k)

        snap = obs.snapshot()
        assert snap['histograms']['elastic.recovery_ms']['count'] >= 1
        assert snap['counters']['distributed.elastic_downsizes'] >= 1
        evs = [e['ev'] for e in obs.event_log()
               if str(e.get('ev', '')).startswith('elastic.')]
        assert 'elastic.rank_death' in evs and 'elastic.downsize' in evs \
            and 'elastic.relaunch' in evs

    def test_rejoin_keeps_world_size(self, tmp_path, telemetry):
        """A rejoin marker inside the grace window re-claims the dead
        slot: the new generation keeps the old world size (no downsize)."""
        import paddle_tpu.distributed as dist
        ctx = dist.spawn(_idle_worker, (0.5,), nprocs=2, backend='cpu',
                         join=False, elastic=True, max_restarts=1,
                         rejoin_grace_s=15.0)
        run_dir = ctx._result_dir
        # pre-arm the replacement offer: _wait_rejoin consumes it the
        # moment the death opens the grace window
        with open(os.path.join(run_dir, 'rejoin_any'), 'w'):
            pass
        results = ctx.join(timeout=120)
        sup = ctx._supervisor
        assert len(results) == 2                 # world size kept
        assert sup.downsizes == 0
        assert sup.generation == 1
        assert [r[2] for r in results] == [1, 1]
        evs = [e['ev'] for e in obs.event_log()]
        assert 'elastic.rejoin' in evs

    def test_budget_exhausted_fails_fast(self, tmp_path):
        """elastic with max_restarts=0... the budget still bounds it: the
        supervisor falls back to the fail-fast RankFailedError."""
        import paddle_tpu.distributed as dist

        ctx = dist.spawn(_always_dying_worker, (), nprocs=2, backend='cpu',
                         join=False, elastic=True, max_restarts=1)
        with pytest.raises(dist.RankFailedError):
            ctx.join(timeout=120)


def _always_dying_worker():
    # rank 0 dies in EVERY generation (it exists at every world size), so
    # the restart budget must eventually exhaust into a fail-fast
    rank = int(os.environ.get('PADDLE_TRAINER_ID', '0'))
    if rank == 0:
        os._exit(23)
    time.sleep(2.0)
    return rank


# ---------------------------------------------------------------------------
# doctor + CLIs
# ---------------------------------------------------------------------------

class TestDoctorDetectors:
    def test_checkpoint_stall_fires_and_names_async_fix(self):
        snapshot = {'histograms': {
            'checkpoint.save_stall_ms': {'count': 4, 'mean': 50.0,
                                         'sum': 200.0, 'p50': 50.0},
            'hapi.step_ms': {'count': 100, 'mean': 100.0, 'sum': 1e4,
                             'p50': 100.0}}, 'counters': {}, 'gauges': {}}
        found = [d for d in obs.diagnose(snapshot=snapshot)
                 if d['cause'] == 'checkpoint_stall']
        assert found and 'async_=True' in found[0]['fix']
        assert found[0]['severity'] == 'warning'

    def test_checkpoint_stall_quiet_when_async(self):
        snapshot = {'histograms': {
            'checkpoint.save_stall_ms': {'count': 4, 'mean': 0.5,
                                         'sum': 2.0, 'p50': 0.5},
            'hapi.step_ms': {'count': 100, 'mean': 100.0, 'sum': 1e4,
                             'p50': 100.0}}, 'counters': {}, 'gauges': {}}
        assert not [d for d in obs.diagnose(snapshot=snapshot)
                    if d['cause'] == 'checkpoint_stall']

    def test_elastic_downsize_info_names_dead_rank(self):
        events = [{'ev': 'elastic.downsize', 'dead_rank': 2,
                   'old_world': 4, 'new_world': 3, 'signal': 'SIGKILL'}]
        found = [d for d in obs.diagnose(events=events)
                 if d['cause'] == 'elastic_downsize']
        assert found and found[0]['severity'] == 'info'
        assert 'rank 2' in found[0]['detail']
        assert found[0]['evidence']['dead_rank'] == 2

    def test_doctor_cli_fail_on_elastic_downsize(self, tmp_path):
        log = tmp_path / 'events.jsonl'
        log.write_text(json.dumps(
            {'ev': 'elastic.downsize', 'dead_rank': 1, 'old_world': 4,
             'new_world': 3}) + '\n')
        out = subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools', 'doctor.py'),
             str(log), '--fail-on', 'elastic_downsize'],
            capture_output=True, text=True)
        assert out.returncode == 1, out.stdout + out.stderr
        assert 'elastic_downsize' in out.stdout


class TestCkptCLI:
    def _cli(self, *args):
        return subprocess.run(
            [sys.executable, os.path.join(REPO, 'tools', 'ckpt.py')]
            + [str(a) for a in args], capture_output=True, text=True)

    def test_inspect_verify_and_compat(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(_state(nleaves=2), step=3, world=4,
                 meta={'epoch': 2})
        out = self._cli(tmp_path, '--verify', '--compat', '2')
        assert out.returncode == 0, out.stderr
        assert 'format 2' in out.stdout and 'shards 4' in out.stdout
        assert 'OK ' in out.stdout and 'feasible' in out.stdout
        assert "'epoch': 2" in out.stdout
        j = self._cli(tmp_path, '--json', '--compat', 'data=2')
        data = json.loads(j.stdout)
        assert data[0]['compat']['degree'] == 2

    def test_corrupt_shard_exits_nonzero(self, tmp_path):
        mgr = CheckpointManager(tmp_path)
        mgr.save(_state(), step=0, world=2)
        fi.corrupt_file(tmp_path / 'ckpt_00000000' / 'shard_rank0.npz',
                        offset=-10, nbytes=2)
        out = self._cli(tmp_path, '--verify')
        assert out.returncode == 1
        assert 'BAD' in out.stdout


# ---------------------------------------------------------------------------
# frontends
# ---------------------------------------------------------------------------

class TestFrontendWiring:
    def test_train_step_restore_state_across_meshes(self, tmp_path):
        """build_train_step + restore_state: the step compiles against the
        restored structure and places it per ITS config."""
        from paddle_tpu.nn.layer_base import buffer_values, param_values
        from paddle_tpu.core import rng as prng
        cfgA = _mesh_cfg(4, 1)
        net, opt = _net_opt()
        stepA = engine.build_train_step(net=net, loss=nn.MSELoss(),
                                        optimizer=opt, sharding=cfgA)
        state = stepA.init_state(param_values(net), buffer_values(net))
        for x, y in _data(3):
            state, out = stepA(state, ((x,), (y,)), prng.next_key())
        float(out.loss)
        mgr = CheckpointManager(tmp_path)
        mgr.save(state, step=0, sharding=cfgA)

        cfgB = _mesh_cfg(2, 1)
        netB, optB = _net_opt(seed=11)
        stepB = engine.build_train_step(net=netB, loss=nn.MSELoss(),
                                        optimizer=optB, sharding=cfgB)
        restored, meta = stepB.restore_state(mgr)
        for k, v in state['params'].items():
            np.testing.assert_array_equal(np.asarray(v),
                                          np.asarray(restored['params'][k]))
        # and it dispatches: the sharded program was compiled by adoption
        restored, out = stepB(restored, ((_data(1)[0][0],),
                                         (_data(1)[0][1],)),
                              prng.next_key())
        assert np.isfinite(float(out.loss))

    def test_rng_exact_resume_with_dropout(self, tmp_path):
        """Regression: a checkpoint carrying ``extra`` (RNG streams) is
        promoted to the manifest format even unsharded — a dropout net's
        resumed run must draw the SAME keys as the uninterrupted one."""
        def build(seed=7):
            paddle.seed(seed)
            net = nn.Sequential(nn.Linear(32, 64), nn.Dropout(0.3),
                                nn.Linear(64, 4))
            opt = paddle.optimizer.Adam(learning_rate=1e-2,
                                        parameters=net.parameters())
            return net, opt

        net, opt = build()
        full = engine.fit(net, nn.MSELoss(), opt, _data(6), epochs=2,
                          prefetch=0)
        net, opt = build()
        engine.fit(net, nn.MSELoss(), opt, _data(6), epochs=1, prefetch=0,
                   checkpoint=str(tmp_path))
        net2, opt2 = build(seed=99)
        resumed = engine.fit(net2, nn.MSELoss(), opt2, _data(6), epochs=2,
                             prefetch=0, resume_from=str(tmp_path))
        for k, v in full['state']['params'].items():
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(resumed['state']['params'][k]),
                err_msg=k)

    def test_model_fit_resumes_from_engine_checkpoint(self, tmp_path):
        """Model.fit(resume_from=) adopts an engine-layout sharded
        checkpoint (params + functional opt slots) saved on another
        mesh."""
        from paddle_tpu.hapi import Model
        net, opt = _net_opt()
        report = engine.fit(net, nn.MSELoss(), opt, _data(4), epochs=1,
                            prefetch=0, sharding=_mesh_cfg(4, 1),
                            checkpoint=str(tmp_path), checkpoint_every=0,
                            preempt_save=False)
        trained = _host_params(report['state'])

        paddle.seed(123)
        net2 = nn.Sequential(nn.Linear(32, 64), nn.Tanh(),
                             nn.Linear(64, 4))
        m = Model(net2)
        m.prepare(optimizer=paddle.optimizer.Adam(
            learning_rate=1e-2, parameters=net2.parameters()),
            loss=nn.MSELoss())
        m.fit(None, epochs=0, verbose=0, resume_from=str(tmp_path))
        for k, v in net2.state_dict().items():
            if k in trained:
                np.testing.assert_array_equal(trained[k], v.numpy(),
                                              err_msg=k)
