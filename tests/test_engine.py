"""engine.build_train_step: the unified zero-stall train-step compiler.

Acceptance anchors (ISSUE 9 / docs/PERF.md):

- hapi ``Model.fit(jit=True)``, the eager convenience loop (``engine.fit``)
  and the static ``Executor`` train path all route through ONE builder:
  the two compiled frontends are bitwise-identical and ``jax.compiles``
  stops growing after warmup on all three (the tier-1 retrace gate);
- the jit fit loop fetches the loss at log cadence only: steady-state
  steps transfer 0 host bytes (proven via the PR 3 interposed counter);
- the NaN guard skips poisoned steps IN-GRAPH (lax.cond state select) —
  no host-side rollback snapshot, donation-compatible — while keeping the
  NanStepError consecutive-limit and GradScaler cooperation semantics;
- the device-feed prefetcher drops the consumer-side dataloader wait
  (``dataloader.next_wait_ms`` p50) under ``faultinject.slow_loader``.
"""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import engine, nn, static
from paddle_tpu import observability as obs
from paddle_tpu.nn.functional import mse_loss
from paddle_tpu.resilience import NanGuard, NanStepError
from paddle_tpu.resilience.nanguard import _obs as _nan_obs  # noqa: F401


@pytest.fixture(autouse=True)
def _telemetry_off():
    yield
    obs.disable()
    obs.reset()


def _enable():
    obs.reset()
    obs.enable()


def _data(n=5, batch=8, feat=3, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.rand(batch, feat).astype('float32'),
             rng.rand(batch, 1).astype('float32')) for _ in range(n)]


def _eager_net():
    paddle.seed(42)
    net = nn.Linear(3, 1)
    init = [np.asarray(p.numpy()).copy() for p in net.parameters()]
    return net, init


def _compiles():
    return obs.snapshot()['counters'].get('jax.compiles', 0)


# ---------------------------------------------------------------------------
# one step builder, three frontends
# ---------------------------------------------------------------------------

def _run_eager(data):
    net, init = _eager_net()
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=net.parameters()),
              loss=nn.MSELoss())
    for x, y in data:
        m.train_batch([x], [y])
    return init, [np.asarray(p.numpy()) for p in net.parameters()]


def _run_hapi_jit(data):
    net, _ = _eager_net()
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=net.parameters()),
              loss=nn.MSELoss(), jit=True)
    for x, y in data:
        m.train_batch([x], [y])
    m._sync_jit_state()
    return [np.asarray(p.numpy()) for p in net.parameters()]


def _run_engine_fit(data):
    net, _ = _eager_net()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    report = engine.fit(net, nn.MSELoss(), opt,
                        [([x], [y]) for x, y in data],
                        epochs=1, log_every=2, prefetch=0)
    return [np.asarray(p.numpy()) for p in net.parameters()], report


def _build_static_program(batch=8, feat=3):
    main = static.Program()
    with static.program_guard(main):
        x = static.data('x', [batch, feat], 'float32')
        label = static.data('label', [batch, 1], 'float32')
        pred = static.nn.fc(x, size=1)
        loss = mse_loss(pred, label)
        opt = paddle.optimizer.SGD(learning_rate=0.1)
        opt.minimize(loss)
    return main, loss


def _run_executor(data, init):
    paddle.enable_static()
    try:
        import jax.numpy as jnp
        main, loss = _build_static_program()
        exe = static.Executor()
        pvars = [v for v in main.list_vars()
                 if v.concrete is not None and
                 getattr(v.concrete, 'trainable', False)]
        by_shape = {tuple(np.asarray(i).shape): i for i in init}
        for v in pvars:
            v.concrete._inplace_value(
                jnp.asarray(by_shape[tuple(v.concrete._value.shape)]))
        for x, y in data:
            exe.run(main, feed={'x': x, 'label': y}, fetch_list=[loss])
        got = {tuple(np.asarray(v.concrete._value).shape):
               np.asarray(v.concrete._value) for v in pvars}
        return [got[tuple(np.asarray(i).shape)] for i in init]
    finally:
        paddle.disable_static()


def test_three_frontends_one_step_parity():
    """The unified-builder guarantee: the hapi jit step, the engine fit
    loop, and the Executor train path produce BITWISE-identical params
    (they are literally the same compiled update); the eager tape path
    stays within float32 ulp noise of them (XLA fuses the compiled graph
    differently than per-op dispatch)."""
    data = _data()
    init, eager = _run_eager(data)
    jit = _run_hapi_jit(data)
    loop, report = _run_engine_fit(data)
    execp = _run_executor(data, init)
    for a, b in zip(jit, loop):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(jit, execp):
        np.testing.assert_array_equal(a, b)
    for a, b in zip(jit, eager):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)
    assert report['steps'] == len(data)
    assert report['compiled_signatures'] == 1


# ---------------------------------------------------------------------------
# tier-1 perf gate: compiles stop growing after warmup, all three frontends
# ---------------------------------------------------------------------------

@pytest.mark.obs
def test_compiles_flat_after_warmup_hapi_jit():
    _enable()
    net, _ = _eager_net()
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=net.parameters()),
              loss=nn.MSELoss(), jit=True)
    data = _data(n=13)
    for x, y in data[:3]:
        m.train_batch([x], [y])
    warm = _compiles()
    assert warm > 0    # the step really compiled in this process
    for x, y in data[3:]:
        m.train_batch([x], [y])
    assert _compiles() == warm, "hapi jit frontend retraced after warmup"


@pytest.mark.obs
def test_compiles_flat_after_warmup_engine_loop():
    _enable()
    net, _ = _eager_net()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    step = engine.build_train_step(net=net, loss=nn.MSELoss(),
                                   optimizer=opt)
    from paddle_tpu.core import rng as _rng
    from paddle_tpu.nn.layer_base import buffer_values, param_values
    pv = param_values(net)
    state = step.init_state(pv, buffer_values(net))
    data = _data(n=13)
    for x, y in data[:3]:
        state, _ = step(state, ((x,), (y,)), _rng.next_key())
    warm = _compiles()
    assert warm > 0
    for x, y in data[3:]:
        state, _ = step(state, ((x,), (y,)), _rng.next_key())
    assert _compiles() == warm, "engine frontend retraced after warmup"
    assert step.cache_size() == 1


@pytest.mark.obs
def test_compiles_flat_after_warmup_executor():
    _enable()
    paddle.enable_static()
    try:
        main, loss = _build_static_program()
        exe = static.Executor()
        data = _data(n=13)
        for x, y in data[:3]:
            exe.run(main, feed={'x': x, 'label': y}, fetch_list=[loss])
        warm = _compiles()
        assert warm > 0
        for x, y in data[3:]:
            exe.run(main, feed={'x': x, 'label': y}, fetch_list=[loss])
        assert _compiles() == warm, "Executor frontend retraced after warmup"
    finally:
        paddle.disable_static()


# ---------------------------------------------------------------------------
# log-cadence host sync: steady-state steps transfer 0 bytes
# ---------------------------------------------------------------------------

class _TransferProbe(paddle.callbacks.Callback):
    """Per-step host-transfer byte deltas, measured across each batch."""

    def __init__(self):
        super().__init__()
        self.deltas = []
        self._before = 0

    def _bytes(self):
        return obs.snapshot()['counters'].get('host_transfer.bytes', 0)

    def on_train_batch_begin(self, step, logs=None):
        self._before = self._bytes()

    def on_train_batch_end(self, step, logs=None):
        self.deltas.append(self._bytes() - self._before)


@pytest.mark.obs
def test_jit_fit_loss_fetch_moves_to_log_cadence():
    """The old _jit_train_batch paid float(np.asarray(loss)) on EVERY
    step. Now the loss rides the engine's DeviceLoss: with telemetry on,
    a 10-step fit with log_freq=5 transfers bytes only on the logging
    steps (0, 5) — every other step moves 0 bytes to the host."""
    _enable()
    net, _ = _eager_net()
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=net.parameters()),
              loss=nn.MSELoss(), jit=True)
    probe = _TransferProbe()
    data = _data(n=10)
    m.fit(data, batch_size=None, epochs=1, log_freq=5, verbose=0,
          shuffle=False, callbacks=[probe])
    assert len(probe.deltas) == 10
    for step, delta in enumerate(probe.deltas):
        if step % 5 == 0:
            assert delta > 0, f"logging step {step} fetched nothing"
        else:
            assert delta == 0, \
                f"non-logging step {step} transferred {delta} bytes"
    # the fetches are attributed to the engine's loss-fetch waist
    snap = obs.snapshot()['counters']
    assert snap.get('host_transfer.engine.loss_fetch.bytes', 0) > 0
    # the step events carry the loss exactly on the materialized steps
    losses = [r for r in obs.event_log() if r.get('ev') == 'step']
    with_loss = [r['step'] for r in losses if 'loss' in r]
    assert 0 in with_loss and all(s % 5 == 0 for s in with_loss[:-1])


def test_device_loss_is_lazy_and_counted():
    _enable()
    import jax.numpy as jnp
    dl = engine.DeviceLoss(jnp.float32(1.5))
    assert not dl.is_ready()
    before = obs.snapshot()['counters'].get('host_transfer.bytes', 0)
    assert float(dl) == 1.5
    after = obs.snapshot()['counters'].get('host_transfer.bytes', 0)
    assert after - before == 4
    assert dl.is_ready()
    assert float(dl) == 1.5     # cached: no second transfer
    assert obs.snapshot()['counters']['host_transfer.bytes'] == after


# ---------------------------------------------------------------------------
# in-graph NaN guard: donation-safe skip, preserved host semantics
# ---------------------------------------------------------------------------

@pytest.mark.fault
def test_in_graph_guard_skips_without_rollback_snapshot():
    """A poisoned step selects the pre-step state via lax.cond inside the
    compiled step — params stay clean with NO host-side prev_state
    snapshot (the donation hazard the old rollback had)."""
    net, _ = _eager_net()
    guard = NanGuard(max_consecutive_skips=5, verbose=False)
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=net.parameters()),
              loss=nn.MSELoss(), jit=True, nan_guard=guard)
    (x, y), = _data(n=1)
    m.train_batch([x], [y])
    m._sync_jit_state()
    before = [np.asarray(p.numpy()).copy() for p in net.parameters()]
    bad = np.full_like(x, np.nan)
    losses, _ = m.train_batch([bad], [y])
    assert np.isnan(losses[0])
    m._sync_jit_state()
    for a, b in zip(before, [np.asarray(p.numpy())
                             for p in net.parameters()]):
        np.testing.assert_array_equal(a, b)
    assert guard.skipped_steps == 1 and guard.consecutive_skips == 1
    # a clean step resets the consecutive count (same as the eager guard)
    m.train_batch([x], [y])
    assert guard.consecutive_skips == 0 and guard.skipped_steps == 1


@pytest.mark.fault
def test_in_graph_guard_consecutive_limit_still_raises():
    net, _ = _eager_net()
    guard = NanGuard(max_consecutive_skips=2, verbose=False)
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=net.parameters()),
              loss=nn.MSELoss(), jit=True, nan_guard=guard)
    (x, y), = _data(n=1)
    bad = np.full_like(x, np.nan)
    m.train_batch([bad], [y])
    with pytest.raises(NanStepError):
        m.train_batch([bad], [y])
    # after the abort the functional state still holds finite params
    m._sync_jit_state()
    for p in net.parameters():
        assert np.isfinite(np.asarray(p.numpy())).all()


@pytest.mark.fault
def test_guard_scaler_cooperation_scale_decays_in_graph():
    """jit + AMP: the GradScaler is folded INTO the step — a poisoned step
    takes the found-inf decrement path on device, and the host scaler
    object sees the decayed scale after the cadence sync (the
    mark_found_inf cooperation contract, now graph-side)."""
    from paddle_tpu.amp import GradScaler
    net, _ = _eager_net()
    scaler = GradScaler(init_loss_scaling=256.0,
                        decr_every_n_nan_or_inf=1, incr_every_n_steps=1000)
    guard = NanGuard(max_consecutive_skips=10, verbose=False)
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=net.parameters()),
              loss=nn.MSELoss(), jit=True, amp_configs=scaler,
              nan_guard=guard)
    (x, y), = _data(n=1)
    m.train_batch([x], [y])
    assert scaler.get_loss_scaling() == 256.0
    m._sync_jit_state()
    before = [np.asarray(p.numpy()).copy() for p in net.parameters()]
    bad = np.full_like(x, np.nan)
    m.train_batch([bad], [y])
    assert scaler.get_loss_scaling() == 128.0     # decayed once, not twice
    assert guard.skipped_steps == 1
    m._sync_jit_state()
    for a, b in zip(before, [np.asarray(p.numpy())
                             for p in net.parameters()]):
        np.testing.assert_array_equal(a, b)       # poisoned update skipped


def test_scaler_dynamic_growth_matches_eager_policy():
    from paddle_tpu.amp import GradScaler
    net, _ = _eager_net()
    scaler = GradScaler(init_loss_scaling=8.0, incr_every_n_steps=2,
                        incr_ratio=2.0)
    m = paddle.Model(net)
    m.prepare(optimizer=paddle.optimizer.SGD(learning_rate=0.1,
                                             parameters=net.parameters()),
              loss=nn.MSELoss(), jit=True, amp_configs=scaler)
    data = _data(n=4)
    for x, y in data:
        m.train_batch([x], [y])
    # 4 clean steps at incr_every=2 -> two doublings, like eager update()
    assert scaler.get_loss_scaling() == 32.0


# ---------------------------------------------------------------------------
# scan microbatching + remat + donation gate
# ---------------------------------------------------------------------------

def test_microbatch_scan_matches_sequential_steps():
    import jax.numpy as jnp
    from paddle_tpu.core import rng as _rng
    from paddle_tpu.nn.layer_base import buffer_values, param_values

    data = _data(n=4)
    paddle.seed(9)
    keys = [_rng.next_key() for _ in range(4)]

    def build(k):
        net, _ = _eager_net()
        opt = paddle.optimizer.Adam(learning_rate=0.01,
                                    parameters=net.parameters())
        step = engine.build_train_step(net=net, loss=nn.MSELoss(),
                                       optimizer=opt, microbatch=k)
        pv = param_values(net)
        return net, step, step.init_state(pv, buffer_values(net))

    net1, one, st1 = build(1)
    for (x, y), key in zip(data, keys):
        st1, _ = one(st1, ((x,), (y,)), key)

    net4, four, st4 = build(4)
    bx = (np.stack([x for x, _ in data]),)
    by = (np.stack([y for _, y in data]),)
    st4, out = four(st4, (bx, by), jnp.stack(keys))
    assert out.losses.shape == (4,)
    assert out.outputs is None    # k>1 keeps only the losses on device
    for a, b in zip(sorted(st1['params']), sorted(st4['params'])):
        np.testing.assert_allclose(np.asarray(st1['params'][a]),
                                   np.asarray(st4['params'][b]),
                                   rtol=1e-6, atol=1e-7)


def test_remat_policy_is_numerically_transparent():
    from paddle_tpu.core import rng as _rng
    from paddle_tpu.nn.layer_base import buffer_values, param_values

    data = _data(n=3)
    keys = [_rng.next_key() for _ in range(3)]

    def run(remat):
        net, _ = _eager_net()
        opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=net.parameters())
        step = engine.build_train_step(net=net, loss=nn.MSELoss(),
                                       optimizer=opt, remat=remat)
        pv = param_values(net)
        st = step.init_state(pv, buffer_values(net))
        for (x, y), key in zip(data, keys):
            st, _ = step(st, ((x,), (y,)), key)
        return st['params']

    base = run(None)
    for policy in ('full', 'dots'):
        got = run(policy)
        for k in base:
            np.testing.assert_allclose(np.asarray(base[k]),
                                       np.asarray(got[k]),
                                       rtol=1e-6, atol=1e-7)
    with pytest.raises(ValueError):
        run('bogus-policy')


def test_donation_gate_follows_backend_and_env(monkeypatch):
    monkeypatch.delenv('PADDLE_TPU_DONATE', raising=False)
    assert engine.donation_supported('tpu') is True
    assert engine.donation_supported('gpu') is True
    assert engine.donation_supported('cpu') is False
    monkeypatch.setenv('PADDLE_TPU_DONATE', '0')
    assert engine.donation_supported('tpu') is False
    monkeypatch.setenv('PADDLE_TPU_DONATE', '1')
    assert engine.donation_supported('cpu') is True


def test_donation_smoke_guarded_by_backend_capability():
    """On a donating backend the pre-step param buffer must be invalidated
    (proof the update is in-place); on CPU the gate keeps donation off and
    the state survives."""
    import jax
    from paddle_tpu.core import rng as _rng
    from paddle_tpu.nn.layer_base import buffer_values, param_values
    net, _ = _eager_net()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    step = engine.build_train_step(net=net, loss=nn.MSELoss(),
                                   optimizer=opt)
    pv = param_values(net)
    state = step.init_state(pv, buffer_values(net))
    donated_inputs = list(state['params'].values())
    (x, y), = _data(n=1)
    state, _ = step(state, ((x,), (y,)), _rng.next_key())
    if engine.donation_supported():
        assert step.donates
        assert all(buf.is_deleted() for buf in donated_inputs)
    else:
        assert not step.donates
        assert all(not buf.is_deleted() for buf in donated_inputs)
        # a second dispatch over the same state must stay valid
        state, _ = step(state, ((x,), (y,)), _rng.next_key())


def test_matmul_preference_env_and_backend(monkeypatch):
    monkeypatch.delenv('PADDLE_TPU_MATMUL_PRECISION', raising=False)
    assert engine.matmul_preference('tpu') == 'bfloat16'
    assert engine.matmul_preference('cpu') is None
    monkeypatch.setenv('PADDLE_TPU_MATMUL_PRECISION', 'float32')
    assert engine.matmul_preference('tpu') == 'float32'
    monkeypatch.setenv('PADDLE_TPU_MATMUL_PRECISION', '')
    assert engine.matmul_preference('tpu') is None


# ---------------------------------------------------------------------------
# device-feed prefetch: the accelerator never waits on host assembly
# ---------------------------------------------------------------------------

def _consume_with_work(loader, work_s):
    n = 0
    for _ in loader:
        time.sleep(work_s)    # stands in for the device step
        n += 1
    return n


@pytest.mark.obs
@pytest.mark.fault
def test_prefetch_overlap_drops_dataloader_wait():
    """faultinject.slow_loader makes every sample cost 20 ms of host time.
    Without prefetch the consumer eats that wait on every next(); with the
    background device-feed prefetcher the assembly overlaps the consumer's
    compute and the dataloader.next_wait_ms p50 collapses."""
    from paddle_tpu.io import DataLoader
    from paddle_tpu.resilience import faultinject

    samples = [(np.ones((4,), np.float32), np.float32(1.0))
               for _ in range(8)]
    slow = faultinject.slow_loader(samples, 0.01)

    def p50(prefetch):
        _enable()
        loader = DataLoader(slow, batch_size=2, shuffle=False,
                            prefetch_to_device=prefetch)
        assert _consume_with_work(loader, 0.03) == 4
        return obs.snapshot()['histograms']['dataloader.next_wait_ms']['p50']

    plain = p50(0)
    overlapped = p50(2)
    # 2 samples x 10ms per batch: the plain consumer waits ~20ms; the
    # prefetched consumer's wait hides inside its 30ms of "compute"
    assert plain >= 15.0, plain
    assert overlapped < plain * 0.5, (plain, overlapped)


def test_prefetcher_propagates_source_failures():
    from paddle_tpu.io.dataloader import (DataLoaderWorkerError,
                                          DevicePrefetcher)

    def bad_source():
        yield np.ones((2,), np.float32)
        raise RuntimeError("poisoned batch assembly")

    pf = DevicePrefetcher(bad_source(), depth=2, timeout=10.0)
    with pytest.raises(DataLoaderWorkerError, match='poisoned batch'):
        list(pf)


def test_prefetcher_stops_thread_on_abandoned_iteration():
    import threading
    from paddle_tpu.io.dataloader import DevicePrefetcher

    def source():
        for i in range(1000):
            yield np.full((2,), i, np.float32)

    pf = DevicePrefetcher(source(), depth=2, timeout=10.0)
    it = iter(pf)
    next(it)
    it.close()
    deadline = time.monotonic() + 5.0
    while time.monotonic() < deadline:
        if not any(t.name == 'paddle-tpu-device-prefetch' and t.is_alive()
                   for t in threading.enumerate()):
            break
        time.sleep(0.05)
    else:
        pytest.fail("prefetch thread leaked after consumer abandoned it")


def test_dataloader_prefetch_env_knob(monkeypatch):
    from paddle_tpu.io import DataLoader
    data = [(np.ones((2,), np.float32), np.float32(0.0)) for _ in range(4)]
    monkeypatch.setenv('PADDLE_TPU_PREFETCH', '1')
    assert DataLoader(data, batch_size=2).prefetch_to_device == 2
    monkeypatch.setenv('PADDLE_TPU_PREFETCH', '3')
    assert DataLoader(data, batch_size=2).prefetch_to_device == 3
    monkeypatch.setenv('PADDLE_TPU_PREFETCH', '')
    assert DataLoader(data, batch_size=2).prefetch_to_device == 0
    loader = DataLoader(data, batch_size=2, prefetch_to_device=2)
    batches = list(loader)
    assert len(batches) == 2 and len(list(loader)) == 2  # re-iterable


# ---------------------------------------------------------------------------
# the eager convenience loop end-to-end
# ---------------------------------------------------------------------------

def test_engine_fit_converges_with_prefetch_and_microbatch():
    paddle.seed(3)
    net = nn.Sequential(nn.Linear(3, 16), nn.ReLU(), nn.Linear(16, 1))
    opt = paddle.optimizer.Adam(learning_rate=0.01,
                                parameters=net.parameters())
    rng = np.random.RandomState(1)
    w = np.array([[1.5], [-2.0], [0.5]], np.float32)
    batches = []
    for _ in range(24):
        x = rng.rand(16, 3).astype('float32')
        batches.append(([x], [x @ w]))
    report = engine.fit(net, nn.MSELoss(), opt, batches, epochs=3,
                        microbatch=4, log_every=2, prefetch=2)
    assert report['microbatch'] == 4
    assert report['steps'] == 72          # 24 batches x 3 epochs
    assert report['dispatches'] == 18
    assert report['compiled_signatures'] == 1
    assert report['loss'][-1] < report['loss'][0] * 0.5
    # the functional result was written back into the eager world
    assert report['state']['params']
    assert opt._accumulators            # Adam moments mirrored for ckpts


@pytest.mark.fault
def test_guard_peak_streak_aborts_even_if_it_ended_before_sync():
    """A limit-length NaN streak that ends between two host reconciles
    must still abort: the guard state carries the running MAX of the
    streak, not just the instantaneous value (the eager guard would have
    aborted mid-streak)."""
    from paddle_tpu.core import rng as _rng
    from paddle_tpu.nn.layer_base import buffer_values, param_values
    net, _ = _eager_net()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    step = engine.build_train_step(net=net, loss=nn.MSELoss(),
                                   optimizer=opt, nan_guard=True)
    guard = NanGuard(max_consecutive_skips=2, verbose=False)
    pv = param_values(net)
    state = step.init_state(pv, buffer_values(net), nan_guard=guard)
    (x, y), = _data(n=1)
    bad = np.full_like(x, np.nan)
    for bx in (bad, bad, x):         # streak of 2 (== limit), then clean
        state, _ = step(state, ((bx,), (y,)), _rng.next_key())
    with pytest.raises(NanStepError):
        step.sync(state, nan_guard=guard)


@pytest.mark.fault
def test_guard_abort_is_recoverable_after_catch():
    """Catching NanStepError and continuing (lower LR, fixed data) must
    behave like eager: the next clean step resets the streak and later
    syncs do NOT re-raise from the stale pre-abort history."""
    from paddle_tpu.core import rng as _rng
    from paddle_tpu.nn.layer_base import buffer_values, param_values
    net, _ = _eager_net()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    step = engine.build_train_step(net=net, loss=nn.MSELoss(),
                                   optimizer=opt, nan_guard=True)
    guard = NanGuard(max_consecutive_skips=2, verbose=False)
    pv = param_values(net)
    state = step.init_state(pv, buffer_values(net), nan_guard=guard)
    (x, y), = _data(n=1)
    bad = np.full_like(x, np.nan)
    for bx in (bad, bad):
        state, _ = step(state, ((bx,), (y,)), _rng.next_key())
    with pytest.raises(NanStepError):
        step.sync(state, nan_guard=guard)
    state, _ = step(state, ((x,), (y,)), _rng.next_key())   # clean step
    step.sync(state, nan_guard=guard)                       # recovered
    assert guard.consecutive_skips == 0 and guard.skipped_steps == 2


def test_empty_trainable_set_updates_nothing():
    """trainable=set() (every param frozen) is a real filter, not 'no
    filter': the step must pass every param through unchanged."""
    import jax.numpy as jnp

    def loss_fn(params, buffers, batch, key):
        return jnp.sum((params['w'] - batch[0]) ** 2), (), buffers

    opt = paddle.optimizer.SGD(learning_rate=0.5)
    step = engine.build_train_step(loss_fn=loss_fn, optimizer=opt,
                                   trainable=set(), with_key=False)
    state = step.init_state({'w': jnp.ones((3,), jnp.float32)})
    state, out = step(state, (jnp.zeros((3,), jnp.float32),))
    np.testing.assert_array_equal(np.asarray(state['params']['w']),
                                  np.ones((3,), np.float32))
    assert float(out.loss) == 3.0


@pytest.mark.fault
def test_microbatch_guard_cadence_scales_with_k():
    """With microbatch=k each dispatch advances the streak by up to k
    steps — the fit loop must reconcile every ceil(limit/k) dispatches so
    the abort cannot overshoot by ~k x (here: limit 4, k 4 -> the FIRST
    poisoned dispatch must already abort, even with a huge log_every)."""
    net, _ = _eager_net()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    bad = np.full((8, 3), np.nan, np.float32)
    y = np.ones((8, 1), np.float32)
    with pytest.raises(NanStepError):
        engine.fit(net, nn.MSELoss(), opt, [([bad], [y])] * 8, epochs=1,
                   microbatch=4, log_every=100, prefetch=0,
                   nan_guard=NanGuard(max_consecutive_skips=4,
                                      verbose=False))


def test_engine_fit_drops_ragged_batches_instead_of_crashing():
    """microbatch>1 over a drop_last=False loader: the tail batch has a
    different shape — it must be dropped (one compiled shape), not
    np.stack-crashed mid-epoch."""
    net, _ = _eager_net()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    rng = np.random.RandomState(0)
    batches = [([rng.rand(8, 3).astype('float32')],
                [rng.rand(8, 1).astype('float32')]) for _ in range(4)]
    batches.append(([rng.rand(3, 3).astype('float32')],   # ragged tail
                    [rng.rand(3, 1).astype('float32')]))
    with pytest.warns(RuntimeWarning, match='dropped 1 batch'):
        report = engine.fit(net, nn.MSELoss(), opt, batches, epochs=1,
                            microbatch=2, log_every=1, prefetch=0)
    assert report['steps'] == 4 and report['dispatches'] == 2
    assert report['compiled_signatures'] == 1


def test_device_loss_supports_numeric_callbacks():
    import jax.numpy as jnp
    dl = engine.DeviceLoss(jnp.float32(2.0))
    assert dl < 3.0 and dl > 1.0 and dl <= 2.0 and dl >= 2.0
    assert dl == 2.0 and dl + 1.0 == 3.0 and 1.0 + dl == 3.0
    assert dl * 2 == 4.0 and dl / 2 == 1.0 and 4.0 / dl == 2.0
    assert -dl == -2.0 and +dl == 2.0 and abs(dl) == 2.0
    assert round(dl) == 2 and round(dl, 1) == 2.0
    assert f"{dl:.2f}" == "2.00"
    assert dl.is_ready()       # any numeric use materialized it (once)


@pytest.mark.fault
def test_engine_fit_nan_guard_limit_aborts():
    net, _ = _eager_net()
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=net.parameters())
    bad = np.full((8, 3), np.nan, np.float32)
    y = np.ones((8, 1), np.float32)
    with pytest.raises(NanStepError):
        engine.fit(net, nn.MSELoss(), opt, [([bad], [y])] * 8, epochs=1,
                   log_every=1, prefetch=0,
                   nan_guard=NanGuard(max_consecutive_skips=3,
                                      verbose=False))
    # the skipped updates never reached the network
    for p in net.parameters():
        assert np.isfinite(np.asarray(p.numpy())).all()
