"""fluid.evaluator + fluid.transpiler shims + utils.image_util.

Parity: reference fluid/evaluator.py:27, fluid/transpiler/__init__.py:21,
paddle/utils/image_util.py:1 (VERDICT r4 missing #5/#6 + transpiler note).
"""
import warnings

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.fluid as fluid


# ---------------------------------------------------------------------------
# fluid.evaluator
# ---------------------------------------------------------------------------

def test_edit_distance_evaluator_eager_accumulation():
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        # two batches: the evaluator's io_callback accumulation fires per
        # construction-time execution in eager mode
        a = paddle.to_tensor(np.array([[1, 2, 3]], np.int64))
        b = paddle.to_tensor(np.array([[1, 2, 4]], np.int64))
        ev = fluid.evaluator.EditDistance(a, b)
        avg, err = ev.eval(None)
    assert avg[0] == pytest.approx(1.0)   # one substitution
    assert err[0] == pytest.approx(1.0)   # 1/1 sequences wrong
    ev.reset(None)
    avg, err = ev.eval(None)
    assert avg[0] == 0.0 and err[0] == 0.0


def test_chunk_evaluator_protocol():
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        # IOB scheme, 1 chunk type: perfect prediction
        label = paddle.to_tensor(np.array([[0, 1, 2, 0]], np.int64))
        ev = fluid.evaluator.ChunkEvaluator(
            label, label, chunk_scheme='IOB', num_chunk_types=1)
        p, r, f1 = ev.eval(None)
    assert p[0] == pytest.approx(1.0)
    assert r[0] == pytest.approx(1.0)
    assert f1[0] == pytest.approx(1.0)
    assert len(ev.metrics) == 3
    ev.reset(None)
    p, r, f1 = ev.eval(None)
    assert f1[0] == 0.0


def test_detection_map_evaluator():
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        det = paddle.to_tensor(np.array(
            [[0, 0.9, 0, 0, 10, 10]], np.float32))
        gt_label = paddle.to_tensor(np.array([0], np.int64))
        gt_box = paddle.to_tensor(np.array([[0, 0, 10, 10]], np.float32))
        ev = fluid.evaluator.DetectionMAP(det, gt_label, gt_box,
                                          class_num=1)
        m = ev.eval(None)
    assert m[0] == pytest.approx(1.0)
    assert ev.get_map_var() is not None


def test_edit_distance_evaluator_static_program_accumulates():
    """The module's central claim: inside a static Program the io_callback
    accumulation op fires on EVERY exe.run, like the reference's
    layers.sums-into-persistable-state."""
    with warnings.catch_warnings():
        warnings.simplefilter('ignore')
        paddle.enable_static()
        try:
            main = fluid.Program()
            startup = fluid.Program()
            with fluid.program_guard(main, startup):
                a = fluid.layers.data(name='a', shape=[-1, 3],
                                      dtype='int64')
                b = fluid.layers.data(name='b', shape=[-1, 3],
                                      dtype='int64')
                ev = fluid.evaluator.EditDistance(a, b)
                out = a  # something cheap to fetch
                exe = fluid.Executor(fluid.CPUPlace())
                exe.run(startup)
                ev.reset(exe)
                # batch 1: one substitution; batch 2: identical sequences
                exe.run(main,
                        feed={'a': np.array([[1, 2, 3]], np.int64),
                              'b': np.array([[1, 2, 4]], np.int64)},
                        fetch_list=[out])
                exe.run(main,
                        feed={'a': np.array([[5, 6, 7]], np.int64),
                              'b': np.array([[5, 6, 7]], np.int64)},
                        fetch_list=[out])
                avg, err = ev.eval(exe)
        finally:
            paddle.disable_static()
    # 2 sequences seen, total distance 1 -> avg 0.5, error rate 0.5
    assert avg[0] == pytest.approx(0.5)
    assert err[0] == pytest.approx(0.5)


def test_evaluator_deprecation_warning():
    with pytest.warns(Warning, match='deprecated'):
        fluid.evaluator.EditDistance(
            paddle.to_tensor(np.array([[1]], np.int64)),
            paddle.to_tensor(np.array([[1]], np.int64)))


# ---------------------------------------------------------------------------
# fluid.transpiler shims
# ---------------------------------------------------------------------------

def test_transpiler_names_exist_and_guide():
    assert hasattr(fluid, 'DistributeTranspiler')
    assert hasattr(fluid.transpiler, 'HashName')
    assert hasattr(fluid.transpiler, 'RoundRobin')
    cfg = fluid.DistributeTranspilerConfig(sync_mode=False)
    assert cfg.sync_mode is False
    t = fluid.DistributeTranspiler(config=cfg)
    with pytest.raises(NotImplementedError, match='fleet'):
        t.transpile(0, pservers='127.0.0.1:6170', trainers=1)
    with pytest.raises(NotImplementedError, match='fleet'):
        t.get_pserver_program('127.0.0.1:6170')


def test_memory_optimize_noop_warns():
    with pytest.warns(DeprecationWarning):
        fluid.memory_optimize(None)
    with pytest.warns(DeprecationWarning):
        fluid.release_memory(None)


# ---------------------------------------------------------------------------
# utils.image_util
# ---------------------------------------------------------------------------

def test_image_util_flip_and_crop():
    from paddle_tpu.utils import image_util as iu
    im = np.arange(2 * 4 * 4, dtype=np.float32).reshape(2, 4, 4)
    f = iu.flip(im)
    np.testing.assert_array_equal(f, im[:, :, ::-1])
    # color center crop: (3, H, W) input
    im3 = np.arange(3 * 6 * 6, dtype=np.float32).reshape(3, 6, 6)
    crop = iu.crop_img(im3, 4, color=True, test=True)
    assert crop.shape == (3, 4, 4)
    np.testing.assert_array_equal(crop, im3[:, 1:5, 1:5])
    # smaller than inner_size: zero-padded
    small = np.ones((3, 2, 2), np.float32)
    crop = iu.crop_img(small, 4, color=True, test=True)
    assert crop.shape == (3, 4, 4)
    assert crop.sum() == pytest.approx(12.0)
    # grayscale path
    g = iu.crop_img(np.ones((5, 5), np.float32), 3, color=False, test=True)
    assert g.shape == (3, 3)


def test_image_util_preprocess_and_oversample():
    from paddle_tpu.utils import image_util as iu
    im = np.ones((3, 8, 8), np.float32)
    mean = np.zeros((3, 4, 4), np.float32)
    flat = iu.preprocess_img(im, mean, 4, is_train=False)
    assert flat.shape == (3 * 4 * 4,)
    np.testing.assert_allclose(flat, 1.0)
    imgs = [np.arange(6 * 6 * 3, dtype=np.float32).reshape(6, 6, 3)]
    crops = iu.oversample(imgs, (4, 4))
    assert crops.shape == (10, 4, 4, 3)
    # second five are mirrors of the first five
    np.testing.assert_array_equal(crops[5:], crops[:5][:, :, ::-1, :])


def test_image_util_load_meta(tmp_path):
    from paddle_tpu.utils import image_util as iu
    mean = np.arange(3 * 8 * 8, dtype=np.float32)
    p = tmp_path / 'meta.npz'
    np.savez(p, data_mean=mean)
    m = iu.load_meta(str(p), 8, 4, color=True)
    assert m.shape == (3, 4, 4)
    expect = mean.reshape(3, 8, 8)[:, 2:6, 2:6]
    np.testing.assert_array_equal(m, expect)


def test_image_transformer():
    from paddle_tpu.utils import image_util as iu
    t = iu.ImageTransformer(transpose=(2, 0, 1), channel_swap=(2, 1, 0),
                            mean=np.array([1.0, 2.0, 3.0], np.float32))
    data = np.random.RandomState(0).rand(4, 4, 3).astype(np.float32)
    out = t.transformer(data)
    expect = data.transpose(2, 0, 1)[(2, 1, 0), :, :] - \
        np.array([1, 2, 3], np.float32)[:, None, None]
    np.testing.assert_allclose(out, expect, rtol=1e-6)
