"""Every paddle_tpu module imports and every __all__ entry resolves."""
import importlib
import pkgutil

import paddle_tpu


def test_all_modules_import_and_exports_resolve():
    bad = []
    # onerror: a package whose __init__ raises must land in `bad` via our
    # own import below, not abort the walk mid-iteration
    for m in pkgutil.walk_packages(paddle_tpu.__path__,
                                   prefix='paddle_tpu.',
                                   onerror=lambda name: None):
        if 'libpaddle_tpu_native' in m.name:   # ctypes .so, not a module
            continue
        try:
            mod = importlib.import_module(m.name)
        except Exception as e:
            bad.append((m.name, 'import', repr(e)))
            continue
        for attr in getattr(mod, '__all__', []):
            if not hasattr(mod, attr):
                bad.append((m.name, 'missing __all__ entry', attr))
    assert not bad, bad
