"""Distributed & input-pipeline fault tolerance: the chaos matrix.

Acceptance anchors (ISSUE 5):
(a) a killed DataLoader worker no longer hangs the consumer — the epoch
    completes (respawn) or raises within the watchdog budget, with the
    quarantine/restart count reported;
(b) barrier() with an expired deadline raises DistributedTimeoutError
    naming the op within 2x the configured timeout;
(c) a SIGKILLed rank under launch()/spawn() terminates all sibling ranks
    with a RankFailedError identifying the rank;
with telemetry counters for restarts/quarantines/timeouts asserted under
PADDLE_TPU_TELEMETRY=1.

Everything is CPU-only, deterministic (resilience.faultinject), and
tier-1-safe (no sleeps beyond ~2s in any surviving code path).
"""
import os
import queue
import signal
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu
from paddle_tpu import observability as obs
from paddle_tpu.io import DataLoader, DataLoaderWorkerError
from paddle_tpu.io.dataset import Dataset
from paddle_tpu.resilience import faultinject as fi
from paddle_tpu.resilience import watchdog

pytestmark = pytest.mark.fault


class Toy(Dataset):
    def __init__(self, n=16):
        self.n = n

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        return np.full((3,), i, np.float32)


@pytest.fixture
def telemetry(monkeypatch):
    """PADDLE_TPU_TELEMETRY=1 for this test, counters zeroed."""
    monkeypatch.setenv('PADDLE_TPU_TELEMETRY', '1')
    obs.enable()
    obs.reset()
    yield obs
    obs.disable()
    obs.reset()


def _loader(ds, **kw):
    kw.setdefault('batch_size', 2)
    kw.setdefault('num_workers', 2)
    kw.setdefault('use_buffer_reader', False)
    return DataLoader(ds, **kw)


def _nbatch_samples(batches):
    return sum(np.asarray(b).shape[0] for b in batches)


# ---------------------------------------------------------------------------
# watchdog primitives
# ---------------------------------------------------------------------------

class TestWatchdog:
    def test_bounded_get_dead_producer_raises_fast(self):
        q = queue.Queue()
        t = threading.Thread(target=lambda: None)   # dies without posting
        t.start()
        t.join(1)
        start = time.monotonic()
        with pytest.raises(watchdog.WatchdogTimeout, match='died'):
            watchdog.bounded_get(q, timeout=30.0, alive=t.is_alive,
                                 what='sentinel')
        assert time.monotonic() - start < 2.0   # liveness, not the deadline

    def test_bounded_get_timeout_when_alive_but_stuck(self):
        q = queue.Queue()
        with pytest.raises(watchdog.WatchdogTimeout, match='within'):
            watchdog.bounded_get(q, timeout=0.3, alive=lambda: True)

    def test_bounded_get_drains_after_producer_death(self):
        q = queue.Queue()
        q.put('last-words')
        assert watchdog.bounded_get(q, alive=lambda: False) == 'last-words'

    def test_heartbeat_file_and_age(self, tmp_path):
        hb_path = tmp_path / 'hb_0'
        hb = watchdog.Heartbeat(hb_path, interval=0.05).start()
        try:
            time.sleep(0.2)
            age = watchdog.heartbeat_age(hb_path)
            assert age is not None and age < 1.0
        finally:
            hb.stop()
        assert watchdog.heartbeat_age(tmp_path / 'missing') is None


# ---------------------------------------------------------------------------
# self-healing DataLoader: threaded path
# ---------------------------------------------------------------------------

class TestThreadedLoader:
    def test_worker_exception_propagates_not_hangs(self):
        """The silent-hang fix: a raising dataset[i] reaches the consumer
        as DataLoaderWorkerError instead of killing the thread silently."""
        dl = _loader(fi.poison_sample(Toy(), [3]), use_shared_memory=False)
        start = time.monotonic()
        with pytest.raises(DataLoaderWorkerError) as ei:
            list(dl)
        assert time.monotonic() - start < 5.0
        assert 'dataset[3]' in str(ei.value)
        assert 'PoisonedSampleError' in str(ei.value)

    def test_quarantine_within_budget(self, telemetry):
        dl = _loader(fi.poison_sample(Toy(), [3, 7]),
                     use_shared_memory=False, skip_bad_samples=2)
        batches = list(dl)
        assert _nbatch_samples(batches) == 14   # 16 - 2 quarantined
        report = dl.quarantine_report()
        assert sorted(i for i, _ in report) == [3, 7]
        assert all('PoisonedSampleError' in err for _, err in report)
        snap = obs.snapshot()['counters']
        assert snap['dataloader.quarantined'] == 2
        assert obs.counters_summary()['quarantined_samples'] == 2

    def test_quarantine_budget_exhausted_raises(self):
        dl = _loader(fi.poison_sample(Toy(), [1, 3, 5]),
                     use_shared_memory=False, skip_bad_samples=1)
        with pytest.raises(DataLoaderWorkerError) as ei:
            list(dl)
        assert 'exhausted' in str(ei.value)
        assert len(dl.quarantine_report()) == 1   # budget, not overrun

    def test_whole_batch_quarantined_keeps_order(self):
        dl = _loader(fi.poison_sample(Toy(8), [2, 3]),
                     use_shared_memory=False, skip_bad_samples=2)
        vals = [v for b in list(dl) for v in np.asarray(b)[:, 0].tolist()]
        assert vals == [0.0, 1.0, 4.0, 5.0, 6.0, 7.0]   # in order, no hole

    def test_sync_path_quarantine(self):
        """skip_bad_samples applies on the num_workers=0 path too."""
        dl = DataLoader(fi.poison_sample(Toy(), [3, 7]), batch_size=2,
                        num_workers=0, use_buffer_reader=False,
                        skip_bad_samples=2)
        batches = list(dl)
        assert _nbatch_samples(batches) == 14
        assert sorted(i for i, _ in dl.quarantine_report()) == [3, 7]

    def test_sync_path_default_budget_fails_loudly(self):
        dl = DataLoader(fi.poison_sample(Toy(), [3]), batch_size=2,
                        num_workers=0, use_buffer_reader=False)
        with pytest.raises(DataLoaderWorkerError, match='exhausted'):
            list(dl)

    def test_hung_worker_trips_watchdog(self, telemetry):
        """A worker wedged mid-sample fails the epoch within the watchdog
        budget instead of hanging the consumer forever."""
        dl = _loader(fi.hang_worker(Toy(8), 2, hang_s=30.0),
                     use_shared_memory=False, timeout=1.0)
        start = time.monotonic()
        with pytest.raises(DataLoaderWorkerError, match='wedged'):
            list(dl)
        assert time.monotonic() - start < 4.0   # ~1s budget + poll slack
        assert obs.snapshot()['counters']['dataloader.watchdog_timeouts'] \
            == 1

    def test_collate_error_propagates(self):
        def bad_collate(samples):
            raise TypeError('collate boom')
        dl = _loader(Toy(8), use_shared_memory=False,
                     collate_fn=bad_collate)
        with pytest.raises(DataLoaderWorkerError, match='collate'):
            list(dl)

    def test_timeout_zero_env_disables_watchdog(self, monkeypatch):
        """PADDLE_TPU_DATA_TIMEOUT=0 (or timeout<0) disables the deadline
        instead of turning it into an instant trip; timeout=0 still means
        'unspecified' (default budget)."""
        monkeypatch.setenv('PADDLE_TPU_DATA_TIMEOUT', '0')
        dl = _loader(Toy(8), use_shared_memory=False)
        assert dl.timeout == 0.0
        assert _nbatch_samples(list(dl)) == 8   # liveness still bounds it
        monkeypatch.delenv('PADDLE_TPU_DATA_TIMEOUT')
        assert _loader(Toy(8), timeout=-1).timeout == 0.0
        assert _loader(Toy(8)).timeout > 0

    def test_skip_budget_env_default(self, monkeypatch):
        monkeypatch.setenv('PADDLE_TPU_DATA_SKIP_BUDGET', '2')
        dl = _loader(fi.poison_sample(Toy(), [0, 15]),
                     use_shared_memory=False)
        assert dl.skip_bad_samples == 2
        assert _nbatch_samples(list(dl)) == 14


# ---------------------------------------------------------------------------
# self-healing DataLoader: fork()ed process workers + shm ring
# ---------------------------------------------------------------------------

def _native_pool_available():
    try:
        import multiprocessing as mp
        from paddle_tpu._native.prefetch import native_available
        return native_available() and 'fork' in mp.get_all_start_methods()
    except Exception:
        return False


needs_pool = pytest.mark.skipif(not _native_pool_available(),
                                reason='native ring / fork unavailable')


@needs_pool
class TestProcessPoolLoader:
    def test_killed_worker_respawns_and_epoch_completes(self, telemetry,
                                                        tmp_path):
        """Acceptance (a): SIGKILLed process worker mid-epoch -> respawn +
        parent-side rebuild of the orphaned batch; every sample arrives."""
        once = tmp_path / 'kill-fired'
        dl = _loader(fi.kill_worker(Toy(), 5, once), timeout=20.0,
                     worker_max_restarts=2)
        batches = list(dl)
        assert _nbatch_samples(batches) == 16       # nothing lost
        assert once.exists()                        # the kill really fired
        snap = obs.snapshot()['counters']
        assert snap['dataloader.worker_restarts'] >= 1
        assert obs.counters_summary()['worker_restarts'] >= 1

    def test_killed_worker_without_restart_budget_raises(self, tmp_path):
        once = tmp_path / 'kill-fired'
        dl = _loader(fi.kill_worker(Toy(), 5, once), timeout=10.0,
                     worker_max_restarts=0)
        start = time.monotonic()
        with pytest.raises(RuntimeError, match='died without a traceback'):
            list(dl)
        assert time.monotonic() - start < 8.0       # bounded, not a hang

    def test_process_poison_quarantine_within_budget(self, telemetry):
        dl = _loader(fi.poison_sample(Toy(), [3, 7]), timeout=10.0,
                     skip_bad_samples=4)
        batches = list(dl)
        assert _nbatch_samples(batches) == 14
        assert sorted(i for i, _ in dl.quarantine_report()) == [3, 7]
        assert obs.snapshot()['counters']['dataloader.quarantined'] == 2


# ---------------------------------------------------------------------------
# reader decorators: no unbounded waits
# ---------------------------------------------------------------------------

class TestReaderLiveness:
    def test_multiprocess_reader_killed_worker_raises(self):
        """A reader worker SIGKILLed mid-stream can never post its done
        sentinel; the liveness-bounded get raises instead of hanging."""
        import multiprocessing as mp
        if 'fork' not in mp.get_all_start_methods():
            pytest.skip('fork unavailable')
        from paddle_tpu.reader import multiprocess_reader

        def suicidal():
            yield np.float32(1.0)
            os.kill(os.getpid(), signal.SIGKILL)

        reader = multiprocess_reader([lambda: suicidal()], queue_size=4)
        start = time.monotonic()
        with pytest.raises(RuntimeError):
            list(reader())
        assert time.monotonic() - start < 10.0

    def test_buffered_reader_error_still_propagates(self):
        from paddle_tpu.reader import buffered

        def boom():
            yield 1
            raise ValueError('reader boom')

        with pytest.raises(ValueError, match='reader boom'):
            list(buffered(lambda: boom(), 4)())


# ---------------------------------------------------------------------------
# collective deadlines
# ---------------------------------------------------------------------------

class TestCollectiveDeadline:
    def test_barrier_deadline_raises_within_2x(self, telemetry):
        """Acceptance (b): expired barrier deadline -> actionable
        DistributedTimeoutError naming the op, within 2x the timeout."""
        import paddle_tpu.distributed as dist
        prev = dist.set_timeout(0.5)
        try:
            start = time.monotonic()
            with fi.slow_collective(30.0, ops=['barrier']):
                with pytest.raises(dist.DistributedTimeoutError) as ei:
                    dist.barrier()
            elapsed = time.monotonic() - start
            assert elapsed < 2 * 0.5, elapsed
            assert ei.value.op == 'barrier'
            assert ei.value.timeout == 0.5
            assert 'barrier' in str(ei.value)
            snap = obs.snapshot()['counters']
            assert snap['distributed.timeouts'] == 1
            assert obs.counters_summary()['dist_timeouts'] == 1
        finally:
            dist.set_timeout(prev)

    def test_eager_all_reduce_deadline(self):
        import paddle_tpu.distributed as dist
        prev = dist.set_timeout(0.4)
        try:
            t = paddle_tpu.to_tensor(np.ones(4, np.float32))
            with fi.slow_collective(30.0, ops=['all_reduce']):
                with pytest.raises(dist.DistributedTimeoutError,
                                   match='all_reduce'):
                    dist.all_reduce(t)
        finally:
            dist.set_timeout(prev)

    def test_collectives_complete_under_deadline(self):
        import paddle_tpu.distributed as dist
        prev = dist.set_timeout(30.0)
        try:
            dist.barrier()
            t = paddle_tpu.to_tensor(np.ones(4, np.float32))
            out = dist.all_reduce(t)
            assert out is not None
        finally:
            dist.set_timeout(prev)

    def test_set_timeout_policy(self, monkeypatch):
        from paddle_tpu.distributed import deadline
        prev = deadline.set_timeout(None)
        try:
            assert deadline.get_timeout() is None
            deadline.set_timeout(7.5)
            assert deadline.get_timeout() == 7.5
            deadline.set_timeout(0)       # 0 disables
            assert deadline.get_timeout() is None
        finally:
            deadline.set_timeout(prev)
        # env seeding
        monkeypatch.setenv('PADDLE_TPU_DIST_TIMEOUT', '12.5')
        assert deadline._env_timeout() == 12.5
        monkeypatch.setenv('PADDLE_TPU_DIST_TIMEOUT', 'nonsense')
        assert deadline._env_timeout() is None


# ---------------------------------------------------------------------------
# supervised launch
# ---------------------------------------------------------------------------

def _sigkill_rank1():
    rank = int(os.environ.get('PADDLE_TRAINER_ID', '0'))
    if rank == 1:
        os.kill(os.getpid(), signal.SIGKILL)
    for _ in range(20):        # ~2s ceiling; the supervisor kills us first
        time.sleep(0.1)
    return rank


def _rank_times_ten():
    return int(os.environ.get('PADDLE_TRAINER_ID', '0')) * 10


@pytest.mark.skipif(sys.platform == 'win32', reason='posix only')
class TestSupervisedSpawn:
    def test_sigkilled_rank_fails_fast_with_diagnostics(self, telemetry):
        """Acceptance (c): SIGKILL on rank 1 -> RankFailedError naming the
        rank + signal, siblings terminated, telemetry counter bumped."""
        import paddle_tpu.distributed as dist
        ctx = dist.spawn(fi.slow_rank(_sigkill_rank1, rank=0, delay_s=0.0),
                         nprocs=2, backend='cpu', join=False)
        with pytest.raises(dist.RankFailedError) as ei:
            ctx.join()
        e = ei.value
        assert e.rank == 1
        assert e.signal_name == 'SIGKILL'
        assert 'rank 1' in str(e) and 'SIGKILL' in str(e)
        assert not any(p.is_alive() for p in ctx.processes)   # kill-tree
        assert obs.snapshot()['counters']['distributed.rank_failures'] == 1

    def test_boot_failure_restarted_within_budget(self, telemetry):
        import paddle_tpu.distributed as dist
        with fi.boot_fail(rank=1, times=1):
            res = dist.spawn(_rank_times_ten, nprocs=2, backend='cpu',
                             max_restarts=1).join()
        assert res == [0, 10]
        snap = obs.snapshot()['counters']
        assert snap['distributed.rank_restarts'] == 1
        assert obs.counters_summary()['rank_restarts'] == 1

    def test_boot_failure_without_budget_raises(self):
        import paddle_tpu.distributed as dist
        with fi.boot_fail(rank=1, times=1):
            with pytest.raises(dist.RankFailedError) as ei:
                dist.spawn(_rank_times_ten, nprocs=2, backend='cpu')
        assert ei.value.rank == 1
        assert ei.value.exitcode == 43

    def test_join_timeout_terminates_stragglers(self):
        import paddle_tpu.distributed as dist
        ctx = dist.spawn(fi.slow_rank(_rank_times_ten, rank=1, delay_s=60),
                         nprocs=2, backend='cpu', join=False)
        with pytest.raises(RuntimeError) as ei:
            ctx.join(timeout=4.0)
        assert 'still running' in str(ei.value)
        assert 'exit codes' in str(ei.value)
        assert not any(p.is_alive() for p in ctx.processes)


@pytest.mark.skipif(sys.platform == 'win32', reason='posix only')
class TestSupervisedLaunchCLI:
    def test_first_nonzero_exit_kills_siblings(self, tmp_path):
        """launch() fail-fast: rank 1 exits 3 -> rank 0 is terminated and
        the launcher reports which rank failed."""
        script = tmp_path / 'failing_rank.py'
        script.write_text(
            "import os, sys, time\n"
            "rank = int(os.environ['PADDLE_TRAINER_ID'])\n"
            "if rank == 1:\n"
            "    print('rank 1 bailing', file=sys.stderr)\n"
            "    sys.exit(3)\n"
            "for _ in range(600):\n"     # rank 0: 60s unless terminated
            "    time.sleep(0.1)\n")
        import subprocess as sp
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS='cpu', PALLAS_AXON_POOL_IPS='',
                   PYTHONPATH=os.pathsep.join(
                       [repo] + ([os.environ['PYTHONPATH']]
                                 if os.environ.get('PYTHONPATH') else [])))
        start = time.monotonic()
        out = sp.run([sys.executable, '-m', 'paddle_tpu.distributed.launch',
                      '--nproc_per_node', '2', '--log_dir', str(tmp_path),
                      str(script)],
                     env=env, capture_output=True, text=True, timeout=300)
        elapsed = time.monotonic() - start
        assert out.returncode != 0
        assert 'rank 1' in out.stderr
        assert 'exit code 3' in out.stderr
        assert 'rank 1 bailing' in out.stderr     # log tail quoted
        assert elapsed < 45, elapsed              # rank 0 did NOT run 60s

    def test_boot_restart_flag(self, tmp_path):
        """--max_restarts heals a transient boot crash (script version:
        crash on first attempt, succeed on retry via a marker file)."""
        script = tmp_path / 'flaky_boot.py'
        script.write_text(
            "import os, pathlib, sys\n"
            "rank = os.environ['PADDLE_TRAINER_ID']\n"
            "marker = pathlib.Path(__file__).parent / ('boot_%s' % rank)\n"
            "if rank == '1' and not marker.exists():\n"
            "    marker.write_text('fired')\n"
            "    os._exit(9)\n"
            "(pathlib.Path(__file__).parent / ('ok_%s' % rank))"
            ".write_text('done')\n")
        import subprocess as sp
        repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ, JAX_PLATFORMS='cpu', PALLAS_AXON_POOL_IPS='',
                   PYTHONPATH=os.pathsep.join(
                       [repo] + ([os.environ['PYTHONPATH']]
                                 if os.environ.get('PYTHONPATH') else [])))
        out = sp.run([sys.executable, '-m', 'paddle_tpu.distributed.launch',
                      '--nproc_per_node', '2', '--max_restarts', '1',
                      '--log_dir', str(tmp_path), str(script)],
                     env=env, capture_output=True, text=True, timeout=300)
        assert out.returncode == 0, out.stderr[-2000:]
        assert (tmp_path / 'ok_0').exists() and (tmp_path / 'ok_1').exists()
        assert (tmp_path / 'boot_1').exists()     # the crash really fired


# ---------------------------------------------------------------------------
# hapi surfacing
# ---------------------------------------------------------------------------

class TestHapiQuarantineSurfacing:
    def test_fit_warns_on_quarantined_samples(self):
        import paddle_tpu.nn as nn
        from paddle_tpu.hapi import Model

        class Pair(Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return (np.full((3,), i, np.float32),
                        np.zeros((1,), np.int64))

        net = nn.Linear(3, 2)
        model = Model(net)
        model.prepare(
            optimizer=paddle_tpu.optimizer.SGD(
                learning_rate=0.1, parameters=net.parameters()),
            loss=nn.CrossEntropyLoss())
        loader = DataLoader(fi.poison_sample(Pair(), [2]), batch_size=2,
                            num_workers=2, use_shared_memory=False,
                            skip_bad_samples=1)
        with pytest.warns(RuntimeWarning, match='quarantined 1'):
            model.fit(loader, epochs=1, verbose=0)
